"""Cell-based RNN API (ref layers/rnn.py:48-1700): GRUCell/LSTMCell +
rnn() vs numpy oracles, BeamSearchDecoder + dynamic_decode vs a
reference beam-search implementation, dynamic_lstmp vs oracle."""
import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers


def _fresh():
    from paddle_tpu.fluid import framework, unique_name

    framework.switch_main_program(framework.Program())
    framework.switch_startup_program(framework.Program())
    unique_name.switch()
    fluid.default_startup_program().random_seed = 11
    fluid.default_main_program().random_seed = 11


def _fetch_params(exe, names):
    scope = fluid.global_scope()
    return [np.asarray(scope[n]) for n in names]


def _sigmoid(x):
    return 1.0 / (1.0 + np.exp(-x))


# ---------------------------------------------------------------------------
# rnn() + GRUCell
# ---------------------------------------------------------------------------
def test_rnn_gru_cell_matches_numpy():
    _fresh()
    B, T, D_in, D = 3, 5, 4, 6
    x = fluid.data("x", (None, T, D_in), "float32")
    cell = layers.GRUCell(hidden_size=D)
    outs, final = layers.rnn(cell, x)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())

    rng = np.random.default_rng(0)
    xv = rng.standard_normal((B, T, D_in)).astype("float32")
    out_v, fin_v = exe.run(feed={"x": xv}, fetch_list=[outs, final])

    # oracle using the traced parameters
    prog = fluid.default_main_program()
    pnames = [p.name for p in prog.global_block().all_parameters()]
    gw, gb, cw, cb = _fetch_params(exe, pnames)
    h = np.zeros((B, D), "float32")
    ref = []
    for t in range(T):
        concat = np.concatenate([xv[:, t], h], axis=1)
        gates = _sigmoid(concat @ gw + gb)
        r, u = gates[:, :D], gates[:, D:]
        cand = np.tanh(
            np.concatenate([xv[:, t], r * h], axis=1) @ cw + cb)
        h = u * h + (1 - u) * cand
        ref.append(h)
    ref = np.stack(ref, axis=1)
    np.testing.assert_allclose(np.asarray(out_v), ref, rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(fin_v), ref[:, -1],
                               rtol=2e-5, atol=2e-5)


def test_rnn_lstm_cell_seq_len_and_reverse():
    _fresh()
    B, T, D_in, D = 2, 4, 3, 5
    x = fluid.data("x", (None, T, D_in), "float32")
    sl = fluid.data("sl", (None, ), "int64")
    cell = layers.LSTMCell(hidden_size=D)
    outs, final = layers.rnn(cell, x, sequence_length=sl)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())

    rng = np.random.default_rng(1)
    xv = rng.standard_normal((B, T, D_in)).astype("float32")
    slv = np.array([4, 2], "int64")
    out_v, h_fin, c_fin = exe.run(
        feed={"x": xv, "sl": slv},
        fetch_list=[outs, final[0], final[1]])

    prog = fluid.default_main_program()
    pnames = [p.name for p in prog.global_block().all_parameters()]
    w, b = _fetch_params(exe, pnames)
    h = np.zeros((B, D), "float32")
    c = np.zeros((B, D), "float32")
    hs = []
    for t in range(T):
        gates = np.concatenate([xv[:, t], h], axis=1) @ w + b
        i, j, f, o = np.split(gates, 4, axis=1)
        c_new = c * _sigmoid(f + 1.0) + _sigmoid(i) * np.tanh(j)
        h_new = np.tanh(c_new) * _sigmoid(o)
        # ref rnn() masks only the carried STATE; step outputs stay the
        # raw cell output (computed from the frozen state past the length)
        hs.append(h_new)
        live = (t < slv)[:, None]
        h = np.where(live, h_new, h)
        c = np.where(live, c_new, c)
    ref = np.stack(hs, axis=1)
    np.testing.assert_allclose(np.asarray(out_v), ref, rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(h_fin), h, rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(c_fin), c, rtol=2e-5, atol=2e-5)


def test_rnn_is_reverse():
    _fresh()
    B, T, D_in, D = 2, 3, 3, 4
    x = fluid.data("x", (None, T, D_in), "float32")
    cell = layers.GRUCell(hidden_size=D, name="revgru")
    outs, _ = layers.rnn(cell, x, is_reverse=True)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    rng = np.random.default_rng(3)
    xv = rng.standard_normal((B, T, D_in)).astype("float32")
    out_v = np.asarray(exe.run(feed={"x": xv}, fetch_list=[outs])[0])

    prog = fluid.default_main_program()
    pnames = [p.name for p in prog.global_block().all_parameters()]
    gw, gb, cw, cb = _fetch_params(exe, pnames)
    h = np.zeros((B, D), "float32")
    ref = [None] * T
    for t in reversed(range(T)):
        concat = np.concatenate([xv[:, t], h], axis=1)
        gates = _sigmoid(concat @ gw + gb)
        r, u = gates[:, :D], gates[:, D:]
        cand = np.tanh(np.concatenate([xv[:, t], r * h], axis=1) @ cw + cb)
        h = u * h + (1 - u) * cand
        ref[t] = h
    np.testing.assert_allclose(out_v, np.stack(ref, axis=1),
                               rtol=2e-5, atol=2e-5)


def test_rnn_trains():
    _fresh()
    B, T, D_in, D = 4, 6, 3, 8
    x = fluid.data("x", (None, T, D_in), "float32")
    y = fluid.data("y", (None, 1,), "float32")
    cell = layers.LSTMCell(hidden_size=D)
    _, final = layers.rnn(cell, x)
    pred = layers.fc(final[0], 1)
    loss = layers.reduce_mean(layers.square_error_cost(pred, y))
    fluid.optimizer.Adam(0.02).minimize(loss)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    rng = np.random.default_rng(5)
    xv = rng.standard_normal((B, T, D_in)).astype("float32")
    yv = xv.sum(axis=(1, 2), keepdims=False)[:, None].astype("float32")
    first = last = None
    for _ in range(40):
        (lv,) = exe.run(feed={"x": xv, "y": yv}, fetch_list=[loss])
        lv = float(lv)
        first = lv if first is None else first
        last = lv
    assert last < first * 0.5, (first, last)


# ---------------------------------------------------------------------------
# dynamic_decode + BeamSearchDecoder
# ---------------------------------------------------------------------------
def test_beam_search_decoder_matches_numpy():
    _fresh()
    B, V, D, beam, steps = 2, 7, 5, 3, 5
    enc = fluid.data("enc", (None, D,), "float32")  # (B, D) encoder final state

    emb_w = fluid.ParamAttr(name="trg_emb")
    out_w = fluid.ParamAttr(name="out_w")

    def embedding_fn(ids):
        return layers.embedding(ids, size=[V, D], param_attr=emb_w)

    def output_fn(x):
        return layers.fc(x, size=V, num_flatten_dims=len(x.shape) - 1,
                         param_attr=out_w, bias_attr=False)

    cell = layers.GRUCell(hidden_size=D, name="decgru")
    decoder = layers.BeamSearchDecoder(
        cell, start_token=0, end_token=1, beam_size=beam,
        embedding_fn=embedding_fn, output_fn=output_fn)
    outputs, final_states = layers.dynamic_decode(
        decoder, inits=enc, max_step_num=steps - 1)

    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    rng = np.random.default_rng(7)
    encv = rng.standard_normal((B, D)).astype("float32")
    pred_v = np.asarray(
        exe.run(feed={"enc": encv}, fetch_list=[outputs])[0])

    prog = fluid.default_main_program()
    name2p = {p.name: p for p in prog.global_block().all_parameters()}
    gw, gb, cw, cb = _fetch_params(
        exe, [n for n in name2p if n.startswith("decgru")])
    (ew,) = _fetch_params(exe, ["trg_emb"])
    (ow,) = _fetch_params(exe, ["out_w"])

    # oracle starts from the tiled encoder state
    kinf = 1e9
    ref_pred, _ = _np_beam_search_with_h0(
        gw, gb, cw, cb, ew, ow, B, V, D, beam, 0, 1, steps,
        h0=np.tile(encv[:, None, :], (1, beam, 1)))
    # fluid returns batch-major (B, T, beam)
    np.testing.assert_array_equal(pred_v, ref_pred.transpose(1, 0, 2))


def _np_beam_search_with_h0(gw, gb, cw, cb, ew, ow, B, V, D, beam, start,
                            end, steps, h0):
    kinf = 1e9
    h = h0.astype("float32").copy()
    log_probs = np.tile(
        np.array([[0.0] + [-kinf] * (beam - 1)], "float32"), (B, 1))
    finished = np.zeros((B, beam), bool)
    lengths = np.zeros((B, beam), "int64")
    ids = np.full((B, beam), start, "int64")
    pred_hist, parent_hist = [], []
    for _ in range(steps):
        emb = ew[ids]
        xh = np.concatenate([emb, h], axis=-1)
        gates = _sigmoid(xh @ gw + gb)
        r, u = gates[..., :D], gates[..., D:]
        cand = np.tanh(np.concatenate([emb, r * h], axis=-1) @ cw + cb)
        h_new = u * h + (1 - u) * cand
        logits = h_new @ ow
        mx = logits.max(-1, keepdims=True)
        lp = np.log(np.exp(logits - mx)
                    / np.exp(logits - mx).sum(-1, keepdims=True))
        noend = np.full((V,), -kinf, "float32")
        noend[end] = 0.0
        lp = np.where(finished[..., None], noend, lp)
        flat = (lp + log_probs[..., None]).reshape(B, beam * V)
        top = np.argsort(-flat, axis=1, kind="stable")[:, :beam]
        log_probs = np.take_along_axis(flat, top, axis=1)
        beam_idx = top // V
        token_idx = top % V
        h = np.take_along_axis(h_new, beam_idx[..., None], axis=1)
        finished = np.take_along_axis(finished, beam_idx, axis=1)
        lengths = np.take_along_axis(lengths, beam_idx, axis=1)
        lengths = lengths + (~finished).astype("int64")
        finished = finished | (token_idx == end)
        pred_hist.append(token_idx)
        parent_hist.append(beam_idx)
        ids = token_idx
    Tm = len(pred_hist)
    preds = np.stack(pred_hist)
    parents = np.stack(parent_hist)
    out = np.zeros_like(preds)
    for b in range(B):
        for k in range(beam):
            j = k
            for t in reversed(range(Tm)):
                out[t, b, k] = preds[t, b, j]
                j = parents[t, b, j]
    return out, lengths


# ---------------------------------------------------------------------------
# dynamic_lstmp
# ---------------------------------------------------------------------------
def test_dynamic_lstmp_matches_numpy():
    _fresh()
    B, T, D, P = 2, 4, 6, 3
    xp = fluid.data("xp", (None, T, 4 * D), "float32")
    proj, cell = layers.dynamic_lstmp(
        xp, size=4 * D, proj_size=P, use_peepholes=False)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    rng = np.random.default_rng(9)
    xv = rng.standard_normal((B, T, 4 * D)).astype("float32")
    proj_v, cell_v = exe.run(feed={"xp": xv}, fetch_list=[proj, cell])

    prog = fluid.default_main_program()
    pnames = [p.name for p in prog.global_block().all_parameters()]
    w, w_proj, b = _fetch_params(exe, pnames)
    r = np.zeros((B, P), "float32")
    c = np.zeros((B, D), "float32")
    rs, cs = [], []
    for t in range(T):
        gates = xv[:, t] + b.reshape(1, -1) + r @ w
        i, g, f, o = (gates[:, :D], gates[:, D:2 * D],
                      gates[:, 2 * D:3 * D], gates[:, 3 * D:])
        c = _sigmoid(f) * c + _sigmoid(i) * np.tanh(g)
        h = _sigmoid(o) * np.tanh(c)
        r = np.tanh(h @ w_proj)
        rs.append(r)
        cs.append(c)
    np.testing.assert_allclose(
        np.asarray(proj_v), np.stack(rs, 1), rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(
        np.asarray(cell_v), np.stack(cs, 1), rtol=2e-5, atol=2e-5)


def test_dynamic_lstmp_peephole_clip_runs():
    _fresh()
    B, T, D, P = 2, 3, 4, 2
    xp = fluid.data("xp2", (None, T, 4 * D), "float32")
    proj, cell = layers.dynamic_lstmp(
        xp, size=4 * D, proj_size=P, use_peepholes=True,
        cell_clip=1.0, proj_clip=0.5)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    rng = np.random.default_rng(13)
    xv = rng.standard_normal((B, T, 4 * D)).astype("float32")
    proj_v, cell_v = exe.run(feed={"xp2": xv}, fetch_list=[proj, cell])
    assert np.abs(np.asarray(proj_v)).max() <= 0.5 + 1e-6
    assert np.abs(np.asarray(cell_v)).max() <= 1.0 + 1e-6
    assert np.isfinite(np.asarray(proj_v)).all()


def test_get_initial_states_structure():
    _fresh()
    x = fluid.data("gis_x", (None, 4,), "float32")
    cell = layers.LSTMCell(hidden_size=6)
    states = cell.get_initial_states(batch_ref=x)
    assert isinstance(states, list) and len(states) == 2
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    a, b = exe.run(feed={"gis_x": np.zeros((3, 4), "float32")},
                   fetch_list=list(states))
    assert np.asarray(a).shape == (3, 6) and np.asarray(b).shape == (3, 6)


def test_rnn_time_major():
    _fresh()
    B, T, D_in, D = 3, 7, 4, 6
    # time-major layout: declare the full (T, B, D_in) shape with the
    # batch placeholder in dim 1, not the auto-prepended dim 0
    x = layers.data("xtm", (T, -1, D_in), append_batch_size=False,
                    dtype="float32")
    cell = layers.GRUCell(hidden_size=D, name="tmgru")
    outs, final = layers.rnn(cell, x, time_major=True)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    rng = np.random.default_rng(17)
    xv = rng.standard_normal((T, B, D_in)).astype("float32")
    out_v, fin_v = exe.run(feed={"xtm": xv}, fetch_list=[outs, final])
    assert np.asarray(out_v).shape == (T, B, D)
    assert np.asarray(fin_v).shape == (B, D)
    np.testing.assert_allclose(np.asarray(out_v)[-1], np.asarray(fin_v),
                               rtol=1e-6, atol=1e-6)


def test_dynamic_decode_final_states_are_final():
    _fresh()
    B, V, D, beam, steps = 2, 6, 4, 2, 4
    enc = fluid.data("encf", (None, D,), "float32")
    cell = layers.GRUCell(hidden_size=D, name="fsgru")
    decoder = layers.BeamSearchDecoder(
        cell, start_token=0, end_token=1, beam_size=beam,
        embedding_fn=lambda ids: layers.embedding(
            ids, size=[V, D], param_attr=fluid.ParamAttr(name="fsemb")),
        output_fn=lambda x: layers.fc(
            x, size=V, num_flatten_dims=len(x.shape) - 1, bias_attr=False))
    outputs, final_states = layers.dynamic_decode(
        decoder, inits=enc, max_step_num=steps - 1)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    rng = np.random.default_rng(19)
    encv = rng.standard_normal((B, D)).astype("float32")
    lens, fin, lp = exe.run(
        feed={"encf": encv},
        fetch_list=[final_states.lengths, final_states.finished,
                    final_states.log_probs])
    lens = np.asarray(lens)
    # lengths must have advanced past t=0 (the round-1 bug returned all 0)
    assert lens.max() >= 1, lens
    assert lens.max() <= steps
    assert np.asarray(lp).shape == (B, beam)
    assert np.asarray(fin).dtype == bool


def test_shared_param_attr_not_aliased():
    """A single ParamAttr instance passed to a multi-weight layer must
    yield DISTINCT parameters (the helper deepcopies the attr, ref
    layer_helper_base.py) — regression for gate/candidate weight
    aliasing in GRUCell and Weight/ProjWeight in dynamic_lstmp."""
    _fresh()
    x = fluid.data("pax", (None, 5, 4), "float32")
    cell = layers.GRUCell(hidden_size=6, param_attr=fluid.ParamAttr())
    outs, _ = layers.rnn(cell, x)
    prog = fluid.default_main_program()
    pnames = [p.name for p in prog.global_block().all_parameters()]
    assert len(pnames) == len(set(pnames)) == 4, pnames
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    out = exe.run(feed={"pax": np.zeros((2, 5, 4), "float32")},
                  fetch_list=[outs])[0]
    assert np.asarray(out).shape == (2, 5, 6)

    _fresh()
    xp = fluid.data("paxp", (None, 3, 24), "float32")
    proj, _ = layers.dynamic_lstmp(
        xp, size=24, proj_size=3, param_attr=fluid.ParamAttr(),
        use_peepholes=False)
    prog = fluid.default_main_program()
    pnames = [p.name for p in prog.global_block().all_parameters()]
    assert len(pnames) == len(set(pnames)) == 3, pnames
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    out = exe.run(feed={"paxp": np.zeros((2, 3, 24), "float32")},
                  fetch_list=[proj])[0]
    assert np.asarray(out).shape == (2, 3, 3)


def test_basic_gru_single_layer_matches_rnn_oracle():
    from paddle_tpu.fluid.contrib.layers import basic_gru

    _fresh()
    B, T, D_in, D = 2, 4, 3, 5
    x = fluid.data("bgx", (None, T, D_in), "float32")
    out, last_h = basic_gru(x, None, D, num_layers=1, name="bg1")
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    rng = np.random.default_rng(21)
    xv = rng.standard_normal((B, T, D_in)).astype("float32")
    out_v, lh_v = exe.run(feed={"bgx": xv}, fetch_list=[out, last_h])
    out_v, lh_v = np.asarray(out_v), np.asarray(lh_v)
    assert out_v.shape == (B, T, D)
    assert lh_v.shape == (1, B, D)
    prog = fluid.default_main_program()
    pnames = [p.name for p in prog.global_block().all_parameters()]
    gw, gb, cw, cb = _fetch_params(exe, pnames)
    h = np.zeros((B, D), "float32")
    for t in range(T):
        gates = _sigmoid(np.concatenate([xv[:, t], h], 1) @ gw + gb)
        r, u = gates[:, :D], gates[:, D:]
        cand = np.tanh(np.concatenate([xv[:, t], r * h], 1) @ cw + cb)
        h = u * h + (1 - u) * cand
        np.testing.assert_allclose(out_v[:, t], h, rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(lh_v[0], h, rtol=2e-5, atol=2e-5)


def test_basic_lstm_bidirectional_stacked():
    from paddle_tpu.fluid.contrib.layers import basic_lstm

    _fresh()
    B, T, D_in, D, L = 2, 5, 4, 6, 2
    x = fluid.data("blx", (None, T, D_in), "float32")
    out, last_h, last_c = basic_lstm(
        x, None, None, D, num_layers=L, bidirectional=True,
        dropout_prob=0.0, name="bl2")
    y = fluid.data("bly", (None, 1,), "float32")
    pred = layers.fc(layers.reduce_mean(out, dim=1), 1)
    loss = layers.reduce_mean(layers.square_error_cost(pred, y))
    fluid.optimizer.Adam(0.02).minimize(loss)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    rng = np.random.default_rng(23)
    xv = rng.standard_normal((B, T, D_in)).astype("float32")
    yv = xv.sum((1, 2))[:, None].astype("float32")
    o, lh, lc = exe.run(feed={"blx": xv, "bly": yv},
                        fetch_list=[out, last_h, last_c])
    assert np.asarray(o).shape == (B, T, 2 * D)
    assert np.asarray(lh).shape == (2 * L, B, D)
    assert np.asarray(lc).shape == (2 * L, B, D)
    first = last = None
    for _ in range(30):
        (lv,) = exe.run(feed={"blx": xv, "bly": yv}, fetch_list=[loss])
        first = float(lv) if first is None else first
        last = float(lv)
    assert last < first * 0.7, (first, last)


def test_basic_gru_init_hidden_consumed():
    from paddle_tpu.fluid.contrib.layers import basic_gru

    _fresh()
    B, T, D_in, D = 2, 3, 3, 4
    x = fluid.data("bghx", (None, T, D_in), "float32")
    h0 = layers.data("bgh0", (1, -1, D), append_batch_size=False,
                     dtype="float32")
    out, last_h = basic_gru(x, h0, D, num_layers=1, name="bgh")
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    rng = np.random.default_rng(29)
    xv = rng.standard_normal((B, T, D_in)).astype("float32")
    h0a = rng.standard_normal((1, B, D)).astype("float32")
    h0b = np.zeros((1, B, D), "float32")
    oa = np.asarray(exe.run(feed={"bghx": xv, "bgh0": h0a},
                            fetch_list=[out])[0])
    ob = np.asarray(exe.run(feed={"bghx": xv, "bgh0": h0b},
                            fetch_list=[out])[0])
    assert not np.allclose(oa, ob)  # init hidden actually flows in


def test_basic_lstm_partial_init_and_named_attr():
    """init_hidden without init_cell must still flow in (not silently
    zero both), and a NAMED param_attr must produce distinct per-layer
    per-direction per-role parameters."""
    from paddle_tpu.fluid.contrib.layers import basic_lstm

    _fresh()
    B, T, D_in, D = 2, 3, 3, 4
    x = fluid.data("plx", (None, T, D_in), "float32")
    h0 = layers.data("plh0", (1, -1, D), append_batch_size=False,
                     dtype="float32")
    out, lh, lc = basic_lstm(
        x, h0, None, D, num_layers=2, bidirectional=False,
        param_attr=fluid.ParamAttr(name="bl_named"), name="blpi")
    prog = fluid.default_main_program()
    pnames = [p.name for p in prog.global_block().all_parameters()]
    assert len(pnames) == len(set(pnames)), pnames
    named = [n for n in pnames if n.startswith("bl_named")]
    assert len(named) == 2, named  # one weight per layer, role-suffixed
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    rng = np.random.default_rng(31)
    xv = rng.standard_normal((B, T, D_in)).astype("float32")
    oa = np.asarray(exe.run(
        feed={"plx": xv,
              "plh0": rng.standard_normal((2, B, D)).astype("float32")},
        fetch_list=[out])[0])
    ob = np.asarray(exe.run(
        feed={"plx": xv, "plh0": np.zeros((2, B, D), "float32")},
        fetch_list=[out])[0])
    assert not np.allclose(oa, ob)  # h0 flows in despite init_cell=None


def test_rnn_cell_under_data_parallel_mesh():
    """rnn()'s lax.scan lowers under the dp-sharded CompiledProgram mesh
    (GSPMD partitions the carried state over the batch axis)."""
    _fresh()
    B, T, D_in, D = 8, 4, 3, 6
    x = fluid.data("dpx", (None, T, D_in), "float32")
    y = fluid.data("dpy", (None, 1,), "float32")
    cell = layers.GRUCell(hidden_size=D, name="dpgru")
    outs, final = layers.rnn(cell, x)
    pred = layers.fc(final, 1)
    loss = layers.reduce_mean(layers.square_error_cost(pred, y))
    fluid.optimizer.Adam(0.02).minimize(loss)

    prog = fluid.CompiledProgram(
        fluid.default_main_program()).with_data_parallel(
            loss_name=loss.name)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    rng = np.random.default_rng(41)
    xv = rng.standard_normal((B, T, D_in)).astype("float32")
    yv = xv.sum((1, 2))[:, None].astype("float32")
    losses = [float(np.asarray(exe.run(prog, feed={"dpx": xv, "dpy": yv},
                                       fetch_list=[loss])[0]))
              for _ in range(25)]
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])
