"""KV-cache incremental transformer decode correctness: the
TransformerDecodeCell (models/transformer_nmt.py) under
BeamSearchDecoder must reproduce, token for token, a greedy re-decode
that re-runs the FULL training graph on the growing prefix with the
SAME weights (shared by parameter name)."""
import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.models import transformer_nmt as T


def _cfg():
    return T.NMTConfig(src_vocab=40, tgt_vocab=40, hidden=32, heads=4,
                       ffn=64, enc_layers=2, dec_layers=2, max_len=16,
                       dropout=0.0)


def test_kv_cache_greedy_matches_full_prefix_rerun():
    cfg = _cfg()
    src_len, out_len = 6, 8
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 9
    with fluid.program_guard(main, startup):
        dec_vs = T.build_transformer_beam_decode(
            cfg, src_len, out_len, beam_size=1)
        # the training graph shares every parameter by name
        train_vs = T.build_transformer_nmt(cfg, src_len, out_len)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)

    rng = np.random.default_rng(4)
    B = 3
    src = rng.integers(cfg.pad_id + 1, cfg.src_vocab,
                       size=(B, src_len)).astype("int64")
    dummy = np.zeros((B, out_len), dtype="int64")
    ids = np.asarray(exe.run(
        main,
        feed={"src_ids": src, "tgt_ids": dummy, "tgt_labels": dummy},
        fetch_list=[dec_vs["ids"]])[0])
    assert ids.shape == (B, out_len, 1)
    beam0 = ids[:, :, 0]

    # greedy reference: feed the growing prefix through the TRAINING
    # decoder (full attention over the whole prefix, no cache)
    prefix = np.full((B, out_len), cfg.bos_id, dtype="int64")
    done = np.zeros(B, dtype=bool)
    greedy = np.zeros((B, out_len), dtype="int64")
    dummy_labels = np.zeros((B, out_len), dtype="int64")
    for t in range(out_len):
        logits = np.asarray(exe.run(
            main,
            feed={"src_ids": src, "tgt_ids": prefix,
                  "tgt_labels": dummy_labels},
            fetch_list=[train_vs["logits"]])[0])
        nxt = np.argmax(logits[:, t, :], axis=-1)
        nxt = np.where(done, cfg.eos_id, nxt)
        greedy[:, t] = nxt
        done |= nxt == cfg.eos_id
        if t + 1 < out_len:
            prefix[:, t + 1] = nxt

    np.testing.assert_array_equal(beam0, greedy)


def test_beam_scores_monotone_and_finite():
    cfg = _cfg()
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 2
    with fluid.program_guard(main, startup):
        vs = T.build_transformer_beam_decode(cfg, 5, 6, beam_size=4)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    src = np.random.default_rng(0).integers(
        cfg.pad_id + 1, cfg.src_vocab, size=(2, 5)).astype("int64")
    ids, scores = exe.run(main, feed={"src_ids": src},
                          fetch_list=[vs["ids"], vs["scores"]])
    scores = np.asarray(scores)
    assert np.isfinite(scores).all()
    # beams are cumulative log-probs: all <= 0 and beam 0 is the best
    assert (scores <= 1e-5).all()
    assert np.allclose(scores[:, 0], scores.max(axis=1))


def test_decode_cache_write_matches_masked_path():
    """The decode_cache_write fast path (dynamic_update_slice at the
    uniform position) is bit-identical to the one-hot masked rewrite,
    and update_cache without pos or masks raises."""
    import numpy as np
    import pytest

    import paddle_tpu.fluid as fluid
    from paddle_tpu.models.decode_utils import step_masks, update_cache

    B, T, H, P = 3, 6, 4, 2
    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        cache = fluid.data("cw_cache", (None, T, H), "float32")
        val = fluid.data("cw_val", (None, 1, H), "float32")
        pos = fluid.data("cw_pos", (None, 1), "int64")
        w3, k3, _ = step_masks(pos, T)
        fast = update_cache(cache, val, pos=pos)
        masked = update_cache(cache, val, w3, k3)
        with pytest.raises(ValueError, match="pos .*or the write3"):
            update_cache(cache, val)
    exe = fluid.Executor()
    exe.run(startup)
    rng = np.random.default_rng(0)
    feed = {
        "cw_cache": rng.standard_normal((B, T, H)).astype("float32"),
        "cw_val": rng.standard_normal((B, 1, H)).astype("float32"),
        "cw_pos": np.full((B, 1), P, "int64"),
    }
    f, m = exe.run(prog, feed=feed, fetch_list=[fast, masked])
    np.testing.assert_array_equal(np.asarray(f), np.asarray(m))
    # the write landed at position P and only there
    np.testing.assert_array_equal(np.asarray(f)[:, P], feed["cw_val"][:, 0])
    np.testing.assert_array_equal(
        np.delete(np.asarray(f), P, axis=1),
        np.delete(feed["cw_cache"], P, axis=1))
