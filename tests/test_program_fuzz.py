"""Seeded program fuzzer: random layer stacks must build, run, and
backprop finite values — broad-spectrum robustness over the op library
(complements the per-op oracle tests)."""
import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import framework, unique_name


@pytest.fixture(autouse=True)
def _fresh_program():
    framework.switch_main_program(framework.Program())
    framework.switch_startup_program(framework.Program())
    unique_name.switch()
    yield


def _rand_stack(rng, x, width):
    """Apply 3-6 random layers, keeping a 2-D (batch, width) tensor."""
    L = fluid.layers
    n_layers = int(rng.integers(3, 7))
    for _ in range(n_layers):
        choice = int(rng.integers(0, 8))
        if choice == 0:
            x = L.fc(x, size=width, act="relu")
        elif choice == 1:
            x = L.fc(x, size=width, act="tanh")
        elif choice == 2:
            x = L.dropout(x, dropout_prob=0.1)
        elif choice == 3:
            x = L.layer_norm(x)
        elif choice == 4:
            x = L.elementwise_add(x, L.scale(x, scale=0.5))
        elif choice == 5:
            x = L.hard_swish(x)
        elif choice == 6:
            x = L.softmax(x)
        else:
            x = L.elementwise_mul(
                x, L.sigmoid(L.fc(x, size=width))
            )
    return x


@pytest.mark.parametrize("seed", range(8))
def test_random_program_trains_finite(seed):
    rng = np.random.default_rng(seed)
    batch = int(rng.integers(2, 9))
    width = int(rng.integers(4, 33))
    fluid.default_startup_program().random_seed = seed + 1
    fluid.default_main_program().random_seed = seed + 1
    x = fluid.data(name="x", shape=[batch, width], dtype="float32",
                   append_batch_size=False)
    y = fluid.data(name="y", shape=[batch, 1], dtype="float32",
                   append_batch_size=False)
    h = _rand_stack(rng, x, width)
    pred = fluid.layers.fc(h, size=1)
    loss = fluid.layers.reduce_mean(
        fluid.layers.square_error_cost(pred, y))
    opt_cls = [fluid.optimizer.SGD, fluid.optimizer.Adam,
               fluid.optimizer.Momentum][seed % 3]
    if opt_cls is fluid.optimizer.Momentum:
        opt = opt_cls(learning_rate=1e-3, momentum=0.9)
    else:
        opt = opt_cls(learning_rate=1e-3)
    opt.minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    feed = {
        "x": rng.standard_normal((batch, width), dtype=np.float32),
        "y": rng.standard_normal((batch, 1), dtype=np.float32),
    }
    for _ in range(3):
        lv = float(exe.run(feed=feed, fetch_list=[loss])[0])
        assert np.isfinite(lv)
    # repeatability: the same seeded program re-runs identically
    lv2 = float(exe.run(feed=feed, fetch_list=[loss])[0])
    assert np.isfinite(lv2)
