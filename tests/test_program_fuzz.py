"""Seeded program fuzzer: random layer stacks must build, run, and
backprop finite values — broad-spectrum robustness over the op library
(complements the per-op oracle tests)."""
import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import framework, unique_name


@pytest.fixture(autouse=True)
def _fresh_program():
    framework.switch_main_program(framework.Program())
    framework.switch_startup_program(framework.Program())
    unique_name.switch()
    yield


def _rand_stack(rng, x, width):
    """Apply 3-6 random layers, keeping a 2-D (batch, width) tensor."""
    L = fluid.layers
    n_layers = int(rng.integers(3, 7))
    for _ in range(n_layers):
        choice = int(rng.integers(0, 8))
        if choice == 0:
            x = L.fc(x, size=width, act="relu")
        elif choice == 1:
            x = L.fc(x, size=width, act="tanh")
        elif choice == 2:
            x = L.dropout(x, dropout_prob=0.1)
        elif choice == 3:
            x = L.layer_norm(x)
        elif choice == 4:
            x = L.elementwise_add(x, L.scale(x, scale=0.5))
        elif choice == 5:
            x = L.hard_swish(x)
        elif choice == 6:
            x = L.softmax(x)
        else:
            x = L.elementwise_mul(
                x, L.sigmoid(L.fc(x, size=width))
            )
    return x


@pytest.mark.parametrize("seed", range(8))
def test_random_program_trains_finite(seed):
    rng = np.random.default_rng(seed)
    batch = int(rng.integers(2, 9))
    width = int(rng.integers(4, 33))
    fluid.default_startup_program().random_seed = seed + 1
    fluid.default_main_program().random_seed = seed + 1
    x = fluid.data(name="x", shape=[batch, width], dtype="float32")
    y = fluid.data(name="y", shape=[batch, 1], dtype="float32")
    h = _rand_stack(rng, x, width)
    pred = fluid.layers.fc(h, size=1)
    loss = fluid.layers.reduce_mean(
        fluid.layers.square_error_cost(pred, y))
    opt_cls = [fluid.optimizer.SGD, fluid.optimizer.Adam,
               fluid.optimizer.Momentum][seed % 3]
    if opt_cls is fluid.optimizer.Momentum:
        opt = opt_cls(learning_rate=1e-3, momentum=0.9)
    else:
        opt = opt_cls(learning_rate=1e-3)
    opt.minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    feed = {
        "x": rng.standard_normal((batch, width), dtype=np.float32),
        "y": rng.standard_normal((batch, 1), dtype=np.float32),
    }
    for _ in range(3):
        lv = float(exe.run(feed=feed, fetch_list=[loss])[0])
        assert np.isfinite(lv)
    # repeatability: the same seeded program re-runs identically
    lv2 = float(exe.run(feed=feed, fetch_list=[loss])[0])
    assert np.isfinite(lv2)


def _rand_seq_stack(rng, x, width):
    """Random sequence-model stack over a (B, T, D) tensor using the cell
    API (GRU/LSTM rnn), fc, dropout, and layer_norm — ends with a
    (B, D') tensor."""
    L = fluid.layers
    n = int(rng.integers(1, 4))
    for _ in range(n):
        choice = int(rng.integers(0, 5))
        if choice == 0:
            cell = L.GRUCell(hidden_size=width,
                             name="fz_gru%d" % int(rng.integers(1e6)))
            x, _ = L.rnn(cell, x)
        elif choice == 1:
            cell = L.LSTMCell(hidden_size=width,
                              name="fz_lstm%d" % int(rng.integers(1e6)))
            x, _ = L.rnn(cell, x, is_reverse=bool(rng.integers(0, 2)))
        elif choice == 2:
            x = L.fc(x, size=width, num_flatten_dims=2, act="relu")
        elif choice == 3:
            x = L.dropout(x, dropout_prob=0.1)
        else:
            x = L.layer_norm(x, begin_norm_axis=2)
    pool = int(rng.integers(0, 3))
    if pool == 0:
        return L.reduce_mean(x, dim=1)
    if pool == 1:
        return L.reduce_max(x, dim=1)
    return L.sequence_last_step(x)


@pytest.mark.parametrize("seed", range(100, 106))
def test_random_seq_program_trains_finite(seed):
    rng = np.random.default_rng(seed)
    batch = int(rng.integers(2, 5))
    T = int(rng.integers(3, 7))
    width = int(rng.integers(4, 17))
    fluid.default_startup_program().random_seed = seed + 1
    fluid.default_main_program().random_seed = seed + 1
    x = fluid.data(name="x", shape=[batch, T, width], dtype="float32")
    y = fluid.data(name="y", shape=[batch, 1], dtype="float32")
    h = _rand_seq_stack(rng, x, width)
    pred = fluid.layers.fc(h, size=1)
    loss = fluid.layers.reduce_mean(
        fluid.layers.square_error_cost(pred, y))
    fluid.optimizer.Adam(0.01).minimize(loss)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    xv = np.random.default_rng(seed).standard_normal(
        (batch, T, width)).astype("float32")
    yv = xv.sum((1, 2))[:, None].astype("float32")
    vals = [float(exe.run(feed={"x": xv, "y": yv},
                          fetch_list=[loss])[0]) for _ in range(5)]
    assert all(np.isfinite(v) for v in vals), vals
