"""Optimizer numeric + convergence tests (mirrors reference
test_optimizer.py + per-optimizer op tests): every optimizer must descend a
quadratic bowl; Adam/Momentum checked against closed-form updates."""
import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid.executor import global_scope


def _bowl_loss(name="wq"):
    """loss = mean((w - 3)^2) over a 4-vector parameter."""
    w = fluid.layers.create_parameter(
        [4], "float32", name=name,
        default_initializer=fluid.initializer.Constant(0.0))
    target = fluid.layers.fill_constant([4], "float32", 3.0)
    diff = fluid.layers.elementwise_sub(w, target)
    return fluid.layers.reduce_mean(fluid.layers.square(diff))


OPTIMIZERS = [
    ("sgd", lambda: fluid.optimizer.SGD(learning_rate=0.2), 60),
    ("momentum", lambda: fluid.optimizer.Momentum(0.1, momentum=0.9), 60),
    # LARS trust ratio ~ ||w||/||g|| is tiny near w=0, so it needs more steps
    ("lars", lambda: fluid.optimizer.LarsMomentum(0.5, momentum=0.9), 300),
    ("adagrad", lambda: fluid.optimizer.Adagrad(0.5), 120),
    ("decayed_adagrad",
     lambda: fluid.optimizer.DecayedAdagrad(0.5), 120),
    ("adadelta",
     lambda: fluid.optimizer.Adadelta(3.0, epsilon=1e-4), 150),
    ("adam", lambda: fluid.optimizer.Adam(0.3), 80),
    ("adamax", lambda: fluid.optimizer.Adamax(0.3), 80),
    ("rmsprop", lambda: fluid.optimizer.RMSProp(0.3), 80),
    ("ftrl", lambda: fluid.optimizer.Ftrl(0.9), 150),
    ("lamb", lambda: fluid.optimizer.Lamb(0.1), 120),
    ("dpsgd", lambda: fluid.optimizer.Dpsgd(0.3, clip=5.0, batch_size=1.0,
                                            sigma=0.0), 200),
]


@pytest.mark.parametrize("name,make,steps", OPTIMIZERS,
                         ids=[o[0] for o in OPTIMIZERS])
def test_optimizer_descends_bowl(name, make, steps):
    loss = _bowl_loss()
    opt = make()
    opt.minimize(loss)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    first = float(exe.run(fetch_list=[loss])[0])
    for _ in range(steps - 1):
        out = exe.run(fetch_list=[loss])
    last = float(out[0])
    assert last < first * 0.15, (
        "%s failed to descend: %.4f -> %.4f" % (name, first, last))


def test_sgd_matches_closed_form():
    loss = _bowl_loss(name="w_sgd")
    fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    exe.run(fetch_list=[loss])
    # grad of mean((w-3)^2) at w=0 is 2*(0-3)/4 = -1.5 ; w1 = 0.1*1.5
    np.testing.assert_allclose(
        np.asarray(global_scope()["w_sgd"]),
        np.full(4, 0.15, "float32"), rtol=1e-5)


def test_adam_first_step_matches_formula():
    loss = _bowl_loss(name="w_adam")
    fluid.optimizer.Adam(learning_rate=0.01, beta1=0.9, beta2=0.999,
                         epsilon=1e-8).minimize(loss)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    exe.run(fetch_list=[loss])
    g = -1.5
    m = 0.1 * g
    v = 0.001 * g * g
    mh = m / (1 - 0.9)
    vh = v / (1 - 0.999)
    expect = 0.0 - 0.01 * mh / (np.sqrt(vh) + 1e-8)
    np.testing.assert_allclose(
        np.asarray(global_scope()["w_adam"]),
        np.full(4, expect, "float32"), rtol=1e-4)


def test_momentum_accumulator_state_persists():
    loss = _bowl_loss(name="w_mom")
    fluid.optimizer.Momentum(0.1, momentum=0.9).minimize(loss)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    exe.run(fetch_list=[loss])
    w1 = np.asarray(global_scope()["w_mom"]).copy()
    exe.run(fetch_list=[loss])
    w2 = np.asarray(global_scope()["w_mom"])
    # velocity carries over: second step moves farther than the first
    assert np.all(np.abs(w2 - w1) > np.abs(w1 - 0.0))


def test_grad_clip_by_global_norm():
    loss = _bowl_loss(name="w_clip")
    fluid.clip.set_gradient_clip(
        fluid.clip.GradientClipByGlobalNorm(clip_norm=0.01))
    fluid.optimizer.SGD(learning_rate=1.0).minimize(loss)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    exe.run(fetch_list=[loss])
    w = np.asarray(global_scope()["w_clip"])
    # ||update|| = lr * clip_norm
    assert np.linalg.norm(w) <= 0.0101


def test_l2_regularizer_changes_update():
    w = fluid.layers.create_parameter(
        [4], "float32", name="w_reg",
        default_initializer=fluid.initializer.Constant(1.0),
        attr=fluid.ParamAttr(
            name="w_reg",
            regularizer=fluid.regularizer.L2Decay(0.5)))
    loss = fluid.layers.reduce_mean(w)  # grad = 0.25 each
    fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    exe.run(fetch_list=[loss])
    # update = lr * (0.25 + 0.5 * 1.0) = 0.075
    np.testing.assert_allclose(
        np.asarray(global_scope()["w_reg"]),
        np.full(4, 1.0 - 0.075, "float32"), rtol=1e-5)


def test_lr_scheduler_exponential_decay():
    loss = _bowl_loss(name="w_lr")
    lr = fluid.layers.exponential_decay(
        learning_rate=0.1, decay_steps=1, decay_rate=0.5, staircase=True)
    fluid.optimizer.SGD(learning_rate=lr).minimize(loss)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    exe.run(fetch_list=[loss])
    w1 = np.asarray(global_scope()["w_lr"]).copy()
    # step 0 used lr=0.1 -> w1 = 0.1 * 1.5 = 0.15
    np.testing.assert_allclose(w1, np.full(4, 0.15, "float32"), rtol=1e-5)
    exe.run(fetch_list=[loss])
    w2 = np.asarray(global_scope()["w_lr"])
    # step 1 used lr=0.05; grad at 0.15 = 2*(0.15-3)/4 = -1.425
    np.testing.assert_allclose(
        w2, w1 + 0.05 * 1.425, rtol=1e-4)


def test_ema_tracks_params():
    loss = _bowl_loss(name="w_ema")
    opt = fluid.optimizer.SGD(learning_rate=0.2)
    opt.minimize(loss)
    ema = fluid.optimizer.ExponentialMovingAverage(0.5)
    ema.update()
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    for _ in range(5):
        exe.run(fetch_list=[loss])
    w = np.asarray(global_scope()["w_ema"]).copy()
    with ema.apply(exe):
        w_avg = np.asarray(global_scope()["w_ema"]).copy()
    w_restored = np.asarray(global_scope()["w_ema"])
    np.testing.assert_allclose(w_restored, w)
    # EMA lags behind the raw trajectory toward 3.0
    assert np.all(w_avg < w)


def test_recompute_optimizer_same_result_as_plain():
    import paddle_tpu.fluid as fl

    def run(use_recompute):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.data("x", [None, 8], dtype="float32")
            h1 = fl.layers.fc(x, size=8, act="relu",
                              param_attr=fluid.ParamAttr(
                                  name="rw1",
                                  initializer=fluid.initializer.Constant(0.1)))
            h2 = fl.layers.fc(h1, size=8, act="relu",
                              param_attr=fluid.ParamAttr(
                                  name="rw2",
                                  initializer=fluid.initializer.Constant(0.1)))
            loss = fl.layers.reduce_mean(h2)
            opt = fluid.optimizer.SGD(learning_rate=0.5)
            if use_recompute:
                opt = fluid.optimizer.RecomputeOptimizer(opt)
                opt._set_checkpoints([h1])
            opt.minimize(loss)
        exe = fluid.Executor()
        from paddle_tpu.fluid.executor import Scope, scope_guard
        with scope_guard(Scope()):
            exe.run(startup)
            exe.run(main, feed={"x": np.ones((2, 8), "float32")},
                    fetch_list=[loss])
            from paddle_tpu.fluid.executor import global_scope
            return np.asarray(global_scope()["rw1"]).copy()

    np.testing.assert_allclose(run(False), run(True), rtol=1e-6)


def test_model_average_apply_restore_numeric():
    """ModelAverage must (a) capture params by default (ParamAttr's
    do_model_average defaults True like the reference — regression: it
    was False, silently averaging NOTHING), (b) swap in the accumulated
    average under apply(), (c) restore originals exactly."""
    prog, startup = fluid.Program(), fluid.Program()
    prog.random_seed = startup.random_seed = 11
    with fluid.program_guard(prog, startup):
        x = fluid.data("max", (None, 4,), "float32")
        y = fluid.data("may", (None, 1,), "float32")
        pred = fluid.layers.fc(x, 1, param_attr=fluid.ParamAttr(name="maw"),
                               bias_attr=False)
        loss = fluid.layers.reduce_mean(
            fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(0.1).minimize(loss)
        ma = fluid.optimizer.ModelAverage(
            0.5, min_average_window=1, max_average_window=4)
    assert any(p.name == "maw" for p, _ in ma.params_grads)
    exe = fluid.Executor()
    exe.run(startup)
    rng = np.random.default_rng(0)
    feed = {"max": rng.standard_normal((8, 4)).astype("float32"),
            "may": rng.standard_normal((8, 1)).astype("float32")}
    history = []
    for _ in range(10):
        exe.run(prog, feed=feed, fetch_list=[loss])
        history.append(np.asarray(fluid.global_scope()["maw"]).copy())
    final = history[-1].copy()
    # two-window oracle mirroring the accumulate rule: sum_1 shifts into
    # sum_2 when num_acc reaches min(max_w, max(min_w, rate*num_updates))
    rate, min_w, max_w = 0.5, 1, 4
    s1 = s2 = np.zeros_like(history[0])
    n_acc = old = nupd = 0.0
    for h in history:
        s1 = s1 + h
        n_acc += 1
        nupd += 1
        thresh = min(max_w, max(min_w, rate * nupd))
        if n_acc >= thresh:
            s2, old = s1, n_acc
            s1, n_acc = np.zeros_like(s1), 0.0
    want = (s1 + s2) / (n_acc + old)
    with ma.apply(exe):
        averaged = np.asarray(fluid.global_scope()["maw"]).copy()
        assert not np.allclose(averaged, final)
        np.testing.assert_allclose(averaged, want, rtol=1e-5, atol=1e-6)
    np.testing.assert_array_equal(
        np.asarray(fluid.global_scope()["maw"]), final)
