"""Regression tests for review findings: prune w/ control-flow sub-blocks,
sharding-rule anchoring, density priors, nms_top_k, box_clip rank, stable
endpoint hashing, NMT pad/eos separation."""
import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers


def test_prune_keeps_params_used_inside_while_body():
    i = layers.fill_constant([1], "float32", 0.0)
    n = layers.fill_constant([1], "float32", 3.0)
    x = fluid.data("x", [None, 4], dtype="float32")
    acc = layers.fill_constant_batch_size_like(x, [-1, 4], "float32", 0.0)

    def body(it, a):
        h = layers.fc(a, size=4,
                      param_attr=fluid.ParamAttr(name="loop_w"),
                      bias_attr=False)
        return layers.increment(it, in_place=False), h

    _, out = layers.while_loop(
        lambda it, a: layers.less_than(it, n), body, [i, acc])
    pruned = fluid.default_main_program()._prune([out])
    kept = {v.name for v in pruned.list_vars()}
    assert "loop_w" in kept, "param used only in while body must survive prune"
    # and the pruned program still runs
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    (o,) = exe.run(pruned, feed={"x": np.ones((2, 4), "float32")},
                   fetch_list=[out])
    assert np.asarray(o).shape == (2, 4)


def test_prune_keeps_producer_of_var_read_only_in_sub_block():
    """A var produced OUTSIDE the loop but read only INSIDE the body must
    keep its producing op through _prune."""
    x = fluid.data("x", [None, 4], dtype="float32")
    bias = layers.scale(x, scale=3.0)  # producer outside the loop
    i = layers.fill_constant([1], "float32", 0.0)
    n = layers.fill_constant([1], "float32", 2.0)
    acc = layers.fill_constant_batch_size_like(x, [-1, 4], "float32", 0.0)

    def body(it, a):
        return (layers.increment(it, in_place=False),
                layers.elementwise_add(a, bias))

    _, out = layers.while_loop(
        lambda it, a: layers.less_than(it, n), body, [i, acc])
    pruned = fluid.default_main_program()._prune([out])
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    (o,) = exe.run(pruned, feed={"x": np.ones((2, 4), "float32")},
                   fetch_list=[out])
    np.testing.assert_allclose(np.asarray(o), np.full((2, 4), 6.0))


def test_sharding_rule_annotation_is_exact_match():
    from paddle_tpu.parallel.sharding import DistributedProgram
    from paddle_tpu.parallel.mesh import build_mesh
    from jax.sharding import PartitionSpec as P
    import jax

    if len(jax.devices()) < 8:
        return
    mesh = build_mesh({"tp": 8})
    prog = fluid.default_main_program()
    prog._sharding_spec = [("emb", P("tp", None))]
    dist = DistributedProgram(prog, mesh, feed_axis=None)
    sharded = dist.param_sharding("emb", (16, 4))
    other = dist.param_sharding("src_emb", (16, 4))
    assert sharded.spec == P("tp", None)
    assert other.spec == P()  # suffix name must NOT inherit the rule


def test_density_prior_box_subgrid_offsets():
    feat = fluid.data("feat", [1, 8, 2, 2])
    img = fluid.data("img", [1, 3, 64, 64])
    box, var = layers.density_prior_box(
        feat, img, densities=[2], fixed_sizes=[16.0], fixed_ratios=[1.0])
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    (b,) = exe.run(
        feed={"feat": np.zeros((1, 8, 2, 2), "float32"),
              "img": np.zeros((1, 3, 64, 64), "float32")},
        fetch_list=[box])
    b = np.asarray(b)  # (H, W, 4 priors, 4)
    assert b.shape == (2, 2, 4, 4)
    cell = b[0, 0]  # 4 priors of one cell
    # density 2 => the 4 priors sit on a 2x2 sub-grid, NOT stacked identical
    assert len({tuple(np.round(p, 5)) for p in cell}) == 4
    # sub-grid shift = step/d = 32/2 = 16px => 0.25 normalized
    centers_x = (cell[:, 0] + cell[:, 2]) / 2
    assert np.isclose(sorted(set(np.round(centers_x, 4)))[1]
                      - sorted(set(np.round(centers_x, 4)))[0], 0.25)


def test_multiclass_nms_respects_nms_top_k():
    # two far-apart boxes, same class, both above threshold
    boxes = np.array([[[0, 0, 10, 10], [50, 50, 60, 60]]], "float32")
    scores = np.array([[[0.0, 0.0], [0.9, 0.8]]], "float32")  # class1 scores
    b = fluid.data("b", [1, 2, 4])
    s = fluid.data("s", [1, 2, 2])
    out = layers.multiclass_nms(b, s, score_threshold=0.1, nms_top_k=1,
                                keep_top_k=5, background_label=0)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    (o,) = exe.run(feed={"b": boxes, "s": scores}, fetch_list=[out])
    o = np.asarray(o)[0]
    n_detected = int((o[:, 0] >= 0).sum())
    assert n_detected == 1, "nms_top_k=1 must keep only the best candidate"


def test_box_clip_preserves_2d_rank():
    b = fluid.data("b", [5, 4])
    info = fluid.data("im", [1, 3])
    out = layers.box_clip(b, info)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    (o,) = exe.run(
        feed={"b": np.array([[-5, -5, 200, 200]] * 5, "float32"),
              "im": np.array([[100, 100, 1.0]], "float32")},
        fetch_list=[out])
    assert np.asarray(o).shape == (5, 4)
    assert np.asarray(o).max() <= 99.0


def test_hashname_dispatch_is_stable_digest():
    import zlib
    from paddle_tpu.fluid.transpiler import HashName

    eps = ["ep0", "ep1", "ep2"]

    class V:
        def __init__(self, name):
            self.name = name

    vs = [V("fc_0.w_0"), V("emb"), V("fc_1.b_0")]
    got = HashName(eps).dispatch(vs)
    expect = [eps[zlib.crc32(v.name.encode()) % 3] for v in vs]
    assert got == expect


def test_nmt_trains_eos_but_masks_pad():
    from paddle_tpu.models.transformer_nmt import (
        NMTConfig, synthetic_pair_batch)

    cfg = NMTConfig(src_vocab=50, tgt_vocab=50, hidden=16, heads=2, ffn=32,
                    enc_layers=1, dec_layers=1)
    src, tgt, labels = synthetic_pair_batch(cfg, 4, 8, 8)
    assert (labels == cfg.eos_id).any(), "labels must contain real EOS"
    assert not (labels == cfg.pad_id).any()
    assert src.min() > cfg.pad_id


def test_prune_keeps_cond_branch_params():
    """_prune must follow true_block/false_block attrs: params used only
    inside a cond branch survive pruning (save_inference_model path)."""
    import numpy as np
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import framework, layers, unique_name
    from paddle_tpu.fluid.param_attr import ParamAttr

    framework.switch_main_program(framework.Program())
    framework.switch_startup_program(framework.Program())
    unique_name.switch()
    fluid.default_startup_program().random_seed = 2

    x = fluid.data(name="x", shape=[None, 4], dtype="float32")
    pred = layers.greater_than(
        layers.reduce_sum(x), layers.fill_constant([1], "float32", 0.0)
    )
    out = layers.cond(
        pred,
        lambda: layers.fc(x, 4, param_attr=ParamAttr(name="w_cond")),
        lambda: layers.scale(x, 2.0),
    )
    prog = fluid.default_main_program()
    pruned = prog._prune([out])
    assert "w_cond" in pruned.global_block().vars

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    res = exe.run(
        pruned,
        feed={"x": np.ones((2, 4), np.float32)},
        fetch_list=[out.name],
    )[0]
    assert res.shape == (2, 4)


def test_dropout_rbg_mask_consistent_between_fwd_and_grad():
    """The rbg dropout path (ops/nn_ops.py _dropout_keep_mask) must
    reproduce the SAME mask in the vjp replay as in the forward pass:
    grad(mean(dropout(x)*w)) w.r.t. x is nonzero exactly where the
    forward output kept elements."""
    import numpy as np
    import paddle_tpu.fluid as fluid

    prog = fluid.Program()
    startup = fluid.Program()
    prog.random_seed = 5
    with fluid.program_guard(prog, startup):
        x = fluid.data("drx", (None, 64,), "float32")
        y = fluid.layers.dropout(
            x, dropout_prob=0.5, dropout_implementation="upscale_in_train")
        loss = fluid.layers.reduce_mean(y)
        grads = fluid.backward.gradients([loss], [x])
    exe = fluid.Executor()
    exe.run(startup)
    xv = np.random.default_rng(3).standard_normal((8, 64)).astype("float32")
    xv[xv == 0] = 1.0
    y_v, g_v = exe.run(prog, feed={"drx": xv}, fetch_list=[y, grads[0]])
    y_v, g_v = np.asarray(y_v), np.asarray(g_v)
    kept_fwd = y_v != 0
    kept_bwd = g_v != 0
    np.testing.assert_array_equal(kept_fwd, kept_bwd)
    # masks advance with the step counter (fresh randomness each run)
    y2 = np.asarray(exe.run(prog, feed={"drx": xv}, fetch_list=[y])[0])
    assert (y_v != y2).any()
    # keep rate plausible for p=0.5
    assert 0.3 < kept_fwd.mean() < 0.7


def test_dropout_8bit_masks_unbiased(monkeypatch):
    """The opt-in 8-bit rbg mask path (PADDLE_TPU_DROPOUT_BITS=8):
    keep rate matches the QUANTIZED threshold t/256 and upscale uses
    that exact probability, so E[dropout(x)] == x. The default (32)
    produces a float-threshold mask."""
    import numpy as np
    import paddle_tpu.fluid as fluid

    def run(bits, p=0.1, n=(64, 1024)):
        monkeypatch.setenv("PADDLE_TPU_DROPOUT_BITS", bits)
        prog, startup = fluid.Program(), fluid.Program()
        prog.random_seed = 11
        with fluid.program_guard(prog, startup):
            x = fluid.data("d8x", (None, n[1]), "float32")
            y = fluid.layers.dropout(
                x, dropout_prob=p,
                dropout_implementation="upscale_in_train")
        exe = fluid.Executor()
        exe.run(startup)
        xv = np.ones(n, np.float32)
        return np.asarray(exe.run(prog, feed={"d8x": xv},
                                  fetch_list=[y])[0])

    y8 = run("8")
    kept = y8 != 0
    # threshold for p=0.1: t = round(0.9*256) = 230 -> keep 230/256
    t_keep = 230.0 / 256.0
    assert abs(kept.mean() - t_keep) < 0.01
    # kept values upscaled by the EXACT quantized keep prob
    np.testing.assert_allclose(y8[kept], 256.0 / 230.0, rtol=1e-6)
    # unbiased: E[y] == 1
    assert abs(y8.mean() - 1.0) < 0.02

    y32 = run("32")
    kept32 = y32 != 0
    assert abs(kept32.mean() - 0.9) < 0.01
    np.testing.assert_allclose(y32[kept32], 1.0 / 0.9, rtol=1e-6)


def test_dropout_8bit_quantization_gate(monkeypatch):
    """Tiny drop rates fall back to the float-threshold path even with
    8-bit masks opted in: p=0.002 quantized to 1/256 would nearly
    double the drop rate, so the gate must reject it (drop rate stays
    ~0.002, not ~0.0039)."""
    import numpy as np
    import paddle_tpu.fluid as fluid

    monkeypatch.setenv("PADDLE_TPU_DROPOUT_BITS", "8")
    prog, startup = fluid.Program(), fluid.Program()
    prog.random_seed = 13
    with fluid.program_guard(prog, startup):
        x = fluid.data("dqx", (None, 4096), "float32")
        y = fluid.layers.dropout(
            x, dropout_prob=0.002,
            dropout_implementation="upscale_in_train")
    exe = fluid.Executor()
    exe.run(startup)
    xv = np.ones((64, 4096), np.float32)
    yv = np.asarray(exe.run(prog, feed={"dqx": xv}, fetch_list=[y])[0])
    drop_rate = (yv == 0).mean()
    assert abs(drop_rate - 0.002) < 0.0008, drop_rate
    # kept values scaled by exactly 1/(1-0.002) -> float path was used
    np.testing.assert_allclose(yv[yv != 0], 1.0 / 0.998, rtol=1e-6)
