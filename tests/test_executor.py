"""Executor feed/fetch, scope persistence, compile-cache tests (mirrors
reference fluid/tests/unittests/test_executor_and_mul.py etc.)."""
import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid.executor import Scope, global_scope, scope_guard


def _simple_net():
    x = fluid.data("x", [None, 4], dtype="float32")
    y = fluid.layers.fc(
        x, size=2,
        param_attr=fluid.ParamAttr(
            name="w", initializer=fluid.initializer.Constant(0.5)),
        bias_attr=fluid.ParamAttr(
            name="b", initializer=fluid.initializer.Constant(0.1)))
    return x, y


def test_feed_fetch_numpy():
    _, y = _simple_net()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    x_np = np.ones((3, 4), "float32")
    (out,) = exe.run(feed={"x": x_np}, fetch_list=[y])
    np.testing.assert_allclose(np.asarray(out),
                               np.full((3, 2), 4 * 0.5 + 0.1, "float32"),
                               rtol=1e-6)


def test_fetch_by_name_string():
    _, y = _simple_net()
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    (out,) = exe.run(feed={"x": np.zeros((1, 4), "float32")},
                     fetch_list=[y.name])
    np.testing.assert_allclose(np.asarray(out), [[0.1, 0.1]], rtol=1e-6)


def test_startup_initializes_scope_params():
    _simple_net()
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    scope = global_scope()
    assert "w" in scope and "b" in scope
    np.testing.assert_allclose(np.asarray(scope["w"]),
                               np.full((4, 2), 0.5, "float32"))


def test_param_updates_persist_across_runs():
    x = fluid.data("x", [None, 4], dtype="float32")
    y = fluid.layers.fc(x, size=1, param_attr=fluid.ParamAttr(name="w2"))
    loss = fluid.layers.reduce_mean(y)
    fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    w0 = np.asarray(global_scope()["w2"]).copy()
    feed = {"x": np.ones((2, 4), "float32")}
    exe.run(feed=feed, fetch_list=[loss])
    w1 = np.asarray(global_scope()["w2"]).copy()
    assert not np.allclose(w0, w1), "SGD step must mutate scope param"
    exe.run(feed=feed, fetch_list=[loss])
    w2 = np.asarray(global_scope()["w2"])
    assert not np.allclose(w1, w2)


def test_compile_cache_reused_for_same_shapes():
    _, y = _simple_net()
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    feed = {"x": np.ones((2, 4), "float32")}
    exe.run(feed=feed, fetch_list=[y])
    n_after_first = len(exe._cache)
    for _ in range(3):
        exe.run(feed=feed, fetch_list=[y])
    assert len(exe._cache) == n_after_first
    # new batch size -> new specialization
    exe.run(feed={"x": np.ones((5, 4), "float32")}, fetch_list=[y])
    assert len(exe._cache) == n_after_first + 1


def test_scope_guard_isolates_state():
    _, y = _simple_net()
    exe = fluid.Executor()
    fresh = Scope()
    with scope_guard(fresh):
        exe.run(fluid.default_startup_program())
        assert "w" in fresh
    assert "w" not in global_scope()


def test_scope_tree():
    s = Scope()
    s.set("a", np.zeros(2))
    child = s.new_scope()
    assert child.find_var("a") is not None
    child.set("b", np.ones(2))
    assert "b" not in s
    s.drop_kids()


def test_run_specific_program():
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data("x", [None, 2], dtype="float32")
        y = fluid.layers.scale(x, scale=10.0)
    exe = fluid.Executor()
    exe.run(startup)
    (out,) = exe.run(main, feed={"x": np.array([[1.0, 2.0]], "float32")},
                     fetch_list=[y])
    np.testing.assert_allclose(np.asarray(out), [[10.0, 20.0]])


def test_feed_dtype_coercion_and_errors():
    _, y = _simple_net()
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    # float64 feed is coerced to the var's float32
    (out,) = exe.run(feed={"x": np.ones((1, 4), "float64")}, fetch_list=[y])
    assert np.asarray(out).dtype == np.float32


def test_missing_feed_raises():
    _, y = _simple_net()
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    try:
        exe.run(feed={}, fetch_list=[y])
    except Exception as e:
        assert "x" in str(e)
    else:
        raise AssertionError("expected error for missing feed")


def test_cache_eviction_order_respects_recency(monkeypatch):
    """The LRU is a true LRU: a cache HIT refreshes the entry's
    recency, so the next over-cap insert evicts the least-recently-USED
    signature, not the least-recently-inserted one."""
    from paddle_tpu import observability as obs

    monkeypatch.setenv("PADDLE_TPU_EXECUTOR_CACHE_CAP", "2")
    monkeypatch.setenv("PADDLE_TPU_TELEMETRY", "on")
    x = fluid.data(name="ex", shape=[None, 4], dtype="float32")
    out = fluid.layers.scale(x, scale=2.0)
    exe = fluid.Executor(fluid.CPUPlace())

    def run(batch):
        return exe.run(feed={"ex": np.ones((batch, 4), "float32")},
                       fetch_list=[out])[0]

    def cached_batches():
        # sig[2] is the sorted feed signature: ((name, shape, dtype),)
        return sorted(sig[2][0][1][0] for sig in exe._cache)

    evicts0 = obs.counter("executor.cache_evict")
    run(1)
    run(2)
    assert cached_batches() == [1, 2]
    run(1)                       # HIT: batch-1 becomes most recent
    run(3)                       # over cap: batch-2 is now the oldest
    assert cached_batches() == [1, 3]
    assert obs.counter("executor.cache_evict") - evicts0 == 1


def test_failed_dispatch_evicts_exactly_once(monkeypatch):
    """A dispatch failure may have consumed the donated state buffers,
    so the executor evicts the (possibly poisoned) entry — exactly one
    ``executor.cache_evict`` bump — and a retry recompiles cleanly."""
    from paddle_tpu import observability as obs

    monkeypatch.setenv("PADDLE_TPU_TELEMETRY", "on")
    x = fluid.data(name="fx", shape=[None, 4], dtype="float32")
    out = fluid.layers.scale(x, scale=3.0)
    exe = fluid.Executor(fluid.CPUPlace())
    feed = {"fx": np.ones((2, 4), "float32")}
    exe.run(feed=feed, fetch_list=[out])
    assert len(exe._cache) == 1
    sig = next(iter(exe._cache))

    def boom(*args):
        raise RuntimeError("poisoned executable")

    exe._cache[sig] = boom
    evicts0 = obs.counter("executor.cache_evict")
    try:
        exe.run(feed=feed, fetch_list=[out])
    except RuntimeError as e:
        assert "poisoned" in str(e)
    else:
        raise AssertionError("expected the dispatch failure to surface")
    assert obs.counter("executor.cache_evict") - evicts0 == 1
    assert sig not in exe._cache
    # the guarded-retry path: a re-run recompiles and succeeds
    o = exe.run(feed=feed, fetch_list=[out])[0]
    np.testing.assert_allclose(o, 3.0)
    assert obs.counter("executor.cache_evict") - evicts0 == 1


def test_return_numpy_false_returns_lazy_handles():
    _, y = _simple_net()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    (out,) = exe.run(feed={"x": np.ones((3, 4), "float32")},
                     fetch_list=[y], return_numpy=False)
    assert hasattr(out, "block_until_ready"), "expected a lazy jax array"
    np.testing.assert_allclose(np.asarray(out),
                               np.full((3, 2), 4 * 0.5 + 0.1, "float32"),
                               rtol=1e-6)


def test_executor_cache_lru_bound(monkeypatch):
    """The compile cache is LRU-bounded (each entry pins an XLA
    executable); distinct feed signatures beyond the cap evict oldest."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import framework, unique_name

    framework.switch_main_program(framework.Program())
    framework.switch_startup_program(framework.Program())
    unique_name.switch()
    monkeypatch.setenv("PADDLE_TPU_EXECUTOR_CACHE_CAP", "2")
    x = fluid.data(name="cx", shape=[None, 4], dtype="float32")
    out = fluid.layers.scale(x, scale=2.0)
    exe = fluid.Executor(fluid.CPUPlace())
    for batch in (1, 2, 3, 4):
        o = exe.run(feed={"cx": np.ones((batch, 4), "float32")},
                    fetch_list=[out])[0]
        np.testing.assert_allclose(o, 2.0)
    assert len(exe._cache) <= 2
    # evicted signature still recompiles and runs correctly
    o = exe.run(feed={"cx": np.ones((1, 4), "float32")},
                fetch_list=[out])[0]
    np.testing.assert_allclose(o, 2.0)
