"""End-to-end data integrity (ISSUE 17): content-digest envelopes on
every byte path (checkpoint shards, KV handoffs, compile-cache
entries, FileStore mailbox docs), ``corrupt=`` fault arms driving the
chaos drills, and the SDC sentinel that catches a lying chip by
sampled replay + cross-replica vote and quarantines it through a
journaled autopilot action.

Exactness bar: every drill that corrupts a byte path must end with the
SAME bits an unfaulted run produces — re-prefilled tokens bit-identical
to the solo reference, fallback restores bit-identical to the previous
consensus step — with ``failed_streams == 0`` and the violation
attributed (tensor / file / replica) in counters and events.
"""
import json
import os

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu import observability as obs
from paddle_tpu.autopilot import Autopilot
from paddle_tpu.fluid import resilience as R
from paddle_tpu.integrity import digest as dg
from paddle_tpu.integrity import envelope as env
from paddle_tpu.integrity import jsonl as tj
from paddle_tpu.integrity.sentinel import SDCSentinel, fetch_digest
from paddle_tpu.models import gpt
from paddle_tpu.parallel import checkpoint as ckpt
from paddle_tpu.serving.disagg import disagg_fleet, encode_kv

pytestmark = pytest.mark.integrity


@pytest.fixture(autouse=True)
def _no_leaked_injector():
    R.FaultInjector.uninstall()
    yield
    R.FaultInjector.uninstall()


# ---------------------------------------------------------------------------
# digests
# ---------------------------------------------------------------------------

def test_bytes_and_file_digest(tmp_path):
    d = dg.bytes_digest(b"abc")
    assert d.startswith("sha256:") and d == dg.bytes_digest([b"a", b"bc"])
    p = tmp_path / "blob"
    p.write_bytes(b"abc")
    assert dg.file_digest(str(p)) == d


def test_tensor_digest_is_dtype_and_shape_sensitive():
    a = np.arange(6, dtype=np.float32)
    assert dg.tensor_digest(a) == dg.tensor_digest(a.copy())
    assert dg.tensor_digest(a) != dg.tensor_digest(a.astype(np.float64))
    assert dg.tensor_digest(a) != dg.tensor_digest(a.reshape(2, 3))
    b = a.copy()
    b[3] = np.nextafter(b[3], 99, dtype=np.float32)  # one-ULP flip
    assert dg.tensor_digest(a) != dg.tensor_digest(b)


def test_doc_digest_canonical_across_key_order_and_roundtrip():
    d1 = dg.doc_digest({"a": 1, "b": [1, 2], "c": "x"})
    d2 = dg.doc_digest(json.loads('{"c": "x", "b": [1, 2], "a": 1}'))
    assert d1 == d2
    assert d1 != dg.doc_digest({"a": 1, "b": [1, 2], "c": "y"})


def test_state_mismatches_attributes_tensor():
    state = {"w": np.ones(4, np.float32), "b": np.zeros(2, np.float32)}
    digests = dg.digest_state(state)
    assert dg.state_mismatches(state, digests) == []
    state["w"][1] = 7.0
    bad = dg.state_mismatches(state, digests)
    assert [m[0] for m in bad] == ["w"]
    missing = dg.state_mismatches({"b": state["b"]}, digests)
    assert missing[0][0] == "w" and missing[0][2] is None


# ---------------------------------------------------------------------------
# envelopes
# ---------------------------------------------------------------------------

def test_seal_unseal_roundtrip_and_failure_modes():
    sealed = env.seal_bytes(b"payload", kind="blob")
    assert env.is_sealed(sealed)
    assert env.unseal_bytes(sealed, kind="blob") == b"payload"
    with pytest.raises(dg.IntegrityError, match="kind"):
        env.unseal_bytes(sealed, kind="other")
    with pytest.raises(dg.IntegrityError):
        env.unseal_bytes(b"not sealed at all")
    with pytest.raises(dg.IntegrityError):
        env.unseal_bytes(sealed[:-3])  # truncated payload
    flipped = bytearray(sealed)
    flipped[-1] ^= 1
    with pytest.raises(dg.IntegrityError, match="digest"):
        env.unseal_bytes(bytes(flipped))


def test_manifest_roundtrip_and_corruption(tmp_path):
    p = str(tmp_path / "m.json")
    assert env.read_manifest(p) is None  # absent != corrupt
    doc = env.make_manifest({"w": "sha256:ab"}, kind="checkpoint", step=3)
    env.write_manifest(p, doc)
    back = env.read_manifest(p)
    assert back["digests"] == {"w": "sha256:ab"} and back["step"] == 3
    with open(p, "w") as f:
        f.write("{torn")
    with pytest.raises(dg.IntegrityError):
        env.read_manifest(p)


def test_stamp_and_check_doc():
    doc = {"rank": 3, "t": 1.5}
    stamped = env.stamp_doc(doc)
    assert env.STAMP_KEY in stamped and env.STAMP_KEY not in doc
    ok, clean = env.check_doc(json.loads(json.dumps(stamped)))
    assert ok and clean == doc
    tampered = dict(stamped, rank=4)
    ok, _ = env.check_doc(tampered)
    assert not ok
    ok, clean = env.check_doc({"plain": True})  # unstamped passes
    assert ok and clean == {"plain": True}


# ---------------------------------------------------------------------------
# the tolerant JSONL reader (shared by journal / traces / mailbox)
# ---------------------------------------------------------------------------

def test_parse_lines_counts_torn_not_blank():
    recs, dropped = tj.parse_lines(['{"a": 1}', "", "  ", '{"b"', '{"c": 3}'])
    assert recs == [{"a": 1}, {"c": 3}] and dropped == 1


def test_read_jsonl_and_doc_tolerate_absence(tmp_path):
    assert tj.read_jsonl(str(tmp_path / "nope.jsonl")) == ([], 0)
    assert tj.read_json_doc(str(tmp_path / "nope.json")) == (None, 0)
    p = tmp_path / "t.json"
    p.write_text("{torn")
    assert tj.read_json_doc(str(p)) == (None, 1)


def test_decision_journal_read_skips_torn_tail(tmp_path):
    from paddle_tpu.autopilot.actions import AutopilotAction, DecisionJournal

    path = str(tmp_path / "journal.jsonl")
    j = DecisionJournal(path=path)
    j.append(AutopilotAction("calibrate", "cadence", "propose"))
    j.append(AutopilotAction("kill_replica", "slo:a:ttft", "apply"))
    with open(path, "a") as f:
        f.write('{"seq": 3, "kind": "torn-mid-')  # crash mid-append
    obs.reset()
    back = DecisionJournal.read_jsonl(path)
    assert [r["kind"] for r in back] == ["calibrate", "kill_replica"]
    assert obs.snapshot()["counters"]["integrity.jsonl_dropped"] == 1


def test_read_spans_uses_tolerant_reader(tmp_path):
    from paddle_tpu.observability.distributed import read_spans

    with open(tmp_path / "trace-1.jsonl", "w") as f:
        f.write('{"span": "a", "trace": "t"}\n{"span": "b", "tr')
    spans = read_spans(str(tmp_path))
    assert [s["span"] for s in spans] == ["a"]


# ---------------------------------------------------------------------------
# FileStore mailbox docs
# ---------------------------------------------------------------------------

def test_filestore_docs_stamped_and_verified(tmp_path):
    from paddle_tpu.parallel.elastic import FileStore

    fs = FileStore(str(tmp_path))
    fs.put("hb", "w0", {"rank": 0})
    raw = json.load(open(tmp_path / "hb" / "w0.json"))
    assert env.STAMP_KEY in raw            # stamped on disk...
    assert fs.all("hb") == {"w0": {"rank": 0}}  # ...stripped on read
    # silent tamper: doc is skipped, not served
    with open(tmp_path / "hb" / "w0.json", "w") as f:
        json.dump(dict(raw, rank=9), f)
    fs._cache.clear()
    obs.reset()
    assert fs.all("hb") == {}
    assert obs.snapshot()["counters"]["integrity.mailbox_doc_corrupt"] == 1


def test_filestore_mailbox_fault_arm_torn_write(tmp_path):
    from paddle_tpu.parallel.elastic import FileStore

    fs = FileStore(str(tmp_path))
    R.FaultInjector.install("mailbox:at=1:corrupt=torn")
    fs.put("hb", "w0", {"rank": 0})
    R.FaultInjector.uninstall()
    fs.put("hb", "w1", {"rank": 1})
    fs._cache.clear()
    obs.reset()
    docs = fs.all("hb")
    assert docs == {"w1": {"rank": 1}}  # torn doc dropped, not served
    assert obs.snapshot()["counters"]["integrity.mailbox_doc_torn"] >= 1


# ---------------------------------------------------------------------------
# compile-cache entries
# ---------------------------------------------------------------------------

def test_compile_cache_digest_vs_deserialize_corruption(tmp_path,
                                                        monkeypatch):
    import jax

    from paddle_tpu.fluid import compile_cache as cc
    from paddle_tpu.observability import recorder

    monkeypatch.setenv(cc.CACHE_DIR_ENV, str(tmp_path))
    obs.reset()
    f = jax.jit(lambda x: x * 2)
    x = np.ones((4,), np.float32)
    assert cc.store("k1", f, (x,))
    assert cc.load("k1") is not None
    # bitflip on disk: the envelope digest catches it BEFORE jax.export
    path = cc._entry_path("k1")
    blob = bytearray(open(path, "rb").read())
    blob[len(blob) // 2] ^= 1
    open(path, "wb").write(bytes(blob))
    assert cc.load("k1") is None and not os.path.exists(path)
    # digest-clean garbage: the deserializer is what rejects it
    with open(cc._entry_path("k2"), "wb") as f2:
        f2.write(env.seal_bytes(b"junk", kind="compile-cache"))
    assert cc.load("k2") is None
    c = obs.snapshot()["counters"]
    assert c["compile_cache.corrupt"] == 2
    assert c["compile_cache.corrupt_digest"] == 1
    assert c["compile_cache.corrupt_deserialize"] == 1
    # both split counters ride the crash dump
    p = recorder.FlightRecorder().crash_dump(
        path=str(tmp_path / "dump.json"))
    doc = json.load(open(p))
    assert doc["compile_cache"]["corrupt_digest"] == 1
    assert doc["compile_cache"]["corrupt_deserialize"] == 1


# ---------------------------------------------------------------------------
# checkpoint digests
# ---------------------------------------------------------------------------

def _state(fill):
    # incompressible payloads: ocdbt zlib-packs uniform data so hard
    # that a mid-file bitflip hits framing instead of tensor bytes
    rng = np.random.default_rng(fill)
    return {"w": rng.standard_normal((64, 64)).astype(np.float32),
            "b": rng.standard_normal(64).astype(np.float32)}


def test_checkpoint_save_writes_manifest_and_returns_digests(tmp_path):
    d = str(tmp_path / "ck")
    digests = ckpt.save_checkpoint(d, _state(1), step=1, wait=True)
    assert sorted(digests) == ["b", "w"]
    m = env.read_manifest(ckpt.manifest_path(d, 1))
    assert m["digests"] == digests and m["step"] == 1
    assert ckpt.verify_checkpoint(d, 1)
    state = ckpt.load_checkpoint(d, step=1)
    np.testing.assert_array_equal(state["w"], _state(1)["w"])
    ckpt.finalize(d)


def test_checkpoint_digest_opt_out(tmp_path, monkeypatch):
    monkeypatch.setenv(ckpt._DIGEST_ENV, "0")
    d = str(tmp_path / "ck")
    assert ckpt.save_checkpoint(d, _state(1), step=1, wait=True) is None
    assert not os.path.exists(ckpt.manifest_path(d, 1))
    ckpt.finalize(d)


def _flip_data_byte(dirname, step):
    """Bitflip the middle byte of the largest ocdbt DATA file of a
    step (files under a ``/d/`` component — flipping metadata makes
    orbax itself raise, which exercises the wrong layer)."""
    victims = []
    for root, _, files in os.walk(os.path.join(dirname, str(step))):
        for f in files:
            p = os.path.join(root, f)
            if ("%sd%s" % (os.sep, os.sep)) in p:
                victims.append((os.path.getsize(p), p))
    size, path = max(victims)
    with open(path, "r+b") as fh:
        fh.seek(size // 2)
        byte = fh.read(1)
        fh.seek(size // 2)
        fh.write(bytes([byte[0] ^ 0x01]))
    return path


def test_checkpoint_bitflip_caught_with_tensor_attribution(tmp_path):
    d = str(tmp_path / "ck")
    ckpt.save_checkpoint(d, _state(1), step=1, wait=True)
    ckpt.save_checkpoint(d, _state(2), step=2, wait=True)
    ckpt.finalize(d)
    _flip_data_byte(d, 2)
    obs.reset()
    with pytest.raises(dg.IntegrityError) as ei:
        ckpt.load_checkpoint(d, step=2)
    msg = str(ei.value)
    assert "step 2" in msg and "failed digest verification" in msg
    assert ei.value.tensor in ("w", "b")
    c = obs.snapshot()["counters"]
    assert c["integrity.checkpoint_digest_mismatch"] >= 1
    # resume falls back to step 1, bit-identically
    with pytest.warns(UserWarning, match="falling back"):
        step, state = ckpt.restore_latest(d)
    assert step == 1
    np.testing.assert_array_equal(state["w"], _state(1)["w"])
    np.testing.assert_array_equal(state["b"], _state(1)["b"])
    ckpt.finalize(d)


def test_manifest_tamper_fails_verify_and_falls_back(tmp_path):
    d = str(tmp_path / "ck")
    ckpt.save_checkpoint(d, _state(1), step=1, wait=True)
    ckpt.save_checkpoint(d, _state(2), step=2, wait=True)
    ckpt.finalize(d)
    with open(ckpt.manifest_path(d, 2), "r+b") as fh:
        fh.seek(os.path.getsize(ckpt.manifest_path(d, 2)) // 2)
        fh.write(b"\x00")
    with pytest.warns(UserWarning, match="corrupt digest manifest"):
        # a corrupt manifest fails the step (absent would not)
        assert not ckpt.verify_checkpoint(d, 2)
    with pytest.warns(UserWarning):
        state = ckpt.load_checkpoint(d)
    np.testing.assert_array_equal(state["w"], _state(1)["w"])
    ckpt.finalize(d)


def test_consensus_restore_falls_back_past_digest_failing_step(tmp_path):
    d = str(tmp_path)
    w = 0
    wdir = ckpt.worker_dir(d, w)
    for step, fill in ((1, 1), (2, 2)):
        digests = ckpt.save_checkpoint(wdir, _state(fill), step=step,
                                       wait=True)
        ckpt.mark_save_complete(d, step, w, world_size=1, digests=digests)
    ckpt.finalize(wdir)
    # rot the newest shard AND rewrite its manifest to match, modeling
    # bit rot after consensus formed (the local manifest alone can no
    # longer tell) — the digests recorded in the done-marker at
    # consensus time still catch it
    import orbax.checkpoint as ocp

    _flip_data_byte(wdir, 2)
    mgr = ckpt._manager(wdir)
    rotted = {k: np.asarray(v) for k, v in
              mgr.restore(2, args=ocp.args.StandardRestore()).items()}
    env.write_manifest(
        ckpt.manifest_path(wdir, 2),
        env.make_manifest(dg.digest_state(rotted), kind="checkpoint",
                          step=2))
    obs.reset()
    with pytest.warns(UserWarning, match="done-marker digests"):
        step, state = ckpt.restore_latest_consensus(d, worker_index=w)
    assert step == 1
    np.testing.assert_array_equal(state["w"], _state(1)["w"])
    c = obs.snapshot()["counters"]
    assert c["integrity.checkpoint_digest_mismatch"] >= 1
    ckpt.finalize(wdir)


def test_save_load_fault_arms_on_manifest_path(tmp_path):
    # save arm: the manifest bytes rot in flight to disk; the load-side
    # verification refuses the step instead of trusting it
    d = str(tmp_path / "ck1")
    R.FaultInjector.install("save:at=1:corrupt=bitflip")
    ckpt.save_checkpoint(d, _state(1), step=1, wait=True)
    R.FaultInjector.uninstall()
    with pytest.raises(dg.IntegrityError):
        ckpt.load_checkpoint(d, step=1)
    ckpt.finalize(d)
    # load arm: clean disk, corruption on the read path
    d2 = str(tmp_path / "ck2")
    ckpt.save_checkpoint(d2, _state(1), step=1, wait=True)
    R.FaultInjector.install("load:at=1:corrupt=bitflip")
    with pytest.raises(dg.IntegrityError):
        ckpt.load_checkpoint(d2, step=1)
    R.FaultInjector.uninstall()
    ckpt.finalize(d2)


# ---------------------------------------------------------------------------
# KV handoff sealing (pure numpy)
# ---------------------------------------------------------------------------

def test_kv_handoff_seal_rides_wire_and_catches_tamper():
    rng = np.random.default_rng(3)
    k = rng.standard_normal((2, 8, 16)).astype(np.float32)
    v = rng.standard_normal((2, 8, 16)).astype(np.float32)
    h = encode_kv(k, v, 42, 5, np.arange(1, 6), wire_dtype="int8")
    assert h.digest and h.digest.startswith("sha256:")
    h.verify()  # sealed and intact
    from paddle_tpu.serving.disagg import KVHandoff

    h2 = KVHandoff.from_wire(h.to_wire())
    assert h2.digest == h.digest
    h2.verify()
    h2.k = h2.k.copy()
    h2.k[0, 0, 0] ^= 1
    with pytest.raises(dg.IntegrityError, match="refusing to adopt"):
        h2.verify()
    # unsealed handoffs (hand-built) adopt unverified
    h3 = KVHandoff(k, v, None, None, 1, 5, np.arange(1, 6), "fp32")
    assert h3.digest is None
    h3.verify()


def test_wire_fault_arm_corrupts_after_seal():
    rng = np.random.default_rng(4)
    k = rng.standard_normal((2, 8, 16)).astype(np.float32)
    v = rng.standard_normal((2, 8, 16)).astype(np.float32)
    R.FaultInjector.install("wire:at=1:corrupt=bitflip")
    h = encode_kv(k, v, 42, 5, np.arange(1, 6), wire_dtype="fp32")
    with pytest.raises(dg.IntegrityError):
        h.verify()
    h2 = encode_kv(k, v, 42, 5, np.arange(1, 6), wire_dtype="fp32")
    h2.verify()  # at=1 is one-shot


# ---------------------------------------------------------------------------
# the SDC sentinel (unit level)
# ---------------------------------------------------------------------------

def test_sentinel_sampling_cadence_and_disarm():
    s = SDCSentinel(check_every=4)
    hits = [i for i in range(1, 13) if s.sample("r1")]
    assert hits == [4, 8, 12]
    assert all(not SDCSentinel(check_every=0).sample() for _ in range(8))


def test_fetch_digest_dict_order_independent():
    a, b = np.arange(4.0), np.ones(3)
    assert fetch_digest({"x": a, "y": b}) == fetch_digest({"y": b, "x": a})
    assert fetch_digest([a, b]) != fetch_digest([b, a])


def test_replay_check_agree_and_disagree():
    s = SDCSentinel(check_every=1)
    outs = [np.arange(4.0)]
    assert s.replay_check("r1", lambda: [np.arange(4.0)], outs)
    assert not s.pending
    assert not s.replay_check("r1", lambda: [np.arange(4.0) + 1], outs,
                              feeds={"f": 1}, step=7)
    assert len(s.pending) == 1
    assert s.pending[0]["replica"] == "r1" and s.pending[0]["step"] == 7


def test_vote_confirms_with_peer_quorum_and_abstains_without():
    s = SDCSentinel(check_every=1)
    good = lambda feeds: [np.arange(4.0)]  # noqa: E731
    s.register("liar", lambda feeds: [np.arange(4.0) + 1])
    s.register("p1", good)
    s.register("p2", good)
    s.replay_check("liar", lambda: [np.arange(4.0) + 2], [np.arange(4.0) + 1])
    v = s.vote()
    assert v is not None and v["replica"] == "liar"
    assert v["votes"] == 2 and v["peers"] == 2
    assert s.confirmed_verdicts() == [v] and s.confirmed_verdicts() == []
    # no peers at all -> inconclusive, never a quarantine
    s2 = SDCSentinel(check_every=1)
    s2.register("only", lambda feeds: [np.arange(4.0)])
    s2.replay_check("only", lambda: [np.arange(4.0) + 1], [np.arange(4.0)])
    assert s2.vote() is None and not s2.confirmed


def test_autopilot_integrity_leg_gates_and_quarantines():
    class FakeDisagg:
        def __init__(self):
            self.decode = ["1", "2"]
            self.killed = []
            self._stats = {"failed_streams": 0}

        def live_replicas(self):
            return [], list(self.decode)

        def stats(self):
            return dict(self._stats)

        def quarantine_replica(self, rid):
            self.decode.remove(rid)
            self.killed.append(rid)

        def decode_latencies(self):
            return {}

    def confirmed(replica):
        s = SDCSentinel(check_every=1)
        s.register(replica, lambda feeds: [np.zeros(2)])
        s.register("peer", lambda feeds: [np.arange(2.0)])
        s.replay_check(replica, lambda: [np.ones(2)], [np.full(2, 2.0)])
        return s

    # apply mode: verdict -> journaled quarantine, replica removed
    fleet = FakeDisagg()
    pilot = Autopilot(disagg=fleet, sentinel=confirmed("1"), mode="apply")
    acts = [a for a in pilot.tick() if a.kind == "quarantine_replica"]
    assert len(acts) == 1 and acts[0].outcome == "verified"
    assert fleet.killed == ["1"]
    assert pilot.journal.tail()[-1]["kind"] == "quarantine_replica"
    # never the last decode replica
    fleet2 = FakeDisagg()
    fleet2.decode = ["9"]
    pilot2 = Autopilot(disagg=fleet2, sentinel=confirmed("9"), mode="apply")
    acts2 = [a for a in pilot2.tick() if a.kind == "quarantine_replica"]
    assert acts2[0].outcome == "rejected"
    assert acts2[0].detail["reason"] == "last decode replica"
    assert fleet2.killed == []
    # propose mode records without touching the fleet
    fleet3 = FakeDisagg()
    pilot3 = Autopilot(disagg=fleet3, sentinel=confirmed("1"),
                       mode="propose")
    acts3 = [a for a in pilot3.tick() if a.kind == "quarantine_replica"]
    assert acts3[0].outcome == "proposed" and fleet3.killed == []


# ---------------------------------------------------------------------------
# end-to-end chaos drills (tiny trained GPT; shared module fixture)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def m():
    from paddle_tpu.fluid import framework, unique_name

    framework.switch_main_program(framework.Program())
    framework.switch_startup_program(framework.Program())
    unique_name.switch()
    fluid.default_startup_program().random_seed = 7
    cfg = gpt.gpt_tiny(vocab=97, max_len=256)
    vs = gpt.build_gpt_lm(cfg, 16)
    fluid.optimizer.Adam(5e-3).minimize(vs["loss"])
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    ids, labels = gpt.synthetic_lm_batch(cfg, 16, 16)
    for _ in range(30):
        exe.run(feed={"gpt_ids": ids, "gpt_labels": labels},
                fetch_list=[vs["loss"]])
    yield {"cfg": cfg, "exe": exe, "scope": fluid.global_scope(),
           "ref": {}}


def _solo(m, prompt, n_new):
    from paddle_tpu.fluid import unique_name

    key = (tuple(int(t) for t in prompt), int(n_new))
    if key in m["ref"]:
        return m["ref"][key]
    g_prog, g_st = fluid.Program(), fluid.Program()
    with fluid.program_guard(g_prog, g_st), unique_name.guard():
        gen = gpt.build_gpt_generate(m["cfg"], len(prompt), n_new,
                                     mode="greedy")
    out = np.asarray(m["exe"].run(
        g_prog, feed={"gpt_prompt": np.asarray(prompt).reshape(1, -1)},
        fetch_list=[gen["ids"]], scope=m["scope"])[0])
    m["ref"][key] = [int(t) for t in out[0, len(prompt) - 1:]]
    return m["ref"][key]


def _prompt(n, seed=11):
    rng = np.random.default_rng(seed + n)
    return rng.integers(1, 97, n).astype("int64")


@pytest.mark.chaos
def test_chaos_corrupted_handoff_reprefills_bit_exact(m,
                                                      armed_sanitizers):
    """A bitflipped KV handoff is caught by its sealed digest at adopt
    time, the inner stream fails, and the router's migration path
    re-prefills — the client sees the bit-exact token stream and
    ``failed_streams`` stays 0."""
    router = disagg_fleet(m["cfg"], m["scope"], n_prefill=1, n_decode=2,
                          slots=2, cache_len=64, prompt_buckets=(8, 32),
                          kv_dtype="fp32", wire_dtype="fp32",
                          name="integ-wire")
    try:
        ref = _solo(m, _prompt(6), 10)
        obs.reset()
        R.FaultInjector.install("wire:at=1:corrupt=bitflip")
        got = router.submit(_prompt(6), max_new=10).result(120.0)
        st = router.stats()
        assert got == ref
        assert st["failed_streams"] == 0
        assert st["migrations"] >= 1
        c = obs.snapshot()["counters"]
        assert c["integrity.handoff_digest_mismatch"] == 1
        assert c["integrity.fault_corrupt_fired"] == 1
        # unfaulted traffic afterwards stays clean
        R.FaultInjector.uninstall()
        ref2 = _solo(m, _prompt(5), 8)
        assert router.submit(_prompt(5), max_new=8).result(120.0) == ref2
        assert router.stats()["failed_streams"] == 0
    finally:
        R.FaultInjector.uninstall()
        router.stop(drain=False, timeout=10.0)


@pytest.mark.chaos
def test_chaos_sdc_sentinel_catches_and_quarantines_liar(
        m, armed_sanitizers, tmp_path, monkeypatch):
    """A decode replica whose chip lies exactly once is caught by the
    sampled replay BEFORE its tokens are emitted, confirmed by the
    peer vote, and quarantined through a journaled, traced
    ``quarantine_replica`` autopilot action — while the client stream
    migrates and stays bit-exact."""
    monkeypatch.setenv(obs.TRACE_DIR_ENV, str(tmp_path))
    monkeypatch.setenv("PADDLE_TPU_TRACE_SAMPLE", "1.0")
    router = disagg_fleet(m["cfg"], m["scope"], n_prefill=1, n_decode=2,
                          slots=2, cache_len=64, prompt_buckets=(8, 32),
                          kv_dtype="fp32", wire_dtype="fp32",
                          name="integ-sdc")
    journal_path = str(tmp_path / "journal.jsonl")
    from paddle_tpu.autopilot.actions import DecisionJournal

    sent = SDCSentinel(check_every=3)
    router.attach_sentinel(sent)
    pilot = Autopilot(disagg=router, sentinel=sent, mode="apply",
                      journal=DecisionJournal(path=journal_path))

    class LyingPred:
        """One-shot SDC: the 3rd run (a sampled step's LIVE dispatch)
        returns perturbed outputs; the replay sees the truth."""

        def __init__(self, inner):
            self.inner = inner
            self.calls = 0

        def run(self, feeds, **kw):
            outs = self.inner.run(feeds, **kw)
            self.calls += 1
            if self.calls == 3:
                outs = list(outs)
                outs[0] = np.asarray(outs[0]) + 1
            return outs

        def __getattr__(self, k):
            return getattr(self.inner, k)

    _, decode_rids = router.live_replicas()
    victim = decode_rids[0]
    with router._lock:
        veng = router._decode[victim].engine
    veng._step_pred = LyingPred(veng._step_pred)
    try:
        ref = _solo(m, _prompt(6), 12)
        obs.reset()
        ctx = obs.TraceContext.new()
        got = router.submit(_prompt(6), max_new=12,
                            trace_ctx=ctx).result(120.0)
        acts = pilot.tick()
        st = router.stats()
        _, live_after = router.live_replicas()
        # never serves a corrupted token
        assert got == ref
        assert st["failed_streams"] == 0 and st["migrations"] >= 1
        assert st["sdc_disagree"] == 1 and st["quarantined"] == 1
        assert victim not in live_after
        q = [a for a in acts if a.kind == "quarantine_replica"]
        assert len(q) == 1 and q[0].outcome == "verified"
        assert q[0].detail["failed_streams"] == 0
        c = obs.snapshot()["counters"]
        assert c["integrity.sdc_replay_disagree"] == 1
        assert c["integrity.sdc_vote_confirmed"] == 1
        assert c["integrity.replicas_quarantined"] == 1
        # journaled...
        back = DecisionJournal.read_jsonl(journal_path)
        assert any(r["kind"] == "quarantine_replica"
                   and r["outcome"] == "verified" for r in back)
        # ...and visible in one Perfetto trace: the incident trace_id
        # carries detect -> act -> verify spans
        from paddle_tpu.observability.distributed import (
            chrome_trace, read_spans)

        spans = read_spans(str(tmp_path))
        qspans = [s for s in spans
                  if s.get("args", {}).get("kind") == "quarantine_replica"
                  or (s.get("name") == "autopilot.detect"
                      and str(s.get("args", {}).get("trigger", ""))
                      .startswith("sdc:"))]
        assert {s["name"] for s in qspans} >= {
            "autopilot.detect", "autopilot.act", "autopilot.verify"}
        incident = {s["trace"] for s in qspans}
        assert len(incident) == 1  # ...on ONE incident timeline
        perfetto = chrome_trace(spans, trace_id=incident.pop())
        names = {ev.get("name") for ev in perfetto["traceEvents"]}
        assert "autopilot.act" in names
    finally:
        router.stop(drain=False, timeout=10.0)
