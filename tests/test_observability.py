"""Observability spine: telemetry hub, spans, flight recorder, crash
dumps, and the end-to-end instrumented executor/resilience session
(ISSUE 3 acceptance)."""
import json
import os
import re
import time

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu import observability as obs
from paddle_tpu.fluid.resilience import (
    EventLog, FaultInjector, GuardedExecutor, TrainGuard,
)


@pytest.fixture(autouse=True)
def _fresh_hub(monkeypatch):
    """Every test gets an empty hub/ring and a clean env switch."""
    monkeypatch.delenv(obs.TELEMETRY_ENV, raising=False)
    monkeypatch.delenv(obs.CRASH_DUMP_ENV, raising=False)
    obs.reset()
    yield
    obs.reset()
    FaultInjector.uninstall()


def _build_sgd_program():
    x = fluid.data("ox", shape=[None, 4], dtype="float32")
    y = fluid.data("oy", shape=[None, 1], dtype="float32")
    p = fluid.layers.fc(x, 1)
    loss = fluid.layers.reduce_mean(
        fluid.layers.square_error_cost(p, y))
    fluid.optimizer.SGD(0.05).minimize(loss)
    return loss


def _feed(n=4):
    rng = np.random.default_rng(0)
    xv = rng.standard_normal((n, 4)).astype("float32")
    return {"ox": xv, "oy": xv.sum(1, keepdims=True).astype("float32")}


# ---------------------------------------------------------------------------
# hub primitives
# ---------------------------------------------------------------------------


class TestHub:
    def test_counters_gauges_histograms(self):
        obs.inc("a.b")
        obs.inc("a.b", 2)
        obs.set_gauge("g", 1.5)
        for v in (0.1, 0.2, 0.3):
            obs.observe("h", v)
        snap = obs.snapshot()
        assert snap["counters"]["a.b"] == 3
        assert snap["gauges"]["g"] == 1.5
        h = snap["histograms"]["h"]
        assert h["count"] == 3
        assert h["min"] == pytest.approx(0.1)
        assert h["max"] == pytest.approx(0.3)
        assert h["mean"] == pytest.approx(0.2)

    def test_histogram_reservoir_bounded(self):
        hist = obs.Histogram(cap=16)
        for i in range(1000):
            hist.observe(float(i))
        assert hist.count == 1000
        assert len(hist._reservoir) == 16
        s = hist.summary()
        assert s["max"] == 999.0 and s["min"] == 0.0
        # reservoir keeps the newest observations
        assert s["p50"] >= 984.0

    def test_off_mode_writes_nothing(self, monkeypatch):
        monkeypatch.setenv(obs.TELEMETRY_ENV, "off")
        obs.inc("x")
        obs.observe("y", 1.0)
        obs.set_gauge("z", 2.0)
        obs.event("boom", source="test")
        with obs.span("dead"):
            pass
        snap = obs.snapshot()
        assert snap["counters"] == {}
        assert snap["gauges"] == {}
        assert snap["histograms"] == {}
        assert obs.get_recorder().tail() == []
        assert snap["mode"] == "off"

    def test_mode_parsing(self, monkeypatch):
        assert obs.mode() == obs.ON
        for v in ("off", "OFF", "0", "false", "none"):
            monkeypatch.setenv(obs.TELEMETRY_ENV, v)
            assert obs.mode() == obs.OFF
        monkeypatch.setenv(obs.TELEMETRY_ENV, "trace")
        assert obs.mode() == obs.TRACE
        monkeypatch.setenv(obs.TELEMETRY_ENV, "on")
        assert obs.mode() == obs.ON

    def test_event_counts_and_records(self):
        obs.event("retry", source="guard", attempt=1)
        assert obs.get_telemetry().counter("guard.retry") == 1
        evs = obs.get_recorder().of("retry")
        assert len(evs) == 1
        assert evs[0]["source"] == "guard"
        assert evs[0]["attempt"] == 1


# ---------------------------------------------------------------------------
# prom exposition
# ---------------------------------------------------------------------------

_PROM_LINE = re.compile(
    r"^(?:# (?:TYPE|HELP) [a-zA-Z_][a-zA-Z0-9_]*(?: \w+)?$"
    r"|[a-zA-Z_][a-zA-Z0-9_]*(?:\{[^}]*\})? -?[0-9.eE+-]+$)")


class TestProm:
    def test_render_prom_parses_line_by_line(self):
        obs.inc("executor.cache_hit", 3)
        obs.set_gauge("reader.queue_depth", 4)
        obs.observe("checkpoint.save_seconds", 0.25)
        obs.observe("checkpoint.save_seconds", 0.75)
        text = obs.render_prom()
        lines = text.strip().split("\n")
        assert lines
        for line in lines:
            assert _PROM_LINE.match(line), "bad prom line: %r" % line
        assert "paddle_tpu_executor_cache_hit 3" in lines
        assert "paddle_tpu_reader_queue_depth 4" in lines
        assert "paddle_tpu_checkpoint_save_seconds_count 2" in lines
        # default exposition is a proper Prometheus histogram
        assert "# TYPE paddle_tpu_checkpoint_save_seconds histogram" in lines
        buckets = [
            l for l in lines
            if l.startswith('paddle_tpu_checkpoint_save_seconds_bucket{le=')]
        assert buckets
        # the +Inf bucket closes the series and equals the count
        assert any('le="+Inf"} 2' in l for l in buckets)
        # cumulative: bucket counts never decrease
        counts = [float(l.rsplit(" ", 1)[1]) for l in buckets]
        assert counts == sorted(counts)
        assert any(
            l.startswith("paddle_tpu_checkpoint_save_seconds_sum ")
            for l in lines)

    def test_render_prom_summary_fallback(self, monkeypatch):
        obs.observe("checkpoint.save_seconds", 0.25)
        obs.observe("checkpoint.save_seconds", 0.75)
        # explicit style argument restores the legacy quantile lines
        text = obs.render_prom(style="summary")
        lines = text.strip().split("\n")
        for line in lines:
            assert _PROM_LINE.match(line), "bad prom line: %r" % line
        assert any(
            l.startswith('paddle_tpu_checkpoint_save_seconds{quantile=')
            for l in lines)
        assert not any("_bucket{le=" in l for l in lines)
        # ... and so does the env flag with no argument
        monkeypatch.setenv(obs.PROM_STYLE_ENV, "summary")
        env_lines = obs.render_prom().strip().split("\n")
        assert any(
            l.startswith('paddle_tpu_checkpoint_save_seconds{quantile=')
            for l in env_lines)

    def test_render_prom_empty_hub(self):
        assert obs.render_prom() == ""


# ---------------------------------------------------------------------------
# spans
# ---------------------------------------------------------------------------


class TestSpans:
    def test_nesting_and_histograms(self):
        with obs.span("outer"):
            time.sleep(0.01)
            with obs.span("inner"):
                time.sleep(0.01)
                active = obs.active_spans()
        frames = active["MainThread"]
        assert [n for n, _ in frames] == ["outer", "inner"]
        snap = obs.snapshot()
        outer = snap["histograms"]["span.outer.seconds"]
        inner = snap["histograms"]["span.inner.seconds"]
        assert outer["count"] == inner["count"] == 1
        assert outer["sum"] >= inner["sum"] >= 0.01
        # everything popped: no active spans remain
        assert obs.active_spans() == {}

    def test_span_pops_on_exception(self):
        with pytest.raises(ValueError):
            with obs.span("boom"):
                raise ValueError("x")
        assert obs.active_spans() == {}
        assert obs.snapshot()["histograms"]["span.boom.seconds"]["count"] \
            == 1

    def test_trace_mode_records_span_events(self, monkeypatch):
        monkeypatch.setenv(obs.TELEMETRY_ENV, "trace")
        with obs.span("outer"):
            with obs.span("inner"):
                pass
        evs = obs.get_recorder().of("span")
        names = [(e["name"], e["parent"]) for e in evs]
        assert ("inner", "outer") in names
        assert ("outer", None) in names

    def test_on_mode_records_no_span_events(self):
        with obs.span("outer"):
            pass
        assert obs.get_recorder().of("span") == []


# ---------------------------------------------------------------------------
# flight recorder + crash dumps
# ---------------------------------------------------------------------------


class TestRecorder:
    def test_ring_bounded_and_ordered(self):
        rec = obs.FlightRecorder(maxlen=8)
        for i in range(20):
            rec.record("tick", i=i)
        evs = rec.tail()
        assert len(evs) == 8
        assert [e["i"] for e in evs] == list(range(12, 20))
        assert all(evs[j]["ts"] <= evs[j + 1]["ts"]
                   for j in range(len(evs) - 1))

    def test_dump_jsonl(self, tmp_path):
        rec = obs.FlightRecorder()
        rec.record("a", value=np.float32(1.5))
        rec.record("b", arr=np.arange(3))
        path = rec.dump_jsonl(str(tmp_path / "flight.jsonl"))
        lines = [json.loads(l) for l in open(path)]
        assert [l["kind"] for l in lines] == ["a", "b"]
        assert lines[0]["value"] == 1.5
        assert lines[1]["arr"] == [0, 1, 2]

    def test_eventlog_interleaves_into_one_stream(self, tmp_path):
        rec = obs.FlightRecorder()
        res_log = EventLog(recorder=rec, source="resilience")
        fleet_log = EventLog(recorder=rec, source="fleet")
        res_log.emit("step", step=1)
        fleet_log.emit("worker_dead", worker=2)
        res_log.emit("save", step=1)
        path = rec.dump_jsonl(str(tmp_path / "joint.jsonl"))
        lines = [json.loads(l) for l in open(path)]
        assert [(l["kind"], l["source"]) for l in lines] == [
            ("step", "resilience"), ("worker_dead", "fleet"),
            ("save", "resilience")]
        ts = [l["ts"] for l in lines]
        assert ts == sorted(ts)

    def test_crash_dump_contents(self, tmp_path):
        obs.inc("executor.cache_miss")
        obs.get_recorder().record("compile_done", seconds=1.0)
        target = str(tmp_path / "crash.json")
        with obs.span("executor.run"):
            try:
                raise RuntimeError("chip fell over")
            except RuntimeError as e:
                path = obs.get_recorder().crash_dump(target, exc=e)
        assert path == target
        doc = json.load(open(path))
        assert doc["exception"]["type"] == "RuntimeError"
        assert "chip fell over" in doc["exception"]["message"]
        assert "RuntimeError" in doc["exception"]["traceback"]
        assert [e["kind"] for e in doc["events"]] == ["compile_done"]
        spans = doc["active_spans"]["MainThread"]
        assert spans[0][0] == "executor.run"
        assert doc["telemetry"]["counters"]["executor.cache_miss"] == 1

    def test_crash_dump_env_path(self, monkeypatch, tmp_path):
        target = str(tmp_path / "env_crash.json")
        monkeypatch.setenv(obs.CRASH_DUMP_ENV, target)
        assert obs.crash_dump_path() == target
        assert obs.get_recorder().crash_dump() == target
        assert os.path.exists(target)

    def test_explicit_recorder_ignores_off_mode(self, monkeypatch):
        monkeypatch.setenv(obs.TELEMETRY_ENV, "off")
        rec = obs.FlightRecorder()
        rec.record("still_here")
        assert len(rec.tail()) == 1
        # ...but the GLOBAL recorder follows the switch
        obs.get_recorder().record("dropped")
        assert obs.get_recorder().tail() == []


# ---------------------------------------------------------------------------
# instrumented executor
# ---------------------------------------------------------------------------


class TestExecutorInstrumentation:
    def test_cache_hit_miss_two_run_session(self):
        loss = _build_sgd_program()
        exe = fluid.Executor()
        exe.run(fluid.default_startup_program())
        obs.reset()  # scope to the scripted session
        exe.run(fluid.default_main_program(), feed=_feed(),
                fetch_list=[loss])
        exe.run(fluid.default_main_program(), feed=_feed(),
                fetch_list=[loss])
        snap = obs.snapshot()
        assert snap["counters"]["executor.cache_miss"] == 1
        assert snap["counters"]["executor.cache_hit"] == 1
        hist = snap["histograms"]
        assert hist["executor.compile_seconds"]["count"] == 1
        # phase spans: one per run
        for name in ("executor.run", "executor.feed_convert",
                     "executor.device_compute", "executor.fetch"):
            assert hist["span.%s.seconds" % name]["count"] == 2, name
        kinds = [e["kind"] for e in obs.get_recorder().tail()]
        assert kinds.count("compile_start") == 1
        assert kinds.count("compile_done") == 1

    def test_cache_evict_counted(self, monkeypatch):
        loss = _build_sgd_program()
        exe = fluid.Executor()
        exe._cache_cap = 1
        exe.run(fluid.default_startup_program())
        obs.reset()
        exe.run(fluid.default_main_program(), feed=_feed(4),
                fetch_list=[loss])
        # different batch size -> new signature -> evicts the first
        exe.run(fluid.default_main_program(), feed=_feed(8),
                fetch_list=[loss])
        snap = obs.snapshot()
        assert snap["counters"]["executor.cache_miss"] == 2
        assert snap["counters"]["executor.cache_evict"] >= 1

    def test_disabled_mode_overhead(self, monkeypatch):
        """The off path must stay cheap: a cached executor.run traverses
        ~10 guarded sites (4 span enter/exits, the cache-hit counter,
        the trace check); their total off-mode cost must stay under 5%
        of the per-step time of a tight run loop."""
        loss = _build_sgd_program()
        exe = fluid.Executor()
        exe.run(fluid.default_startup_program())
        feed = _feed()
        monkeypatch.setenv(obs.TELEMETRY_ENV, "off")
        exe.run(fluid.default_main_program(), feed=feed,
                fetch_list=[loss])  # warm the executable cache
        steps = 30
        t0 = time.perf_counter()
        for _ in range(steps):
            exe.run(fluid.default_main_program(), feed=feed,
                    fetch_list=[loss])
        per_step = (time.perf_counter() - t0) / steps
        calls = 50000
        t0 = time.perf_counter()
        for _ in range(calls):
            obs.inc("off.overhead")
        per_call = (time.perf_counter() - t0) / calls
        assert obs.get_telemetry().counter("off.overhead") == 0
        sites = 15  # upper bound on mode checks in one cached run()
        assert sites * per_call < 0.05 * per_step, (
            "off-mode guards cost %.1fus/step (%.0fns/site) vs "
            "%.1fus/step run loop"
            % (1e6 * sites * per_call, 1e9 * per_call, 1e6 * per_step))

    def test_trace_mode_blocks_and_spans(self, monkeypatch):
        monkeypatch.setenv(obs.TELEMETRY_ENV, "trace")
        loss = _build_sgd_program()
        exe = fluid.Executor()
        exe.run(fluid.default_startup_program())
        exe.run(fluid.default_main_program(), feed=_feed(),
                fetch_list=[loss])
        span_names = {e["name"] for e in obs.get_recorder().of("span")}
        assert {"executor.run", "executor.feed_convert",
                "executor.device_compute",
                "executor.fetch"} <= span_names


# ---------------------------------------------------------------------------
# the acceptance session (ISSUE 3)
# ---------------------------------------------------------------------------


@pytest.mark.faults
class TestAcceptanceSession:
    def _scripted_session(self, tmp_path):
        """2 executor.run calls, one injected run fault, one checkpoint
        save — the canonical flight-recorder session."""
        loss = _build_sgd_program()
        exe = fluid.Executor()
        exe.run(fluid.default_startup_program())
        obs.reset()  # the session starts AFTER startup
        FaultInjector.install("run:at=1:RuntimeError")
        guard = TrainGuard(
            exe, program=fluid.default_main_program(),
            ckpt_dir=str(tmp_path / "ck"), fetch_list=[loss],
            feed_fn=lambda step: _feed(), save_every=2, final_save=False,
            backoff_base=0.001)
        guard.train(num_steps=2)
        FaultInjector.uninstall()

    def test_snapshot_counts(self, tmp_path):
        self._scripted_session(tmp_path)
        snap = obs.snapshot()
        c = snap["counters"]
        assert c["executor.cache_miss"] == 1, c
        assert c["executor.cache_hit"] == 1, c
        assert c["guard.retry"] == 1, c
        assert c["resilience.save"] == 1, c
        hist = snap["histograms"]
        assert hist["checkpoint.save_seconds"]["count"] == 1
        assert hist["checkpoint.save_seconds"]["sum"] > 0
        # the ring interleaves guard + resilience + executor streams
        evs = obs.get_recorder().tail()
        kinds = [e["kind"] for e in evs]
        assert "retry" in kinds and "save" in kinds \
            and "compile_done" in kinds
        ts = [e["ts"] for e in evs]
        assert ts == sorted(ts)
        # exactly once each: no double-count through the relay
        assert kinds.count("retry") == 1
        assert kinds.count("save") == 1

    def test_off_mode_produces_none(self, tmp_path, monkeypatch):
        monkeypatch.setenv(obs.TELEMETRY_ENV, "off")
        self._scripted_session(tmp_path)
        snap = obs.snapshot()
        assert snap["counters"] == {}
        assert snap["histograms"] == {}
        assert obs.get_recorder().tail() == []


# ---------------------------------------------------------------------------
# shared-recorder wiring (satellite)
# ---------------------------------------------------------------------------


class TestSharedRecorder:
    def test_trainguard_custom_recorder_stream(self, tmp_path):
        rec = obs.FlightRecorder()
        loss = _build_sgd_program()
        exe = fluid.Executor()
        exe.run(fluid.default_startup_program())
        guard = TrainGuard(
            exe, program=fluid.default_main_program(),
            ckpt_dir=str(tmp_path / "ck"), fetch_list=[loss],
            feed_fn=lambda step: _feed(), save_every=2, final_save=False,
            recorder=rec)
        guard.train(num_steps=2)
        kinds = [e["kind"] for e in rec.tail()]
        assert "step" in kinds and "save" in kinds
        path = rec.dump_jsonl(str(tmp_path / "stream.jsonl"))
        lines = [json.loads(l) for l in open(path)]
        assert all("ts" in l and "kind" in l for l in lines)

    def test_guarded_executor_recorder_param(self):
        rec = obs.FlightRecorder()
        loss = _build_sgd_program()
        exe = fluid.Executor()
        exe.run(fluid.default_startup_program())
        FaultInjector.install("run:at=1:RuntimeError")
        guard = GuardedExecutor(exe, backoff_base=0.001, recorder=rec)
        guard.run(fluid.default_main_program(), feed=_feed(),
                  fetch_list=[loss])
        FaultInjector.uninstall()
        assert [e["kind"] for e in rec.tail()] == ["retry"]

    def test_fleetguard_recorder_param(self):
        from paddle_tpu.parallel.elastic import FleetGuard

        rec = obs.FlightRecorder()
        loss = _build_sgd_program()
        exe = fluid.Executor()
        exe.run(fluid.default_startup_program())
        guard = FleetGuard(
            exe, program=fluid.default_main_program(), worker_index=0,
            world_size=1, fetch_list=[loss],
            feed_fn=lambda step, g: _feed(), recorder=rec)
        guard.train(num_steps=2)
        kinds = [e["kind"] for e in rec.tail()]
        assert kinds.count("step") == 2
        assert "final" in kinds


# ---------------------------------------------------------------------------
# reader + profiler instrumentation
# ---------------------------------------------------------------------------


class TestPeripheralInstrumentation:
    def test_reader_gauges(self):
        reader = fluid.layers.py_reader(
            capacity=4, shapes=[(2, 3)], dtypes=["float32"])

        def gen():
            for _ in range(3):
                yield [np.ones((2, 3), "float32")]

        reader.decorate_tensor_provider(gen)
        reader.start()
        for _ in range(3):
            assert reader._next_feed() is not None
        snap = obs.snapshot()
        assert "reader.queue_depth" in snap["gauges"]
        assert snap["histograms"]["reader.pop_wait_seconds"]["count"] == 3

    def test_profiler_creates_requested_dir(self, tmp_path):
        from paddle_tpu.fluid import profiler as P

        target = str(tmp_path / "not" / "yet" / "there")
        P.start_profiler("All", profile_path=target)
        try:
            assert os.path.isdir(target)
        finally:
            P.stop_profiler(profile_path=target)
        assert P._trace_dir is None and P._start_time is None
        c = obs.snapshot()["counters"]
        assert c.get("profiler.trace_start") == 1
        assert c.get("profiler.trace_stop") == 1

    def test_profiler_start_failure_is_loud_and_consistent(
            self, monkeypatch, tmp_path):
        import jax

        from paddle_tpu.fluid import profiler as P

        def _boom(path):
            raise RuntimeError("profiler backend unavailable")

        monkeypatch.setattr(jax.profiler, "start_trace", _boom)
        with pytest.warns(UserWarning, match="start_trace"):
            P.start_profiler("All", profile_path=str(tmp_path / "t"))
        assert P._trace_dir is None and P._start_time is None
        assert obs.snapshot()["counters"]["profiler.trace_error"] == 1
        # stop after a failed start: clean no-op
        P.stop_profiler()

    def test_collective_dispatch_counter(self):
        from paddle_tpu.ops import collective_ops as C

        C._guard("c_allreduce_sum")
        C._guard("c_allgather")
        c = obs.snapshot()["counters"]
        assert c["collective.dispatch"] == 2
        assert c["collective.dispatch.c_allreduce_sum"] == 1
        assert c["collective.dispatch.c_allgather"] == 1
