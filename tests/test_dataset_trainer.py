"""Dataset trainer path: DatasetFactory / QueueDataset / InMemoryDataset,
DataFeedDesc, data_generator, Executor.train_from_dataset /
infer_from_dataset, DataLoader.from_dataset. Mirrors ref
fluid/tests/unittests/test_dataset.py coverage the TPU way."""
import io
import os

import numpy as np
import pytest

import paddle_tpu.fluid as fluid


def _write_multislot(path, rows):
    """rows: list of samples; sample = list of slot value-lists."""
    with open(path, "w") as f:
        for sample in rows:
            toks = []
            for vals in sample:
                toks.append(str(len(vals)))
                toks.extend(str(v) for v in vals)
            f.write(" ".join(toks) + "\n")


def _ctr_rows(n, seed, vocab=50, ndense=4, nsparse=3):
    rng = np.random.default_rng(seed)
    rows = []
    for _ in range(n):
        sparse = rng.integers(0, vocab, size=nsparse).tolist()
        label = [int(sparse[0] % 2)]  # learnable: label from first id
        dense = [round(float(x), 4) for x in rng.random(ndense)]
        rows.append([sparse, dense, label])
    return rows


def _ctr_program(vocab=50, ndense=4, nsparse=3):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        sparse = fluid.data("sparse", shape=[None, nsparse], dtype="int64")
        dense = fluid.data("dense", shape=[None, ndense], dtype="float32")
        label = fluid.data("label", shape=[None, 1], dtype="int64")
        emb = fluid.layers.embedding(sparse, size=[vocab, 8])
        feat = fluid.layers.concat(
            [fluid.layers.reshape(emb, [0, nsparse * 8]), dense], axis=1)
        h = fluid.layers.fc(feat, 32, act="relu")
        logit = fluid.layers.fc(h, 2)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logit, label))
        opt = fluid.optimizer.Adam(5e-3)
        opt.minimize(loss)
    return main, startup, [sparse, dense, label], loss


def test_datafeed_desc_roundtrip(tmp_path):
    proto = """
name: "MultiSlotDataFeed"
batch_size: 16
multi_slot_desc {
  slots { name: "words" type: "uint64" is_dense: false is_used: false }
  slots { name: "dense_f" type: "float" is_dense: false is_used: false }
  slots { name: "label" type: "uint64" is_dense: false is_used: false }
}
"""
    p = tmp_path / "feed.proto"
    p.write_text(proto)
    desc = fluid.DataFeedDesc(str(p))
    desc.set_batch_size(64)
    desc.set_dense_slots(["dense_f"])
    desc.set_use_slots(["words", "label"])
    text = desc.desc()
    again = fluid.DataFeedDesc(text)
    assert again._batch_size == 64
    by_name = {s.name: s for s in again.slots}
    assert by_name["dense_f"].is_dense
    assert by_name["words"].is_used and by_name["label"].is_used
    assert not by_name["dense_f"].is_used
    with pytest.raises(ValueError):
        desc.set_use_slots(["nope"])


def test_queue_dataset_batches(tmp_path):
    rows = _ctr_rows(25, 0)
    f1, f2 = str(tmp_path / "a.txt"), str(tmp_path / "b.txt")
    _write_multislot(f1, rows[:13])
    _write_multislot(f2, rows[13:])
    main, startup, use_vars, loss = _ctr_program()
    ds = fluid.DatasetFactory().create_dataset("QueueDataset")
    ds.set_batch_size(4)
    ds.set_thread(2)
    ds.set_filelist([f1, f2])
    ds.set_use_var(use_vars)
    ds._prepare_to_run()
    batches = list(ds._batch_iterator())
    total = sum(len(b) for b in batches)
    assert total == 25
    # sample fields parse to the right widths/types
    s0 = batches[0][0]
    assert len(s0) == 3 and len(s0[0]) == 3 and len(s0[1]) == 4
    assert isinstance(s0[0][0], int) and isinstance(s0[1][0], float)
    # multiset of samples is preserved across threading
    seen = sorted(tuple(tuple(sl) for sl in s) for b in batches for s in b)
    want = sorted(
        tuple(tuple(sl) for sl in s)
        for s in ((r[0], [float(x) for x in r[1]], r[2]) for r in rows)
    )
    assert seen == want


def test_queue_dataset_refuses_shuffle(tmp_path):
    ds = fluid.DatasetFactory().create_dataset("QueueDataset")
    with pytest.raises(NotImplementedError):
        ds.local_shuffle()
    with pytest.raises(NotImplementedError):
        ds.global_shuffle()


def test_in_memory_dataset_shuffle_and_sizes(tmp_path):
    rows = _ctr_rows(30, 1)
    fn = str(tmp_path / "mem.txt")
    _write_multislot(fn, rows)
    main, startup, use_vars, loss = _ctr_program()
    ds = fluid.DatasetFactory().create_dataset("InMemoryDataset")
    ds.set_batch_size(8)
    ds.set_thread(3)
    ds.set_filelist([fn])
    ds.set_use_var(use_vars)
    ds.load_into_memory()
    assert ds.get_memory_data_size() == 30
    before = [tuple(tuple(sl) for sl in s) for s in ds._memory]
    ds.local_shuffle()
    after = [tuple(tuple(sl) for sl in s) for s in ds._memory]
    assert sorted(before) == sorted(after)
    assert before != after  # 30 samples: astronomically unlikely to match
    assert ds.get_shuffle_data_size() == 30
    ds.release_memory()
    with pytest.raises(RuntimeError):
        ds.get_memory_data_size()


def test_in_memory_preload_and_merge_by_lineid(tmp_path):
    # lines with instance ids: two lines share id "u1" and merge
    fn = str(tmp_path / "ins.txt")
    with open(fn, "w") as f:
        f.write("u1 2 5 6 1 1\n")
        f.write("u2 1 7 1 0\n")
        f.write("u1 1 9 1 1\n")
    main = fluid.Program()
    with fluid.program_guard(main, fluid.Program()):
        words = fluid.layers.data("words", shape=[1], dtype="int64",
                                  lod_level=1)
        label = fluid.layers.data("mlabel", shape=[1], dtype="int64",
                                  lod_level=1)
    ds = fluid.DatasetFactory().create_dataset("InMemoryDataset")
    ds.set_filelist([fn])
    ds.set_use_var([words, label])
    ds.set_parse_ins_id(True)
    ds.set_merge_by_lineid(2)
    ds.preload_into_memory(thread_num=2)
    ds.wait_preload_done()
    assert ds.get_memory_data_size() == 2
    by_id = {s[0]: s[1:] for s in ds._memory}
    assert by_id["u1"][0] == [5, 6, 9]       # merged word ids
    assert by_id["u1"][1] == [1, 1]          # merged labels
    assert by_id["u2"][0] == [7]
    # batches strip the ins id
    b = list(ds._batch_iterator())[0]
    assert len(b[0]) == 2


def test_pipe_command_preprocessing(tmp_path):
    # raw file is NOT multislot; the pipe command converts it
    fn = str(tmp_path / "raw.txt")
    with open(fn, "w") as f:
        for i in range(6):
            f.write("%d %d\n" % (i, i % 2))
    main = fluid.Program()
    with fluid.program_guard(main, fluid.Program()):
        x = fluid.data("px", shape=[None, 1], dtype="int64")
        y = fluid.data("py", shape=[None, 1], dtype="int64")
    ds = fluid.DatasetFactory().create_dataset("QueueDataset")
    ds.set_batch_size(3)
    ds.set_filelist([fn])
    ds.set_use_var([x, y])
    ds.set_pipe_command("awk '{print 1, $1, 1, $2}'")
    ds._prepare_to_run()
    batches = list(ds._batch_iterator())
    flat = [s for b in batches for s in b]
    assert sorted(s[0][0] for s in flat) == [0, 1, 2, 3, 4, 5]


def test_data_generator_to_dataset(tmp_path):
    from paddle_tpu.fluid.incubate.data_generator import (
        MultiSlotDataGenerator,
        MultiSlotStringDataGenerator,
    )

    class Gen(MultiSlotDataGenerator):
        def generate_sample(self, line):
            def it():
                for i in range(10):
                    yield [("ids", [i, i + 1]), ("lab", [i % 2])]
            return it

    buf = io.StringIO()
    g = Gen()
    g.run_from_memory(out=buf)
    fn = str(tmp_path / "gen.txt")
    with open(fn, "w") as f:
        f.write(buf.getvalue())
    assert g._proto_info == [("ids", "uint64"), ("lab", "uint64")]

    main = fluid.Program()
    with fluid.program_guard(main, fluid.Program()):
        ids = fluid.data("gids", shape=[None, 2], dtype="int64")
        lab = fluid.data("glab", shape=[None, 1], dtype="int64")
    ds = fluid.DatasetFactory().create_dataset("InMemoryDataset")
    ds.set_filelist([fn])
    ds.set_use_var([ids, lab])
    ds.load_into_memory()
    assert ds.get_memory_data_size() == 10
    assert ds._memory[3][0] == [3, 4]

    # string generator run_from_stdin path via pipe_command text
    class SGen(MultiSlotStringDataGenerator):
        def generate_sample(self, line):
            def it():
                if line is None:
                    return
                a, b = line.split()
                yield [("ids", [a, a]), ("lab", [b])]
            return it

    sbuf = io.StringIO()
    import sys
    old = sys.stdin
    sys.stdin = io.StringIO("4 1\n5 0\n")
    try:
        SGen().run_from_stdin(out=sbuf)
    finally:
        sys.stdin = old
    assert sbuf.getvalue() == "2 4 4 1 1\n2 5 5 1 0\n"


def test_train_from_dataset_wide_deep_loss_drops(tmp_path):
    rows = _ctr_rows(256, 2)
    files = []
    for k in range(2):
        fn = str(tmp_path / ("train%d.txt" % k))
        _write_multislot(fn, rows[k::2])
        files.append(fn)
    main, startup, use_vars, loss = _ctr_program()
    ds = fluid.DatasetFactory().create_dataset("InMemoryDataset")
    ds.set_batch_size(32)
    ds.set_thread(2)
    ds.set_filelist(files)
    ds.set_use_var(use_vars)
    ds.load_into_memory()
    ds.local_shuffle()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    scope = fluid.global_scope()

    def epoch_loss():
        tot, n = 0.0, 0
        for b in ds._batch_iterator():
            from paddle_tpu.fluid.data_feeder import DataFeeder
            feed = DataFeeder(use_vars, exe.place, program=main).feed(b)
            # fetch loss WITHOUT training: use the pruned infer clone
            (lv,) = exe.run(exe._strip_training_ops(main), feed=feed,
                            fetch_list=[loss])
            tot += float(lv) * len(b)
            n += len(b)
        return tot / n

    l0 = epoch_loss()
    for _ in range(6):
        exe.train_from_dataset(program=main, dataset=ds,
                               fetch_list=[loss], print_period=10**9)
    l1 = epoch_loss()
    assert l1 < l0 * 0.8, (l0, l1)


def test_infer_from_dataset_does_not_touch_params(tmp_path):
    rows = _ctr_rows(32, 3)
    fn = str(tmp_path / "inf.txt")
    _write_multislot(fn, rows)
    main, startup, use_vars, loss = _ctr_program()
    ds = fluid.DatasetFactory().create_dataset("QueueDataset")
    ds.set_batch_size(8)
    ds.set_filelist([fn])
    ds.set_use_var(use_vars)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    scope = fluid.global_scope()
    pnames = [p.name for p in main.global_block().all_parameters()]
    before = {n: np.asarray(scope.find_var(n).get_tensor()).copy()
              for n in pnames}
    exe.infer_from_dataset(program=main, dataset=ds, fetch_list=[loss],
                           print_period=10**9)
    for n in pnames:
        np.testing.assert_array_equal(
            np.asarray(scope.find_var(n).get_tensor()), before[n])


def test_dataloader_from_dataset(tmp_path):
    rows = _ctr_rows(20, 4)
    fn = str(tmp_path / "dl.txt")
    _write_multislot(fn, rows)
    main, startup, use_vars, loss = _ctr_program()
    ds = fluid.DatasetFactory().create_dataset("QueueDataset")
    ds.set_batch_size(8)
    ds.set_filelist([fn])
    ds.set_use_var(use_vars)
    loader = fluid.DataLoader.from_dataset(ds, [fluid.CPUPlace()])
    feeds = list(loader())
    assert len(feeds) == 2  # 20 samples, bs=8, ragged tail dropped
    assert set(feeds[0].keys()) >= {"sparse", "dense", "label"}
    assert feeds[0]["dense"].shape == (8, 4)


def test_fetch_handler_monitor():
    import time
    from paddle_tpu.fluid.trainer_factory import (
        FetchHandler, FetchHandlerMonitor,
    )

    scope = fluid.global_scope()
    scope.set("fh_var", np.array([3.25], "float32"))
    got = []

    class H(FetchHandler):
        def handler(self, res):
            got.append(res)

    mon = FetchHandlerMonitor(scope, H({"v": "fh_var"}, period_secs=0.05))
    mon.start()
    time.sleep(0.3)
    mon.stop()
    assert got and float(got[0]["v"][0]) == 3.25


def test_inmemory_columnar_fast_path(tmp_path):
    """InMemoryDataset's fixed-width batches take the columnar fast
    path (ColumnarBatch slices) and feed IDENTICALLY to the per-sample
    conversion; shuffle keeps columns aligned; ragged slots fall back."""
    from paddle_tpu.fluid.data_feeder import ColumnarBatch, DataFeeder

    rows = _ctr_rows(12, 9)
    fn = str(tmp_path / "col.txt")
    _write_multislot(fn, rows)
    main, startup, use_vars, loss = _ctr_program()
    ds = fluid.DatasetFactory().create_dataset("InMemoryDataset")
    ds.set_batch_size(4)
    ds.set_filelist([fn])
    ds.set_use_var(use_vars)
    ds.load_into_memory()

    batches = list(ds._batch_iterator())
    assert len(batches) == 3
    assert all(isinstance(b, ColumnarBatch) for b in batches)
    feeder = DataFeeder(use_vars, fluid.CPUPlace(), program=main)
    for b in batches:
        fast = feeder.feed(b)
        # the sample-tuple view of the same batch takes the slow path
        slow = feeder.feed([b[i] for i in range(len(b))])
        assert set(fast) == set(slow)
        for k in fast:
            assert fast[k].dtype == slow[k].dtype
            np.testing.assert_array_equal(fast[k], slow[k])

    # shuffle permutes columns and samples together
    ds.local_shuffle()
    b0 = next(iter(ds._batch_iterator()))
    s0 = ds._memory[0]
    np.testing.assert_array_equal(b0.columns[0][0], np.asarray(s0[0]))
    np.testing.assert_array_equal(b0.columns[1][0],
                                  np.asarray(s0[1], dtype=np.float32))

    # ragged slot (variable-width id list) -> per-sample fallback
    main2, startup2 = fluid.Program(), fluid.Program()
    with fluid.program_guard(main2, startup2):
        words = fluid.data("cwords", shape=[None], dtype="int64",
                           lod_level=1)
        lab = fluid.data("clab", shape=[None, 1], dtype="int64")
    ragged = [[[1, 2, 3], [1]], [[4], [0]], [[5, 6], [1]]]
    fn2 = str(tmp_path / "ragged.txt")
    _write_multislot(fn2, ragged)
    ds2 = fluid.DatasetFactory().create_dataset("InMemoryDataset")
    ds2.set_batch_size(2)
    ds2.set_filelist([fn2])
    ds2.set_use_var([words, lab])
    ds2.load_into_memory()
    b2 = list(ds2._batch_iterator())
    assert not isinstance(b2[0], ColumnarBatch)
    assert b2[0][0][0] == [1, 2, 3]


def test_trainer_loader_cache_and_release(tmp_path):
    """train_from_dataset reuses ONE loader (and native pipe) across
    epochs; changing use_var refreshes the feed list; release_memory
    frees the cached loader and its pipe."""
    rows = _ctr_rows(16, 2)
    fn = str(tmp_path / "cache.txt")
    _write_multislot(fn, rows)
    main, startup, use_vars, loss = _ctr_program()
    ds = fluid.DatasetFactory().create_dataset("InMemoryDataset")
    ds.set_batch_size(8)
    ds.set_filelist([fn])
    ds.set_use_var(use_vars)
    ds.load_into_memory()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    exe.train_from_dataset(program=main, dataset=ds)
    cached1 = ds._loader_cache
    assert cached1 is not None
    exe.train_from_dataset(program=main, dataset=ds)
    assert ds._loader_cache[1] is cached1[1]  # same loader reused
    # feed list refreshed from the dataset's current use_vars each call
    assert ds._loader_cache[1]._feed_list == list(ds.use_vars)
    pipe = getattr(cached1[1], "_pipe", None)
    ds.release_memory()
    assert ds._loader_cache is None
    if pipe is not None:      # native toolchain present
        assert pipe._handle is None  # arena destroyed, mlock released


def test_dataset_scan_steps_bitexact(tmp_path, monkeypatch):
    """K steps per dispatch (lax.scan over the step body,
    PADDLE_TPU_DATASET_STEPS_PER_CALL) trains BIT-IDENTICALLY to the
    single-step loop: scan is sequential and consumes the same per-step
    PRNG key sequence."""
    rows = _ctr_rows(40, 7)
    fn = str(tmp_path / "scan.txt")
    _write_multislot(fn, rows)

    def train(k):
        from paddle_tpu.fluid import framework, unique_name

        framework.switch_main_program(framework.Program())
        framework.switch_startup_program(framework.Program())
        unique_name.switch()
        monkeypatch.setenv("PADDLE_TPU_DATASET_STEPS_PER_CALL", str(k))
        main, startup, use_vars, loss = _ctr_program()
        startup.random_seed = 7
        main.random_seed = 11
        ds = fluid.DatasetFactory().create_dataset("InMemoryDataset")
        ds.set_batch_size(4)
        ds.set_filelist([fn])
        ds.set_use_var(use_vars)
        ds.load_into_memory()
        scope = fluid.Scope()
        exe = fluid.Executor(fluid.CPUPlace())
        with fluid.scope_guard(scope):
            exe.run(startup)
            for _ in range(3):   # 3 epochs: warmup + scan-path epochs
                exe.train_from_dataset(program=main, dataset=ds)
        ds.release_memory()
        if k > 1:   # prove the scan path actually engaged
            assert any(isinstance(s, tuple) and s
                       and s[0] == "dataset_scan" for s in exe._cache)
        names = sorted(
            v.name for v in main.global_block().vars.values()
            if v.persistable and scope.find_value(v.name) is not None)
        return {n: np.asarray(scope.find_value(n)) for n in names}

    single = train(1)
    scanned = train(4)
    assert set(single) == set(scanned)
    for n in single:
        np.testing.assert_array_equal(single[n], scanned[n], err_msg=n)


def test_dataset_scan_fresh_scope_rewarms(tmp_path, monkeypatch):
    """A warm PROGRAM with a fresh SCOPE must re-warm (the lazy state
    lives in the scope): no structure-check fallback, scan engages in
    the second epoch, and the PRNG sequence stays aligned."""
    monkeypatch.setenv("PADDLE_TPU_DATASET_STEPS_PER_CALL", "4")
    rows = _ctr_rows(32, 5)
    fn = str(tmp_path / "scope.txt")
    _write_multislot(fn, rows)
    main, startup, use_vars, loss = _ctr_program()
    startup.random_seed = 3
    ds = fluid.DatasetFactory().create_dataset("InMemoryDataset")
    ds.set_batch_size(4)
    ds.set_filelist([fn])
    ds.set_use_var(use_vars)
    ds.load_into_memory()
    exe = fluid.Executor(fluid.CPUPlace())
    for _ in range(2):                       # scope A, then fresh B
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe.run(startup)
            exe.train_from_dataset(program=main, dataset=ds)
            exe.train_from_dataset(program=main, dataset=ds)
        assert main._uid in scope._dataset_scan_warm
    assert any(isinstance(s, tuple) and s and s[0] == "dataset_scan"
               for s in exe._cache)
    ds.release_memory()
