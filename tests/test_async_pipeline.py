"""Pipelined dispatch (fluid/async_pipeline.py): bit-identical results
vs the synchronous step loop, overlap demonstrated via trace-mode span
timestamps, staging invalidation on close(), and the py_reader
device-staging path."""
import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu import observability as obs
from paddle_tpu.fluid.executor import Scope


def _train_net(width=8):
    x = fluid.data("x", [None, width], dtype="float32")
    y = fluid.layers.fc(x, size=width)
    y = fluid.layers.fc(y, size=width)
    y = fluid.layers.fc(y, size=1)
    loss = fluid.layers.reduce_mean(y)
    fluid.optimizer.SGD(learning_rate=0.05).minimize(loss)
    return loss


def _feeds(n, batch, width, dtype="float32", seed=7):
    rng = np.random.RandomState(seed)
    return [{"x": rng.uniform(-1, 1, (batch, width)).astype(dtype)}
            for _ in range(n)]


def test_pipelined_losses_bit_identical_to_sync():
    """Same program, two fresh scopes: the pipelined loop must produce
    the exact loss byte sequence of the sync loop — same feed prep,
    same PRNG counter sequence, same dispatch order."""
    loss = _train_net()
    feeds = _feeds(6, 4, 8)

    exe1 = fluid.Executor(fluid.CPUPlace())
    s1 = Scope()
    exe1.run(fluid.default_startup_program(), scope=s1)
    sync = [np.asarray(exe1.run(feed=f, fetch_list=[loss], scope=s1)[0])
            for f in feeds]

    exe2 = fluid.Executor(fluid.CPUPlace())
    s2 = Scope()
    exe2.run(fluid.default_startup_program(), scope=s2)
    # the pipelined loop dispatches from a background thread; run it
    # under the armed scope sanitizer to prove the handoff is race-free
    from paddle_tpu.analysis import sanitizer

    sanitizer.arm()
    sanitizer.reset()
    try:
        runner = exe2.run_pipelined(feeds=feeds, fetch_list=[loss],
                                    scope=s2)
        piped = [np.asarray(out[0]) for out in runner]
    finally:
        sanitizer.disarm()
    assert sanitizer.violations() == []
    sanitizer.reset()

    assert len(piped) == len(sync)
    for a, b in zip(sync, piped):
        np.testing.assert_array_equal(a, b)
    # the trained weights also match bitwise
    np.testing.assert_array_equal(np.asarray(s1.find_value("fc_0.w_0")),
                                  np.asarray(s2.find_value("fc_0.w_0")))


def test_overlap_shown_by_span_timestamps(monkeypatch):
    """Trace-mode flight recording: at least one ``executor.stage_feed``
    span (stager thread) must overlap an in-flight ``executor.run`` span
    (consumer thread) in wall-clock — the pipelining is real, not just
    interleaved bookkeeping. (The run span, not the much narrower
    device_compute sub-span: on a 1-core host the ~ms staging window can
    legitimately land between two compute windows.)"""
    monkeypatch.setenv("PADDLE_TPU_TELEMETRY", "trace")
    loss = _train_net(width=128)
    # float64 feeds make staging do real work (astype + device_put) and
    # the wide batch makes device_compute dominate each step, so the
    # stager's work for batch N+1 lands inside step N's compute window
    feeds = _feeds(6, 1024, 128, dtype="float64")
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    obs.reset()  # scope span assertions to the pipelined loop only
    runner = exe.run_pipelined(feeds=feeds, fetch_list=[loss],
                               depth=2, window=2)
    results = list(runner)
    assert len(results) == 6

    def intervals(name):
        # span events record exit ts + duration: interval = [ts-dt, ts]
        return [(ev["ts"] - ev["seconds"], ev["ts"])
                for ev in obs.get_recorder().of("span")
                if ev["name"] == name]

    stage = intervals("executor.stage_feed")
    runs = intervals("executor.run")
    assert len(stage) == 6 and len(runs) == 6
    overlapped = sum(
        1 for s0, s1 in stage for r0, r1 in runs
        if min(s1, r1) > max(s0, r0))
    assert overlapped >= 1, \
        "no stage_feed span overlapped an in-flight executor.run span"
    # the summary gauge agrees
    assert runner.overlap_ratio() > 0.0
    assert obs.gauge("executor.overlap_ratio") > 0.0


def test_runner_is_single_use_and_close_discards(monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_TELEMETRY", "on")
    loss = _train_net()
    feeds = _feeds(8, 4, 8)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    runner = exe.run_pipelined(feeds=feeds, fetch_list=[loss])
    it = iter(runner)
    next(it)
    next(it)
    it.close()  # GeneratorExit -> runner.close(): stager stopped
    assert runner._stop.is_set()
    runner.close()  # idempotent
    with pytest.raises(RuntimeError):
        iter(runner)


def test_pipelined_from_py_reader_until_eof():
    """feeds=None pulls from the program's started py_reader and ends
    cleanly at EOF instead of raising."""
    reader = fluid.layers.py_reader(
        capacity=4, shapes=[(4, 8)], dtypes=["float32"], name="prd")
    (x,) = [fluid.layers.read_file(reader)]
    y = fluid.layers.fc(x, size=1)
    loss = fluid.layers.reduce_mean(y)
    fluid.optimizer.SGD(learning_rate=0.05).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())

    batches = [{"prd_slot0": f["x"]} for f in _feeds(5, 4, 8, seed=11)]
    reader.decorate_batch_generator(lambda: iter(batches))
    reader.start()
    runner = exe.run_pipelined(fetch_list=[loss])
    out = [np.asarray(o[0]) for o in runner]
    assert len(out) == 5
    assert all(np.isfinite(v).all() for v in out)


def test_pipelined_without_reader_raises():
    _train_net()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    runner = exe.run_pipelined()  # no feeds, no started reader
    with pytest.raises(fluid.core.ReaderNotStartedError):
        list(runner)


def test_reader_prefetch_to_device_stages_arrays():
    """prefetch_to_device: the consumer pops device-resident arrays and
    reset() invalidates staged batches (generation bump)."""
    reader = fluid.layers.py_reader(
        capacity=4, shapes=[(2, 4)], dtypes=["float32"], name="st")
    exe_place = fluid.CPUPlace()

    batches = [{"st_slot0": np.full((2, 4), i, "float32")}
               for i in range(4)]
    reader.decorate_batch_generator(lambda: iter(batches))
    reader.prefetch_to_device(exe_place)
    reader.start()
    first = reader._next_feed()
    v = first["st_slot0"]
    assert hasattr(v, "block_until_ready"), \
        "staged batch should be a device array"
    np.testing.assert_array_equal(np.asarray(v), batches[0]["st_slot0"])
    reader.reset()
    assert reader._staged is None
    # restart delivers the epoch from the top, staged again
    reader.start()
    first2 = reader._next_feed()
    np.testing.assert_array_equal(np.asarray(first2["st_slot0"]),
                                  batches[0]["st_slot0"])
