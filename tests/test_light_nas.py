"""LightNAS search subsystem end-to-end (round-5 rebuild; ref
contrib/slim/nas/* + slim/tests/test_light_nas.py usage pattern).

A yaml light_nas Compressor config runs a toy width-search on CPU:
tokens pick the hidden width of a 1-hidden-layer classifier, a FLOPs
budget excludes the widest choices, the SAController proposes/updates
over the socket ControllerServer/SearchAgent protocol, and candidates
train+evaluate through the ordinary jitted Executor.
"""
import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid.contrib.slim.nas import SearchSpace

V_IN, NCLS = 8, 3
WIDTHS = [4, 8, 16, 64]          # token t -> hidden width


def _data(n=96, seed=0):
    rng = np.random.default_rng(seed)
    xs = rng.standard_normal((n, V_IN)).astype("float32")
    ys = np.argmax(xs[:, :NCLS], axis=1).astype("int64")[:, None]
    return xs, ys


class ToyWidthSpace(SearchSpace):
    """One token choosing the hidden width; FLOPs grow with width so a
    budget can genuinely exclude candidates."""

    def __init__(self):
        self.created = []     # tokens history, for assertions

    def init_tokens(self):
        return [3]            # start ABOVE the budget on purpose

    def range_table(self):
        return [len(WIDTHS)]

    def create_net(self, tokens=None):
        width = WIDTHS[tokens[0]]
        self.created.append(list(tokens))
        train_p, startup_p = fluid.Program(), fluid.Program()
        train_p.random_seed = startup_p.random_seed = 7
        with fluid.program_guard(train_p, startup_p):
            x = fluid.data("nx", shape=[None, V_IN], dtype="float32")
            y = fluid.data("ny", shape=[None, 1], dtype="int64")
            h = fluid.layers.fc(x, width, act="relu")
            logits = fluid.layers.fc(h, NCLS)
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(logits, y))
            acc = fluid.layers.accuracy(fluid.layers.softmax(logits), y)
        test_p = train_p.clone(for_test=True)
        with fluid.program_guard(train_p, startup_p):
            fluid.optimizer.Adam(5e-2).minimize(loss)
        xs, ys = _data()

        def reader():
            for i in range(0, len(xs), 32):
                yield [(xs[j], ys[j]) for j in range(i, i + 32)]

        train_metrics = [("loss", loss.name)]
        test_metrics = [("acc_top1", acc.name)]
        return (startup_p, train_p, test_p, train_metrics, test_metrics,
                reader, reader)


def test_controller_server_agent_roundtrip():
    from paddle_tpu.fluid.contrib.slim.nas import (
        ControllerServer, SearchAgent)
    from paddle_tpu.fluid.contrib.slim.searcher import SAController

    ctrl = SAController(range_table=[4, 4], init_temperature=10)
    ctrl.reset([4, 4], [0, 0])
    server = ControllerServer(controller=ctrl,
                              address=("127.0.0.1", 0), key="toy-key")
    server.start()
    try:
        agent = SearchAgent("127.0.0.1", server.port(), key="toy-key")
        t1 = agent.next_tokens()
        assert len(t1) == 2 and all(0 <= t < 4 for t in t1)
        t2 = agent.update([1, 2], 0.75)
        assert len(t2) == 2
        assert ctrl._iter == 1             # the update reached the SA
        assert ctrl.best_tokens == [1, 2]
        assert ctrl.max_reward == 0.75
    finally:
        server.close()


def test_light_nas_yaml_search_end_to_end(tmp_path, monkeypatch):
    from paddle_tpu.fluid.contrib.slim import Compressor

    monkeypatch.chdir(tmp_path)   # the strategy drops its flock file
    # budget excludes widths 64 and 16:
    # flops(mul) = V_IN*w + w*NCLS = 11w  -> cap at w<=8 => 88
    cfg = tmp_path / "compress.yaml"
    cfg.write_text("""
version: 1.0
controllers:
    sa_controller:
        class: 'SAController'
        reduce_rate: 0.9
        init_temperature: 1024
        max_iter_number: 300
strategies:
    light_nas_strategy:
        class: 'LightNASStrategy'
        controller: 'sa_controller'
        target_flops: %d
        target_latency: 0
        end_epoch: 2
        retrain_epoch: 1
        metric_name: 'acc_top1'
        is_server: 1
        server_ip: '127.0.0.1'
        max_client_num: 10
        search_steps: 50
compressor:
    epoch: 3
    strategies:
        - light_nas_strategy
""" % (11 * 8))
    space = ToyWidthSpace()
    exe = fluid.Executor(fluid.CPUPlace())
    comp = Compressor(
        place=exe.place, scope=fluid.global_scope(),
        train_program=fluid.Program(),      # replaced per-candidate
        train_reader=None,
        train_feed_list=[("nx", "nx"), ("ny", "ny")],
        train_fetch_list=[("loss", "unused")],
        eval_program=fluid.Program(),
        eval_reader=None,
        eval_feed_list=[("nx", "nx"), ("ny", "ny")],
        eval_fetch_list=[("acc_top1", "unused")],
        search_space=space,
        log_period=2)
    comp.config(str(cfg))
    ctx = comp.run()

    from paddle_tpu.fluid.contrib.slim.graph import GraphWrapper

    # every adopted candidate respected the FLOPs budget (init token 3
    # = width 64 had to be rejected and re-proposed)
    assert any(t == [3] for t in space.created)
    assert ctx.eval_graph.flops() <= 11 * 8
    # rewards flowed: controller saw >= 2 updates (epochs 0 and 1) and
    # holds a best candidate within budget
    strategy = comp.strategies[0]
    ctrl = strategy._controller
    assert ctrl._iter >= 2
    assert WIDTHS[ctrl.best_tokens[0]] <= 8
    assert ctrl.max_reward > 0.3          # toy task is learnable
    # eval results recorded per epoch
    assert len(ctx.eval_results["acc_top1"]) == 3


def test_wrong_key_yields_clear_error():
    import pytest

    from paddle_tpu.fluid.contrib.slim.nas import (
        ControllerServer, SearchAgent)
    from paddle_tpu.fluid.contrib.slim.searcher import SAController

    ctrl = SAController(range_table=[3])
    ctrl.reset([3], [0])
    server = ControllerServer(controller=ctrl,
                              address=("127.0.0.1", 0), key="right")
    server.start()
    try:
        bad = SearchAgent("127.0.0.1", server.port(), key="wrong")
        with pytest.raises(RuntimeError, match="key mismatch"):
            bad.update([1], 0.5)
        assert ctrl._iter == 0    # noise never reached the controller
    finally:
        server.close()
