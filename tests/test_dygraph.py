"""Dygraph mode tests: tape autodiff, Layer modules, static↔dygraph parity,
checkpointing, TracedLayer."""
import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import dygraph


def test_tape_gradients_match_analytic():
    with dygraph.guard():
        x = dygraph.to_variable(np.array([[1.0, 2.0], [3.0, 4.0]], "float32"))
        from paddle_tpu.fluid.dygraph.tracer import call_op

        y = call_op("elementwise_mul", {"X": [x], "Y": [x]}, {"axis": -1})
        loss = call_op("mean", {"X": [y]})
        loss.backward()
        # d(mean(x^2))/dx = 2x / n
        np.testing.assert_allclose(
            x.gradient(), 2 * x.numpy() / 4.0, rtol=1e-6
        )


def test_dygraph_mnist_layer_trains():
    rng = np.random.default_rng(0)
    imgs = rng.standard_normal((64, 16)).astype("float32")
    labels = rng.integers(0, 4, size=(64, 1)).astype("int64")
    for i in range(64):
        imgs[i, labels[i, 0] * 4 : labels[i, 0] * 4 + 4] += 2.0

    with dygraph.guard():
        class Net(dygraph.Layer):
            def __init__(self):
                super().__init__("net")
                self.l1 = dygraph.Linear(16, 32, act="relu")
                self.l2 = dygraph.Linear(32, 4)

            def forward(self, x):
                return self.l2(self.l1(x))

        model = Net()
        opt = fluid.optimizer.Adam(1e-2)
        losses = []
        for step in range(30):
            x = dygraph.to_variable(imgs)
            y = dygraph.to_variable(labels)
            logits = model(x)
            from paddle_tpu.fluid.dygraph.tracer import call_op

            loss_t = call_op(
                "softmax_with_cross_entropy",
                {"Logits": [logits], "Label": [y]},
                {"soft_label": False},
                out_slots=("Softmax", "Loss"),
            )["Loss"][0]
            loss = call_op("mean", {"X": [loss_t]})
            loss.backward()
            opt.minimize(loss, parameter_list=model.parameters())
            model.clear_gradients()
            losses.append(float(loss.numpy()))
        assert losses[-1] < losses[0] * 0.3, (losses[0], losses[-1])


def test_state_dict_roundtrip(tmp_path):
    with dygraph.guard():
        m = dygraph.Linear(4, 3)
        sd = m.state_dict()
        dygraph.save_dygraph(sd, str(tmp_path / "model"))
        params, _ = dygraph.load_dygraph(str(tmp_path / "model"))
        m2 = dygraph.Linear(4, 3)
        m2.set_dict({k: v for k, v in zip(m2.state_dict().keys(),
                                          params.values())})
        x = dygraph.to_variable(np.ones((2, 4), "float32"))
        np.testing.assert_allclose(
            m(x).numpy(), m2(x).numpy(), rtol=1e-6
        )


def test_traced_layer_matches_eager():
    with dygraph.guard():
        m = dygraph.Linear(8, 4, act="relu")
        x = dygraph.to_variable(
            np.random.default_rng(0).standard_normal((5, 8)).astype("float32")
        )
        eager_out = m(x).numpy()
        outs, traced = dygraph.TracedLayer.trace(m, [x])
        np.testing.assert_allclose(outs[0].numpy(), eager_out, rtol=1e-6)
        # second call hits the jitted path
        np.testing.assert_allclose(
            traced([x])[0].numpy(), eager_out, rtol=1e-6
        )


def test_batchnorm_layer_updates_stats_and_eval_mode():
    with dygraph.guard():
        bn = dygraph.BatchNorm(num_channels=3)
        x = dygraph.to_variable(
            (np.random.default_rng(0).standard_normal((4, 3, 5, 5)) * 2 + 1)
            .astype("float32")
        )
        bn.train()
        _ = bn(x)
        mean_after_train = bn._mean.numpy().copy()
        assert not np.allclose(mean_after_train, 0.0)
        bn.eval()
        _ = bn(x)
        # eval must not move the stats
        np.testing.assert_allclose(bn._mean.numpy(), mean_after_train)


def test_static_vs_dygraph_same_numbers():
    """Same weights, same input → same output in both modes."""
    w = np.random.default_rng(1).standard_normal((6, 3)).astype("float32")
    b = np.zeros(3, "float32")
    x = np.random.default_rng(2).standard_normal((4, 6)).astype("float32")

    # static
    xin = fluid.data(name="x", shape=[None, 6], dtype="float32")
    from paddle_tpu.fluid.initializer import NumpyArrayInitializer
    from paddle_tpu.fluid.param_attr import ParamAttr

    y = fluid.layers.fc(
        xin, 3,
        param_attr=ParamAttr(initializer=NumpyArrayInitializer(w)),
        bias_attr=ParamAttr(initializer=NumpyArrayInitializer(b)),
        act="tanh",
    )
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    static_out = exe.run(feed={"x": x}, fetch_list=[y])[0]

    # dygraph
    with dygraph.guard():
        m = dygraph.Linear(
            6, 3,
            param_attr=ParamAttr(initializer=NumpyArrayInitializer(w)),
            bias_attr=ParamAttr(initializer=NumpyArrayInitializer(b)),
            act="tanh",
        )
        dy_out = m(dygraph.to_variable(x)).numpy()
    np.testing.assert_allclose(static_out, dy_out, rtol=1e-5)
