"""Embedding & retrieval serving (ISSUE 20): ep-sharded tables,
distributed-linalg parity, and the RetrievalEngine kind.

Bit-identity note: the sharded lookup combines per-shard gathers with
an integer-bitcast ``psum`` (one non-zero word per element — lossless),
so lookups assert ``array_equal`` against the single-device gather, not
allclose. Top-k scoring runs ONE ``dot_general`` over the full inner
dim per chunk (the reduction is never split), so ids assert exact
equality whenever the synthetic scores are tie-free; score values get
the documented float tolerance.
"""
import json
import urllib.error
import urllib.request

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu import retrieval
from paddle_tpu.analysis import tpu_lint
from paddle_tpu.analysis.diagnostics import ProgramVerifyError
from paddle_tpu.retrieval import (
    RetrievalEngine, ShardedEmbeddingTable, default_query_buckets, ep_mesh,
)
from paddle_tpu.serving import (
    EngineClosedError, ModelRegistry, ServingServer,
)

pytestmark = pytest.mark.retrieval


@pytest.fixture(scope="module")
def mesh8():
    import jax

    if jax.device_count() < 8:
        pytest.skip("needs 8 (virtual) devices")
    return ep_mesh(8)


@pytest.fixture(scope="module")
def table8(mesh8):
    # 1000 rows over 8 shards: 125 rows/shard, no pad — plus the odd
    # table below covers padding
    return ShardedEmbeddingTable(1000, 16, mesh=mesh8, seed=3)


# ---------------------------------------------------------------------------
# sharded table: 8-way lookup parity (the tentpole bit-exactness claim)
# ---------------------------------------------------------------------------

def test_lookup_bit_identical_8way(table8):
    rng = np.random.default_rng(0)
    ids = rng.integers(0, table8.vocab_size, size=257)
    got = table8.lookup(ids)
    ref = table8.host_rows()[ids]
    assert got.dtype == ref.dtype
    assert np.array_equal(
        got.view(np.uint32), ref.view(np.uint32))  # bit for bit


def test_lookup_padded_vocab_and_shapes(mesh8):
    # 1003 rows over 8 shards -> 126/shard with 5 pad rows: ids near
    # the boundary still resolve to the true rows, never the pad
    tbl = ShardedEmbeddingTable(1003, 8, mesh=mesh8, seed=1)
    ids = np.array([[0, 1001], [1002, 500]])
    got = tbl.lookup(ids)
    assert got.shape == (2, 2, 8)
    assert np.array_equal(got, tbl.host_rows()[ids])
    # empty request short-circuits host-side
    assert tbl.lookup(np.zeros((0,), np.int64)).shape == (0, 8)


def test_lookup_float16_bit_identical(mesh8):
    tbl = ShardedEmbeddingTable(200, 8, mesh=mesh8, dtype="float16",
                                seed=2)
    ids = np.arange(0, 200, 3)
    assert np.array_equal(
        tbl.lookup(ids).view(np.uint16),
        tbl.host_rows()[ids].view(np.uint16))


def test_lookup_rejects_bad_ids(table8):
    with pytest.raises(ValueError, match="out of range"):
        table8.lookup([0, table8.vocab_size])
    with pytest.raises(ValueError, match="out of range"):
        table8.lookup([-1])
    with pytest.raises(ValueError, match="integers"):
        table8.lookup(np.array([0.5]))


def test_from_array_and_geometry(mesh8):
    rows = np.arange(24, dtype=np.float32).reshape(6, 4)
    tbl = ShardedEmbeddingTable.from_array(rows, mesh=mesh8, name="toy")
    assert np.array_equal(tbl.host_rows(), rows)
    assert np.array_equal(tbl.lookup([5, 0]), rows[[5, 0]])
    info = tbl.index_info()
    assert info["rows"] == 6 and info["dim"] == 4 and info["shards"] == 8
    # 6 rows pad to 8 (1/shard) and residency accounts the pad
    assert tbl.rows_per_shard == 1
    assert info["resident_bytes"] == 8 * 4 * 4
    assert info["resident_bytes_per_shard"] == 4 * 4


def test_checkpoint_roundtrip_and_reshard(tmp_path, mesh8):
    tbl = ShardedEmbeddingTable(77, 8, mesh=mesh8, seed=9, name="idx")
    tbl.save(str(tmp_path), step=3)
    # restore onto a DIFFERENT ep width: the checkpoint holds plain
    # host rows, so resharding is free — and still bit-identical
    back = ShardedEmbeddingTable.restore(str(tmp_path), ep=4, name="idx")
    assert back.ep == 4
    assert np.array_equal(
        back.host_rows().view(np.uint32),
        tbl.host_rows().view(np.uint32))
    ids = np.arange(77)
    assert np.array_equal(back.lookup(ids), tbl.lookup(ids))
    # a single-table checkpoint is adopted whatever name was asked...
    adopted = ShardedEmbeddingTable.restore(str(tmp_path), name="nope")
    assert adopted.name == "idx"
    # ...but an ambiguous (multi-table) checkpoint raises
    from paddle_tpu.parallel.checkpoint import save_checkpoint

    multi = tmp_path / "multi"
    save_checkpoint(str(multi), {"a.table": tbl.host_rows(),
                                 "b.table": tbl.host_rows()}, step=0)
    with pytest.raises(IOError, match="holds no 'nope' table"):
        ShardedEmbeddingTable.restore(str(multi), name="nope")


# ---------------------------------------------------------------------------
# distributed linalg: blocked matmul / power iteration / sharded top-k
# ---------------------------------------------------------------------------

def test_blocked_matmul_parity(mesh8):
    rng = np.random.default_rng(4)
    # 37 rows: NOT a multiple of ep=8, exercises the row pad; block
    # rounds down to a divisor of the 5-row shard
    a = rng.standard_normal((37, 24)).astype(np.float32)
    b = rng.standard_normal((24, 11)).astype(np.float32)
    out = retrieval.blocked_matmul(a, b, mesh=mesh8, block_rows=3)
    assert out.shape == (37, 11)
    np.testing.assert_allclose(out, a @ b, rtol=2e-5, atol=2e-5)
    with pytest.raises(ValueError, match="blocked_matmul wants"):
        retrieval.blocked_matmul(a, b.T, mesh=mesh8)


def test_power_iteration_dominant_eigenpair(mesh8):
    rng = np.random.default_rng(5)
    g = rng.standard_normal((64, 64)).astype(np.float32)
    psd = (g @ g.T) / 64.0  # PSD: clean eigengap, no +/- ambiguity
    eig, vec, residual = retrieval.power_iteration(
        psd, iters=60, mesh=mesh8)
    ref = float(np.linalg.eigvalsh(psd)[-1])
    assert abs(eig - ref) / ref < 1e-2
    assert residual < 0.05
    assert abs(np.linalg.norm(vec) - 1.0) < 1e-4


def test_sharded_topk_exact_vs_reference(mesh8):
    rng = np.random.default_rng(6)
    tbl = ShardedEmbeddingTable(500, 12, mesh=mesh8, seed=7)
    q = rng.standard_normal((9, 12)).astype(np.float32)
    scores, ids = retrieval.sharded_topk(tbl, q, k=10, chunk_rows=17)
    full = q @ tbl.host_rows().T
    ref_ids = np.argsort(-full, axis=1)[:, :10]
    # continuous random scores are tie-free -> ids match exactly
    assert np.array_equal(ids, ref_ids)
    np.testing.assert_allclose(
        scores, np.take_along_axis(full, ref_ids, axis=1),
        rtol=1e-5, atol=1e-5)
    # 1-d query promotes to one row
    s1, i1 = retrieval.sharded_topk(tbl, q[0], k=3)
    assert i1.shape == (1, 3) and np.array_equal(i1[0], ref_ids[0, :3])
    with pytest.raises(ValueError, match="does not match table dim"):
        retrieval.sharded_topk(tbl, np.zeros((2, 5), np.float32))


def test_roofline_accounting():
    assert retrieval.matmul_flops(3, 5, 7) == 2.0 * 3 * 5 * 7

    class P:
        peak_flops = 1e9

    assert retrieval.fraction_of_roofline(5e8, 1.0, P()) == 0.5
    assert retrieval.fraction_of_roofline(5e8, 1.0, P(), n_devices=2) == 0.25
    assert retrieval.fraction_of_roofline(5e8, 0.0, P()) is None
    assert retrieval.fraction_of_roofline(5e8, 1.0, None) is None


# ---------------------------------------------------------------------------
# RetrievalEngine: the serving surface
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def engine(table8):
    eng = RetrievalEngine(table8, k=5, query_buckets=(2, 4, 8),
                          name="idx8")
    eng.warmup()
    yield eng
    eng.stop(drain=False, timeout=5)


def test_engine_lookup_and_search_parity(engine, table8):
    rng = np.random.default_rng(8)
    ids = rng.integers(0, table8.vocab_size, size=6)
    emb = engine.lookup(ids)
    assert np.array_equal(np.asarray(emb), table8.host_rows()[ids])
    q = rng.standard_normal((3, table8.dim)).astype(np.float32)
    got_ids, got_scores = engine.search(q)
    ref = np.argsort(-(q @ table8.host_rows().T), axis=1)[:, :5]
    assert np.array_equal(np.asarray(got_ids), ref)
    assert np.asarray(got_scores).shape == (3, 5)


def test_engine_coalesces_same_op(engine, table8):
    # several concurrent lookups of the same op land in ONE padded
    # dispatch: per-request results still match the reference exactly
    before = engine.stats().get("coalesced", 0)
    futs = [engine.submit({"op": "lookup", "ids": [i, i + 1]})
            for i in range(5)]
    outs = [f.result(30) for f in futs]
    for i, out in enumerate(outs):
        assert np.array_equal(
            np.asarray(out["embeddings"]),
            table8.host_rows()[[i, i + 1]])
    assert engine.stats().get("coalesced", 0) >= before


def test_engine_rejects_malformed(engine):
    with pytest.raises(ValueError, match="unknown retrieval op"):
        engine.submit({"op": "frobnicate"})
    with pytest.raises(ValueError, match="must be a dict"):
        engine.submit([1, 2])
    with pytest.raises(ValueError, match="out of range"):
        engine.submit({"op": "lookup", "ids": [10**9]})
    with pytest.raises(ValueError, match="one compiled ladder per k"):
        engine.submit({"op": "search",
                       "query": np.zeros((1, 16)), "k": 7})
    with pytest.raises(ValueError, match="largest query bucket"):
        engine.submit({"op": "lookup", "ids": list(range(100))})
    with pytest.raises(ValueError, match="does not match index dim"):
        engine.submit({"op": "search", "query": np.zeros((1, 3))})
    # op inferred from the payload: "query" present -> search
    ids, _ = engine.search(np.zeros((1, 16), np.float32))
    out = engine.predict({"query": np.zeros((1, 16)).tolist()})
    assert np.array_equal(np.asarray(out["ids"]), np.asarray(ids))


def test_engine_budget_gates_warmup(table8):
    eng = RetrievalEngine(table8, k=5, query_buckets=(4, 64),
                          auto_start=False, name="budget")
    # generous budget: every rung priced and admitted
    rungs = eng.check_hbm_budget(budget_bytes=1 << 34)
    assert [b for b, _ in rungs] == [4, 64]
    assert all(peak > 0 for _, peak in rungs)
    # starvation budget: the raise names the over-budget rungs and the
    # text carries the predicted-oom marker the perf gate greps for
    with pytest.raises(ProgramVerifyError, match="predicted-oom"):
        eng.check_hbm_budget(budget_bytes=1024)
    try:
        eng.check_hbm_budget(budget_bytes=1024)
    except ProgramVerifyError as e:
        assert "2 of 2 query ladder rung(s)" in str(e)
        assert "query bucket 64" in str(e)


def test_engine_ladder_lint(table8):
    eng = RetrievalEngine(table8, k=5, query_buckets=(2, 4, 8),
                          auto_start=False, name="lint")
    rep = eng.check_ladder()
    # 3 lookup rungs + 3 search rungs (one k)
    assert rep.meta["retrieval_ladder_programs"] == 6
    assert not rep.findings


def test_engine_stats_and_stop(table8):
    eng = RetrievalEngine(table8, k=3, query_buckets=(2,), name="brief")
    eng.lookup([1, 2])
    st = eng.stats()
    assert st["requests"] >= 1 and st["lookups"] >= 1
    assert eng.queue_depth() == 0
    eng.stop(drain=True, timeout=5)
    assert eng.closed
    with pytest.raises(EngineClosedError):
        eng.submit({"op": "lookup", "ids": [1]})


# ---------------------------------------------------------------------------
# registry + HTTP: the third engine kind through the shared frontend
# ---------------------------------------------------------------------------

def _post(url, doc, timeout=30):
    req = urllib.request.Request(
        url, data=json.dumps(doc).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.load(resp)
    except urllib.error.HTTPError as e:
        return e.code, json.load(e)


def test_http_lookup_search_and_kind_routing(engine, table8):
    reg = ModelRegistry()
    reg.publish("items", engine)
    # registry info carries the served index geometry
    assert reg.info()["items"]["index"]["rows"] == table8.vocab_size
    srv = ServingServer(reg).start()
    try:
        code, doc = _post(srv.url + "/v1/models/items:lookup",
                          {"ids": [3, 14, 159]})
        assert code == 200, doc
        got = np.asarray(doc["embeddings"], dtype=np.float32)
        assert np.array_equal(got, table8.host_rows()[[3, 14, 159]])
        assert doc["model"] == "items"

        rng = np.random.default_rng(10)
        q = rng.standard_normal((2, table8.dim)).astype(np.float32)
        code, doc = _post(srv.url + "/v1/models/items:search",
                          {"query": q.tolist(), "k": 5})
        assert code == 200, doc
        ref = np.argsort(-(q @ table8.host_rows().T), axis=1)[:, :5]
        assert np.array_equal(np.asarray(doc["ids"]), ref)
        assert doc["k"] == 5

        # mismatched verb: 400 that NAMES the engine kind + right verb
        code, doc = _post(srv.url + "/v1/models/items:predict",
                          {"feeds": {"x": [1.0]}})
        assert code == 400 and doc["kind"] == "retrieval"
        assert ":lookup or :search" in doc["error"]
        code, doc = _post(srv.url + "/v1/models/items:generate",
                          {"prompt": [1]})
        assert code == 400 and doc["kind"] == "retrieval"

        # malformed body / unknown model keep the standard mapping
        code, doc = _post(srv.url + "/v1/models/items:search",
                          {"query": [[0.0] * 3]})
        assert code == 400
        code, doc = _post(srv.url + "/v1/models/nope:lookup",
                          {"ids": [1]})
        assert code == 404

        with urllib.request.urlopen(srv.url + "/healthz",
                                    timeout=10) as r:
            hz = json.load(r)
        m = hz["models"]["items"]
        assert m["kind"] == "retrieval"
        assert m["index"]["shards"] == 8 and m["index"]["k"] == 5
    finally:
        srv.stop()  # engine lifecycle belongs to the publish caller


def test_http_predict_engine_refuses_retrieval_verbs(tmp_path):
    # a plain predict engine on :search gets the same kind-naming 400
    from paddle_tpu.fluid.inference import Predictor
    from paddle_tpu.serving import BucketSpec, ServingEngine
    from paddle_tpu.fluid import framework, unique_name

    framework.switch_main_program(framework.Program())
    framework.switch_startup_program(framework.Program())
    unique_name.switch()
    x = fluid.data(name="x", shape=[None, 4], dtype="float32")
    out = fluid.layers.fc(x, size=2, act="softmax")
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    fluid.io.save_inference_model(
        str(tmp_path / "m"), ["x"], [out], exe,
        main_program=fluid.default_main_program())
    eng = ServingEngine(
        Predictor.from_model(str(tmp_path / "m")),
        buckets=[BucketSpec({"x": (4,)}, batch_sizes=(1, 2))])
    reg = ModelRegistry()
    reg.publish("m", eng)
    srv = ServingServer(reg).start()
    try:
        code, doc = _post(srv.url + "/v1/models/m:search",
                          {"query": [[0.0] * 4]})
        assert code == 400 and doc["kind"] == "predict"
        assert ":predict" in doc["error"]
    finally:
        srv.stop(close_registry=True)


# ---------------------------------------------------------------------------
# satellites: planner ingestion, lint, memory accounting
# ---------------------------------------------------------------------------

def test_from_plan_accepts_ep_for_retrieval():
    from paddle_tpu.parallel.fleet import DistributedStrategy
    from paddle_tpu.planner import ParallelPlan

    s = DistributedStrategy.from_plan(
        ParallelPlan({"ep": 8}), workload="retrieval")
    assert s.embedding_parallel_degree == 8
    # the degree feeds ep_mesh directly
    assert ep_mesh(s.embedding_parallel_degree).shape["ep"] == 8
    # dp x ep composes for the embedding workload family
    s = DistributedStrategy.from_plan(
        ParallelPlan({"dp": 2, "ep": 4}), workload="embedding")
    assert s.embedding_parallel_degree == 4


def test_from_plan_refuses_ep_for_train_with_hint():
    from paddle_tpu.parallel.fleet import DistributedStrategy
    from paddle_tpu.planner import ParallelPlan

    with pytest.raises(NotImplementedError) as ei:
        DistributedStrategy.from_plan(ParallelPlan({"ep": 8}))
    msg = str(ei.value)
    assert "workload='retrieval'" in msg
    assert "paddle_tpu.retrieval" in msg
    # pp stays refused even for retrieval workloads
    with pytest.raises(NotImplementedError):
        DistributedStrategy.from_plan(
            ParallelPlan({"ep": 4, "pp": 2}), workload="retrieval")


def test_lint_low_intensity_gather_on_ctr():
    from paddle_tpu.models import wide_deep as wd

    wd.build_wide_deep(num_sparse_fields=6, sparse_vocab=100000,
                       emb_dim=16, num_dense=13, hidden=[32])
    rep = tpu_lint.lint(fluid.default_main_program())
    perf = [d for d in rep.diagnostics
            if d.check == "low-intensity-gather"]
    # the 6.4 MB ctr_emb draws the finding; the 400 KB wide table is
    # under the floor and stays quiet
    assert len(perf) == 1 and perf[0].var == "ctr_emb"
    assert "ShardedEmbeddingTable" in perf[0].message
    # PERF advisories never fail a gate
    assert not [d for d in rep.findings
                if d.check == "low-intensity-gather"]


def test_lint_small_embedding_stays_clean():
    sparse = fluid.data(name="s", shape=[None, 4], dtype="int64")
    fluid.layers.embedding(sparse, size=[1000, 16])
    rep = tpu_lint.lint(fluid.default_main_program())
    assert not [d for d in rep.diagnostics
                if d.check == "low-intensity-gather"]


def test_lint_retrieval_ladder_counts():
    # a sane pow2 ladder is clean
    rep = tpu_lint.lint_retrieval_ladder((1, 2, 4, 8), k_values=(10,))
    assert rep.meta["retrieval_ladder_programs"] == 8
    assert not rep.findings
    # thousands of rungs x many k blows the shared shape-vocab budget
    rep = tpu_lint.lint_retrieval_ladder(
        tuple(range(1, 1001)), k_values=(1, 5, 10, 50, 100))
    assert rep.meta["retrieval_ladder_programs"] == 1000 + 1000 * 5
    assert "unbounded-shape-vocab" in {
        d.check for d in rep.findings}
    # non-pow2 rungs draw the each-is-an-extra-executable INFO
    rep = tpu_lint.lint_retrieval_ladder((3, 4, 8), k_values=(10,))
    assert "retrieval-ladder-rungs" in {d.check for d in rep.diagnostics}
    assert not rep.findings


def test_memory_shard_divisors_ep_divides_params():
    from paddle_tpu.analysis.memory import shard_divisors

    # ep rows-shards the table (a parameter), never the batch
    assert shard_divisors({"ep": 8}) == (8, 1)
    assert shard_divisors({"dp": 2, "ep": 4}) == (4, 2)


def test_ctr_embedding_rides_sharded_table(mesh8):
    """The migration path: train the CTR model's ``ctr_emb`` the fluid
    way, lift the trained rows out of the scope into a sharded table,
    and serve lookups bit-identical to the trained parameter."""
    from paddle_tpu.models import wide_deep as wd

    fluid.default_startup_program().random_seed = 5
    vs = wd.build_wide_deep(num_sparse_fields=6, sparse_vocab=512,
                            emb_dim=8, num_dense=4, hidden=[16])
    fluid.optimizer.Adam(1e-2).minimize(vs["loss"])
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    dense, sparse, label = wd.synthetic_ctr_batch(
        64, num_sparse_fields=6, sparse_vocab=512, num_dense=4)
    for _ in range(2):
        exe.run(feed={"dense": dense, "sparse": sparse,
                      "ctr_label": label},
                fetch_list=[vs["loss"]])
    trained = np.asarray(
        fluid.global_scope().find_var("ctr_emb").get_tensor()).copy()
    tbl = ShardedEmbeddingTable.from_array(trained, mesh=mesh8,
                                           name="ctr_emb")
    ids = np.unique(sparse.reshape(-1))[:32]
    assert np.array_equal(
        tbl.lookup(ids).view(np.uint32),
        trained[ids].view(np.uint32))
    eng = RetrievalEngine(tbl, k=4, query_buckets=(8, 32), name="ctr")
    try:
        out = np.asarray(eng.lookup(ids.tolist()))
        assert np.array_equal(out.view(np.uint32),
                              trained[ids].view(np.uint32))
    finally:
        eng.stop(drain=True, timeout=5)
