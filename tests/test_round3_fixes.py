"""Round-3 regression tests: dygraph grad clipping, ADVICE fixes,
accepted-kwarg audit."""
import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import dygraph
from paddle_tpu.fluid.dygraph_grad_clip import (
    GradClipByValue,
    GradClipByNorm,
    GradClipByGlobalNorm,
)


def _global_norm(arrs):
    return float(np.sqrt(sum(float(np.sum(np.square(a))) for a in arrs)))


def test_grad_clip_by_value_eager():
    g = np.array([[-3.0, 0.5], [2.0, -0.1]], "float32")
    clip = GradClipByValue(-1.0, 1.0)
    (_, out), = clip([(None, g)])
    np.testing.assert_allclose(np.asarray(out), np.clip(g, -1.0, 1.0))
    # min defaults to -max
    clip2 = GradClipByValue(None, 0.25)
    (_, out2), = clip2([(None, g)])
    np.testing.assert_allclose(np.asarray(out2), np.clip(g, -0.25, 0.25))


def test_grad_clip_by_norm_eager():
    g = np.full((4, 4), 2.0, "float32")  # norm = 8
    clip = GradClipByNorm(2.0)
    (_, out), = clip([(None, g)])
    assert abs(_global_norm([np.asarray(out)]) - 2.0) < 1e-4
    # under the limit: unchanged
    small = np.full((2,), 0.1, "float32")
    (_, out2), = clip([(None, small)])
    np.testing.assert_allclose(np.asarray(out2), small, rtol=1e-6)


def test_grad_clip_by_global_norm_eager():
    g1 = np.full((3, 3), 1.0, "float32")
    g2 = np.full((4,), 2.0, "float32")
    orig = _global_norm([g1, g2])
    clip = GradClipByGlobalNorm(1.0)
    out = clip([(None, g1), (None, None), (None, g2)])
    assert out[1][1] is None
    got = _global_norm([np.asarray(out[0][1]), np.asarray(out[2][1])])
    assert abs(got - 1.0) < 1e-4
    # ratio preserved across tensors
    np.testing.assert_allclose(
        np.asarray(out[0][1]) / g1, np.asarray(out[2][1])[0] / 2.0, rtol=1e-5
    )
    assert orig > 1.0


def test_dygraph_minimize_applies_global_norm_clip():
    max_norm = 0.01
    with dygraph.guard():
        m = dygraph.Linear(6, 3)
        x = dygraph.to_variable(
            np.random.default_rng(0).standard_normal((8, 6)).astype("float32")
        )
        from paddle_tpu.fluid.dygraph.tracer import call_op

        before = {p.name: np.asarray(p.value).copy() for p in m.parameters()}
        loss = call_op("mean", {"X": [call_op(
            "elementwise_mul", {"X": [m(x)], "Y": [m(x)]}, {"axis": -1})]})
        loss.backward()
        grads = [np.asarray(p.grad) for p in m.parameters()
                 if p.grad is not None]
        assert _global_norm(grads) > max_norm  # clip must actually bite
        opt = fluid.optimizer.SGD(learning_rate=1.0)
        opt.minimize(loss, parameter_list=m.parameters(),
                     grad_clip=GradClipByGlobalNorm(max_norm))
        # with lr=1.0 sgd, total param delta norm == clipped global norm
        deltas = [
            np.asarray(p.value) - before[p.name] for p in m.parameters()
        ]
        assert abs(_global_norm(deltas) - max_norm) < 1e-4


def test_dygraph_minimize_rejects_bad_grad_clip():
    with dygraph.guard():
        m = dygraph.Linear(2, 2)
        x = dygraph.to_variable(np.ones((1, 2), "float32"))
        from paddle_tpu.fluid.dygraph.tracer import call_op

        loss = call_op("mean", {"X": [m(x)]})
        loss.backward()
        opt = fluid.optimizer.SGD(learning_rate=0.1)
        with pytest.raises(TypeError):
            opt.minimize(loss, parameter_list=m.parameters(),
                         grad_clip=5.0)  # not a GradClipBase


def test_static_minimize_applies_grad_clip():
    max_norm = 0.01
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data("x", shape=[None, 6], dtype="float32")
        y = fluid.layers.fc(x, size=3)
        loss = fluid.layers.reduce_mean(fluid.layers.square(y))
        opt = fluid.optimizer.SGD(learning_rate=1.0)
        opt.minimize(loss, grad_clip=GradClipByGlobalNorm(max_norm))
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    params = [p.name for p in main.global_block().all_parameters()]
    scope = fluid.global_scope()
    xs = np.random.default_rng(1).standard_normal((8, 6)).astype("float32")
    before = {n: np.asarray(scope.find_var(n).get_tensor()).copy()
              for n in params}
    exe.run(main, feed={"x": xs}, fetch_list=[loss])
    deltas = [
        np.asarray(scope.find_var(n).get_tensor()) - before[n] for n in params
    ]
    assert abs(_global_norm(deltas) - max_norm) < 1e-3
    # the Optimizer.apply_gradients contract: static-path grad_clip is
    # real clip ops IN the program — every param update consumes the
    # clipped grad, and the clip ops precede the first update op
    ops = main.global_block().ops
    sgd_idx = [i for i, op in enumerate(ops) if op.type == "sgd"]
    assert sgd_idx
    for i in sgd_idx:
        (g,) = ops[i].inputs["Grad"]
        assert g.endswith("@GCLIP"), g
    clip_writers = [i for i, op in enumerate(ops)
                    if any(n.endswith("@GCLIP")
                           for ns in op.outputs.values() for n in ns)]
    assert clip_writers and max(clip_writers) < min(sgd_idx)


# ---------------------------------------------------------------------------
# silent-kwarg audit fixes
# ---------------------------------------------------------------------------
def test_gradients_target_gradients_scales_seed():
    main = fluid.Program()
    with fluid.program_guard(main, fluid.Program()):
        x = fluid.data("x", shape=[None, 3], dtype="float32")
        y = fluid.layers.reduce_sum(fluid.layers.square(x))  # dy/dx = 2x
        seed = fluid.layers.fill_constant([], "float32", 5.0)
        (gx,) = fluid.gradients(y, x, target_gradients=seed)
    exe = fluid.Executor(fluid.CPUPlace())
    xs = np.array([[1.0, 2.0, 3.0]], "float32")
    (out,) = exe.run(main, feed={"x": xs}, fetch_list=[gx])
    np.testing.assert_allclose(out, 5.0 * 2.0 * xs, rtol=1e-5)


def test_gradients_no_grad_set_blocks_flow():
    main = fluid.Program()
    with fluid.program_guard(main, fluid.Program()):
        x = fluid.data("x", shape=[None, 3], dtype="float32")
        h = fluid.layers.square(x)          # dh/dx = 2x
        z = fluid.layers.scale(h, scale=3.0)
        y = fluid.layers.reduce_sum(fluid.layers.elementwise_add(z, x))
        # block flow through h: only the direct +x path contributes
        (gx,) = fluid.gradients(y, x, no_grad_set={h.name})
    exe = fluid.Executor(fluid.CPUPlace())
    xs = np.array([[1.0, 2.0, 3.0]], "float32")
    (out,) = exe.run(main, feed={"x": xs}, fetch_list=[gx])
    np.testing.assert_allclose(out, np.ones_like(xs), rtol=1e-5)


def test_amp_dynamic_loss_scaling_decreases_on_overflow():
    from paddle_tpu.fluid.contrib import mixed_precision as mp

    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data("x", shape=[None, 4], dtype="float32")
        y = fluid.layers.fc(x, size=1)
        loss = fluid.layers.reduce_mean(y)
        opt = mp.decorate(
            fluid.optimizer.SGD(learning_rate=0.1),
            init_loss_scaling=1024.0,
            use_dynamic_loss_scaling=True,
            use_bf16=False,
            incr_every_n_steps=2,
            decr_every_n_nan_or_inf=1,
            incr_ratio=2.0,
            decr_ratio=0.5,
        )
        opt.minimize(loss)
        scale_var = opt.get_loss_scaling()
    assert hasattr(scale_var, "name"), "dynamic scaling must be a graph var"
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    scope = fluid.global_scope()
    params = [p.name for p in main.global_block().all_parameters()]

    ok = np.ones((2, 4), "float32")
    bad = np.full((2, 4), np.nan, "float32")
    # finite step: params move, scale unchanged (good=1 < incr_every_n=2)
    exe.run(main, feed={"x": ok}, fetch_list=[loss])
    s1 = float(np.asarray(scope.find_var(scale_var.name).get_tensor())[0])
    assert s1 == 1024.0
    before = {n: np.asarray(scope.find_var(n).get_tensor()).copy()
              for n in params}
    # nan step: params must NOT move, scale halves (decr_every_n=1)
    exe.run(main, feed={"x": bad}, fetch_list=[loss])
    s2 = float(np.asarray(scope.find_var(scale_var.name).get_tensor())[0])
    assert s2 == 512.0, s2
    for n in params:
        got = np.asarray(scope.find_var(n).get_tensor())
        np.testing.assert_allclose(got, before[n], atol=0,
                                   err_msg="params moved on overflow step")
    # second finite step reaches good=2 -> scale doubles
    exe.run(main, feed={"x": ok}, fetch_list=[loss])
    exe.run(main, feed={"x": ok}, fetch_list=[loss])
    s3 = float(np.asarray(scope.find_var(scale_var.name).get_tensor())[0])
    assert s3 == 1024.0, s3


def test_model_average_need_restore_false_then_restore():
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data("x", shape=[None, 2], dtype="float32")
        y = fluid.layers.fc(x, size=1)
        loss = fluid.layers.reduce_mean(y)
        opt = fluid.optimizer.SGD(learning_rate=0.5)
        opt.minimize(loss)
        ma = fluid.optimizer.ModelAverage(average_window_rate=0.5,
                                          min_average_window=1,
                                          max_average_window=100)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    for _ in range(4):
        exe.run(main, feed={"x": np.ones((2, 2), "float32")},
                fetch_list=[loss])
    scope = fluid.global_scope()
    pname = main.global_block().all_parameters()[0].name
    trained = np.asarray(scope.find_var(pname).get_tensor()).copy()
    with ma.apply(exe, need_restore=False):
        averaged = np.asarray(scope.find_var(pname).get_tensor()).copy()
    # still averaged after the guard exits
    now = np.asarray(scope.find_var(pname).get_tensor())
    np.testing.assert_allclose(now, averaged)
    assert not np.allclose(trained, averaged)
    ma.restore(exe)
    np.testing.assert_allclose(
        np.asarray(scope.find_var(pname).get_tensor()), trained)


def test_flatten_contiguous_axes():
    main = fluid.Program()
    with fluid.program_guard(main, fluid.Program()):
        x = fluid.layers.fill_constant([2, 3, 4, 5], "float32", 1.0)
        a = fluid.layers.flatten_contiguous(x, 1, 2)
        b = fluid.layers.flatten_contiguous(x, 0, -1)
    assert tuple(a.shape) == (2, 12, 5), a.shape
    assert tuple(b.shape) == (120,), b.shape
    exe = fluid.Executor(fluid.CPUPlace())
    av, bv = exe.run(main, feed={}, fetch_list=[a, b])
    assert av.shape == (2, 12, 5) and bv.shape == (120,)


def test_resize_nearest_nhwc_matches_nchw():
    main = fluid.Program()
    with fluid.program_guard(main, fluid.Program()):
        x = fluid.data("x", shape=[None, 3, 4, 4], dtype="float32")
        up_cf = fluid.layers.resize_nearest(x, out_shape=[8, 8])
        xt = fluid.layers.transpose(x, [0, 2, 3, 1])
        up_cl = fluid.layers.resize_nearest(
            xt, out_shape=[8, 8], data_format="NHWC")
    exe = fluid.Executor(fluid.CPUPlace())
    xs = np.random.default_rng(3).random((2, 3, 4, 4)).astype("float32")
    cf, cl = exe.run(main, feed={"x": xs}, fetch_list=[up_cf, up_cl])
    np.testing.assert_allclose(cf, np.transpose(cl, (0, 3, 1, 2)), rtol=1e-6)


def test_categorical_sample_shape():
    main = fluid.Program()
    with fluid.program_guard(main, fluid.Program()):
        logits = fluid.layers.fill_constant([2, 5], "float32", 0.0)
        dist = fluid.layers.Categorical(logits)
        s = dist.sample([7])
    exe = fluid.Executor(fluid.CPUPlace())
    (out,) = exe.run(main, feed={}, fetch_list=[s])
    assert out.shape == (7, 2)
    assert out.min() >= 0 and out.max() < 5


def test_decorate_reader_drop_last():
    main = fluid.Program()
    with fluid.program_guard(main, fluid.Program()):
        x = fluid.data("x", shape=[None, 2], dtype="float32")
    feeder = fluid.DataFeeder(feed_list=[x], place=fluid.CPUPlace())

    def batches():
        yield [(np.zeros(2, "float32"),)] * 4
        yield [(np.zeros(2, "float32"),)] * 2  # ragged tail

    kept = list(feeder.decorate_reader(batches, drop_last=True)())
    assert len(kept) == 1
    both = list(feeder.decorate_reader(batches, drop_last=False)())
    assert len(both) == 2


def test_imdb_word_idx_caps_vocab():
    from paddle_tpu.dataset import imdb

    small = {("w%d" % i).encode(): i for i in range(50)}
    seqs = [s for s, _ in list(imdb.train(small)())[:64]]
    assert max(max(s) for s in seqs) < 50


def test_wmt16_src_lang_swaps_direction():
    from paddle_tpu.dataset import wmt16

    en = list(wmt16.test()())[:5]
    de = list(wmt16.test(src_lang="de")())[:5]
    for (s_en, t_in_en, _), (s_de, t_in_de, t_next_de) in zip(en, de):
        assert s_de == t_in_en[1:]          # German side becomes source
        assert t_in_de == [0] + s_en        # English becomes target
        assert t_next_de == s_en + [1]


# ---------------------------------------------------------------------------
# ADVICE r2 fixes
# ---------------------------------------------------------------------------
def test_basic_gru_bidirectional_independent_stacks():
    """Layer>0 weights must have input width D (independent per-direction
    stacks, ref topology), not 2D (concat-after-every-layer)."""
    from paddle_tpu.fluid.contrib.layers import basic_gru

    D = 8
    main = fluid.Program()
    with fluid.program_guard(main, fluid.Program()):
        x = fluid.data("x", shape=[None, 5, 12], dtype="float32")
        out, last_h = basic_gru(x, None, D, num_layers=2,
                                bidirectional=True, name="bgadv")
        params = {p.name: p for p in main.global_block().all_parameters()}
    l1_gate = [p for n, p in params.items()
               if "l1" in n and len(p.shape) == 2 and p.shape[1] == 2 * D]
    assert l1_gate, list(params)
    for p in l1_gate:
        assert p.shape[0] == D + D, (
            "layer-1 cell consumes its own direction's D-wide output, "
            "got input width %d" % (p.shape[0] - D)
        )
    assert tuple(out.shape[-1:]) == (2 * D,)
    assert tuple(last_h.shape) == (4, -1, D) or last_h.shape[0] == 4


def test_basic_gru_bidirectional_matches_numpy_two_stacks():
    """Numeric parity vs a numpy oracle implementing the REFERENCE
    topology: two independent 2-layer direction stacks, concat once."""
    from paddle_tpu.fluid.contrib.layers import basic_gru

    D, T, B, W = 4, 6, 3, 5
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data("x", shape=[None, T, W], dtype="float32")
        out, _ = basic_gru(x, None, D, num_layers=2, bidirectional=True,
                           name="bgpar")
        params = {p.name: p for p in main.global_block().all_parameters()}
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    scope = fluid.global_scope()

    def weights(layer, direc):
        tag = "bgpar_l%d_%s" % (layer, direc)
        ps = sorted(n for n in params if n.startswith(tag))
        vals = [np.asarray(scope.find_var(n).get_tensor()) for n in ps]
        gw = next(v for v in vals if v.ndim == 2 and v.shape[1] == 2 * D)
        gb = next(v for v in vals if v.ndim == 1 and v.shape[0] == 2 * D)
        cw = next(v for v in vals if v.ndim == 2 and v.shape[1] == D)
        cb = next(v for v in vals if v.ndim == 1 and v.shape[0] == D)
        return gw, gb, cw, cb

    def sigmoid(v):
        return 1.0 / (1.0 + np.exp(-v))

    def gru_pass(xs_tbw, gw, gb, cw, cb, reverse):
        T_ = xs_tbw.shape[0]
        h = np.zeros((xs_tbw.shape[1], D), "float32")
        outs = [None] * T_
        order = range(T_ - 1, -1, -1) if reverse else range(T_)
        for t in order:
            xt = xs_tbw[t]
            g = sigmoid(np.concatenate([xt, h], 1) @ gw + gb)
            r, u = g[:, :D], g[:, D:]
            c = np.tanh(np.concatenate([xt, r * h], 1) @ cw + cb)
            h = u * h + (1 - u) * c
            outs[t] = h
        return np.stack(outs)

    xs = np.random.default_rng(7).standard_normal((B, T, W)).astype("float32")
    (o,) = exe.run(main, feed={"x": xs}, fetch_list=[out])
    xt = xs.transpose(1, 0, 2)  # (T, B, W)
    fw = gru_pass(gru_pass(xt, *weights(0, "fw"), False),
                  *weights(1, "fw"), False)
    bw = gru_pass(gru_pass(xt, *weights(0, "bw"), True),
                  *weights(1, "bw"), True)
    want = np.concatenate([fw, bw], -1).transpose(1, 0, 2)
    np.testing.assert_allclose(o, want, rtol=2e-4, atol=2e-5)


def test_trainer_checkpoint_retention_keeps_max():
    import os
    from paddle_tpu.fluid.contrib.trainer import CheckpointConfig

    cfg = CheckpointConfig.__new__(CheckpointConfig)
    # emulate the retention arithmetic without a full Trainer
    kept = set()
    cfg.max_num_checkpoints = 3
    for serial in range(6):
        kept.add(serial)
        drop = serial - cfg.max_num_checkpoints
        if drop >= 0:
            kept.discard(drop)
    assert len(kept) == 3, kept


# ---------------------------------------------------------------------------
# fluid.data semantics + small-module import parity
# ---------------------------------------------------------------------------
def test_fluid_data_full_shape_semantics():
    """fluid.data takes the FULL shape (ref data.py) — no implicit batch
    dim, None means any size; layers.data keeps the prepend behavior."""
    main = fluid.Program()
    with fluid.program_guard(main, fluid.Program()):
        a = fluid.data("fd_a", shape=[None, 7], dtype="float32")
        b = fluid.data("fd_b", shape=[3, 2, 1], dtype="float32")
        c = fluid.layers.data("fd_c", shape=[7], dtype="float32")
    assert tuple(a.shape) == (-1, 7)
    assert tuple(b.shape) == (3, 2, 1)
    assert tuple(c.shape) == (-1, 7)  # layers.data prepends batch
    exe = fluid.Executor(fluid.CPUPlace())
    out = exe.run(main, feed={
        "fd_a": np.ones((5, 7), "float32"),
        "fd_b": np.ones((3, 2, 1), "float32"),
        "fd_c": np.ones((5, 7), "float32"),
    }, fetch_list=[a, b, c])
    assert out[0].shape == (5, 7) and out[1].shape == (3, 2, 1)


def test_small_module_parity_surface(tmp_path):
    import io as _io
    import logging
    import sys

    # annotations.deprecated warns and forwards
    from paddle_tpu.fluid.annotations import deprecated

    @deprecated("1.5", "new_fn")
    def old_fn(v):
        return v * 2

    stderr, sys.stderr = sys.stderr, _io.StringIO()
    try:
        assert old_fn(4) == 8
        assert "deprecated" in sys.stderr.getvalue()
    finally:
        sys.stderr = stderr

    # wrapped_decorator keeps signatures through contextmanagers
    from paddle_tpu.fluid.wrapped_decorator import (
        signature_safe_contextmanager,
    )

    @signature_safe_contextmanager
    def ctx(v):
        yield v + 1

    with ctx(1) as got:
        assert got == 2

    # default_scope_funcs stack
    from paddle_tpu.fluid import default_scope_funcs as dsf

    dsf.var("dsf_x").set(np.ones(2), None)
    dsf.enter_local_scope()
    assert dsf.find_var("dsf_x") is not None  # parent chain
    dsf.leave_local_scope()

    # input.one_hot/embedding, fluid-level exports
    main = fluid.Program()
    with fluid.program_guard(main, fluid.Program()):
        ids = fluid.data("ip_ids", shape=[None, 3], dtype="int64")
        emb = fluid.embedding(ids, size=[10, 4])
        oh = fluid.one_hot(fluid.layers.reshape(ids, [-1, 1]), 10)
    assert tuple(emb.shape)[-1] == 4 and tuple(oh.shape)[-1] == 10

    # log_helper
    from paddle_tpu.fluid.log_helper import get_logger

    lg = get_logger("t_lg", logging.INFO, fmt="%(message)s")
    assert lg.level == logging.INFO and not lg.propagate

    # trainer_desc classes
    from paddle_tpu.fluid.trainer_desc import MultiTrainer
    from paddle_tpu.fluid.device_worker import Hogwild

    td = MultiTrainer()
    td._set_thread(4)
    td._set_device_worker(Hogwild())
    assert td._desc()["class_name"] == "MultiTrainer"
    assert td._desc()["thread_num"] == 4

    # fluid-level distribute_lookup_table helpers
    main = fluid.Program()
    with fluid.program_guard(main, fluid.Program()):
        ids2 = fluid.layers.data("dlt_ids", shape=[1], dtype="int64",
                                 lod_level=1)
        fluid.layers.embedding(
            ids2, size=[50, 4], is_distributed=True,
            param_attr=fluid.ParamAttr(name="dlt_emb"))
    from paddle_tpu.fluid import distribute_lookup_table as dlt

    assert dlt.find_distributed_lookup_table(main) == "dlt_emb"
    ins = dlt.find_distributed_lookup_table_inputs(main, "dlt_emb")
    outs = dlt.find_distributed_lookup_table_outputs(main, "dlt_emb")
    assert ins and outs

    # install_check runs end to end
    from paddle_tpu.fluid import install_check

    install_check.run_check()


def test_input_v2_embedding_one_hot_keep_trailing_dim():
    """fluid.embedding/one_hot (v2, ref input.py) append the new dim to
    the id shape AS-IS; layers.* keep the v1 trailing-1 squeeze."""
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        ids = fluid.data("v2_ids", shape=[None, 1], dtype="int64")
        e2 = fluid.embedding(ids, size=[10, 4])
        o2 = fluid.one_hot(ids, 10)
        e1 = fluid.layers.embedding(ids, size=[10, 4])
        o1 = fluid.layers.one_hot(ids, 10)
    assert tuple(e2.shape) == (-1, 1, 4)
    assert tuple(o2.shape) == (-1, 1, 10)
    assert tuple(e1.shape) == (-1, 4)
    assert tuple(o1.shape) == (-1, 10)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    outs = exe.run(main, feed={"v2_ids": np.array([[3], [7]], "int64")},
                   fetch_list=[e2, o2, e1, o1])
    assert outs[0].shape == (2, 1, 4) and outs[1].shape == (2, 1, 10)
    assert outs[2].shape == (2, 4) and outs[3].shape == (2, 10)
    np.testing.assert_allclose(outs[1][:, 0, :], outs[3])


def test_fluid_dygraph_grad_clip_module_resolves():
    """fluid.dygraph_grad_clip must be the real module (a stale alias to
    clip once shadowed it)."""
    assert hasattr(fluid.dygraph_grad_clip, "GradClipByGlobalNorm")
    assert fluid.dygraph_grad_clip.GradClipByGlobalNorm \
        is GradClipByGlobalNorm


def test_clip_module_grad_clip_aliases():
    """ref docstrings import GradClipBy* from fluid.clip — both paths
    must resolve to the same classes."""
    from paddle_tpu.fluid.clip import (
        GradClipByGlobalNorm as A,
        GradClipByNorm as B,
        GradClipByValue as C,
    )

    assert A is GradClipByGlobalNorm
    assert B is GradClipByNorm
    assert C is GradClipByValue
    import pytest as _pytest

    with _pytest.raises(AttributeError):
        from paddle_tpu.fluid import clip as _clip
        _clip.nonexistent_attr
