"""Real multi-process elastic fleet: N worker PROCESSES coordinate
through a FileStore on a shared tmp dir; the parent SIGKILLs one
mid-run (no cooperation from the victim — this is the real crash
shape, unlike the in-thread fault-site kills in test_elastic.py) and
the survivors must detect, shrink, consensus-restore, and finish.

Marked ``multihost`` + ``slow``: each worker pays a full jax import +
trace, so the test runs in the chaos lane, not tier-1.
"""
import json
import os
import signal
import subprocess
import sys
import time

import pytest

pytestmark = [pytest.mark.multihost, pytest.mark.slow, pytest.mark.faults]

_WORKER = r"""
import json, os, sys

import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.parallel import elastic as E

w, world = int(sys.argv[1]), int(sys.argv[2])
root, steps = sys.argv[3], int(sys.argv[4])

fluid.default_startup_program().random_seed = 7
fluid.default_main_program().random_seed = 7
x = fluid.data("mx", shape=[None, 4], dtype="float32")
y = fluid.data("my", shape=[None, 1], dtype="float32")
p = fluid.layers.fc(x, 1)
loss = fluid.layers.reduce_mean(fluid.layers.square_error_cost(p, y))
fluid.optimizer.SGD(0.05).minimize(loss)
exe = fluid.Executor()
exe.run(fluid.default_startup_program())


def feed(step, guard=None):
    rng = np.random.default_rng(1000 + step)
    xv = rng.standard_normal((8, 4)).astype("float32")
    return {"mx": xv,
            "my": (xv.sum(1, keepdims=True) * 0.5).astype("float32")}


cfg = E.ElasticConfig(heartbeat_interval=0.1, miss_threshold=20,
                      collective_timeout=90.0, startup_grace=120.0)
guard = E.FleetGuard(
    exe, store=E.FileStore(os.path.join(root, "store")),
    worker_index=w, world_size=world, config=cfg,
    ckpt_dir=os.path.join(root, "ck"), fetch_list=[loss],
    feed_fn=feed, save_every=3, sync_every=1)
summary = guard.train(num_steps=steps)
summary["max_blocked_ok"] = summary["max_blocked"] <= 91.0
summary.pop("events")
print("SUMMARY " + json.dumps(summary), flush=True)
"""


def _read_beacon(root, worker):
    path = os.path.join(root, "store", "heartbeat", "%d.json" % worker)
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def test_process_fleet_survives_sigkill(tmp_path):
    root = str(tmp_path)
    world, steps, victim = 3, 10, 1
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PADDLE_TPU_FAULT_SPEC", None)
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _WORKER, str(w), str(world), root,
             str(steps)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env, cwd=os.path.dirname(os.path.dirname(__file__)))
        for w in range(world)
    ]
    try:
        # wait until the victim has trained past the first consensus
        # save (save_every=3), then kill it dead — no atexit, no leave()
        deadline = time.time() + 180
        while time.time() < deadline:
            rec = _read_beacon(root, victim)
            if rec and rec.get("step", 0) >= 5:
                break
            if procs[victim].poll() is not None:
                pytest.fail("victim exited before it could be killed:\n%s"
                            % procs[victim].communicate()[0])
            time.sleep(0.2)
        else:
            pytest.fail("victim never reached step 5")
        procs[victim].send_signal(signal.SIGKILL)

        outs = {}
        for w, p in enumerate(procs):
            out, _ = p.communicate(timeout=240)
            outs[w] = out
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()

    assert procs[victim].returncode == -signal.SIGKILL
    survivors = [w for w in range(world) if w != victim]
    for w in survivors:
        assert procs[w].returncode == 0, (
            "worker %d failed:\n%s" % (w, outs[w]))
        line = [ln for ln in outs[w].splitlines()
                if ln.startswith("SUMMARY ")]
        assert line, "worker %d printed no summary:\n%s" % (w, outs[w])
        summary = json.loads(line[-1][len("SUMMARY "):])
        assert summary["final_step"] == steps
        assert summary["members"] == survivors
        assert summary["generation"] >= 1
        assert summary["counters"].get("worker_dead", 0) >= 1
        assert summary["counters"].get("shrink", 0) >= 1
        assert summary["counters"].get("restore", 0) >= 1
        assert summary["max_blocked_ok"], summary["max_blocked"]
