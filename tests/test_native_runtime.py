"""Native C++ host runtime: g++-built ring queue + pinned arena (ref parity:
operators/reader/blocking_queue.h tests + memory allocator tests). Skips
only if no g++ toolchain is present (never expected in CI)."""
import ctypes
import threading
import time

import numpy as np
import pytest

from paddle_tpu.native import build, pipeline


def _lib_or_skip():
    lib = build.load_native()
    if lib is None:
        pytest.skip("native toolchain unavailable")
    return lib


def test_native_lib_builds():
    assert _lib_or_skip() is not None


def test_token_queue_fifo_and_blocking():
    lib = _lib_or_skip()
    q = pipeline._NativeQueue(capacity=2, lib=lib)
    q.put("a")
    q.put("b")

    got = []
    blocked = threading.Event()

    def producer():
        blocked.set()
        q.put("c")             # must block until a get frees a slot

    t = threading.Thread(target=producer, daemon=True)
    t.start()
    blocked.wait(2.0)
    time.sleep(0.1)
    assert t.is_alive()        # capacity 2 full -> producer blocked
    got.append(q.get())
    t.join(2.0)
    assert not t.is_alive()
    got += [q.get(), q.get()]
    assert got == ["a", "b", "c"]


def test_arena_alignment_and_reset():
    lib = _lib_or_skip()
    a = lib.arena_create(1 << 16)
    p1 = lib.arena_alloc(a, 100)
    p2 = lib.arena_alloc(a, 100)
    assert p1 % 64 == 0 and p2 % 64 == 0
    assert p2 - p1 == 128                   # 100 rounded up to 64-multiple
    # exhaustion returns NULL, reset recycles
    assert lib.arena_alloc(a, 1 << 17) in (None, 0)
    lib.arena_reset(a)
    assert lib.arena_alloc(a, 100) == p1
    lib.arena_destroy(a)


def test_dataloader_uses_native_pipe_and_trains():
    q = pipeline.make_queue(capacity=4)
    # when the toolchain exists, make_queue must pick the native path
    if build.load_native() is not None:
        assert isinstance(q, pipeline._NativeQueue)

    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import framework, layers, unique_name

    framework.switch_main_program(framework.Program())
    framework.switch_startup_program(framework.Program())
    unique_name.switch()
    fluid.default_startup_program().random_seed = 4

    x = fluid.data(name="dl_x", shape=[None, 4], dtype="float32")
    y = fluid.data(name="dl_y", shape=[None, 1], dtype="float32")
    loss = layers.mean(
        layers.square_error_cost(layers.fc(x, 1), y)
    )
    fluid.optimizer.SGD(0.05).minimize(loss)

    rng = np.random.default_rng(0)

    def reader():
        for _ in range(10):
            xv = rng.normal(size=(4,)).astype(np.float32)
            yield xv, np.array([xv.sum()], np.float32)

    loader = fluid.DataLoader.from_generator(feed_list=[x, y], capacity=4)
    loader.set_sample_generator(reader, batch_size=2)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    losses = []
    for feed in loader():
        losses.append(float(exe.run(feed=feed, fetch_list=[loss])[0]))
    assert len(losses) == 5
    assert np.isfinite(losses).all()


def test_evaluator_shim_legacy_flow():
    """Deprecated fluid.evaluator.Accuracy: the fetch->update->eval loop
    works, and eval() without updates raises a migration error."""
    import warnings

    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import framework, layers, unique_name

    framework.switch_main_program(framework.Program())
    framework.switch_startup_program(framework.Program())
    unique_name.switch()
    fluid.default_startup_program().random_seed = 4

    x = fluid.data(name="ev_x", shape=[None, 4], dtype="float32")
    y = fluid.data(name="ev_y", shape=[None, 1], dtype="int64")
    pred = layers.fc(x, 3, act="softmax")
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", DeprecationWarning)
        ev = fluid.evaluator.Accuracy(input=pred, label=y)

    with pytest.raises(RuntimeError, match="migrate"):
        ev.eval()

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    xv = np.random.default_rng(0).normal(size=(8, 4)).astype(np.float32)
    yv = np.zeros((8, 1), np.int64)
    acc = exe.run(feed={"ev_x": xv, "ev_y": yv},
                  fetch_list=[ev.metrics[0]])[0]
    ev.update(value=float(acc), weight=8)
    assert 0.0 <= ev.eval() <= 1.0


def test_native_batch_pipe_zero_copy_round_trip():
    """Batch bytes stage through the C++ arena and come back bit-exact as
    zero-copy views (VERDICT #5: the data actually crosses into C++)."""
    from paddle_tpu.native.pipeline import NativeBatchPipe

    pipe = NativeBatchPipe(capacity=2, slot_bytes=1 << 20, n_workers=2)
    try:
        rng = np.random.default_rng(3)
        batch = {
            "x": rng.normal(size=(64, 32)).astype(np.float32),
            "y": rng.integers(0, 9, size=(64, 1)).astype(np.int64),
        }
        pipe.put(batch)
        out, release = pipe.get()
        np.testing.assert_array_equal(out["x"], batch["x"])
        np.testing.assert_array_equal(out["y"], batch["y"])
        # the view is NOT a copy of the producer array: it lives in the
        # arena slab (different buffer than the input)
        assert out["x"].__array_interface__["data"][0] != \
            batch["x"].__array_interface__["data"][0]
        release()
        # sentinel passes through
        pipe.put(None)
        item, rel = pipe.get()
        assert item is None
        rel()
    finally:
        pipe.close()


def test_native_batch_pipe_overlap():
    """Producer prep overlaps consumer steps (VERDICT #5 'done' bar:
    wall < sum of produce + consume)."""
    import threading
    import time

    from paddle_tpu.native.pipeline import NativeBatchPipe

    n_batches = 8
    prep_s = 0.02
    step_s = 0.02
    pipe = NativeBatchPipe(capacity=4, slot_bytes=1 << 20, n_workers=2)
    try:
        data = np.ones((256, 64), np.float32)

        def produce():
            for _ in range(n_batches):
                time.sleep(prep_s)          # host IO / augmentation
                pipe.put({"x": data})
            pipe.put(None)

        t0 = time.time()
        threading.Thread(target=produce, daemon=True).start()
        seen = 0
        release_prev = None
        while True:
            item, release = pipe.get()
            if release_prev is not None:
                release_prev()
            release_prev = release
            if item is None:
                break
            time.sleep(step_s)              # device step
            seen += 1
        release_prev()
        wall = time.time() - t0
        assert seen == n_batches
        serial = n_batches * (prep_s + step_s)
        # overlapped pipeline must beat the serial sum with clear margin
        assert wall < serial * 0.85, (wall, serial)
    finally:
        pipe.close()


def test_dataloader_uses_native_pipe_and_overlaps():
    """DataLoader end-to-end through the C++ staging path."""
    import time

    import paddle_tpu.fluid as fluid

    loader = fluid.reader.DataLoader.from_generator(feed_list=[],
                                                    capacity=4)
    # sleeps sized to dominate scheduler noise on a loaded 1-core box
    n, prep_s, step_s = 10, 0.05, 0.05
    prep_times = []

    def gen():
        for i in range(n):
            t = time.time()
            time.sleep(prep_s)
            prep_times.append(time.time() - t)
            yield {"x": np.full((128, 16), float(i), np.float32)}

    loader.set_batch_generator(gen)
    it = iter(loader())
    # first batch pays one-time costs (arena alloc + mlock, thread spinup)
    # that say nothing about steady-state overlap — exclude from timing
    vals = [float(next(it)["x"][0, 0])]
    t0 = time.time()
    step_total = 0.0
    for batch in it:
        t = time.time()
        time.sleep(step_s)
        step_total += time.time() - t
        vals.append(float(batch["x"][0, 0]))
    wall = time.time() - t0
    assert vals == [float(i) for i in range(n)]
    # overlap: steady-state wall must beat the MEASURED serial sum
    # (sleeps stretch under load; both sides stretch together)
    serial = sum(prep_times[1:]) + step_total
    assert wall < serial * 0.9, (wall, serial)


def test_dataloader_early_exit_and_restart():
    """Breaking out of an epoch must not corrupt the next one (C++ abort
    handshake + pipe reset)."""
    import paddle_tpu.fluid as fluid

    loader = fluid.reader.DataLoader.from_generator(feed_list=[],
                                                    capacity=2)

    def gen():
        for i in range(50):
            yield {"x": np.full((4,), float(i), np.float32)}

    loader.set_batch_generator(gen)
    for batch in loader():
        assert float(batch["x"][0]) == 0.0
        break                     # early exit mid-epoch
    vals = [float(b["x"][0]) for b in loader()]
    assert vals == [float(i) for i in range(50)]


def test_dataloader_producer_error_is_loud():
    """A generator exception surfaces in the training loop, not as a
    silent short epoch."""
    import paddle_tpu.fluid as fluid

    loader = fluid.reader.DataLoader.from_generator(feed_list=[],
                                                    capacity=2)

    def gen():
        yield {"x": np.zeros((4,), np.float32)}
        raise IOError("disk gone")

    loader.set_batch_generator(gen)
    with pytest.raises(RuntimeError, match="disk gone"):
        for _ in loader():
            pass


def test_dataloader_batches_safe_to_retain():
    """Yielded batches are copies — retaining all of them across the epoch
    must not alias recycled ring slots."""
    import paddle_tpu.fluid as fluid

    loader = fluid.reader.DataLoader.from_generator(feed_list=[],
                                                    capacity=2)
    n = 12

    def gen():
        for i in range(n):
            yield {"x": np.full((1024,), float(i), np.float32)}

    loader.set_batch_generator(gen)
    kept = [b["x"] for b in loader()]
    assert [float(a[0]) for a in kept] == [float(i) for i in range(n)]
    assert all(float(a[0]) == float(a[-1]) for a in kept)


def test_native_tsan_build_and_race_free_pipe():
    """Race-detection build (aux subsystem): compile the runtime with
    -fsanitize=thread and hammer the batch pipe from a producer thread in
    a TSan-instrumented subprocess; any data race report fails."""
    import subprocess
    import sys
    import textwrap

    from paddle_tpu.native import build

    try:
        so = build.build_tsan()
    except Exception:
        pytest.skip("tsan toolchain unavailable")
    prog = textwrap.dedent("""
        import ctypes, threading
        lib = ctypes.CDLL(%r)
        lib.pipe_create.restype = ctypes.c_void_p
        lib.pipe_create.argtypes = [ctypes.c_int, ctypes.c_size_t,
                                    ctypes.c_int]
        lib.pipe_acquire_write.restype = ctypes.c_int
        lib.pipe_acquire_write.argtypes = [ctypes.c_void_p]
        lib.pipe_submit_write.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_size_t,
            ctypes.c_void_p, ctypes.c_size_t]
        lib.pipe_wait_writes.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.pipe_commit.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.pipe_acquire_read.restype = ctypes.c_int
        lib.pipe_acquire_read.argtypes = [ctypes.c_void_p]
        lib.pipe_release.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.pipe_destroy.argtypes = [ctypes.c_void_p]
        p = lib.pipe_create(3, 1 << 16, 2)
        src = (ctypes.c_char * 4096)()
        N = 50
        def produce():
            for _ in range(N):
                s = lib.pipe_acquire_write(p)
                lib.pipe_submit_write(p, s, 0, src, 4096)
                lib.pipe_wait_writes(p, s)
                lib.pipe_commit(p, s)
        t = threading.Thread(target=produce)
        t.start()
        for _ in range(N):
            s = lib.pipe_acquire_read(p)
            lib.pipe_release(p, s)
        t.join()
        lib.pipe_destroy(p)
        print("PIPE-TSAN-OK")
    """ % so)
    import glob

    tsan_rt = sorted(glob.glob("/lib/x86_64-linux-gnu/libtsan.so*")) or \
        sorted(glob.glob("/usr/lib/*/libtsan.so*"))
    if not tsan_rt:
        pytest.skip("libtsan runtime not found")
    r = subprocess.run(
        [sys.executable, "-c", prog],
        capture_output=True, text=True, timeout=120,
        env={"PATH": "/usr/bin:/bin", "TSAN_OPTIONS": "exitcode=66",
             # dlopen of a tsan .so into an uninstrumented python needs
             # the runtime preloaded (static TLS)
             "LD_PRELOAD": tsan_rt[0]},
    )
    assert "PIPE-TSAN-OK" in r.stdout, (r.stdout, r.stderr[-800:])
    assert "WARNING: ThreadSanitizer" not in r.stderr, r.stderr[-1500:]
    assert r.returncode == 0, (r.returncode, r.stderr[-800:])


def test_device_ahead_prefetch_stage(monkeypatch):
    """_device_ahead issues the NEXT batch's device_put before yielding
    the current one (double_buffer's device half) and engages only for
    a single accelerator place."""
    import jax

    from paddle_tpu.fluid.reader import _GeneratorLoader

    class _FakeDev:
        platform = "tpu"

    class _FakePlace:
        def jax_device(self):
            return _FakeDev()

    loader = _GeneratorLoader(feed_list=[], capacity=2)
    loader._places = _FakePlace()

    puts = []

    class _Tagged:
        def __init__(self, arr):
            self.arr = arr

    def fake_put(v, dev):
        puts.append(v.sum())
        return _Tagged(v)

    monkeypatch.setattr(jax, "device_put", fake_put)

    batches = [{"x": np.full((2,), i)} for i in range(4)]
    events = []

    def host_iter():
        for i, b in enumerate(batches):
            events.append(("host", i))
            yield b

    out = []
    for item in loader._device_ahead(host_iter()):
        events.append(("yield", int(item["x"].arr[0])))
        out.append(item)
    # every batch arrives exactly once, in order, device-tagged
    assert [int(i["x"].arr[0]) for i in out] == [0, 1, 2, 3]
    assert len(puts) == 4
    # pipelining: batch 1's transfer was issued BEFORE batch 0 yielded
    assert events.index(("host", 1)) < events.index(("yield", 0))

    # reader error mid-epoch: the already-staged batch still arrives
    def failing_iter():
        yield batches[0]
        raise RuntimeError("reader died")

    seen = []
    with pytest.raises(RuntimeError, match="reader died"):
        for item in loader._device_ahead(failing_iter()):
            seen.append(item)
    assert len(seen) == 1 and int(seen[0]["x"].arr[0]) == 0

    # CPU place / placeless / multi-place: transparent numpy pass-through
    monkeypatch.undo()
    import paddle_tpu.fluid as fluid

    for places in (None, fluid.CPUPlace(),
                   [_FakePlace(), _FakePlace()]):
        loader._places = places
        got = list(loader._device_ahead(iter(batches)))
        assert all(isinstance(b["x"], np.ndarray) for b in got)
