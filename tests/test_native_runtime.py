"""Native C++ host runtime: g++-built ring queue + pinned arena (ref parity:
operators/reader/blocking_queue.h tests + memory allocator tests). Skips
only if no g++ toolchain is present (never expected in CI)."""
import ctypes
import threading
import time

import numpy as np
import pytest

from paddle_tpu.native import build, pipeline


def _lib_or_skip():
    lib = build.load_native()
    if lib is None:
        pytest.skip("native toolchain unavailable")
    return lib


def test_native_lib_builds():
    assert _lib_or_skip() is not None


def test_token_queue_fifo_and_blocking():
    lib = _lib_or_skip()
    q = pipeline._NativeQueue(capacity=2, lib=lib)
    q.put("a")
    q.put("b")

    got = []
    blocked = threading.Event()

    def producer():
        blocked.set()
        q.put("c")             # must block until a get frees a slot

    t = threading.Thread(target=producer, daemon=True)
    t.start()
    blocked.wait(2.0)
    time.sleep(0.1)
    assert t.is_alive()        # capacity 2 full -> producer blocked
    got.append(q.get())
    t.join(2.0)
    assert not t.is_alive()
    got += [q.get(), q.get()]
    assert got == ["a", "b", "c"]


def test_arena_alignment_and_reset():
    lib = _lib_or_skip()
    a = lib.arena_create(1 << 16)
    p1 = lib.arena_alloc(a, 100)
    p2 = lib.arena_alloc(a, 100)
    assert p1 % 64 == 0 and p2 % 64 == 0
    assert p2 - p1 == 128                   # 100 rounded up to 64-multiple
    # exhaustion returns NULL, reset recycles
    assert lib.arena_alloc(a, 1 << 17) in (None, 0)
    lib.arena_reset(a)
    assert lib.arena_alloc(a, 100) == p1
    lib.arena_destroy(a)


def test_dataloader_uses_native_pipe_and_trains():
    q = pipeline.make_queue(capacity=4)
    # when the toolchain exists, make_queue must pick the native path
    if build.load_native() is not None:
        assert isinstance(q, pipeline._NativeQueue)

    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import framework, layers, unique_name

    framework.switch_main_program(framework.Program())
    framework.switch_startup_program(framework.Program())
    unique_name.switch()
    fluid.default_startup_program().random_seed = 4

    x = fluid.data(name="dl_x", shape=[4], dtype="float32")
    y = fluid.data(name="dl_y", shape=[1], dtype="float32")
    loss = layers.mean(
        layers.square_error_cost(layers.fc(x, 1), y)
    )
    fluid.optimizer.SGD(0.05).minimize(loss)

    rng = np.random.default_rng(0)

    def reader():
        for _ in range(10):
            xv = rng.normal(size=(4,)).astype(np.float32)
            yield xv, np.array([xv.sum()], np.float32)

    loader = fluid.DataLoader.from_generator(feed_list=[x, y], capacity=4)
    loader.set_sample_generator(reader, batch_size=2)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    losses = []
    for feed in loader():
        losses.append(float(exe.run(feed=feed, fetch_list=[loss])[0]))
    assert len(losses) == 5
    assert np.isfinite(losses).all()


def test_evaluator_shim_legacy_flow():
    """Deprecated fluid.evaluator.Accuracy: the fetch->update->eval loop
    works, and eval() without updates raises a migration error."""
    import warnings

    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import framework, layers, unique_name

    framework.switch_main_program(framework.Program())
    framework.switch_startup_program(framework.Program())
    unique_name.switch()
    fluid.default_startup_program().random_seed = 4

    x = fluid.data(name="ev_x", shape=[4], dtype="float32")
    y = fluid.data(name="ev_y", shape=[1], dtype="int64")
    pred = layers.fc(x, 3, act="softmax")
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", DeprecationWarning)
        ev = fluid.evaluator.Accuracy(input=pred, label=y)

    with pytest.raises(RuntimeError, match="migrate"):
        ev.eval()

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    xv = np.random.default_rng(0).normal(size=(8, 4)).astype(np.float32)
    yv = np.zeros((8, 1), np.int64)
    acc = exe.run(feed={"ev_x": xv, "ev_y": yv},
                  fetch_list=[ev.metrics[0]])[0]
    ev.update(value=float(acc), weight=8)
    assert 0.0 <= ev.eval() <= 1.0
