"""Composed parallelism in ONE program (round-5, VERDICT next-step #5).

Two composition paths, by design (see fluid/pipeline_executor.py notes):

* fluid PipelineOptimizer(mesh=, feed_specs=) — heterogeneous cut_list
  stages composed with dp batch sharding. The stage bodies diverge per
  pp index (lax.switch), so auto-axis collectives must stay within one
  pp coordinate: dp batch groups do, tp weight reshards do not — tp
  param_rules are rejected LOUDLY.
* parallel.pipeline.gpipe_composed — stacked homogeneous stages, manual
  over 'pp' only; the single stage body is executed by every device so
  tp psums are structurally uniform: true dp x tp x pp.

Exactness bars: the composed fluid run reproduces the SEQUENTIAL
single-device losses; gpipe_composed reproduces sequential stage
application (mean-of-microbatch-means == full-batch mean for equal
microbatches; dp/tp sharding is a layout, not an algorithm change).
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import framework, unique_name


def _losses(mode, steps=4):
    from paddle_tpu.fluid import executor as exmod
    from paddle_tpu.parallel.mesh import build_mesh

    framework.switch_main_program(framework.Program())
    framework.switch_startup_program(framework.Program())
    unique_name.switch()
    exmod._scope_stack[:] = [exmod.Scope()]
    fluid.default_main_program().random_seed = 5
    fluid.default_startup_program().random_seed = 5
    x = fluid.layers.data(name="cpx", shape=[16], dtype="float32")
    y = fluid.layers.data(name="cpy", shape=[1], dtype="float32")
    h1 = fluid.layers.fc(x, size=32, act="relu", name="cp1")
    h2 = fluid.layers.fc(h1, size=32, act="relu", name="cp2")
    pred = fluid.layers.fc(h2, size=1, name="cp3")
    loss = fluid.layers.reduce_mean(fluid.layers.square(pred - y))
    opt = fluid.optimizer.SGD(0.05)
    if mode == "composed":
        mesh = build_mesh({"dp": 4, "pp": 2})
        opt = fluid.optimizer.PipelineOptimizer(
            opt, cut_list=[h1], num_microbatches=4, mesh=mesh,
            feed_specs={"cpx": P("dp", None), "cpy": P("dp", None)})
    opt.minimize(loss)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    rs = np.random.RandomState(3)
    feed = {"cpx": rs.randn(8, 16).astype("float32"),
            "cpy": rs.randn(8, 1).astype("float32")}
    return [float(exe.run(feed=feed, fetch_list=[loss])[0])
            for _ in range(steps)]


def test_fluid_composed_dp_pp_matches_sequential():
    seq = _losses("seq")
    comp = _losses("composed")
    assert np.allclose(seq, comp, rtol=1e-4, atol=1e-5), (seq, comp)
    assert comp[-1] < comp[0]


def test_fluid_composed_rejects_tp_param_rules():
    from paddle_tpu.fluid.lowering import OpLoweringError
    from paddle_tpu.parallel.mesh import build_mesh
    from paddle_tpu.parallel.sharding import ShardingRule

    x = fluid.layers.data(name="rjx", shape=[8], dtype="float32")
    h1 = fluid.layers.fc(x, size=8, act="relu", name="rj1")
    pred = fluid.layers.fc(h1, size=1)
    loss = fluid.layers.reduce_mean(fluid.layers.square(pred))
    mesh = build_mesh({"dp": 2, "tp": 2, "pp": 2})
    opt = fluid.optimizer.PipelineOptimizer(
        fluid.optimizer.SGD(0.1), cut_list=[h1], num_microbatches=2,
        mesh=mesh, param_rules=[ShardingRule(r"rj1\.w_0", P(None, "tp"))])
    opt.minimize(loss)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    with pytest.raises(OpLoweringError, match="gpipe_composed"):
        exe.run(feed={"rjx": np.zeros((4, 8), "float32")},
                fetch_list=[loss])


def test_fluid_composed_mesh_needs_pp_axis():
    from paddle_tpu.fluid.lowering import OpLoweringError
    from paddle_tpu.parallel.mesh import build_mesh

    x = fluid.layers.data(name="vx", shape=[4], dtype="float32")
    h1 = fluid.layers.fc(x, size=4, act="relu")
    pred = fluid.layers.fc(h1, size=1)
    loss = fluid.layers.reduce_mean(fluid.layers.square(pred))
    mesh = build_mesh({"dp": 2, "mp": 4})    # no 'pp' axis
    opt = fluid.optimizer.PipelineOptimizer(
        fluid.optimizer.SGD(0.1), cut_list=[h1], num_microbatches=2,
        mesh=mesh)
    opt.minimize(loss)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    with pytest.raises(OpLoweringError, match="'pp' axis"):
        exe.run(feed={"vx": np.zeros((4, 4), "float32")},
                fetch_list=[loss])


# ---------------------------------------------------------------------------
# stacked-stage composed pipeline: true dp x tp x pp
# ---------------------------------------------------------------------------
def _stage(p, h):
    return jnp.tanh(h @ p["w"] + p["b"])


def _setup(mesh):
    rng = np.random.default_rng(0)
    D = 16
    w = (rng.standard_normal((2, D, D)) * 0.3).astype(np.float32)
    b = (rng.standard_normal((2, D)) * 0.1).astype(np.float32)
    x = rng.standard_normal((8, D)).astype(np.float32)
    params = {
        "w": jax.device_put(w, NamedSharding(mesh, P("pp", None, "tp"))),
        "b": jax.device_put(b, NamedSharding(mesh, P("pp", "tp"))),
    }
    xs = jax.device_put(x, NamedSharding(mesh, P("dp", None)))
    return params, xs, w, b, x


def test_gpipe_composed_exact_vs_sequential():
    from paddle_tpu.parallel.mesh import build_mesh
    from paddle_tpu.parallel.pipeline import gpipe_composed

    mesh = build_mesh({"dp": 2, "tp": 2, "pp": 2})
    params, xs, w, b, x = _setup(mesh)
    out = np.asarray(gpipe_composed(_stage, params, xs, mesh,
                                    n_microbatches=4))
    ref = x
    for s in range(2):
        ref = np.tanh(ref @ w[s] + b[s])
    np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-6)


def test_gpipe_composed_trains_and_keeps_shardings():
    from paddle_tpu.parallel.mesh import build_mesh
    from paddle_tpu.parallel.pipeline import gpipe_composed

    mesh = build_mesh({"dp": 2, "tp": 2, "pp": 2})
    params, xs, w, b, x = _setup(mesh)
    tgt = jax.device_put(
        np.tanh(x).astype(np.float32) * 0.5,
        NamedSharding(mesh, P("dp", None)))

    def loss_fn(ps, xb, tb):
        y = gpipe_composed(_stage, ps, xb, mesh, n_microbatches=4)
        return jnp.mean((y - tb) ** 2)

    @jax.jit
    def train_step(ps, xb, tb):
        l, g = jax.value_and_grad(loss_fn)(ps, xb, tb)
        return l, jax.tree_util.tree_map(
            lambda p, gg: p - 0.2 * gg, ps, g)

    losses = []
    ps = params
    for _ in range(4):
        l, ps = train_step(ps, xs, tgt)
        losses.append(float(l))
    assert all(np.isfinite(l) for l in losses), losses
    assert losses[-1] < losses[0], losses
    # the updated weights keep the composed 3-axis sharding
    assert tuple(ps["w"].sharding.spec) == ("pp", None, "tp")


def test_fluid_composed_zero1_opt_state_sharding():
    """ZeRO-1 composed with dp x pp: Adam moments shard over 'dp' (the
    fleet sharding_degree x pipeline composition). Optimizer state is
    only read by POST-pipeline ops, outside the divergent branches, so
    this is safe where param_rules are not. Exactness: bit-identical
    losses vs the sequential run (sharding is a layout)."""
    from paddle_tpu.fluid import executor as exmod
    from paddle_tpu.parallel.mesh import build_mesh
    from paddle_tpu.parallel.sharding import ShardingRule

    def run(mode, steps=4):
        framework.switch_main_program(framework.Program())
        framework.switch_startup_program(framework.Program())
        unique_name.switch()
        exmod._scope_stack[:] = [exmod.Scope()]
        fluid.default_main_program().random_seed = 5
        fluid.default_startup_program().random_seed = 5
        x = fluid.layers.data(name="zx", shape=[16], dtype="float32")
        y = fluid.layers.data(name="zy", shape=[1], dtype="float32")
        h1 = fluid.layers.fc(x, size=32, act="relu", name="zp1")
        pred = fluid.layers.fc(h1, size=1, name="zp2")
        loss = fluid.layers.reduce_mean(fluid.layers.square(pred - y))
        opt = fluid.optimizer.Adam(0.01)
        if mode == "zero_pp":
            mesh = build_mesh({"dp": 4, "pp": 2})
            opt = fluid.optimizer.PipelineOptimizer(
                opt, cut_list=[h1], num_microbatches=4, mesh=mesh,
                feed_specs={"zx": P("dp", None), "zy": P("dp", None)},
                opt_state_rules=[ShardingRule(r"moment", P("dp"))])
        opt.minimize(loss)
        exe = fluid.Executor()
        exe.run(fluid.default_startup_program())
        rs = np.random.RandomState(3)
        feed = {"zx": rs.randn(8, 16).astype("float32"),
                "zy": rs.randn(8, 1).astype("float32")}
        return [float(exe.run(feed=feed, fetch_list=[loss])[0])
                for _ in range(steps)]

    seq = run("seq")
    zp = run("zero_pp")
    assert np.allclose(seq, zp, rtol=1e-4, atol=1e-5), (seq, zp)
    m = fluid.global_scope().find_value("zp1.w_0_moment1_0")
    assert "dp" in tuple(m.sharding.spec), m.sharding


def test_fluid_composed_opt_rules_ignore_non_optimizer_vars():
    """opt_state_rules apply ONLY to belong_to_optimizer state (like
    DistributedProgram): a pattern grazing a parameter name is ignored
    — the weight stays replicated and the run proceeds — rather than
    sharding a var the divergent stage branches read."""
    from paddle_tpu.parallel.mesh import build_mesh
    from paddle_tpu.parallel.sharding import ShardingRule

    x = fluid.layers.data(name="rx", shape=[8], dtype="float32")
    h1 = fluid.layers.fc(x, size=8, act="relu", name="rr1")
    pred = fluid.layers.fc(h1, size=1)
    loss = fluid.layers.reduce_mean(fluid.layers.square(pred))
    mesh = build_mesh({"dp": 4, "pp": 2})
    fluid.optimizer.PipelineOptimizer(
        fluid.optimizer.Adam(0.01), cut_list=[h1], num_microbatches=2,
        mesh=mesh,
        # matches the weight AND its moments; only the moments shard
        opt_state_rules=[ShardingRule(r"rr1\.w_0", P("dp"))],
    ).minimize(loss)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    feed = {"rx": np.ones((4, 8), "float32")}
    l0 = float(exe.run(feed=feed, fetch_list=[loss])[0])
    l1 = float(exe.run(feed=feed, fetch_list=[loss])[0])
    assert np.isfinite(l0) and np.isfinite(l1) and l1 < l0
    # the moment sharded; the weight rule itself was ignored (no
    # divergent-branch deadlock — the weight ENTERS replicated; GSPMD
    # may still dp-shard the post-pipeline UPDATE output, which the
    # next entry re-replicates: that is ZeRO-1's param re-gather)
    m = fluid.global_scope().find_value("rr1.w_0_moment1_0")
    assert "dp" in tuple(m.sharding.spec), m.sharding
