"""transpiler.collective (ref fluid/transpiler/collective.py): after
transpile, plain exe.run(main_program) executes the mesh-sharded step —
GradAllReduce as GSPMD dp, LocalSGD as the per-shard shard_map program."""
import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid.transpiler import collective


def _model(seed=4):
    fluid.default_startup_program().random_seed = seed
    fluid.default_main_program().random_seed = seed
    x = fluid.data("ct_x", [None, 6], "float32")
    y = fluid.data("ct_y", [None, 1], "float32")
    p = fluid.layers.fc(fluid.layers.fc(x, 8, act="relu"), 1)
    loss = fluid.layers.reduce_mean(fluid.layers.square_error_cost(p, y))
    fluid.optimizer.SGD(0.1).minimize(loss)
    return loss


def _eps(n):
    return ["127.0.0.1:%d" % (6170 + i) for i in range(n)]


def _train(loss, steps=5):
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    rng = np.random.default_rng(0)
    xv = rng.standard_normal((16, 6)).astype("float32")
    feed = {"ct_x": xv, "ct_y": xv.sum(1, keepdims=True)}
    return [float(np.asarray(exe.run(feed=feed,
                                     fetch_list=[loss])[0]))
            for _ in range(steps)]


def test_grad_allreduce_transpile_trains_sharded():
    loss = _model()
    t = collective.GradAllReduce()
    main = fluid.default_main_program()
    t.transpile(fluid.default_startup_program(), main, 0, _eps(8),
                _eps(8)[0])
    assert main._transpiled_dist is not None
    assert t.nranks == 8
    losses = _train(loss)
    assert losses[-1] < losses[0], losses


def test_local_sgd_transpile_trains():
    loss = _model()
    t = collective.LocalSGD(k_steps=2)
    main = fluid.default_main_program()
    t.transpile(fluid.default_startup_program(), main, 0, _eps(8),
                _eps(8)[0])
    from paddle_tpu.parallel.local_sgd import LocalSGDProgram

    assert isinstance(main._transpiled_dist, LocalSGDProgram)
    losses = _train(loss)
    assert losses[-1] < losses[0], losses


def test_single_process_multi_thread_defaults():
    loss = _model()
    t = collective.SingleProcessMultiThread()
    t.transpile(main_program=fluid.default_main_program(),
                startup_program=fluid.default_startup_program())
    assert t.nranks == 8  # all visible devices
    losses = _train(loss, steps=3)
    assert np.isfinite(losses).all()


def test_transpile_validates_world():
    loss = _model()
    t = collective.GradAllReduce()
    with pytest.raises(ValueError, match="rank"):
        t.transpile(None, fluid.default_main_program(), 9, _eps(8),
                    _eps(8)[0])
    with pytest.raises(ValueError, match="device count"):
        t.transpile(None, fluid.default_main_program(), 0, _eps(99),
                    _eps(99)[0])
    assert loss is not None
