"""AOT inference engine (ref parity: paddle/fluid/inference api tests —
save_inference_model -> create predictor -> run matches training-time
forward; engine cache per feed-shape signature)."""
import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import framework, layers, unique_name
from paddle_tpu.fluid.inference import Predictor, create_paddle_predictor


@pytest.fixture(autouse=True)
def fresh_programs():
    framework.switch_main_program(framework.Program())
    framework.switch_startup_program(framework.Program())
    unique_name.switch()
    fluid.default_startup_program().random_seed = 5
    fluid.default_main_program().random_seed = 5
    yield


def _build_and_save(tmpdir):
    x = fluid.data(name="x", shape=[None, 6], dtype="float32")
    h = layers.fc(x, size=12, act="relu")
    out = layers.fc(h, size=3, act="softmax")
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    fluid.io.save_inference_model(
        str(tmpdir), ["x"], [out], exe, main_program=fluid.default_main_program()
    )
    xv = np.random.default_rng(0).normal(size=(5, 6)).astype(np.float32)
    ref = exe.run(feed={"x": xv}, fetch_list=[out])[0]
    return xv, ref


def test_predictor_matches_executor(tmp_path):
    xv, ref = _build_and_save(tmp_path)
    pred = Predictor.from_model(str(tmp_path))
    out, = pred.run({"x": xv})
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)
    # list-style feed + __call__
    out2, = pred([xv])
    np.testing.assert_allclose(out2, ref, rtol=1e-5, atol=1e-6)


def test_engine_cache_per_shape(tmp_path):
    xv, _ = _build_and_save(tmp_path)
    pred = create_paddle_predictor(str(tmp_path))
    pred.run({"x": xv})
    pred.run({"x": xv})                       # same sig -> same engine
    pred.run({"x": xv[:2]})                   # new batch size -> new engine
    prof = pred.profile()
    assert prof["n_engines"] == 2
    assert prof["n_params"] >= 4              # 2 weights + 2 biases


def test_from_model_private_scope_does_not_pollute_global(tmp_path):
    """PR 5 satellite: from_model loads params into a per-predictor
    Scope, not the process-wide global_scope()."""
    xv, ref = _build_and_save(tmp_path)
    from paddle_tpu.fluid import executor as executor_mod

    executor_mod._scope_stack[:] = [executor_mod.Scope()]
    try:
        pred = Predictor.from_model(str(tmp_path))
        assert not list(fluid.global_scope().keys()), \
            "inference load leaked params into global_scope()"
        out, = pred.run({"x": xv})
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)
    finally:
        executor_mod._scope_stack[:] = [executor_mod._global_scope]
    # an explicit scope= still works for callers that want sharing
    shared = executor_mod.Scope()
    pred2 = Predictor.from_model(str(tmp_path), scope=shared)
    assert list(shared.keys())
    out2, = pred2.run({"x": xv})
    np.testing.assert_allclose(out2, ref, rtol=1e-5, atol=1e-6)


def test_predictor_warm_sources(tmp_path):
    """warm() reports memory/compile provenance and never double-builds
    one signature."""
    xv, _ = _build_and_save(tmp_path)
    pred = Predictor.from_model(str(tmp_path))
    assert pred.warm({"x": np.zeros_like(xv)}) == "compile"
    assert pred.warm({"x": xv}) == "memory"   # same sig, values ignored
    assert pred.profile()["n_engines"] == 1


def test_analysis_config_predictor_path(tmp_path):
    """Deployment-script path: AnalysisConfig -> create_paddle_predictor
    (ref inference api), including the accepted no-op switches."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import framework, unique_name

    framework.switch_main_program(framework.Program())
    framework.switch_startup_program(framework.Program())
    unique_name.switch()
    fluid.default_startup_program().random_seed = 11
    x = fluid.data(name="acx", shape=[None, 4], dtype="float32")
    y = fluid.layers.fc(x, 2)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    d = str(tmp_path / "m")
    fluid.io.save_inference_model(d, ["acx"], [y], exe)

    cfg = fluid.core.AnalysisConfig(d)
    cfg.disable_gpu()
    cfg.switch_ir_optim(True)
    cfg.enable_mkldnn()
    pred = fluid.core.create_paddle_predictor(cfg)
    out = pred.run({"acx": np.ones((3, 4), "float32")})
    assert out[0].shape == (3, 2)


def test_orbax_checkpoint_roundtrip(tmp_path):
    """save/load_persistables(use_orbax=True): step-managed sharded
    checkpoints (paddle_tpu/parallel/checkpoint.py) restore params AND
    optimizer state exactly."""
    import numpy as np
    import paddle_tpu.fluid as fluid
    from paddle_tpu.parallel import checkpoint as ckpt

    prog, startup = fluid.Program(), fluid.Program()
    prog.random_seed = startup.random_seed = 3
    with fluid.program_guard(prog, startup):
        x = fluid.data("ox", (None, 4,), "float32")
        y = fluid.data("oy", (None, 1,), "float32")
        p = fluid.layers.fc(x, 8, act="relu")
        loss = fluid.layers.reduce_mean(
            fluid.layers.square_error_cost(fluid.layers.fc(p, 1), y))
        fluid.optimizer.Adam(0.05).minimize(loss)
    exe = fluid.Executor()
    exe.run(startup)
    rng = np.random.default_rng(0)
    feed = {"ox": rng.standard_normal((8, 4)).astype("float32"),
            "oy": rng.standard_normal((8, 1)).astype("float32")}
    for _ in range(5):
        exe.run(prog, feed=feed, fetch_list=[loss])

    d = str(tmp_path / "ck")
    fluid.io.save_persistables(exe, d, prog, use_orbax=True, step=5)
    snap = {v.name: np.asarray(fluid.global_scope()[v.name]).copy()
            for v in prog.global_block().vars.values()
            if v.persistable and v.name in fluid.global_scope()}
    assert ckpt.latest_step(d) == 5

    # keep training, then restore and compare every persistable exactly
    for _ in range(3):
        exe.run(prog, feed=feed, fetch_list=[loss])
    changed = any(
        not np.array_equal(np.asarray(fluid.global_scope()[k]), v)
        for k, v in snap.items())
    assert changed
    fluid.io.load_persistables(exe, d, prog, use_orbax=True)
    for k, v in snap.items():
        np.testing.assert_array_equal(
            np.asarray(fluid.global_scope()[k]), v)
    # training resumes from the restored state
    out = exe.run(prog, feed=feed, fetch_list=[loss])
    assert np.isfinite(float(np.asarray(out[0])))
