"""Distributed semantics on the 8-virtual-CPU-device mesh (SURVEY §4):
collective ops, dp grad-allreduce equivalence, tp matmul sharding, ring
attention vs full attention, pipeline parallel."""
import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

import paddle_tpu.fluid as fluid
from paddle_tpu.parallel.mesh import build_mesh
from paddle_tpu.parallel.sharding import DistributedProgram, ShardingRule

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 virtual devices")


def _train_once(dist=None, batch=8, seed=3):
    """Tiny MLP classifier one SGD step; returns (loss0, w_after)."""
    fluid.default_main_program().random_seed = 11
    fluid.default_startup_program().random_seed = 11
    x = fluid.data("x", [None, 16], dtype="float32")
    y = fluid.data("y", [None, 1], dtype="int64")
    h = fluid.layers.fc(
        x, size=32, act="relu",
        param_attr=fluid.ParamAttr(
            name="w1", initializer=fluid.initializer.Constant(0.05)))
    logits = fluid.layers.fc(
        h, size=4,
        param_attr=fluid.ParamAttr(
            name="w2", initializer=fluid.initializer.Constant(0.02)))
    loss = fluid.layers.reduce_mean(
        fluid.layers.softmax_with_cross_entropy(logits, y))
    fluid.optimizer.SGD(learning_rate=0.5).minimize(loss)

    rng = np.random.default_rng(seed)
    x_np = rng.standard_normal((batch, 16)).astype("float32")
    y_np = rng.integers(0, 4, (batch, 1)).astype("int64")

    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    target = dist if dist is not None else fluid.default_main_program()
    if dist is not None:
        out = exe.run(dist, feed={"x": x_np, "y": y_np}, fetch_list=[loss])
    else:
        out = exe.run(feed={"x": x_np, "y": y_np}, fetch_list=[loss])
    from paddle_tpu.fluid.executor import global_scope
    return float(np.asarray(out[0])), np.asarray(global_scope()["w1"]).copy()


def test_dp_matches_single_device():
    """Same global batch, dp=8 vs single device: identical loss + params."""
    loss_1, w_1 = _train_once(dist=None)

    # fresh programs/scope via conftest fixture requires a second test body,
    # so re-create manually here
    from paddle_tpu.fluid import framework, unique_name
    from paddle_tpu.fluid import executor as executor_mod
    framework.switch_main_program(framework.Program())
    framework.switch_startup_program(framework.Program())
    unique_name.switch()
    executor_mod._scope_stack[:] = [executor_mod.Scope()]

    mesh = build_mesh({"dp": 8})
    # build the program, then wrap
    fluid.default_main_program().random_seed = 11
    dist_holder = {}

    def make_dist():
        dist_holder["d"] = DistributedProgram(
            fluid.default_main_program(), mesh, feed_axis="dp")
        return dist_holder["d"]

    # _train_once builds program first, then uses dist; emulate by building
    # inside and wrapping the default program lazily:
    loss_8, w_8 = _train_once(
        dist=_LazyDist(mesh), batch=8)
    assert abs(loss_1 - loss_8) < 1e-5
    np.testing.assert_allclose(w_1, w_8, rtol=1e-5, atol=1e-6)


class _LazyDist:
    """Defers wrapping default_main_program until the executor call."""

    def __init__(self, mesh):
        self.mesh = mesh

    def _executor_run(self, executor, feed, fetch_list, scope, return_numpy):
        d = DistributedProgram(
            fluid.default_main_program(), self.mesh, feed_axis="dp")
        return d._executor_run(executor, feed, fetch_list, scope,
                               return_numpy)


def test_tp_sharded_matmul_matches_replicated():
    """Column-parallel fc over tp axis == unsharded fc."""
    mesh = build_mesh({"tp": 8})
    rng = np.random.default_rng(0)
    x_np = rng.standard_normal((4, 16)).astype("float32")

    x = fluid.data("x", [None, 16], dtype="float32")
    y = fluid.layers.fc(
        x, size=32,
        param_attr=fluid.ParamAttr(
            name="wt", initializer=fluid.initializer.Constant(0.03)),
        bias_attr=False)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    (ref,) = exe.run(feed={"x": x_np}, fetch_list=[y])
    ref = np.asarray(ref)

    dist = DistributedProgram(
        fluid.default_main_program(), mesh,
        param_rules=[ShardingRule("wt", P(None, "tp"))],
        feed_axis=None)
    (out,) = exe.run(dist, feed={"x": x_np}, fetch_list=[y])
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-5, atol=1e-6)


def test_collective_allreduce_psum_semantics():
    """lax.psum over shard_map mesh axis sums shard contributions."""
    from jax.experimental.shard_map import shard_map

    mesh = build_mesh({"dp": 8})
    x = np.arange(8, dtype=np.float32)
    f = shard_map(lambda v: jax.lax.psum(v, "dp"), mesh=mesh,
                  in_specs=P("dp"), out_specs=P("dp"))
    out = np.asarray(f(x))
    np.testing.assert_allclose(out, np.full(8, x.sum()))


def test_collective_layer_ops_single_rank_identity():
    """World-size-1 execution: collective layers behave as identity."""
    from paddle_tpu.fluid.layers import collective as coll

    x = fluid.data("x", [4], dtype="float32")
    y = coll._c_allreduce(x, reduce_type="sum")
    z = coll._c_broadcast(x, root=0)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    x_np = np.array([1.0, 2.0, 3.0, 4.0], "float32")
    y_v, z_v = exe.run(feed={"x": x_np}, fetch_list=[y, z])
    np.testing.assert_allclose(np.asarray(y_v), x_np)
    np.testing.assert_allclose(np.asarray(z_v), x_np)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_full(causal):
    from paddle_tpu.parallel.ring_attention import (
        full_attention, ring_attention_sharded)

    mesh = build_mesh({"sp": 8})
    rng = np.random.default_rng(1)
    B, T, H, D = 2, 64, 2, 8
    q = rng.standard_normal((B, T, H, D)).astype("float32")
    k = rng.standard_normal((B, T, H, D)).astype("float32")
    v = rng.standard_normal((B, T, H, D)).astype("float32")

    ref = np.asarray(full_attention(jnp.array(q), jnp.array(k),
                                    jnp.array(v), causal=causal))
    out = np.asarray(ring_attention_sharded(q, k, v, mesh, axis="sp",
                                            causal=causal))
    np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-4)


def test_compiled_program_with_data_parallel():
    x = fluid.data("x", [None, 16], dtype="float32")
    y = fluid.layers.fc(
        x, size=2,
        param_attr=fluid.ParamAttr(
            name="wdp", initializer=fluid.initializer.Constant(0.1)))
    loss = fluid.layers.reduce_mean(y)
    fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    compiled = fluid.CompiledProgram(
        fluid.default_main_program()).with_data_parallel(
        loss_name=loss.name)
    x_np = np.ones((8, 16), "float32")
    (out,) = exe.run(compiled, feed={"x": x_np}, fetch_list=[loss])
    assert np.isfinite(float(np.asarray(out)))


def test_fleet_distributed_optimizer_runs():
    from paddle_tpu.parallel import fleet

    fleet.init(is_collective=True)
    x = fluid.data("x", [None, 8], dtype="float32")
    y = fluid.layers.fc(x, size=2)
    loss = fluid.layers.reduce_mean(y)
    opt = fleet.distributed_optimizer(fluid.optimizer.SGD(0.1))
    opt.minimize(loss)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    out = exe.run(feed={"x": np.ones((8, 8), "float32")},
                  fetch_list=[loss])
    assert np.isfinite(float(np.asarray(out[0])))


def test_fleet_zero_shards_optimizer_state():
    """sharding_degree=2 (ZeRO-1): optimizer moments shard over dp while
    the parameters stay replicated (VERDICT #8 'done' bar)."""
    from paddle_tpu.fluid.executor import global_scope
    from paddle_tpu.parallel import fleet

    fleet.init(is_collective=True)
    x = fluid.data("zx", [None, 16], dtype="float32")
    y = fluid.layers.fc(x, size=8)
    loss = fluid.layers.reduce_mean(y)
    strategy = fleet.DistributedStrategy()
    strategy.sharding_degree = 2
    opt = fleet.distributed_optimizer(
        fluid.optimizer.Adam(learning_rate=0.01), strategy,
    )
    opt.minimize(loss)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    out = exe.run(fleet.fleet.main_program,
                  feed={"zx": np.ones((8, 16), "float32")},
                  fetch_list=[loss])
    assert np.isfinite(float(np.asarray(out[0])))
    scope = global_scope()
    moment_specs = []
    ndev = len(jax.devices())
    prog = fleet.fleet.main_program._program
    for name, var in prog.global_block().vars.items():
        arr = scope.find_value(name)
        shape = np.shape(arr)
        if (
            getattr(var, "belong_to_optimizer", False)
            and "moment" in name
            and shape
            and shape[0] % ndev == 0
        ):
            moment_specs.append((name, getattr(arr, "sharding", None)))
    assert moment_specs, "no shardable optimizer moments found in scope"
    # every dp-divisible moment lives sharded over dp in HBM — the ZeRO
    # memory win (XLA propagation may additionally shard params, which is
    # FSDP-like and also fine)
    for name, sh in moment_specs:
        assert sh is not None and "dp" in str(sh.spec), (name, sh)


def test_ring_attention_long_context_exact():
    """Long-context scale: T=1024 ring-sharded over sp=8 (128 tokens per
    device) stays exact vs full attention, causal included."""
    from paddle_tpu.parallel.ring_attention import (
        full_attention, ring_attention_sharded,
    )

    b, t, h, d = 1, 1024, 2, 16
    rng = np.random.RandomState(7)
    q = jnp.asarray(rng.rand(b, t, h, d).astype("float32"))
    k = jnp.asarray(rng.rand(b, t, h, d).astype("float32"))
    v = jnp.asarray(rng.rand(b, t, h, d).astype("float32"))
    mesh = build_mesh({"sp": 8})
    for causal in (False, True):
        ref = full_attention(q, k, v, causal=causal)
        out = ring_attention_sharded(q, k, v, mesh, causal=causal)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-5,
        )


def test_fused_attention_rides_ring_under_sp_mesh():
    """fused_multihead_attention through a dp x sp DistributedProgram
    must route to ring attention (exact) — output matches the
    single-device run bit-for-tolerance."""
    import paddle_tpu.fluid.framework as fw
    from paddle_tpu.fluid import unique_name

    b, hds, t, d = 2, 2, 16, 8
    rng = np.random.RandomState(0)
    qv = rng.rand(b, hds, t, d).astype("float32")
    kv = rng.rand(b, hds, t, d).astype("float32")
    vv = rng.rand(b, hds, t, d).astype("float32")

    def build():
        fw.switch_main_program(fw.Program())
        fw.switch_startup_program(fw.Program())
        unique_name.switch()
        q = fluid.data("aq", [b, hds, t, d], dtype="float32")
        k = fluid.data("ak", [b, hds, t, d], dtype="float32")
        v = fluid.data("av", [b, hds, t, d], dtype="float32")
        out = fluid.layers.fused_multihead_attention(q, k, v, causal=True)
        return out

    out = build()
    exe = fluid.Executor(fluid.CPUPlace())
    feed = {"aq": qv, "ak": kv, "av": vv}
    single = exe.run(feed=feed, fetch_list=[out])[0]

    out2 = build()
    mesh = build_mesh({"dp": 2, "sp": 4})
    dist = DistributedProgram(
        fluid.default_main_program(), mesh,
        feed_specs={"aq": P("dp", None, "sp", None),
                    "ak": P("dp", None, "sp", None),
                    "av": P("dp", None, "sp", None)},
    )
    # prove the RING path engaged (the test would pass via plain GSPMD
    # einsum too): count ring_attention trace-time invocations
    from paddle_tpu.parallel import ring_attention as ra_mod

    calls = []
    orig = ra_mod.ring_attention

    def spy(*a, **kw):
        calls.append(1)
        return orig(*a, **kw)

    ra_mod.ring_attention = spy
    try:
        ringed = exe.run(dist, feed=feed, fetch_list=[out2])[0]
    finally:
        ra_mod.ring_attention = orig
    assert calls, "sp-sharded fused attention did not route to ring"
    np.testing.assert_allclose(ringed, single, rtol=2e-4, atol=2e-5)


def test_zero_merges_with_tp_layout():
    """Moments of tp-sharded params keep tp AND gain the dp axis."""
    from jax.sharding import PartitionSpec as P2

    mesh = build_mesh({"dp": 2, "tp": 4})
    import paddle_tpu.fluid.framework as fw

    prog = fw.Program()
    blk = prog.global_block()
    blk.create_var(name="w", shape=(16, 8), dtype="float32")
    mvar = blk.create_var(name="w_moment1_0", shape=(16, 8),
                          dtype="float32")
    mvar.belong_to_optimizer = True
    dist = DistributedProgram(
        prog, mesh,
        param_rules=[ShardingRule(r"^w", P2(None, "tp"))],
        opt_state_rules=[ShardingRule(r".*", P2("dp"))],
    )
    msh = dist.param_sharding("w_moment1_0", (16, 8))
    assert str(msh.spec) in (
        "PartitionSpec('dp', 'tp')", "PartitionSpec('dp', 'tp',)",
    ), msh
    # the param itself keeps its plain tp layout
    wsh = dist.param_sharding("w", (16, 8))
    assert "dp" not in str(wsh.spec) and "tp" in str(wsh.spec)


def test_pipeline_parallel_forward_matches_sequential():
    from paddle_tpu.parallel.pipeline import gpipe_sharded

    mesh = build_mesh({"pp": 4}, devices=jax.devices()[:4])
    rng = np.random.default_rng(5)
    ws = np.stack([rng.standard_normal((8, 8)).astype("float32") * 0.3
                   for _ in range(4)])
    x = rng.standard_normal((16, 8)).astype("float32")

    def stage(w, h):
        return jnp.tanh(h @ w)

    ref = jnp.array(x)
    for w in ws:
        ref = stage(jnp.array(w), ref)

    out = gpipe_sharded(stage, jnp.array(ws), jnp.array(x), mesh,
                        axis="pp", n_microbatches=4)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)
