"""RCNN / RetinaNet detection suite tests: anchor_generator,
sigmoid_focal_loss, target assigns, generate_proposals, detection_map,
multi_box_head + ssd_loss end-to-end, retinanet pieces, FPN routing."""
import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import framework, unique_name


@pytest.fixture(autouse=True)
def _fresh_program():
    framework.switch_main_program(framework.Program())
    framework.switch_startup_program(framework.Program())
    unique_name.switch()
    yield


def _exe():
    return fluid.Executor(fluid.CPUPlace())


def _anchor_oracle(h, w, sizes, ratios, stride, offset=0.5):
    """Numpy re-derivation of anchor_generator_op.h."""
    out = np.zeros((h, w, len(ratios) * len(sizes), 4), np.float32)
    sw, sh = stride
    for hi in range(h):
        for wi in range(w):
            xc = wi * sw + offset * (sw - 1)
            yc = hi * sh + offset * (sh - 1)
            idx = 0
            for ar in ratios:
                base_w = round(np.sqrt(sw * sh / ar))
                base_h = round(base_w * ar)
                for s in sizes:
                    aw = s / sw * base_w
                    ah = s / sh * base_h
                    out[hi, wi, idx] = [
                        xc - 0.5 * (aw - 1), yc - 0.5 * (ah - 1),
                        xc + 0.5 * (aw - 1), yc + 0.5 * (ah - 1),
                    ]
                    idx += 1
    return out


def test_anchor_generator_matches_oracle():
    feat = fluid.data(name="feat", shape=[1, 8, 3, 4], dtype="float32")
    anchors, var = fluid.layers.detection.anchor_generator(
        feat, anchor_sizes=[32.0, 64.0], aspect_ratios=[0.5, 1.0],
        stride=[16.0, 16.0],
    )
    exe = _exe()
    a, v = exe.run(feed={"feat": np.zeros((1, 8, 3, 4), "float32")},
                   fetch_list=[anchors, var])
    assert a.shape == (3, 4, 4, 4)
    oracle = _anchor_oracle(3, 4, [32.0, 64.0], [0.5, 1.0], [16.0, 16.0])
    np.testing.assert_allclose(a, oracle, rtol=1e-5)
    np.testing.assert_allclose(v[0, 0, 0], [0.1, 0.1, 0.2, 0.2], rtol=1e-6)


def test_sigmoid_focal_loss_matches_oracle():
    r, c = 5, 3
    rng = np.random.RandomState(0)
    xv = rng.randn(r, c).astype("float32")
    lv = np.array([[1], [0], [3], [-1], [2]], "int32")
    fg = np.array([2], "int32")
    x = fluid.data(name="x", shape=[r, c], dtype="float32")
    lab = fluid.data(name="lab", shape=[r, 1], dtype="int32")
    fgn = fluid.data(name="fgn", shape=[1], dtype="int32")
    out = fluid.layers.detection.sigmoid_focal_loss(x, lab, fgn,
                                                    gamma=2.0, alpha=0.25)
    o = _exe().run(feed={"x": xv, "lab": lv, "fgn": fg},
                   fetch_list=[out])[0]
    # numpy oracle per sigmoid_focal_loss_op.h
    oracle = np.zeros((r, c), np.float64)
    for i in range(r):
        for d in range(c):
            g = lv[i, 0]
            xx = float(xv[i, d])
            p = 1.0 / (1.0 + np.exp(-xx))
            c_pos = float(g == d + 1)
            c_neg = float((g != -1) and (g != d + 1))
            fgf = max(float(fg[0]), 1.0)
            term_pos = (1 - p) ** 2.0 * np.log(max(p, 1e-38))
            term_neg = p ** 2.0 * np.log(max(1 - p, 1e-38))
            oracle[i, d] = (-c_pos * term_pos * 0.25 / fgf
                            - c_neg * term_neg * 0.75 / fgf)
    np.testing.assert_allclose(o, oracle, rtol=1e-4, atol=1e-6)


def test_target_assign_dense():
    gt = fluid.data(name="gt", shape=[2, 3, 4], dtype="float32")
    match = fluid.data(name="m", shape=[2, 2], dtype="int32")
    out, w = fluid.layers.detection.target_assign(gt, match,
                                                  mismatch_value=7.0)
    gtv = np.arange(24, dtype="float32").reshape(2, 3, 4)
    mv = np.array([[1, -1], [0, 2]], "int32")
    o, wv = _exe().run(feed={"gt": gtv, "m": mv}, fetch_list=[out, w])
    np.testing.assert_allclose(o[0, 0], gtv[0, 1])
    np.testing.assert_allclose(o[0, 1], [7.0] * 4)
    np.testing.assert_allclose(o[1, 0], gtv[1, 0])
    np.testing.assert_allclose(o[1, 1], gtv[1, 2])
    np.testing.assert_allclose(wv[:, :, 0], [[1, 0], [1, 1]])


def test_rpn_target_assign_dense_semantics():
    m, g = 6, 2
    anchors_np = np.array(
        [[0, 0, 9, 9], [10, 10, 19, 19], [30, 30, 49, 49],
         [0, 0, 11, 11], [200, 200, 240, 240], [35, 35, 44, 44]],
        "float32",
    )
    gt_np = np.array(
        [[[0, 0, 10, 10], [30, 30, 50, 50]]], "float32"
    )  # (1, 2, 4)
    crowd_np = np.zeros((1, g), "int32")
    info_np = np.array([[256, 256, 1.0]], "float32")
    anc = fluid.data(name="anc", shape=[m, 4], dtype="float32")
    gt = fluid.data(name="gt", shape=[1, g, 4], dtype="float32")
    crowd = fluid.data(name="crowd", shape=[1, g], dtype="int32")
    info = fluid.data(name="info", shape=[1, 3], dtype="float32")
    bbox_pred = fluid.data(name="bp", shape=[1, m, 4], dtype="float32")
    cls_logits = fluid.data(name="cl", shape=[1, m, 1], dtype="float32")
    _, _, score_t, loc_t, w = fluid.layers.detection.rpn_target_assign(
        bbox_pred, cls_logits, anc, None, gt, crowd, info,
        rpn_batch_size_per_im=4, rpn_positive_overlap=0.7,
        rpn_negative_overlap=0.3, rpn_straddle_thresh=0.0,
    )
    st, lt, wv = _exe().run(
        feed={"anc": anchors_np, "gt": gt_np, "crowd": crowd_np,
              "info": info_np,
              "bp": np.zeros((1, m, 4), "float32"),
              "cl": np.zeros((1, m, 1), "float32")},
        fetch_list=[score_t, loc_t, w],
    )
    st = st[0]
    # anchor 0 overlaps gt0 highly -> fg; anchor 4 is far from every gt -> bg
    assert st[0] == 1
    assert st[4] == 0
    # anchor 5 (inside gt1, IoU ~0.25 w/ 30..50 box) is bg or ignore, not fg
    assert st[5] != 1 or wv[0, 5, 0] in (0.0, 1.0)
    # fg anchors carry encode targets + unit weights, bg carry zeros
    assert np.all(wv[0, st == 1] == 1.0)
    assert np.all(wv[0, st != 1] == 0.0)
    # total sampled <= batch size
    assert np.sum(st >= 0) <= 4
    # loc target for anchor 0 encodes gt0 vs anchor 0 (center-size)
    aw = 9 - 0 + 1.0
    gw = 10 - 0 + 1.0
    np.testing.assert_allclose(lt[0, 0, 2], np.log(gw / aw), rtol=1e-4)


def test_retinanet_target_assign_labels_and_fg_num():
    m, g = 4, 2
    anchors_np = np.array(
        [[0, 0, 10, 10], [28, 28, 52, 52], [100, 100, 120, 120],
         [5, 5, 14, 14]],
        "float32",
    )
    gt_np = np.array([[[0, 0, 10, 10], [30, 30, 50, 50]]], "float32")
    lab_np = np.array([[3, 7]], "int32")
    crowd_np = np.zeros((1, g), "int32")
    info_np = np.array([[256, 256, 1.0]], "float32")
    anc = fluid.data(name="anc", shape=[m, 4], dtype="float32")
    gt = fluid.data(name="gt", shape=[1, g, 4], dtype="float32")
    gl = fluid.data(name="gl", shape=[1, g], dtype="int32")
    crowd = fluid.data(name="crowd", shape=[1, g], dtype="int32")
    info = fluid.data(name="info", shape=[1, 3], dtype="float32")
    bp = fluid.data(name="bp", shape=[1, m, 4], dtype="float32")
    cl = fluid.data(name="cl", shape=[1, m, 9], dtype="float32")
    _, _, score_t, loc_t, w, fg_num = \
        fluid.layers.detection.retinanet_target_assign(
            bp, cl, anc, None, gt, gl, crowd, info, num_classes=9,
        )
    st, fg = _exe().run(
        feed={"anc": anchors_np, "gt": gt_np, "gl": lab_np,
              "crowd": crowd_np, "info": info_np,
              "bp": np.zeros((1, m, 4), "float32"),
              "cl": np.zeros((1, m, 9), "float32")},
        fetch_list=[score_t, fg_num],
    )
    assert st[0, 0] == 3      # fg with gt0's class label
    assert st[0, 1] == 7      # fg with gt1's class label
    assert st[0, 2] == 0      # far anchor -> background
    assert fg[0, 0] == np.sum(st[0] > 0)


def test_generate_proposals_shapes_and_nms():
    n, a, h, w = 1, 2, 2, 2
    m = a * h * w
    scores = fluid.data(name="sc", shape=[n, a, h, w], dtype="float32")
    deltas = fluid.data(name="dl", shape=[n, a * 4, h, w], dtype="float32")
    info = fluid.data(name="info", shape=[n, 3], dtype="float32")
    anc = fluid.data(name="anc", shape=[h, w, a, 4], dtype="float32")
    var = fluid.data(name="var", shape=[h, w, a, 4], dtype="float32")
    rois, probs = fluid.layers.detection.generate_proposals(
        scores, deltas, info, anc, var, pre_nms_top_n=8,
        post_nms_top_n=4, nms_thresh=0.5, min_size=1.0,
    )
    anchors_np = np.zeros((h, w, a, 4), "float32")
    for hi in range(h):
        for wi in range(w):
            for ai in range(a):
                cx, cy = 16 * wi + 8, 16 * hi + 8
                s = 8 * (ai + 1)
                anchors_np[hi, wi, ai] = [cx - s, cy - s, cx + s, cy + s]
    sc_np = np.random.RandomState(3).rand(n, a, h, w).astype("float32")
    dl_np = np.zeros((n, a * 4, h, w), "float32")
    info_np = np.array([[64, 64, 1.0]], "float32")
    var_np = np.ones((h, w, a, 4), "float32")
    r, p = _exe().run(
        feed={"sc": sc_np, "dl": dl_np, "info": info_np,
              "anc": anchors_np, "var": var_np},
        fetch_list=[rois, probs],
    )
    assert r.shape == (1, 4, 4)
    assert p.shape == (1, 4, 1)
    # probs sorted descending, boxes clipped to the image
    pp = p[0, :, 0]
    assert all(pp[i] >= pp[i + 1] - 1e-6 for i in range(3))
    assert r.min() >= 0 and r.max() <= 63


def test_detection_map_perfect_and_partial():
    det = fluid.data(name="det", shape=[1, 3, 6], dtype="float32")
    gt = fluid.data(name="gt", shape=[1, 2, 6], dtype="float32")
    mp = fluid.layers.detection.detection_map(det, gt, class_num=3,
                                              overlap_threshold=0.5)
    exe = _exe()
    gt_np = np.array([[[1, 10, 10, 20, 20, 0],
                       [2, 40, 40, 60, 60, 0]]], "float32")
    det_perfect = np.array([[[1, 0.9, 10, 10, 20, 20],
                             [2, 0.8, 40, 40, 60, 60],
                             [-1, 0, 0, 0, 0, 0]]], "float32")
    v = exe.run(feed={"det": det_perfect, "gt": gt_np}, fetch_list=[mp])[0]
    np.testing.assert_allclose(v, 1.0, atol=1e-5)
    det_half = np.array([[[1, 0.9, 10, 10, 20, 20],
                          [2, 0.8, 100, 100, 110, 110],
                          [-1, 0, 0, 0, 0, 0]]], "float32")
    v2 = exe.run(feed={"det": det_half, "gt": gt_np}, fetch_list=[mp])[0]
    np.testing.assert_allclose(v2, 0.5, atol=1e-5)


def test_polygon_box_transform_oracle():
    x = fluid.data(name="x", shape=[1, 4, 2, 3], dtype="float32")
    out = fluid.layers.detection.polygon_box_transform(x)
    xv = np.random.RandomState(1).rand(1, 4, 2, 3).astype("float32")
    o = _exe().run(feed={"x": xv}, fetch_list=[out])[0]
    oracle = np.zeros_like(xv)
    for c in range(4):
        for hh in range(2):
            for ww in range(3):
                if c % 2 == 0:
                    oracle[0, c, hh, ww] = 4 * ww - xv[0, c, hh, ww]
                else:
                    oracle[0, c, hh, ww] = 4 * hh - xv[0, c, hh, ww]
    np.testing.assert_allclose(o, oracle, rtol=1e-5)


def test_box_decoder_and_assign():
    r, c = 2, 3
    prior = fluid.data(name="p", shape=[r, 4], dtype="float32")
    pvar = fluid.data(name="pv", shape=[4], dtype="float32")
    tb = fluid.data(name="tb", shape=[r, 4 * c], dtype="float32")
    sc = fluid.data(name="sc", shape=[r, c], dtype="float32")
    dec, assign = fluid.layers.detection.box_decoder_and_assign(
        prior, pvar, tb, sc, 4.135,
    )
    pv = np.array([[0, 0, 9, 9], [10, 10, 29, 29]], "float32")
    pvv = np.array([1.0, 1.0, 1.0, 1.0], "float32")
    tbv = np.zeros((r, 4 * c), "float32")
    scv = np.array([[0.8, 0.1, 0.1], [0.1, 0.2, 0.7]], "float32")
    d, a = _exe().run(
        feed={"p": pv, "pv": pvv, "tb": tbv, "sc": scv},
        fetch_list=[dec, assign],
    )
    assert d.shape == (r, 4 * c)
    # zero deltas decode back to the prior box (within the +1 convention)
    np.testing.assert_allclose(d[0, :4], pv[0], atol=1e-4)
    # row 0: argmax class is background -> keeps prior box
    np.testing.assert_allclose(a[0], pv[0], atol=1e-4)
    # row 1: class 2 wins -> assigned its decoded box (= prior here)
    np.testing.assert_allclose(a[1], pv[1], atol=1e-4)


def test_multi_box_head_and_ssd_train_step():
    """VERDICT #4 'done' criterion: an SSD-style head builds and one train
    step runs end-to-end."""
    img = fluid.data(name="img", shape=[2, 3, 32, 32], dtype="float32")
    gt_box = fluid.data(name="gt_box", shape=[3, 4], dtype="float32")
    gt_label = fluid.data(name="gt_label", shape=[3, 1], dtype="int64")
    c1 = fluid.layers.conv2d(img, 8, 3, stride=2, padding=1)
    c2 = fluid.layers.conv2d(c1, 8, 3, stride=2, padding=1)
    locs, confs, boxes, variances = fluid.layers.detection.multi_box_head(
        inputs=[c1, c2], image=img, base_size=32, num_classes=4,
        aspect_ratios=[[1.0], [1.0, 2.0]], min_ratio=20, max_ratio=90,
        offset=0.5, flip=True,
    )
    # ssd_loss is per-image: slice image 0 out of the batched head output
    loc0 = fluid.layers.reshape(
        fluid.layers.slice(locs, [0], [0], [1]), [-1, 4]
    )
    conf0 = fluid.layers.reshape(
        fluid.layers.slice(confs, [0], [0], [1]), [-1, 4]
    )
    loss = fluid.layers.detection.ssd_loss(
        loc0, conf0, gt_box, gt_label, boxes, variances,
    )
    opt = fluid.optimizer.SGD(learning_rate=1e-4)
    opt.minimize(loss)
    exe = _exe()
    exe.run(fluid.default_startup_program())
    feed = {
        "img": np.random.RandomState(0).rand(2, 3, 32, 32).astype("float32"),
        "gt_box": np.array([[0.1, 0.1, 0.4, 0.4], [0.5, 0.5, 0.9, 0.9],
                            [0.2, 0.6, 0.5, 0.95]], "float32"),
        "gt_label": np.array([[1], [2], [3]], "int64"),
    }
    losses = [float(exe.run(feed=feed, fetch_list=[loss])[0])
              for _ in range(4)]
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]  # a few SGD steps reduce the loss


def test_retinanet_detection_output_basic():
    n, m, c = 1, 4, 2
    bb = fluid.data(name="bb", shape=[n, m, 4], dtype="float32")
    sc = fluid.data(name="sc", shape=[n, m, c], dtype="float32")
    anc = fluid.data(name="anc", shape=[m, 4], dtype="float32")
    info = fluid.data(name="info", shape=[n, 3], dtype="float32")
    out = fluid.layers.detection.retinanet_detection_output(
        [bb], [sc], [anc], info, score_threshold=0.1, nms_top_k=4,
        keep_top_k=3,
    )
    anc_np = np.array([[0, 0, 10, 10], [20, 20, 40, 40],
                       [50, 50, 70, 70], [5, 5, 15, 15]], "float32")
    sc_np = np.zeros((n, m, c), "float32")
    sc_np[0, 1, 0] = 0.9   # one confident class-0 detection at anchor 1
    sc_np[0, 2, 1] = 0.6   # one class-1 detection at anchor 2
    o = _exe().run(
        feed={"bb": np.zeros((n, m, 4), "float32"), "sc": sc_np,
              "anc": anc_np, "info": np.array([[100, 100, 1]], "float32")},
        fetch_list=[out],
    )[0]
    assert o.shape == (1, 3, 6)
    assert o[0, 0, 0] == 1.0 and abs(o[0, 0, 1] - 0.9) < 1e-5
    assert o[0, 1, 0] == 2.0 and abs(o[0, 1, 1] - 0.6) < 1e-5
    assert o[0, 2, 0] == -1.0  # padding


def test_locality_aware_nms_merges_adjacent():
    """Two overlapping high-score boxes merge into a weighted average
    before NMS (the EAST pass); a distant box survives separately."""
    bb = fluid.data(name="bb", shape=[1, 3, 4], dtype="float32")
    sc = fluid.data(name="sc", shape=[1, 1, 3], dtype="float32")
    out = fluid.layers.detection.locality_aware_nms(
        bb, sc, score_threshold=0.1, nms_top_k=3, keep_top_k=2,
        nms_threshold=0.3,
    )
    bbv = np.array([[[0, 0, 10, 10], [1, 1, 11, 11], [50, 50, 60, 60]]],
                   "float32")
    scv = np.array([[[0.8, 0.4, 0.9]]], "float32")
    o = _exe().run(feed={"bb": bbv, "sc": scv}, fetch_list=[out])[0]
    assert o.shape == (1, 2, 6)
    kept = o[0]
    # merged cluster score = 0.8 + 0.4; boxes averaged by score weight
    merged_row = kept[np.argmax(kept[:, 1])]
    np.testing.assert_allclose(merged_row[1], 1.2, atol=1e-5)
    exp_box = (np.array([0, 0, 10, 10]) * 0.8
               + np.array([1, 1, 11, 11]) * 0.4) / 1.2
    np.testing.assert_allclose(merged_row[2:], exp_box, rtol=1e-4)
    # the distant box is also kept
    assert any(abs(r[2] - 50) < 1e-3 for r in kept)


def test_generate_proposal_labels_dense():
    r, g = 4, 2
    rois = fluid.data(name="rois", shape=[1, r, 4], dtype="float32")
    gtc = fluid.data(name="gtc", shape=[1, g], dtype="int32")
    crowd = fluid.data(name="crowd", shape=[1, g], dtype="int32")
    gtb = fluid.data(name="gtb", shape=[1, g, 4], dtype="float32")
    info = fluid.data(name="info", shape=[1, 3], dtype="float32")
    outs = fluid.layers.detection.generate_proposal_labels(
        rois, gtc, crowd, gtb, info, batch_size_per_im=6,
        fg_fraction=0.5, fg_thresh=0.5,
    )
    rois_np = np.array([[[0, 0, 10, 10], [30, 30, 50, 50],
                         [100, 100, 120, 120], [1, 1, 9, 9]]], "float32")
    gtb_np = np.array([[[0, 0, 10, 10], [30, 30, 50, 50]]], "float32")
    ro, lab, tgt, w_in, w_out = _exe().run(
        feed={"rois": rois_np, "gtc": np.array([[3, 5]], "int32"),
              "crowd": np.zeros((1, g), "int32"),
              "info": np.array([[200, 200, 1]], "float32"),
              "gtb": gtb_np},
        fetch_list=list(outs),
    )
    assert ro.shape == (1, r + g, 4)     # gt appended to the roi pool
    assert lab[0, 0] == 3                # roi 0 matches gt 0 -> class 3
    assert lab[0, 1] == 5                # roi 1 matches gt 1 -> class 5
    assert lab[0, 2] == 0                # distant roi -> background
    # fg rois carry unit weights + finite targets; bg rois zero weights
    assert np.all(w_in[0, 0] == 1.0) and np.all(w_in[0, 2] == 0.0)
    assert np.all(np.isfinite(tgt))


def test_roi_perspective_transform_identity_quad():
    """An axis-aligned quad warps to a plain crop-resize."""
    x = fluid.data(name="x", shape=[1, 1, 8, 8], dtype="float32")
    rois = fluid.data(name="rois", shape=[1, 8], dtype="float32")
    out = fluid.layers.detection.roi_perspective_transform(
        x, rois, transformed_height=4, transformed_width=4,
    )
    xv = np.arange(64, dtype="float32").reshape(1, 1, 8, 8)
    # the quad covering [2,6)x[2,6), clockwise from top-left
    quad = np.array([[2, 2, 6, 2, 6, 6, 2, 6]], "float32")
    o = _exe().run(feed={"x": xv, "rois": quad}, fetch_list=[out])[0]
    assert o.shape == (1, 1, 4, 4)
    # sampling the center of each output cell maps to input rows 2.5..5.5
    expected00 = xv[0, 0, 2, 2] * 0.25 + xv[0, 0, 2, 3] * 0.25 \
        + xv[0, 0, 3, 2] * 0.25 + xv[0, 0, 3, 3] * 0.25
    np.testing.assert_allclose(o[0, 0, 0, 0], expected00, rtol=1e-4)


def test_roi_perspective_transform_trapezoid_homography():
    """A trapezoid quad must warp with true perspective foreshortening:
    the midline sample point is NOT the uniform (ruled-surface) midpoint."""
    h = w = 32
    x = fluid.data(name="x", shape=[1, 2, h, w], dtype="float32")
    rois = fluid.data(name="rois", shape=[1, 8], dtype="float32")
    out = fluid.layers.detection.roi_perspective_transform(
        x, rois, transformed_height=8, transformed_width=8,
    )
    # gradient image so sampled positions are recoverable from values
    xv = np.zeros((1, 2, h, w), "float32")
    xv[0, 0] = np.arange(w, dtype="float32")[None, :]   # channel0 = x pos
    xv[0, 1] = np.arange(h, dtype="float32")[:, None]   # channel1 = y pos
    quad = np.array([[4, 4, 28, 4, 24, 20, 8, 20]], "float32")  # trapezoid
    o = _exe().run(feed={"x": xv, "rois": quad}, fetch_list=[out])[0]
    # numpy homography oracle (square -> quad, Heckbert closed form)
    q = quad[0].reshape(4, 2)
    p0, p1, p2, p3 = q
    s = p0 - p1 + p2 - p3
    d1, d2 = p1 - p2, p3 - p2
    den = d1[0] * d2[1] - d2[0] * d1[1]
    g = (s[0] * d2[1] - d2[0] * s[1]) / den
    hh = (d1[0] * s[1] - s[0] * d1[1]) / den
    H = np.array([
        [p1[0] - p0[0] + g * p1[0], p3[0] - p0[0] + hh * p3[0], p0[0]],
        [p1[1] - p0[1] + g * p1[1], p3[1] - p0[1] + hh * p3[1], p0[1]],
        [g, hh, 1.0],
    ])
    for (oy, ox) in [(0, 0), (3, 5), (7, 7), (4, 2)]:
        u, v = (ox + 0.5) / 8, (oy + 0.5) / 8
        xyw = H @ np.array([u, v, 1.0])
        ex, ey = xyw[0] / xyw[2], xyw[1] / xyw[2]
        np.testing.assert_allclose(o[0, 0, oy, ox], ex, atol=0.02)
        np.testing.assert_allclose(o[0, 1, oy, ox], ey, atol=0.02)


def test_generate_proposal_labels_excludes_crowd_rows():
    """Crowd gt rows appended to the pool must not become bg samples."""
    r, g = 2, 2
    rois = fluid.data(name="crois", shape=[1, r, 4], dtype="float32")
    gtc = fluid.data(name="cgtc", shape=[1, g], dtype="int32")
    crowd = fluid.data(name="ccrowd", shape=[1, g], dtype="int32")
    gtb = fluid.data(name="cgtb", shape=[1, g, 4], dtype="float32")
    info = fluid.data(name="cinfo", shape=[1, 3], dtype="float32")
    outs = fluid.layers.detection.generate_proposal_labels(
        rois, gtc, crowd, gtb, info, batch_size_per_im=6, fg_thresh=0.5,
        fg_fraction=0.5,
    )
    _, lab, _, w_in, _ = _exe().run(
        feed={"crois": np.array([[[0, 0, 10, 10],
                                  [60, 60, 80, 80]]], "float32"),
              "cgtc": np.array([[3, 7]], "int32"),
              "ccrowd": np.array([[0, 1]], "int32"),   # gt1 is crowd
              "cgtb": np.array([[[0, 0, 10, 10],
                                 [100, 100, 140, 140]]], "float32"),
              "cinfo": np.array([[200, 200, 1]], "float32")},
        fetch_list=list(outs),
    )
    # appended rows: index r+0 (real gt -> fg with its class),
    # r+1 (crowd -> excluded entirely, label -1)
    assert lab[0, r + 0] == 3
    assert lab[0, r + 1] == -1
    assert np.all(w_in[0, r + 1] == 0.0)


def test_generate_mask_labels_rasterizes_polygon():
    """A square polygon covering the left half of its roi rasterizes to a
    half-on mask in the matched class channel; bg rois are all -1."""
    # P=6 with only 4 real vertices: padding rows must not corrupt the
    # gt bbox used for roi matching
    n, g, p, r, res, ncls = 1, 1, 6, 2, 8, 3
    info = fluid.data(name="minfo", shape=[n, 3], dtype="float32")
    gtc = fluid.data(name="mgtc", shape=[n, g], dtype="int32")
    crowd = fluid.data(name="mcrowd", shape=[n, g], dtype="int32")
    segms = fluid.data(name="msegms", shape=[n, g, p, 2], dtype="float32")
    slens = fluid.data(name="mslens", shape=[n, g], dtype="int32")
    rois = fluid.data(name="mrois", shape=[n, r, 4], dtype="float32")
    labs = fluid.data(name="mlabs", shape=[n, r], dtype="int32")
    outs = fluid.layers.detection.generate_mask_labels(
        info, gtc, crowd, segms, rois, labs, num_classes=ncls,
        resolution=res, gt_segm_lens=slens,
    )
    # polygon = left half of [0,16]x[0,16], zero-padded to 6 vertices
    poly = np.zeros((1, 1, 6, 2), "float32")
    poly[0, 0, :4] = [[0, 0], [8, 0], [8, 16], [0, 16]]
    mr, hm, mk = _exe().run(
        feed={"minfo": np.array([[32, 32, 1]], "float32"),
              "mgtc": np.array([[2]], "int32"),
              "mcrowd": np.zeros((n, g), "int32"),
              "msegms": poly, "mslens": np.array([[4]], "int32"),
              "mrois": np.array([[[0, 0, 16, 16],
                                  [20, 20, 30, 30]]], "float32"),
              "mlabs": np.array([[2, 0]], "int32")},
        fetch_list=list(outs),
    )
    assert hm[0].tolist() == [1, 0]
    m = mk[0, 0].reshape(ncls, res, res)
    # class 2 channel: left half on, right half off
    np.testing.assert_array_equal(m[2, :, : res // 2], 1)
    np.testing.assert_array_equal(m[2, :, res // 2:], 0)
    np.testing.assert_array_equal(m[1], 0)   # other classes empty
    assert np.all(mk[0, 1] == -1)            # bg roi ignored


def test_fpn_distribute_and_collect():
    rois = fluid.data(name="rois", shape=[4, 4], dtype="float32")
    outs, restore = fluid.layers.detection.distribute_fpn_proposals(
        rois, min_level=2, max_level=4, refer_level=3, refer_scale=224,
    )
    scores = fluid.data(name="s", shape=[4, 1], dtype="float32")
    collected = fluid.layers.detection.collect_fpn_proposals(
        [rois], [scores], 2, 2, post_nms_top_n=2,
    )
    rois_np = np.array(
        [[0, 0, 112, 112],      # scale 112 -> level 2
         [0, 0, 224, 224],      # scale 224 -> level 3
         [0, 0, 448, 448],      # scale 448 -> level 4
         [0, 0, 1000, 1000]],   # clipped to level 4
        "float32",
    )
    sc_np = np.array([[0.1], [0.9], [0.5], [0.7]], "float32")
    o2, o3, o4, ridx, col = _exe().run(
        feed={"rois": rois_np, "s": sc_np},
        fetch_list=[outs[0], outs[1], outs[2], restore, collected],
    )
    np.testing.assert_allclose(o2[0], rois_np[0])
    assert np.all(o2[1:] == 0)
    np.testing.assert_allclose(o3[1], rois_np[1])
    np.testing.assert_allclose(o4[2], rois_np[2])
    np.testing.assert_allclose(o4[3], rois_np[3])
    # restore_ind: gather(concat(outs), restore_ind) == input order
    concat = np.concatenate([o2, o3, o4], axis=0)
    np.testing.assert_allclose(concat[ridx[:, 0]], rois_np)
    # collect keeps the 2 highest-scoring rois
    np.testing.assert_allclose(col[0], rois_np[1])
    np.testing.assert_allclose(col[1], rois_np[3])


def test_metrics_detection_map_streams():
    """fluid.metrics.DetectionMAP: per-batch mAP + in-graph running mean,
    reset() starts a fresh pass."""
    det = fluid.data(name="mm_det", shape=[1, 3, 6], dtype="float32")
    gtl = fluid.data(name="mm_gtl", shape=[1, 2, 1], dtype="int64")
    gtb = fluid.data(name="mm_gtb", shape=[1, 2, 4], dtype="float32")
    m = fluid.metrics.DetectionMAP(det, gtl, gtb, class_num=3,
                                   overlap_threshold=0.5)
    cur, accum = m.get_map_var()
    exe = _exe()
    exe.run(fluid.default_startup_program())
    gt_feed = {
        "mm_gtl": np.array([[[1], [2]]], "int64"),
        "mm_gtb": np.array([[[10, 10, 20, 20], [40, 40, 60, 60]]],
                           "float32"),
    }
    perfect = np.array([[[1, 0.9, 10, 10, 20, 20],
                         [2, 0.8, 40, 40, 60, 60],
                         [-1, 0, 0, 0, 0, 0]]], "float32")
    half = np.array([[[1, 0.9, 10, 10, 20, 20],
                      [2, 0.8, 100, 100, 110, 110],
                      [-1, 0, 0, 0, 0, 0]]], "float32")
    c1, a1 = exe.run(feed={"mm_det": perfect, **gt_feed},
                     fetch_list=[cur, accum])
    np.testing.assert_allclose(c1, 1.0, atol=1e-5)
    np.testing.assert_allclose(a1, 1.0, atol=1e-5)
    c2, a2 = exe.run(feed={"mm_det": half, **gt_feed},
                     fetch_list=[cur, accum])
    np.testing.assert_allclose(c2, 0.5, atol=1e-5)
    np.testing.assert_allclose(a2, 0.75, atol=1e-5)  # mean(1.0, 0.5)
    m.reset(exe)
    c3, a3 = exe.run(feed={"mm_det": half, **gt_feed},
                     fetch_list=[cur, accum])
    np.testing.assert_allclose(a3, 0.5, atol=1e-5)
