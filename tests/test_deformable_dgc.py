"""deformable_conv, psroi_pool, prroi_pool, DGCMomentum tests."""
import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import framework, unique_name


@pytest.fixture(autouse=True)
def _fresh_program():
    framework.switch_main_program(framework.Program())
    framework.switch_startup_program(framework.Program())
    unique_name.switch()
    yield


def _exe():
    return fluid.Executor(fluid.CPUPlace())


def test_deformable_conv_zero_offset_matches_conv2d():
    """With zero offsets and unit mask, deformable conv == plain conv."""
    torch = pytest.importorskip("torch")
    n, c, h, w, co, k = 1, 2, 6, 6, 3, 3
    x = fluid.data(name="x", shape=[n, c, h, w], dtype="float32")
    off = fluid.data(name="off", shape=[n, 2 * k * k, h, w],
                     dtype="float32")
    mask = fluid.data(name="mask", shape=[n, k * k, h, w],
                      dtype="float32")
    out = fluid.layers.deformable_conv(
        x, off, mask, num_filters=co, filter_size=k, padding=1,
        bias_attr=False,
    )
    exe = _exe()
    exe.run(fluid.default_startup_program())
    import paddle_tpu.fluid.framework as fw

    wname = [
        v.name
        for v in fw.default_main_program().global_block().vars.values()
        if isinstance(v, fw.Parameter)
    ][0]
    xv = np.random.RandomState(0).rand(n, c, h, w).astype("float32")
    o = exe.run(
        feed={"x": xv, "off": np.zeros((n, 2 * k * k, h, w), "float32"),
              "mask": np.ones((n, k * k, h, w), "float32")},
        fetch_list=[out],
    )[0]
    wv = np.asarray(fluid.global_scope().find_var(wname))
    ref = torch.nn.functional.conv2d(
        torch.tensor(xv), torch.tensor(wv), padding=1
    ).numpy()
    np.testing.assert_allclose(o, ref, rtol=1e-4, atol=1e-5)


def test_deformable_conv_integer_offset_shifts():
    """An integer offset of (0, +1) samples one pixel to the right."""
    n, c, h, w, k = 1, 1, 5, 5, 1
    x = fluid.data(name="x", shape=[n, c, h, w], dtype="float32")
    off = fluid.data(name="off", shape=[n, 2, h, w], dtype="float32")
    mask = fluid.data(name="mask", shape=[n, 1, h, w], dtype="float32")
    out = fluid.layers.deformable_conv(
        x, off, mask, num_filters=1, filter_size=1, padding=0,
        bias_attr=False,
        param_attr=fluid.ParamAttr(
            initializer=fluid.initializer.Constant(1.0)),
    )
    exe = _exe()
    exe.run(fluid.default_startup_program())
    xv = np.arange(25, dtype="float32").reshape(1, 1, 5, 5)
    offv = np.zeros((1, 2, 5, 5), "float32")
    offv[0, 1] = 1.0      # dx = +1 (offset pairs are (dy, dx))
    o = exe.run(
        feed={"x": xv, "off": offv,
              "mask": np.ones((1, 1, 5, 5), "float32")},
        fetch_list=[out],
    )[0]
    # interior columns shift left by one; the last column samples x=5 (OOB->0)
    np.testing.assert_allclose(o[0, 0, :, :-1], xv[0, 0, :, 1:], rtol=1e-5)
    np.testing.assert_allclose(o[0, 0, :, -1], 0.0)


def test_psroi_pool_position_sensitive_channels():
    out_c, ph, pw = 2, 2, 2
    c_in = out_c * ph * pw
    x = fluid.data(name="x", shape=[1, c_in, 8, 8], dtype="float32")
    rois = fluid.data(name="rois", shape=[1, 4], dtype="float32")
    out = fluid.layers.psroi_pool(x, rois, out_c, 1.0, ph, pw)
    # each input channel is constant = its channel index
    xv = np.broadcast_to(
        np.arange(c_in, dtype="float32")[None, :, None, None], (1, c_in, 8, 8)
    ).copy()
    o = _exe().run(
        feed={"x": xv, "rois": np.array([[0, 0, 8, 8]], "float32")},
        fetch_list=[out],
    )[0]
    assert o.shape == (1, out_c, ph, pw)
    # out[c, i, j] pools channel c*ph*pw + i*pw + j
    for cc in range(out_c):
        for i in range(ph):
            for j in range(pw):
                assert o[0, cc, i, j] == cc * ph * pw + i * pw + j


def test_prroi_pool_constant_region():
    x = fluid.data(name="x", shape=[1, 1, 8, 8], dtype="float32")
    rois = fluid.data(name="rois", shape=[1, 4], dtype="float32")
    out = fluid.layers.prroi_pool(x, rois, pooled_height=2, pooled_width=2)
    xv = np.full((1, 1, 8, 8), 3.0, "float32")
    o = _exe().run(
        feed={"x": xv, "rois": np.array([[1, 1, 7, 7]], "float32")},
        fetch_list=[out],
    )[0]
    np.testing.assert_allclose(o, 3.0, rtol=1e-4)


class TestDGCMomentum:
    def _run(self, begin_step, steps=4):
        framework.switch_main_program(framework.Program())
        framework.switch_startup_program(framework.Program())
        unique_name.switch()
        fluid.default_startup_program().random_seed = 5
        x = fluid.data(name="x", shape=[None, 8], dtype="float32")
        y = fluid.data(name="y", shape=[None, 1], dtype="float32")
        pred = fluid.layers.fc(x, size=1)
        loss = fluid.layers.reduce_mean(
            fluid.layers.square_error_cost(pred, y)
        )
        opt = fluid.optimizer.DGCMomentumOptimizer(
            learning_rate=0.05, momentum=0.9, rampup_begin_step=begin_step,
            rampup_step=2, sparsity=[0.6, 0.9],
        )
        opt.minimize(loss)
        exe = _exe()
        exe.run(fluid.default_startup_program())
        rs = np.random.RandomState(2)
        feed = {"x": rs.rand(16, 8).astype("float32"),
                "y": rs.rand(16, 1).astype("float32")}
        return [float(exe.run(feed=feed, fetch_list=[loss])[0])
                for _ in range(steps)]

    def test_pre_rampup_matches_plain_momentum(self):
        """With rampup far away, DGC must behave exactly like Momentum."""
        dgc = self._run(begin_step=10 ** 6)
        framework.switch_main_program(framework.Program())
        framework.switch_startup_program(framework.Program())
        unique_name.switch()
        fluid.default_startup_program().random_seed = 5
        x = fluid.data(name="x", shape=[None, 8], dtype="float32")
        y = fluid.data(name="y", shape=[None, 1], dtype="float32")
        pred = fluid.layers.fc(x, size=1)
        loss = fluid.layers.reduce_mean(
            fluid.layers.square_error_cost(pred, y)
        )
        fluid.optimizer.Momentum(0.05, 0.9).minimize(loss)
        exe = _exe()
        exe.run(fluid.default_startup_program())
        rs = np.random.RandomState(2)
        feed = {"x": rs.rand(16, 8).astype("float32"),
                "y": rs.rand(16, 1).astype("float32")}
        plain = [float(exe.run(feed=feed, fetch_list=[loss])[0])
                 for _ in range(4)]
        np.testing.assert_allclose(dgc, plain, rtol=1e-5)

    def test_sparsified_still_converges(self):
        losses = self._run(begin_step=0, steps=12)
        assert losses[-1] < losses[0]
        assert all(np.isfinite(v) for v in losses)
