"""Install introspection (ref: python/paddle/sysconfig.py): paths for
native extension consumers — here the C++ host runtime's directory."""
import os

__all__ = ["get_include", "get_lib"]


def get_include():
    """Directory of the native runtime sources/headers."""
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "native")


def get_lib():
    """Directory containing the built native shared library."""
    return get_include()
