"""ref: python/paddle/check_import_scipy.py — Windows DLL diagnosis for
scipy imports; same contract (no-op unless the import fails on nt)."""

__all__ = ["check_import_scipy"]


def check_import_scipy(OsName):
    if OsName == "nt":
        try:
            import scipy.io  # noqa: F401
        except ImportError as e:
            if "DLL load failed" in str(e):
                raise ImportError(
                    str(e) + "\nplease download visual C++ "
                    "Redistributable from https://www.microsoft.com/"
                    "en-us/download/details.aspx?id=48145"
                )
    return
