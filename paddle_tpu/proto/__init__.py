"""paddle.proto (ref: python/paddle/proto — framework protobuf
definitions: framework_pb2, data_feed_pb2, ...).

Programs here serialize to json (Program.to_json / from_json) instead
of protobufs; accessing a *_pb2 symbol raises with that pointer.
"""

__all__ = []


def __getattr__(name):
    if name.startswith("__"):
        raise AttributeError(name)
    raise NotImplementedError(
        "paddle.proto.%s: ProgramDesc protobufs have no TPU "
        "counterpart — Programs serialize via to_json()/from_json() "
        "(fluid/framework.py), and transpiler.details.program_to_code "
        "gives readable dumps" % name
    )
