"""SSD object detector through the fluid layer API (ref: the reference's
SSD/MobileNet book example built on layers/detection.py multi_box_head +
ssd_loss + detection_output).

TPU notes: the VGG-lite backbone is plain conv2d/pool2d (MXU); priors are
build-time constants; training losses and the NMS inference head are the
static-shape detection ops (no LoD outputs)."""
from .. import fluid
from ..fluid import layers

__all__ = ["build_ssd_train", "build_ssd_infer", "synthetic_batch"]


def _backbone(img):
    """Small VGG-style feature pyramid: returns two feature maps."""
    c = layers.conv2d(img, 32, 3, stride=2, padding=1, act="relu")
    c = layers.conv2d(c, 32, 3, stride=1, padding=1, act="relu")
    f1 = layers.conv2d(c, 64, 3, stride=2, padding=1, act="relu")
    f2 = layers.conv2d(f1, 64, 3, stride=2, padding=1, act="relu")
    return f1, f2


def _head(img, num_classes, image_size):
    f1, f2 = _backbone(img)
    locs, confs, boxes, variances = layers.detection.multi_box_head(
        inputs=[f1, f2],
        image=img,
        base_size=image_size,
        num_classes=num_classes,
        aspect_ratios=[[1.0, 2.0], [1.0, 2.0]],
        min_ratio=20,
        max_ratio=90,
        flip=True,
        offset=0.5,
    )
    return locs, confs, boxes, variances


def build_ssd_train(num_classes=4, image_size=64, max_gt=8):
    """Build the SSD training graph (per-image loss, batch size 1 for the
    gt-matching path; the reference's LoD gt batching maps to fixed
    max_gt padding)."""
    img = fluid.data(name="image", shape=[1, 3, image_size, image_size],
                     dtype="float32")
    gt_box = fluid.data(name="gt_box", shape=[max_gt, 4], dtype="float32")
    gt_label = fluid.data(name="gt_label", shape=[max_gt, 1],
                          dtype="int64")
    locs, confs, boxes, variances = _head(img, num_classes, image_size)
    loc0 = layers.reshape(layers.slice(locs, [0], [0], [1]), [-1, 4])
    conf0 = layers.reshape(
        layers.slice(confs, [0], [0], [1]), [-1, num_classes]
    )
    loss = layers.detection.ssd_loss(
        loc0, conf0, gt_box, gt_label, boxes, variances,
    )
    return {"image": img, "gt_box": gt_box, "gt_label": gt_label,
            "loss": loss}


def build_ssd_infer(num_classes=4, image_size=64, keep_top_k=20):
    """Inference graph: decode + NMS to a static (N, keep_top_k, 6)
    detection tensor [label, score, x1, y1, x2, y2]."""
    img = fluid.data(name="image", shape=[1, 3, image_size, image_size],
                     dtype="float32")
    locs, confs, boxes, variances = _head(img, num_classes, image_size)
    scores = layers.transpose(layers.softmax(confs), [0, 2, 1])
    decoded = layers.detection.box_coder(
        boxes, variances, layers.reshape(locs, [-1, 4]),
        code_type="decode_center_size",
    )
    out = layers.detection.multiclass_nms(
        layers.reshape(decoded, [1, -1, 4]), scores,
        score_threshold=0.01, nms_top_k=100, keep_top_k=keep_top_k,
        nms_threshold=0.45,
    )
    return {"image": img, "detections": out}


def synthetic_batch(rng, image_size=64, max_gt=8, num_classes=4):
    """One synthetic scene: colored rectangles + their boxes/labels."""
    import numpy as np

    img = rng.uniform(0, 0.1, size=(1, 3, image_size, image_size))
    boxes = np.zeros((max_gt, 4), "float32")
    labels = np.zeros((max_gt, 1), "int64")
    n_obj = int(rng.integers(1, 4))
    for i in range(n_obj):
        x0, y0 = rng.uniform(0.05, 0.6, size=2)
        w, h = rng.uniform(0.2, 0.35, size=2)
        x1, y1 = min(x0 + w, 0.95), min(y0 + h, 0.95)
        cls = int(rng.integers(1, num_classes))
        boxes[i] = [x0, y0, x1, y1]
        labels[i] = cls
        xi0, yi0 = int(x0 * image_size), int(y0 * image_size)
        xi1, yi1 = int(x1 * image_size), int(y1 * image_size)
        img[0, cls % 3, yi0:yi1, xi0:xi1] = 0.9
    return (img.astype("float32"), boxes, labels)
