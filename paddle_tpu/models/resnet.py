"""ResNet for ImageNet-style training through the fluid layer API
(parity target: BASELINE.json "ResNet-50 ImageNet (conv2d/batch_norm ops,
ParallelExecutor data-parallel)"; structure per the reference's image
classification book example).

TPU notes: NCHW convs lower to lax.conv_general_dilated (MXU); batch-norm
running stats ride the persistable state through the one jitted step.
"""
from .. import fluid
from ..fluid import layers
from ..fluid.param_attr import ParamAttr

__all__ = ["resnet", "resnet50", "build_resnet_train"]

_DEPTH_CFG = {
    18: ([2, 2, 2, 2], "basic"),
    34: ([3, 4, 6, 3], "basic"),
    50: ([3, 4, 6, 3], "bottleneck"),
    101: ([3, 4, 23, 3], "bottleneck"),
    152: ([3, 8, 36, 3], "bottleneck"),
}


def _conv_bn(x, num_filters, filter_size, stride=1, act=None, name=None):
    conv = layers.conv2d(
        input=x,
        num_filters=num_filters,
        filter_size=filter_size,
        stride=stride,
        padding=(filter_size - 1) // 2,
        bias_attr=False,
        param_attr=ParamAttr(name=name + ".conv.w"),
        name=name,
    )
    return layers.batch_norm(
        conv,
        act=act,
        param_attr=ParamAttr(name=name + ".bn.scale"),
        bias_attr=ParamAttr(name=name + ".bn.bias"),
        moving_mean_name=name + ".bn.mean",
        moving_variance_name=name + ".bn.var",
    )


def _shortcut(x, out_ch, stride, name):
    in_ch = x.shape[1]
    if in_ch != out_ch or stride != 1:
        return _conv_bn(x, out_ch, 1, stride, name=name + ".short")
    return x


def _bottleneck(x, num_filters, stride, name):
    c1 = _conv_bn(x, num_filters, 1, 1, act="relu", name=name + ".c1")
    c2 = _conv_bn(c1, num_filters, 3, stride, act="relu", name=name + ".c2")
    c3 = _conv_bn(c2, num_filters * 4, 1, 1, act=None, name=name + ".c3")
    short = _shortcut(x, num_filters * 4, stride, name)
    return layers.elementwise_add(short, c3, act="relu")


def _basic(x, num_filters, stride, name):
    c1 = _conv_bn(x, num_filters, 3, stride, act="relu", name=name + ".c1")
    c2 = _conv_bn(c1, num_filters, 3, 1, act=None, name=name + ".c2")
    short = _shortcut(x, num_filters, stride, name)
    return layers.elementwise_add(short, c2, act="relu")


def resnet(img, class_num=1000, depth=50):
    """img: (B, 3, H, W) → logits (B, class_num)."""
    blocks, kind = _DEPTH_CFG[depth]
    x = _conv_bn(img, 64, 7, 2, act="relu", name="stem")
    x = layers.pool2d(x, pool_size=3, pool_stride=2, pool_padding=1,
                      pool_type="max")
    num_filters = [64, 128, 256, 512]
    block_fn = _bottleneck if kind == "bottleneck" else _basic
    for stage, n_blocks in enumerate(blocks):
        for b in range(n_blocks):
            stride = 2 if b == 0 and stage > 0 else 1
            x = block_fn(
                x, num_filters[stage], stride,
                name="s%d.b%d" % (stage, b),
            )
    x = layers.pool2d(x, pool_type="avg", global_pooling=True)
    x = layers.flatten(x)
    logits = layers.fc(
        input=x, size=class_num,
        param_attr=ParamAttr(name="fc.w"),
        bias_attr=ParamAttr(name="fc.b"),
    )
    return logits


def resnet50(img, class_num=1000):
    return resnet(img, class_num, 50)


def build_resnet_train(depth=50, class_num=1000, image_size=224):
    img = fluid.data(name="image", shape=[None, 3, image_size, image_size],
                     dtype="float32")
    label = fluid.data(name="label", shape=[None, 1], dtype="int64")
    logits = resnet(img, class_num, depth)
    loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
    acc = layers.accuracy(layers.softmax(logits), label)
    return {"image": img, "label": label, "logits": logits,
            "loss": loss, "acc": acc}
