"""Shared incremental-decode machinery for KV-cache decoder cells
(transformer_nmt.TransformerDecodeCell and gpt.GPTDecodeCell).

One decode step at position ``pos`` needs three masks derived from the
static cache length ``tmax``: a one-hot cache-write selector, its
complement, and the <=pos additive visibility mask. Keeping them (and
the head-split attention) here means a fix to the cache-write or
masking logic lands in every decoder at once.
"""
from paddle_tpu.fluid import layers

__all__ = ["attend", "split_heads", "step_masks", "update_cache"]


def split_heads(t, heads, dh):
    """(B, T, heads*dh) -> (B, heads, T, dh). Reshape + transpose on a
    contiguous input — XLA folds the permutation into the consuming
    dot_general. Replacing BERT's mid-axis slice+squeeze formulation
    with this cut HLO copy traffic 27% per step and measured +2-6%
    (BENCHMARKS round 5)."""
    t = layers.reshape(t, [0, 0, heads, dh])
    return layers.transpose(t, [0, 2, 1, 3])


def attend(q, k, v, mask, heads, hidden):
    """q (B,Tq,H), k/v (B,Tk,H), additive mask broadcastable to
    (B,nh,Tq,Tk) -> context (B,Tq,H)."""
    dh = hidden // heads

    def split(t):
        return split_heads(t, heads, dh)

    scores = layers.matmul(split(q), split(k), transpose_y=True,
                           alpha=dh ** -0.5)
    if mask is not None:
        scores = layers.elementwise_add(scores, mask)
    ctx = layers.matmul(layers.softmax(scores), split(v))
    ctx = layers.transpose(ctx, [0, 2, 1, 3])
    return layers.reshape(ctx, [0, 0, hidden])


def step_masks(pos, tmax):
    """For a (B, 1) int64 position: returns (write3, keep3, self_mask)
    — the (B, T, 1) one-hot cache-write selector, its complement, and
    the (B, 1, 1, T) additive mask hiding positions > pos."""
    steps = layers.unsqueeze(
        layers.range(0, tmax, 1, "int64"), [0])          # (1, T)
    write = layers.cast(layers.equal(steps, pos), "float32")
    write3 = layers.unsqueeze(write, [2])                # (B, T, 1)
    keep3 = layers.scale(write3, scale=-1.0, bias=1.0)
    seen = layers.cast(
        layers.less_equal(steps, pos), "float32")        # (B, T)
    self_mask = layers.scale(seen, scale=1e9, bias=-1e9)
    self_mask = layers.unsqueeze(self_mask, [1, 2])      # (B, 1, 1, T)
    return write3, keep3, self_mask


def update_cache(cache, new_t, write3=None, keep3=None, pos=None,
                 per_row=False):
    """Write the (B, 1, H) step value into the (B, T, H) cache.

    With ``pos`` (the (B, 1) decode position) this is an O(B·H)
    dynamic-update-slice write: uniform across the batch by default
    (every row advances one token per scan step, as in the full-batch
    decoders here), or an independent position per row with
    ``per_row=True`` (slotted continuous-batching decode, where a
    freshly prefilled slot sits at its prompt length while neighbours
    are deep into generation). Without ``pos``, the one-hot masked
    rewrite (``write3``/``keep3`` from :func:`step_masks`) re-reads and
    re-writes the whole cache — kept for callers with neither."""
    if pos is not None:
        from paddle_tpu.fluid.layer_helper import LayerHelper

        helper = LayerHelper("decode_cache_write")
        out = helper.create_variable_for_type_inference(dtype=cache.dtype)
        out.shape = cache.shape
        helper.append_op(
            type="decode_cache_write",
            inputs={"Cache": [cache], "Value": [new_t], "Pos": [pos]},
            outputs={"Out": [out]},
            attrs={"per_row": bool(per_row)},
        )
        return out
    if write3 is None or keep3 is None:
        raise ValueError(
            "update_cache needs either pos (uniform-position fast "
            "path) or the write3/keep3 masks from step_masks")
    return layers.elementwise_add(
        layers.elementwise_mul(cache, keep3),
        layers.elementwise_mul(new_t, write3))
