"""MNIST models (parity: reference book ch.2 / fluid tests recognize_digits)."""
from .. import fluid
from ..fluid import layers


def mlp(img, label, hidden=200):
    h = layers.fc(input=img, size=hidden, act="relu")
    h = layers.fc(input=h, size=hidden, act="relu")
    logits = layers.fc(input=h, size=10)
    loss = layers.mean(
        layers.softmax_with_cross_entropy(logits, label)
    )
    acc = layers.accuracy(input=layers.softmax(logits), label=label)
    return loss, acc, logits


def conv_net(img, label):
    """LeNet-style conv net; img (B, 1, 28, 28)."""
    from ..fluid import nets

    c1 = nets.simple_img_conv_pool(
        input=img, filter_size=5, num_filters=20, pool_size=2,
        pool_stride=2, act="relu",
    )
    c1 = layers.batch_norm(c1)
    c2 = nets.simple_img_conv_pool(
        input=c1, filter_size=5, num_filters=50, pool_size=2,
        pool_stride=2, act="relu",
    )
    logits = layers.fc(input=layers.flatten(c2), size=10)
    loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
    acc = layers.accuracy(input=layers.softmax(logits), label=label)
    return loss, acc, logits
