"""BERT-base pretraining model, built through the paddle_tpu.fluid layer API
(parity target: the reference's transformer_encoder + fused_adam BERT config
in BASELINE.json; layer structure per python/paddle/fluid book examples).

TPU-first choices:
- whole encoder is one Program → one XLA module; attention is plain batched
  matmul+softmax which XLA fuses into an MXU-resident flash-like schedule
- parameters are named so tensor-parallel ShardingRules can target them
  (qkv/ffn1 column-sharded, attnout/ffn2 row-sharded over the 'tp' axis)
- compute dtype bf16 via contrib.mixed_precision, master weights fp32
"""
import numpy as np

from .. import fluid
from ..fluid import layers
from ..fluid.param_attr import ParamAttr
from jax.sharding import PartitionSpec as P

__all__ = ["BertConfig", "build_bert_pretrain", "tp_rules", "bert_base",
           "bert_tiny"]


class BertConfig:
    def __init__(self, vocab_size=30522, hidden=768, num_layers=12, heads=12,
                 ffn=3072, max_seq=512, type_vocab=2, dropout=0.1,
                 use_fused_attention=True):
        self.vocab_size = vocab_size
        self.hidden = hidden
        self.num_layers = num_layers
        self.heads = heads
        self.ffn = ffn
        self.max_seq = max_seq
        self.type_vocab = type_vocab
        self.dropout = dropout
        # fused_multihead_attention op (pallas flash kernels on TPU); the
        # unfused path keeps the reference-shaped matmul/softmax graph
        self.use_fused_attention = use_fused_attention


def bert_base():
    return BertConfig()


def bert_tiny(seq=64):
    return BertConfig(vocab_size=1024, hidden=64, num_layers=2, heads=4,
                      ffn=128, max_seq=seq, dropout=0.0)


def _attn_name(i, part):
    return "enc_l%d_%s" % (i, part)


def _encoder_layer(x, cfg, i, attn_mask, is_test):
    """One post-LN transformer encoder layer (B, T, H)."""
    h = cfg.hidden
    nh = cfg.heads
    dh = h // nh
    qkv = layers.fc(
        input=x,
        size=3 * h,
        num_flatten_dims=2,
        param_attr=ParamAttr(name=_attn_name(i, "qkv.w")),
        bias_attr=ParamAttr(name=_attn_name(i, "qkv.b")),
    )
    # (B, T, 3H): split by CONTIGUOUS last-axis slices, then head-split
    # each (B, T, H) piece. The earlier reshape-to-(B,T,3,nh,dh) +
    # mid-axis slice + squeeze chain cost 27% more HLO copy traffic and
    # worse attention-region fusion (BENCHMARKS round 5: b48 +2%, s512
    # +5.6% from this change).
    from .decode_utils import split_heads

    def _split(part, idx):
        p = layers.slice(part, axes=[2], starts=[idx * h],
                         ends=[(idx + 1) * h])            # (B, T, H)
        return split_heads(p, nh, dh)                     # (B,nh,T,dh)

    q = _split(qkv, 0)
    k = _split(qkv, 1)
    v = _split(qkv, 2)
    if getattr(cfg, "use_fused_attention", False) and attn_mask is None:
        ctxv = layers.fused_multihead_attention(
            q, k, v, dropout_rate=cfg.dropout if not is_test else 0.0,
        )                                                # (B,nh,T,dh)
    else:
        scores = layers.matmul(q, k, transpose_y=True, alpha=dh ** -0.5)
        if attn_mask is not None:
            scores = layers.elementwise_add(scores, attn_mask)
        probs = layers.softmax(scores)
        if cfg.dropout and not is_test:
            probs = layers.dropout(
                probs, cfg.dropout, dropout_implementation="upscale_in_train"
            )
        ctxv = layers.matmul(probs, v)                   # (B,nh,T,dh)
    ctxv = layers.transpose(ctxv, [0, 2, 1, 3])          # (B,T,nh,dh)
    ctxv = layers.reshape(ctxv, [0, 0, h])
    attn_out = layers.fc(
        input=ctxv,
        size=h,
        num_flatten_dims=2,
        param_attr=ParamAttr(name=_attn_name(i, "attnout.w")),
        bias_attr=ParamAttr(name=_attn_name(i, "attnout.b")),
    )
    if cfg.dropout and not is_test:
        attn_out = layers.dropout(
            attn_out, cfg.dropout,
            dropout_implementation="upscale_in_train",
        )
    x = layers.layer_norm(
        layers.elementwise_add(x, attn_out),
        begin_norm_axis=2,
        param_attr=ParamAttr(name=_attn_name(i, "ln1.w")),
        bias_attr=ParamAttr(name=_attn_name(i, "ln1.b")),
    )
    ff1 = layers.fc(
        input=x,
        size=cfg.ffn,
        num_flatten_dims=2,
        act="gelu",
        param_attr=ParamAttr(name=_attn_name(i, "ffn1.w")),
        bias_attr=ParamAttr(name=_attn_name(i, "ffn1.b")),
    )
    ff2 = layers.fc(
        input=ff1,
        size=h,
        num_flatten_dims=2,
        param_attr=ParamAttr(name=_attn_name(i, "ffn2.w")),
        bias_attr=ParamAttr(name=_attn_name(i, "ffn2.b")),
    )
    if cfg.dropout and not is_test:
        ff2 = layers.dropout(
            ff2, cfg.dropout, dropout_implementation="upscale_in_train"
        )
    return layers.layer_norm(
        layers.elementwise_add(x, ff2),
        begin_norm_axis=2,
        param_attr=ParamAttr(name=_attn_name(i, "ln2.w")),
        bias_attr=ParamAttr(name=_attn_name(i, "ln2.b")),
    )


def build_bert_pretrain(cfg, seq_len, is_test=False):
    """Build the MLM pretraining graph in the current default programs.
    Returns dict of the interface variables."""
    ids = fluid.data(name="input_ids", shape=[None, seq_len], dtype="int64")
    mlm_labels = fluid.data(name="mlm_labels", shape=[None, seq_len], dtype="int64")
    emb = layers.embedding(
        ids,
        size=[cfg.vocab_size, cfg.hidden],
        param_attr=ParamAttr(name="word_emb"),
    )
    # positions 0..T-1 added via a learned pos table, sliced to seq_len
    pos_table = layers.create_parameter(
        shape=[cfg.max_seq, cfg.hidden],
        dtype="float32",
        name="pos_emb",
    )
    pos_slice = layers.slice(pos_table, axes=[0], starts=[0], ends=[seq_len])
    x = layers.elementwise_add(emb, layers.unsqueeze(pos_slice, [0]))
    x = layers.layer_norm(
        x, begin_norm_axis=2,
        param_attr=ParamAttr(name="emb_ln.w"),
        bias_attr=ParamAttr(name="emb_ln.b"),
    )
    if cfg.dropout and not is_test:
        x = layers.dropout(
            x, cfg.dropout, dropout_implementation="upscale_in_train"
        )
    for i in range(cfg.num_layers):
        x = _encoder_layer(x, cfg, i, None, is_test)
    # MLM head: tied output embedding
    word_emb_var = fluid.default_main_program().global_block().var("word_emb")
    logits = layers.matmul(x, word_emb_var, transpose_y=True)
    loss = layers.softmax_with_cross_entropy(
        logits, layers.unsqueeze(mlm_labels, [2]), ignore_index=-1
    )
    mean_loss = layers.mean(loss)
    return {
        "input_ids": ids,
        "mlm_labels": mlm_labels,
        "encoder_out": x,
        "logits": logits,
        "loss": mean_loss,
    }


def tp_rules():
    """Tensor-parallel sharding rules for the BERT parameter naming above:
    column-shard qkv/ffn1 (+ their biases), row-shard attnout/ffn2,
    vocab-shard the embedding."""
    return [
        (r"enc_l\d+_qkv\.w", P(None, "tp")),
        (r"enc_l\d+_qkv\.b", P("tp")),
        (r"enc_l\d+_ffn1\.w", P(None, "tp")),
        (r"enc_l\d+_ffn1\.b", P("tp")),
        (r"enc_l\d+_attnout\.w", P("tp", None)),
        (r"enc_l\d+_ffn2\.w", P("tp", None)),
        (r"word_emb", P("tp", None)),
    ]


def synthetic_batch(cfg, batch, seq_len, seed=0):
    rng = np.random.default_rng(seed)
    ids = rng.integers(0, cfg.vocab_size, size=(batch, seq_len), dtype=np.int64)
    labels = ids.copy()
    # mask 15%: label kept, input replaced by token 0 ("[MASK]")
    mask = rng.random((batch, seq_len)) < 0.15
    ids[mask] = 0
    labels[~mask] = -1
    return ids, labels
