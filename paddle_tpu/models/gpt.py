"""GPT-style decoder-only causal LM with KV-cache generation.

Beyond-survey model family (round 5): the reference era shipped
encoder-only (BERT-style) and encoder-decoder (Transformer-NMT) zoo
models; this adds the decoder-only LM pattern users expect — training
graph with a causal mask, and fixed-length incremental generation
(greedy or top-k sampling) through the same dynamic_decode machinery
as NMT beam search (one lax.scan, static shapes, per-layer KV caches).

Training and generation share parameter names, so a trained scope
drives generation directly. Generation is fixed-length (prompt_len +
max_new positions); eos handling is caller-side truncation — a
data-dependent early exit would break the single static scan that
makes TPU decode fast.
"""
import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers
from paddle_tpu.fluid.param_attr import ParamAttr

__all__ = ["GPTConfig", "gpt_tiny", "build_gpt_lm", "GPTDecodeCell",
           "SamplingDecoder", "build_gpt_generate", "build_gpt_prefill",
           "build_gpt_prefill_delta", "build_gpt_verify_block",
           "build_gpt_decode_step", "build_gpt_decode_step_q",
           "tp_rules", "synthetic_lm_batch"]


class GPTConfig:
    def __init__(self, vocab=32000, hidden=768, num_layers=12, heads=12,
                 ffn=3072, max_len=1024, dropout=0.1):
        self.vocab = vocab
        self.hidden = hidden
        self.num_layers = num_layers
        self.heads = heads
        self.ffn = ffn
        self.max_len = max_len
        self.dropout = dropout


def gpt_tiny(vocab=211, max_len=64):
    return GPTConfig(vocab=vocab, hidden=32, num_layers=2, heads=2,
                     ffn=64, max_len=max_len, dropout=0.0)


def _p(name):
    return ParamAttr(name=name)


def _ln(x, name):
    return layers.layer_norm(x, begin_norm_axis=len(x.shape) - 1,
                             param_attr=_p(name + ".w"),
                             bias_attr=_p(name + ".b"))


def _proj(x, size, name, nfd=2):
    return layers.fc(x, size, num_flatten_dims=nfd,
                     param_attr=_p(name + ".w"), bias_attr=_p(name + ".b"))


def _attend(cfg, q, k, v, mask):
    from .decode_utils import attend

    return attend(q, k, v, mask, cfg.heads, cfg.hidden)


def _block_kv(x, cfg, i, mask, is_test):
    """One transformer block exposing its k/v projections — the prefill
    program captures them as the slot's KV cache. Op order matches
    :func:`_block` exactly (q, k, v projections in that order), so the
    factoring cannot perturb trained-weight numerics."""
    n = "gpt%d" % i
    q = _proj(x, cfg.hidden, n + ".self.q")
    k = _proj(x, cfg.hidden, n + ".self.k")
    v = _proj(x, cfg.hidden, n + ".self.v")
    attn = _proj(_attend(cfg, q, k, v, mask), cfg.hidden, n + ".self.o")
    if cfg.dropout and not is_test:
        attn = layers.dropout(attn, dropout_prob=cfg.dropout)
    x = _ln(layers.elementwise_add(x, attn), n + ".ln1")
    h = _proj(x, cfg.ffn, n + ".ffn.fc1")
    h = layers.gelu(h)
    h = _proj(h, cfg.hidden, n + ".ffn.fc2")
    if cfg.dropout and not is_test:
        h = layers.dropout(h, dropout_prob=cfg.dropout)
    return _ln(layers.elementwise_add(x, h), n + ".ln2"), k, v


def _block(x, cfg, i, mask, is_test):
    return _block_kv(x, cfg, i, mask, is_test)[0]


def _embed(ids, cfg, seq_len):
    """Token + learned position embeddings -> (B, T, H)."""
    tok = layers.embedding(ids, size=[cfg.vocab, cfg.hidden],
                           param_attr=_p("gpt_tok_emb"))
    tok = layers.reshape(tok, [-1, seq_len, cfg.hidden])
    pos_table = layers.create_parameter(
        shape=[cfg.max_len, cfg.hidden], dtype="float32",
        name="gpt_pos_emb")
    pos = layers.slice(pos_table, axes=[0], starts=[0], ends=[seq_len])
    return layers.elementwise_add(tok, layers.unsqueeze(pos, [0]))


def build_gpt_lm(cfg, seq_len, is_test=False):
    """Next-token LM training graph: feeds gpt_ids (B, T) and
    gpt_labels (B, T); loss is the mean causal cross-entropy."""
    ids = fluid.data("gpt_ids", shape=[None, seq_len], dtype="int64")
    labels = fluid.data("gpt_labels", shape=[None, seq_len],
                        dtype="int64")
    x = _embed(ids, cfg, seq_len)
    # causal visibility: position t sees <= t
    steps = layers.range(0, seq_len, 1, "int64")
    seen = layers.cast(
        layers.less_equal(layers.unsqueeze(steps, [0]),
                          layers.unsqueeze(steps, [1])), "float32")
    mask = layers.scale(seen, scale=1e9, bias=-1e9)      # (T, T)
    mask = layers.unsqueeze(mask, [0, 1])                # (1, 1, T, T)
    for i in range(cfg.num_layers):
        x = _block(x, cfg, i, mask, is_test)
    logits = _proj(x, cfg.vocab, "gpt_out")              # (B, T, V)
    flat = layers.reshape(logits, [-1, cfg.vocab])
    loss = layers.mean(layers.softmax_with_cross_entropy(
        flat, layers.reshape(labels, [-1, 1])))
    return {"ids": ids, "labels": labels, "logits": logits,
            "loss": loss}


class GPTDecodeCell:
    """One incremental decode step with per-layer KV caches (the
    decoder-only sibling of transformer_nmt.TransformerDecodeCell).

    States: ``[pos (B,1) int64, k0, v0, k1, v1, ...]`` with each cache
    (B, tmax, hidden). Parameter names match build_gpt_lm, so trained
    weights generate directly."""

    def __init__(self, cfg, tmax):
        self.cfg = cfg
        self.tmax = tmax

    def call(self, inputs, states):
        from .decode_utils import step_masks, update_cache

        cfg = self.cfg
        h = cfg.hidden
        pos, caches = states[0], states[1:]
        pos_table = layers.create_parameter(
            shape=[cfg.max_len, h], dtype="float32", name="gpt_pos_emb")
        x = layers.elementwise_add(
            inputs, layers.gather_nd(pos_table, pos))    # (B, H)
        x = layers.unsqueeze(x, [1])                      # (B, 1, H)

        _w3, _k3, self_mask = step_masks(pos, self.tmax)  # masks dead on the pos fast path (DCE'd)

        new_caches = []
        for i in range(cfg.num_layers):
            n = "gpt%d" % i
            q = _proj(x, h, n + ".self.q")
            k_cache = update_cache(caches[2 * i],
                                   _proj(x, h, n + ".self.k"),
                                   pos=pos)
            v_cache = update_cache(caches[2 * i + 1],
                                   _proj(x, h, n + ".self.v"),
                                   pos=pos)
            new_caches += [k_cache, v_cache]
            attn = _proj(_attend(cfg, q, k_cache, v_cache, self_mask),
                         h, n + ".self.o")
            x = _ln(layers.elementwise_add(x, attn), n + ".ln1")
            f = _proj(x, cfg.ffn, n + ".ffn.fc1")
            f = layers.gelu(f)
            f = _proj(f, h, n + ".ffn.fc2")
            x = _ln(layers.elementwise_add(x, f), n + ".ln2")

        logits = _proj(layers.squeeze(x, [1]), cfg.vocab, "gpt_out",
                       nfd=1)
        one = layers.fill_constant([1], "int64", 1)
        return logits, [layers.elementwise_add(pos, one)] + new_caches

    def __call__(self, inputs, states, **kwargs):
        return self.call(inputs, states)


class SamplingDecoder(layers.Decoder):
    """Greedy / top-k sampling generation with prompt teacher-forcing.

    Step t consumes the token at position t and emits the token chosen
    for position t+1; while t+1 is still inside the prompt the choice
    is overridden by the prompt token, so caches are prefilled within
    the SAME scan that generates (no separate prefill program)."""

    def __init__(self, cell, prompt, prompt_len, mode="greedy",
                 topk=10, temperature=1.0):
        if mode not in ("greedy", "topk"):
            raise ValueError("mode must be 'greedy' or 'topk'")
        self.cell = cell
        self.prompt = prompt          # (B, prompt_len) int64
        self.prompt_len = int(prompt_len)
        self.mode = mode
        self.topk = int(topk)
        self.temperature = float(temperature)
        cfg = cell.cfg
        self._embed = lambda ids: layers.reshape(
            layers.embedding(ids, size=[cfg.vocab, cfg.hidden],
                             param_attr=_p("gpt_tok_emb")),
            [-1, cfg.hidden])
        # (plen, B): per-step gather of the forced token by time index
        self._prompt_t = layers.transpose(prompt, [1, 0])

    def _prompt_tok(self, idx):
        """Prompt column ``idx`` (clipped) as (B, 1) int64."""
        last = layers.fill_constant([1], "int64", self.prompt_len - 1)
        idx = layers.elementwise_min(idx, last)
        col = layers.gather(self._prompt_t, idx)          # (1, B)
        return layers.transpose(col, [1, 0])              # (B, 1)

    def initialize(self, inits):
        first = self._prompt_tok(layers.fill_constant([1], "int64", 0))
        finished = layers.cast(
            layers.zeros_like(layers.cast(first, "float32")), "bool")
        return self._embed(first), inits, finished

    def step(self, time, inputs, states, **kwargs):
        logits, next_states = self.cell(inputs, states)   # (B, V)
        if self.mode == "greedy":
            chosen = layers.unsqueeze(
                layers.argmax(logits, axis=-1), [1])      # (B, 1)
        else:
            vals, idx = layers.topk(logits, k=self.topk)
            probs = layers.softmax(
                layers.scale(vals, scale=1.0 / self.temperature))
            j = layers.sampling_id(probs)                 # (B,)
            j2 = layers.unsqueeze(layers.cast(j, "int64"), [1])
            chosen = layers.cast(_gather_rowwise(idx, j2), "int64")
        chosen = layers.cast(chosen, "int64")
        # teacher-force while t+1 is still a prompt position
        one = layers.fill_constant([1], "int64", 1)
        nxt = layers.elementwise_add(time, one)           # (1,)
        plen = layers.fill_constant([1], "int64", self.prompt_len)
        forced = layers.cast(layers.less_than(nxt, plen), "int64")
        tok = layers.elementwise_add(
            layers.elementwise_mul(self._prompt_tok(nxt), forced),
            layers.elementwise_mul(
                chosen, layers.elementwise_sub(one, forced)))
        finished = layers.cast(
            layers.zeros_like(layers.cast(tok, "float32")), "bool")
        return tok, next_states, self._embed(tok), finished


def _gather_rowwise(x, j):
    """x (B, K), j (B, 1) int64 -> x[b, j[b]] as (B, 1)."""
    ones = layers.fill_constant_batch_size_like(
        input=j, shape=[-1, 1], dtype="float32", value=1.0)
    rows = layers.cast(
        layers.cumsum(ones, axis=0, exclusive=True), "int64")
    coords = layers.concat([rows, j], axis=1)             # (B, 2)
    return layers.unsqueeze(layers.gather_nd(x, coords), [1])


def build_gpt_generate(cfg, prompt_len, max_new, mode="greedy",
                       topk=10, temperature=1.0):
    """Fixed-length generation graph. Feeds gpt_prompt (B, prompt_len);
    returns ids (B, prompt_len + max_new - 1): positions 1..plen-1 echo
    the prompt (teacher-forced), the rest are generated."""
    tmax = prompt_len + max_new
    if tmax > cfg.max_len:
        raise ValueError("prompt_len + max_new (%d) exceeds cfg.max_len "
                         "(%d)" % (tmax, cfg.max_len))
    prompt = fluid.data("gpt_prompt", shape=[None, prompt_len],
                        dtype="int64")
    cell = GPTDecodeCell(cfg, tmax)
    decoder = SamplingDecoder(cell, prompt, prompt_len, mode=mode,
                              topk=topk, temperature=temperature)
    pos0 = layers.fill_constant_batch_size_like(
        prompt, shape=[-1, 1], dtype="int64", value=0)
    inits = [pos0]
    for _ in range(cfg.num_layers):
        for _ in ("k", "v"):
            inits.append(layers.fill_constant_batch_size_like(
                prompt, shape=[-1, tmax, cfg.hidden], dtype="float32",
                value=0.0))
    ids, _ = layers.dynamic_decode(
        decoder, inits=inits, max_step_num=prompt_len + max_new - 2)
    ids = layers.squeeze(ids, [2])                        # (B, steps)
    return {"prompt": prompt, "ids": ids}


def _row_coords(col):
    """(B, 1) int64 column indices -> (B, 2) gather_nd coords
    ``[row, col]`` (row = 0..B-1 via the cumsum trick)."""
    ones = layers.fill_constant_batch_size_like(
        input=col, shape=[-1, 1], dtype="float32", value=1.0)
    rows = layers.cast(
        layers.cumsum(ones, axis=0, exclusive=True), "int64")
    return layers.concat([rows, col], axis=1)


def build_gpt_prefill(cfg, prompt_len, cache_len):
    """Slot-prefill program for continuous-batching decode: one parallel
    pass over a (right-padded) prompt bucket that writes a slot's KV
    cache and emits the first generated token.

    Feeds ``gpt_prefill_ids`` (B, prompt_len) int64 — prompts right-
    padded to the bucket with any token — and ``gpt_prefill_len``
    (B, 1) int64, the real lengths. The batch dim is a *slot* dim:
    every row is an independent sequence. Padded positions are causally
    invisible to real ones and their k/v rows are zeroed, so the cache
    leaving this program is bit-identical to feeding the prompt through
    the incremental decoder one token at a time (what
    :func:`build_gpt_generate`'s teacher-forced scan does).

    Returns vars: ``ids``/``len`` feeds, ``next`` (B, 1) int64 — the
    greedy token for position ``len`` — plus ``k``/``v``
    (B, num_layers, cache_len, hidden) slot caches (positions >=
    ``len`` are zero; the decode step writes them one per step).
    """
    if not (1 <= prompt_len <= cache_len):
        raise ValueError(
            "need 1 <= prompt_len (%d) <= cache_len (%d)"
            % (prompt_len, cache_len))
    if cache_len > cfg.max_len:
        raise ValueError("cache_len (%d) exceeds cfg.max_len (%d)"
                         % (cache_len, cfg.max_len))
    ids = fluid.data("gpt_prefill_ids", shape=[None, prompt_len],
                     dtype="int64")
    plen = fluid.data("gpt_prefill_len", shape=[None, 1], dtype="int64")
    x = _embed(ids, cfg, prompt_len)
    steps = layers.range(0, prompt_len, 1, "int64")
    steps0 = layers.unsqueeze(steps, [0])                 # (1, P)
    seen = layers.cast(
        layers.less_equal(steps0,
                          layers.unsqueeze(steps, [1])), "float32")
    mask = layers.scale(seen, scale=1e9, bias=-1e9)       # (P, P)
    mask = layers.unsqueeze(mask, [0, 1])                 # (1, 1, P, P)
    # rows >= len are pad: zero their k/v so the cache handed to the
    # step program matches the incremental fill (zeros beyond pos)
    valid = layers.cast(layers.less_than(steps0, plen), "float32")
    valid3 = layers.unsqueeze(valid, [2])                 # (B, P, 1)
    ks, vs = [], []
    for i in range(cfg.num_layers):
        x, k, v = _block_kv(x, cfg, i, mask, is_test=True)
        ks.append(layers.elementwise_mul(k, valid3))
        vs.append(layers.elementwise_mul(v, valid3))
    if cache_len > prompt_len:
        pad = layers.fill_constant_batch_size_like(
            ids, shape=[-1, cache_len - prompt_len, cfg.hidden],
            dtype="float32", value=0.0)
        ks = [layers.concat([k, pad], axis=1) for k in ks]
        vs = [layers.concat([v, pad], axis=1) for v in vs]
    k_cache = layers.stack(ks, axis=1)   # (B, L, cache_len, H)
    v_cache = layers.stack(vs, axis=1)
    one = layers.fill_constant([1], "int64", 1)
    last = layers.elementwise_sub(plen, one)              # (B, 1)
    x_last = layers.gather_nd(x, _row_coords(last))       # (B, H)
    logits = _proj(x_last, cfg.vocab, "gpt_out", nfd=1)
    nxt = layers.cast(
        layers.unsqueeze(layers.argmax(logits, axis=-1), [1]), "int64")
    return {"ids": ids, "len": plen, "next": nxt, "logits": logits,
            "k": k_cache, "v": v_cache,
            "feed_names": ["gpt_prefill_ids", "gpt_prefill_len"],
            "fetch_vars": [nxt, k_cache, v_cache]}


def build_gpt_prefill_delta(cfg, suffix_len, cache_len):
    """Delta-prefill program: extend an ALREADY-prefilled KV cache by a
    (right-padded) prompt suffix in one parallel pass — the prefix-cache
    fast path. Where :func:`build_gpt_prefill` computes every prompt
    row, this one adopts ``start`` rows verbatim from a cached prefix
    (a :class:`~paddle_tpu.serving.prefix_pool.PrefixPool` hit or a
    hibernated session's wire payload) and computes only the suffix
    rows, so shared-prefix traffic pays prefill FLOPs proportional to
    the UNSHARED tail.

    Feeds: ``gpt_dpre_ids`` (B, suffix_len) int64 suffix tokens right-
    padded with any token, ``gpt_dpre_len`` (B, 1) int64 real suffix
    lengths, ``gpt_dpre_start`` (B, 1) int64 adopted-prefix lengths
    (suffix token i sits at absolute position ``start + i``), and the
    adopted fp32 base caches ``gpt_dpre_k`` / ``gpt_dpre_v``
    (B, num_layers, cache_len, hidden) — rows >= ``start`` are ignored
    and overwritten. The caller must guarantee ``start + suffix_len <=
    cache_len`` (dynamic_update_slice clamps out-of-range starts, which
    would silently corrupt adopted rows).

    Bit-exactness: suffix row ``start + i`` attends over adopted rows
    ``<= start + i`` with the same exact-zero masked-softmax padding as
    the cold prefill, and adopted rows are bit-identical to what a cold
    prefill of the full prompt computes for those positions (the
    prefill-vs-incremental parity the decode tests already pin), so
    ``next`` and the outgoing cache match the cold path bit-for-bit.

    Returns vars ``next`` (B, 1) int64 — the greedy token for position
    ``start + len`` — and the full updated ``k``/``v`` caches.
    """
    from .decode_utils import update_cache

    if not (1 <= suffix_len <= cache_len):
        raise ValueError(
            "need 1 <= suffix_len (%d) <= cache_len (%d)"
            % (suffix_len, cache_len))
    if cache_len > cfg.max_len:
        raise ValueError("cache_len (%d) exceeds cfg.max_len (%d)"
                         % (cache_len, cfg.max_len))
    h = cfg.hidden
    nl = cfg.num_layers
    ids = fluid.data("gpt_dpre_ids", shape=[None, suffix_len],
                     dtype="int64")
    slen = fluid.data("gpt_dpre_len", shape=[None, 1], dtype="int64")
    start = fluid.data("gpt_dpre_start", shape=[None, 1], dtype="int64")
    k_all = fluid.data("gpt_dpre_k", shape=[None, nl, cache_len, h],
                       dtype="float32")
    v_all = fluid.data("gpt_dpre_v", shape=[None, nl, cache_len, h],
                       dtype="float32")
    steps = layers.range(0, suffix_len, 1, "int64")
    steps0 = layers.unsqueeze(steps, [0])                 # (1, P)
    pos_idx = layers.elementwise_add(steps0, start)       # (B, P) abs pos
    tok = layers.reshape(
        layers.embedding(ids, size=[cfg.vocab, h],
                         param_attr=_p("gpt_tok_emb")),
        [-1, suffix_len, h])
    pos_table = layers.create_parameter(
        shape=[cfg.max_len, h], dtype="float32", name="gpt_pos_emb")
    pe = layers.reshape(
        layers.gather_nd(pos_table, layers.reshape(pos_idx, [-1, 1])),
        [-1, suffix_len, h])
    x = layers.elementwise_add(tok, pe)                   # (B, P, H)
    # suffix row i (absolute start+i) sees cache columns j <= start+i:
    # the adopted prefix plus the causal part of the suffix itself
    csteps = layers.range(0, cache_len, 1, "int64")
    csteps2 = layers.unsqueeze(csteps, [0, 1])            # (1, 1, T)
    seen = layers.cast(
        layers.less_equal(csteps2, layers.unsqueeze(pos_idx, [2])),
        "float32")                                        # (B, P, T)
    mask = layers.unsqueeze(
        layers.scale(seen, scale=1e9, bias=-1e9), [1])    # (B, 1, P, T)
    # suffix rows >= len are pad: zero their k/v before the block write
    # so dead rows land as zeros (matching the incremental fill)
    valid = layers.cast(layers.less_than(steps0, slen), "float32")
    valid3 = layers.unsqueeze(valid, [2])                 # (B, P, 1)

    def layer_cache(t, i):
        return layers.squeeze(
            layers.slice(t, axes=[1], starts=[i], ends=[i + 1]), [1])

    new_ks, new_vs = [], []
    for i in range(nl):
        n = "gpt%d" % i
        q = _proj(x, h, n + ".self.q")
        k_new = layers.elementwise_mul(
            _proj(x, h, n + ".self.k"), valid3)
        v_new = layers.elementwise_mul(
            _proj(x, h, n + ".self.v"), valid3)
        k_cache = update_cache(layer_cache(k_all, i), k_new,
                               pos=start, per_row=True)
        v_cache = update_cache(layer_cache(v_all, i), v_new,
                               pos=start, per_row=True)
        new_ks.append(k_cache)
        new_vs.append(v_cache)
        attn = _proj(_attend(cfg, q, k_cache, v_cache, mask),
                     h, n + ".self.o")
        x = _ln(layers.elementwise_add(x, attn), n + ".ln1")
        f = _proj(x, cfg.ffn, n + ".ffn.fc1")
        f = layers.gelu(f)
        f = _proj(f, h, n + ".ffn.fc2")
        x = _ln(layers.elementwise_add(x, f), n + ".ln2")
    one = layers.fill_constant([1], "int64", 1)
    last = layers.elementwise_sub(slen, one)              # (B, 1)
    x_last = layers.gather_nd(x, _row_coords(last))       # (B, H)
    logits = _proj(x_last, cfg.vocab, "gpt_out", nfd=1)
    nxt = layers.cast(
        layers.unsqueeze(layers.argmax(logits, axis=-1), [1]), "int64")
    k_out = layers.stack(new_ks, axis=1)                  # (B, L, T, H)
    v_out = layers.stack(new_vs, axis=1)
    return {"ids": ids, "len": slen, "start": start,
            "k_in": k_all, "v_in": v_all,
            "next": nxt, "logits": logits, "k": k_out, "v": v_out,
            "feed_names": ["gpt_dpre_ids", "gpt_dpre_len",
                           "gpt_dpre_start", "gpt_dpre_k",
                           "gpt_dpre_v"],
            "fetch_vars": [nxt, k_out, v_out]}


def build_gpt_verify_block(cfg, block_len, cache_len):
    """Speculative-decoding verify program: score a block of
    ``block_len`` candidate tokens for EVERY slot in one batched pass —
    the target-model half of draft/verify speculation. Row semantics
    extend :func:`build_gpt_decode_step` from one token to a block:
    slot s feeds its current token plus the draft's proposals at
    absolute positions ``pos .. pos + block_len - 1``, and gets back
    the greedy next-token for each of those positions.

    Feeds: ``gpt_vrf_tok`` (S, block_len) int64 — column 0 is the
    slot's current token (what the non-speculative step would feed),
    columns 1.. are draft proposals — ``gpt_vrf_pos`` (S, 1) int64,
    and the fp32 caches ``gpt_vrf_k`` / ``gpt_vrf_v``
    (S, num_layers, cache_len, hidden). The caller must guarantee
    ``pos + block_len <= cache_len`` for every live row (the engine
    falls back to the single-token step near the cache edge).

    Returns ``next`` (S, block_len) int64 where ``next[s, i]`` is the
    target's greedy pick after consuming block tokens 0..i — column 0
    is bit-identical to the non-speculative step's output by
    construction (same math, same mask at position pos) — plus the
    updated caches with ALL block rows written. Rows past the accepted
    prefix are dirty-but-invisible: every consumer masks by position,
    and the next write at those positions overwrites them, the same
    contract dead slots already rely on.
    """
    from .decode_utils import update_cache

    if not (1 <= block_len <= cache_len):
        raise ValueError(
            "need 1 <= block_len (%d) <= cache_len (%d)"
            % (block_len, cache_len))
    if cache_len > cfg.max_len:
        raise ValueError("cache_len (%d) exceeds cfg.max_len (%d)"
                         % (cache_len, cfg.max_len))
    h = cfg.hidden
    nl = cfg.num_layers
    tok = fluid.data("gpt_vrf_tok", shape=[None, block_len],
                     dtype="int64")
    pos = fluid.data("gpt_vrf_pos", shape=[None, 1], dtype="int64")
    k_all = fluid.data("gpt_vrf_k", shape=[None, nl, cache_len, h],
                       dtype="float32")
    v_all = fluid.data("gpt_vrf_v", shape=[None, nl, cache_len, h],
                       dtype="float32")
    steps = layers.range(0, block_len, 1, "int64")
    steps0 = layers.unsqueeze(steps, [0])                 # (1, K)
    pos_idx = layers.elementwise_add(steps0, pos)         # (S, K) abs pos
    emb = layers.reshape(
        layers.embedding(tok, size=[cfg.vocab, h],
                         param_attr=_p("gpt_tok_emb")),
        [-1, block_len, h])
    pos_table = layers.create_parameter(
        shape=[cfg.max_len, h], dtype="float32", name="gpt_pos_emb")
    pe = layers.reshape(
        layers.gather_nd(pos_table, layers.reshape(pos_idx, [-1, 1])),
        [-1, block_len, h])
    x = layers.elementwise_add(emb, pe)                   # (S, K, H)
    # block row i (absolute pos+i) sees cache columns j <= pos+i —
    # the per-row visibility the single-token step's mask generalizes
    csteps = layers.range(0, cache_len, 1, "int64")
    csteps2 = layers.unsqueeze(csteps, [0, 1])            # (1, 1, T)
    seen = layers.cast(
        layers.less_equal(csteps2, layers.unsqueeze(pos_idx, [2])),
        "float32")                                        # (S, K, T)
    mask = layers.unsqueeze(
        layers.scale(seen, scale=1e9, bias=-1e9), [1])    # (S, 1, K, T)

    def layer_cache(t, i):
        return layers.squeeze(
            layers.slice(t, axes=[1], starts=[i], ends=[i + 1]), [1])

    new_ks, new_vs = [], []
    for i in range(nl):
        n = "gpt%d" % i
        q = _proj(x, h, n + ".self.q")
        k_cache = update_cache(layer_cache(k_all, i),
                               _proj(x, h, n + ".self.k"),
                               pos=pos, per_row=True)
        v_cache = update_cache(layer_cache(v_all, i),
                               _proj(x, h, n + ".self.v"),
                               pos=pos, per_row=True)
        new_ks.append(k_cache)
        new_vs.append(v_cache)
        attn = _proj(_attend(cfg, q, k_cache, v_cache, mask),
                     h, n + ".self.o")
        x = _ln(layers.elementwise_add(x, attn), n + ".ln1")
        f = _proj(x, cfg.ffn, n + ".ffn.fc1")
        f = layers.gelu(f)
        f = _proj(f, h, n + ".ffn.fc2")
        x = _ln(layers.elementwise_add(x, f), n + ".ln2")
    logits = _proj(x, cfg.vocab, "gpt_out")               # (S, K, V)
    nxt = layers.cast(layers.argmax(logits, axis=-1), "int64")
    k_out = layers.stack(new_ks, axis=1)                  # (S, L, T, H)
    v_out = layers.stack(new_vs, axis=1)
    return {"tok": tok, "pos": pos, "k_in": k_all, "v_in": v_all,
            "next": nxt, "logits": logits, "k": k_out, "v": v_out,
            "feed_names": ["gpt_vrf_tok", "gpt_vrf_pos",
                           "gpt_vrf_k", "gpt_vrf_v"],
            "fetch_vars": [nxt, k_out, v_out]}


def build_gpt_decode_step(cfg, cache_len):
    """One decode step for ALL slots of a continuous-batching engine:
    the :class:`GPTDecodeCell` math with the batch dim reinterpreted as
    a slot dim — every row carries its OWN position (a freshly
    prefilled slot at ``len`` sits beside one deep into generation), so
    cache writes use the per-row dynamic-update-slice path and the
    visibility mask is per-row.

    Feeds: ``gpt_step_tok`` (S, 1) int64 current token per slot,
    ``gpt_step_pos`` (S, 1) int64 write position per slot, and the
    stacked cache pair ``gpt_step_k`` / ``gpt_step_v``
    (S, num_layers, cache_len, hidden). Returns vars ``next`` (S, 1)
    int64 greedy tokens and the updated ``k``/``v`` pair (the engine
    round-trips them device-to-device; dead slots write harmlessly at
    position 0 and are ignored host-side).
    """
    from .decode_utils import step_masks, update_cache

    if cache_len > cfg.max_len:
        raise ValueError("cache_len (%d) exceeds cfg.max_len (%d)"
                         % (cache_len, cfg.max_len))
    h = cfg.hidden
    nl = cfg.num_layers
    tok = fluid.data("gpt_step_tok", shape=[None, 1], dtype="int64")
    pos = fluid.data("gpt_step_pos", shape=[None, 1], dtype="int64")
    k_all = fluid.data("gpt_step_k", shape=[None, nl, cache_len, h],
                       dtype="float32")
    v_all = fluid.data("gpt_step_v", shape=[None, nl, cache_len, h],
                       dtype="float32")
    emb = layers.reshape(
        layers.embedding(tok, size=[cfg.vocab, h],
                         param_attr=_p("gpt_tok_emb")), [-1, h])
    pos_table = layers.create_parameter(
        shape=[cfg.max_len, h], dtype="float32", name="gpt_pos_emb")
    x = layers.elementwise_add(emb, layers.gather_nd(pos_table, pos))
    x = layers.unsqueeze(x, [1])                          # (S, 1, H)
    _w3, _k3, self_mask = step_masks(pos, cache_len)      # per-row mask

    def layer_cache(t, i):
        return layers.squeeze(
            layers.slice(t, axes=[1], starts=[i], ends=[i + 1]), [1])

    new_ks, new_vs = [], []
    for i in range(nl):
        n = "gpt%d" % i
        q = _proj(x, h, n + ".self.q")
        k_cache = update_cache(layer_cache(k_all, i),
                               _proj(x, h, n + ".self.k"),
                               pos=pos, per_row=True)
        v_cache = update_cache(layer_cache(v_all, i),
                               _proj(x, h, n + ".self.v"),
                               pos=pos, per_row=True)
        new_ks.append(k_cache)
        new_vs.append(v_cache)
        attn = _proj(_attend(cfg, q, k_cache, v_cache, self_mask),
                     h, n + ".self.o")
        x = _ln(layers.elementwise_add(x, attn), n + ".ln1")
        f = _proj(x, cfg.ffn, n + ".ffn.fc1")
        f = layers.gelu(f)
        f = _proj(f, h, n + ".ffn.fc2")
        x = _ln(layers.elementwise_add(x, f), n + ".ln2")
    logits = _proj(layers.squeeze(x, [1]), cfg.vocab, "gpt_out", nfd=1)
    nxt = layers.cast(
        layers.unsqueeze(layers.argmax(logits, axis=-1), [1]), "int64")
    k_out = layers.stack(new_ks, axis=1)                  # (S, L, T, H)
    v_out = layers.stack(new_vs, axis=1)
    return {"tok": tok, "pos": pos, "k_in": k_all, "v_in": v_all,
            "next": nxt, "logits": logits, "k": k_out, "v": v_out,
            "feed_names": ["gpt_step_tok", "gpt_step_pos",
                           "gpt_step_k", "gpt_step_v"],
            "fetch_vars": [nxt, k_out, v_out]}


def _quantize_cache_rows(t):
    """In-graph per-(slot, layer, row) block-scaled int8 encode of a
    (S, L, T, H) fp32 cache: block = hidden width, matching
    serving.disagg.kv_wire. Returns (payload int8, scales fp32 with the
    hidden axis collapsed to 1). The 1e-30 clamp keeps all-zero rows
    (unwritten cache positions) at scale 1e-30 / payload 0, and rows
    decoded from an existing (payload, scale) re-encode identically
    (max |element| is exactly 127 * scale), so requantizing the whole
    cache every step does not compound error on unwritten rows."""
    amax = layers.reduce_max(layers.abs(t), dim=3, keep_dim=True)
    scale = layers.scale(layers.clip(amax, 1e-30, 3.0e38),
                         scale=1.0 / 127.0)
    q = layers.round(layers.elementwise_div(t, scale))
    payload = layers.cast(layers.clip(q, -127.0, 127.0), "int8")
    return payload, scale


def build_gpt_decode_step_q(cfg, cache_len):
    """:func:`build_gpt_decode_step` with an int8-**resident** KV
    cache: the engine keeps (payload int8, per-row fp32 scale) buffers
    instead of fp32 caches — ~4x more decode slots per chip at equal
    HBM — and this program dequantizes on entry and requantizes the
    updated caches before returning them.

    Extra feeds beyond the fp32 step: ``gpt_step_kscale`` /
    ``gpt_step_vscale`` (S, num_layers, cache_len, 1) fp32, with
    ``gpt_step_k`` / ``gpt_step_v`` now int8. Fetches next tokens plus
    the requantized (k, v, k_scale, v_scale) quadruple. Compute after
    dequantize is identical op-for-op to the fp32 step, so the only
    numeric delta is the per-row int8 rounding (bounded by scale/2 per
    element — the round-trip tolerance the kv_wire tests pin).
    """
    from .decode_utils import step_masks, update_cache

    if cache_len > cfg.max_len:
        raise ValueError("cache_len (%d) exceeds cfg.max_len (%d)"
                         % (cache_len, cfg.max_len))
    h = cfg.hidden
    nl = cfg.num_layers
    tok = fluid.data("gpt_step_tok", shape=[None, 1], dtype="int64")
    pos = fluid.data("gpt_step_pos", shape=[None, 1], dtype="int64")
    k_all = fluid.data("gpt_step_k", shape=[None, nl, cache_len, h],
                       dtype="int8")
    v_all = fluid.data("gpt_step_v", shape=[None, nl, cache_len, h],
                       dtype="int8")
    k_sc = fluid.data("gpt_step_kscale", shape=[None, nl, cache_len, 1],
                      dtype="float32")
    v_sc = fluid.data("gpt_step_vscale", shape=[None, nl, cache_len, 1],
                      dtype="float32")
    k_f = layers.elementwise_mul(layers.cast(k_all, "float32"), k_sc)
    v_f = layers.elementwise_mul(layers.cast(v_all, "float32"), v_sc)
    emb = layers.reshape(
        layers.embedding(tok, size=[cfg.vocab, h],
                         param_attr=_p("gpt_tok_emb")), [-1, h])
    pos_table = layers.create_parameter(
        shape=[cfg.max_len, h], dtype="float32", name="gpt_pos_emb")
    x = layers.elementwise_add(emb, layers.gather_nd(pos_table, pos))
    x = layers.unsqueeze(x, [1])                          # (S, 1, H)
    _w3, _k3, self_mask = step_masks(pos, cache_len)      # per-row mask

    def layer_cache(t, i):
        return layers.squeeze(
            layers.slice(t, axes=[1], starts=[i], ends=[i + 1]), [1])

    new_ks, new_vs = [], []
    for i in range(nl):
        n = "gpt%d" % i
        q = _proj(x, h, n + ".self.q")
        k_cache = update_cache(layer_cache(k_f, i),
                               _proj(x, h, n + ".self.k"),
                               pos=pos, per_row=True)
        v_cache = update_cache(layer_cache(v_f, i),
                               _proj(x, h, n + ".self.v"),
                               pos=pos, per_row=True)
        new_ks.append(k_cache)
        new_vs.append(v_cache)
        attn = _proj(_attend(cfg, q, k_cache, v_cache, self_mask),
                     h, n + ".self.o")
        x = _ln(layers.elementwise_add(x, attn), n + ".ln1")
        f = _proj(x, cfg.ffn, n + ".ffn.fc1")
        f = layers.gelu(f)
        f = _proj(f, h, n + ".ffn.fc2")
        x = _ln(layers.elementwise_add(x, f), n + ".ln2")
    logits = _proj(layers.squeeze(x, [1]), cfg.vocab, "gpt_out", nfd=1)
    nxt = layers.cast(
        layers.unsqueeze(layers.argmax(logits, axis=-1), [1]), "int64")
    k_q, k_s = _quantize_cache_rows(layers.stack(new_ks, axis=1))
    v_q, v_s = _quantize_cache_rows(layers.stack(new_vs, axis=1))
    return {"tok": tok, "pos": pos, "k_in": k_all, "v_in": v_all,
            "k_scale_in": k_sc, "v_scale_in": v_sc,
            "next": nxt, "logits": logits, "k": k_q, "v": v_q,
            "k_scale": k_s, "v_scale": v_s,
            "feed_names": ["gpt_step_tok", "gpt_step_pos",
                           "gpt_step_k", "gpt_step_v",
                           "gpt_step_kscale", "gpt_step_vscale"],
            "fetch_vars": [nxt, k_q, v_q, k_s, v_s]}


def tp_rules():
    """Tensor-parallel sharding rules for the GPT parameter naming
    (cf. bert.tp_rules): column-shard q/k/v and ffn.fc1 (+ biases),
    row-shard the attention output and ffn.fc2, vocab-shard the token
    embedding and the output projection's vocab dim."""
    from jax.sharding import PartitionSpec as P

    return [
        (r"gpt\d+\.self\.[qkv]\.w", P(None, "tp")),
        (r"gpt\d+\.self\.[qkv]\.b", P("tp")),
        (r"gpt\d+\.ffn\.fc1\.w", P(None, "tp")),
        (r"gpt\d+\.ffn\.fc1\.b", P("tp")),
        (r"gpt\d+\.self\.o\.w", P("tp", None)),
        (r"gpt\d+\.ffn\.fc2\.w", P("tp", None)),
        (r"gpt_tok_emb", P("tp", None)),
        (r"gpt_out\.w", P(None, "tp")),
    ]


def synthetic_lm_batch(cfg, batch, seq_len, seed=0):
    """Deterministic next-token task: x[t+1] = (x[t] * 3 + 1) % vocab —
    fully learnable by a causal LM, random start tokens."""
    rng = np.random.default_rng(seed)
    x = np.zeros((batch, seq_len + 1), np.int64)
    x[:, 0] = rng.integers(1, cfg.vocab, batch)
    for t in range(seq_len):
        x[:, t + 1] = (x[:, t] * 3 + 1) % cfg.vocab
    return x[:, :seq_len], x[:, 1:seq_len + 1]
