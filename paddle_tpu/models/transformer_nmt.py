"""Transformer NMT seq2seq (parity target: BASELINE.json "Transformer NMT
seq2seq (variable-length LoDTensor, beam_search ops)"; structure per the
reference's machine-translation book example).

Dense-padded source/target + @SEQ_LEN lengths stand in for LoDTensors;
greedy/beam decoding uses the static-beam beam_search ops.
"""
import numpy as np

from .. import fluid
from ..fluid import layers
from ..fluid.param_attr import ParamAttr

__all__ = ["NMTConfig", "build_transformer_nmt", "synthetic_pair_batch",
           "TransformerDecodeCell", "build_transformer_beam_decode"]


class NMTConfig:
    def __init__(self, src_vocab=10000, tgt_vocab=10000, hidden=256,
                 heads=8, ffn=1024, enc_layers=4, dec_layers=4,
                 max_len=64, dropout=0.1, bos_id=0, eos_id=1, pad_id=2):
        self.src_vocab = src_vocab
        self.tgt_vocab = tgt_vocab
        self.hidden = hidden
        self.heads = heads
        self.ffn = ffn
        self.enc_layers = enc_layers
        self.dec_layers = dec_layers
        self.max_len = max_len
        self.dropout = dropout
        self.bos_id = bos_id
        self.eos_id = eos_id
        self.pad_id = pad_id  # loss masking target; distinct from eos so
        # the model IS trained to emit end-of-sequence


def _mha(q_in, kv_in, cfg, name, mask=None):
    h, nh = cfg.hidden, cfg.heads
    dh = h // nh
    q = layers.fc(q_in, h, num_flatten_dims=2,
                  param_attr=ParamAttr(name=name + ".q.w"),
                  bias_attr=ParamAttr(name=name + ".q.b"))
    k = layers.fc(kv_in, h, num_flatten_dims=2,
                  param_attr=ParamAttr(name=name + ".k.w"),
                  bias_attr=ParamAttr(name=name + ".k.b"))
    v = layers.fc(kv_in, h, num_flatten_dims=2,
                  param_attr=ParamAttr(name=name + ".v.w"),
                  bias_attr=ParamAttr(name=name + ".v.b"))

    def split_heads(t):
        t = layers.reshape(t, [0, 0, nh, dh])
        return layers.transpose(t, [0, 2, 1, 3])

    qh, kh, vh = split_heads(q), split_heads(k), split_heads(v)
    scores = layers.matmul(qh, kh, transpose_y=True, alpha=dh ** -0.5)
    if mask is not None:
        scores = layers.elementwise_add(scores, mask)
    probs = layers.softmax(scores)
    ctx = layers.matmul(probs, vh)
    ctx = layers.transpose(ctx, [0, 2, 1, 3])
    ctx = layers.reshape(ctx, [0, 0, h])
    return layers.fc(ctx, h, num_flatten_dims=2,
                     param_attr=ParamAttr(name=name + ".o.w"),
                     bias_attr=ParamAttr(name=name + ".o.b"))


def _ffn(x, cfg, name):
    f = layers.fc(x, cfg.ffn, num_flatten_dims=2, act="relu",
                  param_attr=ParamAttr(name=name + ".f1.w"),
                  bias_attr=ParamAttr(name=name + ".f1.b"))
    return layers.fc(f, cfg.hidden, num_flatten_dims=2,
                     param_attr=ParamAttr(name=name + ".f2.w"),
                     bias_attr=ParamAttr(name=name + ".f2.b"))


def _ln(x, name):
    return layers.layer_norm(x, begin_norm_axis=2,
                             param_attr=ParamAttr(name=name + ".w"),
                             bias_attr=ParamAttr(name=name + ".b"))


def _embed(ids, vocab, cfg, name, seq_len):
    emb = layers.embedding(ids, size=[vocab, cfg.hidden],
                           param_attr=ParamAttr(name=name))
    pos = layers.create_parameter(
        shape=[cfg.max_len, cfg.hidden], dtype="float32",
        name=name + ".pos",
    )
    pos_slice = layers.slice(pos, axes=[0], starts=[0], ends=[seq_len])
    return layers.elementwise_add(emb, layers.unsqueeze(pos_slice, [0]))


def _causal_mask(t):
    """(1, 1, t, t) additive causal mask built from ops."""
    ar = layers.range(0, t, 1, "float32")
    rows = layers.unsqueeze(ar, [1])
    cols = layers.unsqueeze(ar, [0])
    allow = layers.cast(
        layers.greater_equal(
            layers.expand(rows, [1, t]), layers.expand(cols, [t, 1])
        ),
        "float32",
    )
    neg = layers.scale(allow, scale=1e9, bias=-1e9)  # 0 where allowed, -1e9 else
    return layers.unsqueeze(neg, [0, 1])


def _encoder_stack(enc, cfg):
    for i in range(cfg.enc_layers):
        n = "enc%d" % i
        enc = _ln(layers.elementwise_add(
            enc, _mha(enc, enc, cfg, n + ".self")), n + ".ln1")
        enc = _ln(layers.elementwise_add(enc, _ffn(enc, cfg, n)), n + ".ln2")
    return enc


def build_transformer_nmt(cfg, src_len, tgt_len):
    src = fluid.data(name="src_ids", shape=[None, src_len], dtype="int64",
                     lod_level=1)
    tgt = fluid.data(name="tgt_ids", shape=[None, tgt_len], dtype="int64",
                     lod_level=1)
    labels = fluid.data(name="tgt_labels", shape=[None, tgt_len],
                        dtype="int64")

    enc = _encoder_stack(
        _embed(src, cfg.src_vocab, cfg, "src_emb", src_len), cfg)

    dec = _embed(tgt, cfg.tgt_vocab, cfg, "tgt_emb", tgt_len)
    cmask = _causal_mask(tgt_len)
    for i in range(cfg.dec_layers):
        n = "dec%d" % i
        dec = _ln(layers.elementwise_add(
            dec, _mha(dec, dec, cfg, n + ".self", mask=cmask)), n + ".ln1")
        dec = _ln(layers.elementwise_add(
            dec, _mha(dec, enc, cfg, n + ".cross")), n + ".ln2")
        dec = _ln(layers.elementwise_add(dec, _ffn(dec, cfg, n)), n + ".ln3")

    logits = layers.fc(dec, cfg.tgt_vocab, num_flatten_dims=2,
                       param_attr=ParamAttr(name="out_proj.w"),
                       bias_attr=ParamAttr(name="out_proj.b"))
    loss = layers.mean(
        layers.softmax_with_cross_entropy(
            logits, layers.unsqueeze(labels, [2]), ignore_index=cfg.pad_id
        )
    )
    return {
        "src_ids": src, "tgt_ids": tgt, "tgt_labels": labels,
        "logits": logits, "loss": loss, "enc_out": enc,
    }


class TransformerDecodeCell:
    """Incremental transformer decoder step with per-layer KV caches —
    the TPU-native replacement for the reference's while_op `fast_decode`
    (ref: transformer book example / layers/rnn.py beam search ops).

    One step costs a 1-token QKV projection + attention over the cache
    (static `tmax` length, masked beyond `pos`) + FFN, instead of
    re-running the whole prefix. All shapes are static so the entire
    decode loop lowers to one lax.scan; beam bookkeeping (top-k, state
    gather by parent beam) is BeamSearchDecoder's.

    States: ``[pos (B,1) int64, k0, v0, k1, v1, ...]`` with each cache
    (B, tmax, hidden). Parameter names match ``build_transformer_nmt``'s
    decoder so trained weights load directly.
    """

    def __init__(self, cfg, tmax):
        self.cfg = cfg
        self.tmax = tmax

    def _attend(self, q, k, v, mask):
        """q (B,1,H), k/v (B,T,H), additive mask broadcastable to
        (B,nh,1,T) -> context (B,1,H)."""
        from .decode_utils import attend

        return attend(q, k, v, mask, self.cfg.heads, self.cfg.hidden)

    def call(self, inputs, states, enc_kv=None):
        from .decode_utils import step_masks, update_cache

        cfg = self.cfg
        h = cfg.hidden
        pos, caches = states[0], states[1:]
        pos_table = layers.create_parameter(
            shape=[cfg.max_len, h], dtype="float32", name="tgt_emb.pos")
        x = layers.elementwise_add(
            inputs, layers.gather_nd(pos_table, pos))      # (B, H)
        x = layers.unsqueeze(x, [1])                        # (B, 1, H)

        # cache-write one-hot and <=pos visibility mask, shared by layers
        _w3, _k3, self_mask = step_masks(pos, self.tmax)  # masks dead on the pos fast path (DCE'd)

        def proj(t, name):
            return layers.fc(t, h, num_flatten_dims=2,
                             param_attr=ParamAttr(name=name + ".w"),
                             bias_attr=ParamAttr(name=name + ".b"))

        new_caches = []
        for i in range(cfg.dec_layers):
            n = "dec%d" % i
            q = proj(x, n + ".self.q")
            k_cache = update_cache(caches[2 * i],
                                   proj(x, n + ".self.k"),
                                   pos=pos)
            v_cache = update_cache(caches[2 * i + 1],
                                   proj(x, n + ".self.v"),
                                   pos=pos)
            new_caches += [k_cache, v_cache]
            attn = proj(self._attend(q, k_cache, v_cache, self_mask),
                        n + ".self.o")
            x = _ln(layers.elementwise_add(x, attn), n + ".ln1")
            ek, ev = enc_kv[i]
            cross = proj(
                self._attend(proj(x, n + ".cross.q"), ek, ev, None),
                n + ".cross.o")
            x = _ln(layers.elementwise_add(x, cross), n + ".ln2")
            x = _ln(layers.elementwise_add(x, _ffn(x, cfg, n)), n + ".ln3")

        logits = layers.fc(layers.squeeze(x, [1]), cfg.tgt_vocab,
                           param_attr=ParamAttr(name="out_proj.w"),
                           bias_attr=ParamAttr(name="out_proj.b"))
        one = layers.fill_constant([1], "int64", 1)
        new_pos = layers.elementwise_add(pos, one)
        return logits, [new_pos] + new_caches

    def __call__(self, inputs, states, **kwargs):
        return self.call(inputs, states, **kwargs)


def build_transformer_beam_decode(cfg, src_len, max_out_len, beam_size):
    """Beam-search translation graph: encoder + KV-cache incremental
    decoder under dynamic_decode/BeamSearchDecoder (static beam, one
    lax.scan). Returns predicted ids (B, T_out, beam) and beam scores."""
    src = fluid.data(name="src_ids", shape=[None, src_len], dtype="int64",
                     lod_level=1)
    enc = _encoder_stack(
        _embed(src, cfg.src_vocab, cfg, "src_emb", src_len), cfg)

    cell = TransformerDecodeCell(cfg, max_out_len)

    def embed_tokens(ids):
        e = layers.embedding(ids, size=[cfg.tgt_vocab, cfg.hidden],
                             param_attr=ParamAttr(name="tgt_emb"))
        # (B, beam) ids with beam==1 hit embedding's trailing-1 ids
        # convention and come back rank-2; restore (B, beam, H)
        return layers.reshape(e, [-1, beam_size, cfg.hidden])

    decoder = layers.BeamSearchDecoder(
        cell, start_token=cfg.bos_id, end_token=cfg.eos_id,
        beam_size=beam_size, embedding_fn=embed_tokens,
    )

    # per-layer cross-attention K/V from the encoder, computed ONCE and
    # beam-tiled (the pserver-era reference recomputes these per step
    # inside its While loop)
    enc_kv = []
    for i in range(cfg.dec_layers):
        n = "dec%d" % i

        def tiled(name):
            t = layers.fc(enc, cfg.hidden, num_flatten_dims=2,
                          param_attr=ParamAttr(name=name + ".w"),
                          bias_attr=ParamAttr(name=name + ".b"))
            return layers.BeamSearchDecoder.tile_beam_merge_with_batch(
                t, beam_size)

        enc_kv.append((tiled(n + ".cross.k"), tiled(n + ".cross.v")))

    pos0 = layers.fill_constant_batch_size_like(
        enc, shape=[-1, 1], dtype="int64", value=0)
    init_states = [pos0]
    for _ in range(cfg.dec_layers):
        for _ in ("k", "v"):
            init_states.append(layers.fill_constant_batch_size_like(
                enc, shape=[-1, max_out_len, cfg.hidden], dtype="float32",
                value=0.0))

    ids, final_states = layers.dynamic_decode(
        decoder, inits=init_states, max_step_num=max_out_len - 1,
        enc_kv=enc_kv)
    return {"src_ids": src, "ids": ids,
            "scores": final_states.log_probs}


def synthetic_pair_batch(cfg, batch, src_len, tgt_len, seed=0):
    """Copy-task pairs: target = source tokens shifted (teaches quickly)."""
    rng = np.random.default_rng(seed)
    # real tokens start above pad_id so padding never collides with content
    lo = cfg.pad_id + 1
    src = rng.integers(lo, cfg.src_vocab, size=(batch, src_len)).astype("int64")
    content = np.clip(src[:, : tgt_len - 1] % cfg.tgt_vocab, lo,
                      cfg.tgt_vocab - 1)
    tgt_full = np.concatenate(
        [np.full((batch, 1), cfg.bos_id, "int64"), content], axis=1
    )
    labels = np.concatenate(
        [content, np.full((batch, 1), cfg.eos_id, "int64")], axis=1
    )
    return src, tgt_full, labels
