"""Model zoo matching the reference's benchmark configs (BASELINE.json):
MNIST MLP, ResNet-50, BERT-base, Transformer NMT, Wide&Deep CTR, SSD —
all built through the paddle_tpu.fluid layer API so they exercise the
framework. Beyond-survey: GPT decoder-only LM with KV-cache generation
(models/gpt.py)."""
