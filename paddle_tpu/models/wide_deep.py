"""Wide&Deep CTR model (parity target: BASELINE.json "Wide&Deep CTR
(lookup_table sparse embedding + distributed pserver→ICI allreduce)").

The reference shards its embedding over parameter servers; the TPU-native
equivalent shards the embedding table's vocab dim over the mesh (see
parallel/sharding.py rules) and lets GSPMD place the gathers.
"""
import numpy as np

from .. import fluid
from ..fluid import layers
from ..fluid.param_attr import ParamAttr

__all__ = ["build_wide_deep", "synthetic_ctr_batch", "wd_tp_rules"]


def build_wide_deep(
    num_sparse_fields=26,
    sparse_vocab=100000,
    emb_dim=16,
    num_dense=13,
    hidden=[400, 400, 400],
):
    dense = fluid.data(name="dense", shape=[None, num_dense], dtype="float32")
    sparse = fluid.data(
        name="sparse", shape=[None, num_sparse_fields], dtype="int64"
    )
    label = fluid.data(name="ctr_label", shape=[None, 1], dtype="int64")

    # deep part: shared big embedding, one gather per field
    emb = layers.embedding(
        sparse,
        size=[sparse_vocab, emb_dim],
        param_attr=ParamAttr(name="ctr_emb"),
        is_sparse=True,
    )  # (B, F, D)
    deep = layers.reshape(emb, [0, num_sparse_fields * emb_dim])
    deep = layers.concat([deep, dense], axis=1)
    for i, h in enumerate(hidden):
        deep = layers.fc(
            deep, h, act="relu",
            param_attr=ParamAttr(name="deep_fc%d.w" % i),
            bias_attr=ParamAttr(name="deep_fc%d.b" % i),
        )
    # wide part: linear over dense + 1-d sparse embedding
    wide_emb = layers.embedding(
        sparse,
        size=[sparse_vocab, 1],
        param_attr=ParamAttr(name="ctr_wide_emb"),
        is_sparse=True,
    )
    wide = layers.reduce_sum(wide_emb, dim=[1, 2], keep_dim=False)
    wide = layers.elementwise_add(
        wide,
        layers.reduce_sum(
            layers.fc(dense, 1, bias_attr=False,
                      param_attr=ParamAttr(name="wide_fc.w")),
            dim=[1],
        ),
    )
    logit = layers.elementwise_add(
        layers.fc(deep, 1, param_attr=ParamAttr(name="head.w"),
                  bias_attr=ParamAttr(name="head.b")),
        layers.unsqueeze(wide, [1]),
    )
    prob = layers.sigmoid(logit)
    loss = layers.mean(
        layers.log_loss(
            layers.clip(prob, 1e-7, 1.0 - 1e-7),
            layers.cast(label, "float32"),
        )
    )
    auc_in = layers.concat(
        [layers.elementwise_sub(
            layers.fill_constant_batch_size_like(prob, [-1, 1], "float32", 1.0),
            prob,
        ), prob],
        axis=1,
    )
    auc_out, auc_states = layers.auc(auc_in, label)
    return {
        "dense": dense, "sparse": sparse, "label": label,
        "prob": prob, "loss": loss, "auc": auc_out,
    }


def wd_tp_rules():
    """Shard the big embedding tables' vocab dim over 'tp' — the ICI-native
    replacement for pserver-sharded lookup tables."""
    from jax.sharding import PartitionSpec as P

    return [(r"ctr_emb", P("tp", None)), (r"ctr_wide_emb", P("tp", None))]


def synthetic_ctr_batch(batch, num_sparse_fields=26, sparse_vocab=100000,
                        num_dense=13, seed=0):
    rng = np.random.default_rng(seed)
    dense = rng.standard_normal((batch, num_dense)).astype("float32")
    sparse = rng.integers(
        0, sparse_vocab, size=(batch, num_sparse_fields)
    ).astype("int64")
    # label correlated with a fixed direction for learnability
    w = np.random.default_rng(1).standard_normal(num_dense)
    label = ((dense @ w + 0.3 * rng.standard_normal(batch)) > 0).astype("int64")
    return dense, sparse, label[:, None]
