"""Rate limiting + regression gating for autopilot actions.

A control loop over a noisy signal flaps without three dampers, and
:class:`ActionGate` is all three in one place:

- **hysteresis** — a trigger must fire ``confirm_n`` consecutive
  observations before it is *confirmed*; one missed observation resets
  the streak. A single slow heartbeat or one bad SLO window never
  moves the fleet.
- **cooldown** — at most one action per ``cooldown_s`` per action
  kind. Remediations act through queues and migrations that take time
  to settle; acting again before the last action's effect is visible
  is how autoscalers oscillate.
- **quarantine** — a trigger whose action was rolled back by the
  regression gate is benched for ``quarantine_base_s``, doubling per
  strike up to ``quarantine_max_s`` (exponential backoff). A trigger
  that keeps producing regressing plans loses the right to re-plan
  until an operator (or :meth:`release`) pardons it.

:func:`verify_measurement` is the regression verdict the apply path
runs after every fleet mutation — the same direction-aware tolerance
framing as the PR-15 bench baseline gate (``bench_experiments/
_baseline.py``), inlined here so a serving process needs no bench
checkout to self-gate.
"""
import threading
import time

__all__ = ["ActionGate", "verify_measurement"]


def verify_measurement(before, after, tolerance_pct=10.0,
                       higher_is_better=False):
    """Direction-aware regression verdict on a post-change measurement.

    Returns ``{"regressed": bool, "delta_pct": float|None, ...}``.
    With ``higher_is_better=False`` (step seconds, latency) a rise
    beyond ``tolerance_pct`` regresses; with ``True`` (tokens/sec) a
    fall beyond it does. An unknown side (None / non-positive
    ``before``) yields a non-regressed verdict with ``delta_pct``
    None — the gate can only judge what was measured."""
    try:
        b = None if before is None else float(before)
        a = None if after is None else float(after)
    except (TypeError, ValueError):
        b = a = None
    if b is None or a is None or b <= 0:
        return {"regressed": False, "delta_pct": None,
                "before": before, "after": after,
                "tolerance_pct": float(tolerance_pct)}
    delta_pct = 100.0 * (a - b) / b
    if higher_is_better:
        regressed = delta_pct < -float(tolerance_pct)
    else:
        regressed = delta_pct > float(tolerance_pct)
    return {"regressed": bool(regressed),
            "delta_pct": round(delta_pct, 3), "before": b, "after": a,
            "tolerance_pct": float(tolerance_pct)}


class ActionGate:
    """Hysteresis + per-kind cooldown + per-trigger quarantine.

    ``clock`` is injectable (tests pin time); everything else is
    internally locked — the gate is shared between the loop thread and
    any operator thread poking :meth:`release`."""

    def __init__(self, cooldown_s=5.0, confirm_n=2,
                 quarantine_base_s=30.0, quarantine_max_s=3600.0,
                 clock=time.monotonic):
        self.cooldown_s = float(cooldown_s)
        self.confirm_n = max(1, int(confirm_n))
        self.quarantine_base_s = float(quarantine_base_s)
        self.quarantine_max_s = float(quarantine_max_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._streak = {}       # trigger -> consecutive firing ticks
        self._last_fire = {}    # action kind -> last action stamp
        self._quarantine = {}   # trigger -> {"until": t, "strikes": n}

    # -- hysteresis ------------------------------------------------------
    def confirm(self, trigger, firing):
        """Count one observation of ``trigger``; True once it has fired
        ``confirm_n`` consecutive times. A non-firing observation
        resets the streak (sustained, not cumulative)."""
        with self._lock:
            if not firing:
                self._streak.pop(trigger, None)
                return False
            n = self._streak.get(trigger, 0) + 1
            self._streak[trigger] = n
            return n >= self.confirm_n

    def clear(self, trigger):
        """Reset a trigger's streak (after acting on it: the next
        incident must re-confirm from scratch)."""
        with self._lock:
            self._streak.pop(trigger, None)

    # -- cooldown --------------------------------------------------------
    def ready(self, kind):
        """True when ``kind`` is outside its cooldown window."""
        with self._lock:
            last = self._last_fire.get(kind)
        return last is None or self._clock() - last >= self.cooldown_s

    def stamp(self, kind):
        """Record that an action of ``kind`` just ran."""
        with self._lock:
            self._last_fire[kind] = self._clock()

    # -- quarantine ------------------------------------------------------
    def quarantine(self, trigger):
        """Bench ``trigger`` with exponential backoff; returns the
        backoff seconds granted this strike."""
        with self._lock:
            q = self._quarantine.get(trigger, {"strikes": 0})
            q["strikes"] += 1
            backoff = min(self.quarantine_max_s,
                          self.quarantine_base_s
                          * (2.0 ** (q["strikes"] - 1)))
            q["until"] = self._clock() + backoff
            self._quarantine[trigger] = q
            return backoff

    def quarantined(self, trigger):
        """True while ``trigger`` is benched. Strikes persist past
        expiry — a repeat offender re-enters at double the backoff."""
        with self._lock:
            q = self._quarantine.get(trigger)
            return q is not None and self._clock() < q["until"]

    def release(self, trigger):
        """Operator pardon: lift the bench AND forget the strikes."""
        with self._lock:
            self._quarantine.pop(trigger, None)

    def state(self):
        """Snapshot for journals/tests: streaks, cooldown stamps,
        quarantine table (with remaining seconds)."""
        now = self._clock()
        with self._lock:
            return {
                "streaks": dict(self._streak),
                "cooldowns": {k: round(now - t, 3)
                              for k, t in self._last_fire.items()},
                "quarantine": {
                    t: {"strikes": q["strikes"],
                        "remaining_s": round(max(0.0, q["until"] - now),
                                             3)}
                    for t, q in self._quarantine.items()},
            }
