"""Typed autopilot decisions + the append-only decision journal.

Every move the autopilot makes — a calibration fit, a standby
activation, a replica kill, a re-plan, a rollback — is one
:class:`AutopilotAction`: a flat, JSON-serializable record carrying
the action kind, the trigger that demanded it, the mode it ran under,
its outcome, and the trace id of the incident timeline its spans were
exported on. The record IS the audit trail: the loop never mutates
the fleet without first minting one.

The :class:`DecisionJournal` persists them append-only (one JSON line
per action, flushed per append, never rewritten) so a post-mortem can
replay exactly what the loop decided and why — including the actions
it *refused* (cooldown, quarantine, missing standby). Journal I/O is
best-effort: a full disk degrades to the in-memory ring and bumps
``autopilot.journal_errors``; it never takes the control loop down.
"""
import json
import os
import threading
import time

from .. import observability as obs

__all__ = ["AUTOPILOT_ENV", "MODES", "AutopilotAction",
           "DecisionJournal", "autopilot_mode"]

# PADDLE_TPU_AUTOPILOT=off|propose|apply — the fleet-wide mode switch.
# ``off`` parks the loop (ticks observe, decide nothing), ``propose``
# records + journals every decision without touching the fleet, and
# ``apply`` executes remediations (still gated, rate-limited, and
# auto-rolled-back on a verified regression).
AUTOPILOT_ENV = "PADDLE_TPU_AUTOPILOT"
MODES = ("off", "propose", "apply")


def autopilot_mode(default="propose"):
    """The env-resolved autopilot mode (an unknown value degrades to
    ``off`` — a typo must park the loop, not arm it)."""
    raw = os.environ.get(AUTOPILOT_ENV)
    if not raw:
        return default
    raw = raw.strip().lower()
    return raw if raw in MODES else "off"


class AutopilotAction:
    """One decision of the control loop.

    ``kind`` names the move (``calibrate`` / ``scale_up`` /
    ``reprice`` / ``reweight`` / ``kill_replica`` /
    ``quarantine_replica`` / ``replan`` / ``apply_plan`` /
    ``rollback``), ``trigger`` names the condition
    that demanded it (``slo:<tenant>:<leg>``, ``drift:<fingerprint>``,
    ``cadence``), and ``outcome`` tracks its lifecycle:

    - ``proposed`` — recorded, not executed (propose mode, or an apply
      pending its verify leg),
    - ``applied`` — executed, verification pending or not applicable,
    - ``verified`` — executed and the post-change measurement held,
    - ``rolled_back`` — executed, regressed, reverted by the gate,
    - ``rejected`` — refused before execution (cooldown, quarantine,
      no standby to activate, mode off),
    - ``quarantined`` — the trigger itself was benched with backoff.
    """

    __slots__ = ("seq", "kind", "trigger", "mode", "outcome", "detail",
                 "trace_id", "wall")

    OUTCOMES = frozenset({"proposed", "applied", "verified",
                          "rolled_back", "rejected", "quarantined"})

    def __init__(self, kind, trigger, mode, outcome="proposed",
                 detail=None, trace_id=None, seq=None, wall=None):
        if outcome not in self.OUTCOMES:
            raise ValueError("unknown action outcome %r (want one of %s)"
                             % (outcome, sorted(self.OUTCOMES)))
        self.seq = seq
        self.kind = str(kind)
        self.trigger = str(trigger)
        self.mode = str(mode)
        self.outcome = outcome
        self.detail = dict(detail or {})
        self.trace_id = trace_id
        self.wall = time.time() if wall is None else float(wall)

    def resolve(self, outcome, **detail):
        """Advance the lifecycle (``applied`` -> ``verified`` /
        ``rolled_back``) in place, merging extra detail fields."""
        if outcome not in self.OUTCOMES:
            raise ValueError("unknown action outcome %r" % (outcome,))
        self.outcome = outcome
        self.detail.update(detail)
        return self

    def to_dict(self):
        return {"seq": self.seq, "wall": self.wall, "kind": self.kind,
                "trigger": self.trigger, "mode": self.mode,
                "outcome": self.outcome, "trace_id": self.trace_id,
                "detail": dict(self.detail)}

    def __repr__(self):
        return ("AutopilotAction(%s, trigger=%r, mode=%s, outcome=%s)"
                % (self.kind, self.trigger, self.mode, self.outcome))


class DecisionJournal:
    """Append-only record of every :class:`AutopilotAction`.

    With a ``path`` each append writes one JSON line and flushes —
    the file is never truncated or rewritten, so a reader can tail it
    live and a crash can lose at most the final partial line (which
    :meth:`read_jsonl` skips). Without a path the journal is the
    in-memory ring alone (tests, propose-mode dry runs)."""

    def __init__(self, path=None, capacity=512):
        self.path = str(path) if path else None
        self.capacity = max(1, int(capacity))
        self._lock = threading.Lock()
        self._ring = []
        self._seq = 0

    def append(self, action):
        """Stamp ``action.seq``, retain it, and (best-effort) persist
        it. Returns the action for chaining."""
        with self._lock:
            self._seq += 1
            action.seq = self._seq
            self._ring.append(action)
            if len(self._ring) > self.capacity:
                del self._ring[:len(self._ring) - self.capacity]
            line = None
            if self.path:
                try:
                    line = json.dumps(action.to_dict(), sort_keys=True)
                except (TypeError, ValueError):
                    # undumpable detail payload: journal the envelope
                    d = action.to_dict()
                    d["detail"] = {"unserializable": True}
                    line = json.dumps(d, sort_keys=True)
        if line is not None:
            try:
                with open(self.path, "a", encoding="utf-8") as fh:
                    fh.write(line + "\n")
                    fh.flush()
            except OSError:
                obs.inc("autopilot.journal_errors")
        return action

    def tail(self, n=32):
        """The most recent ``n`` actions, oldest first (dicts)."""
        with self._lock:
            return [a.to_dict() for a in self._ring[-int(n):]]

    def entries(self):
        with self._lock:
            return [a.to_dict() for a in self._ring]

    def __len__(self):
        with self._lock:
            return len(self._ring)

    @staticmethod
    def read_jsonl(path):
        """Load a journal file back as a list of action dicts. A torn
        final line (crash mid-append) is skipped, matching the
        append-only write discipline; skipped lines bump
        ``integrity.jsonl_dropped`` (shared tolerant reader)."""
        from ..integrity import jsonl as _jsonl

        out, dropped = _jsonl.read_jsonl(path)
        if dropped:
            obs.inc("integrity.jsonl_dropped", dropped)
        return out
