"""The self-healing control loop: ledger -> planner -> fleet.

:class:`Autopilot` closes the observe/decide/act cycle the previous
subsystems left open. One :meth:`tick` runs five legs in order:

1. **calibrate** — measured step times the serving/bench loops feed
   into the :class:`~paddle_tpu.observability.ExecutableLedger` are
   fitted into an *effective* :class:`DeviceProfile`
   (``DeviceProfile.calibrated_from``) on a cadence, so every later
   decision prices against what the chips actually deliver, not table
   constants. A fresh fit also re-prices the decode bucket ladder
   under the calibrated HBM view (the ``reprice`` action).
2. **SLO** — per-tenant burn rates (:class:`SLOMonitor`) above
   ``burn_threshold``, confirmed over ``ActionGate.confirm_n``
   consecutive ticks, trigger the existing remediations in order of
   specificity: ``kill_replica`` + migrate for a confirmed-degraded
   decode replica (beacon latency >= ``degrade_factor`` x its own
   healthy baseline), warm-standby ``scale_up`` on the classic
   router, admission ``reweight`` (demote best-effort tenants one
   priority class) otherwise.
3. **integrity** — pending SDC-sentinel replay disagreements are put
   to a cross-replica vote; a replica its peers confirm as lying is
   pulled from rotation with ``quarantine_replica`` (journaled,
   gated, traced — and never the last decode replica).
4. **train** — the active training run's convergence signal (a
   :class:`~paddle_tpu.observability.RunHealth` bundle, usually the
   one its :class:`~paddle_tpu.fluid.resilience.TrainGuard` carries):
   divergence — non-finite loss, a loss-spike z-score, a grad-norm
   explosion — confirmed over ``confirm_n`` ticks triggers a
   journaled ``rollback_lr_cut``: restore the last checkpoint whose
   state is entirely finite and scale the learning rate down. Never
   acts on an unguarded executor.
5. **drift** — when a measured step time departs the *calibrated*
   re-prediction beyond ``drift_tolerance_pct``, the planner re-ranks
   under the calibrated profile (``replan`` callback, typically a
   ``plan_search`` wrapper) and proposes the new config; in ``apply``
   mode the proposal is applied (``apply`` callback — e.g.
   ``ServingRouter.rolling_reload`` with its built-in rollback),
   measured again, and auto-rolled-back if the post-change
   measurement regresses past ``verify_tolerance_pct`` — with the
   trigger quarantined under exponential backoff.

Every decision is an :class:`AutopilotAction` journaled append-only,
exported as spans on one incident trace (detect -> replan -> apply ->
verify share a trace_id), and rate-limited by the shared
:class:`ActionGate` so the loop cannot flap. The mode switch
(``PADDLE_TPU_AUTOPILOT=off|propose|apply``) is read live: flipping
the env var to ``off`` parks a running loop at its next tick.
"""
import threading
import time

from .. import observability as obs
from ..analysis import concurrency as _conc
from .actions import AutopilotAction, DecisionJournal, autopilot_mode
from .gates import ActionGate, verify_measurement

__all__ = ["Autopilot"]

_MODE_GAUGE = {"off": 0, "propose": 1, "apply": 2}


def _median(xs):
    xs = sorted(x for x in xs if x is not None)
    if not xs:
        return None
    n = len(xs)
    mid = xs[n // 2]
    return mid if n % 2 else (xs[n // 2 - 1] + mid) / 2.0


class Autopilot:
    """Supervised control loop over a serving fleet.

    Wire in what exists — every collaborator is optional and its leg
    simply stays quiet without it:

    - ``ledger`` — an ExecutableLedger (default: the process-global
      one) feeding the calibrate + drift legs.
    - ``tenants`` — a TenantTable; arms the SLO leg (burn rates) and
      the ``reweight`` remediation.
    - ``disagg`` — a DisaggRouter; arms ``kill_replica``+migrate.
    - ``sentinel`` — an :class:`~paddle_tpu.integrity.sentinel.
      SDCSentinel`; arms the integrity leg (cross-replica vote +
      ``quarantine_replica`` for confirmed-lying decode replicas).
    - ``router`` — a ServingRouter; arms warm-standby ``scale_up``.
    - ``trainguard`` / ``runhealth`` — a
      :class:`~paddle_tpu.fluid.resilience.TrainGuard` (and/or its
      RunHealth bundle); arms the TRAIN leg's divergence-triggered
      ``rollback_lr_cut`` (lr scaled by ``train_lr_cut``, default
      0.5).
    - ``replan`` — ``callable(profile) -> proposal dict``; the drift
      leg's planner hook (wrap ``plan_search`` + ``best_runnable``).
    - ``measure`` / ``apply`` / ``rollback`` — the apply path:
      ``measure() -> seconds`` (lower is better) brackets
      ``apply(proposal)``; a regressing delta triggers ``rollback()``
      and quarantines the trigger.

    ``tick()`` is synchronous and returns the actions it took (tests
    drive it directly); ``start()`` runs it on a daemon thread every
    ``interval_s``.
    """

    def __init__(self, ledger=None, tenants=None, router=None,
                 disagg=None, sentinel=None, replan=None, measure=None,
                 apply=None, rollback=None, mode=None, journal=None,
                 gate=None, trainguard=None, runhealth=None,
                 train_lr_cut=0.5,
                 calibration_path=None, device_kind=None,
                 burn_threshold=1.0, slo_budget=0.1,
                 drift_tolerance_pct=50.0, verify_tolerance_pct=15.0,
                 degrade_factor=3.0, calibrate_every_s=30.0,
                 interval_s=0.5, name="autopilot",
                 clock=time.monotonic):
        self.ledger = ledger if ledger is not None else obs.get_ledger()
        self.tenants = tenants
        self.router = router
        self.disagg = disagg
        self.sentinel = sentinel
        self.replan = replan
        self.measure = measure
        self.apply = apply
        self.rollback = rollback
        self._mode_override = mode
        self.journal = journal if journal is not None else DecisionJournal()
        self.gate = gate if gate is not None else ActionGate(clock=clock)
        # TRAIN leg (observability/runhealth.py): a TrainGuard (and/or
        # its RunHealth bundle) arms divergence-triggered rollback —
        # confirmed divergence rolls back to the last finite checkpoint
        # and cuts the lr by `train_lr_cut`
        self.trainguard = trainguard
        self.runhealth = runhealth
        self.train_lr_cut = float(train_lr_cut)
        self.calibration_path = calibration_path
        self.device_kind = device_kind
        self.burn_threshold = float(burn_threshold)
        self.slo_budget = float(slo_budget)
        self.drift_tolerance_pct = float(drift_tolerance_pct)
        self.verify_tolerance_pct = float(verify_tolerance_pct)
        self.degrade_factor = float(degrade_factor)
        self.calibrate_every_s = float(calibrate_every_s)
        self.interval_s = float(interval_s)
        self.name = str(name)
        self._clock = clock
        self._lock = threading.Lock()
        self.profile = None          # latest calibrated DeviceProfile
        self._cal_ratio = None       # median predicted/measured at fit
        self._cal_measured = {}      # measured map the last fit used
        self._last_cal = None        # clock stamp of the last fit
        self._lat_baseline = {}      # decode rid -> healthy latency
        self._ticks = 0
        self._slo_mon = None
        self._stop = threading.Event()
        self._thread = None
        self._owner = _conc.owner_token("autopilot", self.name, self)

    # -- mode ------------------------------------------------------------
    def mode(self):
        """Live mode: the constructor override, else the env var
        (``PADDLE_TPU_AUTOPILOT``), else ``propose``."""
        m = (self._mode_override if self._mode_override is not None
             else autopilot_mode())
        obs.set_gauge("autopilot.mode", _MODE_GAUGE.get(m, 0))
        return m

    # -- record keeping ----------------------------------------------------
    def _record(self, action, ctx=None):
        """Journal + trace + meter one action. ``ctx`` stamps the
        incident trace id the action's spans were exported on."""
        if ctx is not None:
            action.trace_id = ctx.trace_id
        self.journal.append(action)
        obs.inc("autopilot.actions")
        obs.inc("autopilot.%s" % action.outcome)
        obs.event("autopilot_action", source="autopilot",
                  action=action.kind, trigger=action.trigger,
                  mode=action.mode, outcome=action.outcome,
                  seq=action.seq, trace=action.trace_id)
        return action

    def _span(self, name, ctx, **fields):
        """An exported child span on the incident timeline (annotation
        only — the loop proceeds even with tracing unconfigured)."""
        fields.setdefault("proc", "autopilot:%s" % self.name)
        return obs.span(name, ctx=ctx, **fields)

    # -- the loop ----------------------------------------------------------
    def tick(self):
        """One observe/decide/act pass; returns the list of
        :class:`AutopilotAction` records it minted (possibly empty)."""
        mode = self.mode()
        self._ticks += 1
        obs.inc("autopilot.ticks")
        if mode == "off":
            return []
        self._observe_fleet()
        actions = []
        self._leg_calibrate(actions, mode)
        self._leg_slo(actions, mode)
        self._leg_integrity(actions, mode)
        self._leg_train(actions, mode)
        self._leg_drift(actions, mode)
        return actions

    def _observe_fleet(self):
        """Refresh per-replica latency baselines every tick — the first
        latency a replica ever reports is its healthy baseline, so it
        must be captured while the fleet is healthy, not at incident
        time (when the reading is already degraded)."""
        if self.disagg is None:
            return
        try:
            lat = self.disagg.decode_latencies()
        except Exception:  # noqa: BLE001 — beacons are best-effort
            return
        for rid, v in lat.items():
            self._lat_baseline.setdefault(rid, v)

    def start(self):
        """Run :meth:`tick` every ``interval_s`` on a daemon thread."""
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, daemon=True,
                name="autopilot-%s" % self.name)
            _conc.track_thread(self._thread, self._owner)
            self._thread.start()
        return self

    def _run(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception as e:  # noqa: BLE001 — the loop must survive
                obs.inc("autopilot.tick_errors")
                obs.event("autopilot_tick_error", source="autopilot",
                          error="%s: %s" % (type(e).__name__, e))

    def stop(self):
        self._stop.set()
        if self._thread is not None and self._thread.is_alive():
            self._thread.join(timeout=5.0)
        _conc.check_stopped(self._owner, grace=2.0)

    # -- leg 1: continuous calibration -------------------------------------
    def _leg_calibrate(self, actions, mode):
        """Fit an effective DeviceProfile from the ledger's measured
        step times when they changed since the last fit (and the
        cadence elapsed). The fit is a *sensor* update — it runs in
        propose mode too; only fleet mutations honor propose/apply."""
        now = self._clock()
        if (self._last_cal is not None
                and now - self._last_cal < self.calibrate_every_s):
            return
        try:
            snap = self.ledger.snapshot()
        except Exception:  # noqa: BLE001 — observability-side failure
            return
        measured = dict(snap.get("measured") or {})
        if not measured or measured == self._cal_measured:
            return
        from ..analysis.costs import DeviceProfile

        prof = DeviceProfile.calibrated_from(
            snap, path=self.calibration_path)
        self._last_cal = now
        if prof is None:
            return
        ratios = []
        for e in snap.get("entries") or ():
            pred = (e.get("predicted") or {}).get(
                "predicted_step_seconds")
            meas = e.get("measured_step_seconds")
            if pred and meas and pred > 0 and meas > 0:
                ratios.append(float(pred) / float(meas))
        with self._lock:
            self.profile = prof
            self._cal_ratio = _median(ratios)
            self._cal_measured = measured
        obs.inc("autopilot.calibrations")
        if prof.peak_flops:
            obs.set_gauge("autopilot.calibrated_peak_flops",
                          prof.peak_flops)
        actions.append(self._record(AutopilotAction(
            "calibrate", "cadence", mode, outcome="applied",
            detail={"peak_flops": prof.peak_flops, "hbm_bw": prof.hbm_bw,
                    "ratio": self._cal_ratio,
                    "entries_measured": len(measured),
                    "path": self.calibration_path})))
        self._reprice(actions, mode)

    def _reprice(self, actions, mode):
        """Bucket-ladder re-pricing under the freshly calibrated HBM
        view: re-run each decode engine's admission pricing so a
        calibration that shrank the effective capacity surfaces an
        over-budget ladder *now*, not at the next cold warmup."""
        if self.disagg is None or not self.gate.ready("reprice"):
            return
        with self.disagg._lock:
            decodes = list(self.disagg._decode.items())
        budget = self.profile.hbm_bytes if self.profile else None
        priced = {}
        ok = True
        for rid, rep in decodes:
            check = getattr(rep.engine, "check_hbm_budget", None)
            if check is None:
                continue
            try:
                check(budget_bytes=budget)
                priced[str(rid)] = "ok"
            except Exception as e:  # noqa: BLE001 — verdict, not crash
                priced[str(rid)] = "%s: %s" % (type(e).__name__,
                                               str(e)[:120])
                ok = False
        if not priced:
            return
        self.gate.stamp("reprice")
        actions.append(self._record(AutopilotAction(
            "reprice", "cadence", mode,
            outcome="applied" if ok else "rejected",
            detail={"budget_bytes": budget, "replicas": priced})))

    # -- leg 2: SLO burn ----------------------------------------------------
    def _leg_slo(self, actions, mode):
        if self.tenants is None:
            return
        if self._slo_mon is None:
            self._slo_mon = obs.SLOMonitor(self.tenants,
                                           budget=self.slo_budget)
        try:
            burns = self._slo_mon.tick(publish=True)
        except Exception:  # noqa: BLE001 — a broken hub must not stop us
            return
        worst = 0.0
        for tenant, legs in burns.items():
            for leg, key in (("ttft", "ttft_burn"),
                             ("per_token", "per_token_burn")):
                burn = legs.get(key) or 0.0
                worst = max(worst, burn)
                trigger = "slo:%s:%s" % (tenant, leg)
                firing = burn > self.burn_threshold
                if not self.gate.confirm(trigger, firing):
                    continue
                self.gate.clear(trigger)
                if self.gate.quarantined(trigger):
                    actions.append(self._record(AutopilotAction(
                        "remediate", trigger, mode, outcome="rejected",
                        detail={"reason": "quarantined",
                                "burn": round(burn, 3)})))
                    continue
                self._remediate_burn(actions, mode, trigger, tenant,
                                     leg, burn)
        obs.set_gauge("autopilot.worst_burn", worst)

    def _remediate_burn(self, actions, mode, trigger, tenant, leg,
                        burn):
        """One confirmed burn incident: detect span, then the most
        specific available remediation (kill degraded decode replica >
        warm-standby scale-up > admission reweight), then verify."""
        ctx = obs.TraceContext.new()
        with self._span("autopilot.detect", ctx, trigger=trigger,
                        tenant=tenant, leg=leg,
                        burn=round(burn, 3)) as sp:
            ictx = sp.ctx if sp is not None else ctx
        degraded = self._degraded_decode()
        if degraded is not None and self.gate.ready("kill_replica"):
            rid, lat, base = degraded
            act = AutopilotAction(
                "kill_replica", trigger, mode,
                detail={"replica": rid, "latency_s": round(lat, 4),
                        "baseline_s": round(base, 4), "leg": leg,
                        "burn": round(burn, 3)})
            if mode != "apply":
                actions.append(self._record(act, ctx=ictx))
                return
            before = self.disagg.stats().get("failed_streams", 0)
            with self._span("autopilot.act", ictx, kind="kill_replica",
                            replica=rid):
                try:
                    self.disagg.kill_replica(rid)
                except KeyError:
                    actions.append(self._record(act.resolve(
                        "rejected", reason="replica already gone"),
                        ctx=ictx))
                    return
            self.gate.stamp("kill_replica")
            self._lat_baseline.pop(rid, None)
            failed = (self.disagg.stats().get("failed_streams", 0)
                      - before)
            with self._span("autopilot.verify", ictx,
                            kind="kill_replica",
                            failed_streams=failed):
                pass
            actions.append(self._record(act.resolve(
                "verified" if failed == 0 else "applied",
                failed_streams=failed), ctx=ictx))
            return
        if self.router is not None and self.gate.ready("scale_up"):
            act = AutopilotAction(
                "scale_up", trigger, mode,
                detail={"leg": leg, "burn": round(burn, 3)})
            if mode != "apply":
                actions.append(self._record(act, ctx=ictx))
                return
            with self._span("autopilot.act", ictx, kind="scale_up"):
                replica = self.router.scale_up(reason="autopilot")
            if replica is None:
                actions.append(self._record(act.resolve(
                    "rejected", reason="no standby"), ctx=ictx))
                return
            self.gate.stamp("scale_up")
            actions.append(self._record(act.resolve(
                "applied", replica=replica.rid), ctx=ictx))
            return
        if self.gate.ready("reweight"):
            demoted = self._demote_best_effort(tenant, mode)
            act = AutopilotAction(
                "reweight", trigger, mode,
                detail={"burning_tenant": tenant, "leg": leg,
                        "burn": round(burn, 3), "demoted": demoted})
            if not demoted:
                act.resolve("rejected", reason="no demotable tenant")
            elif mode == "apply":
                self.gate.stamp("reweight")
                act.resolve("applied")
            actions.append(self._record(act, ctx=ictx))

    def _degraded_decode(self):
        """``(rid, latency, baseline)`` of the worst decode replica
        whose beacon latency sits ``degrade_factor`` over its own
        healthy baseline (captured by :meth:`_observe_fleet` while the
        fleet was healthy), or None. Never nominates the LAST decode
        replica — killing it would fail every stream, which is worse
        than any slowdown. In a uniformly slow fleet (traffic surge,
        host contention) only the max-latency replica is nominated,
        not all of them."""
        if self.disagg is None:
            return None
        try:
            lat = self.disagg.decode_latencies()
        except Exception:  # noqa: BLE001 — beacons are best-effort
            return None
        if len(lat) < 2:
            return None
        worst = None
        for rid, v in lat.items():
            base = self._lat_baseline.get(rid, v)
            if base <= 0 or v < self.degrade_factor * base:
                continue
            peers = [p for r, p in lat.items() if r != rid]
            med = _median(peers)
            if med is not None and v < self.degrade_factor * med \
                    and len(peers) >= 1:
                # worst of a uniformly slow fleet: still nominate the
                # max-latency one only if it IS the max
                if v < max(lat.values()):
                    continue
            if worst is None or v > worst[1]:
                worst = (rid, v, base)
        return worst

    def _demote_best_effort(self, burning, mode):
        """Demote (priority += 1) every tenant that is NOT the burning
        one and still has headroom below the lowest class — admission
        re-weighting that gives the burning tenant queue priority.
        Returns the list of demoted tenant names (propose mode lists
        them without mutating)."""
        from ..serving.disagg.tenancy import MAX_PRIORITY

        demoted = []
        for spec in self.tenants.specs():
            if spec.name == burning or spec.priority >= MAX_PRIORITY:
                continue
            demoted.append(spec.name)
            if mode == "apply":
                self.tenants.reweight(spec.name,
                                      priority=spec.priority + 1)
        return demoted

    # -- leg 3: SDC sentinel quarantine -------------------------------------
    def _leg_integrity(self, actions, mode):
        """Drain the SDC sentinel: run the cross-replica vote on any
        pending replay disagreements, then quarantine every
        confirmed-lying replica — journaled, gated, traced, and never
        the last decode replica (losing the fleet is strictly worse
        than corruption the sentinel already withheld)."""
        sent = self.sentinel
        if sent is None:
            return
        try:
            if sent.pending:
                sent.vote()
            verdicts = sent.confirmed_verdicts()
        except Exception:  # noqa: BLE001 — sentinel is best-effort
            obs.inc("autopilot.sentinel_errors")
            return
        for verdict in verdicts:
            self._quarantine_confirmed(actions, mode, verdict)

    def _quarantine_confirmed(self, actions, mode, verdict):
        """One confirmed SDC verdict -> a gated ``quarantine_replica``
        action, mirroring the kill path's detect/act/verify spans on
        one incident trace."""
        rid = verdict.get("replica")
        trigger = "sdc:%s" % (rid,)
        ctx = obs.TraceContext.new()
        with self._span("autopilot.detect", ctx, trigger=trigger,
                        replica=str(rid), step=verdict.get("step"),
                        votes=verdict.get("votes"),
                        peers=verdict.get("peers")) as sp:
            ictx = sp.ctx if sp is not None else ctx
        act = AutopilotAction(
            "quarantine_replica", trigger, mode,
            detail={"replica": rid, "step": verdict.get("step"),
                    "votes": verdict.get("votes"),
                    "peers": verdict.get("peers"),
                    "digest_live": verdict.get("digest_live"),
                    "majority_digest": verdict.get("majority_digest")})
        if self.disagg is None:
            actions.append(self._record(act.resolve(
                "rejected", reason="no disagg router"), ctx=ictx))
            return
        if not self.gate.ready("quarantine_replica"):
            actions.append(self._record(act.resolve(
                "rejected", reason="gate cooldown"), ctx=ictx))
            return
        _, decode_live = self.disagg.live_replicas()
        # the sentinel stringifies replica ids; map back to the
        # router's native rid before acting
        live_map = {str(r): r for r in decode_live}
        if str(rid) not in live_map:
            actions.append(self._record(act.resolve(
                "rejected", reason="replica already gone"), ctx=ictx))
            return
        if len(decode_live) <= 1:
            actions.append(self._record(act.resolve(
                "rejected", reason="last decode replica"), ctx=ictx))
            return
        rid = live_map[str(rid)]
        if mode != "apply":
            actions.append(self._record(act, ctx=ictx))
            return
        before = self.disagg.stats().get("failed_streams", 0)
        with self._span("autopilot.act", ictx,
                        kind="quarantine_replica", replica=str(rid)):
            try:
                self.disagg.quarantine_replica(rid)
            except KeyError:
                actions.append(self._record(act.resolve(
                    "rejected", reason="replica already gone"),
                    ctx=ictx))
                return
        self.gate.stamp("quarantine_replica")
        self._lat_baseline.pop(rid, None)
        failed = (self.disagg.stats().get("failed_streams", 0)
                  - before)
        with self._span("autopilot.verify", ictx,
                        kind="quarantine_replica",
                        failed_streams=failed):
            pass
        actions.append(self._record(act.resolve(
            "verified" if failed == 0 else "applied",
            failed_streams=failed), ctx=ictx))

    # -- leg 4: training divergence rollback --------------------------------
    def _leg_train(self, actions, mode):
        """Watch the active training run's convergence (a
        :class:`~paddle_tpu.observability.RunHealth` bundle, either
        passed directly or carried by the ``trainguard``): divergence
        confirmed over ``confirm_n`` consecutive ticks triggers a
        journaled rollback-to-last-finite-checkpoint + lr-cut. Quiet
        without a runhealth signal."""
        rh = self.runhealth
        if rh is None:
            rh = getattr(self.trainguard, "runhealth", None)
        if rh is None:
            return
        try:
            verdict = rh.diverging()
        except Exception:  # noqa: BLE001 — detector bug != outage
            obs.inc("autopilot.runhealth_errors")
            return
        trigger = "train:divergence"
        if not self.gate.confirm(trigger, verdict is not None):
            return
        self.gate.clear(trigger)
        if self.gate.quarantined(trigger):
            actions.append(self._record(AutopilotAction(
                "rollback_lr_cut", trigger, mode, outcome="rejected",
                detail={"reason": "quarantined", "anomaly": verdict})))
            return
        if not self.gate.ready("rollback_lr_cut"):
            return
        self._train_incident(actions, mode, trigger, verdict)

    def _train_incident(self, actions, mode, trigger, verdict):
        """One confirmed divergence: detect -> decide -> act (rollback
        + lr-cut via the TrainGuard) -> verify, children of one trace.
        Never acts on an unguarded executor — without a TrainGuard
        (whose every step runs under the GuardedExecutor) a state
        restore could race a live unguarded dispatch."""
        ctx = obs.TraceContext.new()
        with self._span("autopilot.detect", ctx, trigger=trigger,
                        anomaly=verdict.get("kind"),
                        step=verdict.get("step"),
                        last_step=verdict.get("last_step")) as sp:
            ictx = sp.ctx if sp is not None else ctx
        act = AutopilotAction(
            "rollback_lr_cut", trigger, mode,
            detail={"anomaly": verdict, "lr_cut": self.train_lr_cut})
        tg = self.trainguard
        guarded = tg is not None and getattr(tg, "guard", None) is not None
        with self._span("autopilot.decide", ictx,
                        kind="rollback_lr_cut", guarded=guarded):
            pass
        if not guarded:
            actions.append(self._record(act.resolve(
                "rejected", reason="no guarded executor"), ctx=ictx))
            return
        self.gate.stamp("rollback_lr_cut")
        if mode != "apply":
            actions.append(self._record(act, ctx=ictx))
            return
        with self._span("autopilot.act", ictx, kind="rollback_lr_cut",
                        lr_cut=self.train_lr_cut):
            try:
                result = tg.rollback_to_last_finite(
                    lr_scale=self.train_lr_cut)
            except Exception as e:  # noqa: BLE001 — failed act = no change
                actions.append(self._record(act.resolve(
                    "rejected", error="%s: %s"
                    % (type(e).__name__, str(e)[:200])), ctx=ictx))
                return
        if result is None:
            actions.append(self._record(act.resolve(
                "rejected", reason="no finite checkpoint"), ctx=ictx))
            return
        # rollback_to_last_finite only restores checkpoints whose float
        # state verified finite — surface that check as the verify leg
        with self._span("autopilot.verify", ictx,
                        kind="rollback_lr_cut", finite=True,
                        restored_step=result["step"],
                        lr=result.get("lr")):
            pass
        obs.inc("autopilot.train_rollbacks")
        actions.append(self._record(act.resolve(
            "verified", restored_step=result["step"],
            vars=result["vars"], skipped_steps=result["skipped_steps"],
            lr=result.get("lr")), ctx=ictx))

    # -- leg 5: re-plan on drift --------------------------------------------
    def _leg_drift(self, actions, mode):
        """Score measured step times against the *calibrated*
        re-prediction. Until the first calibration fit the leg stays
        quiet: table constants are nominal, and judging drift against
        them would re-plan on day one of every new device."""
        ratio = self._cal_ratio
        if not ratio or ratio <= 0:
            return
        try:
            rows = obs.drift_rows(self.ledger.snapshot())
        except Exception:  # noqa: BLE001
            return
        worst_pct = 0.0
        for row in rows:
            pred_ms = row.get("predicted_step_ms")
            meas_ms = row.get("measured_step_ms")
            if not pred_ms or not meas_ms:
                continue
            cal_pred_ms = pred_ms / ratio
            drift_pct = 100.0 * (meas_ms - cal_pred_ms) / cal_pred_ms
            worst_pct = max(worst_pct, abs(drift_pct))
            trigger = "drift:%s" % row.get("fingerprint")
            firing = abs(drift_pct) > self.drift_tolerance_pct
            if not self.gate.confirm(trigger, firing):
                continue
            self.gate.clear(trigger)
            if self.gate.quarantined(trigger):
                actions.append(self._record(AutopilotAction(
                    "replan", trigger, mode, outcome="rejected",
                    detail={"reason": "quarantined",
                            "drift_pct": round(drift_pct, 1)})))
                continue
            if not self.gate.ready("replan"):
                continue
            self._replan_incident(actions, mode, trigger, row,
                                  drift_pct)
        obs.set_gauge("autopilot.worst_drift_pct", worst_pct)

    def _replan_incident(self, actions, mode, trigger, row, drift_pct):
        """One confirmed drift incident: detect -> replan -> apply ->
        verify, all children of one trace. A regressing apply is
        rolled back and the trigger quarantined with backoff."""
        ctx = obs.TraceContext.new()
        with self._span("autopilot.detect", ctx, trigger=trigger,
                        drift_pct=round(drift_pct, 1),
                        kind_entry=row.get("kind"),
                        measured_ms=row.get("measured_step_ms")) as sp:
            ictx = sp.ctx if sp is not None else ctx
        profile = self.profile
        proposal = None
        with self._span("autopilot.replan", ictx,
                        profile=getattr(profile, "name", None)):
            if self.replan is not None:
                try:
                    proposal = self.replan(profile)
                except Exception as e:  # noqa: BLE001 — planner bug != outage
                    actions.append(self._record(AutopilotAction(
                        "replan", trigger, mode, outcome="rejected",
                        detail={"error": "%s: %s"
                                % (type(e).__name__, str(e)[:200])}),
                        ctx=ictx))
                    return
            if proposal is None:
                proposal = {"profile": profile.to_dict()
                            if profile is not None else None}
        self.gate.stamp("replan")
        act = AutopilotAction(
            "replan", trigger, mode,
            detail={"drift_pct": round(drift_pct, 1),
                    "proposal": proposal})
        if mode != "apply" or self.apply is None:
            actions.append(self._record(act, ctx=ictx))
            return
        before = self.measure() if self.measure is not None else None
        with self._span("autopilot.apply", ictx,
                        before_s=before):
            try:
                self.apply(proposal)
            except Exception as e:  # noqa: BLE001 — failed apply = no change
                actions.append(self._record(act.resolve(
                    "rejected", error="%s: %s"
                    % (type(e).__name__, str(e)[:200])), ctx=ictx))
                return
        after = self.measure() if self.measure is not None else None
        verdict = verify_measurement(
            before, after, tolerance_pct=self.verify_tolerance_pct,
            higher_is_better=False)
        with self._span("autopilot.verify", ictx,
                        after_s=after,
                        regressed=verdict["regressed"],
                        delta_pct=verdict["delta_pct"]):
            if verdict["regressed"]:
                if self.rollback is not None:
                    try:
                        self.rollback()
                    except Exception as e:  # noqa: BLE001
                        verdict["rollback_error"] = "%s: %s" % (
                            type(e).__name__, str(e)[:200])
                backoff = self.gate.quarantine(trigger)
                obs.inc("autopilot.rollbacks")
                actions.append(self._record(act.resolve(
                    "rolled_back", verify=verdict), ctx=ictx))
                actions.append(self._record(AutopilotAction(
                    "quarantine", trigger, mode, outcome="quarantined",
                    detail={"backoff_s": backoff,
                            "strikes": self.gate.state()
                            ["quarantine"][trigger]["strikes"]}),
                    ctx=ictx))
            else:
                actions.append(self._record(act.resolve(
                    "verified", verify=verdict), ctx=ictx))
