"""Self-healing performance autopilot: ledger -> planner -> fleet.

Every instrument the previous subsystems built — the cost-model
planner (``planner.plan_search``), per-tenant SLO burn rates
(``observability.SLOMonitor``), the executable ledger with
predicted-vs-measured drift and device auto-calibration
(``observability.ExecutableLedger`` + ``DeviceProfile
.calibrated_from``) — reported to a human who then edited configs.
This package closes the loop:

::

                 +--------------------------------------+
                 |            Autopilot.tick()          |
                 +--------------------------------------+
      measured     |  calibrate  |    SLO    |  drift   |
      step times   |  (profile   |   burn    | replan + |
    ledger ------->|   refit +   |  remedi-  |  gated   |
      SLO burn --->|   reprice)  |   ation   |  apply   |
                   +------+------+-----+-----+----+-----+
                          |            |          |
                          v            v          v
                    DeviceProfile  kill_replica  plan_search
                    +cal written   scale_up      -> rolling
                    to disk        reweight         reload

Modes (``PADDLE_TPU_AUTOPILOT``, read live every tick):

- ``off`` — the loop observes nothing and decides nothing.
- ``propose`` (default) — every decision is minted, journaled, and
  traced, but the fleet is never touched: a dry-run audit trail.
- ``apply`` — remediations execute, still rate-limited (hysteresis +
  cooldown), measured before/after, auto-rolled-back on a verified
  regression, and the offending trigger quarantined with exponential
  backoff.

The decision trail: every :class:`AutopilotAction` lands in the
append-only :class:`DecisionJournal` and as ``autopilot.detect`` /
``autopilot.replan`` / ``autopilot.act`` / ``autopilot.apply`` /
``autopilot.verify`` spans sharing one trace id per incident on the
PR-14 request timeline — one merged Perfetto doc shows the slowdown,
the detection, and the fix.
"""
from .actions import (AUTOPILOT_ENV, MODES, AutopilotAction,
                      DecisionJournal, autopilot_mode)
from .gates import ActionGate, verify_measurement
from .loop import Autopilot

__all__ = ["AUTOPILOT_ENV", "MODES", "ActionGate", "Autopilot",
           "AutopilotAction", "DecisionJournal", "autopilot_mode",
           "verify_measurement"]
