"""The one tolerant JSONL/JSON reader (pure stdlib).

Three hand-rolled copies of "skip the torn final line and keep going"
used to live in ``DecisionJournal.read_jsonl``,
``observability.distributed.read_spans``, and the elastic FileStore's
doc scan. They now share this reader, which also *counts* what it
skipped — a dropped record is a data-integrity signal, not something
to swallow silently.

Deliberately import-free of the rest of paddle_tpu: observability
imports this module, so it must never import observability back.
"""
import json


def parse_lines(lines):
    """Parse an iterable of JSONL lines -> ``(records, dropped)``.

    Blank lines are skipped without counting (a trailing newline is
    not corruption); unparseable lines — torn final line of an
    append-only log, a partial write racing the reader — are skipped
    and counted in ``dropped``.
    """
    records, dropped = [], 0
    for line in lines:
        line = line.strip()
        if not line:
            continue
        try:
            records.append(json.loads(line))
        except ValueError:
            dropped += 1
    return records, dropped


def read_jsonl(path):
    """Tolerantly read a JSONL file -> ``(records, dropped)``.

    A missing/unreadable file is ``([], 0)`` — absence is not
    corruption.
    """
    try:
        with open(path, "r", encoding="utf-8", errors="replace") as f:
            return parse_lines(f)
    except OSError:
        return [], 0


def read_json_doc(path):
    """Tolerantly read one JSON doc -> ``(doc_or_None, dropped)``.

    ``dropped`` is 1 when the file existed but did not parse (torn
    write, concurrent replace) and 0 otherwise; a missing file is
    ``(None, 0)``.
    """
    try:
        with open(path, "r", encoding="utf-8", errors="replace") as f:
            return json.load(f), 0
    except OSError:
        return None, 0
    except ValueError:
        return None, 1
