"""End-to-end data integrity (PR 17).

Every host<->disk and host<->host byte path in paddle_tpu carries a
content digest so torn writes, bit rot, and silent data corruption are
*detected and attributed* instead of deserialized into the job:

- :mod:`~paddle_tpu.integrity.digest` — sha256 content digests for
  byte payloads and tensors, plus :class:`IntegrityError` (an
  ``IOError`` subclass so existing fall-back paths treat a digest
  failure like any other unreadable artifact).
- :mod:`~paddle_tpu.integrity.envelope` — the versioned wire format:
  sealed byte blobs (magic + header + payload) for compile-cache
  entries, JSON manifests with per-tensor digests for checkpoint
  steps, and ``_integrity``-stamped JSON docs for FileStore
  mailboxes.
- :mod:`~paddle_tpu.integrity.jsonl` — the one tolerant JSONL/JSON
  reader (torn/blank final-line skip + ``dropped`` count) shared by
  the decision journal, distributed span collection, and FileStore.
- :mod:`~paddle_tpu.integrity.sentinel` — the SDC sentinel:
  deterministically sampled decode-step replay (re-dispatch the same
  program + feeds, compare fetch digests) plus a cross-replica vote
  that turns a confirmed-disagreeing replica into a
  ``quarantine_replica`` autopilot action.

Corruption is drillable end to end via the ``corrupt=`` fault-spec
arms (``PADDLE_TPU_FAULT_SPEC="wire:at=1:corrupt=bitflip"``, see
:mod:`paddle_tpu.fluid.resilience`).

The package import is deliberately lazy — ``jsonl`` is pure stdlib so
observability can use it without pulling numpy/jax.
"""

_SUBMODULES = ("digest", "envelope", "jsonl", "sentinel")
_NAMES = {
    "IntegrityError": "digest",
    "bytes_digest": "digest",
    "tensor_digest": "digest",
    "digest_state": "digest",
    "state_mismatches": "digest",
    "doc_digest": "digest",
    "SDCSentinel": "sentinel",
}


def __getattr__(name):
    import importlib
    if name in _SUBMODULES:
        return importlib.import_module("." + name, __name__)
    mod = _NAMES.get(name)
    if mod is not None:
        return getattr(importlib.import_module("." + mod, __name__), name)
    raise AttributeError("module %r has no attribute %r" % (__name__, name))


def __dir__():
    return sorted(list(_SUBMODULES) + list(_NAMES) + list(globals()))
