"""Content digests for byte payloads and tensors.

One algorithm (sha256), one textual form (``"sha256:<hex>"``), used by
every integrity surface: checkpoint manifests, KV-handoff wire docs,
compile-cache envelopes, FileStore mailbox stamps, and the SDC
sentinel's fetch-digest comparisons. Streaming-friendly —
:func:`bytes_digest` accepts an iterable of chunks and
:func:`file_digest` never holds more than one chunk in memory.

numpy is imported lazily so stdlib-only consumers (observability) can
import the sibling :mod:`~paddle_tpu.integrity.jsonl` without pulling
the numeric stack.
"""
import hashlib
import json

DIGEST_ALGO = "sha256"
_PREFIX = DIGEST_ALGO + ":"


class IntegrityError(IOError):
    """A payload failed content-digest verification.

    Subclasses ``IOError`` deliberately: every existing "skip the bad
    artifact and fall back" path (``restore_latest``, compile-cache
    corrupt-evict, stream migration) already handles ``IOError``, so a
    digest failure is remediated by the same machinery that handles a
    torn file — but with attribution (``path``/``tensor``/``want``/
    ``got`` name exactly what lied).
    """

    def __init__(self, message, path=None, tensor=None, want=None,
                 got=None):
        super().__init__(message)
        self.path = path
        self.tensor = tensor
        self.want = want
        self.got = got


def bytes_digest(data):
    """``"sha256:<hex>"`` of a bytes-like object or iterable of chunks."""
    h = hashlib.sha256()
    if isinstance(data, (bytes, bytearray, memoryview)):
        h.update(data)
    else:
        for chunk in data:
            h.update(chunk)
    return _PREFIX + h.hexdigest()


def file_digest(path, chunk_size=1 << 20):
    """Streaming digest of a file's contents."""
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            chunk = f.read(chunk_size)
            if not chunk:
                break
            h.update(chunk)
    return _PREFIX + h.hexdigest()


def doc_digest(doc):
    """Digest of a JSON-serializable doc under a canonical encoding
    (sorted keys, minimal separators) — stable across a json
    round-trip, so a stamp computed at ``put`` verifies at read."""
    enc = json.dumps(doc, sort_keys=True, separators=(",", ":"),
                     default=str)
    return bytes_digest(enc.encode("utf-8"))


def tensor_digest(arr):
    """Digest of one tensor: dtype + shape header, then C-order bytes.

    Any array-like (numpy, jax, python scalar) is accepted; device
    arrays transfer once. Two tensors share a digest iff they are
    bit-identical with the same dtype and shape.
    """
    import numpy as np
    a = np.ascontiguousarray(np.asarray(arr))
    h = hashlib.sha256()
    h.update(("%s;%s;" % (a.dtype.str,
                          "x".join(str(d) for d in a.shape))).encode())
    h.update(a.data)  # zero-copy: hash the buffer, don't duplicate it
    return _PREFIX + h.hexdigest()


def digest_state(state):
    """Per-tensor digests of a state dict: ``{name: "sha256:..."}``."""
    return {str(k): tensor_digest(v) for k, v in state.items()}


def state_mismatches(state, digests):
    """Compare a state dict against recorded per-tensor digests.

    Returns ``[(name, want, got), ...]`` for every tensor whose digest
    disagrees (``got`` is ``None`` for a tensor missing from
    ``state``). Empty list means every recorded tensor verified.
    """
    out = []
    for name, want in sorted(digests.items()):
        if name not in state:
            out.append((name, want, None))
            continue
        got = tensor_digest(state[name])
        if got != want:
            out.append((name, want, got))
    return out
