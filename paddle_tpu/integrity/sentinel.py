"""SDC sentinel: catch the chip that computes the wrong answer.

Silent data corruption does not crash — a defective core returns
plausible garbage with a clean exit code. The sentinel's contract:

1. **Sampled replay** — every ``check_every``-th decode/exec step is
   re-dispatched with the *same* program and the *same* feeds, and the
   fetch digests are compared. The step is deterministic (one jitted
   XLA module, fixed inputs), so any disagreement is hardware lying,
   not numerics. The check runs *before* the step's tokens are
   emitted, so a disagreeing step never serves its output.
2. **Cross-replica vote** — a replay disagreement is a suspicion, not
   a verdict. Peer replicas re-run the suspect's feeds; if the peers
   agree with each other (majority digest) the suspect is confirmed
   as the liar.
3. **Quarantine** — confirmed verdicts are drained by the autopilot,
   which mints a journaled, gated, traced ``quarantine_replica``
   action (never the last replica) that pulls the chip out of
   rotation; live sessions migrate bit-exactly.

Counters: ``integrity.sdc_replay_ok`` / ``sdc_replay_disagree`` /
``sdc_vote_confirmed`` / ``sdc_vote_inconclusive``; events
``integrity_sdc_disagree`` / ``integrity_sdc_confirmed``.
"""
import collections
import os
import threading
import time

from .. import observability as obs
from .digest import tensor_digest

# Default replay sampling period. At 1-in-128 the replay adds ~0.8%
# to steady-state step cost — inside the <2% overhead budget with
# headroom for the digest transfers.
DEFAULT_CHECK_EVERY = 128
_CHECK_EVERY_ENV = "PADDLE_TPU_SDC_CHECK_EVERY"


def fetch_digest(outs):
    """One digest for a whole fetch set (dict or sequence of arrays),
    order-independent for dicts."""
    if isinstance(outs, dict):
        items = [(str(k), outs[k]) for k in sorted(outs, key=str)]
    else:
        items = [(str(i), v) for i, v in enumerate(outs)]
    import hashlib
    h = hashlib.sha256()
    for name, v in items:
        h.update(name.encode("utf-8"))
        h.update(tensor_digest(v).encode("ascii"))
    return "sha256:" + h.hexdigest()


class SDCSentinel:
    """Deterministically sampled replay checker + cross-replica vote.

    ``check_every`` defaults to ``PADDLE_TPU_SDC_CHECK_EVERY`` (else
    128); ``0`` disarms sampling entirely (``sample`` is then a pure
    counter bump). Engines attach via
    ``DecodeEngine.attach_sentinel``; the disagg router registers one
    replay callable per decode replica so votes can re-run a
    suspect's feeds on its peers.
    """

    def __init__(self, check_every=None):
        if check_every is None:
            check_every = int(
                os.environ.get(_CHECK_EVERY_ENV, DEFAULT_CHECK_EVERY))
        self.check_every = int(check_every)
        self._lock = threading.Lock()
        self._counts = {}
        self.pending = collections.deque()    # disagreements -> vote
        self.confirmed = collections.deque()  # verdicts -> autopilot
        self._replay_fns = {}                 # rid -> feeds -> outs

    # -- replica registry (for votes) -------------------------------------
    def register(self, replica, replay_fn):
        with self._lock:
            self._replay_fns[str(replica)] = replay_fn

    def unregister(self, replica):
        with self._lock:
            self._replay_fns.pop(str(replica), None)

    # -- sampling + replay -------------------------------------------------
    def sample(self, replica="default"):
        """True on the deterministically chosen steps for ``replica``."""
        with self._lock:
            n = self._counts[replica] = self._counts.get(replica, 0) + 1
        return self.check_every > 0 and n % self.check_every == 0

    def replay_check(self, replica, run_fn, outs, feeds=None, step=None):
        """Re-dispatch and compare. True = digests agree; False files
        a pending disagreement for the cross-replica vote.

        ``run_fn`` must re-run the *same* program on the *same* feeds
        (callers capture the feed refs before the live dispatch
        mutates engine state).
        """
        d0 = fetch_digest(outs)
        t0 = time.monotonic()
        outs2 = run_fn()
        d1 = fetch_digest(outs2)
        obs.observe("integrity.sdc_replay_seconds", time.monotonic() - t0)
        if d0 == d1:
            obs.inc("integrity.sdc_replay_ok")
            return True
        obs.inc("integrity.sdc_replay_disagree")
        obs.event("integrity_sdc_disagree", source="integrity",
                  replica=str(replica), step=step,
                  digest_live=d0[:23], digest_replay=d1[:23])
        with self._lock:
            self.pending.append({"replica": str(replica), "feeds": feeds,
                                 "digests": (d0, d1), "step": step})
        return False

    # -- cross-replica vote ------------------------------------------------
    def vote(self):
        """Adjudicate one pending disagreement; returns the verdict
        dict if the suspect is confirmed, else ``None``.

        Peers (every registered replica except the suspect) re-run the
        suspect's feeds; the majority digest among peers is the
        reference answer. The suspect already disagreed with *itself*
        (live vs replay), so peers converging on any answer confirms
        the suspect as the unstable party. No peers, or peers that
        cannot agree, is inconclusive — never a quarantine.
        """
        with self._lock:
            if not self.pending:
                return None
            entry = self.pending.popleft()
            peers = {rid: fn for rid, fn in self._replay_fns.items()
                     if rid != entry["replica"]}
        votes = {}
        for rid, fn in peers.items():
            try:
                votes[rid] = fetch_digest(fn(entry["feeds"]))
            except Exception:  # noqa: BLE001 — a dead peer abstains
                continue
        tally = collections.Counter(votes.values())
        top = tally.most_common(1)
        quorum = len(votes) // 2 + 1
        if not top or top[0][1] < quorum:
            obs.inc("integrity.sdc_vote_inconclusive")
            obs.event("integrity_sdc_vote_inconclusive",
                      source="integrity", replica=entry["replica"],
                      peers=len(votes))
            return None
        verdict = {"replica": entry["replica"], "step": entry["step"],
                   "peers": len(votes), "votes": top[0][1],
                   "majority_digest": top[0][0][:23],
                   "digest_live": entry["digests"][0][:23],
                   "digest_replay": entry["digests"][1][:23]}
        obs.inc("integrity.sdc_vote_confirmed")
        obs.event("integrity_sdc_confirmed", source="integrity",
                  **verdict)
        with self._lock:
            self.confirmed.append(verdict)
        return verdict

    def confirmed_verdicts(self):
        """Drain confirmed verdicts (autopilot consumes these)."""
        out = []
        with self._lock:
            while self.confirmed:
                out.append(self.confirmed.popleft())
        return out

    def stats(self):
        with self._lock:
            return {"check_every": self.check_every,
                    "replicas": sorted(self._replay_fns),
                    "sampled": dict(self._counts),
                    "pending": len(self.pending),
                    "confirmed": len(self.confirmed)}
