"""Versioned content-digest envelopes for every byte path.

Three envelope shapes, one version number:

- **Sealed blobs** (compile-cache ``.jaxexp`` entries): ``MAGIC`` +
  one JSON header line (version, kind, size, digest) + raw payload.
  :func:`unseal_bytes` verifies size then digest and raises
  :class:`~paddle_tpu.integrity.digest.IntegrityError` with the check
  that failed.
- **Manifest docs** (checkpoint steps, done-markers): a JSON doc with
  per-tensor digests, written atomically next to (never inside) the
  orbax step dir.
- **Stamped docs** (FileStore mailboxes): the payload dict itself
  carries an ``_integrity`` key with a canonical-JSON digest of the
  rest of the doc; readers verify and strip the stamp so consumers
  see exactly the doc that was ``put``.

Writers route their encoded bytes through the ``save``/``load``/
``wire``/``mailbox`` corruption fault sites
(:func:`paddle_tpu.fluid.resilience.fault_corrupt`) so every
detection path here is drillable from ``PADDLE_TPU_FAULT_SPEC``.
"""
import json
import os
import uuid

from .digest import IntegrityError, bytes_digest, doc_digest

FORMAT = "paddle-tpu-integrity"
VERSION = 1
MAGIC = b"PTIV1\n"
STAMP_KEY = "_integrity"


def _fault(site, data):
    """Route bytes through the corruption fault injector (lazy import
    so the envelope stays usable before fluid is importable)."""
    try:
        from ..fluid.resilience import fault_corrupt
    except Exception:  # pragma: no cover - circular/partial import
        return data
    return fault_corrupt(site, data)


# -- sealed byte blobs ----------------------------------------------------

def seal_bytes(payload, kind="blob", meta=None):
    """Wrap raw bytes in a digest envelope: MAGIC + header line + payload."""
    doc = {"fmt": FORMAT, "v": VERSION, "kind": kind,
           "size": len(payload), "digest": bytes_digest(payload)}
    if meta:
        doc.update(meta)
    header = json.dumps(doc, sort_keys=True,
                        separators=(",", ":")).encode("utf-8")
    return MAGIC + header + b"\n" + bytes(payload)


def is_sealed(data):
    return bytes(data[:len(MAGIC)]) == MAGIC


def unseal_bytes(data, kind=None, path=None):
    """Verify and strip a sealed envelope, returning the payload.

    Raises :class:`IntegrityError` naming the failing check: missing
    or torn header, version/kind mismatch, truncated payload, or
    digest mismatch.
    """
    if not is_sealed(data):
        raise IntegrityError(
            "missing integrity envelope (no %r magic): %s"
            % (MAGIC, path or "<bytes>"), path=path)
    body = bytes(data[len(MAGIC):])
    nl = body.find(b"\n")
    if nl < 0:
        raise IntegrityError(
            "torn integrity envelope header: %s" % (path or "<bytes>"),
            path=path)
    try:
        doc = json.loads(body[:nl].decode("utf-8"))
        if not isinstance(doc, dict):
            raise ValueError("header is not a dict")
    except (ValueError, UnicodeDecodeError) as e:
        raise IntegrityError(
            "unreadable integrity envelope header (%s): %s"
            % (e, path or "<bytes>"), path=path)
    if doc.get("fmt") != FORMAT or doc.get("v") != VERSION:
        raise IntegrityError(
            "unsupported integrity envelope %r v%r: %s"
            % (doc.get("fmt"), doc.get("v"), path or "<bytes>"),
            path=path)
    if kind is not None and doc.get("kind") != kind:
        raise IntegrityError(
            "integrity envelope kind %r, expected %r: %s"
            % (doc.get("kind"), kind, path or "<bytes>"), path=path)
    payload = body[nl + 1:]
    if len(payload) != doc.get("size"):
        raise IntegrityError(
            "truncated payload (%d of %s bytes): %s"
            % (len(payload), doc.get("size"), path or "<bytes>"),
            path=path, want=doc.get("digest"))
    got = bytes_digest(payload)
    if got != doc.get("digest"):
        raise IntegrityError(
            "payload digest mismatch (want %s got %s): %s"
            % (doc.get("digest"), got, path or "<bytes>"),
            path=path, want=doc.get("digest"), got=got)
    return payload


# -- manifest docs (checkpoints) ------------------------------------------

def make_manifest(digests, kind, **meta):
    doc = {"fmt": FORMAT, "v": VERSION, "kind": kind,
           "digests": dict(digests)}
    doc.update(meta)
    return doc


def write_manifest(path, doc):
    """Atomic (tmp + rename) manifest write, routed through the
    ``save`` corruption fault site."""
    data = json.dumps(doc, sort_keys=True).encode("utf-8")
    data = _fault("save", data)
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = "%s.tmp.%d.%s" % (path, os.getpid(), uuid.uuid4().hex[:8])
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def read_manifest(path):
    """Read a manifest: ``None`` if absent; :class:`IntegrityError` if
    present but torn, unparseable, or the wrong format — a manifest
    that cannot be trusted fails verification rather than silently
    disabling it."""
    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError:
        return None
    data = _fault("load", data)
    try:
        doc = json.loads(data.decode("utf-8"))
        if not isinstance(doc, dict):
            raise ValueError("manifest is not a dict")
    except (ValueError, UnicodeDecodeError) as e:
        raise IntegrityError(
            "unreadable integrity manifest (%s): %s" % (e, path),
            path=path)
    if doc.get("fmt") != FORMAT or doc.get("v") != VERSION:
        raise IntegrityError(
            "unsupported integrity manifest %r v%r: %s"
            % (doc.get("fmt"), doc.get("v"), path), path=path)
    return doc


# -- stamped JSON docs (FileStore mailboxes) ------------------------------

def stamp_doc(doc):
    """Return a copy of ``doc`` carrying an ``_integrity`` stamp over
    its canonical JSON encoding (any pre-existing stamp is replaced)."""
    body = {k: v for k, v in doc.items() if k != STAMP_KEY}
    out = dict(body)
    out[STAMP_KEY] = {"v": VERSION, "digest": doc_digest(body)}
    return out


def check_doc(doc):
    """Verify a stamped doc: ``(ok, cleaned_doc)``.

    Unstamped docs pass unchanged (pre-integrity writers and foreign
    docs stay readable); stamped docs are verified and returned with
    the stamp stripped so consumers never see the envelope.
    """
    stamp = doc.get(STAMP_KEY)
    if stamp is None:
        return True, doc
    body = {k: v for k, v in doc.items() if k != STAMP_KEY}
    ok = (isinstance(stamp, dict)
          and stamp.get("digest") == doc_digest(body))
    return ok, body
