"""Pascal VOC2012 segmentation reader (ref: python/paddle/dataset/
voc2012.py). Yields (image CHW float32, label HW int32) pairs; synthetic
deterministic scenes with consistent image/mask geometry (zero egress)."""
import numpy as np

__all__ = ["train", "test", "val"]

_CLASSES = 21  # 20 + background
_HW = 64


def _scene(rng):
    img = rng.uniform(0, 0.2, size=(3, _HW, _HW)).astype("float32")
    label = np.zeros((_HW, _HW), "int32")
    for _ in range(int(rng.integers(1, 4))):
        cls = int(rng.integers(1, _CLASSES))
        x0, y0 = rng.integers(0, _HW - 16, size=2)
        w, h = rng.integers(8, 16, size=2)
        label[y0:y0 + h, x0:x0 + w] = cls
        # objects are brighter, per-class tint so the mapping is learnable
        img[:, y0:y0 + h, x0:x0 + w] = (
            np.array([cls / _CLASSES, 1 - cls / _CLASSES, 0.5],
                     "float32")[:, None, None]
        )
    return img, label


def _creator(split):
    def reader():
        rng = np.random.default_rng(
            {"train": 61, "test": 62, "val": 63}[split]
        )
        n = {"train": 200, "test": 60, "val": 60}[split]
        for _ in range(n):
            yield _scene(rng)

    return reader


def train():
    return _creator("train")


def test():
    return _creator("test")


def val():
    return _creator("val")
