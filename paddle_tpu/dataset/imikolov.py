"""PTB language-model reader (ref: python/paddle/dataset/imikolov.py).
Builds a word dict and yields n-gram windows (or sequences) of word ids.
Synthesises a Zipfian corpus when PADDLE_TPU_PTB_PATH is absent."""
import os

import numpy as np

__all__ = ["build_dict", "train", "test", "NGram", "Seq"]

NGram = "ngram"
Seq = "seq"

_SYNTH_VOCAB = 1000


def _corpus(split):
    path = os.environ.get("PADDLE_TPU_PTB_PATH")
    if path:
        fname = os.path.join(
            path, "ptb.train.txt" if split == "train" else "ptb.valid.txt"
        )
        with open(fname) as f:
            for line in f:
                yield line.split()
        return
    rng = np.random.default_rng(3 if split == "train" else 4)
    zipf = rng.zipf(1.3, size=(400, 20)) % _SYNTH_VOCAB
    for row in zipf:
        yield ["w%d" % w for w in row]


def build_dict(min_word_freq=0):
    freq = {}
    for words in _corpus("train"):
        for w in words:
            freq[w] = freq.get(w, 0) + 1
    freq = {w: c for w, c in freq.items() if c > min_word_freq}
    ordered = sorted(freq.items(), key=lambda kv: (-kv[1], kv[0]))
    word_idx = {w: i for i, (w, _) in enumerate(ordered)}
    word_idx["<unk>"] = len(word_idx)
    return word_idx


def _reader_creator(split, word_idx, n, data_type):
    def reader():
        unk = word_idx["<unk>"]
        for words in _corpus(split):
            ids = [word_idx.get(w, unk) for w in words] + [unk]
            if data_type == NGram:
                for i in range(len(ids) - n + 1):
                    yield tuple(ids[i:i + n])
            else:
                yield ids[:-1], ids[1:]

    return reader


def train(word_idx, n, data_type=NGram):
    return _reader_creator("train", word_idx, n, data_type)


def test(word_idx, n, data_type=NGram):
    return _reader_creator("test", word_idx, n, data_type)
