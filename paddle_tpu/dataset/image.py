"""Image transform utilities (ref: python/paddle/dataset/image.py).

Pure-numpy implementations (the reference shells out to cv2; this
environment has no cv2 and the transforms are trivial array ops). Images
are HWC uint8/float arrays unless stated otherwise.
"""
import numpy as np

__all__ = [
    "load_image_bytes", "load_image", "resize_short", "to_chw",
    "center_crop", "random_crop", "left_right_flip", "simple_transform",
    "load_and_transform", "batch_images_from_tar",
]


def load_image_bytes(data, is_color=True):
    """Decode raw image bytes. Supports the uncompressed .npy byte form
    this zero-egress environment uses (cv2.imdecode in the reference)."""
    import io

    arr = np.load(io.BytesIO(data), allow_pickle=False)
    return _color(arr, is_color)


def load_image(file, is_color=True):
    arr = np.load(file, allow_pickle=False)
    return _color(arr, is_color)


def _color(im, is_color):
    if is_color and im.ndim == 2:
        im = np.stack([im] * 3, axis=-1)
    if not is_color and im.ndim == 3:
        im = im.mean(axis=-1)
    return im


def _resize_bilinear(im, oh, ow):
    h, w = im.shape[:2]
    ys = (np.arange(oh) + 0.5) * h / oh - 0.5
    xs = (np.arange(ow) + 0.5) * w / ow - 0.5
    y0 = np.clip(np.floor(ys).astype(int), 0, h - 1)
    x0 = np.clip(np.floor(xs).astype(int), 0, w - 1)
    y1 = np.clip(y0 + 1, 0, h - 1)
    x1 = np.clip(x0 + 1, 0, w - 1)
    wy = np.clip(ys - y0, 0, 1)[:, None]
    wx = np.clip(xs - x0, 0, 1)[None, :]
    if im.ndim == 3:
        wy = wy[..., None]
        wx = wx[..., None]
    a = im[y0][:, x0].astype(np.float64)
    b = im[y0][:, x1].astype(np.float64)
    c = im[y1][:, x0].astype(np.float64)
    d = im[y1][:, x1].astype(np.float64)
    out = (a * (1 - wy) * (1 - wx) + b * (1 - wy) * wx
           + c * wy * (1 - wx) + d * wy * wx)
    return out.astype(im.dtype)


def resize_short(im, size):
    """Resize so the SHORTER edge equals `size`, keeping aspect ratio."""
    h, w = im.shape[:2]
    if h < w:
        oh, ow = size, int(round(w * size / h))
    else:
        oh, ow = int(round(h * size / w)), size
    return _resize_bilinear(im, oh, ow)


def to_chw(im, order=(2, 0, 1)):
    return im.transpose(order)


def _check_crop(im, size):
    h, w = im.shape[:2]
    if size > h or size > w:
        raise ValueError(
            "crop size %d exceeds image dims (%d, %d) — resize first"
            % (size, h, w)
        )


def center_crop(im, size, is_color=True):
    _check_crop(im, size)
    h, w = im.shape[:2]
    y0 = max((h - size) // 2, 0)
    x0 = max((w - size) // 2, 0)
    return im[y0:y0 + size, x0:x0 + size]


def random_crop(im, size, is_color=True):
    _check_crop(im, size)
    h, w = im.shape[:2]
    y0 = np.random.randint(0, max(h - size, 0) + 1)
    x0 = np.random.randint(0, max(w - size, 0) + 1)
    return im[y0:y0 + size, x0:x0 + size]


def left_right_flip(im, is_color=True):
    return im[:, ::-1]


def simple_transform(im, resize_size, crop_size, is_train,
                     is_color=True, mean=None):
    """resize_short -> crop (random+flip when training, center otherwise)
    -> CHW float32 -> optional mean subtraction (ref image.py:327)."""
    im = resize_short(im, resize_size)
    if is_train:
        im = random_crop(im, crop_size, is_color)
        if np.random.randint(2) == 0:
            im = left_right_flip(im, is_color)
    else:
        im = center_crop(im, crop_size, is_color)
    if im.ndim == 3:
        im = to_chw(im)
    im = im.astype("float32")
    if mean is not None:
        mean = np.asarray(mean, "float32")
        if mean.ndim == 1 and im.ndim == 3:
            mean = mean[:, None, None]
        im -= mean
    return im


def load_and_transform(filename, resize_size, crop_size, is_train,
                       is_color=True, mean=None):
    return simple_transform(
        load_image(filename, is_color), resize_size, crop_size, is_train,
        is_color, mean,
    )


def batch_images_from_tar(data_file, dataset_name, img2label,
                          num_per_batch=1024):
    raise NotImplementedError(
        "batch_images_from_tar: tar ingestion is host tooling outside this "
        "zero-egress image; stage .npy arrays and use load_image instead"
    )
