"""WMT16 en-de reader (ref: python/paddle/dataset/wmt16.py). Yields
(src_ids, trg_ids, trg_next_ids) with <s>/<e>/<unk> framing like the
reference; synthesises a deterministic parallel corpus (zero egress)."""
import numpy as np

__all__ = ["train", "test", "validation", "get_dict"]

_VOCAB = 500


def get_dict(lang, dict_size=_VOCAB, reverse=False):
    words = ["<s>", "<e>", "<unk>"] + [
        "%s%d" % (lang, i) for i in range(dict_size - 3)
    ]
    if reverse:
        return {i: w for i, w in enumerate(words)}
    return {w: i for i, w in enumerate(words)}


def _pairs(split, src_dict_size, trg_dict_size):
    rng = np.random.default_rng(
        {"train": 21, "test": 22, "validation": 23}[split]
    )
    n = {"train": 800, "test": 150, "validation": 150}[split]
    for _ in range(n):
        slen = int(rng.integers(3, 12))
        src = rng.integers(3, src_dict_size, size=slen)
        # target = deterministic transform of source (learnable mapping)
        trg = [(int(w) * 7 + 3) % (trg_dict_size - 3) + 3 for w in src]
        if int(rng.integers(0, 2)):
            trg = trg[: max(2, slen - 1)]
        yield (
            [int(w) for w in src],
            [0] + trg,          # <s> + target
            trg + [1],          # target + <e>
        )


def _reader_creator(split, src_dict_size, trg_dict_size, src_lang):
    # src_lang selects translation direction (ref wmt16.py): "en" reads
    # en->de; "de" swaps the pair so the German side is the source.
    if src_lang not in ("en", "de"):
        raise ValueError("wmt16: src_lang must be 'en' or 'de'")
    # generate each side under the vocab that will consume it: for "de"
    # the German (generated-target) side becomes the source, so it must
    # be drawn from src_dict_size
    gen_src, gen_trg = (
        (trg_dict_size, src_dict_size) if src_lang == "de"
        else (src_dict_size, trg_dict_size)
    )

    def reader():
        for src, trg_in, trg_next in _pairs(split, gen_src, gen_trg):
            if src_lang == "de":
                de = trg_in[1:]  # strip <s> to recover the raw target side
                yield de, [0] + src, src + [1]
            else:
                yield src, trg_in, trg_next

    return reader


def train(src_dict_size=_VOCAB, trg_dict_size=_VOCAB, src_lang="en"):
    return _reader_creator("train", src_dict_size, trg_dict_size, src_lang)


def test(src_dict_size=_VOCAB, trg_dict_size=_VOCAB, src_lang="en"):
    return _reader_creator("test", src_dict_size, trg_dict_size, src_lang)


def validation(src_dict_size=_VOCAB, trg_dict_size=_VOCAB, src_lang="en"):
    return _reader_creator(
        "validation", src_dict_size, trg_dict_size, src_lang)
