"""WMT14 fr-en reader (ref: python/paddle/dataset/wmt14.py). Same yield
schema — (src_ids, trg_ids, trg_next_ids) with <s>/<e>/<unk> framing —
over a deterministic synthetic parallel corpus (zero egress)."""
import numpy as np

__all__ = ["train", "test", "gen", "get_dict"]

START = "<s>"
END = "<e>"
UNK = "<unk>"
UNK_IDX = 2


def _dicts(dict_size):
    words = [START, END, UNK] + ["w%d" % i for i in range(dict_size - 3)]
    src = {w: i for i, w in enumerate(words)}
    trg = {w: i for i, w in enumerate(words)}
    return src, trg


def _samples(split, dict_size):
    rng = np.random.default_rng({"train": 41, "test": 42, "gen": 43}[split])
    n = {"train": 800, "test": 150, "gen": 50}[split]
    for _ in range(n):
        slen = int(rng.integers(3, 15))
        src = rng.integers(3, dict_size, size=slen)
        trg = [(int(w) * 11 + 5) % (dict_size - 3) + 3 for w in src]
        yield (
            [int(w) for w in src],
            [0] + trg,          # <s> + target
            trg + [1],          # target + <e>
        )


def _creator(split, dict_size):
    def reader():
        yield from _samples(split, dict_size)

    return reader


def train(dict_size):
    return _creator("train", dict_size)


def test(dict_size):
    return _creator("test", dict_size)


def gen(dict_size):
    return _creator("gen", dict_size)


def get_dict(dict_size, reverse=True):
    src, trg = _dicts(dict_size)
    if reverse:
        src = {v: k for k, v in src.items()}
        trg = {v: k for k, v in trg.items()}
    return src, trg


def fetch():
    """No-op (zero-egress): the corpus is synthesized on the fly."""
