"""CIFAR-10/100 readers (ref: python/paddle/dataset/cifar.py). Loads the
python-pickle batches from PADDLE_TPU_CIFAR_DIR when present, else serves a
deterministic synthetic set with the real schema: (3072 float32 image in
[0, 1] laid out CHW, int64 label)."""
import os
import pickle

import numpy as np

__all__ = ["train10", "test10", "train100", "test100"]


def _synthetic(n, n_classes, seed):
    rng = np.random.default_rng(seed)
    images = rng.random((n, 3072)).astype("float32") * 0.4
    labels = rng.integers(0, n_classes, size=n).astype("int64")
    stride = 3072 // n_classes
    for i in range(n):
        c = int(labels[i])
        images[i, c * stride:(c + 1) * stride] += 0.5
    return np.clip(images, 0.0, 1.0), labels


def _load_batches(d, names, label_key):
    images, labels = [], []
    for name in names:
        with open(os.path.join(d, name), "rb") as f:
            batch = pickle.load(f, encoding="latin1")
        images.append(np.asarray(batch["data"], "float32") / 255.0)
        labels.append(np.asarray(batch[label_key], "int64"))
    return np.concatenate(images), np.concatenate(labels)


def _reader_creator(split, n_classes, n_synth, seed):
    def reader():
        d = os.environ.get("PADDLE_TPU_CIFAR_DIR")
        if d:
            if n_classes == 10:
                names = (
                    ["data_batch_%d" % i for i in range(1, 6)]
                    if split == "train" else ["test_batch"]
                )
                images, labels = _load_batches(d, names, "labels")
            else:
                names = ["train"] if split == "train" else ["test"]
                images, labels = _load_batches(d, names, "fine_labels")
        else:
            images, labels = _synthetic(n_synth, n_classes, seed)
        for i in range(len(labels)):
            yield images[i], int(labels[i])

    return reader


def train10():
    return _reader_creator("train", 10, 2000, 7)


def test10():
    return _reader_creator("test", 10, 400, 8)


def train100():
    return _reader_creator("train", 100, 2000, 9)


def test100():
    return _reader_creator("test", 100, 400, 10)
