"""Canned datasets (ref: python/paddle/dataset/). Zero-egress environment:
each dataset synthesizes a deterministic stand-in with the real schema/shape
unless local files are provided via env vars."""
from . import mnist  # noqa: F401
from . import uci_housing  # noqa: F401
from . import imdb  # noqa: F401
