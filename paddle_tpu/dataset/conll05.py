"""CoNLL-2005 SRL reader (ref: python/paddle/dataset/conll05.py). Yields the
8-slot tuple the reference's label_semantic_roles chapter consumes:
(word_ids, ctx_n2, ctx_n1, ctx_0, ctx_p1, ctx_p2, pred_ids, mark, target)."""
import numpy as np

__all__ = ["get_dict", "get_embedding", "test"]

_WORD_VOCAB = 300
_LABEL_N = 30
_PRED_VOCAB = 50


def get_dict():
    word_dict = {"w%d" % i: i for i in range(_WORD_VOCAB)}
    verb_dict = {"v%d" % i: i for i in range(_PRED_VOCAB)}
    label_dict = {"L%d" % i: i for i in range(_LABEL_N)}
    return word_dict, verb_dict, label_dict


def get_embedding():
    rng = np.random.default_rng(17)
    return rng.standard_normal((_WORD_VOCAB, 32)).astype("float32")


def _samples():
    rng = np.random.default_rng(19)
    for _ in range(200):
        n = int(rng.integers(4, 15))
        words = [int(w) for w in rng.integers(0, _WORD_VOCAB, size=n)]
        pred_pos = int(rng.integers(0, n))
        pred = [int(rng.integers(0, _PRED_VOCAB))] * n
        mark = [1 if i == pred_pos else 0 for i in range(n)]

        def ctx(off):
            return [
                words[min(max(i + off, 0), n - 1)] for i in range(n)
            ]

        labels = [
            (words[i] + pred[0] + mark[i] * 7) % _LABEL_N for i in range(n)
        ]
        yield (
            words, ctx(-2), ctx(-1), ctx(0), ctx(1), ctx(2), pred, mark,
            labels,
        )


def test():
    return _samples
