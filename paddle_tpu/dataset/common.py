"""Dataset plumbing (ref: python/paddle/dataset/common.py). This image
has zero egress, so download() only serves files already staged locally
(PADDLE_TPU_DATA_HOME or ~/.cache/paddle_tpu/dataset) and says so
otherwise; the file utilities are real."""
import glob
import hashlib
import os
import pickle

__all__ = [
    "DATA_HOME", "download", "md5file", "split", "cluster_files_reader",
    "must_mkdirs", "fetch_all",
]

DATA_HOME = os.environ.get(
    "PADDLE_TPU_DATA_HOME",
    os.path.join(os.path.expanduser("~"), ".cache", "paddle_tpu",
                 "dataset"),
)


def must_mkdirs(path):
    os.makedirs(path, exist_ok=True)


def md5file(fname):
    hash_md5 = hashlib.md5()
    with open(fname, "rb") as f:
        for chunk in iter(lambda: f.read(4096), b""):
            hash_md5.update(chunk)
    return hash_md5.hexdigest()


def download(url, module_name, md5sum, save_name=None):
    """Resolve an already-staged file (zero-egress environment). The
    canned paddle_tpu.dataset readers synthesize data and never call
    this; it exists for user scripts that stage real corpora."""
    dirname = os.path.join(DATA_HOME, module_name)
    filename = os.path.join(
        dirname, save_name or url.split("/")[-1]
    )
    if os.path.exists(filename) and (
        not md5sum or md5file(filename) == md5sum
    ):
        return filename
    raise RuntimeError(
        "download() cannot fetch %r: this environment has no network "
        "egress. Stage the file at %s (PADDLE_TPU_DATA_HOME to "
        "relocate), or use the synthetic paddle_tpu.dataset readers."
        % (url, filename)
    )


def fetch_all():
    """No-op: canned datasets are synthesized on the fly."""


def split(reader, line_count, suffix="%05d.pickle", dumper=pickle.dump):
    """Shard a reader's samples into pickle files (ref common.py:128)."""
    indx = 0
    lines = []
    for line in reader():
        lines.append(line)
        if len(lines) >= line_count:
            with open(suffix % indx, "wb") as f:
                dumper(lines, f)
            lines = []
            indx += 1
    if lines:
        with open(suffix % indx, "wb") as f:
            dumper(lines, f)


def cluster_files_reader(files_pattern, trainer_count, trainer_id,
                         loader=pickle.load):
    """Read this trainer's shard of the split files (ref common.py:166)."""

    def reader():
        flist = sorted(glob.glob(files_pattern))
        my_files = [
            f for i, f in enumerate(flist)
            if i % trainer_count == trainer_id
        ]
        for fn in my_files:
            with open(fn, "rb") as f:
                for item in loader(f):
                    yield item

    return reader
