"""UCI housing reader (ref: python/paddle/dataset/uci_housing.py) —
synthetic linear-regression stand-in with the real 13-feature schema."""
import numpy as np

_W = None


def _data(n, seed):
    global _W
    rng = np.random.default_rng(seed)
    if _W is None:
        _W = np.random.default_rng(3).standard_normal(13).astype("float32")
    x = rng.standard_normal((n, 13)).astype("float32")
    y = (x @ _W + 0.1 * rng.standard_normal(n)).astype("float32")
    return x, y


def train():
    def reader():
        x, y = _data(404, 5)
        for i in range(len(y)):
            yield x[i], y[i : i + 1]

    return reader


def test():
    def reader():
        x, y = _data(102, 9)
        for i in range(len(y)):
            yield x[i], y[i : i + 1]

    return reader
