"""MovieLens-1M reader (ref: python/paddle/dataset/movielens.py). Yields
(user_id, gender_id, age_id, job_id, movie_id, category_ids, title_ids,
rating) — the schema the reference's recommender chapter trains on. A
deterministic synthetic catalogue stands in without local files."""
import numpy as np

__all__ = [
    "train", "test", "get_movie_title_dict", "max_movie_id", "max_user_id",
    "max_job_id", "age_table", "movie_categories", "user_info", "movie_info",
]

age_table = [1, 18, 25, 35, 45, 50, 56]

_N_USERS = 500
_N_MOVIES = 400
_N_CATS = 18
_TITLE_VOCAB = 300
_N_JOBS = 21


class MovieInfo:
    def __init__(self, movie_id, categories, title_ids):
        self.index = movie_id
        self.categories = categories
        self.title = title_ids


class UserInfo:
    def __init__(self, user_id, gender, age_idx, job_id):
        self.index = user_id
        self.is_male = gender == 0
        self.age = age_table[age_idx]
        self.job_id = job_id


def _catalogue():
    rng = np.random.default_rng(11)
    movies = {}
    for m in range(1, _N_MOVIES + 1):
        cats = rng.choice(_N_CATS, size=rng.integers(1, 4), replace=False)
        title = rng.integers(1, _TITLE_VOCAB, size=rng.integers(2, 6))
        movies[m] = MovieInfo(m, list(map(int, cats)), list(map(int, title)))
    users = {}
    for u in range(1, _N_USERS + 1):
        users[u] = UserInfo(
            u, int(rng.integers(0, 2)), int(rng.integers(0, len(age_table))),
            int(rng.integers(0, _N_JOBS)),
        )
    return movies, users


_MOVIES, _USERS = _catalogue()


def _ratings(split):
    rng = np.random.default_rng(5 if split == "train" else 6)
    n = 4000 if split == "train" else 800
    for _ in range(n):
        u = int(rng.integers(1, _N_USERS + 1))
        m = int(rng.integers(1, _N_MOVIES + 1))
        user, movie = _USERS[u], _MOVIES[m]
        # rating correlates with (user, movie) hash → learnable signal
        base = ((u * 2654435761 + m * 40503) >> 8) % 5
        rating = float(min(5, max(1, base + int(rng.integers(0, 2)))))
        yield (
            u, int(not user.is_male), age_table.index(user.age), user.job_id,
            m, movie.categories, movie.title, rating,
        )


def train():
    return lambda: _ratings("train")


def test():
    return lambda: _ratings("test")


def max_user_id():
    return _N_USERS


def max_movie_id():
    return _N_MOVIES


def max_job_id():
    return _N_JOBS - 1


def movie_categories():
    return ["cat%d" % i for i in range(_N_CATS)]


def get_movie_title_dict():
    return {"w%d" % i: i for i in range(_TITLE_VOCAB)}


def movie_info():
    return dict(_MOVIES)


def user_info():
    return dict(_USERS)
