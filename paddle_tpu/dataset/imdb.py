"""IMDB sentiment reader (ref: python/paddle/dataset/imdb.py) — synthetic
token-sequence stand-in: word-id sequences + binary label."""
import numpy as np

VOCAB_SIZE = 5147


def word_dict():
    return {("w%d" % i).encode(): i for i in range(VOCAB_SIZE)}


def _reader(n, seed, vocab_size=VOCAB_SIZE):
    def reader():
        rng = np.random.default_rng(seed)
        for _ in range(n):
            label = int(rng.integers(0, 2))
            length = int(rng.integers(8, 64))
            base = rng.integers(0, vocab_size // 2, size=length)
            if label:  # positive reviews skew to upper vocab half
                base = base + vocab_size // 2 - 1
            yield base.astype("int64").tolist(), label

    return reader


def _vocab_size(word_idx):
    return len(word_idx) if word_idx else VOCAB_SIZE


def train(word_idx=None):
    return _reader(2048, 13, _vocab_size(word_idx))


def test(word_idx=None):
    return _reader(512, 17, _vocab_size(word_idx))
