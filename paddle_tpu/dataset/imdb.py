"""IMDB sentiment reader (ref: python/paddle/dataset/imdb.py) — synthetic
token-sequence stand-in: word-id sequences + binary label."""
import numpy as np

VOCAB_SIZE = 5147


def word_dict():
    return {("w%d" % i).encode(): i for i in range(VOCAB_SIZE)}


def _reader(n, seed):
    def reader():
        rng = np.random.default_rng(seed)
        for _ in range(n):
            label = int(rng.integers(0, 2))
            length = int(rng.integers(8, 64))
            base = rng.integers(0, VOCAB_SIZE // 2, size=length)
            if label:  # positive reviews skew to upper vocab half
                base = base + VOCAB_SIZE // 2 - 1
            yield base.astype("int64").tolist(), label

    return reader


def train(word_idx=None):
    return _reader(2048, 13)


def test(word_idx=None):
    return _reader(512, 17)
