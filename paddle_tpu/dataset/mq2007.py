"""MQ2007 learning-to-rank reader (ref: python/paddle/dataset/mq2007.py).
Same three access formats — pointwise (feature, score), pairwise
(d_high, d_low), listwise (label_list, feature_list) — over a synthetic
deterministic query/document pool with the real 46-dim feature schema
(zero egress). Local LETOR-format files can be parsed via
load_from_text()."""
import numpy as np

__all__ = ["train", "test"]

FEATURE_DIM = 46


class Query:
    def __init__(self, query_id, relevance_score, feature_vector):
        self.query_id = query_id
        self.relevance_score = relevance_score
        self.feature_vector = list(feature_vector)


class QueryList:
    def __init__(self, querylist=None):
        self.querylist = querylist or []

    def __iter__(self):
        return iter(self.querylist)

    def __len__(self):
        return len(self.querylist)

    def __getitem__(self, i):
        return self.querylist[i]

    def add(self, q):
        self.querylist.append(q)


def _synth_querylists(split):
    rng = np.random.default_rng({"train": 71, "test": 72}[split])
    n_queries = {"train": 120, "test": 40}[split]
    for qid in range(n_queries):
        ql = QueryList()
        w = rng.normal(size=FEATURE_DIM)
        for _ in range(int(rng.integers(4, 12))):
            feat = rng.normal(size=FEATURE_DIM)
            # relevance correlates with a per-query direction (learnable)
            rel = int(np.clip(round(float(feat @ w) / 8 + 1), 0, 2))
            ql.add(Query(qid, rel, feat.astype("float32")))
        yield ql


def load_from_text(filepath, shuffle=False, fill_missing=-1):
    """Parse a LETOR-format file: '<rel> qid:<id> 1:<v> 2:<v> ...'."""
    lists = {}
    with open(filepath) as f:
        for line in f:
            parts = line.strip().split()
            if len(parts) < 2:
                continue
            rel = int(parts[0])
            qid = int(parts[1].split(":")[1])
            feat = [fill_missing] * FEATURE_DIM
            for tok in parts[2:]:
                if ":" not in tok or tok.startswith("#"):
                    break
                k, v = tok.split(":")
                idx = int(k) - 1
                if 0 <= idx < FEATURE_DIM:
                    feat[idx] = float(v)
            lists.setdefault(qid, QueryList()).add(Query(qid, rel, feat))
    out = list(lists.values())
    if shuffle:
        import random
        random.shuffle(out)
    return out


def gen_point(querylist):
    for q in querylist:
        yield q.relevance_score, np.asarray(q.feature_vector, "float32")


def gen_pair(querylist, partial_order="full"):
    if partial_order != "full":
        raise NotImplementedError(
            "mq2007.gen_pair: only partial_order='full' is supported "
            "(every (higher, lower) relevance pair)"
        )
    qs = sorted(querylist, key=lambda q: -q.relevance_score)
    for i, hi in enumerate(qs):
        for lo in qs[i + 1:]:
            if hi.relevance_score > lo.relevance_score:
                yield (
                    np.array([1.0], "float32"),
                    np.asarray(hi.feature_vector, "float32"),
                    np.asarray(lo.feature_vector, "float32"),
                )


def gen_list(querylist):
    labels = [q.relevance_score for q in querylist]
    feats = [np.asarray(q.feature_vector, "float32") for q in querylist]
    yield labels, feats


_FORMATS = {
    "pointwise": gen_point,
    "pairwise": gen_pair,
    "listwise": gen_list,
}


def _creator(split, fmt):
    if fmt not in _FORMATS:
        raise ValueError(
            "mq2007 format must be one of %s" % sorted(_FORMATS)
        )

    def reader():
        for ql in _synth_querylists(split):
            yield from _FORMATS[fmt](ql)

    return reader


def train(format="pairwise"):
    return _creator("train", format)


def test(format="pairwise"):
    return _creator("test", format)


def fetch():
    """No-op (zero-egress): data is synthesized on the fly."""
