"""MNIST reader (ref: python/paddle/dataset/mnist.py). Loads from
PADDLE_TPU_MNIST_DIR (idx files) when present; otherwise serves a
deterministic synthetic digit set with the same schema: (784 float32 image
in [-1, 1], int64 label)."""
import gzip
import os
import struct

import numpy as np


def _synthetic(n, seed):
    rng = np.random.default_rng(seed)
    images = rng.standard_normal((n, 784)).astype("float32") * 0.3
    labels = rng.integers(0, 10, size=n).astype("int64")
    # inject class-dependent signal so models can actually learn
    for i in range(n):
        c = labels[i]
        images[i, c * 78 : (c + 1) * 78] += 1.5
    images = np.clip(images, -1.0, 1.0)
    return images, labels


def _load_idx(image_path, label_path):
    with gzip.open(image_path, "rb") as f:
        magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
        images = np.frombuffer(f.read(), dtype=np.uint8).reshape(n, rows * cols)
    with gzip.open(label_path, "rb") as f:
        magic, n = struct.unpack(">II", f.read(8))
        labels = np.frombuffer(f.read(), dtype=np.uint8)
    images = images.astype("float32") / 127.5 - 1.0
    return images, labels.astype("int64")


def _reader_creator(split, n_synth, seed):
    def reader():
        d = os.environ.get("PADDLE_TPU_MNIST_DIR")
        if d:
            prefix = "train" if split == "train" else "t10k"
            images, labels = _load_idx(
                os.path.join(d, "%s-images-idx3-ubyte.gz" % prefix),
                os.path.join(d, "%s-labels-idx1-ubyte.gz" % prefix),
            )
        else:
            images, labels = _synthetic(n_synth, seed)
        for i in range(len(labels)):
            yield images[i], int(labels[i])

    return reader


def train():
    return _reader_creator("train", 8192, 7)


def test():
    return _reader_creator("test", 1024, 11)
