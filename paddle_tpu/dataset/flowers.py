"""Flowers-102 reader (ref: python/paddle/dataset/flowers.py). Yields
(3x224x224 float32 image, int64 label); synthetic textured images with a
class-dependent signal stand in for the real download."""
import numpy as np

__all__ = ["train", "test", "valid"]

_N_CLASSES = 102


def _samples(split, n):
    rng = np.random.default_rng({"train": 41, "test": 42, "valid": 43}[split])
    for _ in range(n):
        label = int(rng.integers(0, _N_CLASSES))
        img = rng.random((3, 224, 224)).astype("float32") * 0.3
        # class-keyed stripe pattern
        row = (label * 2) % 224
        img[:, row:row + 4, :] += 0.6
        yield np.clip(img, 0.0, 1.0), label


def _make(split, n, mapper, buffered_size, use_xmap):
    base = lambda: _samples(split, n)  # noqa: E731
    if mapper is None:
        return base
    if use_xmap:
        from ..reader_utils import xmap_readers
        return xmap_readers(mapper, base, 4, buffered_size, order=True)
    return lambda: (mapper(s) for s in base())


def train(mapper=None, buffered_size=1024, use_xmap=False):
    return _make("train", 300, mapper, buffered_size, use_xmap)


def test(mapper=None, buffered_size=1024, use_xmap=False):
    return _make("test", 60, mapper, buffered_size, use_xmap)


def valid(mapper=None, buffered_size=1024, use_xmap=False):
    return _make("valid", 60, mapper, buffered_size, use_xmap)
