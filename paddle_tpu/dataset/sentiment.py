"""Movie-review sentiment reader (ref: python/paddle/dataset/sentiment.py).
Yields (word_id_list, 0/1 label); deterministic synthetic corpus with a
learnable polarity signal."""
import numpy as np

__all__ = ["get_word_dict", "train", "test"]

_VOCAB = 400
_POS_BAND = range(10, 60)     # ids that signal positive
_NEG_BAND = range(200, 250)


def get_word_dict():
    return {"w%d" % i: i for i in range(_VOCAB)}


def _samples(split):
    rng = np.random.default_rng(31 if split == "train" else 32)
    n = 600 if split == "train" else 120
    for _ in range(n):
        label = int(rng.integers(0, 2))
        length = int(rng.integers(5, 25))
        words = rng.integers(0, _VOCAB, size=length)
        band = _POS_BAND if label else _NEG_BAND
        k = max(1, length // 4)
        idx = rng.choice(length, size=k, replace=False)
        words[idx] = rng.choice(list(band), size=k)
        yield [int(w) for w in words], label


def train():
    return lambda: _samples("train")


def test():
    return lambda: _samples("test")
