"""Host data pipeline facade.

Uses the C++ ring-buffer queue (dataloader.cpp, built on first use) when
available; otherwise a python queue. The C++ path exists because the
reference's reader stack is C++ (paddle/fluid/operators/reader/
blocking_queue.h) — feeding a TPU at full HBM bandwidth needs the GIL out of
the producer path for real workloads.
"""
import queue as _pyqueue

from . import build


class _PyQueue:
    def __init__(self, capacity):
        self._q = _pyqueue.Queue(maxsize=capacity)

    def put(self, item):
        self._q.put(item)

    def get(self):
        return self._q.get()


class _NativeQueue:
    """ctypes wrapper over the C++ SPSC ring buffer. Python objects are
    passed via an index table (the C++ side manages slot tokens + blocking),
    so arbitrary numpy batches ride through without serialization."""

    def __init__(self, capacity, lib):
        self._lib = lib
        self._handle = lib.ptq_create(capacity)
        self._slots = {}
        self._next = 0

    def put(self, item):
        self._next += 1
        token = self._next
        self._slots[token] = item
        self._lib.ptq_put(self._handle, token)

    def get(self):
        token = self._lib.ptq_get(self._handle)
        return self._slots.pop(token)

    def __del__(self):
        try:
            self._lib.ptq_destroy(self._handle)
        except Exception:
            pass


def make_queue(capacity=64):
    lib = build.load_native()
    if lib is not None:
        try:
            return _NativeQueue(capacity, lib)
        except Exception:
            pass
    return _PyQueue(capacity)


class NativeBatchPipe:
    """Batch bytes staged through the C++ slot ring (pipe_* in
    dataloader.cpp) — the TPU-native rebuild of the reference's
    buffered_reader + pinned allocator.

    Producer thread: put(dict_of_numpy) — acquires a slot (blocking when
    the ring is full = back-pressure), submits per-array memcpy jobs to
    the C++ worker pool, waits, commits. The copies and all blocking run
    outside the GIL, so staging overlaps the consumer's device step.

    Consumer: get() -> (dict_of_views, release) — numpy arrays mapped
    ZERO-COPY onto the slot's (best-effort mlocked) arena memory, valid
    ONLY until release() is called; call it once the batch has been
    consumed (e.g. device transfer issued). A sentinel (None) put is
    passed through for end-of-stream; put_error() forwards a producer
    failure to the consumer, which re-raises from get().

    Shutdown: abort() unblocks every waiter (put returns False, get
    returns end-of-stream); destroy the C++ object with close() only
    after the producer thread has observed the abort and stopped. An
    aborted pipe can be re-armed with reset() for the next epoch.
    """

    _ERROR = "__paddle_tpu_pipe_error__"

    def __init__(self, capacity=4, slot_bytes=64 << 20, n_workers=2):
        import ctypes

        self._lib = build.load_native()
        if self._lib is None:
            raise RuntimeError("native runtime unavailable (g++ failed?)")
        self._ctypes = ctypes
        self._handle = self._lib.pipe_create(capacity, slot_bytes, n_workers)
        self._slot_bytes = slot_bytes
        self._meta = {}          # slot -> list[(name, dtype, shape, offset)]

    @property
    def pinned(self):
        return bool(self._lib.pipe_is_pinned(self._handle))

    def put(self, batch):
        """Stage one batch; returns False when the pipe was aborted."""
        import numpy as np

        slot = self._lib.pipe_acquire_write(self._handle)
        if slot < 0:
            return False
        if batch is None or (
            isinstance(batch, tuple) and batch and batch[0] == self._ERROR
        ):
            self._meta[slot] = batch
            self._lib.pipe_commit(self._handle, slot)
            return True
        try:
            meta, offset = [], 0
            # `keep` pins the source arrays until the worker copies finish
            keep = []
            for name, arr in batch.items():
                arr = np.ascontiguousarray(arr)
                n = arr.nbytes
                if offset + n > self._slot_bytes:
                    raise ValueError(
                        "batch (%d bytes+) exceeds pipe slot size %d — "
                        "raise slot_bytes"
                        % (offset + n, self._slot_bytes)
                    )
                self._lib.pipe_submit_write(
                    self._handle, slot, offset,
                    arr.ctypes.data_as(self._ctypes.c_void_p), n,
                )
                keep.append(arr)
                meta.append((name, arr.dtype, arr.shape, offset))
                offset += (n + 63) & ~63
            self._lib.pipe_wait_writes(self._handle, slot)  # GIL released
            del keep
        except BaseException:
            # copies for this slot must finish before the slot is recycled
            self._lib.pipe_wait_writes(self._handle, slot)
            self._lib.pipe_release(self._handle, slot)
            raise
        self._meta[slot] = meta
        self._lib.pipe_commit(self._handle, slot)
        return True

    def put_error(self, message):
        """Forward a producer-side failure; the consumer's get() raises."""
        return self.put((self._ERROR, str(message)))

    def get(self):
        import numpy as np

        slot = self._lib.pipe_acquire_read(self._handle)  # GIL released
        if slot < 0:  # aborted
            return None, lambda: None
        meta = self._meta.pop(slot)
        if meta is None:
            self._lib.pipe_release(self._handle, slot)
            return None, lambda: None
        if isinstance(meta, tuple) and meta and meta[0] == self._ERROR:
            self._lib.pipe_release(self._handle, slot)
            raise RuntimeError("data pipeline producer failed: %s" % meta[1])
        base = self._lib.pipe_slot_ptr(self._handle, slot)
        out = {}
        for name, dtype, shape, offset in meta:
            n = int(np.prod(shape, dtype=np.int64)) * dtype.itemsize
            buf = (self._ctypes.c_char * n).from_address(base + offset)
            out[name] = np.frombuffer(buf, dtype=dtype).reshape(shape)

        released = []

        def release():
            if not released:
                released.append(True)
                self._lib.pipe_release(self._handle, slot)

        return out, release

    def abort(self):
        if self._handle:
            self._lib.pipe_abort(self._handle)

    def reset(self):
        if self._handle:
            self._lib.pipe_reset(self._handle)
            self._meta.clear()

    def close(self):
        if self._handle:
            self._lib.pipe_destroy(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
