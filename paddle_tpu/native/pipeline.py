"""Host data pipeline facade.

Uses the C++ ring-buffer queue (dataloader.cpp, built on first use) when
available; otherwise a python queue. The C++ path exists because the
reference's reader stack is C++ (paddle/fluid/operators/reader/
blocking_queue.h) — feeding a TPU at full HBM bandwidth needs the GIL out of
the producer path for real workloads.
"""
import queue as _pyqueue

from . import build


class _PyQueue:
    def __init__(self, capacity):
        self._q = _pyqueue.Queue(maxsize=capacity)

    def put(self, item):
        self._q.put(item)

    def get(self):
        return self._q.get()


class _NativeQueue:
    """ctypes wrapper over the C++ SPSC ring buffer. Python objects are
    passed via an index table (the C++ side manages slot tokens + blocking),
    so arbitrary numpy batches ride through without serialization."""

    def __init__(self, capacity, lib):
        self._lib = lib
        self._handle = lib.ptq_create(capacity)
        self._slots = {}
        self._next = 0

    def put(self, item):
        self._next += 1
        token = self._next
        self._slots[token] = item
        self._lib.ptq_put(self._handle, token)

    def get(self):
        token = self._lib.ptq_get(self._handle)
        return self._slots.pop(token)

    def __del__(self):
        try:
            self._lib.ptq_destroy(self._handle)
        except Exception:
            pass


def make_queue(capacity=64):
    lib = build.load_native()
    if lib is not None:
        try:
            return _NativeQueue(capacity, lib)
        except Exception:
            pass
    return _PyQueue(capacity)
