"""Lazy g++ build + ctypes loader for the native host runtime."""
import ctypes
import os
import subprocess
import threading

_HERE = os.path.dirname(os.path.abspath(__file__))
_LIB_PATH = os.path.join(_HERE, "libpaddle_tpu_native.so")
_SRC = os.path.join(_HERE, "dataloader.cpp")
_lock = threading.Lock()
_lib = None
_tried = False


def _compile(lib_path=None, extra_flags=()):
    cmd = [
        "g++", "-O2", "-shared", "-fPIC", "-std=c++17", "-pthread",
        *extra_flags, _SRC, "-o", lib_path or _LIB_PATH,
    ]
    subprocess.run(cmd, check=True, capture_output=True)


def build_tsan():
    """Race-detection build of the native runtime (aux subsystem: the
    reference's CI runs its C++ under sanitizers; here
    -fsanitize=thread covers the slot ring + worker pool). Returns the
    .so path; load it in a TSAN_OPTIONS-configured process to check for
    data races in the pipe/queue/arena paths."""
    path = _LIB_PATH.replace(".so", "_tsan.so")
    _compile(path, ("-fsanitize=thread", "-O1", "-g"))
    return path


def load_native():
    """Return the ctypes lib, building it on first call; None on failure."""
    global _lib, _tried
    with _lock:
        if _lib is not None:
            return _lib
        if _tried:
            return None
        _tried = True
        try:
            if not os.path.exists(_LIB_PATH) or (
                os.path.getmtime(_SRC) > os.path.getmtime(_LIB_PATH)
            ):
                _compile()
            lib = ctypes.CDLL(_LIB_PATH)
            lib.ptq_create.restype = ctypes.c_void_p
            lib.ptq_create.argtypes = [ctypes.c_int]
            lib.ptq_put.argtypes = [ctypes.c_void_p, ctypes.c_long]
            lib.ptq_get.restype = ctypes.c_long
            lib.ptq_get.argtypes = [ctypes.c_void_p]
            lib.ptq_destroy.argtypes = [ctypes.c_void_p]
            lib.arena_create.restype = ctypes.c_void_p
            lib.arena_create.argtypes = [ctypes.c_size_t]
            lib.arena_is_locked.restype = ctypes.c_int
            lib.arena_is_locked.argtypes = [ctypes.c_void_p]
            lib.arena_alloc.restype = ctypes.c_void_p
            lib.arena_alloc.argtypes = [ctypes.c_void_p, ctypes.c_size_t]
            lib.arena_reset.argtypes = [ctypes.c_void_p]
            lib.arena_destroy.argtypes = [ctypes.c_void_p]
            lib.pipe_create.restype = ctypes.c_void_p
            lib.pipe_create.argtypes = [
                ctypes.c_int, ctypes.c_size_t, ctypes.c_int,
            ]
            lib.pipe_is_pinned.restype = ctypes.c_int
            lib.pipe_is_pinned.argtypes = [ctypes.c_void_p]
            lib.pipe_acquire_write.restype = ctypes.c_int
            lib.pipe_acquire_write.argtypes = [ctypes.c_void_p]
            lib.pipe_slot_ptr.restype = ctypes.c_void_p
            lib.pipe_slot_ptr.argtypes = [ctypes.c_void_p, ctypes.c_int]
            lib.pipe_write.argtypes = [
                ctypes.c_void_p, ctypes.c_int, ctypes.c_size_t,
                ctypes.c_void_p, ctypes.c_size_t,
            ]
            lib.pipe_submit_write.argtypes = [
                ctypes.c_void_p, ctypes.c_int, ctypes.c_size_t,
                ctypes.c_void_p, ctypes.c_size_t,
            ]
            lib.pipe_wait_writes.argtypes = [ctypes.c_void_p, ctypes.c_int]
            lib.pipe_commit.argtypes = [ctypes.c_void_p, ctypes.c_int]
            lib.pipe_acquire_read.restype = ctypes.c_int
            lib.pipe_acquire_read.argtypes = [ctypes.c_void_p]
            lib.pipe_release.argtypes = [ctypes.c_void_p, ctypes.c_int]
            lib.pipe_abort.argtypes = [ctypes.c_void_p]
            lib.pipe_reset.argtypes = [ctypes.c_void_p]
            lib.pipe_destroy.argtypes = [ctypes.c_void_p]
            _lib = lib
            return _lib
        except Exception:
            return None
