"""Native (C++) host runtime: prefetching data pipeline + pinned staging
arena (TPU-native analogue of paddle/fluid/operators/reader/ +
paddle/fluid/memory/). Built lazily with g++; pure-python fallback keeps the
framework importable before the first build."""
from . import pipeline  # noqa: F401
