// Native host runtime for paddle_tpu.
//
// TPU-native analogue of the reference's C++ reader stack
// (ref: paddle/fluid/operators/reader/blocking_queue.h,
//  paddle/fluid/framework/blocking_queue.h, operators/reader/
//  buffered_reader.cc) and host memory arena
// (ref: paddle/fluid/memory/allocation/pinned_allocator.cc).
//
// Three layers, all exported C ABI for ctypes:
//
// - ptq_*: bounded MPMC token queue with condition-variable blocking.
//   Python keeps arbitrary batch objects; tokens flow through C++ so
//   producers block/wake without the GIL.
//
// - arena_*: bump-pointer staging arena, 64-byte aligned, mlock()ed on a
//   best-effort basis (the TPU host transfer path reads from here; locking
//   avoids page faults mid-transfer — the analogue of CUDA pinned memory).
//
// - pipe_*: the actual batch pipeline. A ring of fixed-size arena slots +
//   a copy worker pool. Producers acquire a slot, submit memcpy jobs (the
//   copies run on C++ worker threads — and ctypes releases the GIL, so
//   staging overlaps the consumer's device step), commit, and consumers
//   map the slot's bytes zero-copy as numpy views. Back-pressure is the
//   ring itself: acquire_write blocks while every slot is in flight.
#include <condition_variable>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <mutex>
#include <new>
#include <thread>
#include <vector>

#if defined(__unix__) || defined(__APPLE__)
#include <sys/mman.h>
#endif

extern "C" {

// ---------------------------------------------------------------------------
// token queue
// ---------------------------------------------------------------------------
struct TokenQueue {
  std::deque<long> items;
  std::mutex mu;
  std::condition_variable not_empty;
  std::condition_variable not_full;
  size_t capacity;
};

void* ptq_create(int capacity) {
  auto* q = new TokenQueue();
  q->capacity = capacity > 0 ? static_cast<size_t>(capacity) : 1;
  return q;
}

void ptq_put(void* handle, long token) {
  auto* q = static_cast<TokenQueue*>(handle);
  std::unique_lock<std::mutex> lk(q->mu);
  q->not_full.wait(lk, [q] { return q->items.size() < q->capacity; });
  q->items.push_back(token);
  q->not_empty.notify_one();
}

long ptq_get(void* handle) {
  auto* q = static_cast<TokenQueue*>(handle);
  std::unique_lock<std::mutex> lk(q->mu);
  q->not_empty.wait(lk, [q] { return !q->items.empty(); });
  long t = q->items.front();
  q->items.pop_front();
  q->not_full.notify_one();
  return t;
}

void ptq_destroy(void* handle) { delete static_cast<TokenQueue*>(handle); }

// ---------------------------------------------------------------------------
// arena
// ---------------------------------------------------------------------------
struct Arena {
  char* base;
  size_t size;
  size_t offset;
  bool locked;
};

void* arena_create(size_t bytes) {
  auto* a = new Arena();
  a->base = static_cast<char*>(::operator new(bytes, std::align_val_t(64)));
  a->size = bytes;
  a->offset = 0;
  a->locked = false;
#if defined(__unix__) || defined(__APPLE__)
  // best-effort pinning (needs CAP_IPC_LOCK / rlimit; falls back silently)
  a->locked = (mlock(a->base, bytes) == 0);
#endif
  return a;
}

int arena_is_locked(void* handle) {
  return static_cast<Arena*>(handle)->locked ? 1 : 0;
}

void* arena_alloc(void* handle, size_t bytes) {
  auto* a = static_cast<Arena*>(handle);
  size_t aligned = (bytes + 63) & ~size_t(63);
  if (a->offset + aligned > a->size) return nullptr;
  void* p = a->base + a->offset;
  a->offset += aligned;
  return p;
}

void arena_reset(void* handle) { static_cast<Arena*>(handle)->offset = 0; }

void arena_destroy(void* handle) {
  auto* a = static_cast<Arena*>(handle);
#if defined(__unix__) || defined(__APPLE__)
  if (a->locked) munlock(a->base, a->size);
#endif
  ::operator delete(a->base, std::align_val_t(64));
  delete a;
}

// ---------------------------------------------------------------------------
// batch pipe: slot ring + copy worker pool
// ---------------------------------------------------------------------------
enum SlotState { SLOT_FREE = 0, SLOT_WRITING = 1, SLOT_READY = 2,
                 SLOT_READING = 3 };

struct CopyJob {
  char* dst;
  const char* src;
  size_t n;
  int slot;
};

struct BatchPipe {
  void* arena;
  char* base;              // arena-backed slab, capacity * slot_bytes
  size_t slot_bytes;
  int capacity;
  std::vector<int> state;            // SlotState per slot
  std::vector<int> pending_copies;   // outstanding jobs per slot
  std::deque<int> ready;             // committed slot ids, FIFO
  std::mutex mu;
  std::condition_variable cv;        // slot state changes
  bool aborting = false;             // wakes ring waiters with -1
  // worker pool
  std::vector<std::thread> workers;
  std::deque<CopyJob> jobs;
  std::mutex job_mu;
  std::condition_variable job_cv;
  bool stopping = false;
};

static void pipe_worker(BatchPipe* p) {
  for (;;) {
    CopyJob job;
    {
      std::unique_lock<std::mutex> lk(p->job_mu);
      p->job_cv.wait(lk, [p] { return p->stopping || !p->jobs.empty(); });
      if (p->stopping && p->jobs.empty()) return;
      job = p->jobs.front();
      p->jobs.pop_front();
    }
    std::memcpy(job.dst, job.src, job.n);
    {
      std::lock_guard<std::mutex> lk(p->mu);
      p->pending_copies[job.slot]--;
    }
    p->cv.notify_all();
  }
}

void* pipe_create(int capacity, size_t slot_bytes, int n_workers) {
  auto* p = new BatchPipe();
  p->capacity = capacity > 0 ? capacity : 2;
  p->slot_bytes = slot_bytes;
  p->arena = arena_create(static_cast<size_t>(p->capacity) * slot_bytes);
  p->base = static_cast<char*>(
      arena_alloc(p->arena, static_cast<size_t>(p->capacity) * slot_bytes));
  p->state.assign(p->capacity, SLOT_FREE);
  p->pending_copies.assign(p->capacity, 0);
  if (n_workers < 1) n_workers = 1;
  for (int i = 0; i < n_workers; ++i)
    p->workers.emplace_back(pipe_worker, p);
  return p;
}

int pipe_is_pinned(void* handle) {
  return arena_is_locked(static_cast<BatchPipe*>(handle)->arena);
}

// producer: block until a slot is free, mark it writing; -1 when aborted
int pipe_acquire_write(void* handle) {
  auto* p = static_cast<BatchPipe*>(handle);
  std::unique_lock<std::mutex> lk(p->mu);
  int slot = -1;
  p->cv.wait(lk, [p, &slot] {
    if (p->aborting) return true;
    for (int i = 0; i < p->capacity; ++i)
      if (p->state[i] == SLOT_FREE) { slot = i; return true; }
    return false;
  });
  if (p->aborting || slot < 0) return -1;
  p->state[slot] = SLOT_WRITING;
  return slot;
}

// unblock every ring waiter (they return -1); the pipe stays allocated so
// in-flight pipe_* calls stay valid — call pipe_destroy only after the
// producer/consumer threads have observed the abort and stopped
void pipe_abort(void* handle) {
  auto* p = static_cast<BatchPipe*>(handle);
  {
    std::lock_guard<std::mutex> lk(p->mu);
    p->aborting = true;
  }
  p->cv.notify_all();
}

// re-arm an aborted pipe for a fresh epoch (slots reset to FREE; any
// committed-but-unread batches are dropped)
void pipe_reset(void* handle) {
  auto* p = static_cast<BatchPipe*>(handle);
  std::lock_guard<std::mutex> lk(p->mu);
  p->aborting = false;
  p->ready.clear();
  for (int i = 0; i < p->capacity; ++i) p->state[i] = SLOT_FREE;
}

void* pipe_slot_ptr(void* handle, int slot) {
  auto* p = static_cast<BatchPipe*>(handle);
  return p->base + static_cast<size_t>(slot) * p->slot_bytes;
}

// synchronous staging copy (the GIL is released while this runs)
void pipe_write(void* handle, int slot, size_t offset, const void* src,
                size_t n) {
  auto* p = static_cast<BatchPipe*>(handle);
  std::memcpy(p->base + static_cast<size_t>(slot) * p->slot_bytes + offset,
              src, n);
}

// async staging: enqueue to the worker pool; the caller must keep src
// alive until pipe_wait_writes(slot) returns
void pipe_submit_write(void* handle, int slot, size_t offset,
                       const void* src, size_t n) {
  auto* p = static_cast<BatchPipe*>(handle);
  {
    std::lock_guard<std::mutex> lk(p->mu);
    p->pending_copies[slot]++;
  }
  {
    std::lock_guard<std::mutex> lk(p->job_mu);
    p->jobs.push_back(CopyJob{
        p->base + static_cast<size_t>(slot) * p->slot_bytes + offset,
        static_cast<const char*>(src), n, slot});
  }
  p->job_cv.notify_one();
}

void pipe_wait_writes(void* handle, int slot) {
  auto* p = static_cast<BatchPipe*>(handle);
  std::unique_lock<std::mutex> lk(p->mu);
  p->cv.wait(lk, [p, slot] { return p->pending_copies[slot] == 0; });
}

void pipe_commit(void* handle, int slot) {
  auto* p = static_cast<BatchPipe*>(handle);
  {
    std::lock_guard<std::mutex> lk(p->mu);
    p->state[slot] = SLOT_READY;
    p->ready.push_back(slot);
  }
  p->cv.notify_all();
}

// consumer: block until a committed slot is available (FIFO); -1 on abort
int pipe_acquire_read(void* handle) {
  auto* p = static_cast<BatchPipe*>(handle);
  std::unique_lock<std::mutex> lk(p->mu);
  p->cv.wait(lk, [p] { return p->aborting || !p->ready.empty(); });
  if (p->ready.empty()) return -1;
  int slot = p->ready.front();
  p->ready.pop_front();
  p->state[slot] = SLOT_READING;
  return slot;
}

void pipe_release(void* handle, int slot) {
  auto* p = static_cast<BatchPipe*>(handle);
  {
    std::lock_guard<std::mutex> lk(p->mu);
    p->state[slot] = SLOT_FREE;
  }
  p->cv.notify_all();
}

void pipe_destroy(void* handle) {
  auto* p = static_cast<BatchPipe*>(handle);
  {
    std::lock_guard<std::mutex> lk(p->job_mu);
    p->stopping = true;
  }
  p->job_cv.notify_all();
  for (auto& t : p->workers) t.join();
  arena_destroy(p->arena);
  delete p;
}

}  // extern "C"
