// Native host runtime for paddle_tpu.
//
// TPU-native analogue of the reference's C++ reader stack
// (ref: paddle/fluid/operators/reader/blocking_queue.h,
//  paddle/fluid/framework/blocking_queue.h) and host memory arena
// (ref: paddle/fluid/memory/allocation/*).
//
// - ptq_*: bounded MPMC token queue with condition-variable blocking.
//   Python keeps the actual batch objects; tokens flow through C++ so the
//   producer thread blocks/wakes without holding the GIL.
// - arena_*: bump-pointer pinned staging arena for feed buffers (64-byte
//   aligned so dma_map-style transfers stay aligned).
#include <condition_variable>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <mutex>
#include <new>

extern "C" {

struct TokenQueue {
  std::deque<long> items;
  std::mutex mu;
  std::condition_variable not_empty;
  std::condition_variable not_full;
  size_t capacity;
};

void* ptq_create(int capacity) {
  auto* q = new TokenQueue();
  q->capacity = capacity > 0 ? static_cast<size_t>(capacity) : 1;
  return q;
}

void ptq_put(void* handle, long token) {
  auto* q = static_cast<TokenQueue*>(handle);
  std::unique_lock<std::mutex> lk(q->mu);
  q->not_full.wait(lk, [q] { return q->items.size() < q->capacity; });
  q->items.push_back(token);
  q->not_empty.notify_one();
}

long ptq_get(void* handle) {
  auto* q = static_cast<TokenQueue*>(handle);
  std::unique_lock<std::mutex> lk(q->mu);
  q->not_empty.wait(lk, [q] { return !q->items.empty(); });
  long t = q->items.front();
  q->items.pop_front();
  q->not_full.notify_one();
  return t;
}

void ptq_destroy(void* handle) { delete static_cast<TokenQueue*>(handle); }

// ---------------------------------------------------------------------------
struct Arena {
  char* base;
  size_t size;
  size_t offset;
};

void* arena_create(size_t bytes) {
  auto* a = new Arena();
  a->base = static_cast<char*>(::operator new(bytes, std::align_val_t(64)));
  a->size = bytes;
  a->offset = 0;
  return a;
}

void* arena_alloc(void* handle, size_t bytes) {
  auto* a = static_cast<Arena*>(handle);
  size_t aligned = (bytes + 63) & ~size_t(63);
  if (a->offset + aligned > a->size) return nullptr;
  void* p = a->base + a->offset;
  a->offset += aligned;
  return p;
}

void arena_reset(void* handle) { static_cast<Arena*>(handle)->offset = 0; }

void arena_destroy(void* handle) {
  auto* a = static_cast<Arena*>(handle);
  ::operator delete(a->base, std::align_val_t(64));
  delete a;
}

}  // extern "C"
