"""Cross-request KV reuse: prefix cache and session tiering.

Shared-prefix traffic (every request opening with the same system
prompt, multi-turn chat resuming a transcript) makes most prefill
FLOPs redundant: the KV rows for a prompt prefix depend only on the
prefix tokens, so they can be computed once and adopted by every later
request that starts with the same tokens. Two stores implement that
reuse, both sized in bytes and LRU-evicted:

- :class:`PrefixPool` — content-addressed prefix -> prefilled KV rows.
  Keys are ``integrity.digest`` sha256 digests of the token bytes, so
  lookup is exact-match over the declared prefix ladder (longest match
  wins); a hit lets the engine adopt ``plen`` rows verbatim and
  delta-prefill only the suffix
  (:func:`~paddle_tpu.models.gpt.build_gpt_prefill_delta`). Entries
  are fp32 (``store_dtype="fp32"``, bit-exact adoption — what the
  parity tests pin) or int8 per-row block-scaled (``"int8"``, the
  kv_wire codec, ~3.9x more prefixes per byte). ``placement="hbm"``
  keeps entries device-resident (adopt without a host->device copy) and
  is priced into ``DecodeEngine.check_hbm_budget``; ``"host"`` (the
  default) trades an upload per adoption for zero HBM.

- :class:`SessionTier` — hibernated sessions keyed by session id. When
  a stream with a ``session`` id retires, the engine encodes the
  slot's live KV rows into the existing
  :class:`~paddle_tpu.serving.disagg.kv_wire.KVHandoff` wire format
  (int8 by default — the same ~3.9x) and parks it in host RAM; a later
  ``submit(session=...)`` adopts the rows back into a free slot and
  delta-prefills only the new turn. Live-slot count stops bounding
  concurrent sessions: sessions-per-chip = slots + whatever fits the
  tier's byte budget.

Metrics: ``serving.prefix.hits`` / ``misses`` / ``evictions`` /
``inserts`` counters and ``serving.prefix.entries`` / ``bytes``
gauges; ``serving.tier.hibernated`` / ``resumed`` / ``evictions``
counters and ``serving.tier.sessions`` / ``bytes`` gauges.

Thread safety: both stores take a named lock (lock-order sanitizer
aware) around every mutation — the dispatch thread inserts while HTTP
threads submit/lookup.
"""
import collections

import numpy as np

from .. import observability as obs
from ..analysis import concurrency as _conc
from ..integrity.digest import bytes_digest

__all__ = ["PrefixPool", "SessionTier", "prefix_digest"]


def prefix_digest(tokens):
    """Content digest of a token prefix: sha256 over the int64 bytes
    (the :mod:`paddle_tpu.integrity.digest` form, so pool keys read
    like every other integrity surface's)."""
    return bytes_digest(np.ascontiguousarray(
        np.asarray(tokens, np.int64)).tobytes())


class _PrefixEntry:
    __slots__ = ("digest", "plen", "k", "v", "k_scales", "v_scales",
                 "next_token", "store_dtype", "nbytes")

    def __init__(self, digest, plen, k, v, k_scales, v_scales,
                 next_token, store_dtype):
        self.digest = digest
        self.plen = int(plen)
        self.k = k
        self.v = v
        self.k_scales = k_scales
        self.v_scales = v_scales
        # greedy token for position plen — a FULL-prompt hit adopts
        # this as the stream's first token and runs no prefill at all
        self.next_token = None if next_token is None else int(next_token)
        self.store_dtype = store_dtype
        self.nbytes = sum(int(a.nbytes) for a in
                          (k, v, k_scales, v_scales) if a is not None)

    def dense(self):
        """fp32 (k, v) pair shaped (L, cache_len, H)."""
        if self.store_dtype == "fp32":
            return np.asarray(self.k), np.asarray(self.v)
        from .disagg import kv_wire

        return (kv_wire.dequantize_rows(np.asarray(self.k),
                                        np.asarray(self.k_scales)),
                kv_wire.dequantize_rows(np.asarray(self.v),
                                        np.asarray(self.v_scales)))


class PrefixPool:
    """Slot-granular prefix cache: digest(prefix tokens) -> prefilled
    KV rows, LRU-evicted to ``capacity_bytes``.

    ``prefix_lens`` declares the prefix ladder the pool indexes (by
    default the engine's prompt buckets): :meth:`lookup` hashes each
    ladder length that fits the prompt, longest first, so a 24-token
    shared system prompt is found under its 16-token ladder entry even
    when callers append unique tails. ``min_tokens`` skips caching
    trivially short prefixes.
    """

    def __init__(self, capacity_bytes=64 << 20, store_dtype="fp32",
                 placement="host", prefix_lens=None, min_tokens=4,
                 name="default"):
        if store_dtype not in ("fp32", "int8"):
            raise ValueError("store_dtype must be 'fp32' or 'int8', "
                             "got %r" % (store_dtype,))
        if placement not in ("host", "hbm"):
            raise ValueError("placement must be 'host' or 'hbm', "
                             "got %r" % (placement,))
        self.capacity_bytes = int(capacity_bytes)
        self.store_dtype = str(store_dtype)
        self.placement = str(placement)
        self.prefix_lens = (tuple(sorted({int(p) for p in prefix_lens}))
                            if prefix_lens else None)
        self.min_tokens = int(min_tokens)
        self.name = str(name)
        self._lock = _conc.named_lock("serving.prefix_pool")
        self._entries = collections.OrderedDict()  # digest -> entry
        self._bytes = 0
        self._stats = collections.Counter()

    # -- write side ------------------------------------------------------
    def put(self, tokens, k, v, next_token=None):
        """Cache the KV rows of ``tokens`` (a full prefix whose rows
        0..len-1 are written in ``k``/``v``, each (L, cache_len, H)
        fp32 — a leading batch-of-1 axis is squeezed). Stores under the
        full-length digest AND every declared ladder length that
        prefixes it, so later lookups match on the shared head without
        re-prefilling. Returns the number of entries written."""
        tokens = np.asarray(tokens, np.int64).reshape(-1)
        k = np.asarray(k, np.float32)
        v = np.asarray(v, np.float32)
        if k.ndim == 4:
            k, v = k[0], v[0]
        wrote = 0
        lens = {int(tokens.size)}
        if self.prefix_lens:
            lens.update(p for p in self.prefix_lens
                        if p < tokens.size)
        for plen in sorted(lens, reverse=True):
            if plen < self.min_tokens:
                continue
            nt = next_token if plen == tokens.size else None
            wrote += self._put_one(tokens[:plen], plen, k, v, nt)
        return wrote

    def _put_one(self, tokens, plen, k, v, next_token):
        digest = prefix_digest(tokens)
        # zero rows >= plen so an adopted entry matches the "zeros
        # beyond pos" cache invariant regardless of source geometry
        kp = np.zeros_like(k)
        vp = np.zeros_like(v)
        kp[:, :plen] = k[:, :plen]
        vp[:, :plen] = v[:, :plen]
        if self.store_dtype == "int8":
            from .disagg import kv_wire

            kq, ks = kv_wire.quantize_rows(kp)
            vq, vs = kv_wire.quantize_rows(vp)
            entry = _PrefixEntry(digest, plen, kq, vq, ks, vs,
                                 next_token, "int8")
        else:
            entry = _PrefixEntry(digest, plen, kp, vp, None, None,
                                 next_token, "fp32")
        if entry.nbytes > self.capacity_bytes:
            return 0
        if self.placement == "hbm":
            import jax

            entry.k = jax.device_put(entry.k)
            entry.v = jax.device_put(entry.v)
            if entry.k_scales is not None:
                entry.k_scales = jax.device_put(entry.k_scales)
                entry.v_scales = jax.device_put(entry.v_scales)
        with self._lock:
            old = self._entries.pop(digest, None)
            if old is not None:
                self._bytes -= old.nbytes
                # keep a known next_token when the rewrite lacks one
                if entry.next_token is None:
                    entry.next_token = old.next_token
            self._entries[digest] = entry
            self._bytes += entry.nbytes
            self._stats["inserts"] += 1
            evicted = 0
            while self._bytes > self.capacity_bytes and self._entries:
                _, dead = self._entries.popitem(last=False)
                self._bytes -= dead.nbytes
                evicted += 1
            if evicted:
                self._stats["evictions"] += evicted
                obs.inc("serving.prefix.evictions", evicted)
            self._gauges_locked()
        obs.inc("serving.prefix.inserts")
        return 1

    # -- read side -------------------------------------------------------
    def lookup(self, prompt):
        """Longest cached prefix of ``prompt``: tries the full prompt
        first, then each declared ladder length, longest first.
        Returns the (LRU-refreshed) entry or None. A hit with
        ``entry.plen == len(prompt)`` and a known ``next_token`` needs
        NO prefill at all; a shorter hit wants a delta-prefill of the
        remaining suffix."""
        prompt = np.asarray(prompt, np.int64).reshape(-1)
        lens = [int(prompt.size)]
        if self.prefix_lens:
            lens += [p for p in self.prefix_lens if p < prompt.size]
        for plen in sorted(set(lens), reverse=True):
            if plen < self.min_tokens:
                break
            digest = prefix_digest(prompt[:plen])
            with self._lock:
                entry = self._entries.get(digest)
                if entry is not None:
                    self._entries.move_to_end(digest)
                    self._stats["hits"] += 1
                    obs.inc("serving.prefix.hits")
                    return entry
        with self._lock:
            self._stats["misses"] += 1
        obs.inc("serving.prefix.misses")
        return None

    # -- accounting ------------------------------------------------------
    def hbm_bytes(self):
        """Bytes this pool holds device-resident (0 for host
        placement) — what ``check_hbm_budget`` subtracts."""
        return self.capacity_bytes if self.placement == "hbm" else 0

    def _gauges_locked(self):
        obs.set_gauge("serving.prefix.entries", len(self._entries))
        obs.set_gauge("serving.prefix.bytes", self._bytes)

    def stats(self):
        with self._lock:
            out = dict(self._stats)
            out["entries"] = len(self._entries)
            out["bytes"] = self._bytes
        for key in ("hits", "misses", "evictions", "inserts"):
            out.setdefault(key, 0)
        out["capacity_bytes"] = self.capacity_bytes
        out["store_dtype"] = self.store_dtype
        out["placement"] = self.placement
        return out

    def __len__(self):
        with self._lock:
            return len(self._entries)


class SessionTier:
    """Host-RAM hibernation tier for idle sessions' KV state.

    Stores sealed :class:`~paddle_tpu.serving.disagg.kv_wire.KVHandoff`
    payloads keyed by session id — ``handoff.prompt`` carries the FULL
    token history (prompt + generated), ``plen`` the written rows, and
    ``next_token`` the last emitted token, which is exactly what a
    resume must feed first. int8 wire (the default) stores ~3.9x more
    sessions per byte; ``wire_dtype="fp32"`` keeps resume bit-exact on
    fp32 engines (int8-resident engines are bit-exact under int8 wire
    too: requantization is idempotent on untouched rows)."""

    def __init__(self, capacity_bytes=256 << 20, wire_dtype="int8",
                 name="default"):
        self.capacity_bytes = int(capacity_bytes)
        self.wire_dtype = str(wire_dtype)
        self.name = str(name)
        self._lock = _conc.named_lock("serving.session_tier")
        self._sessions = collections.OrderedDict()  # sid -> KVHandoff
        self._bytes = 0
        self._stats = collections.Counter()

    def hibernate(self, session_id, handoff):
        """Park a session's sealed handoff; LRU-evicts to capacity
        (an evicted session simply cold-prefills on resume)."""
        sid = str(session_id)
        nbytes = handoff.wire_bytes()
        with self._lock:
            old = self._sessions.pop(sid, None)
            if old is not None:
                self._bytes -= old.wire_bytes()
            self._sessions[sid] = handoff
            self._bytes += nbytes
            self._stats["hibernated"] += 1
            evicted = 0
            while self._bytes > self.capacity_bytes and self._sessions:
                _, dead = self._sessions.popitem(last=False)
                self._bytes -= dead.wire_bytes()
                evicted += 1
            if evicted:
                self._stats["evictions"] += evicted
                obs.inc("serving.tier.evictions", evicted)
            self._gauges_locked()
        obs.inc("serving.tier.hibernated")
        return sid

    def resume(self, session_id):
        """Pop a hibernated session's handoff (verified against its
        sealed digest by the adopting engine). None = unknown/evicted,
        meaning the caller cold-prefills from its own transcript."""
        with self._lock:
            h = self._sessions.pop(str(session_id), None)
            if h is not None:
                self._bytes -= h.wire_bytes()
                self._stats["resumed"] += 1
                self._gauges_locked()
        if h is not None:
            obs.inc("serving.tier.resumed")
        return h

    def peek(self, session_id):
        """Non-destructive lookup (admission-time validation)."""
        with self._lock:
            return self._sessions.get(str(session_id))

    def _gauges_locked(self):
        obs.set_gauge("serving.tier.sessions", len(self._sessions))
        obs.set_gauge("serving.tier.bytes", self._bytes)

    def stats(self):
        with self._lock:
            out = dict(self._stats)
            out["sessions"] = len(self._sessions)
            out["bytes"] = self._bytes
        for key in ("hibernated", "resumed", "evictions"):
            out.setdefault(key, 0)
        out["capacity_bytes"] = self.capacity_bytes
        out["wire_dtype"] = self.wire_dtype
        return out

    def __len__(self):
        with self._lock:
            return len(self._sessions)
