"""ModelRegistry: multiple named models, isolated scopes, atomic hot
reload.

Each ``load(name, dirname)`` builds a fresh ``Predictor`` over the
``save_inference_model`` directory — the predictor loads its params
into a **private scope** (never the process-wide ``global_scope()``),
so two models with overlapping var names (every fc layer is ``fc_0.w``
somewhere) cannot clobber each other — wraps it in a pre-warmed
:class:`~paddle_tpu.serving.engine.ServingEngine`, and only then
publishes it under ``name`` with one dict assignment (the atomic
version swap). Reloading an already-published name builds and warms the
replacement **fully off to the side** while the old engine keeps
serving; after the swap the old engine drains in the background —
in-flight and queued requests on the old version complete, new requests
route to the new version. No request ever observes a half-loaded model.
"""
import threading

from .. import observability as obs
from .engine import ServingEngine

__all__ = ["ModelRegistry"]


class ModelRegistry:
    """name -> live ServingEngine, with versioned atomic swap.

    ::

        reg = ModelRegistry(max_batch_size=16, max_wait_ms=2.0)
        reg.load("bert", "/models/bert_v1",
                 buckets=[BucketSpec({"ids": (128,)},
                                     dtypes={"ids": "int32"})])
        out = reg.get("bert").predict({"ids": batch})
        reg.reload("bert", "/models/bert_v2")   # hot swap, zero downtime
    """

    def __init__(self, **engine_defaults):
        self._lock = threading.Lock()
        self._models = {}
        self._engine_defaults = dict(engine_defaults)

    def load(self, name, dirname, buckets=(), warm=True,
             predictor_opts=None, **engine_opts):
        """Load (or replace) model `name` from a save_inference_model
        directory and publish it atomically. Returns the live engine."""
        from ..fluid.inference import Predictor

        opts = dict(self._engine_defaults)
        opts.update(engine_opts)
        # every failure below happens BEFORE the publish swap: a build
        # or warmup error on the replacement leaves the currently-
        # published version serving untouched (no version limbo)
        predictor = Predictor.from_model(
            str(dirname), **dict(predictor_opts or {}))
        engine = ServingEngine(
            predictor, buckets=buckets, name=str(name), **opts)
        try:
            warm_report = engine.warmup() if warm else []
        except Exception:
            # don't leak the stillborn engine's dispatch thread
            engine.stop(drain=False, timeout=1.0)
            obs.event("model_load_failed", source="serving",
                      model=str(name), dirname=str(dirname))
            raise
        with self._lock:
            old = self._models.get(name)
            version = (old["version"] + 1) if old else 1
            self._models[name] = {
                "engine": engine, "dirname": str(dirname),
                "version": version, "buckets": tuple(buckets),
                "warm": bool(warm),
                "predictor_opts": dict(predictor_opts or {}),
                "engine_opts": dict(engine_opts),
            }
        obs.event("model_load", source="serving", model=str(name),
                  version=version, dirname=str(dirname),
                  warm_entries=len(warm_report))
        if old is not None:
            # the swap already happened; let the old version finish its
            # queue without blocking the loader
            threading.Thread(
                target=old["engine"].stop, kwargs={"drain": True},
                daemon=True,
                name="serving-drain-%s-v%d" % (name, old["version"]),
            ).start()
        return engine

    def publish(self, name, engine, dirname=None):
        """Publish a pre-built engine-like object — anything with the
        ServingEngine surface (``submit``/``predict``/``stats``/
        ``queue_depth``/``stop``), notably a
        :class:`~paddle_tpu.serving.router.ServingRouter` fronting N
        replicas — under `name` with the same atomic-swap semantics as
        :meth:`load`. The registry does not build, warm, or reload it;
        lifecycle beyond the swap/drain belongs to the caller."""
        with self._lock:
            old = self._models.get(name)
            version = (old["version"] + 1) if old else 1
            self._models[name] = {
                "engine": engine, "dirname": str(dirname or ""),
                "version": version, "buckets": (), "warm": False,
                "predictor_opts": {}, "engine_opts": {},
                "published": True,
            }
        obs.event("model_publish", source="serving", model=str(name),
                  version=version,
                  engine_kind=type(engine).__name__)
        if old is not None:
            threading.Thread(
                target=old["engine"].stop, kwargs={"drain": True},
                daemon=True,
                name="serving-drain-%s-v%d" % (name, old["version"]),
            ).start()
        return engine

    def reload(self, name, dirname=None):
        """Hot-reload `name` — from a new directory when given, else
        re-reading the one it was loaded from — with the same buckets
        and engine options. Atomic swap; the old version drains."""
        with self._lock:
            cur = self._models.get(name)
        if cur is None:
            raise KeyError("no model %r loaded" % name)
        if cur.get("published"):
            raise ValueError(
                "model %r was publish()ed, not load()ed — reload it "
                "through its own surface (e.g. "
                "ServingRouter.rolling_reload)" % name)
        return self.load(
            name, dirname if dirname is not None else cur["dirname"],
            buckets=cur["buckets"], warm=cur["warm"],
            predictor_opts=cur["predictor_opts"], **cur["engine_opts"])

    def get(self, name):
        """The live engine for `name`, or None."""
        with self._lock:
            entry = self._models.get(name)
        return entry["engine"] if entry is not None else None

    def version(self, name):
        with self._lock:
            entry = self._models.get(name)
        return entry["version"] if entry is not None else None

    def names(self):
        with self._lock:
            return sorted(self._models)

    def info(self):
        """Per-model health snapshot (the /healthz payload). Engines
        that expose ``reuse_info()`` (a DecodeEngine with a draft
        model, prefix pool, or session tier attached — or a disagg
        router aggregating them) get a ``reuse`` block: draft
        attachment, speculation acceptance, pool hit/miss/evict
        counters, and the redundant-prefill savings."""
        with self._lock:
            entries = dict(self._models)
        out = {}
        for name, e in entries.items():
            doc = {
                "version": e["version"],
                "dirname": e["dirname"],
                "kind": getattr(e["engine"], "engine_kind", "predict"),
                "queue_depth": e["engine"].queue_depth(),
                "stats": e["engine"].stats(),
            }
            reuse = getattr(e["engine"], "reuse_info", None)
            if callable(reuse):
                doc["reuse"] = reuse()
            index = getattr(e["engine"], "index_info", None)
            if callable(index):
                # retrieval engines: the served index's geometry (rows,
                # dim, shards, resident bytes) next to the queue stats
                doc["index"] = index()
            out[name] = doc
        return out

    def unload(self, name, drain=True):
        """Remove `name`; its engine stops (draining by default)."""
        with self._lock:
            entry = self._models.pop(name, None)
        if entry is None:
            raise KeyError("no model %r loaded" % name)
        entry["engine"].stop(drain=drain)
        obs.event("model_unload", source="serving", count=False,
                  model=str(name), version=entry["version"])

    def close(self, drain=True):
        """Stop every engine (graceful drain by default)."""
        with self._lock:
            entries = list(self._models.values())
            self._models.clear()
        for e in entries:
            e["engine"].stop(drain=drain)
