"""Speculative decoding: a small draft model proposes, the target
verifies a whole block in one step.

Non-speculative decode pays one full target-model dispatch per token.
Speculation breaks that coupling: a cheap draft model (same tokenizer/
vocab, a fraction of the layers/width) runs ``k`` sequential steps to
propose ``k`` tokens, then the target scores the whole candidate block
``[current, d1..dk]`` in ONE batched AOT program
(:func:`~paddle_tpu.models.gpt.build_gpt_verify_block`) and accepts
the longest prefix that matches its own greedy picks. Every emitted
token is the TARGET's greedy argmax — the draft only chooses how many
of them one dispatch yields — so continuations are bit-exact with
non-speculative decode by construction; a useless draft costs speed,
never correctness. Acceptance rate (accepted draft tokens / proposed)
is the economics dial, exported as ``serving.spec.accept_rate``.

:class:`DraftModel` owns the draft's programs and its own slot-shaped
KV buffers, kept row-aligned with the target engine's slots: admission
prefills the draft cache from the same token history, each propose
round advances it alongside the target, and single-token fallback
steps (cache-edge headroom) mirror into it via :meth:`sync_step`, so
draft rows never hole. The draft is fp32-resident (it is small; int8
residency would only dent its accuracy).

Per-round cost: ``k + 1`` draft dispatches (the +1 backfills the row
of the last proposal so a fully-accepted block leaves no gap) plus one
target verify dispatch — profitable whenever the draft step is much
cheaper than the target step and acceptance is decent.
"""
import numpy as np

from .. import observability as obs
from ..analysis import concurrency as _conc

__all__ = ["DraftModel"]


class DraftModel:
    """Draft-model sidecar for a :class:`~paddle_tpu.serving.decode.
    DecodeEngine` (``DecodeEngine(..., draft=DraftModel(dcfg, dscope,
    k=4))``).

    ``cfg``/``scope`` are the draft's own config and trained params —
    ``cfg.vocab`` must match the target's (same token ids) and
    ``cfg.max_len`` must cover the engine's ``cache_len``. ``k`` is
    the proposals per round; the verify block is ``k + 1`` wide.
    """

    def __init__(self, cfg, scope, k=4, name="draft"):
        self.cfg = cfg
        self.k = int(k)
        self.name = str(name)
        if self.k < 1:
            raise ValueError("draft k must be >= 1, got %d" % self.k)
        self._scope = scope
        self._engine = None
        self._params = None
        self._step_pred = None
        self._prefill_preds = {}
        self._buckets = ()
        self._k_buf = self._v_buf = None
        self._write = None
        self.slots = 0
        self.cache_len = 0

    # -- wiring ----------------------------------------------------------
    def bind(self, engine):
        """Build the draft's step + prefill programs and slot buffers
        against ``engine``'s geometry. Called by the engine's
        constructor; idempotent per engine."""
        import jax

        import paddle_tpu.fluid as fluid
        from ..fluid.inference import Predictor
        from ..models.gpt import build_gpt_decode_step, build_gpt_prefill
        from .decode import default_prompt_buckets

        if self._engine is engine:
            return self
        if self._engine is not None:
            raise RuntimeError(
                "draft %r is already bound to engine %r — one draft "
                "per engine (it mirrors that engine's slots)"
                % (self.name, self._engine.name))
        if self.cfg.vocab != engine.cfg.vocab:
            raise ValueError(
                "draft vocab %d != target vocab %d — speculation needs "
                "a shared token space"
                % (self.cfg.vocab, engine.cfg.vocab))
        if engine.cache_len > self.cfg.max_len:
            raise ValueError(
                "engine cache_len %d exceeds draft max_len %d"
                % (engine.cache_len, self.cfg.max_len))
        self._engine = engine
        self.slots = engine.slots
        self.cache_len = engine.cache_len
        # the draft prefill ladder must cover ANY live token history
        # (sessions outgrow the prompt buckets), so merge the engine's
        # buckets with a pow2 ladder up to cache_len
        self._buckets = tuple(sorted(
            set(engine.prompt_buckets)
            | set(default_prompt_buckets(self.cache_len))))
        with fluid.program_guard(fluid.Program(), fluid.Program()):
            sv = build_gpt_decode_step(self.cfg, self.cache_len)
            step_prog = fluid.default_main_program()
        prefill = {}
        for b in self._buckets:
            with fluid.program_guard(fluid.Program(), fluid.Program()):
                pv = build_gpt_prefill(self.cfg, b, self.cache_len)
                prefill[b] = (fluid.default_main_program(), pv)
        persist = {}
        for prog in [step_prog] + [p for p, _ in prefill.values()]:
            for v in prog.list_vars():
                if not getattr(v, "persistable", False) \
                        or v.name in persist:
                    continue
                if v.name not in self._scope:
                    raise KeyError(
                        "param %r required by the draft programs is "
                        "missing from the draft scope" % v.name)
                persist[v.name] = jax.device_put(
                    np.asarray(self._scope[v.name]))
        self._params = persist
        self._step_vars = sv
        self._step_pred = Predictor(
            step_prog, sv["feed_names"], sv["fetch_vars"], scope=persist)
        self._step_pred.ledger_tag = "spec.draft_step:%s" % self.name
        for b, (prog, pv) in prefill.items():
            self._prefill_preds[b] = Predictor(
                prog, pv["feed_names"], pv["fetch_vars"], scope=persist)
            self._prefill_preds[b].ledger_tag = (
                "spec.draft_prefill:%s" % self.name)
        shape = (self.slots, self.cfg.num_layers, self.cache_len,
                 self.cfg.hidden)
        self._k_buf = jax.device_put(np.zeros(shape, np.float32))
        self._v_buf = jax.device_put(np.zeros(shape, np.float32))
        self._write = jax.jit(
            lambda buf, val, slot: jax.lax.dynamic_update_slice(
                buf, val, (slot, 0, 0, 0)),
            donate_argnums=(0,))
        return self

    def warmup(self):
        """Warm every draft program through the compile-cache tier;
        returns the per-program report rows."""
        report = []
        source = self._step_pred.warm({
            "gpt_step_tok": np.zeros((self.slots, 1), np.int64),
            "gpt_step_pos": np.zeros((self.slots, 1), np.int64),
            "gpt_step_k": np.zeros(self._k_buf.shape, np.float32),
            "gpt_step_v": np.zeros(self._v_buf.shape, np.float32)})
        report.append({"program": "draft_step", "k": self.k,
                       "source": source})
        for b in sorted(self._prefill_preds):
            source = self._prefill_preds[b].warm({
                "gpt_prefill_ids": np.zeros((1, b), np.int64),
                "gpt_prefill_len": np.ones((1, 1), np.int64)})
            report.append({"program": "draft_prefill", "bucket": b,
                           "source": source})
        return report

    # -- slot mirroring --------------------------------------------------
    def prefill_slot(self, slot, tokens):
        """Prefill the draft's cache rows for ``slot`` from the full
        token history whose rows the TARGET slot holds (prompt, or
        prompt + generated for adopted/resumed sessions)."""
        tokens = np.asarray(tokens, np.int64).reshape(-1)
        n = int(tokens.size)
        bucket = next((b for b in self._buckets if b >= n), None)
        if bucket is None:
            raise ValueError(
                "draft history %d exceeds the draft ladder (max %d)"
                % (n, self._buckets[-1]))
        ids = np.zeros((1, bucket), np.int64)
        ids[0, :n] = tokens
        if _conc._on:
            _conc.note_blocking("device.dispatch")
        _nxt, k1, v1 = self._prefill_preds[bucket].run(
            {"gpt_prefill_ids": ids,
             "gpt_prefill_len": np.asarray([[n]], np.int64)},
            return_numpy=False)
        slot_i = np.int32(slot)
        self._k_buf = self._write(self._k_buf, k1, slot_i)
        self._v_buf = self._write(self._v_buf, v1, slot_i)

    def _step(self, tok, pos):
        if _conc._on:
            _conc.note_blocking("device.dispatch")
        nxt, self._k_buf, self._v_buf = self._step_pred.run(
            {"gpt_step_tok": tok, "gpt_step_pos": pos,
             "gpt_step_k": self._k_buf, "gpt_step_v": self._v_buf},
            return_numpy=False)
        return np.asarray(nxt)

    def propose(self, tok, pos):
        """One speculation round from the target's ``(tok, pos)`` slot
        arrays: ``k + 1`` sequential draft steps — the first ``k``
        yield proposals (S, k), the last backfills the final
        proposal's cache row so a fully-accepted block leaves the
        draft cache gapless. Caller guarantees ``pos + k + 1 <=
        cache_len`` for live rows."""
        t = np.asarray(tok, np.int64).copy()
        p = np.asarray(pos, np.int64).copy()
        out = np.zeros((t.shape[0], self.k), np.int64)
        for j in range(self.k + 1):
            nxt = self._step(t, p)
            if j < self.k:
                out[:, j] = nxt[:, 0]
            t = nxt.astype(np.int64)
            p = p + 1
        return out

    def sync_step(self, tok, pos):
        """Mirror a non-speculative (fallback) target step: write the
        consumed token's row into the draft cache so later rounds see
        a complete history. The draft's own proposal is discarded."""
        self._step(np.asarray(tok, np.int64), np.asarray(pos, np.int64))

    # -- introspection ---------------------------------------------------
    def resident_bytes(self):
        """HBM bytes of the draft's params + slot buffer pair — what
        the target engine's ``check_hbm_budget`` subtracts."""
        n = 0
        if self._params:
            n += sum(int(np.prod(a.shape)) * a.dtype.itemsize
                     for a in self._params.values())
        if self._k_buf is not None:
            n += 2 * int(np.prod(self._k_buf.shape)) * 4
        return n

    def info(self):
        return {"name": self.name, "k": self.k,
                "vocab": self.cfg.vocab, "hidden": self.cfg.hidden,
                "num_layers": self.cfg.num_layers,
                "resident_bytes": self.resident_bytes(),
                "buckets": list(self._buckets)}
