"""ServingEngine: dynamic micro-batching over an AOT-compiled Predictor.

One bounded request queue + one dispatch thread per model. Concurrent
``submit()`` calls enqueue requests; the dispatch thread coalesces them
into micro-batches (flushing on ``max_batch_size`` rows or
``max_wait_ms``, whichever comes first), pads each same-tail-shape
group up to a declared :class:`~paddle_tpu.serving.batcher.BucketSpec`
batch size, runs ONE pre-warmed AOT executable per bucket, and slices
per-request rows back into each caller's future. ``warmup()`` compiles
every declared (bucket, batch size) through the predictor's
compile-cache disk tier, so a restarted server deserializes the AOT
artifacts instead of paying XLA again (zero ``compile_start`` events on
a warm start).

Admission control (the resilience posture of PR 1, applied to serving):

- **load shedding** — a full queue fast-rejects at ``submit()`` with
  :class:`ShedError` (HTTP 429 upstream) instead of building unbounded
  latency;
- **deadlines** — a request whose ``deadline_ms`` expires while queued
  is dropped at dispatch with :class:`DeadlineExceededError` (504)
  rather than burning chip time on an answer nobody is waiting for;
- **graceful drain** — ``stop(drain=True)`` rejects new work, finishes
  everything queued, then parks the dispatch thread.

Telemetry: ``serving.queue_wait_seconds`` / ``serving.batch_size`` /
``serving.batch_rows`` / ``serving.padding_waste`` /
``serving.request_seconds`` histograms, ``serving.shed`` and
``serving.deadline_miss`` counters (every reject also lands in the
flight recorder), and a ``serving.queue_depth.<model>`` gauge.
"""
import collections
import queue
import threading
import time
from concurrent.futures import Future

from .. import observability as obs
from ..analysis import concurrency as _conc
from .batcher import assemble, round_up_pow2, tail_signature

__all__ = [
    "DeadlineExceededError", "EngineClosedError", "ServingEngine",
    "ShedError",
]


class ShedError(RuntimeError):
    """Fast-reject: the bounded request queue is full (load shedding).

    Carries enough context for the HTTP frontend to answer usefully:
    ``model`` / ``replica`` identify who shed, ``retry_after`` is the
    engine's drain-rate-derived backoff hint in seconds (the 429
    ``Retry-After`` header upstream)."""

    def __init__(self, message="", model=None, replica=None,
                 retry_after=None):
        super().__init__(message)
        self.model = model
        self.replica = replica
        self.retry_after = retry_after


class DeadlineExceededError(RuntimeError):
    """The request's deadline expired while it waited in the queue."""


class EngineClosedError(RuntimeError):
    """The engine is stopped or draining; no new work is admitted."""


class _Request:
    __slots__ = ("feeds", "rows", "sig", "deadline", "future", "t_enqueue")


class ServingEngine:
    """Micro-batching dispatch loop around one Predictor (one model
    version — :class:`~paddle_tpu.serving.registry.ModelRegistry` swaps
    whole engines for hot reload)."""

    def __init__(self, predictor, buckets=(), max_batch_size=8,
                 max_wait_ms=2.0, queue_capacity=64,
                 default_deadline_ms=None, request_timeout_s=60.0,
                 name="default", replica_id=None, auto_start=True):
        self._predictor = predictor
        self.name = str(name)
        # attribute this engine's executables in the ledger/perf CLI
        try:
            predictor.ledger_tag = "serving:%s" % self.name
        except Exception:  # noqa: BLE001 — duck-typed predictors in tests
            pass
        self.replica_id = replica_id
        self._max_batch_size = int(max_batch_size)
        self._max_wait_s = float(max_wait_ms) / 1000.0
        self._default_deadline_ms = default_deadline_ms
        self.request_timeout_s = float(request_timeout_s)
        self._q = queue.Queue(maxsize=int(queue_capacity))
        self._bucket_specs = tuple(buckets)
        self._buckets = {
            spec.signature(): spec.batch_sizes for spec in self._bucket_specs
        }
        self._stop_event = threading.Event()
        self._closed = False
        # admission vs stop() is a race without this lock: a submitter
        # that passed the closed check could land its queue.put AFTER a
        # drain finished, silently stranding the request. Admission
        # (closed check + put) and the stop-side closed flip are both
        # atomic under _admit_lock, so every request either reaches the
        # queue before the drain starts or gets EngineClosedError.
        self._admit_lock = _conc.named_lock("serving.engine.admit")
        self._thread = None
        self._stats_lock = _conc.named_lock("serving.engine.stats")
        self._owner = _conc.owner_token("serving-engine", self.name, self)
        self._stats = collections.Counter()
        # (t_done, n_requests) per dispatched group — the drain-rate
        # window behind retry_after_hint()
        self._rate = collections.deque(maxlen=64)
        if auto_start:
            self.start()

    # -- lifecycle -------------------------------------------------------
    def start(self):
        """Start the dispatch thread (idempotent)."""
        if self._closed:
            raise EngineClosedError("engine %r is closed" % self.name)
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._loop, daemon=True,
                name="serving-dispatch-%s" % self.name)
            _conc.track_thread(self._thread, self._owner)
            self._thread.start()
        return self

    def stop(self, drain=True, timeout=30.0):
        """Stop admitting work; with ``drain=True`` finish everything
        already queued first, else fail queued requests with
        :class:`EngineClosedError`. Idempotent."""
        with self._admit_lock:
            self._closed = True
        alive = self._thread is not None and self._thread.is_alive()
        if drain and alive:
            t_end = time.monotonic() + float(timeout)
            while not self._q.empty() and time.monotonic() < t_end:
                if _conc._on:
                    _conc.note_blocking("time.sleep(drain)")
                time.sleep(0.005)
        self._stop_event.set()
        if alive:
            self._thread.join(timeout=max(0.1, float(timeout)))
        # anything still queued (no thread, or a non-drain stop that
        # beat the loop to them) fails loudly rather than hanging
        while True:
            try:
                r = self._q.get_nowait()
            except queue.Empty:
                break
            r.future.set_exception(EngineClosedError(
                "engine %r stopped before dispatch" % self.name))
        # the dispatch thread must be gone now — a survivor is a leak
        # (recorded as a violation when the lock sanitizer is armed).
        # Grace outlasts an in-flight jit compile on short-join stops;
        # the poll returns the instant the thread exits.
        _conc.check_stopped(self._owner, grace=10.0)
        obs.event("engine_stop", source="serving", count=False,
                  model=self.name, drained=bool(drain))

    # -- admission -------------------------------------------------------
    def submit(self, feeds, deadline_ms=None, trace_ctx=None):
        """Enqueue one request; returns a ``concurrent.futures.Future``
        resolving to the per-request fetch list (rows sliced back out of
        the coalesced batch). Raises :class:`ShedError` immediately when
        the queue is full and :class:`EngineClosedError` after
        ``stop()``. A sampled ``trace_ctx`` exports one
        ``serving.predict`` span (queue wait + batch compute) when the
        request resolves."""
        if self._closed:  # cheap early reject; re-checked under the lock
            raise EngineClosedError(
                "engine %r is draining/stopped" % self.name)
        prepared, _ = self._predictor._prepare(feeds)
        if not prepared:
            raise ValueError("empty request: no feeds")
        rows = int(next(iter(prepared.values())).shape[0])
        for n, v in prepared.items():
            if int(v.shape[0]) != rows:
                raise ValueError(
                    "feed %r has %d rows but %r has %d — all feeds must "
                    "share the leading batch dim"
                    % (n, v.shape[0], self._predictor.feed_names[0], rows))
        if rows < 1:
            raise ValueError("empty request: 0 rows")
        req = _Request()
        req.feeds = prepared
        req.rows = rows
        req.sig = tail_signature(prepared)
        if deadline_ms is None:
            deadline_ms = self._default_deadline_ms
        req.deadline = (
            time.monotonic() + float(deadline_ms) / 1000.0
            if deadline_ms is not None else None)
        req.future = Future()
        req.t_enqueue = time.monotonic()
        try:
            with self._admit_lock:
                if self._closed:
                    raise EngineClosedError(
                        "engine %r is draining/stopped" % self.name)
                self._q.put_nowait(req)
        except queue.Full:
            self._bump("shed")
            obs.event("shed", source="serving", model=self.name, rows=rows,
                      queue_capacity=self._q.maxsize)
            raise ShedError(
                "serving queue full (%d) for model %r%s — request shed"
                % (self._q.maxsize, self.name,
                   "" if self.replica_id is None
                   else " (replica %s)" % self.replica_id),
                model=self.name, replica=self.replica_id,
                retry_after=self.retry_after_hint())
        self._bump("requests")
        obs.set_gauge("serving.queue_depth.%s" % self.name, self._q.qsize())
        if trace_ctx is not None and getattr(trace_ctx, "sampled", False):
            ctx = trace_ctx.child()
            t_wall = time.time()
            req.future.add_done_callback(
                lambda f, c=ctx, t=t_wall: obs.export_span(
                    "serving.predict", c, t, time.time() - t,
                    {"proc": "engine:%s" % self.name, "rows": rows,
                     "error": (type(f.exception()).__name__
                               if f.exception() else None)}))
        return req.future

    def predict(self, feeds, deadline_ms=None, timeout=None):
        """Synchronous submit + wait: returns the fetch list for this
        request's rows."""
        fut = self.submit(feeds, deadline_ms=deadline_ms)
        return fut.result(
            timeout if timeout is not None else self.request_timeout_s)

    # -- warmup ----------------------------------------------------------
    def check_hbm_budget(self, budget_bytes=None):
        """Predict each bucket ladder's worst-bucket peak HBM with the
        static liveness analyzer and reject ladders that cannot fit.

        ``budget_bytes=None`` resolves the device capacity from the
        analyzer's device table (or ``PADDLE_TPU_HBM_BYTES``); when no
        capacity is known the check is a no-op. Raises
        :class:`~paddle_tpu.analysis.ProgramVerifyError` listing every
        over-budget ladder — BEFORE any warmup compile touches XLA."""
        from ..analysis import costs as _costs, memory as _memory
        from ..analysis.diagnostics import ProgramVerifyError
        from ..fluid.executor import _device_kind

        if budget_bytes is None:
            profile = _costs.device_profile(_device_kind())
            budget_bytes = profile.hbm_bytes if profile else None
        if not budget_bytes:
            return []
        pred = self._predictor
        results = []
        worst = 0
        for spec in self._bucket_specs:
            b = spec.max_batch_size
            est = _memory.estimate(
                pred.program, feed_specs=spec.feed_specs(b),
                state_specs=pred._state,
                fetch_names=pred.fetch_names,
                state_names=set(pred._state), default_dim=b)
            worst = max(worst, est.peak_bytes)
            results.append((spec, b, est))
        obs.set_gauge(
            "serving.predicted_peak_hbm.%s" % self.name, worst)
        over = [(spec, b, est) for spec, b, est in results
                if est.peak_bytes > budget_bytes]
        if not over:
            return results
        lines = [
            "bucket %s at batch %d: predicted peak %.2f MB "
            "(params %.2f MB + activations %.2f MB at op %s '%s')"
            % (spec.signature(), b, est.peak_bytes / 1e6,
               est.param_bytes / 1e6, est.act_bytes_at_peak / 1e6,
               est.peak_op_index, est.peak_op_type)
            for spec, b, est in over]
        obs.event("bucket_rejected", source="serving", model=self.name,
                  rejected=len(over), budget_bytes=int(budget_bytes))
        raise ProgramVerifyError(
            "predicted-oom: %d of %d bucket ladder(s) exceed the HBM "
            "budget (%.2f MB) — trim the worst batch sizes or shard the "
            "model:\n%s"
            % (len(over), len(results), budget_bytes / 1e6,
               "\n".join(lines)))

    def warmup(self, check_hbm=True):
        """Pre-build one executable per declared (bucket, batch size)
        through the predictor's compile-cache disk tier. On a restarted
        server every entry resolves from disk — ``source == "disk"``,
        zero ``compile_start`` events. Returns the per-entry report.

        ``check_hbm=True`` first runs :meth:`check_hbm_budget`: a
        ladder whose worst bucket cannot fit the device raises before
        any compile is attempted."""
        if check_hbm:
            self.check_hbm_budget()
        report = []
        for spec in self._bucket_specs:
            for b in spec.batch_sizes:
                source = self._predictor.warm(spec.feeds_for(b))
                report.append({
                    "signature": spec.signature(), "batch_size": b,
                    "source": source,
                })
        if report:
            obs.event(
                "warmup", source="serving", count=False, model=self.name,
                engines=len(report),
                compiled=sum(1 for r in report if r["source"] == "compile"),
                disk_warm=sum(1 for r in report if r["source"] == "disk"))
        return report

    # -- dispatch --------------------------------------------------------
    def _loop(self):
        carry = None  # request popped but not fitting the last batch
        while True:
            if carry is not None:
                first, carry = carry, None
            else:
                try:
                    if _conc._on:
                        _conc.note_blocking("queue.get")
                    first = self._q.get(timeout=0.05)
                except queue.Empty:
                    if self._stop_event.is_set():
                        return
                    continue
            batch = [first]
            rows = first.rows
            t_flush = time.monotonic() + self._max_wait_s
            while rows < self._max_batch_size:
                remaining = t_flush - time.monotonic()
                if remaining <= 0:
                    break
                try:
                    if _conc._on:
                        _conc.note_blocking("queue.get")
                    r = self._q.get(timeout=remaining)
                except queue.Empty:
                    break
                if rows + r.rows > self._max_batch_size:
                    # would overshoot the bucket ladder: starts the NEXT
                    # micro-batch instead of forcing an ad-hoc shape
                    carry = r
                    break
                batch.append(r)
                rows += r.rows
            obs.set_gauge(
                "serving.queue_depth.%s" % self.name, self._q.qsize())
            self._execute(batch)

    def _execute(self, batch):
        now = time.monotonic()
        live = []
        for r in batch:
            if r.deadline is not None and now > r.deadline:
                self._bump("deadline_miss")
                waited_ms = round(1000 * (now - r.t_enqueue), 3)
                obs.event("deadline_miss", source="serving",
                          model=self.name, rows=r.rows,
                          waited_ms=waited_ms)
                r.future.set_exception(DeadlineExceededError(
                    "deadline expired after %s ms in queue (model %r)"
                    % (waited_ms, self.name)))
            else:
                live.append(r)
        groups = collections.OrderedDict()
        for r in live:
            groups.setdefault(r.sig, []).append(r)
        for sig, reqs in groups.items():
            self._run_group(sig, reqs)

    def _bucket_rows(self, sig, rows):
        """The padded batch size for `rows` rows of tail-shape `sig`:
        the smallest declared bucket that fits, exact when the request
        outgrows every bucket, next-pow2 (capped at max_batch_size) for
        undeclared shapes."""
        declared = self._buckets.get(sig)
        if declared:
            for b in declared:
                if b >= rows:
                    return b
            return rows
        if rows >= self._max_batch_size:
            return rows
        return min(round_up_pow2(rows), self._max_batch_size)

    def _run_group(self, sig, reqs):
        t0 = time.monotonic()
        rows = sum(r.rows for r in reqs)
        target = self._bucket_rows(sig, rows)
        for r in reqs:
            obs.observe("serving.queue_wait_seconds", t0 - r.t_enqueue)
        try:
            feeds = assemble(self._predictor.feed_names, reqs, target)
            if _conc._on:
                _conc.note_blocking("device.dispatch")
            outs = self._predictor.run(feeds, return_numpy=True)
            for o in outs:
                if getattr(o, "ndim", 0) < 1 or o.shape[0] != target:
                    raise ValueError(
                        "fetch output shape %s is not row-aligned with "
                        "the %d-row batch — ServingEngine needs per-row "
                        "outputs to slice results back to requests"
                        % (getattr(o, "shape", None), target))
        except Exception as e:  # noqa: BLE001 — fail the requests, not the loop
            self._bump("batch_errors")
            obs.event("batch_error", source="serving", model=self.name,
                      rows=rows, error="%s: %s"
                      % (type(e).__name__, str(e)[:200]))
            for r in reqs:
                r.future.set_exception(e)
            with self._stats_lock:  # errors still drain the queue
                self._rate.append((time.monotonic(), len(reqs)))
            return
        self._bump("batches")
        if len(reqs) > 1:
            self._bump("coalesced")
        self._bump("rows", rows)
        obs.observe("serving.batch_size", len(reqs))
        obs.observe("serving.batch_rows", rows)
        obs.observe("serving.padding_waste", (target - rows) / float(target))
        done = time.monotonic()
        with self._stats_lock:
            self._rate.append((done, len(reqs)))
        off = 0
        for r in reqs:
            # copy the slices: a view would pin the whole padded batch
            # (and every other request's rows) in memory for as long as
            # the caller holds its result
            r.future.set_result(
                [o[off:off + r.rows].copy() for o in outs])
            off += r.rows
            obs.observe("serving.request_seconds", done - r.t_enqueue)

    # -- introspection ---------------------------------------------------
    def _bump(self, key, n=1):
        with self._stats_lock:
            self._stats[key] += n

    def stats(self):
        """Local lifetime counters (independent of the telemetry mode):
        requests/shed/deadline_miss/batches/coalesced/rows/batch_errors."""
        with self._stats_lock:
            out = dict(self._stats)
        for k in ("requests", "shed", "deadline_miss", "batches",
                  "coalesced", "rows", "batch_errors"):
            out.setdefault(k, 0)
        return out

    def queue_depth(self):
        return self._q.qsize()

    def drain_rate(self):
        """Requests/sec the dispatch loop completed over its recent
        window (None until the first batch lands, or after 30s idle)."""
        now = time.monotonic()
        with self._stats_lock:
            pts = [(t, n) for t, n in self._rate if now - t < 30.0]
        if not pts:
            return None
        span = max(1e-3, now - min(t for t, _ in pts))
        return sum(n for _, n in pts) / span

    def retry_after_hint(self):
        """Seconds until the current queue likely drains at the
        observed rate — what a shed client should wait before retrying
        (the HTTP 429 ``Retry-After``). Clamped to [1, 60]."""
        rate = self.drain_rate()
        if not rate:
            return 1.0
        return min(60.0, max(1.0, (self.queue_depth() + 1) / rate))

    @property
    def predictor(self):
        return self._predictor

    @property
    def closed(self):
        return self._closed
