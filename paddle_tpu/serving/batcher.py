"""Shape buckets + micro-batch assembly for the serving engine.

Requests are coalesced along the leading (batch) dimension only: two
requests join the same micro-batch iff every feed agrees on its *tail*
shape (dims after axis 0) and dtype. The coalesced rows are padded up
to a pre-declared bucket batch size by edge-replicating the last real
row — padding the batch dim is the one padding that keeps per-row
results bit-identical to an unpadded run (row-independent inference
graphs: each output row depends only on its own input row), whereas
padding feature/sequence dims would change real rows' math.

A :class:`BucketSpec` declares the tail shapes, dtypes, and the ladder
of batch sizes the engine pre-compiles at load time; requests whose
tail signature matches no declared bucket still batch, rounded up to
the next power of two (bounded executable count without declarations).
"""
import numpy as np

__all__ = [
    "BucketSpec", "assemble", "round_up_pow2", "tail_signature",
]


def round_up_pow2(n):
    """Smallest power of two >= n (n >= 1)."""
    n = int(n)
    if n < 1:
        raise ValueError("round_up_pow2 needs n >= 1, got %d" % n)
    return 1 << (n - 1).bit_length()


def tail_signature(prepared):
    """The coalescing key of a prepared feed dict: per-feed tail shape
    (dims after the batch axis) + dtype, name-sorted."""
    return tuple(
        (n, tuple(int(d) for d in prepared[n].shape[1:]),
         str(prepared[n].dtype))
        for n in sorted(prepared)
    )


class BucketSpec:
    """One pre-declared shape bucket: the tail shape + dtype of every
    feed, and the batch sizes to pre-compile for it.

    ::

        BucketSpec({"x": (6,)}, batch_sizes=(1, 2, 4, 8))
        BucketSpec({"ids": (128,)}, dtypes={"ids": "int32"},
                   batch_sizes=(1, 4, 16))
    """

    def __init__(self, shapes, dtypes=None, batch_sizes=(1, 2, 4, 8)):
        if not shapes:
            raise ValueError("BucketSpec needs at least one feed shape")
        self.shapes = {
            str(n): tuple(int(d) for d in s) for n, s in shapes.items()
        }
        dtypes = dtypes or {}
        self.dtypes = {
            n: str(np.dtype(dtypes.get(n, "float32"))) for n in self.shapes
        }
        self.batch_sizes = tuple(sorted({int(b) for b in batch_sizes}))
        if not self.batch_sizes or self.batch_sizes[0] < 1:
            raise ValueError(
                "batch_sizes must be positive ints, got %r" % (batch_sizes,))

    def signature(self):
        """Tail signature this bucket serves (matches
        :func:`tail_signature` of conforming requests)."""
        return tuple(
            (n, self.shapes[n], self.dtypes[n]) for n in sorted(self.shapes)
        )

    def feeds_for(self, batch_size):
        """Zero-filled dummy feeds of one padded batch shape (warmup
        compiles against these)."""
        return {
            n: np.zeros((int(batch_size),) + self.shapes[n],
                        dtype=self.dtypes[n])
            for n in self.shapes
        }

    @property
    def max_batch_size(self):
        """The ladder's worst (largest) batch — what HBM admission
        prices."""
        return self.batch_sizes[-1]

    def feed_specs(self, batch_size):
        """Abstract (shape, dtype) specs of :meth:`feeds_for` without
        allocating the arrays — capacity planning uses these."""
        import jax

        return {
            n: jax.ShapeDtypeStruct(
                (int(batch_size),) + self.shapes[n],
                np.dtype(self.dtypes[n]))
            for n in self.shapes
        }

    def __repr__(self):
        return "BucketSpec(shapes=%r, dtypes=%r, batch_sizes=%r)" % (
            self.shapes, self.dtypes, self.batch_sizes)


def assemble(feed_names, requests, target_rows):
    """Concatenate the requests' feeds along axis 0 and pad up to
    ``target_rows`` by edge-replicating the last real row. Returns the
    padded feed dict for one executable dispatch."""
    out = {}
    for name in feed_names:
        parts = [np.asarray(r.feeds[name]) for r in requests]
        cat = parts[0] if len(parts) == 1 else np.concatenate(parts, axis=0)
        short = int(target_rows) - cat.shape[0]
        if short > 0:
            cat = np.pad(
                cat, [(0, short)] + [(0, 0)] * (cat.ndim - 1), mode="edge")
        out[name] = cat
    return out
