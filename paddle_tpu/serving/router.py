"""ServingRouter: one model spread across N ServingEngine replicas.

The single-engine stack (PR 4) made one dispatch thread saturate one
chip; this module makes the MODEL survive the replica. A
:class:`ServingRouter` wears the ServingEngine duck-type surface
(``submit`` / ``predict`` / ``stats`` / ``queue_depth`` / ``stop``) so
:meth:`~paddle_tpu.serving.registry.ModelRegistry.publish` and the HTTP
frontend drive a fleet exactly like a single engine — and underneath it
is built from the elastic-fleet guard the TRAINING side already trusts
(``parallel/elastic.py``): every replica publishes heartbeat beacons
(queue depth + model version riding the ``extra`` field) into a shared
:class:`~paddle_tpu.parallel.elastic.HeartbeatStore`, and the router's
:class:`~paddle_tpu.parallel.elastic.HeartbeatMonitor` — a pure
observer, never a member — classifies replicas dead or straggling with
the same silence/lag rules that fence a dead training worker.

Replica flavors:

- :class:`LocalReplica` — in-process engine, optionally pinned to one
  device of an 8-device host (``jax.default_device`` around predictor
  build + warmup), beating into the shared store from a background
  thread. ``kill()`` simulates a crash: the beater goes silent (death
  IS silence — no clean 'left' beacon) and queued futures fail so the
  router replays them on survivors.
- :class:`StoreReplica` / :class:`ReplicaWorker` — the per-process
  pair: the router-side proxy serializes requests into FileStore
  namespaces (``serve/<model>/req/<rid>``), the worker process
  (``python -m paddle_tpu.serving.router``) drains them through its own
  ServingEngine and writes responses back. SIGKILL the worker and its
  beacons stop; the router's health loop fails the orphaned in-flight
  requests with :class:`ReplicaGoneError`, which the dispatch layer
  treats as "replay on the next replica".

Dispatch is least-loaded with shed-aware failover: candidates are the
live replicas ordered by (straggler?, queue depth), depth ties rotated
round-robin so an idle fleet still spreads load; a replica that
sheds (:class:`~.engine.ShedError`) or is draining just moves the
request to the next candidate, and when EVERY replica sheds the router
backs off exponentially and retries inside the request's deadline
budget before surfacing a fleet-wide ShedError (HTTP 429 upstream,
``Retry-After`` from the healthiest replica's drain rate). Retries are
safe because inference is idempotent — a request is only ever resolved
once, by whichever replica finishes it.

Lifecycle:

- **drain-then-kill preemption** — ``remove_replica(rid, drain=True)``
  unmaps the replica first (no new work), then ``stop(drain=True)``
  finishes its queue; an UNplanned death instead replays the queue on
  survivors via failover.
- **autoscale** — sustained queue pressure above ``scale_up_depth``
  activates a warm standby (already built + warmed, just not in the
  dispatch set); sustained idleness below ``scale_down_depth`` returns
  the most recently scaled-up replica to standby after its queue
  drains.
- **rolling reload** — ``rolling_reload(new_dirname)`` upgrades one
  replica at a time: quiesce (out of the dispatch set), drain, rebuild
  from the new version directory, probe (health gate), rejoin. Any
  build/probe failure rolls every already-upgraded replica back to the
  prior version and raises :class:`RolloutError` — no version limbo,
  and the other replicas served v_old the whole time (zero downtime).

Fault sites (``PADDLE_TPU_FAULT_SPEC``): ``dispatch`` fires per router
dispatch attempt, ``replica`` in LocalReplica admission — so
``replica:at=1:RuntimeError`` is a replica crash drill and
``replica:every=3:slow`` a brownout drill, both absorbed by failover.

Telemetry: ``serving.replicas_live`` / ``serving.rollout_state``
gauges, ``serving.failovers`` / ``serving.router_retry`` /
``serving.replica_dead`` counters, ``serving.dispatch_seconds``
histogram.
"""
import collections
import itertools
import json
import os
import threading
import time
from concurrent.futures import Future, InvalidStateError

import numpy as np

from .. import observability as obs
from ..fluid import resilience as R
from ..parallel.elastic import (
    ElasticConfig, FileStore, HeartbeatMonitor, InMemoryStore,
)
from .engine import EngineClosedError, ServingEngine, ShedError

__all__ = [
    "LocalReplica", "NoReplicasError", "ReplicaGoneError", "ReplicaWorker",
    "RolloutError", "ServingRouter", "StoreReplica", "local_fleet",
    "make_engine_factory", "worker_main",
]


class NoReplicasError(RuntimeError):
    """The router has zero live replicas (HTTP 503 upstream — the
    frontend matches this class by name to avoid the import)."""


class ReplicaGoneError(RuntimeError):
    """A replica died with this request in flight; the router treats it
    as retryable and replays the request on a survivor.

    ``dump_paths`` lists any crash-dump files the dead worker
    advertised on its beacons — per-pid paths (see
    :func:`paddle_tpu.observability.crash_dump_path`), so two workers
    crashing together never clobber one dump file."""

    def __init__(self, msg, dump_paths=()):
        RuntimeError.__init__(self, msg)
        self.dump_paths = tuple(dump_paths)


class RolloutError(RuntimeError):
    """A rolling reload failed and was rolled back (or could not be)."""


# ---------------------------------------------------------------------------
# wire format (StoreReplica <-> ReplicaWorker)
# ---------------------------------------------------------------------------


def _encode_array(a):
    a = np.asarray(a)
    return {"data": a.tolist(), "shape": list(a.shape),
            "dtype": str(a.dtype)}


def _decode_array(doc):
    return np.asarray(
        doc["data"], dtype=np.dtype(doc["dtype"])
    ).reshape([int(s) for s in doc["shape"]])


def _encode_feeds(feeds):
    return {str(k): _encode_array(v) for k, v in dict(feeds).items()}


def _decode_feeds(doc):
    return {k: _decode_array(v) for k, v in doc.items()}


def _decode_error(doc, rid, model):
    """Rebuild a typed exception from a worker's error response so the
    router's failover logic sees the same classes it would in-process.
    JSON float round-trips are exact for float32/float64, and these
    names are the whole retry contract."""
    from .engine import DeadlineExceededError

    name = doc.get("error")
    msg = "%s (replica %s of model %r)" % (doc.get("message", ""), rid, model)
    if name == "ShedError":
        return ShedError(msg, model=model, replica=rid,
                         retry_after=doc.get("retry_after"))
    if name == "EngineClosedError":
        return EngineClosedError(msg)
    if name == "DeadlineExceededError":
        return DeadlineExceededError(msg)
    return RuntimeError("%s: %s" % (name, msg))


# ---------------------------------------------------------------------------
# engine factories
# ---------------------------------------------------------------------------


def make_engine_factory(buckets=(), name="default", replica_id=None,
                        device=None, warm=True, predictor_opts=None,
                        **engine_opts):
    """A ``factory(dirname) -> ServingEngine`` closure for replica
    (re)builds — construction AND warmup run under
    ``jax.default_device(device)`` when a device is given, so an
    8-device host gets one committed parameter set per replica."""

    def factory(dirname):
        import contextlib

        import jax

        from ..fluid.inference import Predictor

        cm = (jax.default_device(device) if device is not None
              else contextlib.nullcontext())
        with cm:
            predictor = Predictor.from_model(
                str(dirname), **dict(predictor_opts or {}))
            engine = ServingEngine(
                predictor, buckets=buckets, name=str(name),
                replica_id=replica_id, **engine_opts)
            try:
                if warm:
                    engine.warmup()
            except Exception:
                engine.stop(drain=False, timeout=1.0)
                raise
        return engine

    return factory


# ---------------------------------------------------------------------------
# replicas
# ---------------------------------------------------------------------------


class LocalReplica:
    """One in-process engine + its heartbeat beater.

    The beater publishes ``(queue_depth, version, model)`` in the
    beacon's ``extra`` field every half heartbeat interval; an injected
    ``heartbeat`` fault (or :meth:`kill`) silences it, which IS death
    as far as every observer is concerned."""

    kind = "local"

    def __init__(self, rid, factory, store, name="default", config=None,
                 dirname=None, start_beating=True):
        self.rid = int(rid)
        self.name = str(name)
        self.config = config or ElasticConfig()
        self._factory = factory
        self.dirname = str(dirname) if dirname is not None else None
        self.version = 1
        self.engine = factory(self.dirname)
        self.monitor = HeartbeatMonitor(
            store, self.rid, world_size=1, config=self.config)
        self._beats = 0
        self._beat_stop = threading.Event()
        self._beater = None
        if start_beating:
            self.start_beating()

    # -- heartbeat -------------------------------------------------------
    def _beat_once(self):
        self._beats += 1
        rate = self.engine.drain_rate()
        extra = {"queue_depth": self.engine.queue_depth(),
                 "version": self.version, "model": self.name,
                 "kind": "replica"}
        if obs.mode() != obs.OFF:
            # federation: beacons carry this replica's stats() doc so a
            # FleetMetrics aggregator can merge the fleet off the store
            try:
                extra["metrics"] = obs.replica_metrics_doc(
                    self.engine.stats(), queue_depth=extra["queue_depth"])
            except Exception:  # noqa: BLE001 — beacons must not die
                pass
        self.monitor.beat(
            self._beats,
            # per-request service time: the straggler classifier's
            # latency signal (a slow replica drains slowly)
            latency=(1.0 / rate) if rate else None,
            extra=extra)

    def _beat_loop(self):
        interval = max(0.005, self.config.heartbeat_interval / 2.0)
        while not self._beat_stop.wait(interval):
            try:
                self._beat_once()
            except BaseException:  # noqa: BLE001 — injected heartbeat fault
                return  # a replica that cannot beat is dead to the fleet

    def start_beating(self):
        if self._beater is None or not self._beater.is_alive():
            self._beat_stop.clear()
            try:
                self._beat_once()  # appear immediately, not one tick late
            except BaseException:  # noqa: BLE001
                return
            self._beater = threading.Thread(
                target=self._beat_loop, daemon=True,
                name="serving-beat-%s-%d" % (self.name, self.rid))
            self._beater.start()

    # -- engine surface --------------------------------------------------
    def submit(self, feeds, deadline_ms=None, trace_ctx=None):
        R.fault_check("replica")
        if trace_ctx is not None:
            return self.engine.submit(feeds, deadline_ms=deadline_ms,
                                      trace_ctx=trace_ctx)
        return self.engine.submit(feeds, deadline_ms=deadline_ms)

    def queue_depth(self):
        return self.engine.queue_depth()

    def stats(self):
        return self.engine.stats()

    def retry_after_hint(self):
        return self.engine.retry_after_hint()

    # -- lifecycle -------------------------------------------------------
    def reload(self, dirname):
        """Rebuild from `dirname` fully off to the side (the current
        engine keeps serving until the replacement is built + warmed),
        then swap; the old engine drains in the background."""
        new = self._factory(str(dirname))  # raises => no swap, no limbo
        old, self.engine = self.engine, new
        self.dirname = str(dirname)
        self.version += 1
        threading.Thread(
            target=old.stop, kwargs={"drain": True}, daemon=True,
            name="serving-drain-%s-r%d" % (self.name, self.rid)).start()
        return self.version

    def kill(self):
        """Simulated crash: silence the beacons (no 'left' — peers must
        infer death from the miss threshold) and fail everything queued
        so the router replays it on survivors."""
        self._beat_stop.set()
        if self._beater is not None:
            self._beater.join(timeout=1.0)
        self.engine.stop(drain=False, timeout=0.2)

    def stop(self, drain=True, timeout=30.0):
        """Planned removal: queued work finishes (``drain=True``), then
        the beater leaves cleanly so no observer counts this as death."""
        self.engine.stop(drain=drain, timeout=timeout)
        self._beat_stop.set()
        if self._beater is not None:
            self._beater.join(timeout=1.0)
        try:
            self.monitor.leave()
        except BaseException:  # noqa: BLE001 — best-effort goodbye
            pass


class StoreReplica:
    """Router-side proxy for a replica living in ANOTHER process,
    reached through the FileStore: requests land in
    ``serve/<model>/req/<rid>``, responses come back in
    ``serve/<model>/resp/<rid>``, control (reload/stop) goes through
    ``serve/<model>/ctl/<rid>`` and is acked in ``.../ack/<rid>``. A
    background poller resolves futures from the response namespace;
    :meth:`fail_inflight` is the router's hook for a worker that died
    mid-request."""

    kind = "store"

    def __init__(self, rid, store, name="default", config=None,
                 poll_interval=None):
        self.rid = int(rid)
        self.name = str(name)
        self.store = store
        self.config = config or ElasticConfig()
        self._poll = (float(poll_interval) if poll_interval is not None
                      else max(0.005, self.config.heartbeat_interval / 5.0))
        base = "serve/%s" % self.name
        self._req_ns = "%s/req/%d" % (base, self.rid)
        self._resp_ns = "%s/resp/%d" % (base, self.rid)
        self._ctl_ns = "%s/ctl/%d" % (base, self.rid)
        self._ack_ns = "%s/ack/%d" % (base, self.rid)
        self._seq = itertools.count(1)
        self._ctl_seq = itertools.count(1)
        self._lock = threading.Lock()
        self._pending = {}  # key -> Future
        self._closed = False
        self.version = 1
        self._poller = threading.Thread(
            target=self._poll_loop, daemon=True,
            name="serving-proxy-%s-%d" % (self.name, self.rid))
        self._poller.start()

    # -- engine surface --------------------------------------------------
    def submit(self, feeds, deadline_ms=None, trace_ctx=None):
        if self._closed:
            raise EngineClosedError(
                "replica proxy %d of %r is stopped" % (self.rid, self.name))
        key = "r%d-%d" % (os.getpid(), next(self._seq))
        fut = Future()
        with self._lock:
            self._pending[key] = fut
        doc = {"feeds": _encode_feeds(feeds),
               "deadline_ms": deadline_ms, "t": time.time()}
        if trace_ctx is not None and getattr(trace_ctx, "sampled", False):
            # the req mailbox carries the trace context across the
            # process boundary; the worker's span parents to it
            doc["trace"] = trace_ctx.to_doc()
        self.store.put(self._req_ns, key, doc)
        return fut

    def queue_depth(self):
        # outstanding = queued-or-running on the worker, as this side
        # knows it; good enough for least-loaded ordering
        with self._lock:
            return len(self._pending)

    def stats(self):
        with self._lock:
            return {"pending": len(self._pending)}

    def retry_after_hint(self):
        return None  # the worker's hint rides its ShedError responses

    # -- response poller -------------------------------------------------
    def _poll_loop(self):
        while not self._closed:
            try:
                self._drain_responses()
            except Exception:  # noqa: BLE001 — keep polling through blips
                pass
            time.sleep(self._poll)

    def _drain_responses(self):
        resp = self.store.all(self._resp_ns)
        if not resp:
            return
        with self._lock:
            ready = [(k, self._pending.pop(k))
                     for k in list(self._pending) if k in resp]
        for key, fut in ready:
            doc = resp[key]
            try:
                if doc.get("ok"):
                    fut.set_result(
                        [_decode_array(o) for o in doc["outputs"]])
                else:
                    fut.set_exception(
                        _decode_error(doc, self.rid, self.name))
            except InvalidStateError:
                pass
        # GC every response this proxy has fully consumed — including
        # late answers for requests fail_inflight() already replayed —
        # so the scan stays proportional to in-flight work, not to
        # lifetime traffic
        with self._lock:
            pending_now = set(self._pending)
        for key in resp:
            if key not in pending_now:
                self.store.delete(self._resp_ns, key)

    def fail_inflight(self, exc):
        """Fail every outstanding request (worker confirmed dead);
        returns how many — the router replays them on survivors."""
        with self._lock:
            doomed = list(self._pending.values())
            self._pending.clear()
        for fut in doomed:
            try:
                fut.set_exception(exc)
            except InvalidStateError:
                pass
        return len(doomed)

    # -- control ---------------------------------------------------------
    def _command(self, cmd, timeout, **fields):
        seq = next(self._ctl_seq)
        self.store.put(self._ctl_ns, "c%d" % seq,
                       dict(fields, cmd=cmd, seq=seq))
        deadline = time.monotonic() + float(timeout)
        while time.monotonic() < deadline:
            ack = self.store.all(self._ack_ns).get(str(seq))
            if ack is not None:
                return ack
            time.sleep(self._poll)
        return None

    def reload(self, dirname, timeout=120.0):
        """Ask the worker to rebuild from `dirname`; blocks on the ack."""
        ack = self._command("reload", timeout, dirname=str(dirname))
        if ack is None:
            raise RolloutError(
                "replica %d of %r did not ack reload within %.1fs"
                % (self.rid, self.name, timeout))
        if not ack.get("ok"):
            raise RolloutError(
                "replica %d of %r failed reload: %s"
                % (self.rid, self.name, ack.get("error")))
        self.version = int(ack.get("version", self.version + 1))
        return self.version

    def kill(self):  # parity with LocalReplica: drop the proxy side
        self._closed = True
        self.fail_inflight(ReplicaGoneError(
            "replica %d of %r killed" % (self.rid, self.name)))

    def stop(self, drain=True, timeout=30.0):
        ack = self._command("stop", timeout, drain=bool(drain))
        self._closed = True
        n = self.fail_inflight(EngineClosedError(
            "replica %d of %r stopped" % (self.rid, self.name)))
        if ack is None and n:
            obs.event("replica_stop_unacked", source="serving",
                      model=self.name, replica=self.rid, orphaned=n)


class ReplicaWorker:
    """The worker-process half of :class:`StoreReplica`: drains the
    request namespace through a local ServingEngine, writes responses
    back, beats with queue depth + version, and obeys reload/stop
    control commands. ``run_forever()`` is the process main loop."""

    def __init__(self, store, rid, factory, dirname, name="default",
                 config=None, poll_interval=None):
        self.store = store
        self.rid = int(rid)
        self.name = str(name)
        self.config = config or ElasticConfig()
        self._poll = (float(poll_interval) if poll_interval is not None
                      else max(0.005, self.config.heartbeat_interval / 5.0))
        self._factory = factory
        self.dirname = str(dirname)
        self.version = 1
        self.engine = factory(self.dirname)
        base = "serve/%s" % self.name
        self._req_ns = "%s/req/%d" % (base, self.rid)
        self._resp_ns = "%s/resp/%d" % (base, self.rid)
        self._ctl_ns = "%s/ctl/%d" % (base, self.rid)
        self._ack_ns = "%s/ack/%d" % (base, self.rid)
        self._seen = set()
        self._done_ctl = set()
        self._beats = 0
        self.monitor = HeartbeatMonitor(
            store, self.rid, world_size=1, config=self.config)
        # crash dump routing: $PADDLE_TPU_CRASH_DUMP names ONE file —
        # route this worker's dump to a per-pid sibling so two workers
        # crashing together never clobber each other, and advertise the
        # path on beacons (the router surfaces it in ReplicaGoneError)
        self._crash_dump = None
        if os.environ.get(obs.CRASH_DUMP_ENV):
            self._crash_dump = obs.crash_dump_path(per_pid=True)
            os.environ[obs.CRASH_DUMP_ENV] = self._crash_dump
        if obs.process_label() == "pid%d" % os.getpid():
            obs.set_process_label(
                "worker:%s-%d" % (self.name, self.rid))

    def _beat(self):
        self._beats += 1
        rate = self.engine.drain_rate()
        extra = {"queue_depth": self.engine.queue_depth(),
                 "version": self.version, "model": self.name,
                 "kind": "replica", "pid": os.getpid()}
        if self._crash_dump:
            extra["crash_dump"] = self._crash_dump
        if obs.mode() != obs.OFF:
            # federation: a worker process owns its whole telemetry
            # hub, so the beacon ships the full federation doc
            try:
                extra["metrics"] = obs.get_telemetry().federation_doc()
            except Exception:  # noqa: BLE001 — beacons must not die
                pass
        self.monitor.beat(
            self._beats, latency=(1.0 / rate) if rate else None,
            extra=extra)

    def _finish(self, key, fut, trace=None, t_wall=None):
        try:
            outs = fut.result()
            payload = {"ok": True,
                       "outputs": [_encode_array(o) for o in outs]}
        except BaseException as e:  # noqa: BLE001 — every failure goes on the wire
            payload = {"ok": False, "error": type(e).__name__,
                       "message": str(e),
                       "retry_after": getattr(e, "retry_after", None)}
        self.store.put(self._resp_ns, key, payload)
        if trace is not None and t_wall is not None:
            obs.export_span(
                "worker.predict", trace, t_wall, time.time() - t_wall,
                {"replica": self.rid, "ok": payload["ok"],
                 "error": payload.get("error")})

    def _take_requests(self):
        reqs = self.store.all(self._req_ns)
        fresh = sorted(
            (k for k in reqs if k not in self._seen),
            key=lambda k: (reqs[k].get("t", 0.0), k))
        for key in fresh:
            self._seen.add(key)
            doc = reqs[key]
            # consumed: GC the mailbox entry so sustained traffic does
            # not grow every later poll's scan (the proxy side recovers
            # lost work from heartbeats, not from the request file)
            self.store.delete(self._req_ns, key)
            trace = obs.TraceContext.from_doc(doc.get("trace"))
            trace = trace.child() if trace is not None else None
            t_wall = time.time() if trace is not None else None
            try:
                fut = self.engine.submit(
                    _decode_feeds(doc["feeds"]),
                    deadline_ms=doc.get("deadline_ms"))
            except BaseException as e:  # noqa: BLE001 — shed/closed/bad feeds
                self.store.put(self._resp_ns, key, {
                    "ok": False, "error": type(e).__name__,
                    "message": str(e),
                    "retry_after": getattr(e, "retry_after", None)})
                continue
            fut.add_done_callback(
                lambda f, key=key, tr=trace, tw=t_wall:
                self._finish(key, f, trace=tr, t_wall=tw))

    def _take_control(self):
        """Returns False once a stop command was obeyed."""
        ctl = self.store.all(self._ctl_ns)
        for key in sorted(ctl, key=lambda k: ctl[k].get("seq", 0)):
            doc = ctl[key]
            seq = doc.get("seq")
            if seq in self._done_ctl:
                continue
            self._done_ctl.add(seq)
            if doc.get("cmd") == "reload":
                try:
                    new = self._factory(doc["dirname"])
                except Exception as e:  # noqa: BLE001 — build failed: no swap
                    self.store.put(self._ack_ns, str(seq), {
                        "ok": False,
                        "error": "%s: %s" % (type(e).__name__, e)})
                    continue
                old, self.engine = self.engine, new
                self.dirname = str(doc["dirname"])
                self.version += 1
                threading.Thread(
                    target=old.stop, kwargs={"drain": True},
                    daemon=True).start()
                self._beat()  # advertise the new version immediately
                self.store.put(self._ack_ns, str(seq),
                               {"ok": True, "version": self.version})
            elif doc.get("cmd") == "stop":
                self.engine.stop(drain=bool(doc.get("drain", True)))
                self.store.put(self._ack_ns, str(seq), {"ok": True})
                self.monitor.leave()
                return False
        return True

    def run_forever(self):
        last_beat = 0.0
        beat_every = max(0.005, self.config.heartbeat_interval / 2.0)
        while True:
            now = time.monotonic()
            if now - last_beat >= beat_every:
                self._beat()
                last_beat = now
            self._take_requests()
            if not self._take_control():
                return
            time.sleep(self._poll)


# ---------------------------------------------------------------------------
# the router
# ---------------------------------------------------------------------------


class ServingRouter:
    """N replicas behind one ServingEngine-shaped surface (see module
    docstring for the dispatch / health / autoscale / rollout story)."""

    def __init__(self, replicas, store, name=None, config=None, standby=(),
                 dirname=None, max_retries=3, retry_base_s=0.05,
                 request_timeout_s=60.0, min_replicas=1,
                 scale_up_depth=8, scale_down_depth=1, scale_window_s=1.0,
                 health_interval=None, start_health=True):
        if not replicas:
            raise ValueError("a router needs at least one replica")
        self.name = str(name if name is not None else replicas[0].name)
        self.config = config or ElasticConfig()
        self.store = store
        self.dirname = str(dirname) if dirname is not None else None
        self.max_retries = int(max_retries)
        self.retry_base_s = float(retry_base_s)
        self.request_timeout_s = float(request_timeout_s)
        self.min_replicas = int(min_replicas)
        self.scale_up_depth = int(scale_up_depth)
        self.scale_down_depth = int(scale_down_depth)
        self.scale_window_s = float(scale_window_s)
        self._lock = threading.RLock()
        self._live = {r.rid: r for r in replicas}
        self._standby = list(standby)
        self._dead = {}
        self._scaled_up = []      # rids activated by pressure (LIFO)
        self._stragglers = set()
        self._rr = 0              # round-robin cursor for depth ties
        self._pressure = collections.deque()
        self._closed = False
        self._inflight = set()
        self._inflight_lock = threading.Lock()
        self._counters = collections.Counter()
        self._rollout_lock = threading.Lock()
        # observer only: worker_index -1 never beats, never counts as a
        # member — it just reads the replica beacon table
        self.monitor = HeartbeatMonitor(
            store, -1, world_size=max(self._live) + 1, config=self.config)
        self._health_interval = (
            float(health_interval) if health_interval is not None
            else max(0.02, self.config.heartbeat_interval / 2.0))
        self._health_stop = threading.Event()
        self._health = None
        obs.set_gauge("serving.replicas_live", len(self._live))
        obs.set_gauge("serving.rollout_state", 0)
        # pre-register the fleet counters so /metrics shows them at 0
        # from the first scrape instead of only after the first incident
        for name in ("failovers", "router_retry", "replica_dead"):
            obs.inc("serving.%s" % name, 0)
        if start_health:
            self.start_health()

    # -- introspection surface (engine duck type) ------------------------
    @property
    def closed(self):
        return self._closed

    def queue_depth(self):
        with self._lock:
            return sum(r.queue_depth() for r in self._live.values())

    def replicas_live(self):
        with self._lock:
            return sorted(self._live)

    def stats(self):
        """Fleet-aggregate engine counters + router-level counters."""
        with self._lock:
            pool = list(self._live.values()) + list(self._standby) \
                + list(self._dead.values())
            out = collections.Counter()
            for r in pool:
                try:
                    for k, v in r.stats().items():
                        if isinstance(v, (int, float)):
                            out[k] += v
                except Exception:  # noqa: BLE001 — dead proxies can't count
                    continue
            out.update(self._counters)
            out["replicas_live"] = len(self._live)
            out["replicas_standby"] = len(self._standby)
            return dict(out)

    def retry_after_hint(self):
        with self._lock:
            hints = []
            for r in self._live.values():
                try:
                    h = r.retry_after_hint()
                except Exception:  # noqa: BLE001
                    h = None
                if h:
                    hints.append(float(h))
        return min(hints) if hints else 1.0

    # -- dispatch --------------------------------------------------------
    def submit(self, feeds, deadline_ms=None, trace_ctx=None):
        """Engine-compatible: returns ONE future the caller holds while
        the router moves the request between replicas underneath.
        ``trace_ctx`` (a sampled TraceContext) rides the dispatch to
        the chosen replica — across the FileStore wire for worker
        processes."""
        if self._closed:
            raise EngineClosedError(
                "router %r is draining/stopped" % self.name)
        t0 = time.monotonic()
        budget = (float(deadline_ms) / 1000.0 if deadline_ms is not None
                  else self.request_timeout_s)
        if trace_ctx is not None and not getattr(trace_ctx, "sampled",
                                                 False):
            trace_ctx = None
        state = {"feeds": feeds, "deadline_ms": deadline_ms,
                 "future": Future(), "t0": t0, "t_deadline": t0 + budget,
                 "tried": set(), "rounds": 0, "trace": trace_ctx}
        with self._inflight_lock:
            self._inflight.add(state["future"])
        state["future"].add_done_callback(self._forget)
        self._bump("router_requests")
        self._dispatch(state)  # ValueError/KeyError (bad feeds) raise here
        return state["future"]

    def predict(self, feeds, deadline_ms=None, timeout=None):
        fut = self.submit(feeds, deadline_ms=deadline_ms)
        return fut.result(
            timeout if timeout is not None else self.request_timeout_s)

    def _forget(self, fut):
        with self._inflight_lock:
            self._inflight.discard(fut)

    def _candidates(self, tried):
        """Live replicas this request has not tried, least-loaded
        first; depth ties rotate round-robin so an idle fleet spreads
        even a strictly serial stream instead of funnelling every
        request at the lowest rid; flagged stragglers sort behind
        healthy peers."""
        with self._lock:
            reps = [r for r in self._live.values() if r.rid not in tried]
            if reps:
                k = self._rr % len(reps)
                self._rr += 1
                reps = reps[k:] + reps[:k]
            pool = [(r.rid in self._stragglers, r.queue_depth(), r)
                    for r in reps]
        pool.sort(key=lambda t: t[:2])  # stable: ties keep rotation
        return [r for *_, r in pool]

    def _dispatch(self, state):
        try:
            R.fault_check("dispatch")
        except Exception:  # noqa: BLE001 — injected blip: transient, retry
            self._retry_later(state)
            return
        for replica in self._candidates(state["tried"]):
            try:
                if state.get("trace") is not None:
                    try:
                        fut = replica.submit(
                            state["feeds"],
                            deadline_ms=state["deadline_ms"],
                            trace_ctx=state["trace"])
                    except TypeError:
                        # duck-typed replica without the kwarg: the
                        # request matters more than its trace
                        fut = replica.submit(
                            state["feeds"],
                            deadline_ms=state["deadline_ms"])
                else:
                    fut = replica.submit(
                        state["feeds"], deadline_ms=state["deadline_ms"])
            except (ValueError, KeyError):
                raise  # malformed request: permanent, caller's problem
            except Exception:  # noqa: BLE001 — shed/closed/injected: next
                state["tried"].add(replica.rid)
                self._bump("failovers")
                obs.inc("serving.failovers")
                continue
            obs.observe("serving.dispatch_seconds",
                        time.monotonic() - state["t0"])
            fut.add_done_callback(
                lambda f, rid=replica.rid: self._on_replica_done(
                    state, rid, f))
            return
        self._retry_later(state)  # everyone shed (or nobody's live)

    def _retry_later(self, state):
        now = time.monotonic()
        with self._lock:
            n_live = len(self._live)
        out_of_budget = (state["rounds"] >= self.max_retries
                         or now >= state["t_deadline"] or self._closed)
        if out_of_budget:
            if n_live == 0:
                exc = NoReplicasError(
                    "model %r has no live replicas" % self.name)
            else:
                exc = ShedError(
                    "all %d replica(s) of %r shed across %d attempt(s)"
                    % (n_live, self.name, state["rounds"] + 1),
                    model=self.name,
                    retry_after=self.retry_after_hint())
            self._fail(state, exc)
            return
        state["rounds"] += 1
        state["tried"] = set()  # new round: everyone eligible again
        self._bump("router_retry")
        obs.inc("serving.router_retry")
        delay = min(self.retry_base_s * (2 ** (state["rounds"] - 1)),
                    max(0.001, state["t_deadline"] - now), 1.0)
        timer = threading.Timer(delay, self._redispatch, args=(state,))
        timer.daemon = True
        timer.start()

    def _redispatch(self, state):
        if state["future"].done():
            return
        if self._closed:
            self._fail(state, EngineClosedError(
                "router %r stopped mid-retry" % self.name))
            return
        try:
            self._dispatch(state)
        except Exception as e:  # noqa: BLE001 — timer thread: fail the future
            self._fail(state, e)

    def _on_replica_done(self, state, rid, fut):
        pub = state["future"]
        if pub.done():
            return
        exc = fut.exception()
        if exc is None:
            try:
                pub.set_result(fut.result())
            except InvalidStateError:
                pass
            return
        if isinstance(exc, (ShedError, EngineClosedError,
                            ReplicaGoneError)):
            # the replica bailed, the request did not run: replay it
            self._bump("failovers")
            obs.inc("serving.failovers")
            # count=False: serving.failovers (inc'd above) is the one
            # canonical counter — it also covers submit-time sheds,
            # which steer without an event
            obs.event("failover", source="serving", count=False,
                      model=self.name, replica=rid,
                      error=type(exc).__name__)
            state["tried"].add(rid)
            try:
                self._dispatch(state)
            except Exception as e:  # noqa: BLE001
                self._fail(state, e)
        else:
            # model error or expired deadline: retrying can't help
            try:
                pub.set_exception(exc)
            except InvalidStateError:
                pass

    def _fail(self, state, exc):
        try:
            state["future"].set_exception(exc)
        except InvalidStateError:
            pass

    def _bump(self, key, n=1):
        with self._lock:
            self._counters[key] += n

    # -- health / membership ---------------------------------------------
    def start_health(self):
        if self._health is None or not self._health.is_alive():
            self._health_stop.clear()
            self._health = threading.Thread(
                target=self._health_loop, daemon=True,
                name="serving-router-health-%s" % self.name)
            self._health.start()
        return self

    def _health_loop(self):
        while not self._health_stop.wait(self._health_interval):
            try:
                self._health_tick()
            except Exception as e:  # noqa: BLE001 — the loop must survive
                obs.event("router_health_error", source="serving",
                          model=self.name,
                          error="%s: %s" % (type(e).__name__, e))

    def _health_tick(self):
        with self._lock:
            members = set(self._live)
        if members:
            for rid in self.monitor.dead_peers(members=members) & members:
                self._mark_dead(rid)
            with self._lock:
                members = set(self._live)
            # step_lag=False: replica beats count from each process's
            # start, not a shared training step — lag is meaningless
            # here and would pin late-built replicas behind forever
            self._stragglers = (
                self.monitor.stragglers(members=members, step_lag=False)
                if len(members) >= 2 else set())
        obs.set_gauge("serving.queue_depth.%s" % self.name,
                      self.queue_depth())
        self._autoscale_tick()

    def _mark_dead(self, rid):
        with self._lock:
            replica = self._live.pop(rid, None)
            if replica is None:
                return
            self._dead[rid] = replica
            if rid in self._scaled_up:
                self._scaled_up.remove(rid)
            n_live = len(self._live)
        self._bump("replica_dead")
        obs.set_gauge("serving.replicas_live", n_live)
        dumps = []
        try:
            table = self.monitor.table()
            beacon = table.get(rid, table.get(str(rid)))
            if isinstance(beacon, dict) and beacon.get("crash_dump"):
                dumps.append(str(beacon["crash_dump"]))
        except Exception:  # noqa: BLE001 — diagnostics only
            pass
        replayed = 0
        fail = getattr(replica, "fail_inflight", None)
        if fail is not None:
            # orphaned in-flight requests come back through
            # _on_replica_done as ReplicaGoneError -> replayed
            replayed = fail(ReplicaGoneError(
                "replica %d of %r died mid-request (missed %d beacons)%s"
                % (rid, self.name, self.config.miss_threshold,
                   " — crash dump: %s" % ", ".join(dumps)
                   if dumps else ""),
                dump_paths=dumps))
        obs.event("replica_dead", source="serving", model=self.name,
                  replica=rid, replayed=replayed, live=n_live,
                  crash_dump=dumps[0] if dumps else None)
        self._activate_standby(reason="replace_dead")

    def _activate_standby(self, reason, scaled=False):
        with self._lock:
            if not self._standby:
                return None
            replica = self._standby.pop(0)
            self._live[replica.rid] = replica
            if scaled:
                self._scaled_up.append(replica.rid)
            n_live = len(self._live)
        obs.set_gauge("serving.replicas_live", n_live)
        obs.event("replica_activate", source="serving", model=self.name,
                  replica=replica.rid, reason=reason, live=n_live)
        return replica

    def scale_up(self, reason="manual"):
        """Activate one warm standby into the dispatch set NOW —
        the operator/autopilot override of the sustained-pressure
        autoscaler. The replica counts as scaled-up, so the autoscaler
        parks it back once pressure subsides. Returns the activated
        replica, or None when no standby is available."""
        replica = self._activate_standby(reason=str(reason), scaled=True)
        if replica is not None:
            self._pressure.clear()
        return replica

    def _autoscale_tick(self):
        now = time.monotonic()
        with self._lock:
            live = list(self._live.values())
            depth = (sum(r.queue_depth() for r in live) / len(live)
                     if live else 0.0)
        self._pressure.append((now, depth))
        while self._pressure and \
                now - self._pressure[0][0] > self.scale_window_s:
            self._pressure.popleft()
        if len(self._pressure) < 3 or \
                now - self._pressure[0][0] < 0.75 * self.scale_window_s:
            return  # not enough window yet: pressure must be SUSTAINED
        samples = [d for _, d in self._pressure]
        if min(samples) >= self.scale_up_depth:
            if self._activate_standby(reason="pressure",
                                      scaled=True) is not None:
                self._pressure.clear()
        elif max(samples) <= self.scale_down_depth:
            self._scale_down()

    def _scale_down(self):
        with self._lock:
            if not self._scaled_up or len(self._live) <= self.min_replicas:
                return
            rid = self._scaled_up.pop()
            replica = self._live.pop(rid, None)
            n_live = len(self._live)
        if replica is None:
            return
        obs.set_gauge("serving.replicas_live", n_live)
        # warm parkback: wait out its queue (it is out of the dispatch
        # set, so the depth only falls), keep the engine running
        deadline = time.monotonic() + 2.0
        while replica.queue_depth() > 0 and time.monotonic() < deadline:
            time.sleep(0.005)
        with self._lock:
            self._standby.append(replica)
        obs.event("replica_parked", source="serving", model=self.name,
                  replica=rid, live=n_live)
        self._pressure.clear()

    def remove_replica(self, rid, drain=True):
        """Planned removal: out of the dispatch set FIRST (no new
        work), then a draining stop — queued requests finish instead of
        being replayed. Returns the removed replica."""
        with self._lock:
            replica = self._live.pop(int(rid), None)
            if replica is None:
                raise KeyError(
                    "no live replica %s on router %r" % (rid, self.name))
            if int(rid) in self._scaled_up:
                self._scaled_up.remove(int(rid))
            n_live = len(self._live)
        obs.set_gauge("serving.replicas_live", n_live)
        replica.stop(drain=drain)
        obs.event("replica_remove", source="serving", model=self.name,
                  replica=int(rid), drained=bool(drain), live=n_live)
        return replica

    # -- rolling reload ---------------------------------------------------
    def rolling_reload(self, dirname, probe_feeds=None, watch_s=0.0,
                       reload_timeout=120.0):
        """Upgrade the fleet to `dirname` one replica at a time:
        quiesce -> drain -> rebuild -> probe -> rejoin. The other
        replicas keep serving the old version throughout (zero
        downtime). Any failure rolls every upgraded replica back to the
        pre-rollout version and raises :class:`RolloutError`."""
        with self._rollout_lock:
            if self._closed:
                raise EngineClosedError(
                    "router %r is draining/stopped" % self.name)
            with self._lock:
                order = sorted(self._live)
            if not order:
                raise NoReplicasError(
                    "model %r has no live replicas to reload" % self.name)
            old_dirname = self.dirname
            obs.set_gauge("serving.rollout_state", 1)
            obs.event("rollout_start", source="serving", model=self.name,
                      dirname=str(dirname), replicas=order)
            done = []
            for rid in order:
                with self._lock:
                    replica = self._live.pop(rid, None)  # quiesce
                if replica is None:
                    continue  # died mid-rollout; survivors carry on
                try:
                    self._wait_idle(replica, timeout=reload_timeout)
                    version = replica.reload(dirname)
                    if probe_feeds is not None:
                        # the health gate: the NEW version must answer
                        # before this replica rejoins the dispatch set
                        replica.submit(probe_feeds).result(
                            timeout=reload_timeout)
                except Exception as e:  # noqa: BLE001 — any failure => rollback
                    with self._lock:
                        self._live[rid] = replica
                    self._abort_rollout(done + [rid], old_dirname, e)
                with self._lock:
                    self._live[rid] = replica  # unquiesce
                done.append(rid)
                obs.event("rollout_step", source="serving",
                          model=self.name, replica=rid, version=version)
                if watch_s > 0 and self._regressed(replica, watch_s):
                    self._abort_rollout(
                        done, old_dirname,
                        RuntimeError(
                            "error-rate regression on replica %d after "
                            "reload" % rid))
            self.dirname = str(dirname)
            obs.set_gauge("serving.rollout_state", 0)
            obs.event("rollout_done", source="serving", model=self.name,
                      dirname=str(dirname), replicas=done)
            return done

    def _wait_idle(self, replica, timeout):
        deadline = time.monotonic() + float(timeout)
        while replica.queue_depth() > 0 and time.monotonic() < deadline:
            time.sleep(0.005)

    def _regressed(self, replica, watch_s):
        """Live-traffic canary: any fresh batch errors inside the watch
        window on the just-upgraded replica reads as a bad version."""
        try:
            before = int(replica.stats().get("batch_errors", 0))
        except Exception:  # noqa: BLE001
            return False
        time.sleep(float(watch_s))
        try:
            after = int(replica.stats().get("batch_errors", 0))
        except Exception:  # noqa: BLE001
            return False
        return after > before

    def _abort_rollout(self, touched, old_dirname, cause):
        """Roll every touched replica back to the pre-rollout version,
        then raise. A replica whose rollback ALSO fails is reported in
        the error rather than silently left on the bad version."""
        stuck = []
        if old_dirname is not None:
            for rid in touched:
                with self._lock:
                    replica = self._live.get(rid)
                if replica is None:
                    continue
                try:
                    replica.reload(old_dirname)
                except Exception:  # noqa: BLE001
                    stuck.append(rid)
        obs.set_gauge("serving.rollout_state", 2)
        obs.event("rollout_rollback", source="serving", model=self.name,
                  touched=list(touched), stuck=stuck,
                  error="%s: %s" % (type(cause).__name__, cause))
        msg = ("rolling reload of %r failed (%s: %s); rolled %d "
               "replica(s) back to %r"
               % (self.name, type(cause).__name__, cause, len(touched),
                  old_dirname))
        if stuck:
            msg += " — ROLLBACK INCOMPLETE on replica(s) %s" % stuck
        raise RolloutError(msg) from cause

    # -- lifecycle -------------------------------------------------------
    def stop(self, drain=True, timeout=30.0):
        """Stop the fleet: no new admissions, health loop down, every
        replica stopped (draining by default), stragglers in the retry
        pipeline failed loudly."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            pool = list(self._live.values()) + list(self._standby)
            self._live.clear()
            self._standby = []
        self._health_stop.set()
        if self._health is not None:
            self._health.join(timeout=2.0)
        for replica in pool:
            try:
                replica.stop(drain=drain, timeout=timeout)
            except Exception:  # noqa: BLE001 — stop the rest regardless
                pass
        with self._inflight_lock:
            doomed = list(self._inflight)
            self._inflight.clear()
        for fut in doomed:
            try:
                fut.set_exception(EngineClosedError(
                    "router %r stopped" % self.name))
            except InvalidStateError:
                pass
        obs.set_gauge("serving.replicas_live", 0)
        obs.event("router_stop", source="serving", count=False,
                  model=self.name, drained=bool(drain))


# ---------------------------------------------------------------------------
# fleet builders + worker CLI
# ---------------------------------------------------------------------------


def local_fleet(dirname, n_replicas=2, buckets=(), name="default",
                store=None, n_standby=0, per_device=False, config=None,
                warm=True, predictor_opts=None, router_opts=None,
                **engine_opts):
    """Build an in-process fleet: `n_replicas` live LocalReplicas (+
    `n_standby` warm standbys) behind a :class:`ServingRouter`. With
    ``per_device=True`` replica i is pinned to ``jax.devices()[i %
    ndev]`` — one committed parameter set per device on an 8-device
    host."""
    store = store if store is not None else InMemoryStore()
    config = config or ElasticConfig()
    devices = None
    if per_device:
        import jax

        devices = jax.devices()
    replicas = []
    for rid in range(int(n_replicas) + int(n_standby)):
        device = devices[rid % len(devices)] if devices else None
        factory = make_engine_factory(
            buckets=buckets, name=name, replica_id=rid, device=device,
            warm=warm, predictor_opts=predictor_opts, **engine_opts)
        replicas.append(LocalReplica(
            rid, factory, store, name=name, config=config,
            dirname=str(dirname)))
    return ServingRouter(
        replicas[:int(n_replicas)], store=store, name=name, config=config,
        standby=replicas[int(n_replicas):], dirname=str(dirname),
        **dict(router_opts or {}))


def _parse_buckets(text):
    from .batcher import BucketSpec

    specs = []
    for doc in json.loads(text or "[]"):
        specs.append(BucketSpec(
            {k: tuple(v) for k, v in doc["feeds"].items()},
            batch_sizes=tuple(doc.get("batch_sizes", (1, 2, 4, 8))),
            dtypes=doc.get("dtypes")))
    return specs


def worker_main(argv=None):
    """Process entry point for one FileStore-transport replica::

        python -m paddle_tpu.serving.router --store /shared/fleet \\
            --rid 0 --name mnist --model-dir /models/mnist \\
            --buckets '[{"feeds": {"img": [784]}, "batch_sizes": [1,4,8]}]'
    """
    import argparse

    p = argparse.ArgumentParser(
        prog="paddle_tpu.serving.router",
        description="one serving-fleet replica worker over a FileStore")
    p.add_argument("--store", required=True,
                   help="FileStore root shared with the router")
    p.add_argument("--rid", type=int, required=True)
    p.add_argument("--name", default="default")
    p.add_argument("--model-dir", required=True)
    p.add_argument("--buckets", default="",
                   help='JSON: [{"feeds": {name: [dims...]}, '
                        '"batch_sizes": [...], "dtypes": {...}?}, ...]')
    p.add_argument("--max-batch-size", type=int, default=8)
    p.add_argument("--max-wait-ms", type=float, default=2.0)
    p.add_argument("--queue-capacity", type=int, default=64)
    p.add_argument("--no-warm", action="store_true")
    p.add_argument("--heartbeat-interval", type=float, default=None)
    p.add_argument("--trace-proc", default=None,
                   help="trace track label for this process (default "
                        "worker:<name>-<rid>)")
    args = p.parse_args(argv)

    obs.set_process_label(
        args.trace_proc or "worker:%s-%d" % (args.name, args.rid))
    obs.install_excepthook()
    config = ElasticConfig(heartbeat_interval=args.heartbeat_interval)
    factory = make_engine_factory(
        buckets=_parse_buckets(args.buckets), name=args.name,
        replica_id=args.rid, warm=not args.no_warm,
        max_batch_size=args.max_batch_size, max_wait_ms=args.max_wait_ms,
        queue_capacity=args.queue_capacity)
    worker = ReplicaWorker(
        FileStore(args.store), args.rid, factory, args.model_dir,
        name=args.name, config=config)
    print("replica %d serving %r from %s (pid %d)"
          % (args.rid, args.name, args.model_dir, os.getpid()), flush=True)
    worker.run_forever()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(worker_main())
