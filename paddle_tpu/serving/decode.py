"""DecodeEngine: slotted KV-cache decode with continuous batching.

The micro-batching :class:`~paddle_tpu.serving.engine.ServingEngine`
coalesces fixed-shape ``predict`` calls; the millions-of-users workload
is autoregressive *decode*, where a full-batch ``lax.scan`` generator
(:func:`~paddle_tpu.models.gpt.build_gpt_generate`) makes every request
wait for the slowest sequence in its batch and admits nothing
mid-generation. This engine removes the full-batch barrier:

- **Slotted KV cache** — ONE pre-allocated device buffer pair
  ``(slots, layers, cache_len, heads*dh)`` holds every live sequence's
  keys/values. A slot is a sequence's home for its whole generation;
  retiring frees the slot the same step.
- **Two programs, both AOT** — a *prefill* program per declared prompt
  bucket (parallel pass over the right-padded prompt writes a slot's
  cache and emits the first token) and ONE *step* program (one token
  for ALL slots per iteration, per-slot positions). Both resolve
  through the PR-4 compile-cache disk tier at :meth:`warmup`, so a
  restarted server never compiles and steady-state decode never sees
  XLA.
- **Continuous batching** — a single dispatch thread interleaves the
  two: finished sequences (EOS or max-new) retire in-flight and queued
  requests are prefilled into freed slots between steps; the other
  slots never stall on a barrier. Per-request tokens are bit-identical
  to a solo ``build_gpt_generate`` run (row independence + per-slot
  masks), which the tests assert token-for-token.
- **Streaming** — ``submit()`` returns a :class:`DecodeStream` whose
  ``tokens()`` generator yields each token as the step loop produces
  it; ``serving.http`` exposes it as a chunked-transfer ``:generate``
  endpoint. Cancelling a stream (client disconnect) frees its slot at
  the next loop iteration.

Admission control mirrors the serving engine: full queue fast-rejects
with :class:`~paddle_tpu.serving.engine.ShedError` (HTTP 429 +
Retry-After from the observed retire rate), a queued request whose
deadline expires is shed BEFORE its prefill with
:class:`~paddle_tpu.serving.engine.DeadlineExceededError` (504), and
:meth:`check_hbm_budget` prices the KV buffer pair + params + step
peak with the static liveness analyzer before any warmup compile.

Telemetry: ``serving.decode.slot_utilization`` /
``serving.decode.cache_occupancy`` gauges,
``serving.decode.prefill_seconds`` / ``step_seconds`` /
``ttft_seconds`` / ``request_seconds`` histograms, and
``serving.decode.tokens`` / ``requests`` / ``retired`` / ``shed`` /
``deadline_miss`` / ``cancelled`` counters.

``barrier=True`` is the ablation mode benches compare against: slots
are only refilled once EVERY slot has retired — the classic full-batch
generation schedule, identical programs, no in-flight admission.

Disaggregation hooks (PR 12, ``serving.disagg``): ``kv_dtype="int8"``
keeps the slot cache **resident in int8** with per-(slot, layer, row)
fp32 scales — ~4x the decode slots at equal HBM, priced honestly by
:meth:`check_hbm_budget` — swapping in the dequantize-in-program step
(:func:`~paddle_tpu.models.gpt.build_gpt_decode_step_q`);
``role="decode"`` builds NO prefill programs (a pure step replica) and
:meth:`submit_prefilled` adopts a serialized
:class:`~paddle_tpu.serving.disagg.kv_wire.KVHandoff` from a prefill
replica straight into a slot.

KV-reuse + speculation hooks (``serving.prefix_pool`` /
``serving.spec``):

- ``prefix_pool=PrefixPool(...)`` — before a cold prefill the engine
  hashes the prompt against the pool; a full hit adopts the cached
  rows and emits the cached first token with NO program run, a prefix
  hit adopts ``plen`` rows and **delta-prefills** only the suffix
  (:func:`~paddle_tpu.models.gpt.build_gpt_prefill_delta`), and every
  cold/delta prefill inserts its rows back. Redundant-prefill
  economics land in the ``prefill_rows_computed`` /
  ``prefill_rows_saved`` counters.
- ``draft=DraftModel(...)`` — speculative decoding (fp32-resident
  engines): each iteration the draft proposes ``k`` tokens and ONE
  verify dispatch (:func:`~paddle_tpu.models.gpt.
  build_gpt_verify_block`) scores the block; the longest prefix
  matching the target's own greedy picks is emitted (plus the
  correction/bonus token), so every stream stays bit-exact with
  non-speculative decode while one dispatch yields up to ``k + 1``
  tokens. Near the cache edge the engine falls back to the plain step
  (mirrored into the draft via ``sync_step``). Acceptance is exported
  as ``serving.spec.accept_rate``.
- ``session_tier=SessionTier(...)`` — ``submit(session=...)``
  hibernates the slot's KV rows to host RAM (the KVHandoff wire
  format) when the stream retires, and a later submit with the same
  session id re-adopts them and delta-prefills only the new turn, so
  concurrent sessions stop being bounded by live slots.
"""
import collections
import queue
import threading
import time

import numpy as np

from .. import observability as obs
from ..analysis import concurrency as _conc
from ..analysis import dataflow as _dataflow
from ..fluid import resilience as R
from .engine import DeadlineExceededError, EngineClosedError, ShedError

__all__ = ["DecodeEngine", "DecodeStream", "default_prompt_buckets",
           "kv_slot_bytes"]


def kv_slot_bytes(cfg, cache_len, kv_dtype="fp32"):
    """HBM bytes ONE decode slot's KV cache pair occupies — the slot
    economics `disagg` trades on: int8 residency pays 1 byte/element
    plus one fp32 scale per (layer, row) instead of 4 bytes/element,
    so slots-per-budget multiplies by ~4 (3.9x at hidden 32+)."""
    if kv_dtype not in ("fp32", "int8"):
        raise ValueError("kv_dtype must be 'fp32' or 'int8', got %r"
                         % (kv_dtype,))
    n = int(cfg.num_layers) * int(cache_len) * int(cfg.hidden)
    if kv_dtype == "int8":
        rows = int(cfg.num_layers) * int(cache_len)
        return 2 * (n + rows * 4)
    return 2 * n * 4


def default_prompt_buckets(cache_len, smallest=8):
    """Pow2 prompt-length ladder up to ``cache_len`` (always at least
    one bucket)."""
    buckets = []
    b = min(int(smallest), int(cache_len))
    while b < cache_len:
        buckets.append(b)
        b *= 2
    buckets.append(int(cache_len))
    return tuple(sorted(set(buckets)))


class DecodeStream:
    """Streaming handle for one generation request.

    The dispatch thread feeds it; the caller either iterates
    :meth:`tokens` (per-token streaming — what the HTTP chunked
    endpoint does) or blocks on :meth:`result` for the full list.
    ``finish_reason`` is ``"eos"`` / ``"length"`` / ``"cancelled"`` /
    ``"error"`` once done. :meth:`cancel` (idempotent, thread-safe)
    frees the request's slot at the dispatch loop's next iteration —
    or drops it from the queue if it never reached a slot."""

    # distributed-trace context of a sampled request (None otherwise);
    # class attr so pre-trace pickles/subclasses still read it
    trace = None

    def __init__(self, prompt_len, max_new, stall_timeout_s=60.0):
        self.prompt_len = int(prompt_len)
        self.max_new = int(max_new)
        self.stall_timeout_s = float(stall_timeout_s)
        self.finish_reason = None
        self.t_submit = time.monotonic()
        self._q = queue.Queue()
        self._tokens = []
        self._done = threading.Event()
        self._cancelled = threading.Event()
        self._error = None

    # -- caller surface --------------------------------------------------
    @property
    def cancelled(self):
        return self._cancelled.is_set()

    @property
    def done(self):
        return self._done.is_set()

    def cancel(self):
        """Stop generating for this request (client went away)."""
        self._cancelled.set()

    def tokens(self, timeout=None):
        """Generator yielding token ids as the engine produces them.
        ``timeout`` bounds the wait for EACH token (default: the
        engine's request timeout); a stalled engine raises
        ``TimeoutError``, a failed request raises its error."""
        wait = self.stall_timeout_s if timeout is None else float(timeout)
        while True:
            try:
                kind, val = self._q.get(timeout=wait)
            except queue.Empty:
                raise TimeoutError(
                    "no token for %.1fs (generated %d so far)"
                    % (wait, len(self._tokens)))
            if kind == "tok":
                yield val
            elif kind == "err":
                raise val
            else:  # done
                return

    def result(self, timeout=None):
        """Block until generation finishes; returns the full token
        list (raises the request's error if it failed)."""
        wait = self.stall_timeout_s if timeout is None else timeout
        if not self._done.wait(wait):
            raise TimeoutError(
                "generation not done after %.1fs" % float(wait))
        if self._error is not None:
            raise self._error
        return list(self._tokens)

    def so_far(self):
        """Tokens generated so far (snapshot, no wait)."""
        return list(self._tokens)

    # -- engine surface --------------------------------------------------
    def _emit(self, tok):
        self._tokens.append(tok)
        self._q.put(("tok", tok))

    def _finish(self, reason):
        self.finish_reason = reason
        self._done.set()
        self._q.put(("done", reason))

    def _fail(self, exc):
        self._error = exc
        self.finish_reason = "error"
        self._done.set()
        self._q.put(("err", exc))


class _Request:
    __slots__ = ("prompt", "plen", "bucket", "max_new", "eos_id",
                 "deadline", "handle", "handoff", "tenant", "priority",
                 "trace", "t_wall",
                 # KV-reuse routing: "session" id (tiering), "base"
                 # (adopted rows: a KVHandoff on resume, a pool entry
                 # on a prefix hit), "start" adopted row count,
                 # "suffix"/"sbucket" the delta-prefill tail, "hist"
                 # the token-per-written-row history
                 "session", "base", "start", "suffix", "sbucket",
                 "hist")


class _Slot:
    __slots__ = ("handle", "remaining", "eos_id", "t_prefill",
                 "trace", "t_wall", "t_last", "session", "hist")

    def __init__(self, handle, remaining, eos_id, trace=None,
                 session=None, hist=None):
        self.handle = handle
        self.remaining = remaining
        self.eos_id = eos_id
        self.t_prefill = time.monotonic()
        # sampled TraceContext of the span that filled this slot; the
        # per-token spans and the retire summary parent to it
        self.trace = trace
        self.t_wall = time.time() if trace is not None else None
        self.t_last = self.t_prefill
        # tiering: session id to hibernate under at retire, plus the
        # token history whose rows the slot held at admission (the
        # emitted tokens extend it — see _hibernate)
        self.session = session
        self.hist = hist


class DecodeEngine:
    """Continuous-batching decode engine over a prefill/step program
    pair (GPT-family by default; any builder pair with the same feed/
    fetch contract plugs in via ``build_prefill``/``build_step``).

    ::

        eng = DecodeEngine(cfg, scope=trained_scope, slots=8,
                           cache_len=128, eos_id=2, name="gpt")
        eng.warmup()
        for tok in eng.submit(prompt_ids, max_new=64).tokens():
            ...

    ``scope`` is any name->array mapping holding the trained params
    (a ``fluid.Scope``, ``global_scope()`` after training, or a plain
    dict); :meth:`from_dir` loads a ``save_persistables`` /
    ``save_inference_model`` directory. Params are device_put ONCE and
    shared by every program (prefill buckets + step), not duplicated
    per predictor."""

    engine_kind = "decode"

    def __init__(self, cfg, scope, slots=4, cache_len=64,
                 prompt_buckets=None, eos_id=None, queue_capacity=64,
                 default_max_new=32, default_deadline_ms=None,
                 request_timeout_s=60.0, name="default",
                 barrier=False, auto_start=True,
                 build_prefill=None, build_step=None,
                 kv_dtype="fp32", role="colocated",
                 draft=None, prefix_pool=None, session_tier=None):
        import jax

        import paddle_tpu.fluid as fluid
        from ..fluid.inference import Predictor

        if kv_dtype not in ("fp32", "int8"):
            raise ValueError("kv_dtype must be 'fp32' or 'int8', got %r"
                             % (kv_dtype,))
        if role not in ("colocated", "decode"):
            raise ValueError("role must be 'colocated' or 'decode', "
                             "got %r" % (role,))
        if draft is not None and kv_dtype != "fp32":
            raise ValueError(
                "speculative decoding needs an fp32-resident cache "
                "(the verify program scores the raw fp32 rows); drop "
                "the draft or use kv_dtype='fp32'")
        if role == "decode" and (prefix_pool is not None
                                 or session_tier is not None):
            raise ValueError(
                "prefix_pool/session_tier need the delta-prefill "
                "program a pure decode-role replica does not build — "
                "attach them to the router's prefill side instead")
        if build_prefill is None or build_step is None:
            from ..models.gpt import (build_gpt_decode_step,
                                      build_gpt_decode_step_q,
                                      build_gpt_prefill)

            build_prefill = build_prefill or build_gpt_prefill
            build_step = build_step or (
                build_gpt_decode_step_q if kv_dtype == "int8"
                else build_gpt_decode_step)
        self._jax = jax
        self.cfg = cfg
        self.name = str(name)
        self.slots = int(slots)
        self.cache_len = int(cache_len)
        self.kv_dtype = str(kv_dtype)
        self.role = str(role)
        self.eos_id = eos_id
        self.default_max_new = int(default_max_new)
        self._default_deadline_ms = default_deadline_ms
        self.request_timeout_s = float(request_timeout_s)
        self.barrier = bool(barrier)
        if prompt_buckets is None:
            prompt_buckets = default_prompt_buckets(self.cache_len)
        self.prompt_buckets = tuple(sorted({int(b) for b in prompt_buckets}))
        if not self.prompt_buckets or self.prompt_buckets[0] < 1:
            raise ValueError("prompt_buckets must be positive ints")
        if self.prompt_buckets[-1] > self.cache_len:
            raise ValueError(
                "largest prompt bucket (%d) exceeds cache_len (%d)"
                % (self.prompt_buckets[-1], self.cache_len))

        self._prefix_pool = prefix_pool
        self._session_tier = session_tier
        self._draft = draft

        # -- build the program pair (never touching the caller's
        # default_main_program) and share ONE device param set ---------
        with fluid.program_guard(fluid.Program(), fluid.Program()):
            step_vars = build_step(cfg, self.cache_len)
            step_prog = fluid.default_main_program()
        prefill = {}
        if self.role != "decode":  # a pure decode replica never prefills
            for b in self.prompt_buckets:
                with fluid.program_guard(fluid.Program(), fluid.Program()):
                    pv = build_prefill(cfg, b, self.cache_len)
                    prefill[b] = (fluid.default_main_program(), pv)
        # delta-prefill ladder (prefix-pool hits + session resumes):
        # same bucket widths as cold prefill, suffix-sized at use
        delta = {}
        if prefix_pool is not None or session_tier is not None:
            from ..models.gpt import build_gpt_prefill_delta

            for b in self.prompt_buckets:
                with fluid.program_guard(fluid.Program(), fluid.Program()):
                    dv = build_gpt_prefill_delta(cfg, b, self.cache_len)
                    delta[b] = (fluid.default_main_program(), dv)
        # block-verify program (speculative decoding): k proposals +
        # the slot's current token = a k+1 wide block per dispatch
        verify = None
        if draft is not None:
            from ..models.gpt import build_gpt_verify_block

            with fluid.program_guard(fluid.Program(), fluid.Program()):
                vv = build_gpt_verify_block(cfg, draft.k + 1,
                                            self.cache_len)
                verify = (fluid.default_main_program(), vv)
        persist = {}
        all_progs = ([step_prog] + [p for p, _ in prefill.values()]
                     + [p for p, _ in delta.values()]
                     + ([verify[0]] if verify is not None else []))
        for prog in all_progs:
            for v in prog.list_vars():
                if not getattr(v, "persistable", False):
                    continue
                if v.name in persist:
                    continue
                if v.name not in scope:
                    raise KeyError(
                        "param %r required by the decode programs is "
                        "missing from the given scope — train the model "
                        "or load its persistables first" % v.name)
                # snapshot through the host: device_put on a committed
                # jax array is a no-op, and sharing the training
                # executor's buffers would let its donating step
                # invalidate them under this engine mid-serve
                persist[v.name] = jax.device_put(np.asarray(scope[v.name]))
        if _conc._on:
            # the copy above breaks aliasing with the training executor's
            # donated buffers — register it so the donation registry can
            # prove (not assume) no cross-program alias survives
            _dataflow.note_capture(scope, persist,
                                   "decode-engine %r" % self.name,
                                   snapshot=True)
        self._params = persist
        self._step_vars = step_vars
        self._step_pred = Predictor(
            step_prog, step_vars["feed_names"], step_vars["fetch_vars"],
            scope=persist)
        self._step_pred.ledger_tag = "decode.step:%s" % self.name
        self._prefill_preds = {}
        self._prefill_vars = {}
        for b, (prog, pv) in prefill.items():
            self._prefill_preds[b] = Predictor(
                prog, pv["feed_names"], pv["fetch_vars"], scope=persist)
            self._prefill_preds[b].ledger_tag = (
                "decode.prefill:%s" % self.name)
            self._prefill_vars[b] = pv
        self._delta_preds = {}
        for b, (prog, dv) in delta.items():
            self._delta_preds[b] = Predictor(
                prog, dv["feed_names"], dv["fetch_vars"], scope=persist)
            self._delta_preds[b].ledger_tag = (
                "decode.delta_prefill:%s" % self.name)
        self._verify_pred = None
        if verify is not None:
            prog, vv = verify
            self._verify_pred = Predictor(
                prog, vv["feed_names"], vv["fetch_vars"], scope=persist)
            self._verify_pred.ledger_tag = "decode.verify:%s" % self.name

        # -- the persistent slot buffer pair + host-side slot state ----
        shape = (self.slots, cfg.num_layers, self.cache_len, cfg.hidden)
        self._cache_np_dtype = (np.int8 if self.kv_dtype == "int8"
                                else np.float32)
        self._k = jax.device_put(np.zeros(shape, self._cache_np_dtype))
        self._v = jax.device_put(np.zeros(shape, self._cache_np_dtype))
        self._kscale = self._vscale = None
        if self.kv_dtype == "int8":
            sshape = shape[:-1] + (1,)
            self._kscale = jax.device_put(np.zeros(sshape, np.float32))
            self._vscale = jax.device_put(np.zeros(sshape, np.float32))
        self._tok = np.zeros((self.slots, 1), np.int64)
        self._pos = np.zeros((self.slots, 1), np.int64)
        self._slots = [None] * self.slots
        # slot writes trace once (slot index is a traced scalar); the
        # old buffer is donated so the pair never triples up in HBM
        self._write = jax.jit(
            lambda buf, val, slot: jax.lax.dynamic_update_slice(
                buf, val, (slot, 0, 0, 0)),
            donate_argnums=(0,))

        self._q = queue.Queue(maxsize=int(queue_capacity))
        self._stop_event = threading.Event()
        self._abort = False
        self._closed = False
        self._admit_lock = _conc.named_lock("serving.decode.admit")
        self._stats_lock = _conc.named_lock("serving.decode.stats")
        self._stats = collections.Counter()
        self._rate = collections.deque(maxlen=64)  # (t_done, 1) retires
        self._thread = None
        self._owner = _conc.owner_token("decode-engine", self.name, self)
        # cost-model predictions keyed ("step",) / ("prefill", bucket),
        # computed lazily on the first TRACED request (annotation only;
        # unsampled requests never run the analyzer)
        self._cost_cache = {}
        # measured-step feed into the executable ledger ("" = program
        # has no fingerprint, stop trying)
        self._step_fp = None
        self._step_ema = None
        self._step_noted = False
        # SDC sentinel (paddle_tpu/integrity/sentinel.py): attached by
        # the disagg router (or a test); None = zero per-step overhead
        self._sentinel = None
        self._sentinel_id = self.name
        if draft is not None:
            draft.bind(self)
        if auto_start:
            self.start()

    def attach_sentinel(self, sentinel, replica=None):
        """Arm sampled step-replay SDC checking on this engine; a
        replay disagreement fails the step BEFORE any token is emitted
        (streams migrate and regenerate — a lying step never serves).
        ``replica`` names this engine in the sentinel's vote protocol
        (defaults to the engine name)."""
        self._sentinel = sentinel
        self._sentinel_id = str(replica) if replica is not None \
            else self.name
        if sentinel is not None:
            sentinel.register(self._sentinel_id, self.sentinel_replay)
        return self

    def sentinel_replay(self, feeds):
        """Re-dispatch the step program on arbitrary feeds (the
        cross-replica vote path — peers re-run a suspect's feeds).
        Stateless: the jitted step is functional, so this never
        touches this engine's resident cache."""
        return self._step_pred.run(feeds, return_numpy=False)

    # -- construction helpers -------------------------------------------
    @classmethod
    def from_dir(cls, cfg, dirname, filename=None, **kw):
        """Build from a ``save_persistables`` / ``save_params`` /
        ``save_inference_model`` directory (the ``.npz`` payload those
        writers produce)."""
        import os

        candidates = ([filename] if filename else
                      ["__persistables__.npz", "__params__.npz",
                       "__vars__.npz"])
        for fn in candidates:
            path = os.path.join(str(dirname), fn)
            if os.path.exists(path):
                data = np.load(path, allow_pickle=False)
                return cls(cfg, {n: data[n] for n in data.files}, **kw)
        raise FileNotFoundError(
            "no params payload (%s) under %r" % (", ".join(candidates),
                                                 dirname))

    # -- lifecycle -------------------------------------------------------
    def start(self):
        if self._closed:
            raise EngineClosedError("engine %r is closed" % self.name)
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._loop, daemon=True,
                name="decode-dispatch-%s" % self.name)
            _conc.track_thread(self._thread, self._owner)
            self._thread.start()
        return self

    def stop(self, drain=True, timeout=30.0):
        """Stop admitting work. ``drain=True`` finishes every live slot
        and queued request first; ``drain=False`` fails them with
        :class:`EngineClosedError`. Idempotent."""
        with self._admit_lock:
            self._closed = True
        if not drain:
            self._abort = True
        self._stop_event.set()
        if self._thread is not None and self._thread.is_alive():
            self._thread.join(timeout=max(0.1, float(timeout)))
        while True:  # no thread (or it died): fail leftovers loudly
            try:
                req = self._q.get_nowait()
            except queue.Empty:
                break
            req.handle._fail(EngineClosedError(
                "engine %r stopped before prefill" % self.name))
        for i, s in enumerate(self._slots):
            if s is not None:
                self._slots[i] = None
                s.handle._fail(EngineClosedError(
                    "engine %r stopped mid-generation" % self.name))
        # a dispatch thread alive past stop() is a leak (violation when
        # the lock sanitizer is armed). The grace window must outlast an
        # in-flight jit trace+compile — chaos kill() joins for only
        # 0.2s, and a slot-composition signature miss can hold the loop
        # in compile for seconds; the poll returns the instant the
        # thread exits, so clean shutdowns never wait.
        _conc.check_stopped(self._owner, grace=10.0)
        obs.event("engine_stop", source="serving", count=False,
                  model=self.name, engine="decode", drained=bool(drain))

    # -- admission -------------------------------------------------------
    def _bucket_for(self, plen):
        for b in self.prompt_buckets:
            if b >= plen:
                return b
        return None

    def _route_request(self, prompt, plen, h):
        """Build a partially-filled :class:`_Request` routed either
        through a resumed session handoff ``h`` (adopt ``h.plen`` rows,
        delta-prefill ``[h.next_token] + prompt``) or the cold path.
        A resume whose geometry no longer fits a delta pass falls back
        to cold-prefilling the full transcript."""
        req = _Request()
        req.base = None
        req.start = 0
        req.suffix = None
        req.sbucket = None
        if h is not None:
            suffix = np.concatenate(
                [[np.int64(h.next_token)], prompt]).astype(np.int64)
            sbucket = self._bucket_for(len(suffix))
            start = int(h.plen)
            expect = (self.cfg.num_layers, self.cache_len,
                      self.cfg.hidden)
            if (tuple(h.shape) == expect and sbucket is not None
                    and start + sbucket <= self.cache_len):
                req.base = h
                req.start = start
                req.suffix = suffix
                req.sbucket = sbucket
                req.prompt = prompt
                req.plen = plen
                req.bucket = None
                req.hist = np.concatenate(
                    [np.asarray(h.prompt, np.int64), suffix])
                self._bump("resumed")
                return req
            # transcript no longer delta-fits: replay it cold
            prompt = np.concatenate(
                [np.asarray(h.prompt, np.int64), suffix])
            plen = int(prompt.size)
        bucket = self._bucket_for(plen)
        if bucket is None:
            raise ValueError(
                "prompt length %d exceeds the largest prompt bucket "
                "(%d) — raise cache_len/prompt_buckets"
                % (plen, self.prompt_buckets[-1]))
        req.prompt = prompt
        req.plen = plen
        req.bucket = bucket
        req.hist = prompt
        return req

    def submit(self, prompt, max_new=None, eos_id=None, deadline_ms=None,
               tenant=None, priority=None, trace_ctx=None, session=None):
        """Enqueue one generation request; returns a
        :class:`DecodeStream`. Raises :class:`ShedError` when the queue
        is full, :class:`EngineClosedError` after ``stop()``, and
        ``ValueError`` for prompts that cannot fit the ladder.
        ``tenant``/``priority`` are carried for observability — the
        disagg router schedules on them; a lone engine records them.
        A sampled ``trace_ctx`` puts this request's queue/prefill/
        per-token spans into its distributed trace.

        ``session`` (with a ``session_tier`` attached) names a
        resumable conversation: when the stream retires, the slot's KV
        rows hibernate to host RAM under that id, and a later submit
        with the same id adopts them back and delta-prefills only the
        new ``prompt`` tokens (the continuation — NOT the transcript
        so far, which the tier already holds). A first-time or evicted
        session cold-prefills ``prompt`` as usual."""
        if self._closed:
            raise EngineClosedError(
                "engine %r is draining/stopped" % self.name)
        if self.role == "decode":
            raise RuntimeError(
                "engine %r is a decode-role (step-only) replica: it "
                "builds no prefill programs — hand it a prefilled KV "
                "cache via submit_prefilled()" % self.name)
        prompt = np.asarray(prompt, dtype=np.int64).reshape(-1)
        plen = int(prompt.shape[0])
        if plen < 1:
            raise ValueError("empty prompt")
        if prompt.min() < 0 or prompt.max() >= self.cfg.vocab:
            raise ValueError(
                "prompt token out of range [0, %d)" % self.cfg.vocab)
        session = None if session is None else str(session)
        h = None
        if session is not None and self._session_tier is not None:
            h = self._session_tier.resume(session)
        try:
            req = self._route_request(prompt, plen, h)
            max_new = (self.default_max_new if max_new is None
                       else int(max_new))
            if max_new < 1:
                raise ValueError("max_new must be >= 1")
            total = (req.start + len(req.suffix) if req.base is not None
                     else req.plen)
            if total + max_new - 1 > self.cache_len:
                raise ValueError(
                    "context %d + max_new %d - 1 exceeds cache_len %d"
                    % (total, max_new, self.cache_len))
        except Exception:
            if h is not None:
                # a failed resume must not lose the hibernated session
                self._session_tier.hibernate(session, h)
            raise
        req.session = session
        req.max_new = max_new
        req.eos_id = self.eos_id if eos_id is None else eos_id
        req.handoff = None
        req.tenant = tenant
        req.priority = priority
        if deadline_ms is None:
            deadline_ms = self._default_deadline_ms
        req.deadline = (time.monotonic() + float(deadline_ms) / 1000.0
                        if deadline_ms is not None else None)
        sampled = trace_ctx is not None and trace_ctx.sampled
        req.trace = trace_ctx if sampled else None
        req.t_wall = time.time() if sampled else None
        req.handle = DecodeStream(
            plen, max_new, stall_timeout_s=self.request_timeout_s)
        req.handle.tenant = tenant
        req.handle.priority = priority
        req.handle.trace = req.trace
        try:
            with self._admit_lock:
                if self._closed:
                    raise EngineClosedError(
                        "engine %r is draining/stopped" % self.name)
                self._q.put_nowait(req)
        except EngineClosedError:
            if h is not None:
                self._session_tier.hibernate(session, h)
            raise
        except queue.Full:
            self._bump("shed")
            if h is not None:
                self._session_tier.hibernate(session, h)
            obs.event("shed", source="serving", model=self.name,
                      engine="decode", prompt_len=plen,
                      queue_capacity=self._q.maxsize)
            raise ShedError(
                "decode queue full (%d) for model %r — request shed"
                % (self._q.maxsize, self.name),
                model=self.name, retry_after=self.retry_after_hint())
        self._bump("requests")
        obs.set_gauge("serving.queue_depth.%s" % self.name,
                      self._q.qsize())
        return req.handle

    def generate(self, prompt, max_new=None, eos_id=None,
                 deadline_ms=None, timeout=None):
        """Synchronous submit + wait; returns the full token list."""
        h = self.submit(prompt, max_new=max_new, eos_id=eos_id,
                        deadline_ms=deadline_ms)
        return h.result(
            timeout if timeout is not None else self.request_timeout_s)

    def submit_prefilled(self, handoff, max_new=None, eos_id=None,
                         deadline_ms=None, tenant=None, priority=None,
                         trace_ctx=None, session=None):
        """Enqueue a generation whose prefill already happened on
        another replica: ``handoff`` is a
        :class:`~paddle_tpu.serving.disagg.kv_wire.KVHandoff` whose KV
        pair is adopted into a free slot (no prefill program runs here
        — works on ``role="decode"`` replicas). The stream's first
        token is the handoff's ``next_token``; ``max_new`` counts it,
        matching :meth:`submit` semantics, so a handoff at ``plen``
        with ``max_new`` N delivers N tokens total."""
        if self._closed:
            raise EngineClosedError(
                "engine %r is draining/stopped" % self.name)
        expect = (self.cfg.num_layers, self.cache_len, self.cfg.hidden)
        if tuple(handoff.shape) != expect:
            raise ValueError(
                "handoff cache shape %r does not match this engine's "
                "geometry %r" % (tuple(handoff.shape), expect))
        plen = int(handoff.plen)
        if plen < 1 or plen > self.cache_len:
            raise ValueError("handoff plen %d outside [1, cache_len=%d]"
                             % (plen, self.cache_len))
        max_new = self.default_max_new if max_new is None else int(max_new)
        if max_new < 1:
            raise ValueError("max_new must be >= 1")
        if plen + max_new - 1 > self.cache_len:
            raise ValueError(
                "handoff plen %d + max_new %d - 1 exceeds cache_len %d"
                % (plen, max_new, self.cache_len))
        req = _Request()
        req.prompt = np.asarray(handoff.prompt, np.int64).reshape(-1)
        req.plen = plen
        req.bucket = None
        req.max_new = max_new
        req.eos_id = self.eos_id if eos_id is None else eos_id
        req.handoff = handoff
        req.session = None if session is None else str(session)
        req.base = None
        req.start = 0
        req.suffix = None
        req.sbucket = None
        req.hist = req.prompt
        req.tenant = tenant
        req.priority = priority
        if deadline_ms is None:
            deadline_ms = self._default_deadline_ms
        req.deadline = (time.monotonic() + float(deadline_ms) / 1000.0
                        if deadline_ms is not None else None)
        if trace_ctx is None:
            # the handoff's embedded context keeps the prefill-side
            # trace alive across a transport that dropped the kwarg
            trace_ctx = getattr(handoff, "trace", None)
        sampled = trace_ctx is not None and trace_ctx.sampled
        req.trace = trace_ctx if sampled else None
        req.t_wall = time.time() if sampled else None
        req.handle = DecodeStream(
            plen, max_new, stall_timeout_s=self.request_timeout_s)
        req.handle.tenant = tenant
        req.handle.priority = priority
        req.handle.trace = req.trace
        try:
            with self._admit_lock:
                if self._closed:
                    raise EngineClosedError(
                        "engine %r is draining/stopped" % self.name)
                self._q.put_nowait(req)
        except queue.Full:
            self._bump("shed")
            obs.event("shed", source="serving", model=self.name,
                      engine="decode", prompt_len=plen, handoff=True,
                      queue_capacity=self._q.maxsize)
            raise ShedError(
                "decode queue full (%d) for model %r — handoff shed"
                % (self._q.maxsize, self.name),
                model=self.name, retry_after=self.retry_after_hint())
        self._bump("requests")
        obs.set_gauge("serving.queue_depth.%s" % self.name,
                      self._q.qsize())
        return req.handle

    # -- admission checks before warmup ----------------------------------
    def check_hbm_budget(self, budget_bytes=None):
        """Price params + the persistent KV buffer pair + the step
        program's transient peak with the static liveness analyzer,
        BEFORE any warmup compile. The cache feeds/fetches are passed
        as ``resident_names`` so the analyzer holds them live across
        the whole decode region instead of letting them die like
        ordinary activations. ``budget_bytes=None`` resolves the device
        capacity from the analyzer's device table; unknown capacity is
        a no-op. Raises ``ProgramVerifyError`` when the engine cannot
        fit."""
        from ..analysis import costs as _costs, memory as _memory
        from ..analysis.diagnostics import ProgramVerifyError
        from ..fluid.executor import _device_kind

        if budget_bytes is None:
            profile = _costs.device_profile(_device_kind())
            budget_bytes = profile.hbm_bytes if profile else None
        if not budget_bytes:
            return None
        # co-resident KV-reuse state eats budget before the step does:
        # an hbm-placed prefix pool reserves its full capacity, a bound
        # draft its params + slot buffer pair
        overhead = 0
        if self._prefix_pool is not None:
            overhead += self._prefix_pool.hbm_bytes()
        if self._draft is not None:
            overhead += self._draft.resident_bytes()
        budget_bytes = budget_bytes - overhead
        jax = self._jax
        pred = self._step_pred
        sv = self._step_vars
        cache_names = [sv["k_in"].name, sv["v_in"].name,
                       sv["k"].name, sv["v"].name]
        # the cache feed dtype drives the byte pricing: int8 residency
        # costs 1 byte/element where fp32 cost 4, plus the per-row fp32
        # scale planes — exactly the slot multiplier disagg banks on
        feed_specs = {
            sv["tok"].name: jax.ShapeDtypeStruct(
                (self.slots, 1), np.int64),
            sv["pos"].name: jax.ShapeDtypeStruct(
                (self.slots, 1), np.int64),
            sv["k_in"].name: jax.ShapeDtypeStruct(
                tuple(self._k.shape), self._cache_np_dtype),
            sv["v_in"].name: jax.ShapeDtypeStruct(
                tuple(self._v.shape), self._cache_np_dtype),
        }
        if self.kv_dtype == "int8":
            cache_names += [sv["k_scale_in"].name, sv["v_scale_in"].name,
                            sv["k_scale"].name, sv["v_scale"].name]
            feed_specs[sv["k_scale_in"].name] = jax.ShapeDtypeStruct(
                tuple(self._kscale.shape), np.float32)
            feed_specs[sv["v_scale_in"].name] = jax.ShapeDtypeStruct(
                tuple(self._vscale.shape), np.float32)
        est = _memory.estimate(
            pred.program, feed_specs=feed_specs,
            state_specs=pred._state, fetch_names=pred.fetch_names,
            state_names=set(pred._state), default_dim=self.slots,
            resident_names=cache_names)
        obs.set_gauge(
            "serving.predicted_peak_hbm.%s" % self.name, est.peak_bytes)
        if est.peak_bytes > budget_bytes:
            obs.event("bucket_rejected", source="serving",
                      model=self.name, engine="decode",
                      budget_bytes=int(budget_bytes))
            raise ProgramVerifyError(
                "predicted-oom: decode engine %r needs %.2f MB "
                "(params %.2f MB + resident KV pair + step peak at op "
                "%s '%s') but the HBM budget is %.2f MB — shrink "
                "slots/cache_len or shard the model"
                % (self.name, est.peak_bytes / 1e6,
                   est.param_bytes / 1e6, est.peak_op_index,
                   est.peak_op_type, budget_bytes / 1e6))
        return est

    def check_ladder(self):
        """Lint the (slots, cache_len, prompt-buckets) ladder's
        compiled-program count against the shape-vocabulary budget;
        returns the findings (also recorded as events)."""
        from ..analysis import tpu_lint

        report = tpu_lint.lint_decode_ladder(
            self.prompt_buckets, slot_counts=(self.slots,),
            cache_lens=(self.cache_len,),
            kv_dtypes=(self.kv_dtype,),
            delta_buckets=tuple(sorted(self._delta_preds)),
            spec_blocks=((self._draft.k + 1,)
                         if self._draft is not None else ()),
            draft_buckets=(tuple(self._draft._buckets)
                           if self._draft is not None else ()))
        for d in report.findings:
            obs.event("decode_ladder_lint", source="serving",
                      model=self.name, message=d.message[:200])
        return report.findings

    def warmup(self, check_hbm=True):
        """Pre-build the step program and every prompt-bucket prefill
        through the compile-cache disk tier (zero ``compile_start`` on
        a restarted server). Returns the per-program report."""
        if check_hbm:
            self.check_hbm_budget()
        self.check_ladder()
        report = []
        warm_feeds = {
            "gpt_step_tok": self._tok, "gpt_step_pos": self._pos,
            "gpt_step_k": np.zeros(self._k.shape, self._cache_np_dtype),
            "gpt_step_v": np.zeros(self._v.shape, self._cache_np_dtype)}
        if self.kv_dtype == "int8":
            warm_feeds["gpt_step_kscale"] = np.zeros(
                self._kscale.shape, np.float32)
            warm_feeds["gpt_step_vscale"] = np.zeros(
                self._vscale.shape, np.float32)
        source = self._step_pred.warm(warm_feeds)
        report.append({"program": "step", "slots": self.slots,
                       "cache_len": self.cache_len,
                       "kv_dtype": self.kv_dtype, "source": source})
        for b in sorted(self._prefill_preds):
            source = self._prefill_preds[b].warm({
                "gpt_prefill_ids": np.zeros((1, b), np.int64),
                "gpt_prefill_len": np.ones((1, 1), np.int64)})
            report.append({"program": "prefill", "bucket": b,
                           "source": source})
        cache1 = (1, self.cfg.num_layers, self.cache_len,
                  self.cfg.hidden)
        for b in sorted(self._delta_preds):
            source = self._delta_preds[b].warm({
                "gpt_dpre_ids": np.zeros((1, b), np.int64),
                "gpt_dpre_len": np.ones((1, 1), np.int64),
                "gpt_dpre_start": np.zeros((1, 1), np.int64),
                "gpt_dpre_k": np.zeros(cache1, np.float32),
                "gpt_dpre_v": np.zeros(cache1, np.float32)})
            report.append({"program": "delta_prefill", "bucket": b,
                           "source": source})
        if self._verify_pred is not None:
            blk = self._draft.k + 1
            source = self._verify_pred.warm({
                "gpt_vrf_tok": np.zeros((self.slots, blk), np.int64),
                "gpt_vrf_pos": np.zeros((self.slots, 1), np.int64),
                "gpt_vrf_k": np.zeros(self._k.shape, np.float32),
                "gpt_vrf_v": np.zeros(self._v.shape, np.float32)})
            report.append({"program": "verify", "block": blk,
                           "source": source})
            report.extend(self._draft.warmup())
        obs.event(
            "warmup", source="serving", count=False, model=self.name,
            engine="decode", engines=len(report),
            compiled=sum(1 for r in report if r["source"] == "compile"),
            disk_warm=sum(1 for r in report if r["source"] == "disk"))
        return report

    # -- dispatch loop ---------------------------------------------------
    def _loop(self):
        while True:
            self._sweep_cancelled()
            self._admit()
            live = sum(1 for s in self._slots if s is not None)
            if self._abort:
                self._fail_all()
                return
            if live == 0:
                if self._stop_event.is_set() and self._q.empty():
                    return
                if _conc._on:
                    _conc.note_blocking("time.sleep(idle)")
                time.sleep(0.002)
                continue
            if self._draft is not None:
                self._spec_step()
            else:
                self._step()

    def _fail_all(self):
        while True:
            try:
                req = self._q.get_nowait()
            except queue.Empty:
                break
            req.handle._fail(EngineClosedError(
                "engine %r stopped before prefill" % self.name))
        for i, s in enumerate(self._slots):
            if s is not None:
                self._retire(i, "error", error=EngineClosedError(
                    "engine %r stopped mid-generation" % self.name))

    def _sweep_cancelled(self):
        for i, s in enumerate(self._slots):
            if s is not None and s.handle.cancelled:
                self._retire(i, "cancelled")

    def _admit(self):
        """Prefill queued requests into free slots. In ``barrier`` mode
        (the full-batch baseline) admission waits until EVERY slot has
        retired."""
        if self.barrier and any(s is not None for s in self._slots):
            return
        for i in range(self.slots):
            if self._slots[i] is not None:
                continue
            req = None
            while req is None:
                try:
                    req = self._q.get_nowait()
                except queue.Empty:
                    obs.set_gauge(
                        "serving.queue_depth.%s" % self.name,
                        self._q.qsize())
                    return
                if req.handle.cancelled:
                    req.handle._finish("cancelled")
                    self._bump("cancelled")
                    req = None
                    continue
                now = time.monotonic()
                if req.deadline is not None and now > req.deadline:
                    # shed BEFORE prefill: no chip time for an answer
                    # nobody is waiting for
                    self._bump("deadline_miss")
                    waited_ms = round(
                        1000 * (now - req.handle.t_submit), 3)
                    obs.event("deadline_miss", source="serving",
                              model=self.name, engine="decode",
                              waited_ms=waited_ms)
                    req.handle._fail(DeadlineExceededError(
                        "deadline expired after %s ms in decode queue "
                        "(model %r)" % (waited_ms, self.name)))
                    req = None
            self._fill_slot(i, req)
        obs.set_gauge("serving.queue_depth.%s" % self.name,
                      self._q.qsize())

    def _fill_slot(self, slot, req):
        """Route one admitted request onto its cheapest fill path:
        remote handoff adopt, session-resume delta, prefix-pool
        full-hit adopt, prefix-pool delta, or cold prefill."""
        if req.handoff is not None:
            return self._adopt(slot, req)
        if req.base is not None:  # session resume (handoff from tier)
            return self._delta_prefill(slot, req)
        if self._prefix_pool is not None:
            entry = self._prefix_pool.lookup(req.prompt)
            if entry is not None and self._entry_fits(entry, req):
                req.base = entry
                req.start = entry.plen
                if entry.plen == req.plen:
                    return self._adopt_prefix(slot, req)
                req.suffix = req.prompt[entry.plen:]
                req.sbucket = self._bucket_for(len(req.suffix))
                return self._delta_prefill(slot, req)
        return self._prefill(slot, req)

    def _entry_fits(self, entry, req):
        """A pool entry is adoptable when its geometry matches this
        engine, a FULL hit knows its first token, and a partial hit's
        suffix fits a delta bucket without the block write running off
        the cache edge (dynamic_update_slice clamps — never risk it)."""
        if tuple(np.asarray(entry.k).shape) != (
                self.cfg.num_layers, self.cache_len, self.cfg.hidden):
            return False
        if entry.plen > req.plen:
            return False
        if entry.plen == req.plen:
            return entry.next_token is not None
        sbucket = self._bucket_for(req.plen - entry.plen)
        return (sbucket is not None
                and entry.plen + sbucket <= self.cache_len)

    def _write_slot_cache(self, slot, k1, v1, ks=None, vs=None):
        """Install one sequence's cache pair into slot ``slot``.
        ``k1``/``v1`` are (1, L, T, H) in the engine's residency dtype;
        int8 engines also take the (1, L, T, 1) fp32 scale pair."""
        slot_i = np.int32(slot)
        self._k = self._write(self._k, k1, slot_i)
        self._v = self._write(self._v, v1, slot_i)
        if self.kv_dtype == "int8":
            self._kscale = self._write(self._kscale, ks, slot_i)
            self._vscale = self._write(self._vscale, vs, slot_i)

    def _trace_queue_span(self, req, now):
        """Export the (already finished) queue-wait span for a traced
        request; returns the context its work span should parent to."""
        ctx = req.trace.child()
        obs.export_span(
            "decode.queue", ctx, req.t_wall,
            now - req.handle.t_submit,
            {"proc": "decode:%s" % self.name, "tenant": req.tenant})
        return ctx

    def _prefill(self, slot, req):
        t0 = time.monotonic()
        ctx = (self._trace_queue_span(req, t0)
               if req.trace is not None else None)
        sp = None
        if ctx is not None:
            sp = obs.span("decode.prefill", ctx=ctx,
                          proc="decode:%s" % self.name, slot=slot,
                          bucket=req.bucket, plen=req.plen,
                          predicted_s=self._predicted_s(
                              "prefill", req.bucket))
            sp.__enter__()
        ids = np.zeros((1, req.bucket), np.int64)
        ids[0, :req.plen] = req.prompt
        plen = np.asarray([[req.plen]], np.int64)
        try:
            if _conc._on:
                _conc.note_blocking("device.dispatch")
            nxt, k1, v1 = self._prefill_preds[req.bucket].run(
                {"gpt_prefill_ids": ids, "gpt_prefill_len": plen},
                return_numpy=False)
        except Exception as e:  # noqa: BLE001 — fail the request, not the loop
            if sp is not None:
                sp.__exit__(type(e), e, None)
            self._bump("prefill_errors")
            obs.event("prefill_error", source="serving", model=self.name,
                      error="%s: %s" % (type(e).__name__, str(e)[:200]))
            req.handle._fail(e)
            return
        if self.kv_dtype == "int8":
            # the prefill program stays fp32; quantize per row on the
            # way into the resident buffers (same codec as the wire)
            from .disagg import kv_wire

            kq, ks = kv_wire.quantize_rows(np.asarray(k1)[0])
            vq, vs = kv_wire.quantize_rows(np.asarray(v1)[0])
            self._write_slot_cache(slot, kq[None], vq[None],
                                   ks[None], vs[None])
        else:
            self._write_slot_cache(slot, k1, v1)
        if sp is not None:
            sp.__exit__(None, None, None)
        self._tok[slot, 0] = tok = int(np.asarray(nxt)[0, 0])
        self._pos[slot, 0] = req.plen
        self._slots[slot] = _Slot(req.handle, req.max_new, req.eos_id,
                                  trace=sp.ctx if sp is not None
                                  else None, session=req.session,
                                  hist=req.hist)
        self._bump("prefill_rows_computed", req.bucket)
        if self._prefix_pool is not None:
            # bank this prompt's rows (fp32, pre-residency) so the
            # next shared-prefix request adopts instead of recomputing
            try:
                self._prefix_pool.put(req.prompt, np.asarray(k1),
                                      np.asarray(v1), next_token=tok)
            except Exception:  # noqa: BLE001 — caching is best-effort
                self._bump("prefix_insert_errors")
        self._draft_fill(slot, req.hist)
        now = time.monotonic()
        obs.observe("serving.decode.prefill_seconds", now - t0)
        obs.observe("serving.decode.ttft_seconds",
                    now - req.handle.t_submit)
        self._bump("prefills")
        self._emit(slot, tok)
        self._gauges()

    def _adopt_prefix(self, slot, req):
        """FULL prefix-pool hit: the pool holds rows for the whole
        prompt AND the greedy token after it — adopt and emit with no
        program dispatch at all (zero prefill FLOPs)."""
        t0 = time.monotonic()
        entry = req.base
        if req.trace is not None:
            self._trace_queue_span(req, t0)
        kd, vd = entry.dense()
        if self.kv_dtype == "int8":
            from .disagg import kv_wire

            if entry.store_dtype == "int8":
                kq, ks = np.asarray(entry.k), np.asarray(entry.k_scales)
                vq, vs = np.asarray(entry.v), np.asarray(entry.v_scales)
            else:
                kq, ks = kv_wire.quantize_rows(kd)
                vq, vs = kv_wire.quantize_rows(vd)
            self._write_slot_cache(slot, kq[None], vq[None],
                                   ks[None], vs[None])
        else:
            self._write_slot_cache(slot, kd[None], vd[None])
        self._tok[slot, 0] = tok = int(entry.next_token)
        self._pos[slot, 0] = req.plen
        self._slots[slot] = _Slot(req.handle, req.max_new, req.eos_id,
                                  session=req.session, hist=req.hist)
        self._bump("prefix_full_hits")
        self._bump("prefill_rows_saved", entry.plen)
        self._draft_fill(slot, req.hist)
        now = time.monotonic()
        obs.observe("serving.decode.prefill_seconds", now - t0)
        obs.observe("serving.decode.ttft_seconds",
                    now - req.handle.t_submit)
        self._emit(slot, tok)
        self._gauges()

    def _delta_prefill(self, slot, req):
        """Adopt ``req.start`` base rows (a prefix-pool entry or a
        hibernated session's handoff) and run the delta-prefill program
        over only the suffix — prefill FLOPs proportional to the
        unshared tail. The base rows feed the program in fp32; int8-
        resident engines requantize the returned cache, which is
        bit-stable on untouched rows (idempotent codec)."""
        t0 = time.monotonic()
        base = req.base
        if req.trace is not None:
            self._trace_queue_span(req, t0)
        suffix = np.asarray(req.suffix, np.int64).reshape(-1)
        slen = int(suffix.size)
        ids = np.zeros((1, req.sbucket), np.int64)
        ids[0, :slen] = suffix
        try:
            # a hibernated handoff is verified against its sealed
            # digest before any row lands in a slot (same contract as
            # _adopt); pool entries live in-process — their digest is
            # the lookup key, not a seal, and they carry no verify()
            if (getattr(base, "digest", None) is not None
                    and callable(getattr(base, "verify", None))):
                base.verify()
            kd, vd = base.dense()
            if _conc._on:
                _conc.note_blocking("device.dispatch")
            nxt, k1, v1 = self._delta_preds[req.sbucket].run(
                {"gpt_dpre_ids": ids,
                 "gpt_dpre_len": np.asarray([[slen]], np.int64),
                 "gpt_dpre_start": np.asarray([[req.start]], np.int64),
                 "gpt_dpre_k": kd[None], "gpt_dpre_v": vd[None]},
                return_numpy=False)
        except Exception as e:  # noqa: BLE001 — fail the request, not the loop
            self._bump("delta_errors")
            obs.event("delta_error", source="serving", model=self.name,
                      error="%s: %s" % (type(e).__name__, str(e)[:200]))
            req.handle._fail(e)
            return
        if self.kv_dtype == "int8":
            from .disagg import kv_wire

            kq, ks = kv_wire.quantize_rows(np.asarray(k1)[0])
            vq, vs = kv_wire.quantize_rows(np.asarray(v1)[0])
            self._write_slot_cache(slot, kq[None], vq[None],
                                   ks[None], vs[None])
        else:
            self._write_slot_cache(slot, k1, v1)
        self._tok[slot, 0] = tok = int(np.asarray(nxt)[0, 0])
        self._pos[slot, 0] = req.start + slen
        self._slots[slot] = _Slot(req.handle, req.max_new, req.eos_id,
                                  session=req.session, hist=req.hist)
        self._bump("delta_prefills")
        self._bump("prefill_rows_computed", req.sbucket)
        self._bump("prefill_rows_saved", req.start)
        if self._prefix_pool is not None and req.session is None:
            # extend the pool's coverage to the full prompt (resumed
            # sessions skip this: transcripts are not shared prefixes)
            try:
                self._prefix_pool.put(req.prompt, np.asarray(k1),
                                      np.asarray(v1), next_token=tok)
            except Exception:  # noqa: BLE001 — caching is best-effort
                self._bump("prefix_insert_errors")
        self._draft_fill(slot, req.hist)
        now = time.monotonic()
        obs.observe("serving.decode.prefill_seconds", now - t0)
        obs.observe("serving.decode.ttft_seconds",
                    now - req.handle.t_submit)
        self._emit(slot, tok)
        self._gauges()

    def _draft_fill(self, slot, hist):
        """Mirror a freshly filled slot into the draft's cache (the
        draft prefills the same token history). Draft staleness can
        only cost acceptance, never correctness — so a draft prefill
        failure downgrades the slot to effectively non-speculative
        instead of failing the stream."""
        if self._draft is None:
            return
        try:
            self._draft.prefill_slot(slot, hist)
        except Exception as e:  # noqa: BLE001 — speculation is optional
            self._bump("draft_fill_errors")
            obs.event("draft_fill_error", source="serving",
                      model=self.name,
                      error="%s: %s" % (type(e).__name__, str(e)[:200]))

    def _adopt(self, slot, req):
        """Install a remote prefill's :class:`KVHandoff` into a slot —
        the decode half of the disaggregated handoff. An int8 handoff
        whose block is the hidden width drops payload+scales straight
        into an int8-resident engine (no requantize); every other
        combination goes through fp32."""
        t0 = time.monotonic()
        h = req.handoff
        if req.trace is not None:
            self._trace_queue_span(req, t0)
        # the adopt span parents to the PREFILL side's span when the
        # handoff carries one — that's the cross-process flow arrow
        actx = getattr(h, "trace", None) or req.trace
        sp = None
        if actx is not None and actx.sampled:
            sp = obs.span("decode.adopt", ctx=actx,
                          proc="decode:%s" % self.name, slot=slot,
                          plen=req.plen, wire_dtype=h.wire_dtype,
                          wire_bytes=h.wire_bytes())
            sp.__enter__()
        try:
            # digest check FIRST: a corrupted handoff must fail the
            # inner stream here (the router's migration path then
            # re-prefills) — never install garbage into a slot
            if getattr(h, "digest", None) is not None:
                h.verify()
            if self.kv_dtype == "int8":
                if h.wire_dtype == "int8":
                    kq, ks = np.asarray(h.k, np.int8), h.k_scales
                    vq, vs = np.asarray(h.v, np.int8), h.v_scales
                else:
                    from .disagg import kv_wire

                    kd, vd = h.dense()
                    kq, ks = kv_wire.quantize_rows(kd)
                    vq, vs = kv_wire.quantize_rows(vd)
                self._write_slot_cache(
                    slot, kq[None], vq[None],
                    np.asarray(ks, np.float32)[None],
                    np.asarray(vs, np.float32)[None])
            else:
                kd, vd = h.dense()
                self._write_slot_cache(slot, kd[None], vd[None])
        except Exception as e:  # noqa: BLE001 — fail the request, not the loop
            if sp is not None:
                sp.__exit__(type(e), e, None)
            self._bump("adopt_errors")
            from ..integrity.digest import IntegrityError
            if isinstance(e, IntegrityError):
                obs.inc("integrity.handoff_digest_mismatch")
                obs.event("integrity_violation", source="serving",
                          model=self.name, check="kv_handoff",
                          op="adopt", tensor=e.tensor,
                          error=str(e)[:200])
            obs.event("adopt_error", source="serving", model=self.name,
                      error="%s: %s" % (type(e).__name__, str(e)[:200]))
            req.handle._fail(e)
            return
        if sp is not None:
            sp.__exit__(None, None, None)
        self._tok[slot, 0] = tok = int(h.next_token)
        self._pos[slot, 0] = req.plen
        self._slots[slot] = _Slot(req.handle, req.max_new, req.eos_id,
                                  trace=sp.ctx if sp is not None
                                  else None, session=req.session,
                                  hist=req.hist)
        self._draft_fill(slot, req.hist)
        obs.observe("serving.disagg.adopt_seconds",
                    time.monotonic() - t0)
        self._bump("adopts")
        self._emit(slot, tok)
        self._gauges()

    def _emit(self, slot, tok):
        """Deliver one generated token to a slot's stream; retires the
        slot the SAME step when the sequence finishes (EOS or length)."""
        s = self._slots[slot]
        s.handle._emit(tok)
        s.remaining -= 1
        self._bump("tokens")
        obs.inc("serving.decode.tokens")
        if s.trace is not None:
            # one tiny span per generated token on a SAMPLED request:
            # dur is the inter-token gap (the per-token-p99 SLO leg)
            now = time.monotonic()
            gap = now - s.t_last
            s.t_last = now
            obs.export_span(
                "decode.token", s.trace.child(), time.time() - gap, gap,
                {"proc": "decode:%s" % self.name, "slot": slot,
                 "index": len(s.handle._tokens),
                 "predicted_s": self._predicted_s("step")})
        if s.eos_id is not None and tok == s.eos_id:
            self._retire(slot, "eos")
        elif s.remaining <= 0:
            self._retire(slot, "length")

    def _retire(self, slot, reason, error=None):
        s = self._slots[slot]
        if (self._session_tier is not None and s.session is not None
                and reason in ("eos", "length") and error is None):
            try:
                self._hibernate(slot, s)
            except Exception as e:  # noqa: BLE001 — tiering is best-effort
                self._bump("hibernate_errors")
                obs.event("hibernate_error", source="serving",
                          model=self.name, session=s.session,
                          error="%s: %s" % (type(e).__name__,
                                            str(e)[:200]))
        self._slots[slot] = None
        self._tok[slot, 0] = 0
        self._pos[slot, 0] = 0
        if error is not None:
            s.handle._fail(error)
        else:
            s.handle._finish(reason)
        self._bump("retired")
        if reason == "cancelled":
            self._bump("cancelled")
        now = time.monotonic()
        obs.observe("serving.decode.request_seconds",
                    now - s.handle.t_submit)
        if s.trace is not None:
            obs.export_span(
                "decode.stream", s.trace.child(), s.t_wall,
                now - s.t_prefill,
                {"proc": "decode:%s" % self.name, "slot": slot,
                 "reason": reason, "tokens": len(s.handle._tokens)})
        with self._stats_lock:
            self._rate.append((now, 1))
        obs.event("slot_retired", source="serving", count=False,
                  model=self.name, slot=slot, reason=reason,
                  tokens=len(s.handle._tokens))

    def _hibernate(self, slot, s):
        """Encode a retiring session slot's live KV rows into the
        KVHandoff wire format and park them in the session tier.
        ``prompt`` carries the token-per-row history (admission history
        + every emitted token but the last), ``next_token`` the last
        emitted token — exactly what the resume delta-prefill consumes
        first — and ``plen`` the written row count. int8-resident
        engines ship payload + scales verbatim (no requantize), fp32
        engines encode at the tier's wire dtype."""
        from .disagg import kv_wire

        emitted = np.asarray(s.handle._tokens, np.int64)
        if emitted.size == 0:
            return
        pos = int(self._pos[slot, 0])
        hist = np.concatenate([np.asarray(s.hist, np.int64),
                               emitted[:-1]])
        if hist.size != pos:
            raise ValueError(
                "slot %d history %d rows != pos %d — refusing to "
                "hibernate a misaligned session"
                % (slot, hist.size, pos))
        if self.kv_dtype == "int8":
            h = kv_wire.encode_kv_q(
                np.asarray(self._k[slot]), np.asarray(self._v[slot]),
                np.asarray(self._kscale[slot]),
                np.asarray(self._vscale[slot]),
                int(emitted[-1]), pos, hist)
        else:
            h = kv_wire.encode_kv(
                np.asarray(self._k[slot]), np.asarray(self._v[slot]),
                int(emitted[-1]), pos, hist,
                wire_dtype=self._session_tier.wire_dtype)
        self._session_tier.hibernate(s.session, h)
        self._bump("hibernated")

    def _step_feeds(self):
        feeds = {"gpt_step_tok": self._tok, "gpt_step_pos": self._pos,
                 "gpt_step_k": self._k, "gpt_step_v": self._v}
        if self.kv_dtype == "int8":
            feeds["gpt_step_kscale"] = self._kscale
            feeds["gpt_step_vscale"] = self._vscale
        return feeds

    def _step(self):
        t0 = time.monotonic()
        # the feed dict is captured BEFORE dispatch: the run reassigns
        # self._k/_v (and _tok/_pos mutate only at emission, below), so
        # these references are exactly the step's inputs — what the SDC
        # sentinel re-dispatches on a sampled replay
        feeds = self._step_feeds()
        try:
            # chaos site: a 'slow' clause stalls the step in place (it
            # shows up in step_seconds + the ledger, the autopilot
            # drill's seeded degradation); an exception clause flows to
            # the step_error path below like a real device fault
            R.fault_check("dispatch")
            if _conc._on:
                _conc.note_blocking("device.dispatch")
            outs = self._step_pred.run(feeds, return_numpy=False)
            if self.kv_dtype == "int8":
                (nxt, self._k, self._v, self._kscale,
                 self._vscale) = outs
            else:
                nxt, self._k, self._v = outs
        except Exception as e:  # noqa: BLE001 — fail the slots, not the loop
            self._bump("step_errors")
            obs.event("step_error", source="serving", model=self.name,
                      error="%s: %s" % (type(e).__name__, str(e)[:200]))
            for i, s in enumerate(self._slots):
                if s is not None:
                    self._retire(i, "error", error=e)
            return
        dt = time.monotonic() - t0
        obs.observe("serving.decode.step_seconds", dt)
        self._note_step_measured(dt)
        self._bump("steps")
        if (self._sentinel is not None
                and self._sentinel.sample(self._sentinel_id)):
            ok = self._sentinel.replay_check(
                self._sentinel_id,
                lambda: self._step_pred.run(feeds, return_numpy=False),
                outs, feeds=feeds)
            if not ok:
                # the step disagreed with its own replay: retire every
                # live slot BEFORE emission so a possibly-corrupted
                # token is never delivered; the streams migrate and
                # regenerate on a healthy replica while the sentinel's
                # cross-replica vote adjudicates this one
                from ..integrity.digest import IntegrityError
                self._bump("sdc_disagree")
                err = IntegrityError(
                    "SDC replay disagreement on decode replica %r — "
                    "withholding this step's tokens"
                    % (self._sentinel_id,))
                for i, s in enumerate(self._slots):
                    if s is not None:
                        self._retire(i, "error", error=err)
                return
        nxt_np = np.asarray(nxt)
        for i, s in enumerate(self._slots):
            if s is None:
                continue
            tok = int(nxt_np[i, 0])
            self._pos[i, 0] += 1
            self._tok[i, 0] = tok
            self._emit(i, tok)
        self._gauges()

    def _spec_step(self):
        """One speculative iteration: ``k`` draft proposals per slot,
        ONE target verify dispatch over the ``k + 1`` block, emit the
        longest prefix matching the target's own greedy picks plus the
        correction/bonus token. Every emitted token is the target's
        argmax — bit-exact with :meth:`_step` by construction. Any
        live slot without ``k + 1`` rows of cache headroom demotes the
        whole iteration to the plain step (mirrored into the draft so
        its cache stays gapless)."""
        k = self._draft.k
        blk = k + 1
        live = [i for i, s in enumerate(self._slots) if s is not None]
        if any(int(self._pos[i, 0]) + blk > self.cache_len
               for i in live):
            # cache-edge fallback: single-token step, draft mirrored
            self._bump("spec_fallback_steps")
            try:
                self._draft.sync_step(self._tok, self._pos)
            except Exception:  # noqa: BLE001 — speculation is optional
                self._bump("draft_step_errors")
            self._step()
            return
        t0 = time.monotonic()
        try:
            proposals = self._draft.propose(self._tok, self._pos)
        except Exception as e:  # noqa: BLE001 — draft down ≠ engine down
            self._bump("draft_step_errors")
            obs.event("draft_step_error", source="serving",
                      model=self.name,
                      error="%s: %s" % (type(e).__name__, str(e)[:200]))
            self._step()
            return
        feeds = {"gpt_vrf_tok": np.concatenate(
                     [self._tok, proposals], axis=1),
                 "gpt_vrf_pos": self._pos,
                 "gpt_vrf_k": self._k, "gpt_vrf_v": self._v}
        try:
            R.fault_check("dispatch")
            if _conc._on:
                _conc.note_blocking("device.dispatch")
            y, self._k, self._v = self._verify_pred.run(
                feeds, return_numpy=False)
        except Exception as e:  # noqa: BLE001 — fail the slots, not the loop
            self._bump("step_errors")
            obs.event("step_error", source="serving", model=self.name,
                      error="%s: %s" % (type(e).__name__, str(e)[:200]))
            for i, s in enumerate(self._slots):
                if s is not None:
                    self._retire(i, "error", error=e)
            return
        dt = time.monotonic() - t0
        obs.observe("serving.spec.round_seconds", dt)
        y = np.asarray(y)                                 # (S, k+1)
        accepted = 0
        for i in live:
            # longest prefix of the draft's proposals matching the
            # target's picks; emit those + the correction/bonus token
            m = 0
            while m < k and proposals[i, m] == y[i, m]:
                m += 1
            accepted += m
            for j in range(m + 1):
                if self._slots[i] is None:
                    break  # EOS/length retired the slot mid-block
                tok = int(y[i, j])
                self._pos[i, 0] += 1
                self._tok[i, 0] = tok
                self._emit(i, tok)
        self._bump("spec_rounds")
        self._bump("spec_proposed", k * len(live))
        self._bump("spec_accepted", accepted)
        with self._stats_lock:
            proposed = self._stats["spec_proposed"]
            acc = self._stats["spec_accepted"]
        if proposed:
            rate = acc / float(proposed)
            obs.set_gauge("serving.spec.accept_rate", rate)
            obs.set_gauge("serving.spec.accept_rate.%s" % self.name,
                          rate)
        self._gauges()

    def _note_step_measured(self, dt):
        """Feed the measured step time into the executable ledger
        (EMA-smoothed) so drift scoring and device auto-calibration see
        live serving numbers, not only bench runs. Best-effort: the
        ledger must never fail a step."""
        try:
            if self._step_fp is None:
                from ..fluid import compile_cache as _cc

                self._step_fp = _cc.fingerprint_or_none(
                    self._step_pred.program) or ""
            if not self._step_fp:
                return
            ema = self._step_ema
            self._step_ema = dt if ema is None else 0.8 * ema + 0.2 * dt
            obs.get_ledger().note_measured(self._step_fp,
                                           self._step_ema)
            if not self._step_noted:
                self._step_noted = True
                self._predicted_s("step")  # pair a prediction with it
        except Exception:  # noqa: BLE001 — telemetry only
            pass

    def _predicted_s(self, kind, bucket=None):
        """Cost-model predicted seconds for one prefill of `bucket` or
        one step, cached; None when the analyzer can't price it (trace
        annotation is best-effort — never fail a request on it). The
        full prediction is also attached to the program's ledger entry,
        arming predicted-vs-measured drift for the autopilot."""
        key = (kind, bucket)
        if key in self._cost_cache:
            return self._cost_cache[key]
        val = None
        try:
            from ..analysis import costs as _costs
            from ..fluid import compile_cache as _cc

            kind_dev = getattr(self._jax.devices()[0], "device_kind",
                               None)
            if kind == "step":
                prog = self._step_pred.program
                feeds = {k: np.asarray(v) for k, v in
                         self._step_feeds().items()}
            else:
                prog = self._prefill_preds[bucket].program
                feeds = {"gpt_prefill_ids": np.zeros((1, bucket),
                                                     np.int64),
                         "gpt_prefill_len": np.ones((1, 1), np.int64)}
            pred = _costs.predict_program(
                prog, feed_specs=feeds, is_test=True,
                device_kind=kind_dev)
            val = pred.get("predicted_step_seconds")
            fp = _cc.fingerprint_or_none(prog)
            if fp:
                obs.get_ledger().note_prediction(fp, pred)
        except Exception:  # noqa: BLE001 — annotation only
            val = None
        self._cost_cache[key] = val
        return val

    def _gauges(self):
        live = sum(1 for s in self._slots if s is not None)
        obs.set_gauge("serving.decode.slot_utilization.%s" % self.name,
                      live / float(self.slots))
        occupancy = float(self._pos.sum()) / (self.slots * self.cache_len)
        obs.set_gauge("serving.decode.cache_occupancy.%s" % self.name,
                      occupancy)

    # -- introspection ---------------------------------------------------
    def _bump(self, key, n=1):
        with self._stats_lock:
            self._stats[key] += n
        # mirror every lifecycle counter into the hub so /metrics sees
        # the same numbers stats() reports ("tokens" incs at its own
        # site to keep the hot emit path one call)
        if key != "tokens":
            obs.inc("serving.decode.%s" % key, n)

    def stats(self):
        """Local lifetime counters: requests/tokens/prefills/steps/
        retired/shed/deadline_miss/cancelled/prefill_errors/
        step_errors."""
        with self._stats_lock:
            out = dict(self._stats)
        for k in ("requests", "tokens", "prefills", "adopts", "steps",
                  "retired", "shed", "deadline_miss", "cancelled",
                  "prefill_errors", "adopt_errors", "step_errors",
                  "prefill_rows_computed", "prefill_rows_saved",
                  "prefix_full_hits", "delta_prefills", "delta_errors",
                  "spec_rounds", "spec_proposed", "spec_accepted",
                  "spec_fallback_steps", "hibernated", "resumed"):
            out.setdefault(k, 0)
        out["spec_accept_rate"] = (
            out["spec_accepted"] / float(out["spec_proposed"])
            if out["spec_proposed"] else None)
        out["live_slots"] = sum(1 for s in self._slots if s is not None)
        out["slots"] = self.slots
        out["kv_dtype"] = self.kv_dtype
        out["role"] = self.role
        return out

    def reuse_info(self):
        """KV-reuse + speculation state for ``/healthz``
        (:func:`paddle_tpu.serving.registry.info` attaches it):
        draft-model attachment, prefix-pool and session-tier stats,
        and the redundant-prefill economics counters."""
        with self._stats_lock:
            st = dict(self._stats)
        computed = st.get("prefill_rows_computed", 0)
        saved = st.get("prefill_rows_saved", 0)
        proposed = st.get("spec_proposed", 0)
        return {
            "draft": (self._draft.info()
                      if self._draft is not None else None),
            "spec_accept_rate": (
                st.get("spec_accepted", 0) / float(proposed)
                if proposed else None),
            "prefix_pool": (self._prefix_pool.stats()
                            if self._prefix_pool is not None else None),
            "session_tier": (self._session_tier.stats()
                             if self._session_tier is not None
                             else None),
            "prefill_rows_computed": computed,
            "prefill_rows_saved": saved,
            "prefill_rows_saved_pct": (
                100.0 * saved / float(saved + computed)
                if (saved + computed) else None),
        }

    def slot_bytes(self):
        """HBM bytes one slot's resident KV pair occupies (see
        :func:`kv_slot_bytes`)."""
        return kv_slot_bytes(self.cfg, self.cache_len, self.kv_dtype)

    def queue_depth(self):
        return self._q.qsize()

    def drain_rate(self):
        """Requests/sec retired over the recent window (None until the
        first retire, or after 30s idle)."""
        now = time.monotonic()
        with self._stats_lock:
            pts = [(t, n) for t, n in self._rate if now - t < 30.0]
        if not pts:
            return None
        span = max(1e-3, now - min(t for t, _ in pts))
        return sum(n for _, n in pts) / span

    def retry_after_hint(self):
        """Seconds until the queue likely drains at the observed retire
        rate (the HTTP 429 ``Retry-After``). Clamped to [1, 60]."""
        rate = self.drain_rate()
        if not rate:
            return 1.0
        return min(60.0, max(1.0, (self.queue_depth() + 1) / rate))

    @property
    def closed(self):
        return self._closed
