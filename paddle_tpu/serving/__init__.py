"""paddle_tpu.serving — TPU-native online inference.

The training side of this stack keeps the chip saturated with one
AOT-compiled executable per program signature; this package does the
same for *traffic*: concurrent requests coalesce into padded
micro-batches ahead of pre-warmed per-bucket executables, so serving
cost scales with batches dispatched, not requests received.

Layers::

    ModelRegistry          named models, isolated scopes, atomic hot reload
      └─ ServingRouter     N replicas, heartbeat-driven health, least-
                           loaded dispatch, shed-aware failover, warm
                           standby autoscale, rolling version rollout
        └─ ServingEngine   bounded queue + dispatch thread, dynamic
                           micro-batching, deadlines, load shedding
             └─ Predictor  AOT executable per shape bucket, pre-warmed
                           through fluid.compile_cache (restart == warm)
    ServingServer          stdlib HTTP/JSON frontend
                           (/v1/models/<name>:predict, /healthz, /metrics)

A single-engine deployment stays exactly as before (``reg.load``); a
fleet swaps in one line — ``reg.publish("m", router.local_fleet(dir,
n_replicas=4))`` — because the router wears the engine's duck type.

Autoregressive decode gets its own engine:
:class:`~paddle_tpu.serving.decode.DecodeEngine` holds a persistent
slotted KV cache and runs a two-program loop (bucketed prefill + one
step program for every live slot), retiring finished sequences and
prefilling queued requests into freed slots *between* steps —
continuous batching, no full-batch barrier. It publishes like any
engine (``reg.publish("gpt", DecodeEngine(cfg, scope))``) and streams
per-token over ``POST /v1/models/<name>:generate`` (chunked
transfer-encoding).

Decode multiplies tokens/sec and sessions-per-chip with **KV reuse +
speculation** (:mod:`~paddle_tpu.serving.prefix_pool`,
:mod:`~paddle_tpu.serving.spec`): a :class:`PrefixPool` banks
prefilled KV rows under content-hash prefix digests so shared-prefix
traffic adopts instead of recomputing (full hits cost ZERO prefill
FLOPs; partial hits delta-prefill only the unshared tail), a
:class:`SessionTier` hibernates idle conversations' KV to host RAM in
the int8 wire format and re-adopts them on resume, and a
:class:`DraftModel` sidecar proposes ``k`` tokens per round for the
target to verify in one block dispatch — bit-exact with plain greedy
decode by construction, since every emitted token is the target's own
argmax. All three attach as constructor kwargs
(``DecodeEngine(cfg, scope, draft=..., prefix_pool=...,
session_tier=...)``) and surface through ``/healthz`` reuse blocks.

Decode scales past one engine by **disaggregating the phases**
(:mod:`~paddle_tpu.serving.disagg`): prefill replicas turn prompts
into serialized int8 block-scaled KV handoffs, step-only decode
replicas (optionally int8-*resident*, ~4x slots/chip) adopt them, and
:func:`~paddle_tpu.serving.disagg.disagg_fleet` fronts the fleet with
a :class:`~paddle_tpu.serving.disagg.DisaggRouter` — session-affine,
migrates sessions off dead replicas via re-prefill, and gates
admission with per-tenant priorities/quotas/SLOs
(:class:`~paddle_tpu.serving.disagg.TenantTable`).

Embedding/retrieval traffic gets the third engine kind
(:class:`~paddle_tpu.retrieval.engine.RetrievalEngine`, imported from
:mod:`paddle_tpu.retrieval` to keep the layering one-way): an
``ep``-sharded embedding table served through ``:lookup``
(id -> embedding rows, bit-identical to the single-device gather) and
``:search`` (query -> exact brute-force top-k), publishing like any
engine — ``reg.publish("items", RetrievalEngine(table, k=10))`` —
with query-bucket ladders priced through ``check_hbm_budget`` before
warmup and the index geometry (rows/dim/shards/resident bytes)
surfaced in ``/healthz``.

Quick start::

    from paddle_tpu import serving

    reg = serving.ModelRegistry(max_batch_size=16, max_wait_ms=2.0)
    reg.load("mnist", "/models/mnist",
             buckets=[serving.BucketSpec({"img": (784,)},
                                         batch_sizes=(1, 2, 4, 8, 16))])
    server = serving.ServingServer(reg, port=8500).start()

Well-known telemetry (``paddle_tpu.observability``):
``serving.queue_wait_seconds`` / ``batch_size`` / ``batch_rows`` /
``padding_waste`` / ``request_seconds`` histograms,
``serving.shed`` / ``serving.deadline_miss`` counters (each reject also
lands in the flight recorder), ``serving.queue_depth.<model>`` gauges —
plus the fleet layer: ``serving.replicas_live`` /
``serving.rollout_state`` gauges, ``serving.failovers`` /
``serving.router_retry`` / ``serving.replica_dead`` counters, and the
``serving.dispatch_seconds`` histogram.
"""
from .batcher import BucketSpec, round_up_pow2, tail_signature  # noqa: F401
from .decode import (  # noqa: F401
    DecodeEngine, DecodeStream, default_prompt_buckets,
)
from .engine import (  # noqa: F401
    DeadlineExceededError, EngineClosedError, ServingEngine, ShedError,
)
from .http import ServingHandler, ServingServer  # noqa: F401
from .prefix_pool import PrefixPool, SessionTier, prefix_digest  # noqa: F401
from .registry import ModelRegistry  # noqa: F401
from .spec import DraftModel  # noqa: F401
from .router import (  # noqa: F401
    LocalReplica, NoReplicasError, ReplicaGoneError, ReplicaWorker,
    RolloutError, ServingRouter, StoreReplica, local_fleet,
    make_engine_factory,
)
from .disagg import (  # noqa: F401  (after .decode/.router: it layers on them)
    DisaggReplica, DisaggRouter, DisaggStream, KVHandoff, PrefillEngine,
    PrefillTicket, TenantSpec, TenantTable, disagg_fleet,
)

__all__ = [
    "BucketSpec", "DeadlineExceededError", "DecodeEngine", "DecodeStream",
    "DisaggReplica", "DisaggRouter", "DisaggStream", "DraftModel",
    "EngineClosedError", "KVHandoff", "LocalReplica", "ModelRegistry",
    "NoReplicasError", "PrefillEngine", "PrefillTicket", "PrefixPool",
    "ReplicaGoneError", "ReplicaWorker", "RolloutError", "ServingEngine",
    "ServingHandler", "ServingRouter", "ServingServer", "SessionTier",
    "ShedError", "StoreReplica", "TenantSpec", "TenantTable",
    "default_prompt_buckets", "disagg_fleet", "local_fleet",
    "make_engine_factory", "prefix_digest", "round_up_pow2",
    "tail_signature",
]
