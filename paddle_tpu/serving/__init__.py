"""paddle_tpu.serving — TPU-native online inference.

The training side of this stack keeps the chip saturated with one
AOT-compiled executable per program signature; this package does the
same for *traffic*: concurrent requests coalesce into padded
micro-batches ahead of pre-warmed per-bucket executables, so serving
cost scales with batches dispatched, not requests received.

Layers::

    ModelRegistry          named models, isolated scopes, atomic hot reload
      └─ ServingEngine     bounded queue + dispatch thread, dynamic
                           micro-batching, deadlines, load shedding
           └─ Predictor    AOT executable per shape bucket, pre-warmed
                           through fluid.compile_cache (restart == warm)
    ServingServer          stdlib HTTP/JSON frontend
                           (/v1/models/<name>:predict, /healthz, /metrics)

Quick start::

    from paddle_tpu import serving

    reg = serving.ModelRegistry(max_batch_size=16, max_wait_ms=2.0)
    reg.load("mnist", "/models/mnist",
             buckets=[serving.BucketSpec({"img": (784,)},
                                         batch_sizes=(1, 2, 4, 8, 16))])
    server = serving.ServingServer(reg, port=8500).start()

Well-known telemetry (``paddle_tpu.observability``):
``serving.queue_wait_seconds`` / ``batch_size`` / ``batch_rows`` /
``padding_waste`` / ``request_seconds`` histograms,
``serving.shed`` / ``serving.deadline_miss`` counters (each reject also
lands in the flight recorder), ``serving.queue_depth.<model>`` gauges.
"""
from .batcher import BucketSpec, round_up_pow2, tail_signature  # noqa: F401
from .engine import (  # noqa: F401
    DeadlineExceededError, EngineClosedError, ServingEngine, ShedError,
)
from .http import ServingHandler, ServingServer  # noqa: F401
from .registry import ModelRegistry  # noqa: F401

__all__ = [
    "BucketSpec", "DeadlineExceededError", "EngineClosedError",
    "ModelRegistry", "ServingEngine", "ServingHandler", "ServingServer",
    "ShedError", "round_up_pow2", "tail_signature",
]
