"""Stdlib HTTP/JSON frontend over a ModelRegistry.

Endpoints (``http.server.ThreadingHTTPServer`` — one thread per
connection blocks on its request's future while the single dispatch
thread per model does the batching):

- ``POST /v1/models/<name>:predict`` — body
  ``{"feeds": {"x": [[...]]}, "dtypes": {"x": "float32"}?,
  "deadline_ms": 50?, "timeout_s": 10?}``; replies
  ``{"outputs": [{"data": ..., "shape": ..., "dtype": ...}]}``.
  Feed dtypes default to the model's declared var dtypes (ints arriving
  as JSON numbers coerce to the program's int32/int64), so a plain
  nested-list payload round-trips bit-exact for float32 models.
- ``POST /v1/models/<name>:generate`` — decode engines only
  (:class:`~paddle_tpu.serving.decode.DecodeEngine` or a
  :class:`~paddle_tpu.serving.disagg.DisaggRouter` published into the
  registry). Body ``{"prompt": [ids], "max_new_tokens": 32?,
  "eos_id": 2?, "deadline_ms": 50?, "timeout_s": 10?, "stream": true?,
  "tenant": "chat"?, "priority": "interactive"|0..2?}`` — ``tenant``
  must be a non-empty string and ``priority`` an int 0..2 or a named
  class (400 otherwise); both feed the disagg fleet's multi-tenant
  admission and are harmless on a lone engine.
  With ``stream`` (the default) the reply is **chunked
  transfer-encoding** (HTTP/1.1), one JSON line per token flushed as
  the engine's step loop produces it — ``{"token": 7, "index": 0}`` —
  closed by a ``{"done": true, "finish_reason": ..., "tokens": [...]}``
  line. The response headers are only sent once the FIRST token (or
  failure) is known, so queue-time errors still map to real statuses;
  a client disconnect mid-stream cancels the request and frees its
  engine slot at the next dispatch iteration. ``"stream": false``
  returns one aggregate JSON document.
- ``POST /v1/models/<name>:lookup`` / ``:search`` — retrieval engines
  only (:class:`~paddle_tpu.retrieval.engine.RetrievalEngine`).
  ``:lookup`` body ``{"ids": [3, 14, 159], "deadline_ms": 50?,
  "timeout_s": 10?}`` replies ``{"embeddings": [[...]], "shape": ...,
  "dtype": ...}`` — rows bit-identical to the sharded table's gather.
  ``:search`` body ``{"query": [[...]], "k": 10?}`` replies
  ``{"ids": [[...]], "scores": [[...]], "k": 10}`` — exact brute-force
  top-k per query row. Posting any verb to a mismatched engine kind
  answers 400 with the model's actual kind (and the verb it speaks)
  named in the body.
- ``GET /healthz`` — ``{"status": "ok", "models": {...}}`` with
  per-model kind, version, queue depth, lifetime counters, and (for
  retrieval engines) the index block: rows, dim, shards, resident
  bytes.
- ``GET /metrics`` — the telemetry hub's Prometheus text
  (``render_prom()``): serving histograms with p50/p90/p99 quantiles,
  shed/deadline-miss counters, queue-depth gauges.

Status mapping (the admission-control surface): 429 shed (queue full —
the JSON body names the shedding model + replica and the response
carries a ``Retry-After`` header derived from the engine's observed
queue drain rate), 504 deadline missed or wait timeout, 503
draining/stopped or a replica fleet with zero live replicas, 404
unknown model, 400 malformed request. Both ``:predict`` and the
``:generate`` streaming path carry ``Retry-After`` on 429 AND 503 —
a draining engine and a zero-replica fleet are as retryable as a full
queue.

Standalone entry point::

    python -m paddle_tpu.serving.http --model mnist=/models/mnist \
        --port 8500 --max-batch-size 16 --max-wait-ms 2
"""
import json
import re
import threading
import time
from concurrent.futures import TimeoutError as _FutureTimeout
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from .. import observability as obs
from .engine import DeadlineExceededError, EngineClosedError, ShedError

__all__ = ["ServingHandler", "ServingServer", "main"]

_PREDICT_RE = re.compile(r"^/v1/models/([^/:]+):predict$")
_GENERATE_RE = re.compile(r"^/v1/models/([^/:]+):generate$")
_LOOKUP_RE = re.compile(r"^/v1/models/([^/:]+):lookup$")
_SEARCH_RE = re.compile(r"^/v1/models/([^/:]+):search$")

_VERB_FOR_KIND = {"predict": ":predict", "decode": ":generate",
                  "retrieval": ":lookup or :search"}


def _kind_of(engine):
    return getattr(engine, "engine_kind", "predict")


def _wrong_kind_doc(name, engine, wanted):
    """400 body naming the engine's actual kind and the verb it speaks,
    so a misrouted client learns where to go instead of guessing."""
    kind = _kind_of(engine)
    return {
        "error": "model %r is a %r engine, not %r — use %s"
                 % (name, kind, wanted,
                    _VERB_FOR_KIND.get(kind, ":predict")),
        "model": name, "kind": kind,
    }


class ServingHandler(BaseHTTPRequestHandler):
    server_version = "paddle-tpu-serving/0.1"
    # chunked transfer-encoding (the :generate stream) needs HTTP/1.1;
    # every other response carries Content-Length so keep-alive is safe
    protocol_version = "HTTP/1.1"

    def log_message(self, format, *args):  # noqa: A002 — stdlib signature
        pass  # request logging goes through the telemetry hub, not stderr

    def _send_json(self, code, doc, headers=None):
        body = json.dumps(doc).encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    @staticmethod
    def _shed_doc(e, name, engine):
        """429 body: who shed (model + replica), so a client/router tier
        above can steer, not just back off."""
        return {
            "error": str(e),
            "model": getattr(e, "model", None) or name,
            "replica": getattr(e, "replica", None),
            "retry_after_s": getattr(e, "retry_after", None),
        }

    @staticmethod
    def _shed_headers(e, engine):
        """Retry-After derived from the shedding engine's queue drain
        rate (whole seconds, >= 1 per RFC 9110)."""
        hint = getattr(e, "retry_after", None)
        if hint is None:
            hinter = getattr(engine, "retry_after_hint", None)
            hint = hinter() if hinter is not None else None
        seconds = max(1, int(-(-float(hint) // 1))) if hint else 1
        return {"Retry-After": str(seconds)}

    def _fleet_prom(self):
        """Federated ``scope=fleet`` exposition: every published engine
        that aggregates a fleet (``fleet_render_prom``) contributes its
        merged view; a registry with only lone engines answers with the
        process hub so the page is never empty."""
        parts = []
        registry = self.server.registry
        for name in sorted(registry.info()):
            engine = registry.get(name)
            render = getattr(engine, "fleet_render_prom", None)
            if render is None:
                continue
            try:
                parts.append(render())
            except Exception:  # noqa: BLE001 — metrics must not 500
                continue
        return "".join(parts) or obs.render_prom()

    def do_GET(self):  # noqa: N802 — stdlib handler name
        path, _, query = self.path.partition("?")
        if path == "/healthz":
            self._send_json(200, {
                "status": "ok",
                "models": self.server.registry.info(),
            })
        elif path == "/metrics":
            from urllib.parse import parse_qs

            scope = (parse_qs(query).get("scope") or ["process"])[0]
            text = (self._fleet_prom() if scope == "fleet"
                    else obs.render_prom())
            body = text.encode("utf-8")
            self.send_response(200)
            self.send_header("Content-Type", "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        else:
            self._send_json(404, {"error": "not found: %s" % self.path})

    # -- decode streaming (:generate) -----------------------------------
    def _chunk(self, doc):
        """One chunked-transfer frame holding a JSON line, flushed so
        the client sees each token as the step loop emits it."""
        data = (json.dumps(doc) + "\n").encode("utf-8")
        self.wfile.write(b"%X\r\n" % len(data) + data + b"\r\n")
        self.wfile.flush()

    def _generate_errdoc(self, exc, name, engine):
        """(status, doc, headers) for a pre-stream generate failure.
        429 AND 503 both carry Retry-After: a draining engine or a
        zero-replica fleet is as retryable as a full queue."""
        if isinstance(exc, ShedError):
            return (429, self._shed_doc(exc, name, engine),
                    self._shed_headers(exc, engine))
        if isinstance(exc, DeadlineExceededError):
            return 504, {"error": str(exc), "model": name}, None
        if isinstance(exc, EngineClosedError):
            return (503, {"error": str(exc), "model": name},
                    self._shed_headers(exc, engine))
        if isinstance(exc, (TimeoutError, _FutureTimeout)):
            return (504, {"error": "timed out waiting for model %r"
                          % name, "model": name}, None)
        if type(exc).__name__ == "NoReplicasError":
            # fleet with zero live replicas: unavailable, not internal
            # (matched by name to avoid importing the router here)
            return (503, {"error": str(exc), "model": name},
                    self._shed_headers(exc, engine))
        return (500, {"error": "%s: %s" % (type(exc).__name__, exc),
                      "model": name}, None)

    @staticmethod
    def _parse_tenant_priority(body):
        """Validate the multi-tenant request fields; raises ValueError
        (400 upstream) on malformed values. Returns kwargs to forward
        only when the fields are present, so engines that predate them
        keep working."""
        kw = {}
        if "tenant" in body:
            tenant = body["tenant"]
            if not isinstance(tenant, str) or not tenant.strip():
                raise ValueError(
                    "tenant must be a non-empty string, got %r"
                    % (tenant,))
            kw["tenant"] = tenant.strip()
        if "priority" in body and body["priority"] is not None:
            from .disagg.tenancy import resolve_priority

            resolve_priority(body["priority"])  # raises on malformed
            kw["priority"] = body["priority"]
        return kw

    def _trace_ctx(self, body=None):
        """TraceContext for this request: an incoming W3C
        ``traceparent`` header wins (distributed callers pick the
        sampling bit); ``"trace": true`` in the body forces a fresh
        sampled context; otherwise the deterministic stride sampler
        over ``$PADDLE_TPU_TRACE_SAMPLE`` decides."""
        ctx = obs.TraceContext.from_header(
            self.headers.get("traceparent"))
        if ctx is not None:
            return ctx if ctx.sampled else None
        if body and body.get("trace") and obs.trace_dir() is not None:
            return obs.TraceContext.new()
        return obs.sample_request()

    def _do_generate(self, name, engine):
        if _kind_of(engine) != "decode":
            return self._send_json(
                400, _wrong_kind_doc(name, engine, "decode"))
        try:
            n = int(self.headers.get("Content-Length") or 0)
            body = json.loads(self.rfile.read(n) or b"{}")
            prompt = body["prompt"]
            kw = {"max_new": body.get("max_new_tokens"),
                  "eos_id": body.get("eos_id"),
                  "deadline_ms": body.get("deadline_ms")}
            if body.get("session") is not None:
                # resumable-conversation id (engines with a session
                # tier hibernate/adopt KV under it); forwarded only
                # when present so engines that predate it keep working
                session = body["session"]
                if not isinstance(session, str) or not session.strip():
                    raise ValueError(
                        "session must be a non-empty string, got %r"
                        % (session,))
                kw["session"] = session.strip()
            kw.update(self._parse_tenant_priority(body))
            timeout_s = body.get("timeout_s")
            stream = bool(body.get("stream", True))
        except (ValueError, KeyError, TypeError) as e:
            return self._send_json(
                400, {"error": "bad request: %s: %s"
                               % (type(e).__name__, e)})
        tctx = self._trace_ctx(body)
        t_req = time.time() if tctx is not None else None
        if tctx is not None:
            kw["trace_ctx"] = tctx
        try:
            handle = engine.submit(prompt, **kw)
        except (ValueError, TypeError) as e:
            return self._send_json(
                400, {"error": "bad request: %s: %s"
                               % (type(e).__name__, e)})
        except Exception as e:  # noqa: BLE001 — admission errors -> statuses
            return self._send_json(*self._generate_errdoc(e, name, engine))

        if not stream:
            try:
                toks = handle.result(timeout_s)
            except Exception as e:  # noqa: BLE001
                return self._send_json(
                    *self._generate_errdoc(e, name, engine))
            if tctx is not None:
                obs.export_span(
                    "http.generate", tctx, t_req, time.time() - t_req,
                    {"proc": "http", "model": name,
                     "tokens": len(toks)})
            return self._send_json(200, {
                "tokens": toks, "n_tokens": len(toks),
                "finish_reason": handle.finish_reason, "model": name,
                "trace_id": tctx.trace_id if tctx is not None
                else None})

        # hold the headers until the first token (or failure) exists:
        # a request shed/expired in the queue must answer 429/504, not
        # a 200 that dies mid-stream
        gen = handle.tokens(timeout=timeout_s)
        try:
            first = next(gen, None)
        except Exception as e:  # noqa: BLE001
            handle.cancel()
            return self._send_json(*self._generate_errdoc(e, name, engine))
        self.send_response(200)
        self.send_header("Content-Type", "application/jsonl")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()
        try:
            try:
                if first is not None:
                    self._chunk({"token": first, "index": 0})
                    for i, tok in enumerate(gen, start=1):
                        self._chunk({"token": tok, "index": i})
                toks = handle.so_far()
                done = {"done": True,
                        "finish_reason": handle.finish_reason,
                        "tokens": toks, "n_tokens": len(toks)}
                if tctx is not None:
                    done["trace_id"] = tctx.trace_id
                self._chunk(done)
            except (BrokenPipeError, ConnectionResetError):
                # client went away: free the slot at the next dispatch
                # iteration instead of decoding to nobody
                handle.cancel()
                obs.event("client_disconnect", source="serving",
                          model=name, streamed=len(handle.so_far()))
                self.close_connection = True
                return
            except Exception as e:  # noqa: BLE001 — mid-stream engine error
                self._chunk({"error": "%s: %s" % (type(e).__name__, e),
                             "done": True, "finish_reason": "error"})
                return
        finally:
            if not handle.done:
                handle.cancel()
            if tctx is not None:
                obs.export_span(
                    "http.generate", tctx, t_req, time.time() - t_req,
                    {"proc": "http", "model": name,
                     "tokens": len(handle.so_far())})
            try:
                self.wfile.write(b"0\r\n\r\n")
                self.wfile.flush()
            except (BrokenPipeError, ConnectionResetError):
                self.close_connection = True

    # -- retrieval (:lookup / :search) -----------------------------------
    def _do_retrieval(self, name, engine, op):
        """``:lookup`` (``{"ids": [...]}`` -> embedding rows) and
        ``:search`` (``{"query": [[...]], "k": 10?}`` -> top-k ids +
        scores) against a retrieval engine; same status mapping as
        ``:predict`` (429 shed + Retry-After, 504 deadline/timeout,
        503 draining, 400 malformed)."""
        if _kind_of(engine) != "retrieval":
            return self._send_json(
                400, _wrong_kind_doc(name, engine, "retrieval"))
        try:
            n = int(self.headers.get("Content-Length") or 0)
            body = json.loads(self.rfile.read(n) or b"{}")
            if op == "lookup":
                feeds = {"op": "lookup", "ids": body["ids"]}
            else:
                feeds = {"op": "search", "query": body["query"],
                         "k": body.get("k")}
            deadline_ms = body.get("deadline_ms")
            timeout_s = body.get("timeout_s")
        except (ValueError, KeyError, TypeError) as e:
            return self._send_json(
                400, {"error": "bad request: %s: %s"
                               % (type(e).__name__, e)})
        tctx = self._trace_ctx(body)
        t_req = time.time() if tctx is not None else None
        try:
            fut = engine.submit(feeds, deadline_ms=deadline_ms,
                                trace_ctx=tctx)
        except ShedError as e:
            return self._send_json(429, self._shed_doc(e, name, engine),
                                   headers=self._shed_headers(e, engine))
        except EngineClosedError as e:
            return self._send_json(503, {"error": str(e), "model": name})
        except (ValueError, KeyError, TypeError) as e:
            return self._send_json(
                400, {"error": "bad request: %s: %s"
                               % (type(e).__name__, e)})
        try:
            out = fut.result(
                timeout_s if timeout_s is not None
                else engine.request_timeout_s)
        except DeadlineExceededError as e:
            return self._send_json(504, {"error": str(e), "model": name})
        except ShedError as e:
            return self._send_json(429, self._shed_doc(e, name, engine),
                                   headers=self._shed_headers(e, engine))
        except _FutureTimeout:
            return self._send_json(
                504, {"error": "timed out waiting for model %r" % name,
                      "model": name})
        except EngineClosedError as e:
            return self._send_json(503, {"error": str(e), "model": name})
        except Exception as e:  # noqa: BLE001 — engine errors -> 500
            if type(e).__name__ == "NoReplicasError":
                return self._send_json(
                    503, {"error": str(e), "model": name})
            return self._send_json(
                500, {"error": "%s: %s" % (type(e).__name__, e)})
        if tctx is not None:
            obs.export_span(
                "http.%s" % op, tctx, t_req, time.time() - t_req,
                {"proc": "http", "model": name})
        if op == "lookup":
            emb = out["embeddings"]
            doc = {"embeddings": emb.tolist(),
                   "shape": list(emb.shape), "dtype": str(emb.dtype),
                   "model": name}
        else:
            doc = {"ids": out["ids"].tolist(),
                   "scores": out["scores"].tolist(),
                   "k": int(out["ids"].shape[-1]), "model": name}
        if tctx is not None:
            doc["trace_id"] = tctx.trace_id
        self._send_json(200, doc)

    def do_POST(self):  # noqa: N802 — stdlib handler name
        g = _GENERATE_RE.match(self.path)
        if g:
            name = g.group(1)
            engine = self.server.registry.get(name)
            if engine is None:
                return self._send_json(
                    404, {"error": "unknown model %r" % name})
            return self._do_generate(name, engine)
        for op, rx in (("lookup", _LOOKUP_RE), ("search", _SEARCH_RE)):
            r = rx.match(self.path)
            if r:
                name = r.group(1)
                engine = self.server.registry.get(name)
                if engine is None:
                    return self._send_json(
                        404, {"error": "unknown model %r" % name})
                return self._do_retrieval(name, engine, op)
        m = _PREDICT_RE.match(self.path)
        if not m:
            return self._send_json(
                404, {"error": "not found: %s (expected "
                               "/v1/models/<name>:predict, :generate, "
                               ":lookup, or :search)"
                               % self.path})
        name = m.group(1)
        engine = self.server.registry.get(name)
        if engine is None:
            return self._send_json(404, {"error": "unknown model %r" % name})
        if _kind_of(engine) in ("decode", "retrieval"):
            return self._send_json(
                400, _wrong_kind_doc(name, engine, "predict"))
        import numpy as np

        try:
            n = int(self.headers.get("Content-Length") or 0)
            body = json.loads(self.rfile.read(n) or b"{}")
            raw = body["feeds"]
            dtypes = body.get("dtypes") or {}
            feeds = {
                k: (np.asarray(v, dtype=np.dtype(dtypes[k]))
                    if k in dtypes else np.asarray(v))
                for k, v in raw.items()
            }
            deadline_ms = body.get("deadline_ms")
            timeout_s = body.get("timeout_s")
        except (ValueError, KeyError, TypeError) as e:
            return self._send_json(
                400, {"error": "bad request: %s: %s"
                               % (type(e).__name__, e)})
        tctx = self._trace_ctx(body)
        t_req = time.time() if tctx is not None else None
        try:
            if tctx is not None:
                try:
                    fut = engine.submit(feeds, deadline_ms=deadline_ms,
                                        trace_ctx=tctx)
                except TypeError:
                    # engine predates the kwarg: serve untraced
                    fut = engine.submit(feeds, deadline_ms=deadline_ms)
            else:
                fut = engine.submit(feeds, deadline_ms=deadline_ms)
        except ShedError as e:
            return self._send_json(429, self._shed_doc(e, name, engine),
                                   headers=self._shed_headers(e, engine))
        except EngineClosedError as e:
            return self._send_json(
                503, {"error": str(e), "model": name})
        except (ValueError, KeyError) as e:
            return self._send_json(
                400, {"error": "bad request: %s: %s"
                               % (type(e).__name__, e)})
        try:
            outs = fut.result(
                timeout_s if timeout_s is not None
                else engine.request_timeout_s)
        except DeadlineExceededError as e:
            return self._send_json(504, {"error": str(e), "model": name})
        except ShedError as e:
            # the router retried across every replica and all of them
            # shed — same backpressure contract as a direct shed
            return self._send_json(429, self._shed_doc(e, name, engine),
                                   headers=self._shed_headers(e, engine))
        except _FutureTimeout:
            return self._send_json(
                504, {"error": "timed out waiting for model %r" % name,
                      "model": name})
        except EngineClosedError as e:
            return self._send_json(503, {"error": str(e), "model": name})
        except Exception as e:  # noqa: BLE001 — model errors -> 500, not a dead conn
            if type(e).__name__ == "NoReplicasError":
                # fleet router with zero live replicas: unavailable,
                # not an internal error (avoids importing router here)
                return self._send_json(
                    503, {"error": str(e), "model": name})
            return self._send_json(
                500, {"error": "%s: %s" % (type(e).__name__, e)})
        if tctx is not None:
            obs.export_span(
                "http.predict", tctx, t_req, time.time() - t_req,
                {"proc": "http", "model": name})
        self._send_json(200, {"outputs": [
            {"data": o.tolist(), "shape": list(o.shape),
             "dtype": str(o.dtype)}
            for o in outs
        ]})


class ServingServer:
    """ThreadingHTTPServer bound to a ModelRegistry; ``start()`` serves
    on a background thread, ``stop()`` shuts it down (and optionally
    drains the registry)."""

    def __init__(self, registry, host="127.0.0.1", port=0):
        self.registry = registry
        self._httpd = ThreadingHTTPServer((host, int(port)), ServingHandler)
        self._httpd.registry = registry
        self._httpd.daemon_threads = True
        self.host = self._httpd.server_address[0]
        self.port = int(self._httpd.server_address[1])
        self._thread = None

    @property
    def url(self):
        return "http://%s:%d" % (self.host, self.port)

    def start(self):
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._httpd.serve_forever,
                kwargs={"poll_interval": 0.05},
                daemon=True, name="serving-http")
            self._thread.start()
            obs.event("http_start", source="serving", count=False,
                      host=self.host, port=self.port)
        return self

    def stop(self, close_registry=False):
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
        if close_registry:
            self.registry.close()


def main(argv=None):
    """CLI: serve one or more save_inference_model dirs over HTTP."""
    import argparse

    from .registry import ModelRegistry

    p = argparse.ArgumentParser(
        prog="paddle_tpu.serving.http",
        description="JSON/HTTP serving frontend for paddle_tpu models")
    p.add_argument("--model", action="append", required=True,
                   metavar="NAME=DIR",
                   help="model name=save_inference_model dir (repeatable)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8500)
    p.add_argument("--max-batch-size", type=int, default=8)
    p.add_argument("--max-wait-ms", type=float, default=2.0)
    p.add_argument("--queue-capacity", type=int, default=64)
    args = p.parse_args(argv)

    registry = ModelRegistry(
        max_batch_size=args.max_batch_size, max_wait_ms=args.max_wait_ms,
        queue_capacity=args.queue_capacity)
    for spec in args.model:
        name, sep, dirname = spec.partition("=")
        if not sep or not name or not dirname:
            p.error("--model wants NAME=DIR, got %r" % spec)
        registry.load(name, dirname)
    server = ServingServer(registry, host=args.host, port=args.port).start()
    print("serving %s on %s" % (", ".join(registry.names()), server.url),
          flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        server.stop(close_registry=True)
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
