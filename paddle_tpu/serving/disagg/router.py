"""DisaggRouter: a phase-specialized, tenant-aware decode fleet.

Layered on the same elastic-heartbeat machinery as
:class:`~paddle_tpu.serving.router.ServingRouter`, but the replicas
are no longer interchangeable: **prefill replicas**
(:class:`~.prefill.PrefillEngine`, bucketed prefill only) turn prompts
into serialized :class:`~.kv_wire.KVHandoff`\\ s, and **decode
replicas** (``DecodeEngine(role="decode")``, step only) adopt them
into slots and stream tokens. The split is the TTFT-vs-per-token-p99
fix: a long prompt burns a prefill chip, never a step loop.

- **Session affinity** — a stream is placed ONCE: the decode replica
  chosen at adoption (fewest live sessions wins) owns every subsequent
  step, because its slot holds the KV cache. There is no per-token
  routing decision to get wrong.
- **Migration via re-prefill** — when a decode replica dies mid-stream
  (silenced beacons or an :class:`EngineClosedError` out of its slot),
  the session's pump re-prefills ``prompt + so_far()`` on a prefill
  replica — greedy decode is deterministic, so the new handoff's first
  token is exactly the next token the dead replica would have emitted
  — and adopts the result on a surviving decode replica. Live streams
  complete token-for-token identical; ``serving.disagg.failed_streams``
  stays 0 through chaos.
- **Multi-tenant admission** — a :class:`~.tenancy.TenantTable` gates
  ``submit``: per-tenant live-session quotas shed with 429, the
  tenant's priority class orders the prefill queue, and the two SLO
  legs are scored separately (``ttft_slo_ms`` against queue-wait +
  prefill, ``per_token_slo_ms`` against inter-token gaps on the decode
  leg, both per tenant).

Scheduling reads the same signals the gauges publish: prefill
candidates order by queue depth (``serving.queue_depth.*``), decode
candidates by live-session count
(``serving.disagg.decode_sessions.*``).

Telemetry: ``serving.disagg.sessions`` / ``migrations`` /
``failed_streams`` / ``handoffs`` counters,
``serving.disagg.prefill_ttft_seconds`` / ``per_token_seconds`` (and
``per_token_seconds.<tenant>``) histograms,
``serving.disagg.slo_miss_ttft`` / ``slo_miss_per_token`` counters,
``serving.disagg.decode_sessions.<rid>`` gauges.
"""
import collections
import threading
import time

import numpy as np

from ... import observability as obs
from ...analysis import concurrency as _conc
from ...integrity.digest import IntegrityError
from ...parallel.elastic import ElasticConfig, HeartbeatMonitor, InMemoryStore
from ..decode import DecodeEngine, DecodeStream
from ..engine import EngineClosedError, ShedError
from ..router import NoReplicasError
from .prefill import PrefillEngine
from .tenancy import TenantTable, resolve_priority

__all__ = ["DisaggReplica", "DisaggRouter", "DisaggStream",
           "disagg_fleet"]


class _ReplicaLost(RuntimeError):
    """Internal: a decode replica died with this session on it."""

    def __init__(self, rid, cause):
        RuntimeError.__init__(self, "decode replica %d lost: %s"
                              % (rid, cause))
        self.rid = rid
        self.cause = cause


class DisaggStream(DecodeStream):
    """Router-level stream: survives the death of the replica serving
    it (the pump re-attaches underneath). Carries tenant/priority."""

    def __init__(self, prompt_len, max_new, stall_timeout_s=60.0,
                 tenant=None, priority=None):
        DecodeStream.__init__(self, prompt_len, max_new,
                              stall_timeout_s=stall_timeout_s)
        self.tenant = tenant
        self.priority = priority


class DisaggReplica:
    """One phase-specialized engine + its heartbeat beater (the
    LocalReplica pattern: silence IS death — :meth:`kill` stops the
    beacons without a goodbye, :meth:`stop` leaves cleanly)."""

    def __init__(self, rid, engine, store, name="default", config=None,
                 start_beating=True):
        self.rid = int(rid)
        self.engine = engine
        self.kind = getattr(engine, "engine_kind", "decode")
        self.name = str(name)
        self.config = config or ElasticConfig()
        self.monitor = HeartbeatMonitor(
            store, self.rid, world_size=1, config=self.config)
        self._beats = 0
        self._beat_stop = threading.Event()
        self._beater = None
        self._owner = _conc.owner_token(
            "disagg-replica", "%s-%d" % (self.name, self.rid), self)
        if start_beating:
            self.start_beating()

    def _beat_once(self):
        self._beats += 1
        rate = self.engine.drain_rate()
        depth = self.engine.queue_depth()
        extra = {"queue_depth": depth,
                 "model": self.name, "kind": self.kind}
        if obs.mode() != obs.OFF:
            # federation: the beacon carries this replica's metrics doc
            # so a FleetMetrics aggregator anywhere on the store can
            # merge the fleet without talking to engines directly
            try:
                extra["metrics"] = obs.replica_metrics_doc(
                    self.engine.stats(), queue_depth=depth)
            except Exception:  # noqa: BLE001 — beacons must not die
                pass
        self.monitor.beat(
            self._beats,
            latency=(1.0 / rate) if rate else None,
            extra=extra)

    def _beat_loop(self):
        interval = max(0.005, self.config.heartbeat_interval / 2.0)
        while not self._beat_stop.wait(interval):
            try:
                self._beat_once()
            except BaseException:  # noqa: BLE001 — cannot beat => dead
                return

    def start_beating(self):
        if self._beater is None or not self._beater.is_alive():
            self._beat_stop.clear()
            try:
                self._beat_once()
            except BaseException:  # noqa: BLE001
                return
            self._beater = threading.Thread(
                target=self._beat_loop, daemon=True,
                name="disagg-beat-%s-%d" % (self.name, self.rid))
            _conc.track_thread(self._beater, self._owner)
            self._beater.start()

    def queue_depth(self):
        return self.engine.queue_depth()

    def stats(self):
        return self.engine.stats()

    def kill(self):
        """Simulated crash: beacons go silent, queued/live work fails
        so the router's pumps migrate it."""
        self._beat_stop.set()
        if self._beater is not None:
            self._beater.join(timeout=1.0)
        self.engine.stop(drain=False, timeout=0.2)
        _conc.check_stopped(self._owner, grace=1.0)

    def stop(self, drain=True, timeout=30.0):
        self.engine.stop(drain=drain, timeout=timeout)
        self._beat_stop.set()
        if self._beater is not None:
            self._beater.join(timeout=1.0)
        try:
            self.monitor.leave()
        except BaseException:  # noqa: BLE001 — best-effort goodbye
            pass
        _conc.check_stopped(self._owner, grace=1.0)


class _Session:
    __slots__ = ("prompt", "max_new", "eos_id", "spec", "priority",
                 "handle", "deadline_ms", "rid", "trace", "migration")

    def __init__(self, prompt, max_new, eos_id, spec, priority, handle,
                 deadline_ms, trace=None):
        self.prompt = prompt
        self.max_new = max_new
        self.eos_id = eos_id
        self.spec = spec
        self.priority = priority
        self.handle = handle
        self.deadline_ms = deadline_ms
        self.rid = None
        self.trace = trace       # TraceContext (sampled) or None
        self.migration = 0       # bumps on every re-prefill migration


class DisaggRouter:
    """Engine-duck-typed front door over a prefill fleet + a decode
    fleet (``submit``/``generate``/``stats``/``queue_depth``/``stop``
    — the registry and HTTP frontend drive it like one DecodeEngine).

    Build it with :func:`disagg_fleet`, or hand it replicas directly::

        router = DisaggRouter([pre0, pre1], [dec0, dec1],
                              store=store, tenants=table)
        for tok in router.submit(prompt, max_new=64,
                                 tenant="chat",
                                 priority="interactive").tokens():
            ...
    """

    engine_kind = "decode"

    def __init__(self, prefill_replicas, decode_replicas, store=None,
                 name="default", config=None, tenants=None,
                 request_timeout_s=120.0, max_migrations=3,
                 health_interval=None, auto_health=True):
        prefill_replicas = list(prefill_replicas)
        decode_replicas = list(decode_replicas)
        if not prefill_replicas or not decode_replicas:
            raise ValueError(
                "a disagg router needs >=1 prefill and >=1 decode "
                "replica")
        self.name = str(name)
        self.config = config or ElasticConfig()
        self.store = store if store is not None else InMemoryStore()
        self.tenants = tenants or TenantTable(model=self.name)
        self.request_timeout_s = float(request_timeout_s)
        self.max_migrations = int(max_migrations)
        self._lock = _conc.named_lock("serving.disagg.router",
                                      recursive=True)
        self._owner = _conc.owner_token("disagg-router", self.name, self)
        self._prefill = {r.rid: r for r in prefill_replicas}
        self._decode = {r.rid: r for r in decode_replicas}
        if len(self._prefill) + len(self._decode) != (
                len(prefill_replicas) + len(decode_replicas)):
            raise ValueError("replica ids must be unique fleet-wide")
        self._dead = {}
        self._sessions = collections.defaultdict(set)  # rid -> handles
        self._pumps = set()
        self._counters = collections.Counter()
        self._closed = False
        # geometry/validation source: every decode replica was built
        # from the same cfg; the first one speaks for the fleet
        eng = decode_replicas[0].engine
        self.cfg = eng.cfg
        self.cache_len = eng.cache_len
        self.default_max_new = eng.default_max_new
        self.eos_id = eng.eos_id
        self._prompt_buckets = prefill_replicas[0].engine.prompt_buckets
        # observer monitor (worker -1 never beats, never counts)
        world = max(list(self._prefill) + list(self._decode)) + 1
        self.monitor = HeartbeatMonitor(
            self.store, -1, world_size=world, config=self.config)
        self._health_interval = (
            float(health_interval) if health_interval is not None
            else max(0.02, self.config.heartbeat_interval / 2.0))
        self._health_stop = threading.Event()
        self._health = None
        self._sentinel = None
        obs.set_gauge("serving.disagg.prefill_live", len(self._prefill))
        obs.set_gauge("serving.disagg.decode_live", len(self._decode))
        for c in ("sessions", "migrations", "failed_streams"):
            obs.inc("serving.disagg.%s" % c, 0)
        if auto_health:
            self.start_health()

    # -- admission -------------------------------------------------------
    def _bucket_for(self, plen):
        for b in self._prompt_buckets:
            if b >= plen:
                return b
        return None

    def submit(self, prompt, max_new=None, eos_id=None, deadline_ms=None,
               tenant=None, priority=None, trace_ctx=None):
        """Admit one generation session; returns a
        :class:`DisaggStream`. Sheds with 429 when the tenant is at
        quota or the prefill fleet is saturated; malformed priority
        raises ``ValueError`` (400 upstream). ``trace_ctx`` (a sampled
        :class:`~paddle_tpu.observability.TraceContext`, e.g. from a
        ``traceparent`` header) threads one trace_id through the
        prefill leg, the KV handoff and every decode-side span."""
        if self._closed:
            raise EngineClosedError(
                "disagg router %r is draining/stopped" % self.name)
        prompt = np.asarray(prompt, dtype=np.int64).reshape(-1)
        plen = int(prompt.shape[0])
        if plen < 1:
            raise ValueError("empty prompt")
        if prompt.min() < 0 or prompt.max() >= self.cfg.vocab:
            raise ValueError(
                "prompt token out of range [0, %d)" % self.cfg.vocab)
        if self._bucket_for(plen) is None:
            raise ValueError(
                "prompt length %d exceeds the largest prompt bucket "
                "(%d)" % (plen, self._prompt_buckets[-1]))
        max_new = (self.default_max_new if max_new is None
                   else int(max_new))
        if max_new < 1:
            raise ValueError("max_new must be >= 1")
        if plen + max_new - 1 > self.cache_len:
            raise ValueError(
                "prompt_len %d + max_new %d - 1 exceeds cache_len %d"
                % (plen, max_new, self.cache_len))
        spec = self.tenants.acquire(tenant)   # ShedError at quota
        try:
            prio = resolve_priority(priority, default=spec.priority)
        except ValueError:
            self.tenants.release(tenant)
            raise
        handle = DisaggStream(
            plen, max_new, stall_timeout_s=self.request_timeout_s,
            tenant=spec.name, priority=prio)
        if trace_ctx is not None and getattr(trace_ctx, "sampled", False):
            handle.trace = trace_ctx
        else:
            trace_ctx = None
        sess = _Session(prompt, max_new,
                        self.eos_id if eos_id is None else eos_id,
                        spec, prio, handle, deadline_ms,
                        trace=trace_ctx)
        self._bump("sessions")
        obs.inc("serving.disagg.sessions")
        pump = threading.Thread(
            target=self._run_session, args=(sess,), daemon=True,
            name="disagg-session-%s" % self.name)
        with self._lock:
            self._pumps.add(pump)
        _conc.track_thread(pump, self._owner)
        pump.start()
        return handle

    def generate(self, prompt, max_new=None, eos_id=None,
                 deadline_ms=None, tenant=None, priority=None,
                 timeout=None, trace_ctx=None):
        h = self.submit(prompt, max_new=max_new, eos_id=eos_id,
                        deadline_ms=deadline_ms, tenant=tenant,
                        priority=priority, trace_ctx=trace_ctx)
        return h.result(
            timeout if timeout is not None else self.request_timeout_s)

    # -- the per-session pump --------------------------------------------
    def _run_session(self, sess):
        try:
            handoff = self._prefill_leg(sess, sess.prompt)
            migrations = 0
            while True:
                try:
                    self._decode_leg(sess, handoff)
                    return
                except _ReplicaLost as lost:
                    migrations += 1
                    sess.migration = migrations
                    self._bump("migrations")
                    obs.inc("serving.disagg.migrations")
                    obs.event("session_migrated", source="serving",
                              model=self.name, replica=lost.rid,
                              tenant=sess.spec.name,
                              delivered=len(sess.handle.so_far()),
                              migration=migrations)
                    if migrations > self.max_migrations:
                        raise RuntimeError(
                            "session migrated %d times without "
                            "finishing (last: %s)"
                            % (migrations - 1, lost.cause))
                    handoff = self._replay_handoff(sess)
                    if handoff is None:
                        return  # delivered everything already
        except Exception as e:  # noqa: BLE001 — fail the stream, not silence
            if not sess.handle.done:
                self._bump("failed_streams")
                obs.inc("serving.disagg.failed_streams")
                obs.event("stream_failed", source="serving",
                          model=self.name, tenant=sess.spec.name,
                          error="%s: %s" % (type(e).__name__,
                                            str(e)[:200]))
                sess.handle._fail(e)
        finally:
            self.tenants.release(sess.spec.name)
            with self._lock:
                self._pumps.discard(threading.current_thread())

    def _replay_handoff(self, sess):
        """Rebuild a dead session's decode state by re-prefilling
        ``prompt + delivered`` — greedy determinism makes the new
        handoff's first token exactly the next undelivered token."""
        delivered = sess.handle.so_far()
        if sess.eos_id is not None and delivered and \
                delivered[-1] == sess.eos_id:
            sess.handle._finish("eos")
            return None
        if len(delivered) >= sess.max_new:
            sess.handle._finish("length")
            return None
        replay = np.concatenate(
            [sess.prompt, np.asarray(delivered, np.int64)])
        return self._prefill_leg(sess, replay)

    def _prefill_leg(self, sess, prompt):
        """Run one prefill on the least-loaded live prefill replica,
        failing over on dead/shedding replicas. Traced sessions get a
        ``disagg.prefill_leg`` span on the router track annotated with
        the migration count — a re-prefill after replica death shows up
        in the merged timeline under the ORIGINAL trace_id with
        ``migration >= 1``."""
        sp = None
        if sess.trace is not None:
            sp = obs.span(
                "disagg.prefill_leg", ctx=sess.trace,
                proc="router:%s" % self.name, tenant=sess.spec.name,
                plen=int(prompt.shape[0]), migration=sess.migration)
            sp.__enter__()
        try:
            handoff = self._prefill_leg_inner(
                sess, prompt, sp.ctx if sp is not None else None)
        except BaseException as e:
            if sp is not None:
                sp.__exit__(type(e), e, e.__traceback__)
            raise
        if sp is not None:
            sp.__exit__(None, None, None)
        return handoff

    def _prefill_leg_inner(self, sess, prompt, tctx):
        deadline = time.monotonic() + self.request_timeout_s
        tried_all_shed = 0.01
        while True:
            with self._lock:
                if self._closed:
                    raise EngineClosedError(
                        "disagg router %r stopped" % self.name)
                candidates = sorted(
                    self._prefill.values(),
                    key=lambda r: r.engine.queue_depth())
            if not candidates:
                raise NoReplicasError(
                    "no live prefill replicas for %r" % self.name)
            last_err = None
            for rep in candidates:
                try:
                    ticket = rep.engine.submit(
                        prompt, priority=sess.priority,
                        tenant=sess.spec.name,
                        deadline_ms=sess.deadline_ms,
                        trace_ctx=tctx)
                    handoff = ticket.result(self.request_timeout_s)
                    ttft_ms = 1000 * (time.monotonic()
                                      - ticket.t_submit)
                    if (sess.spec.ttft_slo_ms is not None
                            and ttft_ms > sess.spec.ttft_slo_ms):
                        obs.inc("serving.disagg.slo_miss_ttft")
                    return handoff
                except ShedError as e:
                    last_err = e
                    continue
                except (EngineClosedError, TimeoutError) as e:
                    last_err = e
                    self._mark_dead(rep.rid)
                    continue
            if time.monotonic() > deadline:
                raise last_err or NoReplicasError(
                    "every prefill replica shed for %r" % self.name)
            time.sleep(tried_all_shed)
            tried_all_shed = min(0.2, tried_all_shed * 2)

    def _decode_leg(self, sess, handoff):
        """Adopt the handoff on a decode replica (fewest live sessions
        — session affinity is set HERE, once) and pump its tokens into
        the router-level stream until the sequence finishes. Raises
        :class:`_ReplicaLost` if the replica dies underneath."""
        remaining = sess.max_new - len(sess.handle.so_far())
        hsp = None
        if sess.trace is not None:
            # the handoff span bridges the two processes: it parents to
            # the prefill-side span that encoded the KV (carried on the
            # handoff itself) so the merged timeline draws a flow arrow
            # prefill -> router -> decode under one trace_id
            hctx = getattr(handoff, "trace", None) or sess.trace
            hsp = obs.span(
                "disagg.handoff", ctx=hctx,
                proc="router:%s" % self.name,
                wire_dtype=handoff.wire_dtype,
                wire_bytes=handoff.wire_bytes(), plen=handoff.plen,
                migration=sess.migration)
            hsp.__enter__()
        try:
            rep, inner = self._adopt_on_decode(
                sess, handoff, remaining,
                hsp.ctx if hsp is not None else None)
        except BaseException as e:
            if hsp is not None:
                hsp.__exit__(type(e), e, e.__traceback__)
            raise
        if hsp is not None:
            hsp.__exit__(None, None, None)
        rid = rep.rid
        sess.rid = rid
        with self._lock:
            self._sessions[rid].add(sess.handle)
        obs.set_gauge("serving.disagg.decode_sessions.%d" % rid,
                      len(self._sessions[rid]))
        slo_s = (sess.spec.per_token_slo_ms / 1000.0
                 if sess.spec.per_token_slo_ms is not None else None)
        t_prev = time.monotonic()
        try:
            for tok in inner.tokens(timeout=self.request_timeout_s):
                if sess.handle.cancelled:
                    inner.cancel()
                now = time.monotonic()
                gap = now - t_prev
                t_prev = now
                obs.observe("serving.disagg.per_token_seconds", gap)
                obs.observe("serving.disagg.per_token_seconds.%s"
                            % sess.spec.name, gap)
                if slo_s is not None and gap > slo_s:
                    obs.inc("serving.disagg.slo_miss_per_token")
                sess.handle._emit(int(tok))
            if inner.finish_reason == "error":
                raise _ReplicaLost(rid, inner._error)
            sess.handle._finish(inner.finish_reason or "length")
        except IntegrityError as e:
            # corrupted handoff or an SDC-withheld step: the replica is
            # healthy — route through the migration path (re-prefill
            # from prompt + delivered) instead of failing the stream
            raise _ReplicaLost(rid, e)
        except (EngineClosedError, TimeoutError) as e:
            self._mark_dead(rid)
            raise _ReplicaLost(rid, e)
        finally:
            with self._lock:
                self._sessions[rid].discard(sess.handle)
            obs.set_gauge("serving.disagg.decode_sessions.%d" % rid,
                          len(self._sessions[rid]))

    def _adopt_on_decode(self, sess, handoff, remaining, tctx):
        """Place the handoff on the fewest-sessions live decode
        replica, failing over on shed/dead ones; returns
        ``(replica, inner_stream)``."""
        deadline = time.monotonic() + self.request_timeout_s
        backoff = 0.01
        while True:
            with self._lock:
                if self._closed:
                    raise EngineClosedError(
                        "disagg router %r stopped" % self.name)
                candidates = sorted(
                    self._decode.values(),
                    key=lambda r: len(self._sessions[r.rid]))
            if not candidates:
                raise NoReplicasError(
                    "no live decode replicas for %r" % self.name)
            inner = None
            lost = None
            for rep in candidates:
                try:
                    inner = rep.engine.submit_prefilled(
                        handoff, max_new=remaining, eos_id=sess.eos_id,
                        tenant=sess.spec.name, priority=sess.priority,
                        trace_ctx=tctx)
                    break
                except ShedError:
                    continue
                except EngineClosedError as e:
                    lost = e
                    self._mark_dead(rep.rid)
                    continue
            if inner is not None:
                return rep, inner
            if time.monotonic() > deadline:
                raise lost or ShedError(
                    "every decode replica shed for %r" % self.name,
                    model=self.name,
                    retry_after=self.retry_after_hint())
            if _conc._on:
                _conc.note_blocking("time.sleep(backoff)")
            time.sleep(backoff)
            backoff = min(0.2, backoff * 2)

    # -- health / membership ---------------------------------------------
    def start_health(self):
        if self._health is None or not self._health.is_alive():
            self._health_stop.clear()
            self._health = threading.Thread(
                target=self._health_loop, daemon=True,
                name="disagg-health-%s" % self.name)
            _conc.track_thread(self._health, self._owner)
            self._health.start()
        return self

    def _health_loop(self):
        while not self._health_stop.wait(self._health_interval):
            try:
                self._health_tick()
            except Exception as e:  # noqa: BLE001 — the loop must survive
                obs.event("router_health_error", source="serving",
                          model=self.name,
                          error="%s: %s" % (type(e).__name__, e))

    def _health_tick(self):
        with self._lock:
            replicas = dict(self._prefill)
            replicas.update(self._decode)
        if not replicas:
            return
        members = set(replicas)
        for rid in self.monitor.dead_peers(members=members) & members:
            beater = getattr(replicas[rid], "_beater", None)
            stop = getattr(replicas[rid], "_beat_stop", None)
            if (beater is not None and beater.is_alive()
                    and stop is not None and not stop.is_set()):
                # in-process ground truth beats the heartbeat: the
                # beater thread exists and was not told to stop, so the
                # silence is scheduler starvation under load (GIL
                # contention), not death — killing a healthy replica
                # here would cascade migrations. kill() sets _beat_stop
                # first, so real kill drills still classify promptly.
                continue
            self._mark_dead(rid)

    def _mark_dead(self, rid):
        with self._lock:
            replica = self._prefill.pop(rid, None)
            kind = "prefill"
            if replica is None:
                replica = self._decode.pop(rid, None)
                kind = "decode"
            if replica is None:
                return
            self._dead[rid] = replica
            n_pre, n_dec = len(self._prefill), len(self._decode)
            orphans = len(self._sessions.get(rid, ()))
        self._bump("replica_dead")
        obs.inc("serving.disagg.replica_dead")
        obs.set_gauge("serving.disagg.prefill_live", n_pre)
        obs.set_gauge("serving.disagg.decode_live", n_dec)
        obs.event("replica_dead", source="serving", model=self.name,
                  replica=rid, phase=kind, sessions=orphans,
                  prefill_live=n_pre, decode_live=n_dec)
        # ensure the dead engine's streams fail fast so every orphaned
        # pump wakes up and migrates (kill() already did this when the
        # death was a simulated crash; an observed silence may not have)
        try:
            replica.engine.stop(drain=False, timeout=0.2)
        except BaseException:  # noqa: BLE001 — already dead is fine
            pass

    def kill_replica(self, rid):
        """Chaos helper: SIGKILL-equivalent on one replica (beacons go
        silent, its work fails, sessions migrate)."""
        with self._lock:
            replica = self._prefill.get(rid) or self._decode.get(rid)
        if replica is None:
            raise KeyError("no live replica %r" % (rid,))
        replica.kill()
        self._mark_dead(rid)

    # -- SDC sentinel ----------------------------------------------------
    def attach_sentinel(self, sentinel):
        """Arm sampled step-replay SDC checking on every decode
        replica and register each replica's replay callable for the
        sentinel's cross-replica vote (see
        :mod:`paddle_tpu.integrity.sentinel`). The autopilot drains
        the sentinel's confirmed verdicts into ``quarantine_replica``
        actions."""
        with self._lock:
            self._sentinel = sentinel
            replicas = dict(self._decode)
        for rid, rep in replicas.items():
            rep.engine.attach_sentinel(sentinel, replica=rid)
        return sentinel

    def quarantine_replica(self, rid):
        """Integrity remediation: pull a confirmed-lying decode
        replica out of rotation. Mechanically a kill (its streams fail
        fast and migrate — regenerated tokens are bit-exact, so the
        client never sees the corruption), but counted and evented as
        a quarantine so the fleet ledger distinguishes 'died' from
        'caught lying'."""
        with self._lock:
            if rid not in self._prefill and rid not in self._decode:
                raise KeyError("no live replica %r" % (rid,))
            sentinel = self._sentinel
        if sentinel is not None:
            sentinel.unregister(rid)
        self._bump("quarantined")
        obs.inc("integrity.replicas_quarantined")
        obs.event("replica_quarantined", source="integrity",
                  model=self.name, replica=rid)
        self.kill_replica(rid)

    # -- introspection / lifecycle ---------------------------------------
    def _bump(self, key, n=1):
        with self._lock:
            self._counters[key] += n

    def warmup(self, check_hbm=True):
        report = []
        with self._lock:
            pool = list(self._prefill.values()) + \
                list(self._decode.values())
        for rep in pool:
            if rep.kind == "decode":
                report += rep.engine.warmup(check_hbm=check_hbm)
            else:
                report += rep.engine.warmup()
        return report

    def stats(self):
        with self._lock:
            pool = (list(self._prefill.values())
                    + list(self._decode.values())
                    + list(self._dead.values()))
            out = collections.Counter()
            for rep in pool:
                try:
                    for k, v in rep.stats().items():
                        if isinstance(v, (int, float)):
                            out[k] += v
                except Exception:  # noqa: BLE001
                    continue
            out.update(self._counters)
            out["prefill_live"] = len(self._prefill)
            out["decode_live"] = len(self._decode)
            out["live_sessions"] = sum(
                len(s) for s in self._sessions.values())
        for k in ("sessions", "migrations", "failed_streams",
                  "replica_dead", "quarantined"):
            out.setdefault(k, 0)
        out["tenant_shed"] = sum(
            self.tenants.stats()["shed"].values())
        return dict(out)

    def reuse_info(self):
        """Fleet-wide KV-reuse snapshot: per-replica ``reuse_info()``
        docs (prefix pools on the prefill side, draft/pool/tier state
        on decode replicas) plus summed redundant-prefill economics —
        the ``reuse`` block ``/healthz`` shows for a published
        router."""
        with self._lock:
            pool = (list(self._prefill.values())
                    + list(self._decode.values()))
        replicas = {}
        computed = saved = 0
        for rep in pool:
            fn = getattr(rep.engine, "reuse_info", None)
            if not callable(fn):
                continue
            try:
                doc = fn()
            except Exception:  # noqa: BLE001 — health must not raise
                continue
            replicas[rep.rid] = doc
            computed += doc.get("prefill_rows_computed") or 0
            saved += doc.get("prefill_rows_saved") or 0
        return {
            "replicas": replicas,
            "prefill_rows_computed": computed,
            "prefill_rows_saved": saved,
            "prefill_rows_saved_pct": (
                100.0 * saved / float(saved + computed)
                if (saved + computed) else None),
        }

    # -- fleet metrics federation ----------------------------------------
    def fleet_metrics(self):
        """A :class:`~paddle_tpu.observability.FleetMetrics` aggregator
        fed from the heartbeat table — every replica's beacon carries
        its metrics doc, so this works identically for in-process
        replicas and store-backed worker processes."""
        fm = obs.FleetMetrics()
        fm.ingest_beacons(self.monitor.table())
        return fm

    def fleet_render_prom(self, style=None):
        """Prometheus exposition of the federated fleet view (what
        ``/metrics?scope=fleet`` serves): merged ``fleet.*`` series
        plus per-tenant SLO burn-rate gauges."""
        fm = self.fleet_metrics()
        out = fm.render_prom(style=style)
        try:
            obs.SLOMonitor(self.tenants).tick(publish=True)
            slo = "\n".join(
                ln for ln in obs.render_prom().splitlines()
                if "fleet_slo_burn" in ln)
            if slo:
                out += slo + "\n"
        except Exception:  # noqa: BLE001 — metrics must not 500
            pass
        return out

    def queue_depth(self):
        with self._lock:
            return sum(r.engine.queue_depth()
                       for r in list(self._prefill.values())
                       + list(self._decode.values()))

    def live_replicas(self):
        """``(prefill_rids, decode_rids)`` of the live fleet — a
        membership view that does not depend on heartbeat beacons
        (the quarantine leg's last-replica guard uses it)."""
        with self._lock:
            return sorted(self._prefill), sorted(self._decode)

    def decode_latencies(self):
        """{rid: beacon latency seconds} for the live decode fleet —
        each replica's inverse drain rate as last published on its
        heartbeat. The autopilot's degraded-replica signal: a replica
        whose latency departs its own baseline (and its peers') is the
        kill+migrate candidate."""
        with self._lock:
            rids = set(self._decode)
        return {rid: lat
                for rid, lat in self.monitor.latencies(
                    members=rids).items()
                if rid in rids}

    def drain_rate(self):
        rates = []
        with self._lock:
            pool = list(self._decode.values())
        for rep in pool:
            try:
                r = rep.engine.drain_rate()
            except Exception:  # noqa: BLE001
                r = None
            if r:
                rates.append(r)
        return sum(rates) if rates else None

    def retry_after_hint(self):
        rate = self.drain_rate()
        if not rate:
            return 1.0
        return min(60.0, max(1.0, (self.queue_depth() + 1) / rate))

    @property
    def closed(self):
        return self._closed

    def stop(self, drain=True, timeout=30.0):
        with self._lock:
            self._closed = True
            pumps = list(self._pumps)
        self._health_stop.set()
        if self._health is not None and self._health.is_alive():
            self._health.join(timeout=1.0)
        if drain:
            end = time.monotonic() + float(timeout)
            for p in pumps:
                p.join(timeout=max(0.05, end - time.monotonic()))
        with self._lock:
            pool = (list(self._prefill.values())
                    + list(self._decode.values()))
        for rep in pool:
            rep.stop(drain=drain, timeout=timeout)
        # pumps unwind once their replica streams fail/finish; the
        # grace window covers that unwind (including a migration
        # re-prefill dispatch caught mid-flight) before declaring a leak
        _conc.check_stopped(self._owner, grace=10.0)
        obs.event("engine_stop", source="serving", count=False,
                  model=self.name, engine="disagg", drained=bool(drain))


def disagg_fleet(cfg, scope, n_prefill=2, n_decode=2, slots=4,
                 cache_len=64, prompt_buckets=None, kv_dtype="fp32",
                 wire_dtype="int8", tenants=None, name="default",
                 store=None, config=None, eos_id=None,
                 default_max_new=32, queue_capacity=64,
                 request_timeout_s=120.0, warm=False, **router_kw):
    """Build a disaggregated fleet in-process: ``n_prefill`` prefill
    replicas + ``n_decode`` step-only decode replicas over one shared
    heartbeat store, fronted by a :class:`DisaggRouter`.

    ``kv_dtype="int8"`` makes the decode replicas int8-resident
    (~4x slots per HBM budget); ``wire_dtype`` picks the handoff codec
    ("int8" compresses ~3.9x, "fp32" is lossless — what bit-identity
    tests pin)."""
    store = store if store is not None else InMemoryStore()
    config = config or ElasticConfig(heartbeat_interval=0.05)
    prefills, decodes = [], []
    rid = 0
    for _ in range(int(n_prefill)):
        eng = PrefillEngine(
            cfg, scope, cache_len=cache_len,
            prompt_buckets=prompt_buckets,
            queue_capacity=queue_capacity, wire_dtype=wire_dtype,
            request_timeout_s=request_timeout_s,
            name="%s-pre%d" % (name, rid))
        prefills.append(DisaggReplica(rid, eng, store, name=name,
                                      config=config))
        rid += 1
    for _ in range(int(n_decode)):
        eng = DecodeEngine(
            cfg, scope, slots=slots, cache_len=cache_len,
            prompt_buckets=prompt_buckets, eos_id=eos_id,
            queue_capacity=queue_capacity,
            default_max_new=default_max_new,
            request_timeout_s=request_timeout_s,
            name="%s-dec%d" % (name, rid), kv_dtype=kv_dtype,
            role="decode")
        decodes.append(DisaggReplica(rid, eng, store, name=name,
                                     config=config))
        rid += 1
    router = DisaggRouter(
        prefills, decodes, store=store, name=name, config=config,
        tenants=tenants, request_timeout_s=request_timeout_s,
        **router_kw)
    if warm:
        router.warmup()
    return router
