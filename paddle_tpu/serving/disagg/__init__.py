"""Disaggregated prefill/decode serving (PR 12).

The :class:`~paddle_tpu.serving.decode.DecodeEngine` runs prefill and
step on the same device, so one long prompt stalls every live stream.
This package splits the two phases across the fleet machinery:

- :mod:`.kv_wire` — the serialized KV handoff (EQuARX int8
  block-scaled per (layer, row) with fp32 scales; ``fp32`` lossless
  mode) between the phases.
- :mod:`.prefill` — :class:`PrefillEngine`: bucketed-prefill-only
  replicas with a priority queue and a TTFT SLO.
- :mod:`.tenancy` — per-tenant priority classes, quotas, and SLO
  targets gating admission.
- :mod:`.router` — :class:`DisaggRouter`: session-affine routing over
  prefill + decode replicas, dead-replica migration via re-prefill
  (zero failed streams), and the :func:`disagg_fleet` builder.

The int8-**resident** slot cache lives in
``DecodeEngine(kv_dtype="int8")`` (``serving/decode.py``) — same codec,
applied to residency instead of transport.
"""
from .kv_wire import (
    KVHandoff, decode_kv, dequantize_rows, encode_kv,
    handoff_compression, handoff_wire_bytes, quantize_rows,
)
from .prefill import PrefillEngine, PrefillTicket
from .router import DisaggReplica, DisaggRouter, DisaggStream, disagg_fleet
from .tenancy import (
    PRIORITY_CLASSES, TenantSpec, TenantTable, resolve_priority,
)

__all__ = [
    "KVHandoff", "encode_kv", "decode_kv", "quantize_rows",
    "dequantize_rows", "handoff_wire_bytes", "handoff_compression",
    "PrefillEngine", "PrefillTicket",
    "DisaggReplica", "DisaggRouter", "DisaggStream", "disagg_fleet",
    "PRIORITY_CLASSES", "TenantSpec", "TenantTable", "resolve_priority",
]
