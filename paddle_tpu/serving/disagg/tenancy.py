"""Multi-tenant admission control for the disaggregated fleet.

A tenant is a traffic class, not a user: "interactive" chat sessions
that buy per-token p99, "batch" summarization that buys throughput.
Each :class:`TenantSpec` carries a **priority class** (0 = most
urgent — orders the prefill queue), **quotas** (max live sessions +
max queued per tenant: one tenant's burst cannot occupy every decode
slot), and **SLO targets** (TTFT for the prefill leg, per-token p99
for the decode leg) that the router scores observed latencies against.

Admission is quota-then-queue: :meth:`TenantTable.acquire` either
claims a live-session token or raises
:class:`~paddle_tpu.serving.engine.ShedError` (HTTP 429 upstream, with
the tenant named so a client tier can steer). Quota rejections are
per-tenant backpressure — the fleet may be idle while one tenant is at
its cap, which is the point.

Telemetry: ``serving.disagg.tenant_live.<tenant>`` gauges,
``serving.disagg.tenant_shed`` / ``tenant_sessions`` counters, and the
per-tenant SLO miss counters the router publishes
(``serving.disagg.slo_miss_ttft`` / ``slo_miss_per_token``).
"""
import threading

from ... import observability as obs
from ..engine import ShedError

__all__ = ["PRIORITY_CLASSES", "TenantSpec", "TenantTable",
           "resolve_priority"]

# named priority classes a request may carry instead of a raw integer
PRIORITY_CLASSES = {"interactive": 0, "standard": 1, "batch": 2}
MAX_PRIORITY = 2


def resolve_priority(priority, default=1):
    """Normalize a request's priority field: None -> the tenant's
    default, a named class -> its rank, an int 0..2 -> itself;
    anything else raises ``ValueError`` (HTTP 400 upstream)."""
    if priority is None:
        return int(default)
    if isinstance(priority, str):
        if priority not in PRIORITY_CLASSES:
            raise ValueError(
                "unknown priority class %r (known: %s)"
                % (priority, sorted(PRIORITY_CLASSES)))
        return PRIORITY_CLASSES[priority]
    if isinstance(priority, bool) or not isinstance(priority, int):
        raise ValueError(
            "priority must be an int 0..%d or one of %s, got %r"
            % (MAX_PRIORITY, sorted(PRIORITY_CLASSES), priority))
    if not 0 <= priority <= MAX_PRIORITY:
        raise ValueError(
            "priority %d out of range 0..%d" % (priority, MAX_PRIORITY))
    return priority


class TenantSpec:
    """One tenant's contract with the fleet."""

    __slots__ = ("name", "priority", "max_live", "max_queued",
                 "ttft_slo_ms", "per_token_slo_ms")

    def __init__(self, name, priority=1, max_live=None, max_queued=None,
                 ttft_slo_ms=None, per_token_slo_ms=None):
        self.name = str(name)
        self.priority = resolve_priority(priority)
        self.max_live = None if max_live is None else int(max_live)
        self.max_queued = None if max_queued is None else int(max_queued)
        self.ttft_slo_ms = (None if ttft_slo_ms is None
                            else float(ttft_slo_ms))
        self.per_token_slo_ms = (None if per_token_slo_ms is None
                                 else float(per_token_slo_ms))


class TenantTable:
    """name -> :class:`TenantSpec` with live-session accounting.

    ``allow_unknown=True`` (the default) folds unlisted tenants into a
    default spec instead of rejecting them — a fleet should degrade an
    anonymous tenant to the standard class, not 403 it."""

    def __init__(self, specs=(), default_spec=None, allow_unknown=True,
                 model="default"):
        self._specs = {s.name: s for s in specs}
        self.default_spec = default_spec or TenantSpec("default")
        self.allow_unknown = bool(allow_unknown)
        self.model = str(model)
        self._lock = threading.Lock()
        self._live = {}
        self._queued = {}
        self._shed = {}

    def specs(self):
        """Every configured spec plus the default (deduped by name) —
        the set the SLO monitor scores burn rates for."""
        out = {self.default_spec.name: self.default_spec}
        out.update(self._specs)
        return list(out.values())

    def resolve(self, tenant):
        """The spec governing `tenant` (None -> the default spec)."""
        if tenant is None:
            return self.default_spec
        tenant = str(tenant)
        spec = self._specs.get(tenant)
        if spec is not None:
            return spec
        if not self.allow_unknown:
            raise ValueError("unknown tenant %r" % tenant)
        return TenantSpec(tenant, priority=self.default_spec.priority,
                          max_live=self.default_spec.max_live,
                          max_queued=self.default_spec.max_queued,
                          ttft_slo_ms=self.default_spec.ttft_slo_ms,
                          per_token_slo_ms=(
                              self.default_spec.per_token_slo_ms))

    def reweight(self, tenant, priority=None, max_live=None,
                 max_queued=None):
        """Admission re-weighting: adjust one tenant's priority class
        and/or quotas in place (None = keep). New requests see the new
        weights immediately — live sessions are untouched. Unlisted
        tenants are materialized from the default spec first, so the
        autopilot can demote an anonymous burst. Returns the updated
        spec."""
        with self._lock:
            spec = self._specs.get(str(tenant))
            if spec is None:
                spec = self.resolve(tenant)
                self._specs[spec.name] = spec
            if priority is not None:
                spec.priority = resolve_priority(
                    min(int(priority), MAX_PRIORITY)
                    if isinstance(priority, int)
                    and not isinstance(priority, bool) else priority)
            if max_live is not None:
                spec.max_live = int(max_live)
            if max_queued is not None:
                spec.max_queued = int(max_queued)
        obs.event("tenant_reweight", source="serving", model=self.model,
                  tenant=spec.name, priority=spec.priority,
                  max_live=spec.max_live, max_queued=spec.max_queued)
        return spec

    # -- quota accounting ------------------------------------------------
    def acquire(self, tenant):
        """Claim one live-session token for `tenant`; raises
        :class:`ShedError` at the quota. Returns the resolved spec."""
        spec = self.resolve(tenant)
        with self._lock:
            live = self._live.get(spec.name, 0)
            if spec.max_live is not None and live >= spec.max_live:
                self._shed[spec.name] = self._shed.get(spec.name, 0) + 1
                shed = self._shed[spec.name]
        if spec.max_live is not None and live >= spec.max_live:
            obs.inc("serving.disagg.tenant_shed")
            obs.event("tenant_shed", source="serving", model=self.model,
                      tenant=spec.name, live=live, quota=spec.max_live,
                      total_shed=shed)
            raise ShedError(
                "tenant %r at its live-session quota (%d) on model %r"
                % (spec.name, spec.max_live, self.model),
                model=self.model)
        with self._lock:
            self._live[spec.name] = self._live.get(spec.name, 0) + 1
            live = self._live[spec.name]
        obs.inc("serving.disagg.tenant_sessions")
        obs.set_gauge("serving.disagg.tenant_live.%s" % spec.name, live)
        return spec

    def release(self, tenant):
        spec = self.resolve(tenant)
        with self._lock:
            live = max(0, self._live.get(spec.name, 0) - 1)
            self._live[spec.name] = live
        obs.set_gauge("serving.disagg.tenant_live.%s" % spec.name, live)

    def live(self, tenant=None):
        with self._lock:
            if tenant is not None:
                return self._live.get(str(tenant), 0)
            return dict(self._live)

    def stats(self):
        with self._lock:
            return {"live": dict(self._live), "shed": dict(self._shed)}
