"""PrefillEngine: the prompt half of a disaggregated decode fleet.

A prefill replica runs ONLY the bucketed prefill programs — no step
program, no slot buffers, no streaming. Its product is a
:class:`~paddle_tpu.serving.disagg.kv_wire.KVHandoff`: the prompt's KV
cache (int8 block-scaled per row on the wire by default) plus the
first greedy token, which a decode replica adopts via
``DecodeEngine.submit_prefilled``. Splitting the phases is what stops
a long prompt from stalling every live stream: the O(prompt²) prefill
burns a prefill replica's chip while the decode replicas keep
stepping.

Scheduling is a **priority queue**, not FIFO: requests carry the
tenant's priority class (0 = interactive first), ties break by arrival
order, and a queued request whose deadline lapses is shed before any
chip time is spent. TTFT is this engine's SLO: the queue-wait +
prefill time is observed as ``serving.disagg.prefill_ttft_seconds``
and scored against ``ttft_slo_ms`` (``serving.disagg.slo_miss_ttft``).

Admission mirrors the decode engine: a full queue fast-rejects with
:class:`~paddle_tpu.serving.engine.ShedError` carrying a Retry-After
from the observed drain rate.
"""
import collections
import contextlib
import heapq
import threading
import time

import numpy as np

from ... import observability as obs
from ...analysis import concurrency as _conc
from ...analysis import dataflow as _dataflow
from ..engine import DeadlineExceededError, EngineClosedError, ShedError
from . import kv_wire

__all__ = ["PrefillEngine", "PrefillTicket"]


class PrefillTicket:
    """Future-like handle for one queued prefill; ``result()`` blocks
    for the :class:`KVHandoff`."""

    def __init__(self, prompt_len, timeout_s):
        self.prompt_len = int(prompt_len)
        self.t_submit = time.monotonic()
        self._timeout_s = float(timeout_s)
        self._done = threading.Event()
        self._cancelled = threading.Event()
        self._result = None
        self._error = None

    @property
    def done(self):
        return self._done.is_set()

    @property
    def cancelled(self):
        return self._cancelled.is_set()

    def cancel(self):
        self._cancelled.set()

    def result(self, timeout=None):
        wait = self._timeout_s if timeout is None else float(timeout)
        if not self._done.wait(wait):
            raise TimeoutError(
                "prefill not done after %.1fs" % float(wait))
        if self._error is not None:
            raise self._error
        return self._result

    # -- engine surface --------------------------------------------------
    def _set(self, handoff):
        self._result = handoff
        self._done.set()

    def _fail(self, exc):
        self._error = exc
        self._done.set()


class _PrefillReq:
    __slots__ = ("prompt", "plen", "bucket", "priority", "tenant",
                 "deadline", "ticket", "wire_dtype", "trace", "t_wall")


class PrefillEngine:
    """Bucketed prefill-only engine producing serialized KV handoffs.

    ::

        pre = PrefillEngine(cfg, scope, cache_len=128, name="gpt-pre")
        handoff = pre.submit(prompt_ids, priority=0).result()
        stream = decode_engine.submit_prefilled(handoff, max_new=64)

    Shares the builder/param-snapshot conventions of ``DecodeEngine``:
    params are device_put once and shared by every bucket program."""

    engine_kind = "prefill"

    def __init__(self, cfg, scope, cache_len=64, prompt_buckets=None,
                 queue_capacity=64, name="prefill", wire_dtype="int8",
                 ttft_slo_ms=None, request_timeout_s=60.0,
                 auto_start=True, build_prefill=None, prefix_pool=None):
        import jax

        import paddle_tpu.fluid as fluid
        from ..decode import default_prompt_buckets
        from ...fluid.inference import Predictor

        if build_prefill is None:
            from ...models.gpt import build_gpt_prefill

            build_prefill = build_gpt_prefill
        self.cfg = cfg
        self.name = str(name)
        self.cache_len = int(cache_len)
        self.wire_dtype = str(wire_dtype)
        self._prefix_pool = prefix_pool
        self.ttft_slo_ms = (None if ttft_slo_ms is None
                            else float(ttft_slo_ms))
        self.request_timeout_s = float(request_timeout_s)
        if prompt_buckets is None:
            prompt_buckets = default_prompt_buckets(self.cache_len)
        self.prompt_buckets = tuple(sorted({int(b) for b in prompt_buckets}))
        if not self.prompt_buckets or self.prompt_buckets[0] < 1:
            raise ValueError("prompt_buckets must be positive ints")
        if self.prompt_buckets[-1] > self.cache_len:
            raise ValueError(
                "largest prompt bucket (%d) exceeds cache_len (%d)"
                % (self.prompt_buckets[-1], self.cache_len))

        prefill = {}
        for b in self.prompt_buckets:
            with fluid.program_guard(fluid.Program(), fluid.Program()):
                pv = build_prefill(cfg, b, self.cache_len)
                prefill[b] = (fluid.default_main_program(), pv)
        # a prefix pool turns this replica into a delta-prefill source:
        # pooled base rows + the suffix program cost only the unshared
        # tail of each prompt (same ladder widths as cold prefill)
        delta = {}
        if prefix_pool is not None:
            from ...models.gpt import build_gpt_prefill_delta

            for b in self.prompt_buckets:
                with fluid.program_guard(fluid.Program(), fluid.Program()):
                    dv = build_gpt_prefill_delta(cfg, b, self.cache_len)
                    delta[b] = (fluid.default_main_program(), dv)
        persist = {}
        for prog, _ in list(prefill.values()) + list(delta.values()):
            for v in prog.list_vars():
                if not getattr(v, "persistable", False):
                    continue
                if v.name in persist:
                    continue
                if v.name not in scope:
                    raise KeyError(
                        "param %r required by the prefill programs is "
                        "missing from the given scope" % v.name)
                persist[v.name] = jax.device_put(np.asarray(scope[v.name]))
        if _conc._on:
            _dataflow.note_capture(scope, persist,
                                   "prefill-engine %r" % self.name,
                                   snapshot=True)
        self._params = persist
        self._prefill_preds = {}
        for b, (prog, pv) in prefill.items():
            self._prefill_preds[b] = Predictor(
                prog, pv["feed_names"], pv["fetch_vars"], scope=persist)
        self._delta_preds = {}
        for b, (prog, dv) in delta.items():
            self._delta_preds[b] = Predictor(
                prog, dv["feed_names"], dv["fetch_vars"], scope=persist)
            self._delta_preds[b].ledger_tag = (
                "prefill.delta:%s" % self.name)

        self._capacity = int(queue_capacity)
        self._heap = []          # (priority, seq, req) — min-heap
        self._seq = 0
        # submit/stop coordination needs wait/notify — a Condition's
        # inner lock stays a plain threading primitive (the lock-order
        # recorder only wraps plain mutexes)
        self._cond = threading.Condition()
        self._closed = False
        self._abort = False
        self._stats_lock = _conc.named_lock("serving.prefill.stats")
        self._stats = collections.Counter()
        self._rate = collections.deque(maxlen=64)
        self._thread = None
        self._owner = _conc.owner_token("prefill-engine", self.name, self)
        # cost-model predicted prefill seconds per bucket, computed
        # lazily on the first TRACED request touching the bucket (the
        # static analysis costs ~ms; unsampled requests never pay it)
        self._cost_cache = {}
        if auto_start:
            self.start()

    # -- lifecycle -------------------------------------------------------
    def start(self):
        if self._closed:
            raise EngineClosedError("engine %r is closed" % self.name)
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._loop, daemon=True,
                name="prefill-dispatch-%s" % self.name)
            _conc.track_thread(self._thread, self._owner)
            self._thread.start()
        return self

    def stop(self, drain=True, timeout=30.0):
        """Stop admitting work; ``drain=False`` fails queued requests
        with :class:`EngineClosedError`. Idempotent."""
        with self._cond:
            self._closed = True
            if not drain:
                self._abort = True
            self._cond.notify_all()
        if self._thread is not None and self._thread.is_alive():
            self._thread.join(timeout=max(0.1, float(timeout)))
        with self._cond:
            leftovers = [req for _, _, req in self._heap]
            self._heap = []
        for req in leftovers:
            req.ticket._fail(EngineClosedError(
                "engine %r stopped before prefill" % self.name))
        # grace outlasts an in-flight jit compile on short-join stops;
        # the poll returns the instant the thread exits
        _conc.check_stopped(self._owner, grace=10.0)
        obs.event("engine_stop", source="serving", count=False,
                  model=self.name, engine="prefill", drained=bool(drain))

    # -- admission -------------------------------------------------------
    def _bucket_for(self, plen):
        for b in self.prompt_buckets:
            if b >= plen:
                return b
        return None

    def submit(self, prompt, priority=1, tenant=None, deadline_ms=None,
               wire_dtype=None, trace_ctx=None):
        """Enqueue one prefill; returns a :class:`PrefillTicket` whose
        ``result()`` is the :class:`KVHandoff`. Lower ``priority``
        numbers run first (ties FIFO). ``wire_dtype`` overrides the
        engine's handoff codec for this one request (e.g. ``"fp32"``
        for a lossless handoff out of an int8-wire fleet).
        ``trace_ctx`` (a sampled
        :class:`~paddle_tpu.observability.TraceContext`) makes the
        queue-wait and prefill-compute spans part of the request's
        distributed trace and rides the handoff to the decode side."""
        if self._closed:
            raise EngineClosedError(
                "engine %r is draining/stopped" % self.name)
        prompt = np.asarray(prompt, dtype=np.int64).reshape(-1)
        plen = int(prompt.shape[0])
        if plen < 1:
            raise ValueError("empty prompt")
        if prompt.min() < 0 or prompt.max() >= self.cfg.vocab:
            raise ValueError(
                "prompt token out of range [0, %d)" % self.cfg.vocab)
        bucket = self._bucket_for(plen)
        if bucket is None:
            raise ValueError(
                "prompt length %d exceeds the largest prompt bucket "
                "(%d)" % (plen, self.prompt_buckets[-1]))
        req = _PrefillReq()
        req.prompt = prompt
        req.plen = plen
        req.bucket = bucket
        req.priority = int(priority)
        req.tenant = tenant
        req.deadline = (time.monotonic() + float(deadline_ms) / 1000.0
                        if deadline_ms is not None else None)
        req.wire_dtype = (str(wire_dtype) if wire_dtype is not None
                          else self.wire_dtype)
        sampled = trace_ctx is not None and trace_ctx.sampled
        req.trace = trace_ctx if sampled else None
        req.t_wall = time.time() if sampled else None
        req.ticket = PrefillTicket(plen, self.request_timeout_s)
        with self._cond:
            if self._closed:
                raise EngineClosedError(
                    "engine %r is draining/stopped" % self.name)
            if len(self._heap) >= self._capacity:
                self._bump("shed")
                obs.event("shed", source="serving", model=self.name,
                          engine="prefill", prompt_len=plen,
                          queue_capacity=self._capacity)
                raise ShedError(
                    "prefill queue full (%d) for model %r — request "
                    "shed" % (self._capacity, self.name),
                    model=self.name,
                    retry_after=self.retry_after_hint())
            self._seq += 1
            heapq.heappush(self._heap, (req.priority, self._seq, req))
            depth = len(self._heap)
            self._cond.notify()
        self._bump("requests")
        obs.set_gauge("serving.queue_depth.%s" % self.name, depth)
        return req.ticket

    def prefill(self, prompt, priority=1, tenant=None, deadline_ms=None,
                timeout=None, wire_dtype=None):
        """Synchronous submit + wait; returns the handoff."""
        t = self.submit(prompt, priority=priority, tenant=tenant,
                        deadline_ms=deadline_ms, wire_dtype=wire_dtype)
        return t.result(
            timeout if timeout is not None else self.request_timeout_s)

    # -- dispatch --------------------------------------------------------
    def _loop(self):
        while True:
            with self._cond:
                while not self._heap and not self._closed:
                    self._cond.wait(0.05)
                if not self._heap:
                    if self._closed:
                        return
                    continue
                if self._abort:
                    return  # stop() fails the leftovers
                _, _, req = heapq.heappop(self._heap)
                obs.set_gauge("serving.queue_depth.%s" % self.name,
                              len(self._heap))
            if req.ticket.cancelled:
                self._bump("cancelled")
                req.ticket._fail(EngineClosedError("prefill cancelled"))
                continue
            now = time.monotonic()
            if req.deadline is not None and now > req.deadline:
                self._bump("deadline_miss")
                waited_ms = round(1000 * (now - req.ticket.t_submit), 3)
                obs.event("deadline_miss", source="serving",
                          model=self.name, engine="prefill",
                          waited_ms=waited_ms)
                req.ticket._fail(DeadlineExceededError(
                    "deadline expired after %s ms in prefill queue "
                    "(model %r)" % (waited_ms, self.name)))
                continue
            self._run_one(req)

    def _run_one(self, req):
        t0 = time.monotonic()
        ctx = req.trace
        sp_fields = None
        if ctx is not None:
            # the queue-wait span already finished (submit -> pop);
            # export it directly, then parent the compute span to it
            ctx = ctx.child()
            obs.export_span(
                "prefill.queue", ctx, req.t_wall,
                t0 - req.ticket.t_submit,
                {"proc": "prefill:%s" % self.name, "bucket": req.bucket,
                 "plen": req.plen, "tenant": req.tenant})
            sp_fields = {"proc": "prefill:%s" % self.name,
                         "bucket": req.bucket, "plen": req.plen}
            if req.tenant is not None:
                sp_fields["tenant"] = str(req.tenant)
            pred = self._predicted_s(req.bucket)
            if pred is not None:
                sp_fields["predicted_s"] = pred
        try:
            if _conc._on:
                _conc.note_blocking("device.dispatch")
            cm = (obs.span("disagg.prefill", ctx=ctx, **sp_fields)
                  if ctx is not None else contextlib.nullcontext())
            with cm as sp:
                tok, k1, v1 = self._compute_kv(req)
                handoff = kv_wire.encode_kv(
                    k1, v1, tok, req.plen, req.prompt,
                    wire_dtype=req.wire_dtype,
                    trace=getattr(sp, "ctx", None))
        except Exception as e:  # noqa: BLE001 — fail the request, not the loop
            self._bump("prefill_errors")
            obs.event("prefill_error", source="serving", model=self.name,
                      engine="prefill",
                      error="%s: %s" % (type(e).__name__, str(e)[:200]))
            req.ticket._fail(e)
            return
        now = time.monotonic()
        ttft = now - req.ticket.t_submit
        obs.observe("serving.disagg.prefill_ttft_seconds", ttft)
        if req.tenant is not None:
            obs.observe(
                "serving.disagg.prefill_ttft_seconds.%s" % req.tenant,
                ttft)
        obs.observe("serving.decode.prefill_seconds", now - t0)
        if (self.ttft_slo_ms is not None
                and ttft * 1000.0 > self.ttft_slo_ms):
            self._bump("slo_miss_ttft")
            obs.inc("serving.disagg.slo_miss_ttft")
        self._bump("prefills")
        obs.inc("serving.disagg.handoffs")
        obs.set_gauge("serving.disagg.handoff_bytes.%s" % self.name,
                      handoff.wire_bytes())
        with self._stats_lock:
            self._rate.append((now, 1))
        req.ticket._set(handoff)

    def _entry_fits(self, entry, req):
        """Same adoption contract as the decode engine: geometry match,
        a full hit knows its next token, a partial hit's suffix fits a
        delta bucket without the block write running off the cache."""
        if tuple(np.asarray(entry.k).shape) != (
                self.cfg.num_layers, self.cache_len, self.cfg.hidden):
            return False
        if entry.plen > req.plen:
            return False
        if entry.plen == req.plen:
            return entry.next_token is not None
        sbucket = self._bucket_for(req.plen - entry.plen)
        return (sbucket is not None
                and entry.plen + sbucket <= self.cache_len)

    def _compute_kv(self, req):
        """Produce ``(next_token, k, v)`` for one prompt by the
        cheapest route: pool full hit (zero dispatch), pool partial hit
        (delta-prefill of the suffix), or the cold bucket program.
        Cold and delta results are banked back into the pool so the
        next shared-prefix prompt adopts instead of recomputing."""
        entry = (self._prefix_pool.lookup(req.prompt)
                 if self._prefix_pool is not None else None)
        if entry is not None and self._entry_fits(entry, req):
            kd, vd = entry.dense()
            if entry.plen == req.plen:
                self._bump("prefix_full_hits")
                self._bump("prefill_rows_saved", entry.plen)
                return int(entry.next_token), kd, vd
            suffix = req.prompt[entry.plen:]
            slen = int(suffix.size)
            sbucket = self._bucket_for(slen)
            ids = np.zeros((1, sbucket), np.int64)
            ids[0, :slen] = suffix
            nxt, k1, v1 = self._delta_preds[sbucket].run(
                {"gpt_dpre_ids": ids,
                 "gpt_dpre_len": np.asarray([[slen]], np.int64),
                 "gpt_dpre_start": np.asarray([[entry.plen]], np.int64),
                 "gpt_dpre_k": kd[None], "gpt_dpre_v": vd[None]})
            tok = int(np.asarray(nxt)[0, 0])
            k1, v1 = np.asarray(k1)[0], np.asarray(v1)[0]
            self._bump("delta_prefills")
            self._bump("prefill_rows_computed", sbucket)
            self._bump("prefill_rows_saved", entry.plen)
            try:
                self._prefix_pool.put(req.prompt, k1, v1, next_token=tok)
            except Exception:  # noqa: BLE001 — caching is best-effort
                self._bump("prefix_insert_errors")
            return tok, k1, v1
        ids = np.zeros((1, req.bucket), np.int64)
        ids[0, :req.plen] = req.prompt
        nxt, k1, v1 = self._prefill_preds[req.bucket].run(
            {"gpt_prefill_ids": ids,
             "gpt_prefill_len": np.asarray([[req.plen]], np.int64)})
        tok = int(np.asarray(nxt)[0, 0])
        self._bump("prefill_rows_computed", req.bucket)
        if self._prefix_pool is not None:
            try:
                self._prefix_pool.put(req.prompt, np.asarray(k1),
                                      np.asarray(v1), next_token=tok)
            except Exception:  # noqa: BLE001 — caching is best-effort
                self._bump("prefix_insert_errors")
        return tok, k1, v1

    def _predicted_s(self, bucket):
        """Cost-model predicted seconds for one prefill of `bucket`,
        cached per bucket; None when the analyzer can't price it (the
        trace annotation is best-effort — never fail a request on it)."""
        if bucket in self._cost_cache:
            return self._cost_cache[bucket]
        val = None
        try:
            import jax

            from ...analysis import costs as _costs

            pred = _costs.predict_program(
                self._prefill_preds[bucket].program,
                feed_specs={
                    "gpt_prefill_ids": np.zeros((1, bucket), np.int64),
                    "gpt_prefill_len": np.ones((1, 1), np.int64)},
                is_test=True,
                device_kind=getattr(jax.devices()[0], "device_kind",
                                    None))
            val = pred.get("predicted_step_seconds")
        except Exception:  # noqa: BLE001 — annotation only
            val = None
        self._cost_cache[bucket] = val
        return val

    # -- warmup / introspection ------------------------------------------
    def warmup(self):
        """Pre-build every bucket program through the compile-cache
        disk tier; returns the per-program report."""
        report = []
        for b in self.prompt_buckets:
            source = self._prefill_preds[b].warm({
                "gpt_prefill_ids": np.zeros((1, b), np.int64),
                "gpt_prefill_len": np.ones((1, 1), np.int64)})
            report.append({"program": "prefill", "bucket": b,
                           "source": source})
        cache1 = (1, self.cfg.num_layers, self.cache_len,
                  self.cfg.hidden)
        for b in sorted(self._delta_preds):
            source = self._delta_preds[b].warm({
                "gpt_dpre_ids": np.zeros((1, b), np.int64),
                "gpt_dpre_len": np.ones((1, 1), np.int64),
                "gpt_dpre_start": np.zeros((1, 1), np.int64),
                "gpt_dpre_k": np.zeros(cache1, np.float32),
                "gpt_dpre_v": np.zeros(cache1, np.float32)})
            report.append({"program": "delta_prefill", "bucket": b,
                           "source": source})
        obs.event(
            "warmup", source="serving", count=False, model=self.name,
            engine="prefill", engines=len(report),
            compiled=sum(1 for r in report if r["source"] == "compile"),
            disk_warm=sum(1 for r in report if r["source"] == "disk"))
        return report

    def _bump(self, key, n=1):
        with self._stats_lock:
            self._stats[key] += n
        obs.inc("serving.disagg.prefill_%s" % key, n)

    def stats(self):
        with self._stats_lock:
            out = dict(self._stats)
        for k in ("requests", "prefills", "shed", "deadline_miss",
                  "cancelled", "prefill_errors", "slo_miss_ttft",
                  "prefix_full_hits", "delta_prefills",
                  "prefill_rows_computed", "prefill_rows_saved"):
            out.setdefault(k, 0)
        with self._cond:
            out["queued"] = len(self._heap)
        return out

    def reuse_info(self):
        """Prefix-pool reuse snapshot (``/healthz`` + router
        aggregation) — mirrors ``DecodeEngine.reuse_info``'s shape."""
        with self._stats_lock:
            st = dict(self._stats)
        computed = st.get("prefill_rows_computed", 0)
        saved = st.get("prefill_rows_saved", 0)
        return {
            "prefix_pool": (self._prefix_pool.stats()
                            if self._prefix_pool is not None else None),
            "prefill_rows_computed": computed,
            "prefill_rows_saved": saved,
            "prefill_rows_saved_pct": (
                100.0 * saved / float(saved + computed)
                if (saved + computed) else None),
        }

    def queue_depth(self):
        with self._cond:
            return len(self._heap)

    def drain_rate(self):
        now = time.monotonic()
        with self._stats_lock:
            pts = [(t, n) for t, n in self._rate if now - t < 30.0]
        if not pts:
            return None
        span = max(1e-3, now - min(t for t, _ in pts))
        return sum(n for _, n in pts) / span

    def retry_after_hint(self):
        rate = self.drain_rate()
        if not rate:
            return 1.0
        return min(60.0, max(1.0, (self.queue_depth() + 1) / rate))

    @property
    def closed(self):
        return self._closed
