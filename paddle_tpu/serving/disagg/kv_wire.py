"""Serialized KV handoff between prefill and decode replicas.

The disaggregated fleet splits one generation across two machines: a
prefill replica computes the prompt's KV cache and first token, then a
decode replica adopts that cache into a slot and steps. What crosses
the wire is a :class:`KVHandoff` — the EQuARX block-scaled int8 format
already trusted by the gradient-comms subsystem
(:mod:`paddle_tpu.parallel.comms.quantize`), applied per **(layer,
row)**: the block size IS the hidden width, so every cache row carries
its own fp32 scale and a small row next to a large one is not drowned
in the large row's scale. int8 payload + one fp32 scale per row cuts
the handoff ~3.9x vs fp32 (``handoff_wire_bytes``); ``wire_dtype=
"fp32"`` is the lossless escape hatch (bit-identical adoption — what
the migration bit-identity tests pin) and ``"fp8_e4m3"`` rides the
same gate as the comms wire.

Per-(layer, row) scales are also exactly the layout the int8-
**resident** decode cache uses (``DecodeEngine(kv_dtype="int8")``), so
an int8 handoff whose block equals the hidden width drops straight
into the resident buffers — encode once at prefill, never requantize
on adoption.

Quantization here is idempotent for untouched rows: a row decoded from
``(payload, scale)`` re-encodes to the SAME payload and scale (the max
|element| is exactly ``127 * scale``), which is what lets the int8-
resident step program requantize the whole cache every step without
compounding error on rows it did not write.

Every handoff is sealed with a content digest at encode time
(``KVHandoff.digest``, riding ``to_wire`` docs unchanged); the decode
engine verifies it before adoption, so a corrupted handoff fails the
*inner* stream and the router's migration path re-prefills — garbage
is never installed into a slot and ``failed_streams`` stays 0. The
``wire`` corruption fault site (``wire:at=1:corrupt=bitflip``) perturbs
the payload after sealing, which is the end-to-end drill.
"""
import hashlib

import numpy as np

from ...integrity.digest import IntegrityError
from ...parallel.comms import quantize as Q

__all__ = [
    "KVHandoff", "encode_kv", "encode_kv_q", "decode_kv",
    "quantize_rows", "dequantize_rows", "handoff_wire_bytes",
    "handoff_compression",
]


def quantize_rows(cache, wire_dtype="int8"):
    """Per-(…, row) block-scaled encode of a float cache whose LAST
    axis is the hidden width: block size = hidden, so scales get shape
    ``cache.shape[:-1] + (1,)`` (broadcast-ready). Returns numpy
    ``(payload, scales)``."""
    cache = np.asarray(cache, np.float32)
    hidden = int(cache.shape[-1])
    payload, scales = Q.quantize_blocks(
        cache.reshape(-1), block_size=hidden, wire_dtype=wire_dtype)
    return (np.asarray(payload).reshape(cache.shape),
            np.asarray(scales, np.float32).reshape(
                cache.shape[:-1] + (1,)))


def dequantize_rows(payload, scales):
    """Inverse of :func:`quantize_rows` (fp32 numpy)."""
    payload = np.asarray(payload)
    hidden = int(payload.shape[-1])
    flat = Q.dequantize_blocks(
        payload.reshape(-1), np.asarray(scales, np.float32).reshape(-1),
        block_size=hidden)
    return np.asarray(flat, np.float32).reshape(payload.shape)


class KVHandoff:
    """One prefilled sequence, ready for a decode replica to adopt.

    Fields: ``k``/``v`` payloads shaped (layers, cache_len, hidden) —
    int8 (or fp8) with per-row fp32 ``k_scales``/``v_scales`` shaped
    (layers, cache_len, 1), or raw fp32 with scales ``None`` —
    ``next_token`` (the greedy token the prefill emitted, the stream's
    first token), ``plen`` (cache rows already written), and the
    ``prompt`` itself (migration re-prefills from it).
    """

    __slots__ = ("k", "v", "k_scales", "v_scales", "next_token",
                 "plen", "prompt", "wire_dtype", "trace", "digest")

    def __init__(self, k, v, k_scales, v_scales, next_token, plen,
                 prompt, wire_dtype, trace=None, digest=None):
        self.k = k
        self.v = v
        self.k_scales = k_scales
        self.v_scales = v_scales
        self.next_token = int(next_token)
        self.plen = int(plen)
        self.prompt = np.asarray(prompt, np.int64).reshape(-1)
        self.wire_dtype = str(wire_dtype)
        # TraceContext of the prefill-side span that produced this
        # handoff — the decode replica's adopt span parents to it so
        # one trace_id spans both processes
        self.trace = trace
        # content digest stamped by the sender (seal()); None means an
        # unsealed (hand-built) handoff, which adopts unverified
        self.digest = digest

    @property
    def shape(self):
        return tuple(int(s) for s in self.k.shape)  # (L, T, H)

    # -- content integrity -----------------------------------------------
    def content_digest(self):
        """sha256 over the handoff's semantic content: geometry +
        scalars + prompt + payloads + scales, in a fixed order."""
        h = hashlib.sha256()
        h.update(("%s;%s;%d;%d;" % (self.wire_dtype, self.shape,
                                    self.next_token, self.plen)).encode())
        h.update(np.ascontiguousarray(self.prompt).tobytes())
        for a in (self.k, self.v, self.k_scales, self.v_scales):
            if a is None:
                h.update(b";none")
            else:
                a = np.ascontiguousarray(a)
                h.update(a.dtype.str.encode())
                h.update(a.tobytes())
        return "sha256:" + h.hexdigest()

    def seal(self):
        """Stamp the sender-side content digest; returns self."""
        self.digest = self.content_digest()
        return self

    def verify(self):
        """Raise :class:`IntegrityError` if the payload no longer
        matches the sealed digest. Unsealed handoffs pass (there is
        nothing to verify against)."""
        if self.digest is None:
            return
        got = self.content_digest()
        if got != self.digest:
            raise IntegrityError(
                "KV handoff digest mismatch (want %s got %s): "
                "%d-layer cache, plen=%d, next_token=%d — refusing "
                "to adopt" % (self.digest, got, self.shape[0],
                              self.plen, self.next_token),
                tensor="kv_cache", want=self.digest, got=got)

    def dense(self):
        """The fp32 ``(k, v)`` cache pair this handoff decodes to."""
        if self.wire_dtype == "fp32":
            return (np.asarray(self.k, np.float32),
                    np.asarray(self.v, np.float32))
        return (dequantize_rows(self.k, self.k_scales),
                dequantize_rows(self.v, self.v_scales))

    def wire_bytes(self):
        """Bytes this handoff puts on the wire (payloads + scales +
        the int64 prompt; the two scalars are noise)."""
        n = int(np.prod(self.shape))
        if self.wire_dtype == "fp32":
            payload = 2 * n * 4
        else:
            itemsize = Q.WIRE_DTYPES[self.wire_dtype][0]
            rows = int(np.prod(self.shape[:-1]))
            payload = 2 * (n * itemsize + rows * 4)
        return payload + self.prompt.size * 8

    # -- serialization ---------------------------------------------------
    def to_wire(self):
        """Flat dict of bytes + metadata — what a cross-process
        transport (FileStore namespace, socket frame) would ship."""
        doc = {
            "wire_dtype": self.wire_dtype,
            "shape": list(self.shape),
            "next_token": self.next_token,
            "plen": self.plen,
            "prompt": np.asarray(self.prompt).tobytes(),
            "k": np.ascontiguousarray(self.k).tobytes(),
            "v": np.ascontiguousarray(self.v).tobytes(),
        }
        if self.k_scales is not None:
            doc["k_scales"] = np.ascontiguousarray(
                self.k_scales, np.float32).tobytes()
            doc["v_scales"] = np.ascontiguousarray(
                self.v_scales, np.float32).tobytes()
        if self.trace is not None:
            doc["trace"] = self.trace.to_doc()
        if self.digest is not None:
            doc["digest"] = self.digest
        return doc

    @classmethod
    def from_wire(cls, doc):
        from ...observability.distributed import TraceContext

        shape = tuple(int(s) for s in doc["shape"])
        wire_dtype = doc["wire_dtype"]
        pdt = np.float32 if wire_dtype == "fp32" else np.int8
        k = np.frombuffer(doc["k"], pdt).reshape(shape)
        v = np.frombuffer(doc["v"], pdt).reshape(shape)
        ks = vs = None
        if "k_scales" in doc:
            sshape = shape[:-1] + (1,)
            ks = np.frombuffer(doc["k_scales"], np.float32).reshape(sshape)
            vs = np.frombuffer(doc["v_scales"], np.float32).reshape(sshape)
        return cls(k, v, ks, vs, doc["next_token"], doc["plen"],
                   np.frombuffer(doc["prompt"], np.int64), wire_dtype,
                   trace=TraceContext.from_doc(doc.get("trace")),
                   digest=doc.get("digest"))


def encode_kv(k, v, next_token, plen, prompt, wire_dtype="int8",
              trace=None):
    """Encode a prefilled slot cache pair (each (layers, cache_len,
    hidden) fp32 — a leading batch-of-1 axis is squeezed) into a
    :class:`KVHandoff`."""
    k = np.asarray(k, np.float32)
    v = np.asarray(v, np.float32)
    if k.ndim == 4:
        if k.shape[0] != 1:
            raise ValueError(
                "encode_kv wants one sequence, got batch %d" % k.shape[0])
        k, v = k[0], v[0]
    if wire_dtype == "fp32":
        h = KVHandoff(k, v, None, None, next_token, plen, prompt,
                      wire_dtype, trace=trace)
    else:
        kq, ks = quantize_rows(k, wire_dtype)
        vq, vs = quantize_rows(v, wire_dtype)
        h = KVHandoff(kq, vq, ks, vs, next_token, plen, prompt,
                      wire_dtype, trace=trace)
    h.seal()
    return _wire_fault(h)


def encode_kv_q(k, v, k_scales, v_scales, next_token, plen, prompt,
                wire_dtype="int8", trace=None):
    """Build a sealed :class:`KVHandoff` from ALREADY-quantized rows —
    an int8-**resident** engine's payload + per-row scale planes, each
    with an optional leading batch-of-1 axis. This is the session-
    hibernation path: the resident layout IS the wire layout (block =
    hidden width), so parking a slot costs a host copy and a digest,
    never a requantize — and re-adoption restores bit-identical
    payloads (the codec is idempotent on untouched rows)."""
    k = np.asarray(k)
    v = np.asarray(v)
    k_scales = np.asarray(k_scales, np.float32)
    v_scales = np.asarray(v_scales, np.float32)
    if k.ndim == 4:
        if k.shape[0] != 1:
            raise ValueError(
                "encode_kv_q wants one sequence, got batch %d"
                % k.shape[0])
        k, v = k[0], v[0]
        k_scales, v_scales = k_scales[0], v_scales[0]
    h = KVHandoff(k, v, k_scales, v_scales, next_token, plen, prompt,
                  wire_dtype, trace=trace)
    h.seal()
    return _wire_fault(h)


def _wire_fault(h):
    """The ``wire`` corruption fault site: perturb the sealed payload
    in transit (shape-preserving — the transport object must stay
    well-formed; the digest is what catches it on the decode side)."""
    from ...fluid.resilience import corrupt_array, fault_corrupt_mode

    mode = fault_corrupt_mode("wire")
    if mode is not None:
        h.k = corrupt_array(mode, h.k)
    return h


def decode_kv(handoff):
    """fp32 ``(k, v)`` pair of a handoff (convenience alias)."""
    return handoff.dense()


def handoff_wire_bytes(num_layers, cache_len, hidden,
                       wire_dtype="int8"):
    """Wire bytes for one cache PAIR of the given geometry (excluding
    the prompt — deterministic accounting for lint/bench)."""
    n = int(num_layers) * int(cache_len) * int(hidden)
    if wire_dtype == "fp32":
        return 2 * n * 4
    return 2 * Q.wire_bytes(n, block_size=int(hidden),
                            wire_dtype=wire_dtype)


def handoff_compression(num_layers, cache_len, hidden,
                        wire_dtype="int8"):
    """fp32 pair bytes over wire pair bytes — ~3.9x for int8 at the
    typical hidden widths (block = hidden)."""
    full = handoff_wire_bytes(num_layers, cache_len, hidden, "fp32")
    return full / float(
        handoff_wire_bytes(num_layers, cache_len, hidden, wire_dtype))
