"""ref import path python/paddle/reader; the decorators live in
reader_utils (thread-based designs documented there)."""
from .. import reader_utils as decorator  # noqa: F401  paddle.reader.decorator
from ..reader_utils import (  # noqa: F401
    ComposeNotAligned,
    buffered,
    cache,
    chain,
    compose,
    firstn,
    map_readers,
    multiprocess_reader,
    retry_reader,
    shuffle,
    xmap_readers,
)

__all__ = [
    "cache", "map_readers", "buffered", "compose", "chain", "shuffle",
    "ComposeNotAligned", "firstn", "xmap_readers", "multiprocess_reader",
    "retry_reader",
]
