"""ref import path python/paddle/reader/decorator.py; one shared
implementation in paddle_tpu/reader_utils.py."""
from ..reader_utils import (  # noqa: F401
    ComposeNotAligned,
    batch,
    buffered,
    cache,
    chain,
    compose,
    firstn,
    map_readers,
    multiprocess_reader,
    retry_reader,
    shuffle,
    xmap_readers,
)

__all__ = [
    "cache", "map_readers", "buffered", "compose", "chain", "shuffle",
    "ComposeNotAligned", "firstn", "xmap_readers", "multiprocess_reader",
    "retry_reader",
]
