"""Print a model config protobuf (ref: python/paddle/utils/show_pb.py).

The reference deserializes a paddle-v1 ``ModelConfig`` protobuf. This
framework's programs are plain python objects with a json serde — dump
those with ``fluid.transpiler.details.program_to_code(program)`` or
``print(program)`` instead; reading v1 protobufs would need the retired
proto definitions, so that path raises with this guidance.
"""
import sys

__all__ = ["show_pb"]


def show_pb(path):
    raise NotImplementedError(
        "show_pb reads retired paddle-v1 ModelConfig protobufs (%r). "
        "paddle_tpu Programs serialize to json — use "
        "fluid.transpiler.details.program_to_code(program) or "
        "program.to_string() for a readable dump." % (path,)
    )


if __name__ == "__main__":
    show_pb(sys.argv[1] if len(sys.argv) > 1 else None)
