"""Training-curve plotter (ref: python/paddle/utils/plot.py). The book
chapters call Ploter.append/plot each pass; plotting degrades to a
text log when matplotlib/display is unavailable (same spirit as the
reference's DISABLE_PLOT env check)."""
import os

__all__ = ["PlotData", "Ploter"]


class PlotData:
    def __init__(self):
        self.reset()

    def append(self, step, value):
        self.step.append(step)
        self.value.append(value)

    def reset(self):
        self.step = []
        self.value = []


class Ploter:
    def __init__(self, *args):
        self.__args__ = args
        self.__plot_data__ = {title: PlotData() for title in args}
        self.__disable_plot__ = os.environ.get("DISABLE_PLOT", "")

    def __plot_is_disabled__(self):
        return self.__disable_plot__ == "True"

    def append(self, title, step, value):
        if title not in self.__plot_data__:
            raise ValueError("no title %r in Ploter(%s)"
                             % (title, ", ".join(self.__args__)))
        self.__plot_data__[title].append(step, value)

    def _log_text(self):
        for title, data in self.__plot_data__.items():
            if data.step:
                print("%s: step %s value %s"
                      % (title, data.step[-1], data.value[-1]))

    def plot(self, path=None):
        if self.__plot_is_disabled__():
            return
        if path is None:
            # no file target and no interactive display here — log the
            # latest values instead of silently drawing an unseen figure
            self._log_text()
            return
        try:
            import matplotlib.pyplot as plt
        except Exception:  # noqa: BLE001 — plotless hosts log instead
            self._log_text()
            return
        # draw on an explicit figure: never touch the caller's backend,
        # current figure, or other open figures
        fig, ax = plt.subplots()
        titles = []
        for title, data in self.__plot_data__.items():
            if len(data.step) > 0:
                ax.plot(data.step, data.value, label=title)
                titles.append(title)
        ax.legend(titles, loc="upper left")
        fig.savefig(path)
        plt.close(fig)

    def reset(self):
        for data in self.__plot_data__.values():
            data.reset()
