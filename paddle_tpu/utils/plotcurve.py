"""Plot training/testing curves from paddle-style logs
(ref: python/paddle/utils/plotcurve.py — same CLI and log grammar).

Log lines look like ``... Batch=200 AvgCost=0.5 ... Eval: AvgCost=0.6``;
``plot_paddle_curve`` extracts each requested key's train ("pass"-line)
and test ("Eval"-line) series and renders them with matplotlib.
"""
import re
import sys

__all__ = ["plot_paddle_curve", "main"]


def _series(keys, lines):
    train = {k: [] for k in keys}
    test = {k: [] for k in keys}
    for line in lines:
        is_test = "Eval" in line or "Test" in line
        for k in keys:
            for m in re.finditer(r"%s[=:]\s*([0-9.eE+-]+)" % re.escape(k),
                                 line):
                try:
                    (test if is_test else train)[k].append(
                        float(m.group(1)))
                except ValueError:
                    pass
    return train, test


def plot_paddle_curve(keys, inputfile, outputfile, format="png",
                      show_fig=False):
    """Extract ``keys`` from the log stream and save the curve figure
    (ref plotcurve.py:62)."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    if not keys:
        keys = ["AvgCost"]
    lines = inputfile.readlines() if hasattr(inputfile, "readlines") \
        else list(inputfile)
    train, test = _series(keys, lines)
    if not any(train[k] or test[k] for k in keys):
        sys.stderr.write("No data to plot. Exiting!\n")
        return
    plt.figure()
    for k in keys:
        if train[k]:
            plt.plot(range(len(train[k])), train[k], label="train-" + k)
        if test[k]:
            plt.plot(range(len(test[k])), test[k], "--",
                     label="test-" + k)
    plt.xlabel("pass")
    plt.ylabel(", ".join(keys))
    plt.legend()
    plt.savefig(outputfile, format=format)
    if show_fig:
        plt.show()
    plt.close()


def main(argv):
    import argparse

    parser = argparse.ArgumentParser(
        description="Plot training and testing curves from paddle log "
                    "file.")
    parser.add_argument("key", nargs="*", help="keys of scores to plot, "
                        "the default will be AvgCost")
    parser.add_argument("-i", "--input", default="-",
                        help="input filename of paddle log")
    parser.add_argument("-o", "--output", required=True,
                        help="output filename of figure")
    parser.add_argument("--format", default="png",
                        help="figure format(png|pdf|ps|eps|svg)")
    args = parser.parse_args(argv)
    fin = sys.stdin if args.input in ("-", "") else open(args.input)
    try:
        plot_paddle_curve(args.key or ["AvgCost"], fin, args.output,
                          args.format)
    finally:
        if fin is not sys.stdin:
            fin.close()


if __name__ == "__main__":
    main(sys.argv[1:])
