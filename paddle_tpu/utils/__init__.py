"""paddle.utils (ref: python/paddle/utils) — the pieces the book
chapters and detection pipelines actually use: Ploter (training-curve
logging) and image_util (numpy image preprocessing)."""
from . import plot  # noqa: F401
from . import image_util  # noqa: F401
from .plot import Ploter, PlotData  # noqa: F401

__all__ = ["plot", "image_util", "Ploter", "PlotData"]
