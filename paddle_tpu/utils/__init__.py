"""paddle.utils (ref: python/paddle/utils): Ploter (training-curve
logging), image_util (numpy image preprocessing), plus the legacy
preprocessing/conversion modules (real where the behavior survives,
loud raises where they target retired v1 formats — see each module)."""
from . import plot  # noqa: F401
from . import image_util  # noqa: F401
from . import plotcurve  # noqa: F401
from . import preprocess_util  # noqa: F401
from . import preprocess_img  # noqa: F401
from . import show_pb  # noqa: F401
from . import torch2paddle  # noqa: F401
from .plot import Ploter, PlotData  # noqa: F401

__all__ = ["plot", "image_util", "Ploter", "PlotData", "plotcurve",
           "preprocess_util", "preprocess_img", "show_pb",
           "torch2paddle"]
