"""Convert lua-torch .t7 model files (ref: python/paddle/utils/
torch2paddle.py — torchfile-based weight import into the v1 parameter
format).

Both ends of that pipeline are retired (lua-torch sources, paddle-v1
parameter files). For PyTorch interop, load the state_dict with torch
(installed in this image) and assign arrays into the scope::

    sd = torch.load("model.pt", map_location="cpu")
    for name, tensor in sd.items():
        fluid.global_scope().update(mapped_name(name), tensor.numpy())

The legacy entry points below raise with this guidance.
"""
__all__ = ["main"]

_MSG = (
    "torch2paddle converted lua-torch .t7 files into retired paddle-v1 "
    "parameter files; neither format exists here. For PyTorch weights, "
    "torch.load the state_dict and write arrays into "
    "fluid.global_scope() (see module docstring)."
)


def t7_to_paddle(*args, **kwargs):
    raise NotImplementedError(_MSG)


def main(*args, **kwargs):
    raise NotImplementedError(_MSG)
