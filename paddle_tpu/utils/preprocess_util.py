"""Dataset preprocessing helpers
(ref: python/paddle/utils/preprocess_util.py — file listing, label sets,
grouped shuffling). The generic pieces are implemented for real; the
paddle-v1 binary "batch" pickling (create_batches) belongs to the
retired v1 trainer format and raises with the modern path.
"""
import os
import pickle
import random

__all__ = [
    "save_file", "save_list", "exclude_pattern", "list_dirs",
    "list_images", "list_files", "get_label_set_from_dir", "Label",
    "Dataset", "DataBatcher", "DatasetCreater",
]


def save_file(data, filename):
    """Pickle ``data`` to ``filename`` (ref preprocess_util.py:22)."""
    with open(filename, "wb") as f:
        pickle.dump(data, f, protocol=pickle.HIGHEST_PROTOCOL)


def save_list(l, outfile):
    """Write one entry per line (ref :31)."""
    with open(outfile, "w") as f:
        for item in l:
            f.write(str(item) + "\n")


def exclude_pattern(f):
    """Hidden/underscore names are excluded (ref :40)."""
    return f.startswith(".") or f.startswith("_")


def list_dirs(path):
    """Immediate subdirectories, pattern-filtered (ref :48)."""
    return sorted(
        d for d in os.listdir(path)
        if os.path.isdir(os.path.join(path, d)) and not exclude_pattern(d)
    )


def list_images(path, exts=frozenset(("jpg", "png", "bmp", "jpeg"))):
    """Image files under ``path`` (ref :60)."""
    return sorted(
        f for f in os.listdir(path)
        if os.path.isfile(os.path.join(path, f))
        and not exclude_pattern(f)
        and f.rsplit(".", 1)[-1].lower() in exts
    )


def list_files(path):
    """All regular files under ``path`` (ref :71)."""
    return sorted(
        f for f in os.listdir(path)
        if os.path.isfile(os.path.join(path, f)) and not exclude_pattern(f)
    )


def get_label_set_from_dir(path):
    """label name -> id from subdirectory names (ref :81)."""
    return {name: i for i, name in enumerate(list_dirs(path))}


class Label(object):
    """ref :97."""

    def __init__(self, label, name):
        self.label = label
        self.name = name

    def __hash__(self):
        return hash(self.label)

    def __eq__(self, other):
        return isinstance(other, Label) and self.label == other.label

    def convert_to_paddle_format(self):
        return int(self.label)


class Dataset(object):
    """Grouped, shuffle-able sample collection (ref :123). ``data`` is a
    list of tuples, ``keys`` names each tuple slot."""

    def __init__(self, data, keys):
        self.data = list(data)
        self.keys = list(keys)

    def check_valid(self):
        for d in self.data:
            if len(d) != len(self.keys):
                return False
        return True

    def uniform_permute(self):
        random.shuffle(self.data)

    def permute_by_key(self, key_id, num_per_batch):
        """Shuffle groups that share data[key_id], then shuffle at batch
        granularity so each ``num_per_batch`` chunk mixes groups
        (ref :155's two-level permute)."""
        groups = {}
        for d in self.data:
            groups.setdefault(d[key_id], []).append(d)
        keys = list(groups)
        random.shuffle(keys)
        flat = [d for k in keys for d in groups[k]]
        if num_per_batch:
            chunks = [flat[i:i + num_per_batch]
                      for i in range(0, len(flat), num_per_batch)]
            random.shuffle(chunks)
            flat = [d for c in chunks for d in c]
        self.data = flat

    permute = permute_by_key


class DataBatcher(object):
    """ref :199 — emits paddle-v1 binary batch files; retired format."""

    def __init__(self, train_data, test_data, label_set):
        self.train_data = train_data
        self.test_data = test_data
        self.label_set = label_set

    def create_batches_and_list(self, *args, **kwargs):
        raise NotImplementedError(
            "DataBatcher writes the retired paddle-v1 binary batch "
            "format; feed samples through fluid.dataset "
            "(InMemoryDataset MultiSlot shards) or a DataLoader "
            "generator instead"
        )

    create_batches = create_batches_and_list


class DatasetCreater(object):
    """ref :264 — directory-walking batch creator; same retired format."""

    def __init__(self, data_path):
        self.data_path = data_path
        self.train_dir_name = "train"
        self.test_dir_name = "test"
        self.batch_dir_name = "batches"

    def create_dataset(self, *args, **kwargs):
        raise NotImplementedError(
            "DatasetCreater targets the retired paddle-v1 batch format; "
            "use fluid.dataset / DataLoader pipelines instead"
        )
