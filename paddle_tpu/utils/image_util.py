"""Numpy image preprocessing helpers (behavioral parity target:
python/paddle/utils/image_util.py) — short-side resize, flips, padded
center/random crops, 10-crop oversampling, and the channel/mean
transformer used by the classic image pipelines.

Written as vectorized numpy over an (N, H, W, C) batch axis where the
operation allows it; single images are the N=1 case.
"""
import numpy as np

__all__ = [
    "resize_image", "flip", "crop_img", "preprocess_img", "load_image",
    "oversample", "ImageTransformer",
]


def resize_image(img, target_size):
    """Scale a PIL image so its SHORT side equals target_size, keeping
    aspect ratio."""
    w, h = img.size
    scale = target_size / min(w, h)
    return img.resize((round(w * scale), round(h * scale)))


def flip(im):
    """Mirror the width axis of a (C, H, W) or (H, W) array."""
    return np.flip(im, axis=-1)


def _pad_to_square_min(im, size, spatial_axes):
    """Zero-pad so every spatial axis is at least `size`."""
    pads = [(0, 0)] * im.ndim
    for ax in spatial_axes:
        short = max(size - im.shape[ax], 0)
        pads[ax] = (short // 2, short - short // 2)
    if any(p != (0, 0) for p in pads):
        im = np.pad(im, pads)
    return im


def crop_img(im, inner_size, color=True, test=True):
    """Crop to inner_size x inner_size: centered when `test`, else a
    uniformly random window plus a coin-flip mirror. Images smaller than
    the crop are zero-padded to fit first. Layout: (C, H, W) when color,
    (H, W) otherwise."""
    im = np.asarray(im, dtype="float32")
    spatial = (-2, -1) if color else (0, 1)
    im = _pad_to_square_min(im, inner_size, spatial)
    room_y = im.shape[spatial[0]] - inner_size
    room_x = im.shape[spatial[1]] - inner_size
    if test:
        y0, x0 = room_y // 2, room_x // 2
    else:
        y0 = np.random.randint(room_y + 1)
        x0 = np.random.randint(room_x + 1)
    window = im[..., y0:y0 + inner_size, x0:x0 + inner_size]
    if not test and np.random.randint(2) == 0:
        window = flip(window)
    return window


def preprocess_img(im, img_mean, crop_size, is_train, color=True):
    """Crop (random when training, center otherwise) then subtract the
    pixel mean."""
    return crop_img(im, crop_size, color, test=not is_train) - img_mean


def load_image(img_path, is_color=True):
    """Read an image file into a PIL image (RGB or grayscale)."""
    from PIL import Image

    with Image.open(img_path) as f:
        f.load()
        return f.convert("RGB" if is_color else "L")


def oversample(img, crop_dims):
    """Classic 10-crop TTA: four corners + center, each mirrored.

    img: sequence of (H, W, C) arrays sharing one shape.
    Returns (10 * len(img), ch, cw, C), ordered per image as the five
    crops followed by their mirrors.
    """
    batch = np.stack([np.asarray(i, dtype="float32") for i in img])
    _, H, W, _ = batch.shape
    ch, cw = int(crop_dims[0]), int(crop_dims[1])
    # window origins: corners then center (int floor of the centered box)
    ys = [0, 0, H - ch, H - ch, int(H / 2.0 - ch / 2.0)]
    xs = [0, W - cw, 0, W - cw, int(W / 2.0 - cw / 2.0)]
    views = np.stack(
        [batch[:, y:y + ch, x:x + cw, :] for y, x in zip(ys, xs)], axis=1
    )                                        # (N, 5, ch, cw, C)
    both = np.concatenate([views, views[:, :, :, ::-1, :]], axis=1)
    return both.reshape(-1, ch, cw, batch.shape[-1])


class ImageTransformer:
    """Axis-order / channel-order / mean normalization applied in that
    sequence; mean given per channel is broadcast over H, W."""

    def __init__(self, transpose=None, channel_swap=None, mean=None,
                 is_color=True):
        self.is_color = is_color
        self.set_transpose(transpose)
        self.set_channel_swap(channel_swap)
        self.set_mean(mean)

    def _check3(self, order, what):
        if order is not None and self.is_color and len(order) != 3:
            raise ValueError("%s needs 3 entries for color images" % what)

    def set_transpose(self, order):
        self._check3(order, "transpose order")
        self.transpose = order

    def set_channel_swap(self, order):
        self._check3(order, "channel swap")
        self.channel_swap = order

    def set_mean(self, mean):
        if mean is not None:
            mean = np.asarray(mean)
            if mean.ndim == 1:  # per-channel -> broadcastable (C, 1, 1)
                mean = mean.reshape(-1, 1, 1)
        self.mean = mean

    def transformer(self, data):
        if self.transpose is not None:
            data = np.transpose(data, self.transpose)
        if self.channel_swap is not None:
            data = np.take(data, self.channel_swap, axis=0)
        if self.mean is not None:
            data = data - self.mean
        return data
