"""Numpy image preprocessing helpers (ref: python/paddle/utils/
image_util.py) — resize/flip/crop/oversample/mean-transform used by the
classic image pipelines. Pure numpy (PIL only for file IO)."""
import numpy as np

__all__ = [
    "resize_image", "flip", "crop_img", "preprocess_img", "load_image",
    "oversample", "ImageTransformer",
]


def resize_image(img, target_size):
    """Resize so the SHORT side equals target_size (ref image_util.py:20).
    img is a PIL image."""
    percent = target_size / float(min(img.size[0], img.size[1]))
    resized = (int(round(img.size[0] * percent)),
               int(round(img.size[1] * percent)))
    return img.resize(resized)


def flip(im):
    """Horizontal flip of a (C, H, W) or (H, W) array."""
    if im.ndim == 3:
        return im[:, :, ::-1]
    return im[:, ::-1]


def crop_img(im, inner_size, color=True, test=True):
    """Center (test) or random crop to inner_size (ref image_util.py:45);
    im is (C, H, W) when color else (H, W)."""
    im = im.astype("float32")
    if color:
        height, width = max(inner_size, im.shape[1]), max(
            inner_size, im.shape[2])
        padded_im = np.zeros((3, height, width), dtype=im.dtype)
        startY = (height - im.shape[1]) // 2
        startX = (width - im.shape[2]) // 2
        endY, endX = startY + im.shape[1], startX + im.shape[2]
        padded_im[:, startY:endY, startX:endX] = im
    else:
        height, width = max(inner_size, im.shape[0]), max(
            inner_size, im.shape[1])
        padded_im = np.zeros((height, width), dtype=im.dtype)
        startY = (height - im.shape[0]) // 2
        startX = (width - im.shape[1]) // 2
        endY, endX = startY + im.shape[0], startX + im.shape[1]
        padded_im[startY:endY, startX:endX] = im
    if test:
        startY = (height - inner_size) // 2
        startX = (width - inner_size) // 2
    else:
        startY = np.random.randint(0, height - inner_size + 1)
        startX = np.random.randint(0, width - inner_size + 1)
    endY, endX = startY + inner_size, startX + inner_size
    if color:
        pic = padded_im[:, startY:endY, startX:endX]
    else:
        pic = padded_im[startY:endY, startX:endX]
    if not test and np.random.randint(2) == 0:
        pic = flip(pic)
    return pic


def preprocess_img(im, img_mean, crop_size, is_train, color=True):
    """Crop + mean-subtract (ref image_util.py:96)."""
    im = im.astype("float32")
    test = not is_train
    pic = crop_img(im, crop_size, color, test)
    return pic - img_mean


def load_image(img_path, is_color=True):
    """Load an image file as a PIL image (ref image_util.py:133)."""
    from PIL import Image

    img = Image.open(img_path)
    img.load()
    return img.convert("RGB") if is_color else img.convert("L")


def oversample(img, crop_dims):
    """10-crop oversampling: 4 corners + center, mirrored
    (ref image_util.py:144). img: iterable of (H, W, C) arrays."""
    im_shape = np.array(img[0].shape)
    crop_dims = np.array(crop_dims)
    im_center = im_shape[:2] / 2.0

    h_indices = (0, im_shape[0] - crop_dims[0])
    w_indices = (0, im_shape[1] - crop_dims[1])
    crops_ix = np.empty((5, 4), dtype=int)
    curr = 0
    for i in h_indices:
        for j in w_indices:
            crops_ix[curr] = (i, j, i + crop_dims[0], j + crop_dims[1])
            curr += 1
    crops_ix[4] = np.tile(im_center, (1, 2)) + np.concatenate(
        [-crop_dims / 2.0, crop_dims / 2.0])
    crops_ix = np.tile(crops_ix, (2, 1))

    crops = np.empty(
        (10 * len(img), crop_dims[0], crop_dims[1], im_shape[-1]),
        dtype=np.float32)
    ix = 0
    for im in img:
        for crop in crops_ix:
            crops[ix] = im[crop[0]:crop[2], crop[1]:crop[3], :]
            ix += 1
        crops[ix - 5:ix] = crops[ix - 5:ix, :, ::-1, :]  # mirror
    return crops


class ImageTransformer:
    """Channel-order + mean transform (ref image_util.py:183)."""

    def __init__(self, transpose=None, channel_swap=None, mean=None,
                 is_color=True):
        self.is_color = is_color
        self.set_transpose(transpose)
        self.set_channel_swap(channel_swap)
        self.set_mean(mean)

    def set_transpose(self, order):
        if order is not None and self.is_color and len(order) != 3:
            raise ValueError("transpose order needs 3 dims for color")
        self.transpose = order

    def set_channel_swap(self, order):
        if order is not None and self.is_color and len(order) != 3:
            raise ValueError("channel swap needs 3 channels for color")
        self.channel_swap = order

    def set_mean(self, mean):
        if mean is not None:
            mean = np.array(mean)
            if mean.ndim == 1:
                mean = mean[:, np.newaxis, np.newaxis]
        self.mean = mean

    def transformer(self, data):
        if self.transpose is not None:
            data = data.transpose(self.transpose)
        if self.channel_swap is not None:
            data = data[self.channel_swap, :, :]
        if self.mean is not None:
            data -= self.mean
        return data
