"""Image dataset preprocessing (ref: python/paddle/utils/
preprocess_img.py — PIL resize + the v1 batch creator).

``resize_image`` (the generally useful piece) is real; the batch
creators target the retired paddle-v1 binary format and raise with the
modern pipeline (see preprocess_util.DataBatcher).
"""
import os

import numpy as np

from . import preprocess_util
from .image_util import crop_img

__all__ = ["resize_image", "DiskImage", "ImageClassificationDatasetCreater"]


def resize_image(img, target_size):
    """Shorter-edge resize to ``target_size`` keeping aspect ratio
    (ref preprocess_img.py:25)."""
    from PIL import Image

    percent = target_size / float(min(img.size[0], img.size[1]))
    resized_size = (int(round(img.size[0] * percent)),
                    int(round(img.size[1] * percent)))
    return img.resize(resized_size, Image.LANCZOS)


class DiskImage(object):
    """An image on disk, lazily loaded + resized (ref :43)."""

    def __init__(self, path, target_size):
        self.path = path
        self.target_size = target_size
        self.img = None

    def read_image(self):
        if self.img is None:
            from PIL import Image

            img = Image.open(self.path)
            if img.mode != "RGB":
                img = img.convert("RGB")
            self.img = resize_image(img, self.target_size)

    def convert_to_array(self):
        self.read_image()
        np_array = np.array(self.img)
        if len(np_array.shape) == 3:
            np_array = np.swapaxes(np_array, 1, 2)
            np_array = np.swapaxes(np_array, 0, 1)
        return np_array

    def convert_to_paddle_format(self):
        """CHW uint8 bytes, center-cropped square (ref :67)."""
        self.read_image()
        return crop_img(
            np.asarray(self.img), self.target_size, test=True
        ).tobytes()


class ImageClassificationDatasetCreater(preprocess_util.DatasetCreater):
    """ref :83 — walks label dirs and writes v1 batches; the walker is
    real (uses preprocess_util listings), the batch write raises."""

    def __init__(self, data_path, target_size, color=True):
        super().__init__(data_path)
        self.target_size = target_size
        self.color = color

    def create_dataset_from_dir(self, path):
        labels = preprocess_util.get_label_set_from_dir(path)
        data = []
        for name, label in labels.items():
            for img in preprocess_util.list_images(
                    os.path.join(path, name)):
                data.append((DiskImage(os.path.join(path, name, img),
                                       self.target_size),
                             preprocess_util.Label(label, name)))
        return preprocess_util.Dataset(data, ["image", "label"])

    create_dataset_from_list = preprocess_util.DatasetCreater.create_dataset
