"""paddle_tpu — a TPU-native deep-learning framework with the capabilities
of PaddlePaddle Fluid (reference: SunAhong1993/Paddle), built from scratch on
jax/XLA/pallas/pjit with a C++ host runtime.

Usage mirrors the reference::

    import paddle_tpu as paddle
    import paddle_tpu.fluid as fluid
"""
from . import reader  # noqa: F401  paddle.reader.* (real package)
# like the reference __init__: import the module, then rebind the name to
# the function — paddle.batch(...) calls it, import paddle_tpu.batch works
# (the parent attribute is only auto-set on the FIRST submodule import,
# which is this one)
from . import batch  # noqa: F401
batch = batch.batch
from . import observability  # noqa: F401  paddle.observability.* (hub)
from . import fluid  # noqa: F401
from . import serving  # noqa: F401  paddle.serving.* (online inference)
from . import dataset  # noqa: F401
from . import distributed  # noqa: F401
from . import compat  # noqa: F401
from . import sysconfig  # noqa: F401
from . import utils  # noqa: F401
# ref paddle/__init__.py runs the Windows scipy-DLL diagnosis at import
from .check_import_scipy import check_import_scipy
import os as _os

check_import_scipy(_os.name)
del _os

__version__ = "0.1.0"

# paddle.* conveniences of the 1.5/1.6 era
enable_dygraph = fluid.dygraph.enable_dygraph
disable_dygraph = fluid.dygraph.disable_dygraph


def version():
    return __version__
