"""py2/3 compat helpers (ref: python/paddle/compat.py). Python 3 only
here, so these are thin but behavior-matching."""
import math

__all__ = [
    "long_type", "to_text", "to_bytes", "round", "floor_division",
    "get_exception_message",
]

long_type = int


def _map(obj, fn, encoding, inplace):
    if obj is None:
        return obj
    if isinstance(obj, (list, set)):
        if inplace:
            items = [_map(o, fn, encoding, inplace) for o in obj]
            if isinstance(obj, list):
                obj[:] = items
                return obj
            obj.clear()
            obj.update(items)
            return obj
        return type(obj)(_map(o, fn, encoding, inplace) for o in obj)
    return fn(obj, encoding)


def to_text(obj, encoding="utf-8", inplace=False):
    """bytes/list/set -> str recursively (ref compat.py:36)."""
    def one(o, enc):
        if isinstance(o, bytes):
            return o.decode(enc)
        return str(o) if not isinstance(o, str) else o

    return _map(obj, one, encoding, inplace)


def to_bytes(obj, encoding="utf-8", inplace=False):
    """str/list/set -> bytes recursively (ref compat.py:120)."""
    def one(o, enc):
        if isinstance(o, str):
            return o.encode(enc)
        return bytes(o) if not isinstance(o, bytes) else o

    return _map(obj, one, encoding, inplace)


def round(x, d=0):
    """py2-style banker's-free rounding (ref compat.py:193)."""
    p = 10 ** d
    if x > 0:
        return float(math.floor((x * p) + 0.5)) / p
    if x < 0:
        return float(math.ceil((x * p) - 0.5)) / p
    return 0.0


def floor_division(x, y):
    return x // y


def get_exception_message(exc):
    return str(exc)
