"""Candidate enumeration: mesh factorizations x strategy variants.

The mesh leg comes from :func:`parallel.mesh.factorizations` over
(dp, tp, pp); the strategy leg mirrors exactly what ``Fleet._build``
accepts so every emitted plan is constructible:

- "gspmd" gradient sync composes with any mesh;
- ZeRO-1 (``sharding_degree``) needs a dp axis > 1 and gspmd sync;
- the explicit comms subsystem (bucketed fp32 / int8 block-scaled with
  backward overlap) is pure-dp only;
- AMP toggles independently of everything else.

Model-shape constraints prune meshes that cannot be realized: tp must
divide some dimension of every 2D+ trainable parameter (a column/row
shard must land on whole tiles), and pp cannot exceed the number of
sliceable layers.
"""
from .plan import ParallelPlan

__all__ = ["enumerate_plans", "tp_compatible", "MAX_TP", "MAX_PP"]

# search bounds: tp/pp beyond these never win on the model sizes this
# framework targets and only bloat the candidate table
MAX_TP = 16
MAX_PP = 8


def tp_compatible(tp, param_shapes):
    """tp is realizable when every >=2D parameter has at least one
    dimension divisible by tp (there is a whole-tile axis to shard)."""
    if tp <= 1:
        return True
    for shape in param_shapes or ():
        dims = [int(d) for d in shape if isinstance(d, int) and d > 0]
        if len(dims) < 2:
            continue
        if not any(d % tp == 0 for d in dims):
            return False
    return True


def enumerate_plans(n_devices, param_shapes=(), n_layers=None,
                    microbatches=8, amp_choices=(False, True),
                    max_tp=MAX_TP, max_pp=MAX_PP,
                    grad_bucket_bytes=4 << 20, grad_quantize_block=256):
    """All candidate :class:`ParallelPlan`s for ``n_devices``.

    ``param_shapes``: trainable-parameter shapes for the tp divisibility
    check. ``n_layers``: pipeline-sliceable layer count (pp <= this).
    ``microbatches``: the schedule depth pp plans amortize their bubble
    over. Deterministic emission order."""
    from ..parallel.mesh import factorizations

    plans = []
    seen = set()
    for mesh in factorizations(n_devices, axes=("dp", "tp", "pp")):
        tp = mesh.get("tp", 1)
        pp = mesh.get("pp", 1)
        dp = mesh.get("dp", 1)
        if tp > max_tp or not tp_compatible(tp, param_shapes):
            continue
        if pp > max_pp or (n_layers is not None and pp > max(1, n_layers)):
            continue
        mb = microbatches if pp > 1 else 1
        variants = [dict(grad_sync_mode="gspmd")]
        if dp > 1 and pp == 1:
            variants.append(dict(grad_sync_mode="gspmd",
                                 sharding_degree=dp))
        if dp > 1 and tp == 1 and pp == 1:
            # explicit comms sync is pure-dp (Fleet._build refuses the
            # tp/sp composition); fp32-bucketed and int8-quantized legs
            variants.append(dict(grad_sync_mode="comms",
                                 grad_quantize=False,
                                 grad_overlap=True))
            variants.append(dict(grad_sync_mode="comms",
                                 grad_quantize=True,
                                 grad_overlap=True))
        for var in variants:
            for amp in amp_choices:
                plan = ParallelPlan(
                    mesh=mesh, microbatches=mb, amp=amp,
                    grad_bucket_bytes=grad_bucket_bytes,
                    grad_quantize_block=grad_quantize_block, **var)
                if plan.name in seen:
                    continue
                seen.add(plan.name)
                plans.append(plan)
    return plans
