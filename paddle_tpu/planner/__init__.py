"""Auto-parallelism planner: cost-model-driven search over
mesh x DistributedStrategy x comms settings.

Closes the loop from "we can price a config" (``analysis/costs.py`` +
``analysis/memory.py``) to "we pick the config": enumerate every mesh
factorization of the device count crossed with the strategy knobs the
fleet exposes (gradient sync mode, int8 quantized comms, bucketed
overlap, ZeRO-1, AMP), price each candidate's compute / comm / bubble
legs under a :class:`~paddle_tpu.analysis.costs.DeviceProfile`, reject
what cannot fit HBM (op-attributed), and rank the rest by predicted
step seconds.

CLI: ``python -m paddle_tpu.analysis --plan --devices 256 --device
v5e`` prints the ranked table; ``--json-out`` writes a plan document
``DistributedStrategy.from_plan`` / ``bench.py``'s auto-tuned lane can
apply directly.
"""
from .plan import ParallelPlan, MESH_AXIS_ORDER
from .candidates import enumerate_plans, tp_compatible
from .pricing import (PricedPlan, ProgramBase, build_base, price_plan)
from .search import PlanSearchResult, plan_search, price_composition

__all__ = [
    "ParallelPlan", "MESH_AXIS_ORDER", "enumerate_plans",
    "tp_compatible", "PricedPlan", "ProgramBase", "build_base",
    "price_plan", "PlanSearchResult", "plan_search",
    "price_composition",
]
