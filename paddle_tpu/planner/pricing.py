"""Plan pricing: predicted step seconds + peak HBM for one candidate.

The analyzed program is treated as the GLOBAL batch of work — every
candidate is priced on "seconds to complete the same global step", so
predictions are comparable across meshes (a dp=8 plan runs 1/8 of the
batch per chip, a tp=8 plan runs 1/8 of every matmul; both divide the
single-chip roofline by 8 and differ in what they pay the wire).

Legs, all from the existing passes:

- compute: per-op roofline (``costs.op_costs``) summed, divided by the
  chip count, inflated by the GPipe bubble fraction
  ``(pp-1)/microbatches`` (``costs.pipeline_bubble_fraction``);
- dp gradient allreduce: ``costs.ring_allreduce_seconds`` over the
  fp32 (or int8 block-scaled — ``comms.quantize.compression_ratio``)
  payload, on ICI while the job fits one slice and on DCN past
  ``DeviceProfile.slice_chips`` (``costs.allreduce_bandwidth``),
  discounted by the bucketed backward-overlap ratio
  (``comms.bucketing.plan_buckets``);
- tp activation allreduce: per-layer output traffic over the tp group
  (Megatron-style — per-chip volume roughly constant in tp);
- pp boundary sends: stage-boundary activations, point-to-point;
- memory: ``memory.estimate`` under ``shard_divisors`` of the plan's
  mesh, with ZeRO-1 deducting the dp-sharded optimizer-state slice and
  AMP halving activation residency. Over-budget plans carry an
  op-attributed rejection instead of a rank.

AMP constants are deliberately coarse (bf16 matmul speedup, halved
activation traffic) — the planner needs ORDERING fidelity, not
absolute accuracy; the dryrun-zoo test asserts exactly that.
"""
from ..analysis import costs as costs_mod
from ..analysis import memory as memory_mod

__all__ = ["ProgramBase", "build_base", "price_plan", "PricedPlan",
           "AMP_COMPUTE_SPEEDUP", "AMP_BYTES_FACTOR",
           "AMP_ACT_MEM_FACTOR", "TP_BWD_COMM_MULT",
           "GSPMD_OVERLAP_RATIO"]

# bf16 matmul throughput over fp32 (MXU runs both, fp32 at half rate
# conservatively) and the HBM-traffic cut from half-width activations
AMP_COMPUTE_SPEEDUP = 1.5
AMP_BYTES_FACTOR = 0.6
# AMP halves activation residency at the liveness peak (params stay
# fp32 master copies)
AMP_ACT_MEM_FACTOR = 0.5
# tp comm volume: one output allreduce forward + two backward
TP_BWD_COMM_MULT = 3.0
# the XLA partitioner schedules collectives itself; we price its
# overlap conservatively at zero so the explicit comms subsystem's
# measured bucketed overlap is an honest advantage, not a wash
GSPMD_OVERLAP_RATIO = 0.0


class ProgramBase:
    """One-time program analysis every candidate shares: the per-op
    cost table, gradient footprint, trainable-parameter layout, and a
    memoized ``memory.estimate`` per shard layout."""

    def __init__(self, program, env, per_op, grad_bytes, param_shapes,
                 state_total_bytes, feed_specs=None, state_specs=None,
                 fetch_names=(), state_names=None, default_dim=None):
        self.program = program
        self.env = env
        self.per_op = list(per_op)
        self.grad_bytes = float(grad_bytes)
        self.param_shapes = list(param_shapes)  # [(name, shape)] fwd order
        self.state_total_bytes = float(state_total_bytes)
        self.feed_specs = feed_specs
        self.state_specs = state_specs
        self.fetch_names = fetch_names
        self.state_names = state_names
        self.default_dim = default_dim
        self.total_flops = float(sum(c.flops for c in self.per_op))
        self.total_bytes = float(sum(c.bytes for c in self.per_op))
        # forward MXU-ish output traffic (tp allreduce / pp boundary leg)
        self.mxu_out_bytes = 0.0
        self.n_heavy_ops = 0
        for c in self.per_op:
            if c.op_type == "backward" or not c.flops or c.op is None:
                continue
            out_b = sum(
                costs_mod._spec_nbytes(env[n])
                for ns in c.op.outputs.values() for n in ns if n in env)
            if c.flops >= 2.0 * max(out_b, 1.0):
                # contraction-like (matmul/conv): the ops tp shards
                self.mxu_out_bytes += out_b
                self.n_heavy_ops += 1
        self._mem_cache = {}
        self._roofline_cache = {}

    def roofline_seconds(self, profile, amp=False):
        """Single-chip roofline step seconds under ``profile`` with the
        AMP adjustment applied per op (memoized per (profile id, amp))."""
        if profile is None or (not profile.peak_flops
                               and not profile.hbm_bw):
            return None
        key = (id(profile), bool(amp))
        if key in self._roofline_cache:
            return self._roofline_cache[key]
        fl_div = (profile.peak_flops or 0.0) * (
            AMP_COMPUTE_SPEEDUP if amp else 1.0)
        by_fac = AMP_BYTES_FACTOR if amp else 1.0
        t = 0.0
        for c in self.per_op:
            legs = []
            if fl_div:
                legs.append(c.flops / fl_div)
            if profile.hbm_bw:
                legs.append(c.bytes * by_fac / profile.hbm_bw)
            t += max(legs)
        self._roofline_cache[key] = t
        return t

    def memory_report(self, param_shards, act_shards):
        key = (int(param_shards), int(act_shards))
        if key not in self._mem_cache:
            self._mem_cache[key] = memory_mod.estimate(
                self.program, env=self.env, feed_specs=self.feed_specs,
                state_specs=self.state_specs,
                fetch_names=self.fetch_names,
                state_names=self.state_names,
                default_dim=self.default_dim,
                param_shards=key[0], act_shards=key[1])
        return self._mem_cache[key]


def build_base(program, feed_names=None, feed_specs=None,
               state_specs=None, fetch_names=(), state_names=None,
               is_test=False, platform="cpu", default_dim=None):
    """Analyze ``program`` once (shape propagation + per-op costing +
    gradient/parameter footprints) into a :class:`ProgramBase`."""
    from ..analysis import shapes

    if feed_specs is None and feed_names:
        feed_specs = shapes.feed_specs_from_program(
            program, feed_names=list(feed_names), default_dim=default_dim)
    env, _ = shapes.propagate(
        program, feed_specs=feed_specs, state_specs=state_specs,
        is_test=is_test, platform=platform, default_dim=default_dim,
        check_declared=False)
    per_op = costs_mod.op_costs(program, env, is_test=is_test,
                                platform=platform)
    grad_bytes = costs_mod.dp_grad_bytes(program, env)
    gb = program.global_block()
    param_shapes = []
    for p in gb.all_parameters():
        if not getattr(p, "trainable", True):
            continue
        shape = tuple(getattr(p, "shape", ()) or ())
        if shape and all(isinstance(d, int) and d > 0 for d in shape):
            param_shapes.append((p.name, shape))
    sizes = memory_mod.sizes_from(program, env=env, feed_specs=feed_specs,
                                  state_specs=state_specs,
                                  default_dim=default_dim)
    if state_names is None:
        persist = {n for n, v in gb.vars.items() if v.persistable}
    else:
        persist = set(state_names)
    state_total = float(sum(sizes[n] for n in persist if n in sizes))
    return ProgramBase(program, env, per_op, grad_bytes, param_shapes,
                       state_total, feed_specs=feed_specs,
                       state_specs=state_specs, fetch_names=fetch_names,
                       state_names=state_names, default_dim=default_dim)


class PricedPlan:
    """One candidate with its predicted legs; ``rejected`` is None for
    rankable plans or an op-attributed diagnostic dict for plans the
    HBM budget excludes."""

    __slots__ = ("plan", "predicted_step_seconds", "compute_seconds",
                 "bubble_fraction", "dp_comm_seconds",
                 "exposed_comm_seconds", "comm_wire", "overlap_ratio",
                 "tp_comm_seconds", "pp_comm_seconds", "predicted_mfu",
                 "scaling_efficiency", "peak_hbm_bytes", "hbm_budget",
                 "rejected")

    def __init__(self, plan, **kw):
        self.plan = plan
        for k in self.__slots__[1:]:
            setattr(self, k, kw.get(k))

    def to_dict(self):
        def f6(x):
            return None if x is None else float("%.6g" % x)

        d = {"plan": self.plan.to_dict(),
             "predicted_step_seconds": f6(self.predicted_step_seconds),
             "compute_seconds": f6(self.compute_seconds),
             "bubble_fraction": f6(self.bubble_fraction),
             "dp_comm_seconds": f6(self.dp_comm_seconds),
             "exposed_comm_seconds": f6(self.exposed_comm_seconds),
             "comm_wire": self.comm_wire,
             "overlap_ratio": f6(self.overlap_ratio),
             "tp_comm_seconds": f6(self.tp_comm_seconds),
             "pp_comm_seconds": f6(self.pp_comm_seconds),
             "predicted_mfu": f6(self.predicted_mfu),
             "scaling_efficiency": f6(self.scaling_efficiency),
             "peak_hbm_bytes": (None if self.peak_hbm_bytes is None
                                else int(self.peak_hbm_bytes)),
             "hbm_budget": (None if self.hbm_budget is None
                            else int(self.hbm_budget))}
        if self.rejected is not None:
            d["rejected"] = self.rejected
        return d


def price_plan(base, plan, profile, hbm_budget=None):
    """Price one :class:`ParallelPlan` against a ``DeviceProfile``;
    returns a :class:`PricedPlan` (rejected when over the HBM budget)."""
    n_dev = plan.n_devices
    dp = plan.dp
    tp = plan.tp
    pp = plan.pp

    # -- compute leg ------------------------------------------------------
    single = base.roofline_seconds(profile, amp=plan.amp)
    compute_s = None
    bubble = costs_mod.pipeline_bubble_fraction(pp, plan.microbatches)
    if single is not None:
        compute_s = single / float(n_dev) * (1.0 + bubble)

    # -- dp gradient allreduce -------------------------------------------
    dp_comm_s = exposed_s = None
    overlap_ratio = 0.0
    bw, wire = costs_mod.allreduce_bandwidth(profile, n_dev)
    if dp > 1 and base.grad_bytes and bw:
        grad_elems = base.grad_bytes / 4.0 / float(plan.model_shards)
        payload = grad_elems * 4.0
        if plan.grad_sync_mode == "comms" and plan.grad_quantize:
            from ..parallel.comms.quantize import (compression_ratio,
                                                   round_up)

            padded = round_up(max(int(grad_elems), 1),
                              plan.grad_quantize_block)
            payload = padded * 4.0 / compression_ratio(
                padded, plan.grad_quantize_block)
        dp_comm_s = costs_mod.ring_allreduce_seconds(payload, dp, bw)
        if plan.grad_sync_mode == "comms" and plan.grad_overlap:
            from ..parallel.comms.bucketing import plan_buckets

            shard = max(1, plan.model_shards)
            named = [(n, (max(1, int(_numel(s)) // shard),))
                     for n, s in base.param_shapes]
            if named:
                overlap_ratio = plan_buckets(
                    named, plan.grad_bucket_bytes).overlap_ratio(True)
        else:
            overlap_ratio = GSPMD_OVERLAP_RATIO
        exposed_s = dp_comm_s * (1.0 - overlap_ratio)
    elif dp > 1 and base.grad_bytes:
        wire = None  # no bandwidth figure: comm leg unpredictable

    # -- tp activation allreduce -----------------------------------------
    tp_comm_s = None
    if tp > 1 and profile is not None and profile.ici_bw:
        act_bytes = base.mxu_out_bytes * TP_BWD_COMM_MULT / float(max(dp, 1))
        tp_comm_s = costs_mod.ring_allreduce_seconds(
            act_bytes, tp, profile.ici_bw)

    # -- pp boundary point-to-point --------------------------------------
    pp_comm_s = None
    if pp > 1 and profile is not None and profile.ici_bw:
        boundary = (base.mxu_out_bytes / float(max(base.n_heavy_ops, 1))
                    / float(max(dp, 1)))
        pp_comm_s = 2.0 * (pp - 1) * boundary / profile.ici_bw

    total = None
    if compute_s is not None:
        total = compute_s
        for leg in (exposed_s, tp_comm_s, pp_comm_s):
            if leg:
                total += leg

    mfu = eff = None
    if total and profile is not None and profile.peak_flops:
        mfu = (base.total_flops / float(n_dev)) / (
            total * profile.peak_flops)
    if total and compute_s:
        eff = compute_s / total

    # -- memory gate ------------------------------------------------------
    param_shards, act_shards = memory_mod.shard_divisors(plan.mesh)
    mem = base.memory_report(param_shards, act_shards)
    peak = float(mem.peak_bytes)
    if plan.sharding_degree > 1 and dp > 1:
        opt_state = max(0.0, base.state_total_bytes - base.grad_bytes)
        sharded_opt = opt_state / float(max(param_shards, 1))
        peak -= sharded_opt * (1.0 - 1.0 / float(dp))
    if plan.amp:
        peak -= mem.act_bytes_at_peak * (1.0 - AMP_ACT_MEM_FACTOR)
    peak = max(peak, 0.0)
    budget = hbm_budget
    if budget is None and profile is not None:
        budget = profile.hbm_bytes
    rejected = None
    if budget and peak > budget:
        rejected = {
            "reason": "predicted-oom",
            "peak_bytes": int(peak),
            "hbm_bytes": int(budget),
            "peak_op_index": mem.peak_op_index,
            "peak_op_type": mem.peak_op_type,
            "top_residents": [
                {"name": n, "bytes": int(b)} for n, b in mem.top[:3]],
        }
    return PricedPlan(
        plan,
        predicted_step_seconds=total,
        compute_seconds=compute_s,
        bubble_fraction=bubble,
        dp_comm_seconds=dp_comm_s,
        exposed_comm_seconds=exposed_s,
        comm_wire=(wire if dp > 1 and dp_comm_s is not None else None),
        overlap_ratio=overlap_ratio,
        tp_comm_seconds=tp_comm_s,
        pp_comm_seconds=pp_comm_s,
        predicted_mfu=mfu,
        scaling_efficiency=eff,
        peak_hbm_bytes=peak,
        hbm_budget=budget,
        rejected=rejected)


def _numel(shape):
    n = 1
    for d in shape:
        n *= int(d)
    return n
