"""Plan search: enumerate -> price -> gate on HBM -> rank.

``plan_search`` is the subsystem's front door: hand it a Program (or
let the CLI build the bench BERT pretrain target), a device count, and
a :class:`DeviceProfile`, and it returns a :class:`PlanSearchResult`
whose ``ranked`` list is ordered by predicted step seconds (ties break
on the plan's stable name so two processes always emit identical
JSON) and whose ``rejected`` list carries op-attributed predicted-OOM
diagnostics for every candidate the HBM budget excluded.
"""
from .candidates import enumerate_plans
from .plan import ParallelPlan
from .pricing import build_base, price_plan

__all__ = ["PlanSearchResult", "plan_search", "price_composition"]


class PlanSearchResult:
    """Ranked candidates + exclusions for one (program, devices,
    profile) search."""

    def __init__(self, n_devices, profile, ranked, rejected,
                 unpriced, base):
        self.n_devices = int(n_devices)
        self.profile = profile
        self.ranked = list(ranked)      # PricedPlan, best first
        self.rejected = list(rejected)  # PricedPlan with .rejected set
        self.unpriced = list(unpriced)  # PricedPlan with no prediction
        self.base = base

    @property
    def best(self):
        return self.ranked[0] if self.ranked else None

    def best_runnable(self):
        """Best plan ``Fleet._build`` accepts today (dp/tp/sp mesh)."""
        for pp in self.ranked:
            if pp.plan.fleet_runnable():
                return pp
        return None

    def to_dict(self, top=None):
        ranked = self.ranked if top is None else self.ranked[:top]
        d = {
            "n_devices": self.n_devices,
            "device": (self.profile.to_dict()
                       if self.profile is not None else None),
            "n_candidates": (len(self.ranked) + len(self.rejected)
                             + len(self.unpriced)),
            "n_rejected": len(self.rejected),
            "n_unpriced": len(self.unpriced),
            "ranked": [p.to_dict() for p in ranked],
            "rejected": [p.to_dict() for p in self.rejected],
        }
        if self.best is not None:
            d["best"] = self.best.to_dict()
        return d

    def render_text(self, top=10):
        """Human table: rank, plan, predicted legs."""
        lines = ["plan search: %d candidates over %d devices "
                 "(%d OOM-rejected, %d unpriced)"
                 % (len(self.ranked) + len(self.rejected)
                    + len(self.unpriced),
                    self.n_devices, len(self.rejected),
                    len(self.unpriced))]
        if self.profile is not None:
            lines.append("device: %s" % self.profile.name)
        hdr = ("  %-4s %-28s %12s %10s %10s %8s"
               % ("rank", "plan", "step_s", "compute_s", "comm_s",
                  "peak_GB"))
        lines.append(hdr)
        for i, p in enumerate(self.ranked[:top], 1):
            comm = sum(x for x in (p.exposed_comm_seconds,
                                   p.tp_comm_seconds,
                                   p.pp_comm_seconds) if x)
            lines.append(
                "  %-4d %-28s %12.4g %10.4g %10.4g %8.2f"
                % (i, p.plan.name, p.predicted_step_seconds or 0.0,
                   p.compute_seconds or 0.0, comm,
                   (p.peak_hbm_bytes or 0) / 1e9))
        for p in self.rejected[:max(0, top - len(self.ranked))]:
            rej = p.rejected or {}
            lines.append(
                "  OOM  %-28s peak %.2f GB > %.2f GB at op %s '%s'"
                % (p.plan.name, rej.get("peak_bytes", 0) / 1e9,
                   rej.get("hbm_bytes", 0) / 1e9,
                   rej.get("peak_op_index"), rej.get("peak_op_type")))
        return "\n".join(lines)


def plan_search(program, n_devices, device_kind=None, profile=None,
                feed_names=None, feed_specs=None, state_specs=None,
                fetch_names=(), state_names=None, is_test=False,
                platform="cpu", default_dim=None, microbatches=8,
                amp_choices=(False, True), hbm_budget=None,
                max_tp=None, max_pp=None, base=None):
    """Search mesh x strategy x comms for ``program`` on ``n_devices``
    chips of ``device_kind`` (or an explicit ``profile``). Returns a
    :class:`PlanSearchResult`."""
    from ..analysis.costs import device_profile
    from . import candidates as cand_mod

    if profile is None:
        profile = device_profile(device_kind)
    if base is None:
        base = build_base(
            program, feed_names=feed_names, feed_specs=feed_specs,
            state_specs=state_specs, fetch_names=fetch_names,
            state_names=state_names, is_test=is_test, platform=platform,
            default_dim=default_dim)
    n_layers = max(1, base.n_heavy_ops // 2)
    plans = enumerate_plans(
        n_devices,
        param_shapes=[s for _, s in base.param_shapes],
        n_layers=n_layers, microbatches=microbatches,
        amp_choices=amp_choices,
        max_tp=max_tp if max_tp is not None else cand_mod.MAX_TP,
        max_pp=max_pp if max_pp is not None else cand_mod.MAX_PP)
    ranked, rejected, unpriced = [], [], []
    for plan in plans:
        priced = price_plan(base, plan, profile, hbm_budget=hbm_budget)
        if priced.rejected is not None:
            rejected.append(priced)
        elif priced.predicted_step_seconds is None:
            unpriced.append(priced)
        else:
            ranked.append(priced)
    ranked.sort(key=lambda p: (p.predicted_step_seconds,
                               p.plan.sort_key()))
    rejected.sort(key=lambda p: p.plan.sort_key())
    unpriced.sort(key=lambda p: p.plan.sort_key())
    return PlanSearchResult(n_devices, profile, ranked, rejected,
                            unpriced, base)


def price_composition(program, mesh, strategy=None, device_kind=None,
                      profile=None, microbatches=1, amp=None,
                      base=None, **base_kw):
    """Price ONE composition — a mesh dict plus (optionally) the
    ``DistributedStrategy`` gating it — without running the search.
    Used by the dryrun-zoo validation test and the
    ``suboptimal-parallel-plan`` lint."""
    from ..analysis.costs import device_profile

    if profile is None:
        profile = device_profile(device_kind)
    if base is None:
        base = build_base(program, **base_kw)
    kw = {}
    if strategy is not None:
        kw = dict(
            grad_sync_mode=getattr(strategy, "grad_sync_mode", "gspmd"),
            grad_quantize=getattr(strategy, "grad_quantize", False),
            grad_quantize_block=getattr(strategy, "grad_quantize_block",
                                        256),
            grad_bucket_bytes=getattr(strategy, "grad_bucket_bytes",
                                      4 << 20),
            grad_overlap=getattr(strategy, "grad_overlap", True),
            sharding_degree=getattr(strategy, "sharding_degree", 1),
        )
        if amp is None:
            amp = getattr(strategy, "amp", False)
    plan = ParallelPlan(mesh=mesh, microbatches=microbatches,
                        amp=bool(amp), **kw)
    return price_plan(base, plan, profile)
