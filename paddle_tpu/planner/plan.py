"""The plan record: one candidate parallel configuration.

A :class:`ParallelPlan` is the unit the planner enumerates, prices,
ranks, and emits — a mesh shape plus the `DistributedStrategy` knobs
that matter for step time (gradient-sync mode, quantization, bucketed
overlap, ZeRO optimizer-state sharding, AMP) and the microbatch count
a pipeline schedule amortizes its bubble over. Everything is plain
ints/bools/strs so ``to_dict`` round-trips through JSON byte-stably
(fixed key order, no floats, no process-local ids) — the fingerprint
discipline the compile cache already follows.
"""

__all__ = ["ParallelPlan", "MESH_AXIS_ORDER"]

# canonical axis emission order — mesh dicts serialize in this order so
# two processes producing the same plan produce the same bytes
MESH_AXIS_ORDER = ("dp", "tp", "sp", "pp", "ep")


class ParallelPlan:
    """One (mesh x strategy) candidate.

    ``mesh`` maps axis name -> size (size-1 axes omitted); the product
    must equal the device count the plan targets. ``grad_sync_mode``
    mirrors ``DistributedStrategy``: "gspmd" leaves gradient allreduce
    to the XLA partitioner, "comms" runs the explicit bucketed
    (optionally int8 block-scaled) sync with backward overlap.
    """

    __slots__ = ("mesh", "microbatches", "grad_sync_mode",
                 "grad_quantize", "grad_quantize_block",
                 "grad_bucket_bytes", "grad_overlap", "sharding_degree",
                 "amp")

    def __init__(self, mesh, microbatches=1, grad_sync_mode="gspmd",
                 grad_quantize=False, grad_quantize_block=256,
                 grad_bucket_bytes=4 << 20, grad_overlap=True,
                 sharding_degree=1, amp=False):
        self.mesh = {str(a): int(s) for a, s in (mesh or {}).items()
                     if int(s) > 1}
        if not self.mesh:
            self.mesh = {"dp": 1}
        self.microbatches = max(1, int(microbatches))
        self.grad_sync_mode = str(grad_sync_mode)
        self.grad_quantize = bool(grad_quantize)
        self.grad_quantize_block = int(grad_quantize_block)
        self.grad_bucket_bytes = int(grad_bucket_bytes)
        self.grad_overlap = bool(grad_overlap)
        self.sharding_degree = max(1, int(sharding_degree))
        self.amp = bool(amp)

    # -- axis accessors ---------------------------------------------------
    def axis(self, name):
        return int(self.mesh.get(name, 1))

    @property
    def dp(self):
        return self.axis("dp")

    @property
    def tp(self):
        return self.axis("tp")

    @property
    def pp(self):
        return self.axis("pp")

    @property
    def n_devices(self):
        n = 1
        for s in self.mesh.values():
            n *= int(s)
        return n

    @property
    def model_shards(self):
        """Shards each gradient/parameter is split across (every
        non-batch axis); the dp allreduce payload divides by this."""
        n = 1
        for a, s in self.mesh.items():
            if a.lower() not in ("dp", "data", "batch", "sp", "seq"):
                n *= int(s)
        return n

    # -- identity ---------------------------------------------------------
    def _mesh_items(self):
        """Mesh items in canonical order (unknown axes last, sorted)."""
        known = [(a, self.mesh[a]) for a in MESH_AXIS_ORDER
                 if a in self.mesh]
        extra = sorted((a, s) for a, s in self.mesh.items()
                       if a not in MESH_AXIS_ORDER)
        return known + extra

    @property
    def name(self):
        """Stable human tag: ``dp4_tp2+zero+int8+amp``."""
        parts = ["%s%d" % (a, s) for a, s in self._mesh_items()]
        tag = "_".join(parts)
        if self.pp > 1:
            tag += "_mb%d" % self.microbatches
        if self.sharding_degree > 1:
            tag += "+zero"
        if self.grad_sync_mode == "comms":
            tag += "+int8" if self.grad_quantize else "+comms"
            if self.grad_overlap:
                tag += "+ov"
        if self.amp:
            tag += "+amp"
        return tag

    def sort_key(self):
        """Deterministic total-order tie-break for equal predictions."""
        return self.name

    def fleet_runnable(self):
        """Whether ``Fleet._build`` accepts this plan today: the
        collective build handles dp/tp/sp meshes; pp routes through
        PipelineOptimizer and ep through the MoE path, so plans using
        them are emitted for capacity planning but not auto-applied."""
        return all(a in ("dp", "tp", "sp") for a in self.mesh)

    def to_dict(self):
        """JSON-stable dict (insertion order is the canonical order)."""
        d = {"mesh": dict(self._mesh_items()),
             "microbatches": self.microbatches,
             "grad_sync_mode": self.grad_sync_mode,
             "grad_quantize": self.grad_quantize,
             "grad_quantize_block": self.grad_quantize_block,
             "grad_bucket_bytes": self.grad_bucket_bytes,
             "grad_overlap": self.grad_overlap,
             "sharding_degree": self.sharding_degree,
             "amp": self.amp,
             "name": self.name,
             "fleet_runnable": self.fleet_runnable()}
        return d

    @classmethod
    def from_dict(cls, d):
        return cls(mesh=d.get("mesh") or {},
                   microbatches=d.get("microbatches", 1),
                   grad_sync_mode=d.get("grad_sync_mode", "gspmd"),
                   grad_quantize=d.get("grad_quantize", False),
                   grad_quantize_block=d.get("grad_quantize_block", 256),
                   grad_bucket_bytes=d.get("grad_bucket_bytes", 4 << 20),
                   grad_overlap=d.get("grad_overlap", True),
                   sharding_degree=d.get("sharding_degree", 1),
                   amp=d.get("amp", False))

    def __repr__(self):
        return "ParallelPlan(%s)" % self.name

    def __eq__(self, other):
        return (isinstance(other, ParallelPlan)
                and self.to_dict() == other.to_dict())

    def __hash__(self):
        return hash(self.name)
