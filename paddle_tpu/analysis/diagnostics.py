"""Diagnostic records + analysis report.

Every analysis pass (verifier, shape propagation, TPU-lint) emits
:class:`Diagnostic` records into an :class:`AnalysisReport`. Severity
taxonomy:

- ``error``   — the program would provably fail at lowering/compile time
                (missing input value, un-computable fetch, broken
                sub-block reference, shape-inference failure). The
                executor raises :class:`ProgramVerifyError` on these
                BEFORE handing anything to XLA.
- ``warning`` — well-formed but hazardous (float64 creep on TPU,
                donated-buffer-also-fetched, host callbacks inside scan
                regions, unbounded shape vocabulary). Counted as
                *findings* by the CLI (nonzero exit) but never blocks a
                run.
- ``perf``    — TPU efficiency hints (matmul/conv dims not padded to
                the 8/128 lane grid). Informational for small models by
                design: a lane-padding hint must not fail a smoke lint.
- ``info``    — observations (dead ops/vars relative to the fetch
                targets, undeclared produced names).

``findings`` = errors + warnings. ``to_json`` output is stable: records
sorted on a deterministic key, ``sort_keys=True``, no timestamps.
"""
import json

from ..fluid.lowering import OpLoweringError, _format_callstack

__all__ = [
    "Diagnostic", "AnalysisReport", "ProgramVerifyError",
    "ERROR", "WARNING", "PERF", "INFO", "SEVERITIES",
]

ERROR = "error"
WARNING = "warning"
PERF = "perf"
INFO = "info"
SEVERITIES = (ERROR, WARNING, PERF, INFO)


class ProgramVerifyError(OpLoweringError):
    """A static verifier error: the program would fail at lowering time.

    Subclasses :class:`OpLoweringError` so every caller that already
    treats lowering errors as non-retryable user-graph errors
    (``GuardedExecutor.NEVER_RETRY``, ``Executor.run``'s AOT fallback,
    existing ``pytest.raises(OpLoweringError)`` tests) handles the
    earlier, attributed failure identically.
    """

    def __init__(self, message, report=None):
        super().__init__(message)
        self.report = report


class Diagnostic:
    """One finding: (severity, check, message) + op/var attribution."""

    __slots__ = ("severity", "check", "message", "block_idx", "op_index",
                 "op_type", "var", "callstack")

    def __init__(self, severity, check, message, block_idx=None,
                 op_index=None, op_type=None, var=None, op=None):
        if severity not in SEVERITIES:
            raise ValueError("bad severity %r" % (severity,))
        self.severity = severity
        self.check = check
        self.message = message
        self.block_idx = block_idx
        self.op_index = op_index
        self.op_type = op_type
        self.var = var
        self.callstack = None
        if op is not None:
            if op_type is None:
                self.op_type = op.type
            # the op's recorded python callstack: the build site (or the
            # from_json load site) — how a finding maps back to user code
            self.callstack = _format_callstack(op).split("\n")

    def _key(self):
        return (
            SEVERITIES.index(self.severity),
            self.block_idx if self.block_idx is not None else -1,
            self.op_index if self.op_index is not None else -1,
            self.check,
            self.var or "",
        )

    def to_dict(self):
        d = {"severity": self.severity, "check": self.check,
             "message": self.message}
        for k in ("block_idx", "op_index", "op_type", "var", "callstack"):
            v = getattr(self, k)
            if v is not None:
                d[k] = v
        return d

    def __str__(self):
        loc = ""
        if self.block_idx is not None:
            loc = " [block %s" % self.block_idx
            if self.op_index is not None:
                loc += " op %s" % self.op_index
            if self.op_type is not None:
                loc += " '%s'" % self.op_type
            loc += "]"
        s = "%s(%s)%s: %s" % (self.severity, self.check, loc, self.message)
        if self.callstack:
            s += "\n  defined at:\n" + "\n".join(self.callstack)
        return s

    __repr__ = __str__


class AnalysisReport:
    """Accumulated diagnostics for one analyzed program."""

    def __init__(self, checks=None):
        self.diagnostics = []
        self.checks = list(checks or [])  # pass names that actually ran
        self.meta = {}  # stable program facts (n_blocks, n_ops, ...)

    # -- emit -----------------------------------------------------------
    def add(self, severity, check, message, **kw):
        d = Diagnostic(severity, check, message, **kw)
        self.diagnostics.append(d)
        return d

    def extend(self, other):
        self.diagnostics.extend(other.diagnostics)
        for c in other.checks:
            if c not in self.checks:
                self.checks.append(c)
        self.meta.update(other.meta)
        return self

    # -- query ----------------------------------------------------------
    def by_severity(self, severity):
        return [d for d in self.diagnostics if d.severity == severity]

    @property
    def errors(self):
        return self.by_severity(ERROR)

    @property
    def findings(self):
        """Errors + warnings — what 'lint clean' means and what makes
        the CLI exit nonzero. perf/info records never count."""
        return [d for d in self.diagnostics
                if d.severity in (ERROR, WARNING)]

    def counts(self):
        c = {s: 0 for s in SEVERITIES}
        for d in self.diagnostics:
            c[d.severity] += 1
        return c

    def summary(self):
        c = self.counts()
        parts = ["%d %s" % (c[s], s) for s in SEVERITIES if c[s]]
        head = ", ".join(parts) if parts else "clean"
        worst = next((d for d in sorted(self.diagnostics,
                                        key=lambda d: d._key())), None)
        if worst is not None:
            head += " | first: %s(%s) %s" % (
                worst.severity, worst.check, worst.message)
        return head

    def raise_if_errors(self):
        errs = self.errors
        if not errs:
            return self
        msg = "program verification failed with %d error(s):\n\n%s" % (
            len(errs), "\n\n".join(str(d) for d in errs[:8]))
        if len(errs) > 8:
            msg += "\n\n... and %d more" % (len(errs) - 8)
        raise ProgramVerifyError(msg, report=self)

    # -- render ---------------------------------------------------------
    def to_dict(self):
        return {
            "checks": sorted(self.checks),
            "counts": self.counts(),
            "findings": len(self.findings),
            "meta": dict(self.meta),
            "diagnostics": [
                d.to_dict()
                for d in sorted(self.diagnostics, key=lambda d: d._key())
            ],
        }

    def to_json(self, indent=None):
        return json.dumps(self.to_dict(), sort_keys=True, indent=indent)

    def __str__(self):
        lines = ["analysis: %s" % self.summary()]
        for d in sorted(self.diagnostics, key=lambda d: d._key()):
            lines.append(str(d))
        return "\n".join(lines)
