"""Liveness-based peak-HBM estimation (static, pure python).

Fluid's ``memory_optimize``/``DistributeTranspiler`` memory passes
rewrote the program to reuse buffers; under XLA the compiler does that
reuse, so what the framework owes the user instead is a *prediction*:
will this program fit, and which op is resident at the peak? This pass
answers that with def-use liveness over the global block (sub-block
closure reads included via :func:`.walker._op_reads`):

- persistable state (params, optimizer moments) is live for the whole
  step — divided by ``param_shards`` when the mesh shards parameters
  (ZeRO/tp);
- every other name is live from its defining op through its last
  reader (fetch targets stay live to the end) — divided by
  ``act_shards`` when the mesh shards the batch (dp/sp);
- the symbolic ``backward`` op reads every activation its forward
  region produced (vjp residuals), so activations stay resident
  through it — exactly the "peak at the backward pass" shape real
  training has.

Sizes come from the inferred shape env when available (exact), else
from feed/state specs, else from declared var metadata with ``-1``
dims resolved to ``default_dim``. The result is an *estimate* —
XLA fusion avoids materializing some intermediates — but it is a
usable upper bound for admission control and capacity planning.
"""
import numpy as np

from . import walker

__all__ = ["MemoryReport", "estimate", "sizes_from", "shard_divisors",
           "var_nbytes"]

DEFAULT_DIM = 8  # matches shapes.DEFAULT_DIM (keep import-light)

# mesh axis names that shard the BATCH (divide activations); every
# other axis is assumed to shard parameters (tp/mp/ZeRO) — including
# ep, which rows-shards embedding tables (paddle_tpu.retrieval), so an
# ep-width mesh divides the table's HBM residency, not the batch
_BATCH_AXES = ("dp", "data", "batch", "sp", "seq")


def shard_divisors(mesh):
    """``{axis: size}`` -> ``(param_shards, act_shards)``: batch-like
    axes divide activation footprints, everything else (tp/mp/ZeRO/ep)
    divides parameter footprints."""
    param_shards = act_shards = 1
    for axis, size in (mesh or {}).items():
        if str(axis).lower() in _BATCH_AXES:
            act_shards *= int(size)
        else:
            param_shards *= int(size)
    return max(param_shards, 1), max(act_shards, 1)


def var_nbytes(shape, dtype, default_dim=None):
    """Bytes of a declared (shape, dtype) with -1 dims resolved to
    ``default_dim``; None when the shape is unknown."""
    if shape is None:
        return None
    default_dim = DEFAULT_DIM if default_dim is None else default_dim
    n = 1
    for d in shape:
        n *= default_dim if (d is None or d < 0) else int(d)
    try:
        item = np.dtype(dtype or "float32").itemsize
    except TypeError:
        from ..fluid import core

        item = np.dtype(core.np_dtype(dtype)).itemsize
    return n * item


def _spec_nbytes(spec):
    n = 1
    for d in getattr(spec, "shape", ()) or ():
        n *= int(d)
    return n * np.dtype(spec.dtype).itemsize


def sizes_from(program, env=None, feed_specs=None, state_specs=None,
               default_dim=None):
    """name -> bytes for every sizable name: inferred env first
    (exact), then feed/state specs (real arrays at the executor gate),
    then declared var metadata across all blocks."""
    sizes = {}
    for name, v in _iter_declared_vars(program):
        b = var_nbytes(v.shape, v.dtype, default_dim)
        if b is not None:
            sizes[name] = b
    for src in (state_specs, feed_specs, env):
        for name, spec in (src or {}).items():
            try:
                sizes[name] = _spec_nbytes(spec)
            except TypeError:
                pass
    return sizes


def _iter_declared_vars(program):
    for block in program.blocks:
        for name, v in block.vars.items():
            yield name, v


class MemoryReport:
    """Peak live-set estimate with op attribution."""

    __slots__ = ("peak_bytes", "peak_op_index", "peak_op_type",
                 "param_bytes", "act_bytes_at_peak", "n_ops",
                 "param_shards", "act_shards", "top", "unsized")

    def __init__(self, peak_bytes, peak_op_index, peak_op_type,
                 param_bytes, act_bytes_at_peak, n_ops, param_shards,
                 act_shards, top, unsized):
        self.peak_bytes = peak_bytes
        self.peak_op_index = peak_op_index
        self.peak_op_type = peak_op_type
        self.param_bytes = param_bytes
        self.act_bytes_at_peak = act_bytes_at_peak
        self.n_ops = n_ops
        self.param_shards = param_shards
        self.act_shards = act_shards
        self.top = top          # [(name, bytes)] largest residents at peak
        self.unsized = unsized  # names with no shape info (uncounted)

    def to_dict(self):
        d = {
            "peak_bytes": int(self.peak_bytes),
            "param_bytes": int(self.param_bytes),
            "act_bytes_at_peak": int(self.act_bytes_at_peak),
            "n_ops": self.n_ops,
            "top_residents": [
                {"name": n, "bytes": int(b)} for n, b in self.top],
        }
        if self.peak_op_index is not None:
            d["peak_op_index"] = self.peak_op_index
            d["peak_op_type"] = self.peak_op_type
        if self.param_shards != 1 or self.act_shards != 1:
            d["param_shards"] = self.param_shards
            d["act_shards"] = self.act_shards
        if self.unsized:
            d["unsized_vars"] = len(self.unsized)
        return d


def _ceil_div(a, b):
    return -(-int(a) // int(b))


def estimate(program, env=None, feed_specs=None, state_specs=None,
             fetch_names=(), state_names=None, default_dim=None,
             param_shards=1, act_shards=1, sizes=None,
             resident_names=()):
    """Run the liveness walk; returns a :class:`MemoryReport`.

    ``state_names=None`` treats every persistable as state (executor
    semantics). ``param_shards``/``act_shards`` divide parameter and
    activation footprints (see :func:`shard_divisors`).
    ``resident_names`` pins names live across the WHOLE program
    regardless of their def/use span — e.g. the persistent per-slot KV
    buffer pair a decode engine round-trips device-to-device every
    step: def-use liveness would let the fed copy die at its last
    reader, but the serving process holds both the fed and the fetched
    buffer for the region's entire lifetime."""
    gb = program.global_block()
    if sizes is None:
        sizes = sizes_from(program, env=env, feed_specs=feed_specs,
                           state_specs=state_specs,
                           default_dim=default_dim)
    if state_names is None:
        state_names = {n for n, v in gb.vars.items() if v.persistable}
    else:
        state_names = set(state_names)
    fetch_names = set(fetch_names or ())
    feed_names = set(feed_specs or ())
    resident_names = set(resident_names or ())

    param_bytes = sum(
        _ceil_div(sizes[n], param_shards)
        for n in state_names if n in sizes)
    unsized = sorted(
        n for n in state_names if n not in sizes)

    n_ops = len(gb.ops)
    if n_ops == 0:
        return MemoryReport(param_bytes, None, None, param_bytes, 0, 0,
                            param_shards, act_shards, [], unsized)

    # def/last-use per transient name; the backward op reads its whole
    # forward region's outputs (vjp residuals)
    first_def = {}
    last_use = {}
    produced_before = set()  # non-persistable outputs of preceding ops
    reads_at = []
    for i, op in enumerate(gb.ops):
        reads = set(walker._op_reads(program, op))
        if op.type == "backward":
            reads |= set(produced_before)
        reads_at.append(reads)
        for n in reads:
            last_use[n] = i
        for ns in op.outputs.values():
            for n in ns:
                first_def.setdefault(n, i)
                if n not in state_names:
                    produced_before.add(n)

    transient = {}
    seen_unsized = set(unsized)
    for n in set(first_def) | set(last_use) | feed_names | resident_names:
        if n in state_names:
            continue
        if n not in sizes:
            if n not in seen_unsized:
                seen_unsized.add(n)
                unsized.append(n)
            continue
        start = first_def.get(n, 0) if n not in feed_names else 0
        end = last_use.get(n, start)
        if n in fetch_names:
            end = n_ops - 1
        if n in resident_names:
            start, end = 0, n_ops - 1
        end = max(end, start)
        transient[n] = (start, end, _ceil_div(sizes[n], act_shards))

    # sweep: +size at def, -size after last use
    delta = [0] * (n_ops + 1)
    for _n, (start, end, b) in transient.items():
        delta[start] += b
        delta[end + 1] -= b
    live = 0
    peak_live = -1
    peak_i = 0
    for i in range(n_ops):
        live += delta[i]
        if live > peak_live:
            peak_live = live
            peak_i = i
    peak_live = max(peak_live, 0)

    top = sorted(
        ((n, b) for n, (s, e, b) in transient.items()
         if s <= peak_i <= e),
        key=lambda kv: (-kv[1], kv[0]))[:8]
    return MemoryReport(
        peak_bytes=param_bytes + peak_live,
        peak_op_index=peak_i,
        peak_op_type=gb.ops[peak_i].type,
        param_bytes=param_bytes,
        act_bytes_at_peak=peak_live,
        n_ops=n_ops,
        param_shards=param_shards,
        act_shards=act_shards,
        top=top,
        unsized=sorted(unsized),
    )
