"""Runtime scope sanitizer: cross-thread Scope mutation detector.

Three subsystems mutate Scopes from background threads — the serving
dispatch thread (``serving/engine.py``), the async-pipeline stager
(``fluid/async_pipeline.py``), and guarded/watchdog runs
(``fluid/resilience.py``). Each is designed single-writer-per-scope; a
refactor that silently breaks that invariant corrupts training state in
ways that surface steps later as NaNs or stale params.

Opt-in (``PADDLE_TPU_SCOPE_SANITIZER=on`` or :func:`arm`): every
``Scope.set``/``Scope.update`` records the writing thread per
``(scope, var)``. A write from a different thread while the previous
writer is STILL ALIVE is an unsynchronized cross-thread mutation —
recorded as a violation with both threads and the write-site stacks.
Sequential handoff (previous writer already exited, e.g. a finished
watchdog worker) transfers ownership silently: that is a
happens-before edge, not a race.

Off (the default), the hook in ``Scope`` is a single module-bool check.
Stdlib-only (+observability) so the executor can import it at module
level without accelerator init.
"""
import os
import threading
import traceback

from .. import observability as obs

__all__ = ["armed", "arm", "disarm", "record_write", "violations",
           "reset", "SANITIZER_ENV"]

SANITIZER_ENV = "PADDLE_TPU_SCOPE_SANITIZER"

# the hot-path gate: Scope.set/update check this single bool
_on = os.environ.get(SANITIZER_ENV, "").lower() in ("1", "on", "true")

_lock = threading.Lock()
_writers = {}     # (id(scope), name) -> (thread, stack_summary)
_violations = []


def armed():
    return _on


def arm():
    """Enable tracking (tests / debugging sessions)."""
    global _on
    _on = True


def disarm():
    global _on
    _on = False


def record_write(scope, name):
    """Called by Scope.set/update when armed. Never raises."""
    me = threading.current_thread()
    stack = traceback.extract_stack(limit=7)[:-2]
    key = (id(scope), name)
    with _lock:
        prev = _writers.get(key)
        _writers[key] = (me, stack)
        if prev is None:
            return
        prev_thread, prev_stack = prev
        if prev_thread is me or not prev_thread.is_alive():
            return
        v = {
            "var": name,
            "scope": id(scope),
            "threads": [prev_thread.name, me.name],
            "stacks": [
                ["%s:%d in %s" % (f.filename, f.lineno, f.name)
                 for f in s[-3:]]
                for s in (prev_stack, stack)
            ],
        }
        _violations.append(v)
    obs.event("scope_race", source="sanitizer", var=name,
              threads="%s -> %s" % (prev_thread.name, me.name))


def violations():
    """Snapshot of recorded violations (list of dicts)."""
    with _lock:
        return list(_violations)


def reset():
    """Clear tracked writers + violations (does not change armed state)."""
    with _lock:
        _writers.clear()
        del _violations[:]
