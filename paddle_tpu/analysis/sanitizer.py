"""Runtime scope sanitizer: cross-thread Scope mutation detector.

Three subsystems mutate Scopes from background threads — the serving
dispatch thread (``serving/engine.py``), the async-pipeline stager
(``fluid/async_pipeline.py``), and guarded/watchdog runs
(``fluid/resilience.py``). Each is designed single-writer-per-scope; a
refactor that silently breaks that invariant corrupts training state in
ways that surface steps later as NaNs or stale params.

Opt-in (``PADDLE_TPU_SCOPE_SANITIZER=on`` or :func:`arm`): every
``Scope.set``/``Scope.update`` records the writing thread per
``(scope, var)``. A write from a different thread while the previous
writer is STILL ALIVE is an unsynchronized cross-thread mutation —
recorded as a violation with both threads and the write-site stacks.
Sequential handoff (previous writer already exited, e.g. a finished
watchdog worker) transfers ownership silently: that is a
happens-before edge, not a race.

Scopes are identified by a monotonically increasing token bound to the
scope via ``weakref.finalize`` — NOT by raw ``id(scope)``. Raw ids
leak an entry per dead scope AND, worse, CPython reuses ids after GC,
so a fresh scope allocated at a recycled address would inherit the
dead scope's writer records and mis-attribute a legitimate handoff as
a same-scope cross-thread write. Finalizers evict a dead scope's
tokens and writer entries, so long sessions stay bounded. Violations
are bounded too (:data:`MAX_VIOLATIONS`, overflow counted by
:func:`dropped`) — a hot racing pair must not OOM the process it is
diagnosing.

Off (the default), the hook in ``Scope`` is a single module-bool check.
Stdlib-only (+observability) so the executor can import it at module
level without accelerator init.
"""
import collections
import itertools
import os
import threading
import traceback
import weakref

from .. import observability as obs

__all__ = ["armed", "arm", "disarm", "record_write", "violations",
           "reset", "dropped", "scope_token", "SANITIZER_ENV",
           "MAX_VIOLATIONS"]

SANITIZER_ENV = "PADDLE_TPU_SCOPE_SANITIZER"

# the hot-path gate: Scope.set/update check this single bool
_on = os.environ.get(SANITIZER_ENV, "").lower() in ("1", "on", "true")

MAX_VIOLATIONS = 256

_lock = threading.Lock()
_writers = {}       # (scope_token, name) -> (thread, stack_summary)
_scope_tokens = {}  # id(scope) -> token (valid while the scope lives)
_next_token = itertools.count(1)
_violations = collections.deque(maxlen=MAX_VIOLATIONS)
_dropped = 0


def armed():
    return _on


def arm():
    """Enable tracking (tests / debugging sessions)."""
    global _on
    _on = True


def disarm():
    global _on
    _on = False


def scope_token(scope):
    """Process-unique token for a live scope. Unlike ``id(scope)``, a
    token is never reused: a finalizer retires it (and its writer
    entries) when the scope is collected, so a new scope at a recycled
    address gets a fresh token."""
    key = id(scope)
    with _lock:
        tok = _scope_tokens.get(key)
        if tok is not None:
            return tok
        tok = next(_next_token)
        _scope_tokens[key] = tok
    try:
        weakref.finalize(scope, _evict_scope, key, tok)
    except TypeError:
        # non-weakref-able scope stand-ins (tests may pass plain dicts);
        # the entry stays until reset() — degraded, not wrong, since the
        # token still never aliases another live scope
        pass
    return tok


def _evict_scope(key, tok):
    """Finalizer: retire a dead scope's token + writer entries."""
    with _lock:
        if _scope_tokens.get(key) == tok:
            del _scope_tokens[key]
        for k in [k for k in _writers if k[0] == tok]:
            del _writers[k]


def record_write(scope, name):
    """Called by Scope.set/update when armed. Never raises."""
    global _dropped
    me = threading.current_thread()
    stack = traceback.extract_stack(limit=7)[:-2]
    key = (scope_token(scope), name)
    with _lock:
        prev = _writers.get(key)
        _writers[key] = (me, stack)
        if prev is None:
            return
        prev_thread, prev_stack = prev
        if prev_thread is me or not prev_thread.is_alive():
            return
        v = {
            "var": name,
            "scope": key[0],
            "threads": [prev_thread.name, me.name],
            "stacks": [
                ["%s:%d in %s" % (f.filename, f.lineno, f.name)
                 for f in s[-3:]]
                for s in (prev_stack, stack)
            ],
        }
        if len(_violations) == _violations.maxlen:
            _dropped += 1
        _violations.append(v)
    obs.inc("sanitizer.violations")
    obs.event("scope_race", source="sanitizer", var=name,
              threads="%s -> %s" % (prev_thread.name, me.name))


def violations():
    """Snapshot of recorded violations (list of dicts, oldest first)."""
    with _lock:
        return list(_violations)


def dropped():
    """Violations discarded because the bounded buffer overflowed."""
    with _lock:
        return _dropped


def reset():
    """Clear tracked writers + violations (does not change armed state
    or retire live scope tokens — those stay valid for reuse)."""
    global _dropped
    with _lock:
        _writers.clear()
        _violations.clear()
        _dropped = 0
