"""Def-use/donation dataflow pass: proven buffer-donation hazards.

The executor lowers every program to ``jax.jit(step, donate_argnums=
(0,))`` with argument 0 = the state dict of ALL persistables gathered
from the Scope (``fluid/executor.py``). Donation is what makes in-place
parameter updates fit in HBM — and it is also an aliasing footgun:
after dispatch the donated input buffers are invalid, and any output
the runtime hands back may occupy one of them. ``tpu_lint`` flags the
shallow heuristic (``donated-and-fetched``); this pass walks the
def-use chains and upgrades the provable cases to errors:

- ``use-after-donate`` (ERROR) — a fetch target is donated state that
  the program REWRITES: the fetched array is (or aliases) the buffer
  the next dispatch donates, so holding it across the next ``run()``
  reads freed memory. Also: a donated var whose update op has non-
  writer readers BOTH before and after it — one step observes two
  generations of the same parameter (e.g. a gradient computed against
  the old value while a later op consumes the new one), the exact
  misordered-update class donation turns from "stale value" into
  "garbage read". Reads only-after are fine (lr-decay then optimizer
  reads is the canonical pattern) and stay silent.
- ``double-donate`` (ERROR) — two distinct global-block ops rewrite
  one donated var: the first generation is silently discarded and XLA
  may consume the donated buffer twice across the fused step.
- ``cross-program-donated-alias`` (WARNING, :func:`check_cross_program`
  / runtime :func:`note_donation`+:func:`note_capture`) — one Scope
  var both donated by a training signature and captured by a
  serving/decode engine. Engines that host-snapshot params
  (``jax.device_put(np.asarray(...))``) pass ``snapshot=True`` and are
  exempt; a zero-copy capture of a donated buffer is flagged, because
  the next training dispatch invalidates the engine's weights mid-
  flight.

Sub-block reads count: an op whose ``while``/``cond`` body reads a
donated name via closure (no declared input) is a reader at the owning
op's position — ``walker._op_reads`` supplies those, mirroring the
lowering env-copy semantics.

The static pass runs at ``level="full"`` in :func:`analyzer.analyze`
and in the CLI; the runtime registry is gated on the concurrency
sanitizer (``PADDLE_TPU_LOCK_SANITIZER``) and costs one module-bool
check when off.
"""
import weakref

from . import concurrency, walker
from .diagnostics import ERROR, WARNING, AnalysisReport

__all__ = [
    "analyze_donation", "check_cross_program", "note_capture",
    "note_donation", "reset_runtime",
]


def _global_writers(program, donated):
    """donated name -> [op indices in the global block writing it]."""
    writers = {}
    gb = program.global_block()
    for i, op in enumerate(gb.ops):
        for ns in op.outputs.values():
            for n in ns:
                if n in donated:
                    writers.setdefault(n, []).append(i)
    return writers


def analyze_donation(program, feed_names=(), fetch_names=(),
                     state_names=None):
    """Run the static donation dataflow pass over one program.

    ``state_names`` mirrors the executor's donation set; ``None`` means
    every global-block persistable (what ``_gather_state`` donates).
    """
    report = AnalysisReport(checks=["dataflow"])
    gb = program.global_block()
    donated = set(state_names) if state_names is not None else {
        n for n, v in gb.vars.items() if v.persistable}
    writers = _global_writers(program, donated)
    report.meta["donated_vars"] = len(donated)
    report.meta["donated_rewritten"] = len(writers)

    # -- feed shadows donated state: the host feed wins, the scope copy
    # is donated anyway, so the value the user fed never persists ---------
    for name in feed_names:
        if name in donated:
            report.add(
                WARNING, "feed-shadows-donated-state",
                "feed var '%s' is also donated state: the dispatch "
                "donates the scope copy while the host feed shadows it, "
                "so the fed value never persists past this run() — feed "
                "a non-persistable input or drop it from the state set"
                % name, block_idx=0, var=name)

    # -- double-donate: two ops rewrite one donated buffer ----------------
    for name in sorted(writers):
        idxs = writers[name]
        if len(idxs) > 1:
            first, last = idxs[0], idxs[-1]
            report.add(
                ERROR, "double-donate",
                "donated var '%s' is rewritten by %d ops (op %d '%s' "
                "then op %d '%s'): the intermediate generation is "
                "discarded and the donated buffer is consumed more than "
                "once in one step — fold the updates into one op or "
                "stage through a non-persistable temp"
                % (name, len(idxs), first, gb.ops[first].type, last,
                   gb.ops[last].type),
                block_idx=0, op_index=last, var=name, op=gb.ops[last])

    # -- use-after-donate: fetched donated-and-rewritten buffer -----------
    for name in fetch_names:
        if name in donated and name in writers:
            idx = writers[name][-1]
            report.add(
                ERROR, "use-after-donate",
                "fetch var '%s' is donated state rewritten by op %d "
                "'%s': the fetched array occupies a buffer the NEXT "
                "dispatch donates, so holding it across another run() "
                "reads invalidated memory — fetch a non-persistable "
                "copy (assign to a temp) or read it from the scope "
                "after the run" % (name, idx, gb.ops[idx].type),
                block_idx=0, op_index=idx, var=name, op=gb.ops[idx])

    # -- use-after-donate: reads straddling the update op -----------------
    # reader map at global-op granularity, closure reads included
    for name in sorted(set(writers) - set(fetch_names)):
        if len(writers[name]) != 1:
            continue  # double-donate already errored; keep one report
        widx = writers[name][0]
        before, after = [], []
        for i, op in enumerate(gb.ops):
            if i == widx:
                continue  # the update op's own read is the old gen by
                # construction — functional lowering, not a hazard
            if name in walker._op_reads(program, op):
                (before if i < widx else after).append(i)
        if before and after:
            a = after[0]
            closure = name not in {
                n for ns in gb.ops[a].inputs.values() for n in ns}
            report.add(
                ERROR, "use-after-donate",
                "donated var '%s' is read%s by op %d '%s' AFTER its "
                "update at op %d '%s', while op %d '%s' read it before: "
                "one step observes both generations of a donated "
                "buffer — move the update after every consumer, or "
                "stage the pre-update value in a temp"
                % (name,
                   " (via sub-block closure)" if closure else "",
                   a, gb.ops[a].type, widx, gb.ops[widx].type,
                   before[0], gb.ops[before[0]].type),
                block_idx=0, op_index=a, var=name, op=gb.ops[a])
    return report


def check_cross_program(donor_program, reader_program,
                        donor_state_names=None, donor_label="training",
                        reader_label="serving"):
    """Static cross-program aliasing check: vars the donor program
    donates AND rewrites that the reader program also consumes. Sharing
    one Scope between them means the donor's dispatch invalidates the
    reader's captured weights."""
    report = AnalysisReport(checks=["dataflow"])
    dgb = donor_program.global_block()
    donated = set(donor_state_names) if donor_state_names is not None \
        else {n for n, v in dgb.vars.items() if v.persistable}
    rewritten = set(_global_writers(donor_program, donated))
    if not rewritten:
        return report
    reads = set()
    for _block, _i, op in walker.iter_ops(reader_program):
        reads |= walker._op_reads(reader_program, op)
        for ns in op.inputs.values():
            reads.update(ns)
    for name in sorted(rewritten & reads):
        report.add(
            WARNING, "cross-program-donated-alias",
            "var '%s' is donated and rewritten by the %s program and "
            "read by the %s program: sharing one Scope aliases the %s "
            "weights to a buffer the %s dispatch donates — run them on "
            "separate scopes, or host-snapshot the captured params "
            "(jax.device_put(np.asarray(...)))"
            % (name, donor_label, reader_label, reader_label,
               donor_label),
            block_idx=0, var=name)
    return report


# ---------------------------------------------------------------------------
# runtime donation/capture registry (armed with the lock sanitizer)
# ---------------------------------------------------------------------------

# scope token -> {var name -> (consumer, snapshot)}
_captures = {}
_finalized = set()


def _scope_key(scope):
    from . import sanitizer
    tok = sanitizer.scope_token(scope)
    if tok not in _finalized:
        _finalized.add(tok)
        try:
            weakref.finalize(scope, _evict, tok)
        except TypeError:
            pass
    return tok


def _evict(tok):
    _captures.pop(tok, None)
    _finalized.discard(tok)


def note_capture(scope, names, consumer, snapshot=False):
    """An engine captured ``names`` from ``scope``. ``snapshot=True``
    means it copied host-side (decode/prefill engines) — exempt from
    aliasing. Gated on the concurrency sanitizer; off = one bool check."""
    if not concurrency._on:
        return
    caps = _captures.setdefault(_scope_key(scope), {})
    for n in names:
        caps[n] = (str(consumer), bool(snapshot))


def note_donation(scope, names):
    """The executor is about to donate ``names`` from ``scope``. Any
    non-snapshot capture of one of them is a live aliasing hazard —
    recorded as a ``cross-program-donated-alias`` violation on the
    shared concurrency report surface."""
    if not concurrency._on:
        return
    caps = _captures.get(_scope_key(scope))
    if not caps:
        return
    for n in names:
        hit = caps.get(n)
        if hit is None or hit[1]:
            continue
        consumer = hit[0]
        caps.pop(n, None)  # report each capture once
        concurrency._record_violation({
            "check": "cross-program-donated-alias",
            "var": n,
            "consumer": consumer,
            "locks": [],
            "threads": [],
            "stacks": [concurrency._stack(skip=2)],
            "message": "scope var %r is captured (zero-copy) by %s and "
                       "is about to be DONATED by a training dispatch "
                       "on the same scope — the capture's buffer is "
                       "invalidated mid-flight; snapshot the params "
                       "host-side or split the scopes" % (n, consumer),
        })


def reset_runtime():
    """Drop every recorded capture (tests / session scoping)."""
    _captures.clear()
