"""Program walker: the one place that knows the IR's control-flow shape.

Every analysis pass (verifier, shape propagation, TPU-lint), the
debugger's pretty-printer, and the graphviz dump walk Programs through
these helpers instead of re-implementing sub-block descent — the
conventions live in the control-flow lowerings (ops/control_ops.py) and
drift here would mean false positives everywhere.

Conventions mirrored from the lowerings:

- ``BLOCK_ATTRS``: op attrs referencing a body block by index
  (while/conditional_block/static_rnn/dynamic_rnn use ``sub_block``;
  cond uses ``true_block``/``false_block``).
- Sub-block bodies run in a COPY of the outer env — they read any name
  defined in the outer block at the op's position without declaring it
  as an op input.
- The owning op's lowering BINDS extra names into the body env before
  the body runs (``injected_names``): while binds its carried vars +
  cond var, static/dynamic_rnn bind per-step memory + slice vars,
  conditional_block binds the current values of its written vars.
  A use-before-def pass that doesn't seed these reports every RNN body
  as broken.
"""

__all__ = [
    "BLOCK_ATTRS", "sub_block_indices", "sub_blocks", "injected_names",
    "iter_blocks", "iter_ops", "block_owners", "producer_index",
    "live_report",
]

BLOCK_ATTRS = ("sub_block", "true_block", "false_block")

# owning-op type -> attrs whose names the lowering binds into the body
# env before running body ops (see module docstring)
_INJECTED_NAME_ATTRS = {
    "while": ("carried_names", "cond_name"),
    "static_rnn": ("mem_names", "x_names"),
    "dynamic_rnn": ("mem_names", "x_names"),
    "conditional_block": ("written_names",),
}


def sub_block_indices(op):
    """Block indices an op's body attrs reference, in attr order."""
    out = []
    for attr in BLOCK_ATTRS:
        idx = op.attrs.get(attr)
        if idx is not None:
            out.append((attr, idx))
    return out


def sub_blocks(program, op):
    """Resolved (attr, Block) pairs; silently skips broken indices (the
    verifier reports those explicitly via check_sub_blocks)."""
    out = []
    n = len(program.blocks)
    for attr, idx in sub_block_indices(op):
        if isinstance(idx, int) and 0 <= idx < n:
            out.append((attr, program.block(idx)))
    return out


def injected_names(op):
    """Names the op's lowering binds into its body env before the body
    ops run — defined-on-entry for any sub-block analysis."""
    attrs = _INJECTED_NAME_ATTRS.get(op.type, ())
    names = set()
    for a in attrs:
        v = op.attrs.get(a)
        if v is None:
            continue
        if isinstance(v, str):
            names.add(v)
        else:
            names.update(v)
    return names


def iter_blocks(program):
    """Yield ``(block, owner_op)`` in pre-order: block 0 with owner
    ``None`` first, then each sub-block right after the op that owns it.
    Blocks no op references (dead sub-blocks) come last with owner
    ``None`` so walkers still see every block."""
    seen = set()

    def walk(block, owner):
        if block.idx in seen:
            return
        seen.add(block.idx)
        yield block, owner
        for op in block.ops:
            for _attr, sub in sub_blocks(program, op):
                yield from walk(sub, op)

    yield from walk(program.global_block(), None)
    for block in program.blocks:
        if block.idx not in seen:
            seen.add(block.idx)
            yield block, None


def block_owners(program):
    """block idx -> owning Operator (absent for block 0 / dead blocks)."""
    owners = {}
    for block, owner in iter_blocks(program):
        if owner is not None:
            owners[block.idx] = owner
    return owners


def iter_ops(program):
    """Yield ``(block, op_index, op)`` over every reachable block in
    pre-order (sub-block ops nested right after their owner)."""
    for block, _owner in iter_blocks(program):
        for i, op in enumerate(block.ops):
            yield block, i, op


def producer_index(block):
    """name -> index of the last op in `block` writing it."""
    produced = {}
    for i, op in enumerate(block.ops):
        for ns in op.outputs.values():
            for n in ns:
                produced[n] = i
    return produced


def _op_reads(program, op):
    """All names an op may read, including sub-block closure reads
    (mirrors lowering.op_read_names but tolerates broken block refs)."""
    reads = set()
    for ns in op.inputs.values():
        reads.update(ns)
    for _attr, sub in sub_blocks(program, op):
        produced = set(injected_names(op))
        for sop in sub.ops:
            reads |= _op_reads(program, sop) - produced
            for ns in sop.outputs.values():
                produced.update(ns)
    return reads


def live_report(program, fetch_names, state_names=None):
    """Liveness relative to the fetch targets + persistable state.

    Returns ``(live_op_idx, dead_ops, dead_vars)`` for the global block:
    ``live_op_idx`` the set of global-block op indices on the backward
    slice from the targets, ``dead_ops`` the ``(idx, op)`` pairs off it,
    ``dead_vars`` declared global-block var names neither read nor
    written by any live op (and not targets/feeds/persistables).

    ``state_names=None`` treats every persistable as live (executor
    semantics: new_state collects ALL persistables, so optimizer update
    ops are live even when nothing fetches them).
    """
    gb = program.global_block()
    if state_names is None:
        state_names = {v.name for v in gb.vars.values() if v.persistable}
    needed = set(fetch_names) | set(state_names)
    live = set()
    for i in range(len(gb.ops) - 1, -1, -1):
        op = gb.ops[i]
        outs = set()
        for ns in op.outputs.values():
            outs.update(ns)
        if outs & needed:
            live.add(i)
            needed |= _op_reads(program, op)
    dead_ops = [(i, op) for i, op in enumerate(gb.ops) if i not in live]
    used = set(fetch_names)
    for i in live:
        op = gb.ops[i]
        used |= _op_reads(program, op)
        for ns in op.outputs.values():
            used.update(ns)
    dead_vars = [
        name for name, v in gb.vars.items()
        if name not in used and not v.is_data and not v.persistable
    ]
    return live, dead_ops, dead_vars
