"""TPU-lint: hazards a well-formed Program still ships to the chip.

Severity policy (see diagnostics.py): lane-padding hints are ``perf``
(a small smoke model must lint clean); float64 creep and missing
collective deadlines are ``warning`` when linting for TPU and ``info``
on CPU, so CPU-platform test programs stay finding-free while the CLI
(which lints for deployment, platform ``tpu`` by default) flags them.
"""
from ..fluid import core
from . import walker
from .diagnostics import INFO, PERF, WARNING, AnalysisReport

__all__ = ["lint", "lint_decode_ladder", "lint_parallel_plan",
           "lint_retrieval_ladder", "SUBOPTIMAL_PLAN_SLOWDOWN"]

# MXU is 128x128, VPU lanes are 8x128; a float32 tile is (8, 128)
# (see the pallas guide) — XLA pads unaligned dims with dead lanes.
SUBLANE, LANE = 8, 128

# ops whose operands hit the MXU
_MATMUL_OPS = {"mul", "matmul"}
_CONV_OPS = {"conv2d", "depthwise_conv2d", "conv2d_transpose"}

# ops that synchronize with the host python interpreter per call
_HOST_SYNC_OPS = {"py_func"}

# loop-body owners: a host sync inside these runs once per scan step
_SCAN_OWNERS = {"while", "static_rnn", "dynamic_rnn"}

_COLLECTIVE_EXTRA = {"barrier", "ppermute", "all_to_all"}

# estimated compile-cache entries per dynamic feed axis (a pow2 bucket
# ladder over one axis is ~8 rungs: 1..128)
_BUCKETS_PER_AXIS = 8
SHAPE_VOCAB_THRESHOLD = 2048

# how many FLOPs-ranked ops the cost model promotes to "hottest" status
HOT_K = 5

# fp32 allreduce payload past which block-scaled quantization pays off:
# below this, per-collective latency dominates and the ~3.9x wire cut
# saves nothing worth the extra quantize/dequantize
QUANTIZABLE_ALLREDUCE_BYTES = 1 << 16

# a gated composition priced this much slower than the best
# same-device-count plan draws the suboptimal-parallel-plan finding
SUBOPTIMAL_PLAN_SLOWDOWN = 1.25

# gather-family ops: ~zero FLOPs per byte streamed from HBM
_GATHER_OPS = {"lookup_table", "lookup_table_v2", "gather", "gather_nd"}

# tables smaller than this gather fast from anywhere — the
# low-intensity-gather finding targets embedding tables where HBM
# streaming dominates the step, and keeps small smoke models clean
LOW_INTENSITY_GATHER_BYTES = 1 << 20


def lint(program, shape_env=None, feed_names=(), fetch_names=(),
         state_names=None, platform="tpu", cost=None):
    """Lint a Program; returns an :class:`AnalysisReport`.

    ``shape_env``: inferred name -> spec from :mod:`.shapes` (falls back
    to declared var metadata when absent). ``state_names``: persistable
    names the executor will donate (``None`` = every persistable).
    ``cost``: a :class:`.costs.CostReport` — when given, tiling findings
    on the top-``HOT_K`` FLOPs-ranked ops are upgraded to
    intensity-ranked ``hot-unpadded-*`` findings, and the ranking lands
    in ``report.meta["hottest_ops"]``.
    """
    report = AnalysisReport(checks=["tpu_lint"])
    gb = program.global_block()
    on_tpu = platform == "tpu"
    shape_env = shape_env or {}

    hot = {}
    if cost is not None and cost.per_op:
        total = cost.total_flops or 1.0
        ranked = cost.hottest(HOT_K)
        for rank, oc in enumerate(ranked, 1):
            hot[oc.op_index] = (rank, oc)
        report.meta["hottest_ops"] = [
            dict(oc.to_dict(), rank=rank,
                 flops_share=round(oc.flops / total, 4))
            for rank, oc in ((r, ranked[r - 1])
                             for r in range(1, len(ranked) + 1))]

    def shape_of(block, name):
        v = shape_env.get(name)
        if v is not None:
            return tuple(v.shape)
        blk = block
        while blk is not None:
            if name in blk.vars:
                return blk.vars[name].shape
            blk = blk.parent_block
        return None

    owners = walker.block_owners(program)

    collectives = []
    for block, i, op in walker.iter_ops(program):
        # -- lane padding ---------------------------------------------------
        if op.type in _MATMUL_OPS or op.type in _CONV_OPS:
            hot_rank = hot.get(i) if block.idx == 0 else None
            _lint_tiling(block, i, op, shape_of, report,
                         hot_rank=hot_rank,
                         total_flops=(cost.total_flops
                                      if cost is not None else None))
        # -- memory-bound embedding gathers ---------------------------------
        if op.type in _GATHER_OPS:
            _lint_low_intensity_gather(block, i, op, shape_of, report)
        # -- host sync inside scan regions ----------------------------------
        if op.type in _HOST_SYNC_OPS and block.idx != 0:
            owner = owners.get(block.idx)
            if owner is not None and owner.type in _SCAN_OWNERS:
                report.add(
                    WARNING, "host-sync-in-scan",
                    "op '%s' synchronizes with host python inside a "
                    "'%s' body — every loop iteration stalls the device "
                    "on a host round-trip; hoist it out of the loop or "
                    "precompute its values as a feed"
                    % (op.type, owner.type),
                    block_idx=block.idx, op_index=i, op=op)
        if op.type.startswith("c_") or op.type in _COLLECTIVE_EXTRA:
            collectives.append((block, i, op))

    # -- float64 creep ------------------------------------------------------
    for name, v in gb.vars.items():
        if v.dtype == core.VarType.FP64:
            report.add(
                WARNING if on_tpu else INFO, "float64-creep",
                "var '%s' is declared float64: TPUs have no f64 units, "
                "and without jax x64 the value is SILENTLY truncated to "
                "float32 — declare float32 (or enable x64 off-TPU) so "
                "precision loss is explicit" % name,
                block_idx=0, var=name)

    # -- donation/aliasing hazard -------------------------------------------
    donated = set(state_names) if state_names is not None else {
        n for n, v in gb.vars.items() if v.persistable}
    produced = set()
    for op in gb.ops:
        for ns in op.outputs.values():
            produced.update(ns)
    for n in fetch_names:
        if n in donated:
            report.add(
                WARNING, "donated-and-fetched",
                "fetch var '%s' is persistable state the executor "
                "donates (donate_argnums): the fetched buffer aliases a "
                "donated input%s — fetch a non-persistable copy (e.g. "
                "assign it to a temp) or read it from the scope after "
                "the run" % (
                    n, "" if n in produced
                    else ", and no op rewrites it, so XLA cannot reuse "
                         "the donation at all"),
                block_idx=0, var=n)

    # -- quantizable fp32 allreduces ----------------------------------------
    _lint_quantizable_allreduce(collectives, shape_of, shape_env, report)

    # -- collectives without a deadline -------------------------------------
    if collectives:
        from ..fluid.resilience import deadline_remaining

        if deadline_remaining() is None:
            block, i, op = collectives[0]
            report.add(
                WARNING if on_tpu else INFO, "collective-missing-deadline",
                "program issues %d collective op(s) (first: '%s') and no "
                "collective deadline is armed on this thread — a hung "
                "peer turns every collective into an infinite wait; wrap "
                "dispatch in resilience.collective_deadline(seconds) "
                "(FleetGuard arms one automatically)"
                % (len(collectives), op.type),
                block_idx=block.idx, op_index=i, op=op)

    # -- compile-cache shape vocabulary -------------------------------------
    _lint_shape_vocab(gb, feed_names, report)
    return report


def _lint_tiling(block, i, op, shape_of, report, hot_rank=None,
                 total_flops=None):
    """Flag MXU operand dims off the (8, 128) tile grid. With a cost
    ranking, a finding on a top-K op carries its FLOPs rank, share, and
    arithmetic intensity — the padding fix with the largest payoff
    first."""
    checked = []
    if op.type in _MATMUL_OPS:
        for slot in ("X", "Y"):
            for n in op.input(slot):
                checked.append((n, shape_of(block, n)))
    else:
        for n in op.input("Filter"):
            checked.append((n, shape_of(block, n)))
        for n in op.output("Output"):
            checked.append((n, shape_of(block, n)))
    bad = []
    for n, shape in checked:
        if not shape or len(shape) < 2:
            continue
        sub, lane = shape[-2], shape[-1]
        if lane is None or sub is None or lane < 0 or sub < 0:
            continue  # dynamic dims: bucketing decides the padding
        if lane % LANE or (sub % SUBLANE and sub >= SUBLANE):
            waste = (1.0
                     - (sub * lane)
                     / (_round_up(sub, SUBLANE) * _round_up(lane, LANE)))
            bad.append((n, shape, waste))
    check = ("unpadded-matmul" if op.type in _MATMUL_OPS
             else "unpadded-conv")
    prefix = ""
    if hot_rank is not None:
        rank, oc = hot_rank
        check = "hot-" + check
        share = (oc.flops / total_flops) if total_flops else 0.0
        inten = oc.intensity
        prefix = (
            "rank #%d hottest op (%.0f%% of program FLOPs%s): "
            % (rank, 100.0 * share,
               ", intensity %.1f flops/byte" % inten
               if inten is not None else ""))
    for n, shape, waste in bad:
        report.add(
            PERF, check,
            "%soperand '%s' of '%s' has minor dims %s not aligned to "
            "the 8x128 tile grid — XLA pads with ~%d%% dead lanes; pad "
            "the layer width (or fold small dims) to multiples of 128/8"
            % (prefix, n, op.type, tuple(shape[-2:]), round(100 * waste)),
            block_idx=block.idx, op_index=i, op=op, var=n)


def _lint_quantizable_allreduce(collectives, shape_of, shape_env, report):
    """Flag full-precision sum-allreduces of large fp32 tensors: the
    block-scaled quantized lowering (``c_allreduce_quant``, or
    ``DistributedStrategy.grad_quantize`` for the gradient path) moves
    ~3.9x fewer wire bytes at block 256 with error feedback absorbing
    the rounding. Small payloads are latency-bound and stay exact."""
    import numpy as np

    for block, i, op in collectives:
        if op.type != "c_allreduce_sum":
            continue
        for n in op.input("X"):
            shape = shape_of(block, n)
            if not shape or any(d is None or d < 0 for d in shape):
                continue
            spec = shape_env.get(n)
            if spec is not None:
                if np.dtype(spec.dtype) != np.float32:
                    continue
            else:
                blk, declared = block, None
                while blk is not None:
                    if n in blk.vars:
                        declared = blk.vars[n].dtype
                        break
                    blk = blk.parent_block
                if declared != core.VarType.FP32:
                    continue
            nbytes = 4
            for d in shape:
                nbytes *= int(d)
            if nbytes < QUANTIZABLE_ALLREDUCE_BYTES:
                continue
            report.add(
                PERF, "quantizable-allreduce",
                "'c_allreduce_sum' of '%s' moves %d fp32 bytes per "
                "participant at full precision — block-scaled int8 "
                "('c_allreduce_quant', or DistributedStrategy."
                "grad_quantize for gradients) cuts the wire ~3.9x at "
                "block 256, with error feedback absorbing the rounding"
                % (n, nbytes),
                block_idx=block.idx, op_index=i, op=op, var=n)


def _round_up(x, m):
    return ((x + m - 1) // m) * m


def _memory_bound_knee():
    """The roofline knee (FLOP/byte) of the lint target device, when
    the cost model knows it: ops below it are HBM-bandwidth-bound no
    matter how the MXU is fed."""
    try:
        from ..fluid.executor import _device_kind
        from .costs import device_profile

        p = device_profile(_device_kind())
        if p is not None and p.peak_flops and p.hbm_bw:
            return p.peak_flops / p.hbm_bw
    except Exception:  # noqa: BLE001 — advisory pass only
        pass
    return None


def _lint_low_intensity_gather(block, i, op, shape_of, report):
    """PERF-flag embedding lookups that are pure HBM streaming: a
    gather performs ~zero FLOPs per byte it moves, so its arithmetic
    intensity sits far below the memory-bound knee — the fix is not
    feeding the MXU better but streaming less table per chip
    (paddle_tpu.retrieval's ep-sharded tables). Gated on a table-size
    floor so small smoke models lint clean."""
    slot = "W" if op.type.startswith("lookup_table") else "X"
    names = op.inputs.get(slot) or ()
    if not names:
        return
    shape = shape_of(block, names[0])
    if not shape or len(shape) < 2 or any(
            s is None or s < 0 for s in shape):
        return
    table_bytes = 4  # fp32 rows; dtype refinement isn't worth a miss
    for s in shape:
        table_bytes *= int(s)
    if table_bytes < LOW_INTENSITY_GATHER_BYTES:
        return
    knee = _memory_bound_knee()
    report.add(
        PERF, "low-intensity-gather",
        "op '%s' gathers from table '%s' (%s, ~%.1f MB): arithmetic "
        "intensity ~0 FLOP/byte is far below the memory-bound knee%s — "
        "the lookup is pure HBM streaming and scales with table bytes "
        "per chip, not FLOPs; shard the table over an ep mesh "
        "(paddle_tpu.retrieval.ShardedEmbeddingTable) so each chip "
        "streams 1/ep of it"
        % (op.type, names[0], "x".join(str(s) for s in shape),
           table_bytes / 1e6,
           " (%.0f FLOP/byte here)" % knee if knee else ""),
        block_idx=block.idx, op_index=i, op=op, var=names[0])


def _lint_shape_vocab(gb, feed_names, report):
    """Estimate how many distinct feed signatures (≈ compiled
    executables) this program's dynamic dims can generate. Axis 0 is the
    batch dim shared across feeds (one ladder); every additional dynamic
    axis multiplies the vocabulary."""
    names = list(feed_names) or [n for n, v in gb.vars.items() if v.is_data]
    axes = 0
    batch_dynamic = False
    detail = []
    for n in names:
        if not gb.has_var(n):
            continue
        shape = gb.var(n).shape or ()
        extra = 0
        for ax, s in enumerate(shape):
            if s is None or s < 0:
                if ax == 0:
                    batch_dynamic = True
                else:
                    extra += 1
        if extra:
            detail.append("%s:%d" % (n, extra))
        axes += extra
    if batch_dynamic:
        axes += 1
    estimate = _BUCKETS_PER_AXIS ** axes if axes else 1
    report.meta["shape_vocab_estimate"] = estimate
    if estimate > SHAPE_VOCAB_THRESHOLD:
        report.add(
            WARNING, "unbounded-shape-vocab",
            "feeds carry %d dynamic axes (%s%s) — a pow2 bucket ladder "
            "per axis compiles ~%d executables, blowing up compile time "
            "and the AOT cache; fix non-batch dims (pad to a single "
            "length) or declare explicit serving BucketSpecs"
            % (axes,
               "batch" if batch_dynamic else "",
               (", " + ", ".join(detail)) if detail else "",
               estimate),
            block_idx=0)


def lint_parallel_plan(program, mesh, strategy=None, n_devices=None,
                       device_kind=None, profile=None, level="full",
                       microbatches=1, amp=None, feed_names=None,
                       feed_specs=None, state_specs=None, fetch_names=(),
                       state_names=None, is_test=False, default_dim=None,
                       search_result=None):
    """Price the composition a program is gated under (``mesh`` +
    optionally its ``DistributedStrategy``) against the planner's best
    same-device-count plan; emits a ``suboptimal-parallel-plan`` PERF
    finding naming the better plan when the gated one is priced
    >= ``SUBOPTIMAL_PLAN_SLOWDOWN`` slower. Off below ``full`` level —
    the search runs one shape-propagation + a few hundred pricings, far
    too heavy for the µs verify gate. A planner failure degrades to
    report meta, never an exception."""
    report = AnalysisReport(checks=["parallel_plan"])
    if level != "full":
        return report
    mesh = dict(mesh or {})
    if n_devices is None:
        n_devices = 1
        for s in mesh.values():
            n_devices *= int(s)
    if n_devices < 2:
        return report
    try:
        from ..planner import plan_search, price_composition
        from .costs import device_profile

        if profile is None:
            profile = device_profile(device_kind)
        result = search_result
        if result is None:
            result = plan_search(
                program, n_devices, profile=profile,
                feed_names=feed_names, feed_specs=feed_specs,
                state_specs=state_specs, fetch_names=fetch_names,
                state_names=state_names, is_test=is_test,
                default_dim=default_dim,
                microbatches=max(microbatches, 8))
        else:
            profile = result.profile
        current = price_composition(
            program, mesh, strategy=strategy, profile=profile,
            microbatches=microbatches, amp=amp, base=result.base)
        best = result.best
        cur_s = current.predicted_step_seconds
        if best is None or cur_s is None:
            return report
        best_s = best.predicted_step_seconds
        report.meta["parallel_plan"] = {
            "current": current.to_dict(), "best": best.to_dict()}
        if best_s and cur_s >= SUBOPTIMAL_PLAN_SLOWDOWN * best_s:
            report.add(
                PERF, "suboptimal-parallel-plan",
                "this composition (%s) is priced %.3g s/step — %.1fx "
                "the best same-device-count plan '%s' at %.3g s/step; "
                "run `python -m paddle_tpu.analysis --plan --devices "
                "%d` for the ranked table and apply the winner via "
                "DistributedStrategy.from_plan"
                % (current.plan.name, cur_s, cur_s / best_s,
                   best.plan.name, best_s, n_devices),
                block_idx=0)
    except Exception as e:  # noqa: BLE001 — advisory pass only
        report.meta["parallel_plan_error"] = "%s: %s" % (
            type(e).__name__, e)
    return report


def lint_decode_ladder(prompt_buckets, slot_counts=(1,), cache_lens=(),
                       threshold=None, kv_dtypes=("fp32",),
                       delta_buckets=(), spec_blocks=(),
                       draft_buckets=()):
    """Lint a decode engine's AOT program ladder BEFORE it compiles.

    A DecodeEngine compiles one prefill program per (prompt bucket,
    cache_len) and one step program per (slot count, cache_len, KV
    residency dtype) — a disaggregated fleet that runs both fp32- and
    int8-resident decode replicas doubles its step variants, which is
    why ``kv_dtypes`` multiplies the step leg. KV reuse and
    speculation widen the ladder further, and each leg is declared
    here so the estimate never undercounts: ``delta_buckets`` adds one
    delta-prefill program per (bucket, cache_len) (prefix-pool +
    session-tier engines), ``spec_blocks`` one block-verify program
    per (block width, slot count, cache_len), and ``draft_buckets``
    the attached draft model's own ladder — its prefill rungs plus one
    draft step per slot count. An over-wide ladder (per-token prompt
    buckets, a cache_len per client) quietly re-creates the
    unbounded-shape-vocab hazard the feed lint catches for dynamic
    axes — but here every rung is *declared*, so the feed shapes all
    look static. Warns against the same ``SHAPE_VOCAB_THRESHOLD``
    budget; also flags non-pow2 prompt buckets (each odd rung is a
    whole extra executable a pow2 ladder would have covered)."""
    report = AnalysisReport(checks=["decode_ladder"])
    prompt_buckets = sorted({int(b) for b in (prompt_buckets or ())})
    slot_counts = sorted({int(s) for s in (slot_counts or (1,))})
    cache_lens = sorted({int(c) for c in (cache_lens or (1,))})
    kv_dtypes = sorted({str(d) for d in (kv_dtypes or ("fp32",))})
    delta_buckets = sorted({int(b) for b in (delta_buckets or ())})
    spec_blocks = sorted({int(b) for b in (spec_blocks or ())})
    draft_buckets = sorted({int(b) for b in (draft_buckets or ())})
    threshold = SHAPE_VOCAB_THRESHOLD if threshold is None else threshold
    spec_programs = len(cache_lens) * len(slot_counts) * len(spec_blocks)
    draft_programs = 0
    if draft_buckets:
        draft_programs = len(cache_lens) * (
            len(draft_buckets) + len(slot_counts))
    programs = len(cache_lens) * (
        len(prompt_buckets) + len(delta_buckets)
        + len(slot_counts) * len(kv_dtypes)
    ) + spec_programs + draft_programs
    report.meta["decode_ladder_programs"] = programs
    report.meta["decode_ladder_kv_dtypes"] = list(kv_dtypes)
    report.meta["decode_ladder_delta_programs"] = (
        len(cache_lens) * len(delta_buckets))
    report.meta["decode_ladder_spec_programs"] = spec_programs
    report.meta["decode_ladder_draft_programs"] = draft_programs
    if programs > threshold:
        report.add(
            WARNING, "unbounded-shape-vocab",
            "decode ladder compiles %d AOT programs (%d prompt buckets "
            "+ %d delta buckets + %d slot counts x %d KV dtypes over "
            "%d cache lengths, + %d verify + %d draft) — over the %d "
            "shape-vocabulary budget; thin the prompt-bucket ladder "
            "(pow2 rungs) and pin one (slots, cache_len, kv_dtype) "
            "per engine"
            % (programs, len(prompt_buckets), len(delta_buckets),
               len(slot_counts), len(kv_dtypes), len(cache_lens),
               spec_programs, draft_programs, threshold),
            block_idx=0)
    odd = [b for b in prompt_buckets
           if b & (b - 1) and b != max(prompt_buckets or [0])]
    if odd:
        report.add(
            INFO, "decode-ladder-rungs",
            "non-pow2 prompt buckets %s: each is an extra executable a "
            "pow2 ladder would already cover" % (odd,), block_idx=0)
    return report


def lint_retrieval_ladder(query_buckets, ops=("lookup", "search"),
                          k_values=(10,), threshold=None):
    """Lint a RetrievalEngine's AOT program ladder BEFORE it compiles
    — the retrieval arm of the unbounded-shape-vocab count. The engine
    compiles one lookup program per query bucket plus one top-k search
    program per (query bucket, k); like the decode ladder, every rung
    is *declared*, so the feed lint sees only static shapes and this
    count is the one that keeps the vocabulary honest. Warns against
    the shared ``SHAPE_VOCAB_THRESHOLD`` budget; non-pow2 rungs draw
    the same each-is-an-extra-executable INFO."""
    report = AnalysisReport(checks=["retrieval_ladder"])
    buckets = sorted({int(b) for b in (query_buckets or ())})
    k_values = sorted({int(k) for k in (k_values or (10,))})
    ops = tuple(ops or ())
    threshold = SHAPE_VOCAB_THRESHOLD if threshold is None else threshold
    programs = 0
    if "lookup" in ops:
        programs += len(buckets)
    if "search" in ops:
        programs += len(buckets) * len(k_values)
    report.meta["retrieval_ladder_programs"] = programs
    report.meta["retrieval_ladder_k_values"] = list(k_values)
    if programs > threshold:
        report.add(
            WARNING, "unbounded-shape-vocab",
            "retrieval ladder compiles %d AOT programs (%d query "
            "buckets%s) — over the %d shape-vocabulary budget; thin "
            "the query-bucket ladder (pow2 rungs) and serve one k per "
            "engine"
            % (programs, len(buckets),
               " x %d k value(s) for search" % len(k_values)
               if "search" in ops else "",
               threshold),
            block_idx=0)
    odd = [b for b in buckets
           if b & (b - 1) and b != max(buckets or [0])]
    if odd:
        report.add(
            INFO, "retrieval-ladder-rungs",
            "non-pow2 query buckets %s: each is an extra executable a "
            "pow2 ladder would already cover" % (odd,), block_idx=0)
    return report
