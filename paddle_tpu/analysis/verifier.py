"""Static IR verifier.

Proves a Program well-formed BEFORE fingerprinting/compilation by
statically evaluating the exact conditions the lowering would hit at
trace time (``lowering.resolve_inputs``'s missing-value error,
``build_step_fn``'s un-computable fetch) plus structural sanity the
lowering only discovers as an opaque KeyError deep inside a sub-block.

Error-severity checks are restricted to conditions that provably fail
at lowering time — the verifier gates every first compile by default,
so a heuristic error here would break working programs. Heuristics
(dead stores, dead ops/vars, undeclared outputs) report as
warning/info.
"""
from . import walker
from .diagnostics import ERROR, INFO, WARNING, AnalysisReport

__all__ = ["verify"]


def _feed_set(program, feed_names):
    """Feed names as the executor would prepare them: every fed
    lod_level>0 var also gets its ``@SEQ_LEN`` companion feed
    (Executor._prepare_feeds)."""
    gb = program.global_block()
    feeds = set(feed_names)
    for name in list(feeds):
        seq = name + "@SEQ_LEN"
        if gb.has_var(seq):
            feeds.add(seq)
    return feeds


def verify(program, feed_names=(), fetch_names=(), state_names=None,
           check_liveness=True):
    """Verify a Program; returns an :class:`AnalysisReport`.

    ``feed_names``: names fed this run (defaults to declared data vars
    when empty). ``state_names``: persistable names with a value in the
    scope; ``None`` assumes every persistable is initialized (standalone
    analysis — the startup program would have run). ``fetch_names``
    drive the reachability + dead-code checks.
    """
    report = AnalysisReport(checks=["verifier"])
    gb = program.global_block()

    feeds = _feed_set(program, feed_names)
    if not feed_names:
        # standalone mode: data vars are the feedable surface
        feeds |= {name for name, v in gb.vars.items() if v.is_data}
        feeds = _feed_set(program, feeds)

    persistables = {name for name, v in gb.vars.items() if v.persistable}
    if state_names is None:
        state = set(persistables)
    else:
        state = set(state_names)

    report.meta["n_blocks"] = len(program.blocks)
    report.meta["n_ops"] = sum(len(b.ops) for b in program.blocks)

    # ---- sub-block sanity -------------------------------------------------
    _check_sub_blocks(program, report)

    # ---- every name produced anywhere (for dangling-vs-ordering msgs) ----
    produced_anywhere = set()
    for block in program.blocks:
        for op in block.ops:
            for ns in op.outputs.values():
                produced_anywhere.update(ns)

    # ---- per-block sequential walk ---------------------------------------
    entry0 = feeds | state
    _walk_block(program, gb, entry0, produced_anywhere, persistables,
                state_names is not None, report, _seen=set())

    # ---- fetch reachability ----------------------------------------------
    producible0 = set(entry0)
    for op in gb.ops:
        for ns in op.outputs.values():
            producible0.update(ns)
    for n in fetch_names:
        if n not in producible0:
            report.add(
                ERROR, "fetch-unreachable",
                "fetch var '%s' is never computed by the program (not "
                "produced by any global-block op, not fed, not in state)"
                % n, block_idx=0, var=n)

    # ---- feed usage -------------------------------------------------------
    if feed_names:
        read_anywhere = set()
        for op in gb.ops:
            read_anywhere |= walker._op_reads(program, op)
        for n in feed_names:
            if n not in read_anywhere and not n.endswith("@SEQ_LEN"):
                report.add(INFO, "unused-feed",
                           "feed '%s' is never read by any op" % n,
                           block_idx=0, var=n)

    # ---- dead code relative to fetch targets ------------------------------
    if check_liveness and fetch_names:
        _live, dead_ops, dead_vars = walker.live_report(
            program, fetch_names, state_names=None)
        for i, op in dead_ops:
            report.add(INFO, "dead-op",
                       "op contributes to no fetch target and no "
                       "persistable state", block_idx=0, op_index=i, op=op)
        for n in dead_vars:
            report.add(INFO, "dead-var",
                       "var is read/written by no live op", block_idx=0,
                       var=n)
    return report


def _check_sub_blocks(program, report):
    n_blocks = len(program.blocks)
    required_attrs = {
        "while": ("carried_names", "cond_name"),
        "static_rnn": ("mem_names", "mem_updated", "x_names", "out_names"),
        "dynamic_rnn": ("mem_names", "mem_updated", "x_names", "out_names"),
        "conditional_block": ("written_names",),
        "cond": ("true_out_names", "false_out_names"),
    }
    for block, i, op in walker.iter_ops(program):
        refs = walker.sub_block_indices(op)
        for attr, idx in refs:
            if not isinstance(idx, int) or not (0 <= idx < n_blocks):
                report.add(
                    ERROR, "bad-sub-block",
                    "op attr %s=%r does not reference a block of this "
                    "program (%d blocks)" % (attr, idx, n_blocks),
                    block_idx=block.idx, op_index=i, op=op)
            elif idx == 0:
                report.add(
                    ERROR, "bad-sub-block",
                    "op attr %s references the global block — a "
                    "control-flow body cannot be block 0" % attr,
                    block_idx=block.idx, op_index=i, op=op)
        if refs:
            for a in required_attrs.get(op.type, ()):
                if op.attrs.get(a) is None:
                    report.add(
                        ERROR, "bad-sub-block",
                        "control-flow op is missing required attr %r "
                        "(its lowering reads it unconditionally)" % a,
                        block_idx=block.idx, op_index=i, op=op)


def _walk_block(program, block, available, produced_anywhere, persistables,
                have_state, report, _seen):
    """Sequential availability walk of one block; recurses into
    sub-blocks with the owner's available set + injected names.
    Also runs the dead-store (conflicting write) heuristic per block."""
    if block.idx in _seen:
        return
    _seen.add(block.idx)
    available = set(available)
    last_write = {}      # name -> op index of last write in this block
    read_since = set()   # names read since their last write

    for i, op in enumerate(block.ops):
        reads = walker._op_reads(program, op)
        for n in reads:
            read_since.add(n)
            if n in available:
                continue
            if n in persistables:
                if have_state:
                    report.add(
                        ERROR, "uninitialized-persistable",
                        "op reads persistable '%s' which has no value in "
                        "the scope and is not produced earlier — was the "
                        "startup program run?" % n,
                        block_idx=block.idx, op_index=i, op=op, var=n)
                # else: standalone mode assumed persistables initialized
            elif n in produced_anywhere:
                report.add(
                    ERROR, "use-before-def",
                    "op reads '%s' before any op produces it (a later op "
                    "writes it — op ordering bug?)" % n,
                    block_idx=block.idx, op_index=i, op=op, var=n)
            else:
                report.add(
                    ERROR, "dangling-input",
                    "op reads '%s' which no op produces and which is "
                    "neither fed nor persistable state" % n,
                    block_idx=block.idx, op_index=i, op=op, var=n)
            available.add(n)  # report each missing name once per block

        outs = []
        for ns in op.outputs.values():
            outs.extend(ns)
        for n in outs:
            prev = last_write.get(n)
            if (prev is not None and n not in read_since
                    and n not in reads):
                report.add(
                    WARNING, "conflicting-write",
                    "op overwrites '%s' (written by op %d) before anything "
                    "reads it — dead store or two ops racing for one name"
                    % (n, prev),
                    block_idx=block.idx, op_index=i, op=op, var=n)
            last_write[n] = i
            read_since.discard(n)
            available.add(n)
            if not _declared(block, n):
                report.add(
                    INFO, "undeclared-output",
                    "op writes '%s' which is not declared as a Variable "
                    "in the block tree" % n,
                    block_idx=block.idx, op_index=i, op=op, var=n)

        for _attr, sub in walker.sub_blocks(program, op):
            sub_avail = available | walker.injected_names(op)
            _walk_block(program, sub, sub_avail, produced_anywhere,
                        persistables, have_state, report, _seen)


def _declared(block, name):
    blk = block
    while blk is not None:
        if name in blk.vars:
            return True
        blk = blk.parent_block
    return False
