"""Analysis orchestration: one entry point over the passes + the
executor's verify-on-first-compile mode switch.

``PADDLE_TPU_ANALYSIS`` selects what gates a compile:

- ``off``    — no analysis (bit-for-bit the pre-analyzer executor).
- ``verify`` — (default) the structural verifier only: a pure-python
  walk, microseconds even on big programs, catching everything that
  would die at lowering time with attributed diagnostics instead.
- ``full``   — verifier + abstract shape/dtype propagation + TPU-lint.
  Costs one ``jax.eval_shape`` per op; meant for CI lanes, the CLI, and
  first-failure triage (GuardedExecutor re-runs it on a failed
  dispatch), not for every interactive run.
"""
import os

from .diagnostics import AnalysisReport
from . import verifier

__all__ = ["analyze", "mode", "ANALYSIS_ENV", "MODES"]

ANALYSIS_ENV = "PADDLE_TPU_ANALYSIS"
MODES = ("off", "verify", "full")


def mode(default="verify"):
    """Current analysis mode, env-driven (live read, like telemetry)."""
    m = os.environ.get(ANALYSIS_ENV, default).lower() or default
    return m if m in MODES else default


def analyze(program, feed_names=(), fetch_names=(), state_names=None,
            feed_specs=None, state_specs=None, platform="cpu",
            level="full", is_test=False, default_dim=None):
    """Run the analyzer at ``level`` ("verify" | "full").

    Returns an :class:`AnalysisReport` merging every pass that ran.
    ``feed_specs``/``state_specs`` (name -> array-like or
    ShapeDtypeStruct) make the shape pass exact; omitted, shapes derive
    from declared var metadata with -1 dims defaulted.
    """
    report = AnalysisReport()
    report.extend(verifier.verify(
        program, feed_names=feed_names, fetch_names=fetch_names,
        state_names=state_names))
    if level == "full" and not report.errors:
        # shape propagation assumes structural well-formedness; on a
        # broken program the verifier errors are the actionable output
        from . import shapes, tpu_lint

        if feed_specs is None and feed_names:
            # derive specs for the caller's ACTUAL feed list — it may
            # feed vars that are not declared is_data (hand-built
            # programs), and those must enter the abstract env or every
            # op reading them is silently skipped as unresolvable
            feed_specs = shapes.feed_specs_from_program(
                program, feed_names=list(feed_names),
                default_dim=default_dim)
        env, shape_report = shapes.propagate(
            program, feed_specs=feed_specs, state_specs=state_specs,
            is_test=is_test, platform=platform, default_dim=default_dim)
        report.extend(shape_report)
        report.extend(tpu_lint.lint(
            program, shape_env=env, feed_names=feed_names,
            fetch_names=fetch_names, state_names=state_names,
            platform=platform))
    return report
