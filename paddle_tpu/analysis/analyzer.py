"""Analysis orchestration: one entry point over the passes + the
executor's verify-on-first-compile mode switch.

``PADDLE_TPU_ANALYSIS`` selects what gates a compile:

- ``off``    — no analysis (bit-for-bit the pre-analyzer executor).
- ``verify`` — (default) the structural verifier + the pure-python
  liveness peak-HBM estimate (microseconds even on big programs),
  catching everything that would die at lowering time — and programs
  that provably cannot fit the device — with attributed diagnostics.
- ``full``   — verifier + abstract shape/dtype propagation + the
  roofline cost model (per-op FLOPs/bytes, predicted step seconds and
  MFU) + TPU-lint + the donation dataflow pass (use-after-donate /
  double-donate proven over def-use chains, sub-block closure reads
  included). Costs one ``jax.eval_shape``/``make_jaxpr`` per op;
  meant for CI lanes, the CLI, and first-failure triage
  (GuardedExecutor re-runs it on a failed dispatch), not for every
  interactive run.

The predicted-OOM check compares the liveness peak against the device
HBM capacity (table entry for the device kind, or
``PADDLE_TPU_HBM_BYTES``); when it trips, the gate raises with an
``error``-severity Diagnostic attributed to the op resident at the
peak — BEFORE any ``compile_start`` event.
"""
import os

from .diagnostics import ERROR, AnalysisReport
from . import verifier

__all__ = ["analyze", "mode", "ANALYSIS_ENV", "MODES"]

ANALYSIS_ENV = "PADDLE_TPU_ANALYSIS"
MODES = ("off", "verify", "full")


def mode(default="verify"):
    """Current analysis mode, env-driven (live read, like telemetry)."""
    m = os.environ.get(ANALYSIS_ENV, default).lower() or default
    return m if m in MODES else default


def analyze(program, feed_names=(), fetch_names=(), state_names=None,
            feed_specs=None, state_specs=None, platform="cpu",
            level="full", is_test=False, default_dim=None,
            device_kind=None, param_shards=1, act_shards=1):
    """Run the analyzer at ``level`` ("verify" | "full").

    Returns an :class:`AnalysisReport` merging every pass that ran.
    ``feed_specs``/``state_specs`` (name -> array-like or
    ShapeDtypeStruct) make the shape pass exact; omitted, shapes derive
    from declared var metadata with -1 dims defaulted. ``device_kind``
    selects the roofline/capacity profile (env overrides always apply);
    ``param_shards``/``act_shards`` divide parameter/activation
    footprints for sharded meshes.
    """
    report = AnalysisReport()
    report.extend(verifier.verify(
        program, feed_names=feed_names, fetch_names=fetch_names,
        state_names=state_names))
    env = None
    cost = None
    if level == "full" and not report.errors:
        # shape propagation assumes structural well-formedness; on a
        # broken program the verifier errors are the actionable output
        from . import costs, dataflow, shapes, tpu_lint

        if feed_specs is None and feed_names:
            # derive specs for the caller's ACTUAL feed list — it may
            # feed vars that are not declared is_data (hand-built
            # programs), and those must enter the abstract env or every
            # op reading them is silently skipped as unresolvable
            feed_specs = shapes.feed_specs_from_program(
                program, feed_names=list(feed_names),
                default_dim=default_dim)
        env, shape_report = shapes.propagate(
            program, feed_specs=feed_specs, state_specs=state_specs,
            is_test=is_test, platform=platform, default_dim=default_dim)
        report.extend(shape_report)
        try:
            cost = costs.analyze_cost(
                program, env=env, feed_specs=feed_specs,
                state_specs=state_specs, fetch_names=fetch_names,
                state_names=state_names, is_test=is_test,
                platform=platform, default_dim=default_dim,
                device_kind=device_kind, param_shards=param_shards,
                act_shards=act_shards)
        except Exception as e:  # noqa: BLE001 — the cost model must
            # never break a lint run; the structural passes stand alone
            report.meta["cost_pass_error"] = "%s: %s" % (
                type(e).__name__, e)
        report.extend(tpu_lint.lint(
            program, shape_env=env, feed_names=feed_names,
            fetch_names=fetch_names, state_names=state_names,
            platform=platform, cost=cost))
        # donation dataflow: proves the hazards tpu_lint only
        # heuristically warns about (use-after-donate, double-donate)
        report.extend(dataflow.analyze_donation(
            program, feed_names=feed_names, fetch_names=fetch_names,
            state_names=state_names))
    if not report.errors:
        _quantify(report, program, cost=cost, feed_specs=feed_specs,
                  state_specs=state_specs, fetch_names=fetch_names,
                  state_names=state_names, default_dim=default_dim,
                  device_kind=device_kind, param_shards=param_shards,
                  act_shards=act_shards)
    return report


def _fmt_bytes(n):
    """Human-readable byte count at whichever scale is non-trivial."""
    n = float(n)
    for div, unit in ((1e9, "GB"), (1e6, "MB"), (1e3, "KB")):
        if n >= div:
            return "%.2f %s" % (n / div, unit)
    return "%d B" % n


def _quantify(report, program, cost=None, feed_specs=None,
              state_specs=None, fetch_names=(), state_names=None,
              default_dim=None, device_kind=None, param_shards=1,
              act_shards=1):
    """Fold the quantitative layer into ``report``: peak-HBM meta (and
    the predicted-OOM error when it exceeds capacity) at every level;
    roofline meta when a ``full``-level cost report is at hand. A crash
    here must never break the gate — it degrades to meta."""
    from . import costs, memory

    try:
        if cost is not None:
            mem = cost.memory
        else:
            # cheap path (default gate): declared metadata + real
            # feed/state shapes, no jax tracing. -1 dims resolve to the
            # actual feed batch when the caller did not pin one.
            dd = default_dim
            if dd is None:
                dims = [int(v.shape[0]) for v in (feed_specs or {}).values()
                        if getattr(v, "shape", None)]
                dd = max(dims) if dims else None
            mem = memory.estimate(
                program, feed_specs=feed_specs, state_specs=state_specs,
                fetch_names=fetch_names, state_names=state_names,
                default_dim=dd, param_shards=param_shards,
                act_shards=act_shards)
    except Exception as e:  # noqa: BLE001 — estimate bug, not user's
        report.meta["memory_pass_error"] = "%s: %s" % (
            type(e).__name__, e)
        return
    report.meta["predicted_peak_hbm_bytes"] = int(mem.peak_bytes)
    if cost is not None:
        report.meta["total_flops"] = round(cost.total_flops, 1)
        report.meta["total_bytes"] = round(cost.total_bytes, 1)
        if cost.predicted_step_seconds is not None:
            report.meta["predicted_step_seconds"] = float(
                "%.6g" % cost.predicted_step_seconds)
        if cost.predicted_mfu is not None:
            report.meta["predicted_mfu"] = round(cost.predicted_mfu, 4)
    profile = costs.device_profile(device_kind)
    cap = profile.hbm_bytes if profile is not None else None
    if not cap:
        return
    report.meta["hbm_capacity_bytes"] = int(cap)
    if mem.peak_bytes <= cap:
        return
    gb = program.global_block()
    op = None
    if mem.peak_op_index is not None and mem.peak_op_index < len(gb.ops):
        op = gb.ops[mem.peak_op_index]
    top = ", ".join(
        "%s (%s)" % (n, _fmt_bytes(b)) for n, b in mem.top[:3])
    report.add(
        ERROR, "predicted-oom",
        "predicted peak live-set %s exceeds device HBM %s "
        "(%.0f%%): params %s + activations %s resident at op "
        "%s '%s'%s — reduce the batch/sequence, shard params across a "
        "mesh, or add recompute checkpoints"
        % (_fmt_bytes(mem.peak_bytes), _fmt_bytes(cap),
           100.0 * mem.peak_bytes / cap, _fmt_bytes(mem.param_bytes),
           _fmt_bytes(mem.act_bytes_at_peak), mem.peak_op_index,
           mem.peak_op_type,
           ("; largest residents: " + top) if top else ""),
        block_idx=0, op_index=mem.peak_op_index, op=op)
