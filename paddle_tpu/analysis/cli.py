"""``python -m paddle_tpu.analysis <program|model_dir>`` — lint saved
inference models (or raw Program JSON) without touching an executor.

Exit codes: 0 clean, 1 findings (errors+warnings; tune with
``--fail-on``), 2 usage/load failure. Output is a stable JSON report
(sorted keys, deterministically ordered diagnostics, no timestamps) so
CI lanes can diff it; ``--text`` renders for humans.
"""
import argparse
import json
import os
import sys

__all__ = ["main"]


def _load_target(path):
    """Resolve a CLI target to (program, feed_names, fetch_names,
    state_specs)."""
    import numpy as np

    from ..fluid.framework import Program

    model_file = path
    params_file = None
    if os.path.isdir(path):
        model_file = os.path.join(path, "__model__")
        if not os.path.exists(model_file):
            raise IOError(
                "%s is a directory without a __model__ file — expected a "
                "save_inference_model dir" % path)
        cand = os.path.join(path, "__params__.npz")
        params_file = cand if os.path.exists(cand) else None
    with open(model_file) as f:
        doc = json.load(f)
    if isinstance(doc, dict) and "program" in doc:
        # save_inference_model meta: {program, feed_names, fetch_names}
        program = Program.from_json(json.dumps(doc["program"]))
        feed_names = list(doc.get("feed_names") or [])
        fetch_names = list(doc.get("fetch_names") or [])
    else:
        # raw Program.to_json dump
        program = Program.from_json(json.dumps(doc))
        feed_names, fetch_names = [], []
    state_specs = None
    if params_file is not None:
        data = np.load(params_file, allow_pickle=False)
        state_specs = {n: data[n] for n in data.files}
    return program, feed_names, fetch_names, state_specs


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.analysis",
        description="Statically verify + shape-check + TPU-lint a saved "
                    "inference model or Program JSON.")
    ap.add_argument("target",
                    help="save_inference_model dir, __model__ meta file, "
                         "or Program.to_json dump")
    ap.add_argument("--platform", choices=("tpu", "cpu"), default="tpu",
                    help="lint target platform (default: tpu — the "
                         "deployment target)")
    ap.add_argument("--level", choices=("verify", "full"), default="full")
    ap.add_argument("--batch", type=int, default=8,
                    help="placeholder for -1 feed dims (default: 8)")
    ap.add_argument("--text", action="store_true",
                    help="human-readable report instead of JSON")
    ap.add_argument("--fail-on", choices=("findings", "error", "never"),
                    default="findings",
                    help="what makes the exit code nonzero "
                         "(default: findings = errors+warnings)")
    args = ap.parse_args(argv)

    try:
        program, feed_names, fetch_names, state_specs = _load_target(
            args.target)
    except Exception as e:  # noqa: BLE001 — CLI boundary
        print("error: cannot load %s: %s: %s"
              % (args.target, type(e).__name__, e), file=sys.stderr)
        return 2

    from .analyzer import analyze

    # saved models are inference programs: analyze in test mode
    report = analyze(
        program, feed_names=feed_names, fetch_names=fetch_names,
        state_names=set(state_specs) if state_specs is not None else None,
        state_specs=state_specs, platform=args.platform, level=args.level,
        is_test=True, default_dim=args.batch)

    doc = {
        "target": args.target,
        "platform": args.platform,
        "level": args.level,
        "report": report.to_dict(),
    }
    if args.text:
        print("target: %s (platform %s, level %s)"
              % (args.target, args.platform, args.level))
        print(str(report))
    else:
        print(json.dumps(doc, sort_keys=True, indent=2))

    if args.fail_on == "never":
        return 0
    if args.fail_on == "error":
        return 1 if report.errors else 0
    return 1 if report.findings else 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
