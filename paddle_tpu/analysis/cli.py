"""``python -m paddle_tpu.analysis <program|model_dir>`` — lint saved
inference models (or raw Program JSON) without touching an executor.

Exit codes: 0 clean, 1 findings (errors+warnings; tune with
``--fail-on``), 2 usage/load failure. Output is a stable JSON report
(sorted keys, deterministically ordered diagnostics, no timestamps) so
CI lanes can diff it; ``--text`` renders for humans; ``--json-out``
additionally writes the JSON atomically to a file; ``--cost`` adds the
cost-model section (per-op FLOPs/bytes, roofline step/MFU prediction,
liveness peak-HBM vs the ``--device`` capacity).
"""
import argparse
import json
import os
import sys

__all__ = ["main"]

_EPILOG = """\
exit codes (stable API — lanes gate on them):
  0   clean (or --fail-on never); with --plan: a ranked plan exists
  1   findings — errors and warnings per --fail-on (predicted-oom is
      an error: the program's peak live-set exceeds the device HBM);
      with --plan: every candidate was rejected (nothing fits)
  2   usage error / target failed to load / malformed --mesh

lint gating:
  --fail-on picks the severity floor for exit 1: 'findings' (default:
  errors+warnings), 'perf' (errors+warnings+perf hints — the strict
  lane gate, e.g. `python -m paddle_tpu.analysis --fail-on perf DIR`),
  'error', 'never'. Recorded concurrency violations (--concurrency)
  count under every --fail-on except 'never'.

concurrency:
  --concurrency appends the in-process concurrency sanitizer report:
  the named-lock order graph, lock-order cycles (= potential
  deadlocks, with both acquisition stacks), blocking-under-lock /
  thread-leak / cross-program-donated-alias violations, and live
  framework threads. Arm recording with PADDLE_TPU_LOCK_SANITIZER=on
  (or analysis.concurrency.arm() in-process). TARGET is optional when
  --concurrency is given.

plan mode:
  --plan --devices N searches mesh factorizations of N (dp/tp/pp) x
  DistributedStrategy settings (gspmd vs explicit comms, int8
  quantized allreduce, bucketed overlap, ZeRO-1, AMP), prices each
  against the --device profile (compute roofline + pipeline bubble +
  ICI/DCN comm legs), drops predicted-OOM candidates with
  op-attributed diagnostics, and ranks the rest by predicted step
  seconds. TARGET may be omitted: the bench BERT pretrain program is
  built in-process. --json-out writes a plan document that
  DistributedStrategy.from_plan and bench.py's auto-tuned lane apply
  directly; with --mesh the given composition is also priced against
  the winner (suboptimal-parallel-plan finding at >=1.25x).
"""


def _bench_bert_program(batch=8, seq=64):
    """The default --plan target: the bench BERT-tiny pretrain step
    (same construction as bench.py's CPU lane), built in-process so
    ``--plan --devices N`` needs no saved model."""
    from .. import fluid
    from ..fluid import framework
    from ..models import bert

    prog = framework.Program()
    startup = framework.Program()
    with framework.program_guard(prog, startup):
        cfg = bert.bert_tiny(seq=seq)
        vs = bert.build_bert_pretrain(cfg, seq)
        fluid.optimizer.Adam(learning_rate=1e-4).minimize(vs["loss"])
    return prog, ["input_ids", "mlm_labels"], [vs["loss"].name]


def _load_target(path):
    """Resolve a CLI target to (program, feed_names, fetch_names,
    state_specs)."""
    import numpy as np

    from ..fluid.framework import Program

    model_file = path
    params_file = None
    if os.path.isdir(path):
        model_file = os.path.join(path, "__model__")
        if not os.path.exists(model_file):
            raise IOError(
                "%s is a directory without a __model__ file — expected a "
                "save_inference_model dir" % path)
        cand = os.path.join(path, "__params__.npz")
        params_file = cand if os.path.exists(cand) else None
    with open(model_file) as f:
        doc = json.load(f)
    if isinstance(doc, dict) and "program" in doc:
        # save_inference_model meta: {program, feed_names, fetch_names}
        program = Program.from_json(json.dumps(doc["program"]))
        feed_names = list(doc.get("feed_names") or [])
        fetch_names = list(doc.get("fetch_names") or [])
    else:
        # raw Program.to_json dump
        program = Program.from_json(json.dumps(doc))
        feed_names, fetch_names = [], []
    state_specs = None
    if params_file is not None:
        data = np.load(params_file, allow_pickle=False)
        state_specs = {n: data[n] for n in data.files}
    return program, feed_names, fetch_names, state_specs


def _parse_mesh(spec):
    """``"dp=8,tp=2"`` -> {"dp": 8, "tp": 2}. Any axis name is legal
    (dp/data/batch/sp/seq shard activations; tp/mp/pp/ep shard params —
    see memory.shard_divisors). Raises ValueError with an actionable
    message on malformed entries; the CLI maps that to exit 2."""
    mesh = {}
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        axis, _, size = part.partition("=")
        axis = axis.strip()
        if not axis or not size:
            raise ValueError(
                "bad --mesh entry %r (want axis=size, e.g. "
                "'dp=8,tp=2,pp=2')" % part)
        try:
            n = int(size)
        except ValueError:
            raise ValueError(
                "bad --mesh entry %r: size %r is not an integer"
                % (part, size.strip()))
        if n < 1:
            raise ValueError(
                "bad --mesh entry %r: axis size must be >= 1" % part)
        if axis in mesh:
            raise ValueError(
                "bad --mesh: axis %r given twice" % axis)
        mesh[axis] = n
    return mesh


def _atomic_write(path, text):
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(text)
    os.replace(tmp, path)


def _run_plan(args, mesh):
    """--plan mode: search mesh x strategy x comms and emit the ranked
    plan document. Exit 0 when a plan exists, 1 when every candidate
    was rejected, 2 on usage/load errors."""
    if not args.devices or args.devices < 1:
        print("error: --plan requires --devices N (a positive device "
              "count to lay the mesh over)", file=sys.stderr)
        return 2
    is_test = False
    state_specs = None
    if args.target is not None:
        try:
            program, feed_names, fetch_names, state_specs = _load_target(
                args.target)
        except Exception as e:  # noqa: BLE001 — CLI boundary
            print("error: cannot load %s: %s: %s"
                  % (args.target, type(e).__name__, e), file=sys.stderr)
            return 2
        is_test = True  # saved models are inference programs
        target_desc = args.target
    else:
        program, feed_names, fetch_names = _bench_bert_program(
            batch=args.batch)
        target_desc = "bench-bert-tiny (built in-process)"

    from ..planner import plan_search
    from .costs import device_profile

    # a search needs SOME roofline to rank against; with no --device
    # the v5e table row fills whatever the PADDLE_TPU_* env overrides
    # (applied on top, as always) leave unset
    device_defaulted = "v5e" if args.device is None else None
    profile = device_profile(args.device or "v5e")

    amp_choices = {"auto": (False, True), "on": (True,),
                   "off": (False,)}[args.amp]
    result = plan_search(
        program, args.devices, profile=profile,
        feed_names=feed_names, fetch_names=fetch_names,
        state_specs=state_specs,
        state_names=(set(state_specs) if state_specs is not None
                     else None),
        is_test=is_test, default_dim=args.batch,
        microbatches=args.microbatches, amp_choices=amp_choices)
    doc = {
        "target": target_desc,
        "devices": args.devices,
        "plan": result.to_dict(top=args.top),
    }
    if device_defaulted:
        doc["device_defaulted"] = device_defaulted
    if mesh:
        from .tpu_lint import lint_parallel_plan

        rep = lint_parallel_plan(
            program, mesh, n_devices=args.devices,
            microbatches=args.microbatches, level="full",
            search_result=result)
        doc["mesh_check"] = rep.to_dict()
    rendered = json.dumps(doc, sort_keys=True, indent=2)
    if args.text:
        print("target: %s" % target_desc)
        print(result.render_text(top=args.top))
        if mesh and doc.get("mesh_check", {}).get("diagnostics"):
            for d in doc["mesh_check"]["diagnostics"]:
                print("%s [%s] %s"
                      % (d["severity"], d["check"], d["message"]))
    else:
        print(rendered)
    if args.json_out:
        try:
            _atomic_write(args.json_out, rendered + "\n")
        except OSError as e:
            print("error: cannot write %s: %s" % (args.json_out, e),
                  file=sys.stderr)
            return 2
    return 0 if result.ranked else 1


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.analysis",
        description="Statically verify + shape-check + TPU-lint a saved "
                    "inference model or Program JSON.",
        epilog=_EPILOG,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("target", nargs="?", default=None,
                    help="save_inference_model dir, __model__ meta file, "
                         "or Program.to_json dump; optional with --plan "
                         "(defaults to the bench BERT pretrain program)")
    ap.add_argument("--platform", choices=("tpu", "cpu"), default="tpu",
                    help="lint target platform (default: tpu — the "
                         "deployment target)")
    ap.add_argument("--level", choices=("verify", "full"), default="full")
    ap.add_argument("--batch", type=int, default=8,
                    help="placeholder for -1 feed dims (default: 8)")
    ap.add_argument("--cost", action="store_true",
                    help="add the cost-model section: per-op FLOPs/bytes, "
                         "roofline-predicted step seconds and MFU, and "
                         "the liveness peak-HBM estimate vs --device "
                         "capacity (forces --level full); with --mesh "
                         "dp=N also the predicted gradient-allreduce "
                         "seconds (ICI bandwidth from --device or "
                         "PADDLE_TPU_ICI_BW) and dp scaling efficiency")
    ap.add_argument("--device", default=None, metavar="KIND",
                    help="device kind for the roofline/capacity model "
                         "(e.g. v5e, v5p, v4); default: only the "
                         "PADDLE_TPU_PEAK_FLOPS / PADDLE_TPU_HBM_BYTES / "
                         "PADDLE_TPU_HBM_BW env overrides apply")
    ap.add_argument("--mesh", default=None, metavar="AXES",
                    help="mesh axes dividing footprints, e.g. "
                         "'dp=8,tp=2' or 'dp=2,pp=2,ep=2' — "
                         "dp/data/batch/sp axes divide activations, "
                         "every other axis (tp/mp/pp/ep) divides "
                         "params; with --plan, this composition is "
                         "priced against the search winner")
    ap.add_argument("--plan", action="store_true",
                    help="auto-parallelism planner: search mesh x "
                         "strategy x comms for --devices chips and "
                         "emit the ranked plan table (see epilog)")
    ap.add_argument("--devices", type=int, default=None, metavar="N",
                    help="device count the plan search targets "
                         "(required with --plan)")
    ap.add_argument("--microbatches", type=int, default=8, metavar="M",
                    help="pipeline microbatches pp plans amortize "
                         "their (pp-1)/M bubble over (default: 8)")
    ap.add_argument("--top", type=int, default=8, metavar="K",
                    help="ranked plans to include in the report "
                         "(default: 8)")
    ap.add_argument("--amp", choices=("auto", "on", "off"),
                    default="auto",
                    help="AMP leg of the plan search: auto tries both "
                         "(default); on/off pins it")
    ap.add_argument("--json-out", default=None, metavar="PATH",
                    help="also write the JSON report to PATH atomically "
                         "(tmp + rename); stdout is unchanged")
    ap.add_argument("--text", action="store_true",
                    help="human-readable report instead of JSON")
    ap.add_argument("--concurrency", action="store_true",
                    help="append the in-process concurrency sanitizer "
                         "report (lock-order graph, potential-deadlock "
                         "cycles, blocking-under-lock/thread-leak "
                         "violations, live framework threads); recorded "
                         "violations make the exit nonzero; TARGET "
                         "becomes optional (see epilog)")
    ap.add_argument("--fail-on",
                    choices=("findings", "perf", "error", "never"),
                    default="findings",
                    help="severity floor for exit 1: findings (default: "
                         "errors+warnings), perf (also perf hints — the "
                         "strict lane lint gate), error, never")
    args = ap.parse_args(argv)

    # malformed --mesh is a usage error with its own message — not a
    # "cannot load target" traceback
    try:
        mesh = _parse_mesh(args.mesh)
    except ValueError as e:
        print("error: %s" % e, file=sys.stderr)
        return 2

    if args.plan:
        return _run_plan(args, mesh)

    if args.target is None and not args.concurrency:
        print("error: TARGET is required without --plan/--concurrency",
              file=sys.stderr)
        return 2

    report = None
    doc = {}
    level = "full" if args.cost else args.level
    if args.target is not None:
        try:
            program, feed_names, fetch_names, state_specs = _load_target(
                args.target)
        except Exception as e:  # noqa: BLE001 — CLI boundary
            print("error: cannot load %s: %s: %s"
                  % (args.target, type(e).__name__, e), file=sys.stderr)
            return 2

        from .analyzer import analyze
        from .memory import shard_divisors

        param_shards, act_shards = shard_divisors(mesh)

        # saved models are inference programs: analyze in test mode
        report = analyze(
            program, feed_names=feed_names, fetch_names=fetch_names,
            state_names=(set(state_specs)
                         if state_specs is not None else None),
            state_specs=state_specs, platform=args.platform, level=level,
            is_test=True, default_dim=args.batch,
            device_kind=args.device,
            param_shards=param_shards, act_shards=act_shards)

        doc = {
            "target": args.target,
            "platform": args.platform,
            "level": level,
            "report": report.to_dict(),
        }
    if args.cost and args.target is not None:
        from .costs import analyze_cost

        # gradient sync rides the batch-sharding axes; sp/seq shard the
        # sequence and keep full gradients, so they don't widen the group
        dp_shards = 1
        for axis, size in mesh.items():
            if str(axis).lower() in ("dp", "data", "batch"):
                dp_shards *= int(size)
        try:
            cost = analyze_cost(
                program, feed_names=feed_names, state_specs=state_specs,
                fetch_names=fetch_names,
                state_names=(set(state_specs)
                             if state_specs is not None else None),
                is_test=True, platform=args.platform,
                default_dim=args.batch, device_kind=args.device,
                param_shards=param_shards, act_shards=act_shards,
                dp_shards=dp_shards)
            doc["cost"] = cost.to_dict()
        except Exception as e:  # noqa: BLE001 — cost model must not
            # take down the structural report
            doc["cost"] = {"error": "%s: %s" % (type(e).__name__, e)}
    n_conc = 0
    if args.concurrency:
        from . import concurrency

        cdoc = concurrency.report()
        doc["concurrency"] = cdoc
        n_conc = len(cdoc["violations"]) + cdoc["violations_dropped"]

    rendered = json.dumps(doc, sort_keys=True, indent=2)
    if args.text:
        if report is not None:
            print("target: %s (platform %s, level %s)"
                  % (args.target, args.platform, level))
            print(str(report))
        if args.concurrency:
            cdoc = doc["concurrency"]
            print("concurrency: %d lock(s), %d order edge(s), "
                  "%d cycle(s), %d violation(s)%s, %d live thread(s)"
                  % (len(cdoc["locks"]), len(cdoc["edges"]),
                     len(cdoc["cycles"]), len(cdoc["violations"]),
                     " (+%d dropped)" % cdoc["violations_dropped"]
                     if cdoc["violations_dropped"] else "",
                     len(cdoc["live_threads"])))
            for v in cdoc["violations"]:
                print("%s: %s" % (v.get("check"), v.get("message")))
        if (args.cost and report is not None
                and "error" not in doc["cost"]):
            c = doc["cost"]
            print("cost: %.3g flops, %.3g bytes moved, peak HBM %.3g MB"
                  % (c["total_flops"], c["total_bytes"],
                     c["memory"]["peak_bytes"] / 1e6))
            if "predicted_step_seconds" in c:
                print("roofline: %.3g s/step, MFU %.3g (%s-bound on %s)"
                      % (c["predicted_step_seconds"],
                         c.get("predicted_mfu", 0.0),
                         c.get("bound", "?"),
                         c.get("device", {}).get("name", "?")))
            if "comm" in c:
                cc = c["comm"]
                line = ("comm: dp=%d, %.3g grad bytes"
                        % (cc["dp_shards"], cc["grad_bytes"]))
                if "predicted_allreduce_seconds" in cc:
                    line += (", allreduce %.3g s"
                             % cc["predicted_allreduce_seconds"])
                if "scaling_efficiency" in cc:
                    line += (", scaling efficiency %.3g"
                             % cc["scaling_efficiency"])
                print(line)
    else:
        print(rendered)
    if args.json_out:
        try:
            _atomic_write(args.json_out, rendered + "\n")
        except OSError as e:
            print("error: cannot write %s: %s" % (args.json_out, e),
                  file=sys.stderr)
            return 2

    if args.fail_on == "never":
        return 0
    # concurrency violations are error-grade under every gating mode:
    # a recorded lock-order cycle IS a latent deadlock
    if n_conc:
        return 1
    if report is None:
        return 0
    if args.fail_on == "error":
        return 1 if report.errors else 0
    if args.fail_on == "perf":
        return 1 if (report.findings
                     or report.by_severity("perf")) else 0
    return 1 if report.findings else 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
